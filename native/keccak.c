/* Keccak-256 (original padding 0x01, as used by Ethereum) + keccak-f[1600].
 *
 * TPU-native framework host crypto: C equivalent of the reference's
 * assembly keccak (crates/common/crypto/keccak/keccak1600-*.s) — written
 * from the Keccak specification with plain C and -O3 autovectorization.
 *
 * Exposed via a tiny C ABI for ctypes:
 *   void keccak256(const uint8_t *in, size_t len, uint8_t out[32]);
 *   void keccak256_batch(const uint8_t *in, size_t stride, size_t n,
 *                        size_t len, uint8_t *out);   // n msgs, fixed len
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

static const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

#define ROTL64(x, n) (((x) << (n)) | ((x) >> (64 - (n))))

static void keccak_f1600(uint64_t st[25]) {
    uint64_t bc[5], t;
    for (int round = 0; round < 24; round++) {
        /* theta */
        for (int i = 0; i < 5; i++)
            bc[i] = st[i] ^ st[i + 5] ^ st[i + 10] ^ st[i + 15] ^ st[i + 20];
        for (int i = 0; i < 5; i++) {
            t = bc[(i + 4) % 5] ^ ROTL64(bc[(i + 1) % 5], 1);
            for (int j = 0; j < 25; j += 5)
                st[j + i] ^= t;
        }
        /* rho + pi */
        static const int rot[24] = {1, 3, 6, 10, 15, 21, 28, 36, 45, 55, 2,
                                    14, 27, 41, 56, 8,  25, 43, 62, 18, 39,
                                    61, 20, 44};
        static const int piln[24] = {10, 7,  11, 17, 18, 3,  5,  16, 8, 21,
                                     24, 4,  15, 23, 19, 13, 12, 2,  20, 14,
                                     22, 9,  6,  1};
        t = st[1];
        for (int i = 0; i < 24; i++) {
            int j = piln[i];
            bc[0] = st[j];
            st[j] = ROTL64(t, rot[i]);
            t = bc[0];
        }
        /* chi */
        for (int j = 0; j < 25; j += 5) {
            for (int i = 0; i < 5; i++)
                bc[i] = st[j + i];
            for (int i = 0; i < 5; i++)
                st[j + i] ^= (~bc[(i + 1) % 5]) & bc[(i + 2) % 5];
        }
        /* iota */
        st[0] ^= RC[round];
    }
}

void keccak256(const uint8_t *in, size_t len, uint8_t *out) {
    uint64_t st[25];
    memset(st, 0, sizeof(st));
    const size_t rate = 136; /* 1088-bit rate */
    while (len >= rate) {
        for (size_t i = 0; i < rate / 8; i++) {
            uint64_t w;
            memcpy(&w, in + 8 * i, 8);
            st[i] ^= w;
        }
        keccak_f1600(st);
        in += rate;
        len -= rate;
    }
    uint8_t last[136];
    memset(last, 0, sizeof(last));
    memcpy(last, in, len);
    last[len] = 0x01;       /* keccak (pre-SHA3) padding */
    last[rate - 1] |= 0x80;
    for (size_t i = 0; i < rate / 8; i++) {
        uint64_t w;
        memcpy(&w, last + 8 * i, 8);
        st[i] ^= w;
    }
    keccak_f1600(st);
    memcpy(out, st, 32);
}

void keccak256_batch(const uint8_t *in, size_t stride, size_t n, size_t len,
                     uint8_t *out) {
    for (size_t k = 0; k < n; k++)
        keccak256(in + k * stride, len, out + 32 * k);
}
