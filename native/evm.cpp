// Native EVM hot loop: straight-line opcode dispatch / stack / gas in C++.
//
// The seat of the reference's interpreter performance work (the LEVM
// dispatch loop, crates/vm/levm/src/vm.rs hot path).  Scope: every opcode
// whose semantics are FRAME-LOCAL — arithmetic, bitwise, comparisons,
// KECCAK256 (via the in-repo keccak.c), memory, jumps, PUSH/DUP/SWAP/POP,
// calldata/code reads, RETURN/REVERT — runs here at C speed with exact
// gas accounting.  Anything touching the StateDB, environment or
// sub-calls ESCAPES back to the Python interpreter, which executes that
// single opcode with the canonical handlers and re-enters the loop
// (ethrex_tpu/evm/native_vm.py).  Gas constants mirror evm/gas.py and are
// differential-tested over the whole EF fixture ladder.
//
// u256 = 4 x uint64 little-endian limbs, fixed 1024-deep stack owned by
// the frame.  Memory and stack currently round-trip in FULL on every
// escape (pull_into/push_from in native_vm.py) — fine for the measured
// workloads (escapes are rare in hot code), but escape-dense contracts
// with large memory pay O(escapes x mem_size); dirty-range or
// operand-only sync is the known next optimization.

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <vector>

extern "C" void keccak256(const unsigned char *data, size_t len,
                          unsigned char *out);

namespace {

struct u256 {
    uint64_t w[4];  // little-endian limbs
};

static inline u256 zero256() { return u256{{0, 0, 0, 0}}; }

static inline bool is_zero(const u256 &a) {
    return (a.w[0] | a.w[1] | a.w[2] | a.w[3]) == 0;
}

static inline int cmp(const u256 &a, const u256 &b) {
    for (int i = 3; i >= 0; --i) {
        if (a.w[i] < b.w[i]) return -1;
        if (a.w[i] > b.w[i]) return 1;
    }
    return 0;
}

static inline u256 add256(const u256 &a, const u256 &b) {
    u256 r;
    unsigned __int128 c = 0;
    for (int i = 0; i < 4; ++i) {
        unsigned __int128 s = (unsigned __int128)a.w[i] + b.w[i] + c;
        r.w[i] = (uint64_t)s;
        c = s >> 64;
    }
    return r;
}

static inline u256 sub256(const u256 &a, const u256 &b) {
    u256 r;
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 4; ++i) {
        unsigned __int128 d = (unsigned __int128)a.w[i] - b.w[i] - borrow;
        r.w[i] = (uint64_t)d;
        borrow = (d >> 64) & 1;
    }
    return r;
}

static inline u256 mul256(const u256 &a, const u256 &b) {
    uint64_t res[8] = {0};
    for (int i = 0; i < 4; ++i) {
        unsigned __int128 carry = 0;
        for (int j = 0; i + j < 4; ++j) {
            unsigned __int128 cur = (unsigned __int128)a.w[i] * b.w[j]
                + res[i + j] + carry;
            res[i + j] = (uint64_t)cur;
            carry = cur >> 64;
        }
    }
    u256 r;
    memcpy(r.w, res, 32);
    return r;
}

static inline int bits256(const u256 &a) {
    for (int i = 3; i >= 0; --i)
        if (a.w[i]) return 64 * i + (64 - __builtin_clzll(a.w[i]));
    return 0;
}

static inline u256 shl256(const u256 &a, unsigned sh) {
    u256 r = zero256();
    if (sh >= 256) return r;
    unsigned limb = sh / 64, off = sh % 64;
    for (int i = 3; i >= 0; --i) {
        uint64_t v = 0;
        int src = i - (int)limb;
        if (src >= 0) {
            v = a.w[src] << off;
            if (off && src - 1 >= 0) v |= a.w[src - 1] >> (64 - off);
        }
        r.w[i] = v;
    }
    return r;
}

static inline u256 shr256(const u256 &a, unsigned sh) {
    u256 r = zero256();
    if (sh >= 256) return r;
    unsigned limb = sh / 64, off = sh % 64;
    for (int i = 0; i < 4; ++i) {
        uint64_t v = 0;
        unsigned src = i + limb;
        if (src < 4) {
            v = a.w[src] >> off;
            if (off && src + 1 < 4) v |= a.w[src + 1] << (64 - off);
        }
        r.w[i] = v;
    }
    return r;
}

// Knuth-free long division via base-2^32 schoolbook (q, r) = a / b.
static void divmod256(const u256 &a, const u256 &b, u256 &q, u256 &r) {
    q = zero256();
    r = zero256();
    if (is_zero(b)) return;
    if (cmp(a, b) < 0) { r = a; return; }
    int shift = bits256(a) - bits256(b);
    u256 d = shl256(b, shift);
    u256 rem = a;
    for (int i = shift; i >= 0; --i) {
        if (cmp(rem, d) >= 0) {
            rem = sub256(rem, d);
            q.w[i / 64] |= (uint64_t)1 << (i % 64);
        }
        d = shr256(d, 1);
    }
    r = rem;
}

static inline bool neg256(const u256 &a) { return a.w[3] >> 63; }

static inline u256 negate256(const u256 &a) {
    return sub256(zero256(), a);
}

static inline u256 from_u64(uint64_t v) { return u256{{v, 0, 0, 0}}; }

static inline uint64_t low_u64_capped(const u256 &a) {
    // value clamped to "huge" when it exceeds 64 bits (for offsets)
    if (a.w[1] | a.w[2] | a.w[3]) return UINT64_MAX;
    return a.w[0];
}

// big-endian <-> u256
static inline void u256_to_be(const u256 &a, uint8_t out[32]) {
    for (int i = 0; i < 4; ++i) {
        uint64_t w = a.w[3 - i];
        for (int j = 0; j < 8; ++j)
            out[i * 8 + j] = (uint8_t)(w >> (56 - 8 * j));
    }
}

static inline u256 be_to_u256(const uint8_t *p, size_t len) {
    uint8_t buf[32] = {0};
    memcpy(buf + (32 - len), p, len);
    u256 r;
    for (int i = 0; i < 4; ++i) {
        uint64_t w = 0;
        for (int j = 0; j < 8; ++j) w = (w << 8) | buf[i * 8 + j];
        r.w[3 - i] = w;
    }
    return r;
}

// ---- gas constants (mirror ethrex_tpu/evm/gas.py) ------------------------
enum {
    G_BASE = 2, G_VERYLOW = 3, G_LOW = 5, G_MID = 8, G_HIGH = 10,
    G_JUMPDEST = 1, G_KECCAK = 30, G_KECCAK_WORD = 6, G_COPY_WORD = 3,
    G_EXP = 10,
};

enum HaltReason {
    HALT_STOP = 0, HALT_RETURN = 1, HALT_REVERT = 2, HALT_ESCAPE = 3,
    HALT_OOG = 4, HALT_INVALID_OP = 5, HALT_INVALID_JUMP = 6,
    HALT_STACK = 7, HALT_CODE_END = 8,
};

struct Frame {
    std::vector<uint8_t> code;
    std::vector<uint8_t> calldata;
    std::vector<uint8_t> memory;      // 32-byte aligned size
    std::vector<uint8_t> jumpdests;   // bitmap
    u256 stack[1024];
    uint32_t sp = 0;
    uint64_t gas = 0;
    uint64_t pc = 0;
    uint64_t exp_byte = 50;           // fork-dependent EXP byte cost
    // opcode handled-natively bitmap (fork-gated from Python: an opcode
    // absent at the frame's fork is NOT in the map, so it escapes and
    // Python raises its InvalidOpcode with the right semantics)
    uint8_t native_ok[256];
    uint64_t ret_off = 0, ret_len = 0;  // RETURN/REVERT output window
};

static inline uint64_t mem_cost(uint64_t size_bytes) {
    uint64_t w = (size_bytes + 31) / 32;
    return 3 * w + (w * w) / 512;
}

static bool charge(Frame *f, uint64_t amount) {
    if (f->gas < amount) return false;
    f->gas -= amount;
    return true;
}

// returns false on OOG; expands memory (size tracked via vector size)
static bool expand_memory(Frame *f, uint64_t offset, uint64_t length) {
    if (length == 0) return true;
    uint64_t new_size = offset + length;
    if (new_size > f->memory.size()) {
        uint64_t cost = mem_cost(new_size) - mem_cost(f->memory.size());
        if (!charge(f, cost)) return false;
        uint64_t aligned = ((new_size + 31) / 32) * 32;
        f->memory.resize(aligned, 0);
    }
    return true;
}

static const uint64_t MEM_BOUND = (uint64_t)1 << 32;

} // namespace

extern "C" {

void *evm_frame_new(const uint8_t *code, size_t code_len,
                    const uint8_t *calldata, size_t calldata_len,
                    uint64_t gas, uint64_t exp_byte,
                    const uint8_t *native_ok) {
    Frame *f = new Frame();
    f->code.assign(code, code + code_len);
    f->calldata.assign(calldata, calldata + calldata_len);
    f->gas = gas;
    f->exp_byte = exp_byte;
    memcpy(f->native_ok, native_ok, 256);
    // jumpdest analysis (identical rule to vm._valid_jumpdests)
    f->jumpdests.assign((code_len + 7) / 8, 0);
    for (size_t i = 0; i < code_len;) {
        uint8_t op = code[i];
        if (op == 0x5B) {
            f->jumpdests[i / 8] |= 1 << (i % 8);
            i += 1;
        } else if (op >= 0x60 && op <= 0x7F) {
            i += (size_t)(op - 0x5F) + 1;
        } else {
            i += 1;
        }
    }
    return f;
}

void evm_frame_free(void *p) { delete (Frame *)p; }

uint64_t evm_gas(void *p) { return ((Frame *)p)->gas; }
void evm_set_gas(void *p, uint64_t g) { ((Frame *)p)->gas = g; }
uint64_t evm_pc(void *p) { return ((Frame *)p)->pc; }
void evm_set_pc(void *p, uint64_t pc) { ((Frame *)p)->pc = pc; }
uint32_t evm_stack_len(void *p) { return ((Frame *)p)->sp; }

// stack I/O as big-endian 32-byte words (index 0 = bottom)
void evm_stack_read(void *p, uint8_t *out) {
    Frame *f = (Frame *)p;
    for (uint32_t i = 0; i < f->sp; ++i)
        u256_to_be(f->stack[i], out + 32 * i);
}

void evm_stack_write(void *p, const uint8_t *in, uint32_t n) {
    Frame *f = (Frame *)p;
    f->sp = n;
    for (uint32_t i = 0; i < n; ++i)
        f->stack[i] = be_to_u256(in + 32 * i, 32);
}

uint64_t evm_mem_size(void *p) { return ((Frame *)p)->memory.size(); }

void evm_mem_read(void *p, uint8_t *out) {
    Frame *f = (Frame *)p;
    if (!f->memory.empty())
        memcpy(out, f->memory.data(), f->memory.size());
}

void evm_mem_write(void *p, const uint8_t *in, uint64_t n) {
    Frame *f = (Frame *)p;
    f->memory.assign(in, in + n);
}

uint64_t evm_ret_off(void *p) { return ((Frame *)p)->ret_off; }
uint64_t evm_ret_len(void *p) { return ((Frame *)p)->ret_len; }

// Run until halt or escape.  Returns a HaltReason; on HALT_ESCAPE the pc
// points AT the escaping opcode and all state is current.
int evm_run(void *p) {
    Frame *f = (Frame *)p;
    const uint8_t *code = f->code.data();
    const uint64_t n = f->code.size();

#define NEED(k) do { if (f->sp < (k)) return HALT_STACK; } while (0)
#define ROOM() do { if (f->sp >= 1024) return HALT_STACK; } while (0)
#define GAS(g) do { if (!charge(f, (g))) return HALT_OOG; } while (0)
#define BOUND(off, len) \
    do { if ((off) > MEM_BOUND || (len) > MEM_BOUND) return HALT_OOG; } \
    while (0)

    while (f->pc < n) {
        uint8_t op = code[f->pc];
        if (!f->native_ok[op]) return HALT_ESCAPE;
        f->pc++;
        switch (op) {
        case 0x00: return HALT_STOP;
        case 0x01: { // ADD
            GAS(G_VERYLOW); NEED(2);
            f->stack[f->sp - 2] = add256(f->stack[f->sp - 1],
                                         f->stack[f->sp - 2]);
            f->sp--; break;
        }
        case 0x02: { // MUL
            GAS(G_LOW); NEED(2);
            f->stack[f->sp - 2] = mul256(f->stack[f->sp - 1],
                                         f->stack[f->sp - 2]);
            f->sp--; break;
        }
        case 0x03: { // SUB
            GAS(G_VERYLOW); NEED(2);
            f->stack[f->sp - 2] = sub256(f->stack[f->sp - 1],
                                         f->stack[f->sp - 2]);
            f->sp--; break;
        }
        case 0x04: { // DIV
            GAS(G_LOW); NEED(2);
            u256 q, r;
            divmod256(f->stack[f->sp - 1], f->stack[f->sp - 2], q, r);
            f->stack[f->sp - 2] = q;
            f->sp--; break;
        }
        case 0x05: { // SDIV
            GAS(G_LOW); NEED(2);
            u256 a = f->stack[f->sp - 1], b = f->stack[f->sp - 2];
            u256 q, r;
            if (is_zero(b)) { q = zero256(); }
            else {
                u256 ua = neg256(a) ? negate256(a) : a;
                u256 ub = neg256(b) ? negate256(b) : b;
                divmod256(ua, ub, q, r);
                if (neg256(a) != neg256(b)) q = negate256(q);
            }
            f->stack[f->sp - 2] = q;
            f->sp--; break;
        }
        case 0x06: { // MOD
            GAS(G_LOW); NEED(2);
            u256 q, r;
            divmod256(f->stack[f->sp - 1], f->stack[f->sp - 2], q, r);
            f->stack[f->sp - 2] = r;
            f->sp--; break;
        }
        case 0x07: { // SMOD
            GAS(G_LOW); NEED(2);
            u256 a = f->stack[f->sp - 1], b = f->stack[f->sp - 2];
            u256 q, r;
            if (is_zero(b)) { r = zero256(); }
            else {
                u256 ua = neg256(a) ? negate256(a) : a;
                u256 ub = neg256(b) ? negate256(b) : b;
                divmod256(ua, ub, q, r);
                if (neg256(a) && !is_zero(r)) r = negate256(r);
            }
            f->stack[f->sp - 2] = r;
            f->sp--; break;
        }
        case 0x08: case 0x09: { // ADDMOD / MULMOD: escape (needs >256-bit)
            // MULMOD needs 512-bit intermediates; ADDMOD kept with it for
            // simplicity — both are rare in hot code
            f->pc--;
            return HALT_ESCAPE;
        }
        case 0x0A: { // EXP
            NEED(2);
            u256 base = f->stack[f->sp - 1], ex = f->stack[f->sp - 2];
            uint64_t blen = (bits256(ex) + 7) / 8;
            GAS(G_EXP + f->exp_byte * blen);
            u256 result = from_u64(1);
            u256 acc = base;
            int nb = bits256(ex);
            for (int i = 0; i < nb; ++i) {
                if ((ex.w[i / 64] >> (i % 64)) & 1)
                    result = mul256(result, acc);
                acc = mul256(acc, acc);
            }
            f->stack[f->sp - 2] = result;
            f->sp--; break;
        }
        case 0x0B: { // SIGNEXTEND
            GAS(G_LOW); NEED(2);
            u256 k = f->stack[f->sp - 1], v = f->stack[f->sp - 2];
            u256 out = v;
            uint64_t kk = low_u64_capped(k);
            if (kk < 31) {
                unsigned bit = 8 * (unsigned)(kk + 1) - 1;
                bool set = (v.w[bit / 64] >> (bit % 64)) & 1;
                for (unsigned i = bit + 1; i < 256; ++i) {
                    if (set) out.w[i / 64] |= (uint64_t)1 << (i % 64);
                    else out.w[i / 64] &= ~((uint64_t)1 << (i % 64));
                }
            }
            f->stack[f->sp - 2] = out;
            f->sp--; break;
        }
        case 0x10: case 0x11: { // LT / GT
            GAS(G_VERYLOW); NEED(2);
            int c = cmp(f->stack[f->sp - 1], f->stack[f->sp - 2]);
            bool res = (op == 0x10) ? (c < 0) : (c > 0);
            f->stack[f->sp - 2] = from_u64(res);
            f->sp--; break;
        }
        case 0x12: case 0x13: { // SLT / SGT
            GAS(G_VERYLOW); NEED(2);
            u256 a = f->stack[f->sp - 1], b = f->stack[f->sp - 2];
            bool na = neg256(a), nb = neg256(b);
            int c = (na != nb) ? (na ? -1 : 1) : cmp(a, b);
            bool res = (op == 0x12) ? (c < 0) : (c > 0);
            f->stack[f->sp - 2] = from_u64(res);
            f->sp--; break;
        }
        case 0x14: { // EQ
            GAS(G_VERYLOW); NEED(2);
            f->stack[f->sp - 2] =
                from_u64(cmp(f->stack[f->sp - 1], f->stack[f->sp - 2]) == 0);
            f->sp--; break;
        }
        case 0x15: { // ISZERO
            GAS(G_VERYLOW); NEED(1);
            f->stack[f->sp - 1] = from_u64(is_zero(f->stack[f->sp - 1]));
            break;
        }
        case 0x16: case 0x17: case 0x18: { // AND / OR / XOR
            GAS(G_VERYLOW); NEED(2);
            u256 a = f->stack[f->sp - 1], b = f->stack[f->sp - 2], r;
            for (int i = 0; i < 4; ++i)
                r.w[i] = op == 0x16 ? (a.w[i] & b.w[i])
                       : op == 0x17 ? (a.w[i] | b.w[i])
                                    : (a.w[i] ^ b.w[i]);
            f->stack[f->sp - 2] = r;
            f->sp--; break;
        }
        case 0x19: { // NOT
            GAS(G_VERYLOW); NEED(1);
            for (int i = 0; i < 4; ++i)
                f->stack[f->sp - 1].w[i] = ~f->stack[f->sp - 1].w[i];
            break;
        }
        case 0x1A: { // BYTE
            GAS(G_VERYLOW); NEED(2);
            u256 idx = f->stack[f->sp - 1], v = f->stack[f->sp - 2];
            uint64_t i = low_u64_capped(idx);
            uint8_t be[32];
            u256_to_be(v, be);
            f->stack[f->sp - 2] = from_u64(i < 32 ? be[i] : 0);
            f->sp--; break;
        }
        case 0x1B: { // SHL
            GAS(G_VERYLOW); NEED(2);
            uint64_t sh = low_u64_capped(f->stack[f->sp - 1]);
            f->stack[f->sp - 2] = sh >= 256 ? zero256()
                : shl256(f->stack[f->sp - 2], (unsigned)sh);
            f->sp--; break;
        }
        case 0x1C: { // SHR
            GAS(G_VERYLOW); NEED(2);
            uint64_t sh = low_u64_capped(f->stack[f->sp - 1]);
            f->stack[f->sp - 2] = sh >= 256 ? zero256()
                : shr256(f->stack[f->sp - 2], (unsigned)sh);
            f->sp--; break;
        }
        case 0x1D: { // SAR
            GAS(G_VERYLOW); NEED(2);
            uint64_t sh = low_u64_capped(f->stack[f->sp - 1]);
            u256 v = f->stack[f->sp - 2];
            u256 r;
            if (sh >= 256) {
                r = neg256(v) ? sub256(zero256(), from_u64(1)) : zero256();
            } else {
                r = shr256(v, (unsigned)sh);
                if (neg256(v) && sh) {
                    // fill the vacated high bits with ones
                    u256 ones = sub256(zero256(), from_u64(1));
                    u256 mask = shl256(ones, 256 - (unsigned)sh);
                    for (int i = 0; i < 4; ++i) r.w[i] |= mask.w[i];
                }
            }
            f->stack[f->sp - 2] = r;
            f->sp--; break;
        }
        case 0x20: { // KECCAK256
            NEED(2);
            uint64_t off = low_u64_capped(f->stack[f->sp - 1]);
            uint64_t len = low_u64_capped(f->stack[f->sp - 2]);
            BOUND(off, len);
            GAS(G_KECCAK + G_KECCAK_WORD * ((len + 31) / 32));
            if (!expand_memory(f, off, len)) return HALT_OOG;
            uint8_t out[32];
            keccak256(len ? f->memory.data() + off : out, len, out);
            f->sp -= 2;
            f->stack[f->sp++] = be_to_u256(out, 32);
            break;
        }
        case 0x35: { // CALLDATALOAD
            GAS(G_VERYLOW); NEED(1);
            uint64_t off = low_u64_capped(f->stack[f->sp - 1]);
            uint8_t buf[32] = {0};
            if (off < f->calldata.size()) {
                size_t avail = f->calldata.size() - off;
                memcpy(buf, f->calldata.data() + off,
                       avail < 32 ? avail : 32);
            }
            f->stack[f->sp - 1] = be_to_u256(buf, 32);
            break;
        }
        case 0x36: { // CALLDATASIZE
            GAS(G_BASE); ROOM();
            f->stack[f->sp++] = from_u64(f->calldata.size());
            break;
        }
        case 0x37: case 0x39: { // CALLDATACOPY / CODECOPY
            NEED(3);
            uint64_t dst = low_u64_capped(f->stack[f->sp - 1]);
            uint64_t src = low_u64_capped(f->stack[f->sp - 2]);
            uint64_t len = low_u64_capped(f->stack[f->sp - 3]);
            f->sp -= 3;
            BOUND(dst, len);
            GAS(G_VERYLOW + G_COPY_WORD * ((len + 31) / 32));
            if (!expand_memory(f, dst, len)) return HALT_OOG;
            if (len) {
                const std::vector<uint8_t> &srcbuf =
                    op == 0x37 ? f->calldata : f->code;
                uint64_t avail = src < srcbuf.size()
                    ? srcbuf.size() - src : 0;
                uint64_t ncopy = avail < len ? avail : len;
                if (ncopy)
                    memcpy(f->memory.data() + dst, srcbuf.data() + src,
                           ncopy);
                if (ncopy < len)
                    memset(f->memory.data() + dst + ncopy, 0, len - ncopy);
            }
            break;
        }
        case 0x38: { // CODESIZE
            GAS(G_BASE); ROOM();
            f->stack[f->sp++] = from_u64(f->code.size());
            break;
        }
        case 0x50: { // POP
            GAS(G_BASE); NEED(1);
            f->sp--; break;
        }
        case 0x51: { // MLOAD
            NEED(1);
            uint64_t off = low_u64_capped(f->stack[f->sp - 1]);
            BOUND(off, 32);
            GAS(G_VERYLOW);
            if (!expand_memory(f, off, 32)) return HALT_OOG;
            f->stack[f->sp - 1] = be_to_u256(f->memory.data() + off, 32);
            break;
        }
        case 0x52: { // MSTORE
            NEED(2);
            uint64_t off = low_u64_capped(f->stack[f->sp - 1]);
            BOUND(off, 32);
            GAS(G_VERYLOW);
            if (!expand_memory(f, off, 32)) return HALT_OOG;
            u256_to_be(f->stack[f->sp - 2], f->memory.data() + off);
            f->sp -= 2;
            break;
        }
        case 0x53: { // MSTORE8
            NEED(2);
            uint64_t off = low_u64_capped(f->stack[f->sp - 1]);
            BOUND(off, 1);
            GAS(G_VERYLOW);
            if (!expand_memory(f, off, 1)) return HALT_OOG;
            f->memory[off] = (uint8_t)(f->stack[f->sp - 2].w[0] & 0xFF);
            f->sp -= 2;
            break;
        }
        case 0x56: { // JUMP
            GAS(G_MID); NEED(1);
            uint64_t dest = low_u64_capped(f->stack[f->sp - 1]);
            f->sp--;
            if (dest >= n ||
                !(f->jumpdests[dest / 8] & (1 << (dest % 8))))
                return HALT_INVALID_JUMP;
            f->pc = dest;
            break;
        }
        case 0x57: { // JUMPI
            GAS(G_HIGH); NEED(2);
            uint64_t dest = low_u64_capped(f->stack[f->sp - 1]);
            bool cond = !is_zero(f->stack[f->sp - 2]);
            f->sp -= 2;
            if (cond) {
                if (dest >= n ||
                    !(f->jumpdests[dest / 8] & (1 << (dest % 8))))
                    return HALT_INVALID_JUMP;
                f->pc = dest;
            }
            break;
        }
        case 0x58: { // PC
            GAS(G_BASE); ROOM();
            f->stack[f->sp++] = from_u64(f->pc - 1);
            break;
        }
        case 0x59: { // MSIZE
            GAS(G_BASE); ROOM();
            f->stack[f->sp++] = from_u64(f->memory.size());
            break;
        }
        case 0x5A: { // GAS
            GAS(G_BASE); ROOM();
            f->stack[f->sp++] = from_u64(f->gas);
            break;
        }
        case 0x5B: { // JUMPDEST
            GAS(G_JUMPDEST);
            break;
        }
        case 0x5E: { // MCOPY (in the map only when the fork has it)
            NEED(3);
            uint64_t dst = low_u64_capped(f->stack[f->sp - 1]);
            uint64_t src = low_u64_capped(f->stack[f->sp - 2]);
            uint64_t len = low_u64_capped(f->stack[f->sp - 3]);
            f->sp -= 3;
            uint64_t mx = dst > src ? dst : src;
            BOUND(mx, len);
            GAS(G_VERYLOW + G_COPY_WORD * ((len + 31) / 32));
            if (len) {
                if (!expand_memory(f, mx, len)) return HALT_OOG;
                memmove(f->memory.data() + dst, f->memory.data() + src,
                        len);
            }
            break;
        }
        case 0x5F: { // PUSH0
            GAS(G_BASE); ROOM();
            f->stack[f->sp++] = zero256();
            break;
        }
        case 0xF3: case 0xFD: { // RETURN / REVERT
            NEED(2);
            uint64_t off = low_u64_capped(f->stack[f->sp - 1]);
            uint64_t len = low_u64_capped(f->stack[f->sp - 2]);
            f->sp -= 2;
            BOUND(off, len);
            if (!expand_memory(f, off, len)) return HALT_OOG;
            f->ret_off = off;
            f->ret_len = len;
            return op == 0xF3 ? HALT_RETURN : HALT_REVERT;
        }
        case 0xFE:
            return HALT_INVALID_OP;
        default: {
            if (op >= 0x60 && op <= 0x7F) {       // PUSH1..32
                GAS(G_VERYLOW); ROOM();
                size_t nbytes = op - 0x5F;
                size_t avail = f->pc < n ? n - f->pc : 0;
                size_t take = avail < nbytes ? avail : nbytes;
                uint8_t buf[32] = {0};
                // right-pad with zeros like the Python handler
                memcpy(buf, code + f->pc, take);
                memset(buf + take, 0, nbytes - take);
                f->stack[f->sp++] = be_to_u256(buf, nbytes);
                f->pc += nbytes;
                break;
            }
            if (op >= 0x80 && op <= 0x8F) {       // DUP1..16
                GAS(G_VERYLOW);
                uint32_t depth = op - 0x7F;
                NEED(depth); ROOM();
                f->stack[f->sp] = f->stack[f->sp - depth];
                f->sp++;
                break;
            }
            if (op >= 0x90 && op <= 0x9F) {       // SWAP1..16
                GAS(G_VERYLOW);
                uint32_t depth = op - 0x8F;
                NEED(depth + 1);
                u256 tmp = f->stack[f->sp - 1];
                f->stack[f->sp - 1] = f->stack[f->sp - 1 - depth];
                f->stack[f->sp - 1 - depth] = tmp;
                break;
            }
            // anything else that was marked native is a bug; escape
            f->pc--;
            return HALT_ESCAPE;
        }
        }
    }
    return HALT_CODE_END;
}

} // extern "C"
