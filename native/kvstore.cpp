// Append-only persistent KV store with table namespaces.
//
// The native persistence engine behind ethrex_tpu's Store (the seat the
// reference fills with RocksDB, crates/storage/backend/rocksdb.rs).  Design:
// a single append-only log of (table, key, value|tombstone) records replayed
// into an in-memory index on open; kv_compact() rewrites a dense snapshot.
// Crash safety: records are length-prefixed and CRC'd; a torn tail record is
// truncated on replay.
//
// C ABI (ctypes):
//   void*  kv_open(const char* path);
//   int    kv_put(void* h, const char* table, const uint8_t* k, uint32_t kl,
//                 const uint8_t* v, uint32_t vl);
//   int    kv_delete(void* h, const char* table, const uint8_t* k, uint32_t kl);
//   int    kv_get(void* h, const char* table, const uint8_t* k, uint32_t kl,
//                 uint8_t** out, uint32_t* out_len);   // 1=found
//   void   kv_free(uint8_t* buf);
//   int    kv_flush(void* h);
//   int    kv_compact(void* h);
//   void*  kv_scan_start(void* h, const char* table);
//   int    kv_scan_next(void* it, uint8_t** k, uint32_t* kl,
//                       uint8_t** v, uint32_t* vl);    // 1=have entry
//   void   kv_scan_end(void* it);
//   void   kv_close(void* h);

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>
#ifndef _WIN32
#include <fcntl.h>
#include <sys/file.h>
#include <unistd.h>
#endif

namespace {

struct Crc32Table {
    uint32_t t[256];
    Crc32Table() {  // magic static: thread-safe one-time init (C++11)
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t c = i;
            for (int j = 0; j < 8; j++)
                c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
            t[i] = c;
        }
    }
};

uint32_t crc32(const uint8_t* data, size_t len, uint32_t seed) {
    static const Crc32Table table;
    uint32_t c = seed ^ 0xFFFFFFFFu;
    for (size_t i = 0; i < len; i++)
        c = table.t[(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

constexpr uint32_t TOMBSTONE = 0xFFFFFFFFu;

struct Store {
    std::string path;
    FILE* log = nullptr;
    int lock_fd = -1;
    std::map<std::string, std::map<std::string, std::string>> tables;
    std::mutex mu;
};

struct ScanIter {
    std::vector<std::pair<std::string, std::string>> entries;
    size_t pos = 0;
};

bool read_exact(FILE* f, void* buf, size_t n) {
    return fread(buf, 1, n, f) == n;
}

// record: [crc u32][tlen u8][table][klen u32][key][vlen u32][value]
// vlen == TOMBSTONE -> delete, no value bytes.  crc covers everything
// after the crc field.
bool append_record(FILE* f, const std::string& table, const uint8_t* k,
                   uint32_t kl, const uint8_t* v, uint32_t vl) {
    // vl == TOMBSTONE is the delete sentinel; a real value of that size
    // would replay as a delete.  tlen is a u8 on the wire.
    if (v != nullptr && vl >= TOMBSTONE) return false;
    if (table.size() > 255) return false;
    std::vector<uint8_t> rec;
    uint8_t tlen = (uint8_t)table.size();
    rec.push_back(tlen);
    rec.insert(rec.end(), table.begin(), table.end());
    for (int i = 0; i < 4; i++) rec.push_back((kl >> (8 * i)) & 0xFF);
    rec.insert(rec.end(), k, k + kl);
    for (int i = 0; i < 4; i++) rec.push_back((vl >> (8 * i)) & 0xFF);
    if (vl != TOMBSTONE) rec.insert(rec.end(), v, v + vl);
    uint32_t crc = crc32(rec.data(), rec.size(), 0);
    uint8_t crcb[4];
    for (int i = 0; i < 4; i++) crcb[i] = (crc >> (8 * i)) & 0xFF;
    if (fwrite(crcb, 1, 4, f) != 4) return false;
    return fwrite(rec.data(), 1, rec.size(), f) == rec.size();
}

bool replay(Store* s) {
    FILE* f = fopen(s->path.c_str(), "rb");
    if (!f) return true;  // fresh store
    long valid_end = 0;
    bool crc_mismatch = false;
    while (true) {
        long rec_start = ftell(f);
        uint8_t crcb[4];
        if (!read_exact(f, crcb, 4)) break;
        uint32_t want = crcb[0] | (crcb[1] << 8) | (crcb[2] << 16) |
                        ((uint32_t)crcb[3] << 24);
        uint8_t tlen;
        if (!read_exact(f, &tlen, 1)) break;
        std::string table(tlen, '\0');
        if (tlen && !read_exact(f, table.data(), tlen)) break;
        uint8_t lenb[4];
        if (!read_exact(f, lenb, 4)) break;
        uint32_t kl = lenb[0] | (lenb[1] << 8) | (lenb[2] << 16) |
                      ((uint32_t)lenb[3] << 24);
        if (kl > (1u << 28)) break;
        std::string key(kl, '\0');
        if (kl && !read_exact(f, key.data(), kl)) break;
        if (!read_exact(f, lenb, 4)) break;
        uint32_t vl = lenb[0] | (lenb[1] << 8) | (lenb[2] << 16) |
                      ((uint32_t)lenb[3] << 24);
        std::string val;
        if (vl != TOMBSTONE) {
            if (vl > (1u << 30)) break;
            val.resize(vl);
            if (vl && !read_exact(f, val.data(), vl)) break;
        }
        // verify crc
        std::vector<uint8_t> rec;
        rec.push_back(tlen);
        rec.insert(rec.end(), table.begin(), table.end());
        for (int i = 0; i < 4; i++) rec.push_back((kl >> (8 * i)) & 0xFF);
        rec.insert(rec.end(), key.begin(), key.end());
        for (int i = 0; i < 4; i++) rec.push_back((vl >> (8 * i)) & 0xFF);
        rec.insert(rec.end(), val.begin(), val.end());
        if (crc32(rec.data(), rec.size(), 0) != want) {
            crc_mismatch = true;
            break;
        }
        if (vl == TOMBSTONE)
            s->tables[table].erase(key);
        else
            s->tables[table][key] = std::move(val);
        valid_end = ftell(f);
        (void)rec_start;
    }
    long file_end = 0;
    fseek(f, 0, SEEK_END);
    file_end = ftell(f);
    fclose(f);
    long dropped = file_end - valid_end;
    if (dropped > 0) {
        // a torn final record is expected after a crash; a CRC failure with
        // a LOT of data after it smells like mid-file corruption — warn
        // loudly instead of silently rewinding history
        fprintf(stderr,
                "kvstore: dropping %ld bytes after offset %ld in %s%s\n",
                dropped, valid_end, s->path.c_str(),
                (crc_mismatch && dropped > (1 << 16))
                    ? " (CRC mismatch mid-file: possible corruption, "
                      "restore from a snapshot if history is missing)"
                    : "");
    }
    // truncate any torn tail so the append log stays consistent
    FILE* t = fopen(s->path.c_str(), "rb+");
    if (t) {
        fseek(t, 0, SEEK_END);
        if (ftell(t) != valid_end) {
#ifdef _WIN32
            (void)valid_end;
#else
            if (ftruncate(fileno(t), valid_end) != 0) { /* best effort */ }
#endif
        }
        fclose(t);
    }
    return true;
}

}  // namespace

extern "C" {

void* kv_open(const char* path) {
    Store* s = new Store();
    s->path = path;
#ifndef _WIN32
    // exclusive advisory lock on a sidecar LOCK file, taken BEFORE replay:
    // replay truncates what it considers a torn tail, which must never run
    // against a log another process is actively appending to
    std::string lock_path = s->path + ".lock";
    s->lock_fd = open(lock_path.c_str(), O_CREAT | O_RDWR, 0644);
    if (s->lock_fd < 0 || flock(s->lock_fd, LOCK_EX | LOCK_NB) != 0) {
        fprintf(stderr, "kvstore: %s is locked by another process\n", path);
        if (s->lock_fd >= 0) close(s->lock_fd);
        delete s;
        return nullptr;
    }
#endif
    if (!replay(s)) {
#ifndef _WIN32
        close(s->lock_fd);
#endif
        delete s;
        return nullptr;
    }
    s->log = fopen(path, "ab");
    if (!s->log) {
#ifndef _WIN32
        close(s->lock_fd);
#endif
        delete s;
        return nullptr;
    }
    return s;
}

int kv_put(void* h, const char* table, const uint8_t* k, uint32_t kl,
           const uint8_t* v, uint32_t vl) {
    Store* s = (Store*)h;
    std::lock_guard<std::mutex> lock(s->mu);
    if (!s->log || !append_record(s->log, table, k, kl, v, vl)) return 0;
    // hand the record to the kernel immediately: a SIGKILL'd process must
    // not lose acknowledged writes (fsync durability stays in kv_flush)
    fflush(s->log);
    s->tables[table][std::string((const char*)k, kl)] =
        std::string((const char*)v, vl);
    return 1;
}

int kv_delete(void* h, const char* table, const uint8_t* k, uint32_t kl) {
    Store* s = (Store*)h;
    std::lock_guard<std::mutex> lock(s->mu);
    if (!s->log || !append_record(s->log, table, k, kl, nullptr, TOMBSTONE))
        return 0;
    fflush(s->log);
    s->tables[table].erase(std::string((const char*)k, kl));
    return 1;
}

int kv_get(void* h, const char* table, const uint8_t* k, uint32_t kl,
           uint8_t** out, uint32_t* out_len) {
    Store* s = (Store*)h;
    std::lock_guard<std::mutex> lock(s->mu);
    auto t = s->tables.find(table);
    if (t == s->tables.end()) return 0;
    auto it = t->second.find(std::string((const char*)k, kl));
    if (it == t->second.end()) return 0;
    *out = (uint8_t*)malloc(it->second.size());
    memcpy(*out, it->second.data(), it->second.size());
    *out_len = (uint32_t)it->second.size();
    return 1;
}

void kv_free(uint8_t* buf) { free(buf); }

int kv_flush(void* h) {
    Store* s = (Store*)h;
    std::lock_guard<std::mutex> lock(s->mu);
    if (fflush(s->log) != 0) return 0;
#ifndef _WIN32
    fsync(fileno(s->log));
#endif
    return 1;
}

int kv_compact(void* h) {
    Store* s = (Store*)h;
    std::lock_guard<std::mutex> lock(s->mu);
    std::string tmp = s->path + ".compact";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return 0;
    for (auto& [table, entries] : s->tables)
        for (auto& [k, v] : entries)
            if (!append_record(f, table, (const uint8_t*)k.data(),
                               (uint32_t)k.size(), (const uint8_t*)v.data(),
                               (uint32_t)v.size())) {
                fclose(f);
                return 0;
            }
    fflush(f);
#ifndef _WIN32
    fsync(fileno(f));
#endif
    fclose(f);
    fclose(s->log);
    s->log = nullptr;
    if (rename(tmp.c_str(), s->path.c_str()) != 0) {
        s->log = fopen(s->path.c_str(), "ab");  // keep the store usable
        return 0;
    }
    s->log = fopen(s->path.c_str(), "ab");
    return s->log != nullptr;
}

void* kv_scan_start(void* h, const char* table) {
    Store* s = (Store*)h;
    std::lock_guard<std::mutex> lock(s->mu);
    ScanIter* it = new ScanIter();
    auto t = s->tables.find(table);
    if (t != s->tables.end())
        for (auto& [k, v] : t->second) it->entries.emplace_back(k, v);
    return it;
}

int kv_scan_next(void* iter, uint8_t** k, uint32_t* kl, uint8_t** v,
                 uint32_t* vl) {
    ScanIter* it = (ScanIter*)iter;
    if (it->pos >= it->entries.size()) return 0;
    auto& [key, val] = it->entries[it->pos++];
    *k = (uint8_t*)malloc(key.size());
    memcpy(*k, key.data(), key.size());
    *kl = (uint32_t)key.size();
    *v = (uint8_t*)malloc(val.size());
    memcpy(*v, val.data(), val.size());
    *vl = (uint32_t)val.size();
    return 1;
}

void kv_scan_end(void* iter) { delete (ScanIter*)iter; }

void kv_close(void* h) {
    Store* s = (Store*)h;
    {
        std::lock_guard<std::mutex> lock(s->mu);
        if (s->log) {
            fflush(s->log);
#ifndef _WIN32
            fsync(fileno(s->log));  // close implies the durability barrier
#endif
            fclose(s->log);
        }
#ifndef _WIN32
        if (s->lock_fd >= 0) close(s->lock_fd);  // releases the flock
#endif
    }
    delete s;
}

}  // extern "C"
