// Native secp256k1 ECDSA public-key recovery: the sender-recovery hot
// path of L1 block import (parity seat: the reference's batched
// recover_transaction_senders ahead of execution; behavioral parity with
// this repo's ethrex_tpu/crypto/secp256k1.py, which remains the
// reference implementation and the differential-fuzz oracle).
//
// Scope: recovery only (the consensus-critical op).  Signing keeps the
// RFC 6979 pure-Python path — it never sits on the import critical path.
//
// Design:
//   * 4x64-limb field arithmetic with __int128 accumulators; reduction
//     exploits the special forms 2^256 = 0x1000003D1 (mod P) and
//     2^256 = NC (mod N, NC 129 bits).
//   * Jacobian coordinates; u1*G + u2*R via Shamir's trick (the same
//     shape as the Python oracle, so edge cases line up 1:1).
//   * No global state, no allocation: every entry point is pure and
//     thread-safe, so ctypes' GIL release during the call gives a
//     Python thread pool real parallelism (the whole point).
//
// Build: gcc -O3 -shared -fPIC -o libsecp256k1.so secp256k1.c
// ctypes binder + availability probe: ethrex_tpu/crypto/native_secp256k1.py

#include <stdint.h>
#include <string.h>

typedef unsigned __int128 u128;

typedef struct { uint64_t d[4]; } u256;  // little-endian limbs

// ---------------------------------------------------------------------------
// constants

// field prime P = 2^256 - 0x1000003D1
static const u256 FIELD_P = {{0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL,
                              0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL}};
// group order N
static const u256 ORDER_N = {{0xBFD25E8CD0364141ULL, 0xBAAEDCE6AF48A03BULL,
                              0xFFFFFFFFFFFFFFFEULL, 0xFFFFFFFFFFFFFFFFULL}};
// NC = 2^256 - N (129 bits; limb 2 is the 2^128 bit)
static const uint64_t NC0 = 0x402DA1732FC9BEBFULL;
static const uint64_t NC1 = 0x4551231950B75FC4ULL;
static const uint64_t NC2 = 1ULL;
// 2^256 mod P
static const uint64_t PC0 = 0x1000003D1ULL;

static const u256 GEN_X = {{0x59F2815B16F81798ULL, 0x029BFCDB2DCE28D9ULL,
                            0x55A06295CE870B07ULL, 0x79BE667EF9DCBBACULL}};
static const u256 GEN_Y = {{0x9C47D08FFB10D4B8ULL, 0xFD17B448A6855419ULL,
                            0x5DA4FBFC0E1108A8ULL, 0x483ADA7726A3C465ULL}};

// ---------------------------------------------------------------------------
// 256-bit helpers

static void u256_from_be(u256 *r, const uint8_t b[32]) {
    for (int i = 0; i < 4; i++) {
        uint64_t v = 0;
        for (int j = 0; j < 8; j++)
            v = (v << 8) | b[(3 - i) * 8 + j];
        r->d[i] = v;
    }
}

static void u256_to_be(const u256 *a, uint8_t b[32]) {
    for (int i = 0; i < 4; i++) {
        uint64_t v = a->d[3 - i];
        for (int j = 7; j >= 0; j--) {
            b[i * 8 + j] = (uint8_t)(v & 0xFF);
            v >>= 8;
        }
    }
}

static int u256_is_zero(const u256 *a) {
    return (a->d[0] | a->d[1] | a->d[2] | a->d[3]) == 0;
}

static int u256_cmp(const u256 *a, const u256 *b) {
    for (int i = 3; i >= 0; i--) {
        if (a->d[i] < b->d[i]) return -1;
        if (a->d[i] > b->d[i]) return 1;
    }
    return 0;
}

// r = a - b, returns borrow
static uint64_t u256_sub(u256 *r, const u256 *a, const u256 *b) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 t = (u128)a->d[i] - b->d[i] - (uint64_t)borrow;
        r->d[i] = (uint64_t)t;
        borrow = (t >> 64) & 1;  // 1 when the subtraction wrapped
    }
    return (uint64_t)borrow;
}

// r = a + b, returns carry
static uint64_t u256_add(u256 *r, const u256 *a, const u256 *b) {
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 t = (u128)a->d[i] + b->d[i] + (uint64_t)carry;
        r->d[i] = (uint64_t)t;
        carry = t >> 64;
    }
    return (uint64_t)carry;
}

static int u256_bit(const u256 *a, int i) {
    return (int)((a->d[i >> 6] >> (i & 63)) & 1);
}

static int u256_bitlen(const u256 *a) {
    for (int i = 3; i >= 0; i--) {
        if (a->d[i]) {
            int n = 64 * i;
            uint64_t v = a->d[i];
            while (v) { n++; v >>= 1; }
            return n;
        }
    }
    return 0;
}

// 512-bit product a*b -> lo/hi halves
static void u256_mul_wide(const u256 *a, const u256 *b, u256 *lo, u256 *hi) {
    uint64_t w[8] = {0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 t = (u128)a->d[i] * b->d[j] + w[i + j] + (uint64_t)carry;
            w[i + j] = (uint64_t)t;
            carry = t >> 64;
        }
        w[i + 4] = (uint64_t)carry;
    }
    memcpy(lo->d, w, 32);
    memcpy(hi->d, w + 4, 32);
}

// ---------------------------------------------------------------------------
// arithmetic mod P (2^256 = PC0 mod P)

static void fe_reduce_once(u256 *a) {
    if (u256_cmp(a, &FIELD_P) >= 0)
        u256_sub(a, a, &FIELD_P);
}

static void fe_add(u256 *r, const u256 *a, const u256 *b) {
    uint64_t carry = u256_add(r, a, b);
    if (carry) {
        // r = r + 2^256 mod P = r + PC0
        u256 pc = {{PC0, 0, 0, 0}};
        u256_add(r, r, &pc);  // cannot carry again: r < P after wrap
    }
    fe_reduce_once(r);
}

static void fe_sub(u256 *r, const u256 *a, const u256 *b) {
    uint64_t borrow = u256_sub(r, a, b);
    if (borrow)
        u256_add(r, r, &FIELD_P);
}

static void fe_mul(u256 *r, const u256 *a, const u256 *b) {
    u256 lo, hi;
    u256_mul_wide(a, b, &lo, &hi);
    // fold hi*PC0 into lo: hi*PC0 is at most 289 bits
    uint64_t w[5] = {0};
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 t = (u128)hi.d[i] * PC0 + (uint64_t)carry;
        w[i] = (uint64_t)t;
        carry = t >> 64;
    }
    w[4] = (uint64_t)carry;
    u256 t0 = {{w[0], w[1], w[2], w[3]}};
    uint64_t c2 = u256_add(&t0, &lo, &t0);
    uint64_t top = w[4] + c2;  // < 2^34
    // fold top*2^256 = top*PC0
    u128 t = (u128)top * PC0 + t0.d[0];
    t0.d[0] = (uint64_t)t;
    u128 cc = t >> 64;
    for (int i = 1; i < 4 && cc; i++) {
        t = (u128)t0.d[i] + (uint64_t)cc;
        t0.d[i] = (uint64_t)t;
        cc = t >> 64;
    }
    if (cc) {  // wrapped 2^256 once more
        u256 pc = {{PC0, 0, 0, 0}};
        u256_add(&t0, &t0, &pc);
    }
    fe_reduce_once(&t0);
    *r = t0;
}

static void fe_sqr(u256 *r, const u256 *a) { fe_mul(r, a, a); }

// r = a^e mod P (square-and-multiply)
static void fe_pow(u256 *r, const u256 *a, const u256 *e) {
    u256 acc = {{1, 0, 0, 0}};
    int bits = u256_bitlen(e);
    for (int i = bits - 1; i >= 0; i--) {
        fe_sqr(&acc, &acc);
        if (u256_bit(e, i))
            fe_mul(&acc, &acc, a);
    }
    *r = acc;
}

static void fe_inv(u256 *r, const u256 *a) {
    u256 e = FIELD_P;
    u256 two = {{2, 0, 0, 0}};
    u256_sub(&e, &e, &two);
    fe_pow(r, a, &e);
}

// sqrt via a^((P+1)/4); caller must verify the square
static void fe_sqrt(u256 *r, const u256 *a) {
    // (P+1)/4 = (P - 3)/4 + 1, computed once here by shifting P+1
    u256 e = FIELD_P;
    u256 one = {{1, 0, 0, 0}};
    u256_add(&e, &e, &one);  // P+1 fits: P < 2^256 - 1... (no carry: P ends FC2F)
    for (int s = 0; s < 2; s++) {
        uint64_t carry = 0;
        for (int i = 3; i >= 0; i--) {
            uint64_t nxt = e.d[i] & 1;
            e.d[i] = (e.d[i] >> 1) | (carry << 63);
            carry = nxt;
        }
    }
    fe_pow(r, a, &e);
}

// ---------------------------------------------------------------------------
// arithmetic mod N (2^256 = NC mod N, NC = NC2*2^128 + NC1*2^64 + NC0)

static void sc_reduce_once(u256 *a) {
    if (u256_cmp(a, &ORDER_N) >= 0)
        u256_sub(a, a, &ORDER_N);
}

// w[off..] += a * m, propagating the carry through wlen limbs
static void sc_addmul(uint64_t *w, int wlen, int off, const u256 *a,
                      uint64_t m) {
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 t = (u128)a->d[i] * m + w[off + i] + (uint64_t)carry;
        w[off + i] = (uint64_t)t;
        carry = t >> 64;
    }
    for (int i = off + 4; i < wlen && carry; i++) {
        u128 t = (u128)w[i] + (uint64_t)carry;
        w[i] = (uint64_t)t;
        carry = t >> 64;
    }
}

// w[0..6] = lo + hi*NC (hi*NC < 2^385, so the accumulator needs 7 limbs)
static void sc_fold(uint64_t w[7], const uint64_t lo[4], const u256 *hi) {
    for (int i = 0; i < 4; i++)
        w[i] = lo[i];
    w[4] = w[5] = w[6] = 0;
    sc_addmul(w, 7, 0, hi, NC0);
    sc_addmul(w, 7, 1, hi, NC1);
    sc_addmul(w, 7, 2, hi, NC2);
}

static void sc_mul(u256 *r, const u256 *a, const u256 *b) {
    u256 lo, hi;
    u256_mul_wide(a, b, &lo, &hi);
    // fold 1: 512 -> <= 386 bits
    uint64_t w[7];
    sc_fold(w, lo.d, &hi);
    // fold 2: the 130-bit overflow limbs fold to <= 260 bits
    u256 hi2 = {{w[4], w[5], w[6], 0}};
    uint64_t v[7];
    sc_fold(v, w, &hi2);
    // fold 3: v[4] is at most a few bits; result < 2^256 + 2^134
    u256 hi3 = {{v[4], v[5], 0, 0}};
    uint64_t u[7];
    sc_fold(u, v, &hi3);
    u256 out = {{u[0], u[1], u[2], u[3]}};
    if (u[4]) {
        // one final wrap: += 2^256 mod N = NC (cannot carry again)
        u256 nc = {{NC0, NC1, NC2, 0}};
        u256_add(&out, &out, &nc);
    }
    sc_reduce_once(&out);
    sc_reduce_once(&out);
    *r = out;
}

static void sc_pow(u256 *r, const u256 *a, const u256 *e) {
    u256 acc = {{1, 0, 0, 0}};
    int bits = u256_bitlen(e);
    for (int i = bits - 1; i >= 0; i--) {
        sc_mul(&acc, &acc, &acc);
        if (u256_bit(e, i))
            sc_mul(&acc, &acc, a);
    }
    *r = acc;
}

static void sc_inv(u256 *r, const u256 *a) {
    u256 e = ORDER_N;
    u256 two = {{2, 0, 0, 0}};
    u256_sub(&e, &e, &two);
    sc_pow(r, a, &e);
}

// ---------------------------------------------------------------------------
// Jacobian point arithmetic mod P

typedef struct { u256 x, y, z; } jpoint;  // z == 0 => infinity

static void jp_set_infinity(jpoint *p) {
    memset(p, 0, sizeof(*p));
    p->y.d[0] = 1;
}

static int jp_is_infinity(const jpoint *p) { return u256_is_zero(&p->z); }

static void jp_from_affine(jpoint *p, const u256 *x, const u256 *y) {
    p->x = *x;
    p->y = *y;
    memset(&p->z, 0, sizeof(u256));
    p->z.d[0] = 1;
}

static void jp_double(jpoint *r, const jpoint *a) {
    if (jp_is_infinity(a) || u256_is_zero(&a->y)) {
        jp_set_infinity(r);
        return;
    }
    u256 s, m, x2, y2, z2, t, y4;
    // S = 4*X*Y^2
    fe_sqr(&t, &a->y);
    fe_mul(&s, &a->x, &t);
    fe_add(&s, &s, &s);
    fe_add(&s, &s, &s);
    // M = 3*X^2 (a = 0)
    fe_sqr(&m, &a->x);
    fe_add(&x2, &m, &m);
    fe_add(&m, &x2, &m);
    // X' = M^2 - 2S
    fe_sqr(&x2, &m);
    fe_sub(&x2, &x2, &s);
    fe_sub(&x2, &x2, &s);
    // Y' = M*(S - X') - 8*Y^4
    fe_sqr(&y4, &t);            // Y^4
    fe_add(&y4, &y4, &y4);
    fe_add(&y4, &y4, &y4);
    fe_add(&y4, &y4, &y4);      // 8*Y^4
    fe_sub(&t, &s, &x2);
    fe_mul(&y2, &m, &t);
    fe_sub(&y2, &y2, &y4);
    // Z' = 2*Y*Z
    fe_mul(&z2, &a->y, &a->z);
    fe_add(&z2, &z2, &z2);
    r->x = x2;
    r->y = y2;
    r->z = z2;
}

static void jp_add(jpoint *r, const jpoint *a, const jpoint *b) {
    if (jp_is_infinity(a)) { *r = *b; return; }
    if (jp_is_infinity(b)) { *r = *a; return; }
    u256 z1z1, z2z2, u1, u2, s1, s2, t;
    fe_sqr(&z1z1, &a->z);
    fe_sqr(&z2z2, &b->z);
    fe_mul(&u1, &a->x, &z2z2);
    fe_mul(&u2, &b->x, &z1z1);
    fe_mul(&t, &a->y, &b->z);
    fe_mul(&s1, &t, &z2z2);
    fe_mul(&t, &b->y, &a->z);
    fe_mul(&s2, &t, &z1z1);
    if (u256_cmp(&u1, &u2) == 0) {
        if (u256_cmp(&s1, &s2) != 0) {
            jp_set_infinity(r);
            return;
        }
        jp_double(r, a);
        return;
    }
    u256 h, rr, hh, hhh, v, x3, y3, z3;
    fe_sub(&h, &u2, &u1);
    fe_sub(&rr, &s2, &s1);
    fe_sqr(&hh, &h);
    fe_mul(&hhh, &hh, &h);
    fe_mul(&v, &u1, &hh);
    fe_sqr(&x3, &rr);
    fe_sub(&x3, &x3, &hhh);
    fe_sub(&x3, &x3, &v);
    fe_sub(&x3, &x3, &v);
    fe_sub(&t, &v, &x3);
    fe_mul(&y3, &rr, &t);
    fe_mul(&t, &s1, &hhh);
    fe_sub(&y3, &y3, &t);
    fe_mul(&t, &h, &a->z);
    fe_mul(&z3, &t, &b->z);
    r->x = x3;
    r->y = y3;
    r->z = z3;
}

static void jp_neg(jpoint *r, const jpoint *a) {
    *r = *a;
    if (!u256_is_zero(&a->y))
        u256_sub(&r->y, &FIELD_P, &a->y);
}

static void u256_shr1(u256 *a) {
    a->d[0] = (a->d[0] >> 1) | (a->d[1] << 63);
    a->d[1] = (a->d[1] >> 1) | (a->d[2] << 63);
    a->d[2] = (a->d[2] >> 1) | (a->d[3] << 63);
    a->d[3] >>= 1;
}

// width-w non-adjacent form: digits[i] is 0 or odd in
// (-2^(w-1), 2^(w-1)); at most one nonzero digit in any w consecutive
// positions, so the add density drops to ~1/(w+1) vs 1/2 for plain
// binary.  Returns the digit count (<= 257 for 256-bit scalars).
static int wnaf_expand(int8_t *digits, const u256 *k, int w) {
    u256 t = *k;
    uint64_t mask = (((uint64_t)1) << w) - 1;
    uint64_t half = ((uint64_t)1) << (w - 1);
    int len = 0;
    while (!u256_is_zero(&t)) {
        int64_t d = 0;
        if (t.d[0] & 1) {
            uint64_t m = t.d[0] & mask;
            if (m >= half) {
                d = (int64_t)m - (int64_t)(mask + 1);
                u256 up = {{(uint64_t)(-d), 0, 0, 0}};
                u256_add(&t, &t, &up);
            } else {
                d = (int64_t)m;
                u256 down = {{m, 0, 0, 0}};
                u256_sub(&t, &t, &down);
            }
        }
        digits[len++] = (int8_t)d;
        u256_shr1(&t);
    }
    return len;
}

// cached odd multiples of G for w=7 wNAF: {1, 3, ..., 63} * G.
// Built once per process (double-checked under a spinlock: recover_batch
// runs concurrently on pool threads); ~32 adds, amortized to nothing.
#define GTAB_W 7
#define GTAB_SIZE 32
#define RTAB_W 4
#define RTAB_SIZE 4
static jpoint G_TAB[GTAB_SIZE];
static int g_tab_ready = 0;
static int g_tab_lock = 0;

static void ensure_g_table(void) {
    if (__atomic_load_n(&g_tab_ready, __ATOMIC_ACQUIRE))
        return;
    while (__atomic_exchange_n(&g_tab_lock, 1, __ATOMIC_ACQUIRE))
        ;
    if (!g_tab_ready) {
        jpoint dbl;
        jp_from_affine(&G_TAB[0], &GEN_X, &GEN_Y);
        jp_double(&dbl, &G_TAB[0]);
        for (int i = 1; i < GTAB_SIZE; i++)
            jp_add(&G_TAB[i], &G_TAB[i - 1], &dbl);
        __atomic_store_n(&g_tab_ready, 1, __ATOMIC_RELEASE);
    }
    __atomic_store_n(&g_tab_lock, 0, __ATOMIC_RELEASE);
}

// k1*G + k2*P2 via interleaved wNAF (one shared doubling ladder, per-
// scalar add tables).  Same result as the oracle's _double_mul; ~1.5x
// fewer field mults than the binary Shamir ladder it replaced.
static void jp_dual_mul(jpoint *r, const u256 *k1, const u256 *k2,
                        const jpoint *p2) {
    ensure_g_table();
    int8_t n1[264], n2[264];
    int l1 = wnaf_expand(n1, k1, GTAB_W);
    int l2 = wnaf_expand(n2, k2, RTAB_W);
    jpoint t2[RTAB_SIZE], dbl;
    t2[0] = *p2;
    jp_double(&dbl, p2);
    for (int i = 1; i < RTAB_SIZE; i++)
        jp_add(&t2[i], &t2[i - 1], &dbl);
    jpoint acc, tmp;
    jp_set_infinity(&acc);
    int len = l1 > l2 ? l1 : l2;
    for (int i = len - 1; i >= 0; i--) {
        jp_double(&acc, &acc);
        int d;
        if (i < l1 && (d = n1[i]) != 0) {
            if (d > 0) {
                jp_add(&acc, &acc, &G_TAB[(d - 1) >> 1]);
            } else {
                jp_neg(&tmp, &G_TAB[(-d - 1) >> 1]);
                jp_add(&acc, &acc, &tmp);
            }
        }
        if (i < l2 && (d = n2[i]) != 0) {
            if (d > 0) {
                jp_add(&acc, &acc, &t2[(d - 1) >> 1]);
            } else {
                jp_neg(&tmp, &t2[(-d - 1) >> 1]);
                jp_add(&acc, &acc, &tmp);
            }
        }
    }
    *r = acc;
}

// affine (x, y) out; returns 0 at infinity
static int jp_to_affine(const jpoint *p, u256 *x, u256 *y) {
    if (jp_is_infinity(p))
        return 0;
    u256 zi, zi2, zi3;
    fe_inv(&zi, &p->z);
    fe_sqr(&zi2, &zi);
    fe_mul(&zi3, &zi2, &zi);
    fe_mul(x, &p->x, &zi2);
    fe_mul(y, &p->y, &zi3);
    return 1;
}

static int is_on_curve(const u256 *x, const u256 *y) {
    u256 lhs, rhs, t;
    fe_sqr(&lhs, y);
    fe_sqr(&t, x);
    fe_mul(&rhs, &t, x);
    u256 seven = {{7, 0, 0, 0}};
    fe_add(&rhs, &rhs, &seven);
    return u256_cmp(&lhs, &rhs) == 0;
}

// ---------------------------------------------------------------------------
// public API

// Recover the public key from (msg32, r, s, rec_id).  Writes 64 bytes
// (x || y, big-endian) to out64.  Returns 1 on success, 0 when the
// signature is invalid — the SAME acceptance set as the Python oracle
// (r, s in [1, N); rec_id in [0, 3]; r + N < P when rec_id >= 2;
// x^3 + 7 a quadratic residue; result not infinity and on the curve).
int secp256k1_recover(const uint8_t msg32[32], const uint8_t r32[32],
                      const uint8_t s32[32], int rec_id,
                      uint8_t out64[64]) {
    if (rec_id < 0 || rec_id > 3)
        return 0;
    u256 r, s, z;
    u256_from_be(&r, r32);
    u256_from_be(&s, s32);
    u256_from_be(&z, msg32);
    if (u256_is_zero(&r) || u256_cmp(&r, &ORDER_N) >= 0)
        return 0;
    if (u256_is_zero(&s) || u256_cmp(&s, &ORDER_N) >= 0)
        return 0;
    sc_reduce_once(&z);  // z < 2^256 < 2N: one conditional subtract
    // x = r (+ N when rec_id >= 2); must stay below P
    u256 x = r;
    if (rec_id >= 2) {
        uint64_t carry = u256_add(&x, &x, &ORDER_N);
        if (carry || u256_cmp(&x, &FIELD_P) >= 0)
            return 0;
    }
    // y from the curve equation; reject non-residues
    u256 y_sq, y, chk;
    u256 seven = {{7, 0, 0, 0}};
    fe_sqr(&y_sq, &x);
    fe_mul(&y_sq, &y_sq, &x);
    fe_add(&y_sq, &y_sq, &seven);
    fe_sqrt(&y, &y_sq);
    fe_sqr(&chk, &y);
    if (u256_cmp(&chk, &y_sq) != 0)
        return 0;
    if ((int)(y.d[0] & 1) != (rec_id & 1))
        u256_sub(&y, &FIELD_P, &y);
    // Q = r^-1 * (s*R - z*G) = u1*G + u2*R with u1 = -z/r, u2 = s/r
    u256 r_inv, u1, u2;
    sc_inv(&r_inv, &r);
    sc_mul(&u2, &s, &r_inv);
    sc_mul(&u1, &z, &r_inv);
    if (!u256_is_zero(&u1))
        u256_sub(&u1, &ORDER_N, &u1);  // negate mod N
    jpoint rp, q;
    jp_from_affine(&rp, &x, &y);
    jp_dual_mul(&q, &u1, &u2, &rp);
    u256 qx, qy;
    if (!jp_to_affine(&q, &qx, &qy))
        return 0;
    if (!is_on_curve(&qx, &qy))
        return 0;
    u256_to_be(&qx, out64);
    u256_to_be(&qy, out64 + 32);
    return 1;
}

// Batched recovery: n independent inputs, each 32-byte msg/r/s plus an
// int32 rec_id; out is n*64 bytes of pubkeys, ok is n result flags.
// Inputs are packed contiguously so one GIL-releasing ctypes call covers
// a whole block; the loop itself is trivially parallel-safe (no shared
// state), so several pool threads can run disjoint batches at once.
int secp256k1_recover_batch(const uint8_t *msgs, const uint8_t *rs,
                            const uint8_t *ss, const int32_t *rec_ids,
                            int n, uint8_t *out, uint8_t *ok) {
    for (int i = 0; i < n; i++)
        ok[i] = (uint8_t)secp256k1_recover(
            msgs + 32 * i, rs + 32 * i, ss + 32 * i, rec_ids[i],
            out + 64 * i);
    return n;
}
