// Native Merkle-Patricia-Trie engine: the merkleize hot path of block
// import (parity seat: the reference's ethrex-trie + its trie-optimization
// rounds, /root/reference/crates/common/trie; behavioral parity with this
// repo's ethrex_tpu/trie/trie.py, which remains the reference
// implementation and the differential-test oracle).
//
// Design:
//   * The engine OWNS a node map (keccak(rlp) -> rlp bytes) that persists
//     across batch applies, so Python feeds each node at most once.
//   * One C call applies a whole ordered batch of (key, value|delete) ops
//     against a root and commits: new nodes land in the map AND in a
//     "fresh" list Python drains to persist into its own store.
//   * Missing nodes (pruned tables) abort the apply before any mutation
//     and report the full frontier of missing hashes, so the caller feeds
//     them and retries — a few round trips per batch, not per node.
//
// Build: g++ -O3 -shared -fPIC -o libmpt.so mpt.cpp keccak.c
// (keccak.c provides keccak256; see native/keccak.c)

#include <cstdint>
#include <cstring>
#include <memory>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

extern "C" void keccak256(const uint8_t *in, size_t len, uint8_t *out);

namespace {

using bytes = std::string;

struct HashKey {
    std::size_t operator()(const bytes &b) const {
        uint64_t v;
        std::memcpy(&v, b.data(), 8);
        return static_cast<std::size_t>(v);
    }
};

bytes keccak(const bytes &data) {
    bytes out(32, '\0');
    keccak256(reinterpret_cast<const uint8_t *>(data.data()), data.size(),
              reinterpret_cast<uint8_t *>(&out[0]));
    return out;
}

// ---------------------------------------------------------------------------
// Minimal RLP
// ---------------------------------------------------------------------------

struct RlpItem {
    bool is_list = false;
    bytes str;                  // string payload
    bytes raw;                  // full encoding (lists keep raw for reuse)
    std::vector<RlpItem> items; // list members
};

struct RlpError {};

size_t rlp_parse(const uint8_t *d, size_t len, size_t pos, RlpItem &out);

size_t rlp_parse_payload(const uint8_t *d, size_t pos, size_t plen,
                         size_t hdr, bool is_list, RlpItem &out) {
    out.is_list = is_list;
    out.raw.assign(reinterpret_cast<const char *>(d + pos), hdr + plen);
    if (!is_list) {
        out.str.assign(reinterpret_cast<const char *>(d + pos + hdr), plen);
    } else {
        size_t p = pos + hdr, end = pos + hdr + plen;
        while (p < end) {
            RlpItem sub;
            p = rlp_parse(d, end, p, sub);
            out.items.push_back(std::move(sub));
        }
        if (p != end) throw RlpError{};
    }
    return pos + hdr + plen;
}

size_t rlp_parse(const uint8_t *d, size_t len, size_t pos, RlpItem &out) {
    if (pos >= len) throw RlpError{};
    uint8_t b = d[pos];
    if (b < 0x80) {
        out.is_list = false;
        out.str.assign(1, static_cast<char>(b));
        out.raw = out.str;
        return pos + 1;
    }
    auto need = [&](size_t n) { if (pos + n > len) throw RlpError{}; };
    if (b <= 0xB7) {
        size_t plen = b - 0x80;
        need(1 + plen);
        return rlp_parse_payload(d, pos, plen, 1, false, out);
    }
    if (b <= 0xBF) {
        size_t ll = b - 0xB7;
        need(1 + ll);
        size_t plen = 0;
        for (size_t i = 0; i < ll; i++) plen = (plen << 8) | d[pos + 1 + i];
        need(1 + ll + plen);
        return rlp_parse_payload(d, pos, plen, 1 + ll, false, out);
    }
    if (b <= 0xF7) {
        size_t plen = b - 0xC0;
        need(1 + plen);
        return rlp_parse_payload(d, pos, plen, 1, true, out);
    }
    size_t ll = b - 0xF7;
    need(1 + ll);
    size_t plen = 0;
    for (size_t i = 0; i < ll; i++) plen = (plen << 8) | d[pos + 1 + i];
    need(1 + ll + plen);
    return rlp_parse_payload(d, pos, plen, 1 + ll, true, out);
}

bytes rlp_len_prefix(size_t n, uint8_t base_short, uint8_t base_long) {
    bytes out;
    if (n <= 55) {
        out.push_back(static_cast<char>(base_short + n));
        return out;
    }
    bytes be;
    while (n) { be.insert(be.begin(), static_cast<char>(n & 0xFF)); n >>= 8; }
    out.push_back(static_cast<char>(base_long + be.size()));
    out += be;
    return out;
}

bytes rlp_encode_string(const bytes &s) {
    if (s.size() == 1 && static_cast<uint8_t>(s[0]) < 0x80) return s;
    return rlp_len_prefix(s.size(), 0x80, 0xB7) + s;
}

bytes rlp_encode_list_payload(const bytes &payload) {
    return rlp_len_prefix(payload.size(), 0xC0, 0xF7) + payload;
}

// ---------------------------------------------------------------------------
// Trie nodes
// ---------------------------------------------------------------------------

enum Kind : uint8_t { LEAF, EXT, BRANCH, REF_HASH, REF_INLINE };

struct Node {
    Kind kind;
    bytes path;   // nibbles (one per byte), leaf/ext
    bytes value;  // leaf value / branch value
    Node *children[16] = {nullptr};
    Node *child = nullptr; // ext
    bytes ref;    // 32-byte hash (REF_HASH) or raw rlp slice (REF_INLINE)
};

struct MissingError { bytes hash; };

// host resolver: returns 1 when it fed the node (via mpt_load), 0 if the
// node does not exist anywhere — one upcall per unique node, no restarts
typedef int (*resolver_fn)(const uint8_t *hash32);

struct Engine {
    std::unordered_map<bytes, bytes, HashKey> nodes;
    std::vector<bytes> fresh;        // rlp of nodes created by last commit
    std::unordered_set<bytes, HashKey> missing;
    std::vector<std::unique_ptr<Node>> arena;
    resolver_fn resolver = nullptr;

    Node *alloc() {
        arena.emplace_back(new Node());
        return arena.back().get();
    }

    Node *make_ref_hash(const bytes &h) {
        Node *n = alloc();
        n->kind = REF_HASH;
        n->ref = h;
        return n;
    }

    // hex-prefix decode into nibbles + leaf flag
    static void hp_decode(const bytes &data, bytes &nibbles, bool &leaf) {
        if (data.empty()) throw RlpError{};
        uint8_t flag = static_cast<uint8_t>(data[0]) >> 4;
        leaf = (flag & 2) != 0;
        nibbles.clear();
        if (flag & 1) nibbles.push_back(data[0] & 0xF);
        for (size_t i = 1; i < data.size(); i++) {
            nibbles.push_back((static_cast<uint8_t>(data[i]) >> 4));
            nibbles.push_back(data[i] & 0xF);
        }
    }

    static bytes hp_encode(const bytes &nib, bool leaf) {
        uint8_t flag = leaf ? 2 : 0;
        bytes out;
        size_t i = 0;
        if (nib.size() % 2) {
            out.push_back(static_cast<char>(((flag + 1) << 4) | nib[0]));
            i = 1;
        } else {
            out.push_back(static_cast<char>(flag << 4));
        }
        for (; i + 1 < nib.size(); i += 2)
            out.push_back(static_cast<char>((nib[i] << 4) | nib[i + 1]));
        return out;
    }

    Node *decode(const RlpItem &item) {
        if (!item.is_list) {
            if (item.str.empty()) return nullptr;
            Node *n = alloc();
            n->kind = REF_HASH;
            n->ref = item.str;
            return n;
        }
        if (item.items.size() == 17) {
            Node *n = alloc();
            n->kind = BRANCH;
            for (int i = 0; i < 16; i++) {
                const RlpItem &c = item.items[i];
                if (c.is_list) {
                    Node *r = alloc();
                    r->kind = REF_INLINE;
                    r->ref = c.raw;
                    n->children[i] = r;
                } else if (c.str.empty()) {
                    n->children[i] = nullptr;
                } else {
                    n->children[i] = make_ref_hash(c.str);
                }
            }
            n->value = item.items[16].str;
            return n;
        }
        if (item.items.size() == 2) {
            bytes nib;
            bool leaf;
            hp_decode(item.items[0].str, nib, leaf);
            Node *n = alloc();
            n->path = nib;
            if (leaf) {
                n->kind = LEAF;
                n->value = item.items[1].str;
            } else {
                n->kind = EXT;
                const RlpItem &c = item.items[1];
                if (c.is_list) {
                    Node *r = alloc();
                    r->kind = REF_INLINE;
                    r->ref = c.raw;
                    n->child = r;
                } else {
                    n->child = make_ref_hash(c.str);
                }
            }
            return n;
        }
        throw RlpError{};
    }

    Node *decode_bytes(const bytes &raw) {
        RlpItem item;
        rlp_parse(reinterpret_cast<const uint8_t *>(raw.data()), raw.size(),
                  0, item);
        return decode(item);
    }

    Node *resolve(Node *n) {
        while (n && (n->kind == REF_HASH || n->kind == REF_INLINE)) {
            if (n->kind == REF_INLINE) {
                n = decode_bytes(n->ref);
                continue;
            }
            auto it = nodes.find(n->ref);
            if (it == nodes.end()) {
                if (resolver &&
                    resolver(reinterpret_cast<const uint8_t *>(
                        n->ref.data()))) {
                    it = nodes.find(n->ref);
                    if (it != nodes.end()) {
                        n = decode_bytes(it->second);
                        continue;
                    }
                }
                throw MissingError{n->ref};
            }
            n = decode_bytes(it->second);
        }
        return n;
    }

    // ---- mutation (mirrors trie/trie.py exactly) ----------------------

    static size_t common_prefix(const bytes &a, const bytes &b) {
        size_t i = 0;
        while (i < a.size() && i < b.size() && a[i] == b[i]) i++;
        return i;
    }

    Node *make_leaf(const bytes &path, const bytes &value) {
        Node *n = alloc();
        n->kind = LEAF;
        n->path = path;
        n->value = value;
        return n;
    }

    Node *make_ext(const bytes &path, Node *child) {
        Node *n = alloc();
        n->kind = EXT;
        n->path = path;
        n->child = child;
        return n;
    }

    Node *split(const bytes &lpath, const bytes &lvalue, const bytes &path,
                const bytes &value) {
        size_t common = common_prefix(lpath, path);
        Node *branch = alloc();
        branch->kind = BRANCH;
        const bytes *paths[2] = {&lpath, &path};
        const bytes *vals[2] = {&lvalue, &value};
        for (int i = 0; i < 2; i++) {
            bytes rest = paths[i]->substr(common);
            if (rest.empty()) {
                branch->value = *vals[i];
            } else {
                branch->children[static_cast<uint8_t>(rest[0])] =
                    make_leaf(rest.substr(1), *vals[i]);
            }
        }
        if (common) return make_ext(lpath.substr(0, common), branch);
        return branch;
    }

    Node *insert(Node *node, const bytes &path, const bytes &value) {
        node = resolve(node);
        if (!node) return make_leaf(path, value);
        if (node->kind == LEAF) {
            if (node->path == path) return make_leaf(path, value);
            return split(node->path, node->value, path, value);
        }
        if (node->kind == EXT) {
            const bytes &epath = node->path;
            size_t common = common_prefix(epath, path);
            if (common == epath.size()) {
                Node *child = insert(node->child, path.substr(common), value);
                return make_ext(epath, child);
            }
            Node *branch = alloc();
            branch->kind = BRANCH;
            bytes ext_rest = epath.substr(common + 1);
            Node *sub = ext_rest.empty()
                            ? node->child
                            : make_ext(ext_rest, node->child);
            branch->children[static_cast<uint8_t>(epath[common])] = sub;
            if (common < path.size()) {
                branch->children[static_cast<uint8_t>(path[common])] =
                    make_leaf(path.substr(common + 1), value);
            } else {
                branch->value = value;
            }
            if (common) return make_ext(path.substr(0, common), branch);
            return branch;
        }
        // branch
        Node *out = alloc();
        *out = *node;
        if (path.empty()) {
            out->value = value;
            return out;
        }
        uint8_t idx = path[0];
        out->children[idx] = insert(node->children[idx], path.substr(1),
                                    value);
        return out;
    }

    Node *merge_ext(const bytes &prefix, Node *child) {
        child = resolve(child);
        if (child->kind == LEAF)
            return make_leaf(prefix + child->path, child->value);
        if (child->kind == EXT)
            return make_ext(prefix + child->path, child->child);
        return make_ext(prefix, child);
    }

    Node *collapse_branch(Node *node) {
        int live = -1, count = 0;
        for (int i = 0; i < 16; i++)
            if (node->children[i]) { live = i; count++; }
        if (count == 0) {
            if (!node->value.empty()) return make_leaf(bytes(), node->value);
            return nullptr;
        }
        if (count == 1 && node->value.empty()) {
            bytes pre(1, static_cast<char>(live));
            return merge_ext(pre, node->children[live]);
        }
        return node;
    }

    Node *remove(Node *node, const bytes &path) {
        node = resolve(node);
        if (!node) return nullptr;
        if (node->kind == LEAF)
            return node->path == path ? nullptr : node;
        if (node->kind == EXT) {
            const bytes &epath = node->path;
            if (path.compare(0, epath.size(), epath) != 0 ||
                path.size() < epath.size())
                return node;
            Node *child = remove(node->child, path.substr(epath.size()));
            if (!child) return nullptr;
            return merge_ext(epath, child);
        }
        Node *out = alloc();
        *out = *node;
        if (path.empty()) {
            out->value.clear();
        } else {
            uint8_t idx = path[0];
            if (!out->children[idx]) return node;
            out->children[idx] = remove(out->children[idx], path.substr(1));
        }
        return collapse_branch(out);
    }

    // ---- encoding / commit -------------------------------------------

    bytes encode_fields(Node *n);

    bytes child_ref(Node *n) {
        if (n->kind == REF_HASH) return rlp_encode_string(n->ref);
        if (n->kind == REF_INLINE) return n->ref;
        bytes enc = encode_fields(n);
        if (enc.size() < 32) return enc;
        bytes h = keccak(enc);
        store_node(h, enc);
        return rlp_encode_string(h);
    }

    void store_node(const bytes &h, const bytes &enc) {
        auto it = nodes.find(h);
        if (it == nodes.end()) {
            nodes.emplace(h, enc);
            fresh.push_back(enc);
        }
    }

    bytes encode(Node *n) { return encode_fields(n); }

    bytes commit(Node *root, bytes &root_hash_out) {
        if (!root) {
            // keccak(rlp("")) — the empty trie root
            bytes enc = rlp_encode_string(bytes());
            root_hash_out = keccak(enc);
            return root_hash_out;
        }
        if (root->kind == REF_HASH) {
            root_hash_out = root->ref;
            return root_hash_out;
        }
        root = resolve(root);
        bytes enc = encode_fields(root);
        bytes h = keccak(enc);
        store_node(h, enc);
        root_hash_out = h;
        return h;
    }
};

bytes Engine::encode_fields(Node *n) {
    bytes payload;
    if (n->kind == LEAF) {
        payload += rlp_encode_string(hp_encode(n->path, true));
        payload += rlp_encode_string(n->value);
    } else if (n->kind == EXT) {
        payload += rlp_encode_string(hp_encode(n->path, false));
        payload += child_ref(n->child);
    } else if (n->kind == BRANCH) {
        for (int i = 0; i < 16; i++) {
            if (n->children[i])
                payload += child_ref(n->children[i]);
            else
                payload += rlp_encode_string(bytes());
        }
        payload += rlp_encode_string(n->value);
    } else {
        throw RlpError{};
    }
    return rlp_encode_list_payload(payload);
}

bytes nibbles_of(const uint8_t *key, size_t len) {
    bytes out;
    out.reserve(len * 2);
    for (size_t i = 0; i < len; i++) {
        out.push_back(key[i] >> 4);
        out.push_back(key[i] & 0xF);
    }
    return out;
}

const char EMPTY_ROOT_HEX[] =
    "\x56\xe8\x1f\x17\x1b\xcc\x55\xa6\xff\x83\x45\xe6\x92\xc0\xf8\x6e"
    "\x5b\x48\xe0\x1b\x99\x6c\xad\xc0\x01\x62\x2f\xb5\xe3\x63\xb4\x21";

} // namespace

// ---------------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------------

extern "C" {

void *mpt_new() { return new Engine(); }

void mpt_set_resolver(void *ep, resolver_fn fn) {
    static_cast<Engine *>(ep)->resolver = fn;
}

void mpt_free(void *e) { delete static_cast<Engine *>(e); }

// records: (u32 little-endian len | bytes)*
int mpt_load(void *ep, const uint8_t *data, size_t len) {
    Engine *e = static_cast<Engine *>(ep);
    size_t pos = 0;
    int n = 0;
    while (pos + 4 <= len) {
        uint32_t rl;
        std::memcpy(&rl, data + pos, 4);
        pos += 4;
        if (pos + rl > len) return -1;
        bytes raw(reinterpret_cast<const char *>(data + pos), rl);
        pos += rl;
        e->nodes.emplace(keccak(raw), std::move(raw));
        n++;
    }
    return pos == len ? n : -1;
}

// ops: (u32 klen | key | u32 vlen | value)*; vlen == 0 -> delete.
// Returns 0 ok, 1 missing nodes (mpt_missing), -1 malformed input.
int mpt_apply(void *ep, const uint8_t *root, const uint8_t *ops,
              size_t ops_len, uint8_t *new_root_out) {
    Engine *e = static_cast<Engine *>(ep);
    e->missing.clear();
    e->arena.clear();
    Node *r = nullptr;
    if (std::memcmp(root, EMPTY_ROOT_HEX, 32) != 0)
        r = e->make_ref_hash(bytes(reinterpret_cast<const char *>(root), 32));
    try {
        size_t pos = 0;
        while (pos < ops_len) {
            if (pos + 4 > ops_len) return -1;
            uint32_t klen;
            std::memcpy(&klen, ops + pos, 4);
            pos += 4;
            if (pos + klen + 4 > ops_len) return -1;
            bytes nib = nibbles_of(ops + pos, klen);
            pos += klen;
            uint32_t vlen;
            std::memcpy(&vlen, ops + pos, 4);
            pos += 4;
            if (pos + vlen > ops_len) return -1;
            if (vlen == 0) {
                r = e->remove(r, nib);
            } else {
                bytes value(reinterpret_cast<const char *>(ops + pos), vlen);
                r = e->insert(r, nib, value);
            }
            pos += vlen;
        }
        bytes h;
        e->commit(r, h);
        std::memcpy(new_root_out, h.data(), 32);
        e->arena.clear();
        return 0;
    } catch (const MissingError &m) {
        e->missing.insert(m.hash);
        // walk is aborted at the first missing node; collect the rest of
        // the frontier by dry-running every op against the current map
        // would repeat the same abort, so return what we have — the caller
        // feeds and retries (few passes per batch).
        e->arena.clear();
        return 1;
    } catch (const RlpError &) {
        e->arena.clear();
        return -2;
    }
}

// out must hold 32 * count bytes; returns the number written
int mpt_missing(void *ep, uint8_t *out, size_t cap) {
    Engine *e = static_cast<Engine *>(ep);
    size_t n = 0;
    for (const bytes &h : e->missing) {
        if ((n + 1) * 32 > cap) break;
        std::memcpy(out + n * 32, h.data(), 32);
        n++;
    }
    return static_cast<int>(n);
}

// size of the fresh-nodes drain buffer
size_t mpt_fresh_size(void *ep) {
    Engine *e = static_cast<Engine *>(ep);
    size_t total = 0;
    for (const bytes &b : e->fresh) total += 4 + b.size();
    return total;
}

// drains fresh nodes as (u32 len | bytes)*; returns count
int mpt_take_fresh(void *ep, uint8_t *out, size_t cap) {
    Engine *e = static_cast<Engine *>(ep);
    size_t pos = 0;
    int n = 0;
    for (const bytes &b : e->fresh) {
        if (pos + 4 + b.size() > cap) return -1;
        uint32_t l = static_cast<uint32_t>(b.size());
        std::memcpy(out + pos, &l, 4);
        std::memcpy(out + pos + 4, b.data(), b.size());
        pos += 4 + b.size();
        n++;
    }
    e->fresh.clear();
    return n;
}

size_t mpt_node_count(void *ep) {
    return static_cast<Engine *>(ep)->nodes.size();
}

} // extern "C"
