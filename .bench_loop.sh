#!/bin/bash
cd /root/repo
for i in $(seq 1 60); do
  echo "=== attempt $i $(date)" >> /root/repo/.bench_loop.log
  BENCH_TIMEOUT=5400 BENCH_ATTEMPTS=1 python bench.py >> /root/repo/.bench_loop.log 2>&1
  if tail -1 /root/repo/.bench_loop.log | grep -q '"degraded": true'; then
    sleep 600
  else
    echo "=== success $(date)" >> /root/repo/.bench_loop.log
    break
  fi
done
