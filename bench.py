"""Thin CLI shim over the bench suite (ethrex_tpu/perf/bench_suite.py).

All measurement logic, backend probing, the CPU fallback, the
append-only bench_history.jsonl, and the --check-regression gate live
in the package module; this file stays at the repo root so
`python bench.py [--measure|--measure-N|--check-regression]` and the
suite's own child-process re-invocations keep their historical entry
point.  Everything public is re-exported so `import bench` users (tests,
CI scripts) see the same API as before the move.
"""

from __future__ import annotations

import subprocess  # noqa: F401 — tests monkeypatch bench.subprocess.run

from ethrex_tpu.perf.bench_suite import *  # noqa: F401,F403
from ethrex_tpu.perf.bench_suite import cli as _cli

if __name__ == "__main__":
    _cli()
