"""Headline benchmark: BASELINE config 1 — prove a 10-transfer block
end-to-end on one TPU chip.

The measured quantity is the full `--prover tpu` pipeline on a real
committed batch: stateless re-execution, per-tx transfer-log derivation,
and THREE DEEP-FRI STARKs (state-update circuit, transfer VM circuit,
output binding), exactly what `TpuBackend.prove` ships to the proof
coordinator, followed by an independent `verify`.  This replaces round
1-2's synthetic prove-core cells/s metric and its estimated anchor
(VERDICT.md round 2, "produce one honest end-to-end benchmark").

vs_baseline is a measured-vs-measured gas rate: the reference's SP1-CUDA
prover does a 7,898,434-gas mainnet block in 143 s on an RTX 4090
(/root/reference/docs/l2/bench/prover_performance.md:7-9) = 55,234 gas/s;
we report (batch_gas / wall_s) / 55,234.  The batch here is small (210k
gas of transfers), so the comparison favors neither side's batching
amortization; larger configs land as the VM AIR's scope widens.

Resilience: the chip sits behind a flaky network tunnel.  The measurement
runs in a child process under a hard timeout with retries; successes are
persisted to .bench_last.json; if the end-to-end measurement cannot run,
the prove-core microbench (cells/s) is attempted as a live fallback
before degrading to the last-known number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_GAS_PER_SEC = 7_898_434 / 143.0
BASELINE_CELLS_PER_SEC = 1.0e8  # round-1/2 estimated anchor (fallback only)
LAST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_last.json")
ATTEMPT_TIMEOUT = int(os.environ.get("BENCH_TIMEOUT", "3000"))
ATTEMPTS = int(os.environ.get("BENCH_ATTEMPTS", "3"))
PROBE_TIMEOUT = int(os.environ.get("BENCH_PROBE_TIMEOUT", "180"))
NUM_TXS = int(os.environ.get("BENCH_TXS", "10"))


def probe_backend() -> bool:
    """Cheap child-process jax.devices() probe so a dead tunnel costs
    PROBE_TIMEOUT, not a full measurement timeout (the tunnel can hang
    indefinitely rather than erroring)."""
    want_cpu = os.environ.get("BENCH_ALLOW_CPU") == "1"
    check = ("import jax; assert jax.default_backend() != 'cpu'"
             if not want_cpu else "import jax; jax.devices()")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", check],
            capture_output=True, timeout=PROBE_TIMEOUT)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _guard_backend() -> None:
    import jax

    if (jax.default_backend() == "cpu"
            and os.environ.get("BENCH_ALLOW_CPU") != "1"):
        print("backend is cpu, refusing to publish", file=sys.stderr)
        sys.exit(3)
    from ethrex_tpu.utils.jax_cache import enable_persistent_cache

    enable_persistent_cache()


def measure() -> None:
    """BASELINE config 1: one block of NUM_TXS plain transfers, proven
    end-to-end and independently verified."""
    _guard_backend()

    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.guest.execution import ProgramInput
    from ethrex_tpu.guest.witness import generate_witness
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.primitives.transaction import Transaction
    from ethrex_tpu.prover.tpu_backend import TpuBackend

    secret = 0xA11CE
    sender = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(secret))
    genesis = {
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + sender.hex(): {"balance": hex(10**21)}},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }
    node = Node(Genesis.from_json(genesis))
    for n in range(NUM_TXS):
        tx = Transaction(
            tx_type=2, chain_id=1337, nonce=n,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=21_000, to=bytes([0x50 + n]) * 20, value=1000 + n,
        ).sign(secret)
        node.submit_transaction(tx)
    block = node.produce_block()
    gas = block.header.gas_used
    witness = generate_witness(node.chain, [block])
    pi = ProgramInput(blocks=[block], witness=witness, config=node.config)

    backend = TpuBackend()
    # one warm-up prove compiles every XLA program (persistent-cached)
    warm = backend.prove(pi, "stark")
    assert warm.get("vm", {}).get("mode") == "transfer"

    t0 = time.perf_counter()
    proof = backend.prove(pi, "stark")
    wall = time.perf_counter() - t0
    if not backend.verify(proof):
        print("self-verification failed", file=sys.stderr)
        sys.exit(4)

    gas_per_sec = gas / wall
    print(json.dumps({
        "metric": "transfer_batch_prove_wall_s",
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": round(gas_per_sec / BASELINE_GAS_PER_SEC, 4),
        "batch_gas": gas,
        "num_txs": NUM_TXS,
        "gas_per_sec": round(gas_per_sec, 1),
        "proofs_per_hour_chip": round(3600.0 / wall, 2),
        "config": "BASELINE-1 (10-transfer block, vm mode, 3 STARKs)",
    }))


def measure_core() -> None:
    """Fallback microbench: fully-jitted prove-core throughput (the round
    1-2 metric, against its documented estimated anchor)."""
    _guard_backend()
    import jax

    from ethrex_tpu.parallel.core import build_prove_step

    fn, args = build_prove_step(log_n=15, width=64, log_blowup=2,
                                log_final_size=5, mesh=None)
    jax.block_until_ready(fn(*args))
    runs = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        runs.append(time.perf_counter() - t0)
    wall = min(runs)
    value = (1 << 15) * 64 / wall
    print(json.dumps({
        "metric": "stark_prove_core_trace_cells_per_sec",
        "value": round(value, 1),
        "unit": "cells/s",
        "vs_baseline": round(value / BASELINE_CELLS_PER_SEC, 4),
        "note": "fallback microbench; baseline anchor is an estimate",
    }))


def _attempt(flag: str, timeout: int) -> dict | None:
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), flag],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return {"_err": f"timeout {timeout}s"}
    line = ""
    for cand in reversed(proc.stdout.strip().splitlines()):
        if cand.startswith("{"):
            line = cand
            break
    if proc.returncode == 0 and line:
        try:
            return json.loads(line)
        except ValueError:
            return {"_err": "unparseable output"}
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return {"_err": f"rc={proc.returncode} " + " | ".join(tail[-3:])[:400]}


def main() -> None:
    last_err = ""
    for attempt in range(ATTEMPTS):
        if not probe_backend():
            last_err = f"attempt {attempt + 1}: backend probe failed"
            time.sleep(10)
            continue
        result = _attempt("--measure", ATTEMPT_TIMEOUT)
        if result is not None and "_err" not in result:
            try:
                with open(LAST_PATH, "w") as f:
                    json.dump(result, f)
            except OSError:
                pass
            print(json.dumps(result))
            return
        last_err = f"attempt {attempt + 1}: {result.get('_err', '?')}"
        time.sleep(10)
    # live fallback: the core microbench before any cached degradation
    if probe_backend():
        result = _attempt("--measure-core", min(ATTEMPT_TIMEOUT, 1500))
        if result is not None and "_err" not in result:
            result["degraded"] = True
            result["error"] = last_err
            print(json.dumps(result))
            return
    result = {
        "metric": "transfer_batch_prove_wall_s",
        "value": 0.0,
        "unit": "s",
        "vs_baseline": 0.0,
    }
    try:
        with open(LAST_PATH) as f:
            cached = json.load(f)
        # never replay a cached record of a different metric (e.g. the
        # retired cells/s line with its estimated-anchor vs_baseline)
        if cached.get("metric") == result["metric"]:
            result = cached
    except (OSError, ValueError):
        pass
    result["degraded"] = True
    result["error"] = last_err
    print(json.dumps(result))


if __name__ == "__main__":
    if "--measure" in sys.argv:
        measure()
    elif "--measure-core" in sys.argv:
        measure_core()
    else:
        main()
