"""Headline benchmark: flagship STARK prove-core throughput on TPU.

Runs the fully-jitted prover step (trace LDE -> Poseidon2 Merkle commit ->
DEEP combination -> FRI fold/commit chain) on one chip and reports trace
cells (rows x columns) proven per second.

vs_baseline anchors against the reference's SP1-CUDA prover on an RTX 4090
(BASELINE.md: 7.9M-gas block in 143 s).  SP1 executes ~1M zkVM cycles/s on
that hardware for ethrex blocks, and each cycle occupies one row of a
~100-column trace family => ~1e8 trace cells/s.  That anchor is an estimate
(documented, refined in later rounds when the EVM AIR lands and we can
compare per-block wall-clock directly).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

LOG_N = 15
WIDTH = 64
BASELINE_CELLS_PER_SEC = 1.0e8


def main() -> None:
    import jax

    # persistent XLA cache: repeated bench runs skip the multi-minute
    # cold compile (important when the chip sits behind a network tunnel)
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/ethrex_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from ethrex_tpu.parallel.core import build_prove_step

    fn, args = build_prove_step(log_n=LOG_N, width=WIDTH, log_blowup=2,
                                log_final_size=5, mesh=None)
    # warm-up / compile
    jax.block_until_ready(fn(*args))
    runs = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        runs.append(time.perf_counter() - t0)
    wall = min(runs)
    cells = (1 << LOG_N) * WIDTH
    value = cells / wall
    print(json.dumps({
        "metric": "stark_prove_core_trace_cells_per_sec",
        "value": round(value, 1),
        "unit": "cells/s",
        "vs_baseline": round(value / BASELINE_CELLS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
