"""Headline benchmark: flagship STARK prove-core throughput on TPU.

Runs the fully-jitted prover step (trace LDE -> Poseidon2 Merkle commit ->
DEEP combination -> FRI fold/commit chain) on one chip and reports trace
cells (rows x columns) proven per second.

vs_baseline anchors against the reference's SP1-CUDA prover on an RTX 4090
(BASELINE.md: 7.9M-gas block in 143 s).  SP1 executes ~1M zkVM cycles/s on
that hardware for ethrex blocks, and each cycle occupies one row of a
~100-column trace family => ~1e8 trace cells/s.  That anchor is an estimate
(documented, refined in later rounds when the EVM AIR lands and we can
compare per-block wall-clock directly).

Resilience: the chip sits behind a flaky network tunnel (round 1's official
bench failed rc=1 because the tunnel died).  The measurement runs in a child
process under a hard timeout with retries; every success is persisted to
.bench_last.json, and when all attempts fail the last-known number is
reported in degraded mode instead of crashing.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

LOG_N = 15
WIDTH = 64
BASELINE_CELLS_PER_SEC = 1.0e8
LAST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_last.json")
ATTEMPT_TIMEOUT = int(os.environ.get("BENCH_TIMEOUT", "1500"))
ATTEMPTS = int(os.environ.get("BENCH_ATTEMPTS", "3"))
PROBE_TIMEOUT = int(os.environ.get("BENCH_PROBE_TIMEOUT", "180"))


def probe_backend() -> bool:
    """Cheap child-process jax.devices() probe so a dead tunnel costs
    PROBE_TIMEOUT, not a full measurement timeout (the tunnel can hang
    indefinitely rather than erroring)."""
    want_cpu = os.environ.get("BENCH_ALLOW_CPU") == "1"
    check = ("import jax; assert jax.default_backend() != 'cpu'"
             if not want_cpu else "import jax; jax.devices()")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", check],
            capture_output=True, timeout=PROBE_TIMEOUT)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def measure() -> None:
    import jax

    # guard against silently publishing a CPU number as the TPU metric
    # when the tunnel errors fast and JAX falls back to the CPU backend
    if (jax.default_backend() == "cpu"
            and os.environ.get("BENCH_ALLOW_CPU") != "1"):
        print("backend is cpu, refusing to publish", file=sys.stderr)
        sys.exit(3)

    # persistent XLA cache: repeated bench runs skip the multi-minute
    # cold compile (important when the chip sits behind a network tunnel)
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/ethrex_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from ethrex_tpu.parallel.core import build_prove_step

    fn, args = build_prove_step(log_n=LOG_N, width=WIDTH, log_blowup=2,
                                log_final_size=5, mesh=None)
    # warm-up / compile
    jax.block_until_ready(fn(*args))
    runs = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        runs.append(time.perf_counter() - t0)
    wall = min(runs)
    cells = (1 << LOG_N) * WIDTH
    value = cells / wall
    print(json.dumps({
        "metric": "stark_prove_core_trace_cells_per_sec",
        "value": round(value, 1),
        "unit": "cells/s",
        "vs_baseline": round(value / BASELINE_CELLS_PER_SEC, 4),
    }))


def main() -> None:
    last_err = ""
    for attempt in range(ATTEMPTS):
        if not probe_backend():
            last_err = f"attempt {attempt + 1}: backend probe failed"
            time.sleep(10)
            continue
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--measure"],
                capture_output=True, text=True, timeout=ATTEMPT_TIMEOUT,
                cwd=os.path.dirname(os.path.abspath(__file__)))
        except subprocess.TimeoutExpired:
            last_err = f"attempt {attempt + 1}: timeout {ATTEMPT_TIMEOUT}s"
            continue
        line = ""
        for cand in reversed(proc.stdout.strip().splitlines()):
            if cand.startswith("{"):
                line = cand
                break
        if proc.returncode == 0 and line:
            try:
                result = json.loads(line)
            except ValueError:
                last_err = f"attempt {attempt + 1}: unparseable output"
                continue
            try:
                with open(LAST_PATH, "w") as f:
                    json.dump(result, f)
            except OSError:
                pass
            print(json.dumps(result))
            return
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        last_err = (f"attempt {attempt + 1}: rc={proc.returncode} "
                    + " | ".join(tail[-3:])[:500])
        time.sleep(10)
    # degraded mode: report last-known instead of crashing the round
    result = {
        "metric": "stark_prove_core_trace_cells_per_sec",
        "value": 0.0,
        "unit": "cells/s",
        "vs_baseline": 0.0,
    }
    try:
        with open(LAST_PATH) as f:
            result = json.load(f)
    except (OSError, ValueError):
        pass
    result["degraded"] = True
    result["error"] = last_err
    print(json.dumps(result))


if __name__ == "__main__":
    if "--measure" in sys.argv:
        measure()
    else:
        main()
