"""Native C++ KV store backend: durability across reopen, crash-tail
truncation, compaction (the RocksDB-seat tests, reference
crates/storage test pattern)."""

import os
import tempfile

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.storage.persistent import PersistentBackend
from ethrex_tpu.storage.store import Store

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
OTHER = bytes.fromhex("aa" * 20)

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def test_kv_roundtrip_and_reopen():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "db")
        b = PersistentBackend(path)
        t = b.table("trie_nodes")
        t[b"k1"] = b"v1"
        t[b"k2"] = b"v2"
        t.pop(b"k1")
        b.flush()
        b.close()
        b2 = PersistentBackend(path)
        t2 = b2.table("trie_nodes")
        assert t2.get(b"k1") is None
        assert t2[b"k2"] == b"v2"
        b2.close()


def test_torn_tail_truncated():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "db")
        b = PersistentBackend(path)
        t = b.table("code")
        t[b"a"] = b"1"
        t[b"b"] = b"2"
        b.flush()
        b.close()
        # simulate a crash mid-append: chop bytes off the tail
        size = os.path.getsize(path)
        with open(path, "rb+") as f:
            f.truncate(size - 3)
        b2 = PersistentBackend(path)
        t2 = b2.table("code")
        assert t2[b"a"] == b"1"        # first record survives
        assert t2.get(b"b") is None    # torn record dropped, store opens
        b2.close()


def test_compaction_shrinks_log():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "db")
        b = PersistentBackend(path)
        t = b.table("meta")
        for i in range(200):
            t["churn"] = b"x" * 100      # 200 overwrites of one key
        b.flush()
        before = os.path.getsize(path)
        b.compact()
        after = os.path.getsize(path)
        assert after < before / 10
        b.close()
        b2 = PersistentBackend(path)
        assert b2.table("meta")["churn"] == b"x" * 100
        b2.close()


def test_double_open_rejected():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "db")
        b1 = PersistentBackend(path)
        with pytest.raises(OSError):
            PersistentBackend(path)  # flock held by b1
        b1.close()
        b2 = PersistentBackend(path)  # released on close
        b2.close()


def test_full_node_restart_resumes_chain():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "chain.db")
        genesis = Genesis.from_json(GENESIS)

        node = Node(genesis, store=Store(PersistentBackend(path)))
        for i in range(3):
            node.submit_transaction(Transaction(
                tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=i,
                max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                gas_limit=21000, to=OTHER, value=100 + i).sign(SECRET))
            node.produce_block()
        head = node.store.head_header()
        assert head.number == 3
        root = head.state_root
        node.store.flush()
        node.store.backend.close()

        # "restart": fresh objects over the same file
        store2 = Store(PersistentBackend(path))
        node2 = Node(genesis, store=store2)
        head2 = node2.store.head_header()
        assert head2.hash == head.hash
        assert node2.store.account_state(root, OTHER).balance == 303
        # the chain keeps extending after restart
        node2.submit_transaction(Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=3,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=21000, to=OTHER, value=1).sign(SECRET))
        blk = node2.produce_block()
        assert blk.header.number == 4
        store2.backend.close()


def test_concurrent_writers_and_readers_consistent_after_reopen():
    """Hammer one backend from several threads (distinct key ranges +
    interleaved flushes), then reopen and verify every write survived —
    the concurrency seat the RocksDB backend covers in the reference."""
    import threading

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "kv")
        backend = PersistentBackend(path)
        table = backend.table("hammer")
        errors = []

        def writer(tid):
            try:
                for i in range(200):
                    table[f"{tid}:{i}".encode()] = (
                        f"v{tid}-{i}".encode() * (1 + i % 7))
                    if i % 50 == 49:
                        backend.flush()
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def reader():
            try:
                for _ in range(300):
                    for k in list(table.keys())[:20]:
                        table.get(k)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(4)] + [threading.Thread(target=reader)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads)
        assert not errors
        backend.flush()
        backend.close()

        backend2 = PersistentBackend(path)
        table2 = backend2.table("hammer")
        for tid in range(4):
            for i in range(200):
                want = f"v{tid}-{i}".encode() * (1 + i % 7)
                assert table2[f"{tid}:{i}".encode()] == want
        backend2.close()
