"""Row-level constraint evaluation of the BytecodeAir (fast tier): every
opcode class exercised on honest traces, and tampered traces caught —
the same polynomial constraints the STARK proves, evaluated directly
over the trace rows in pure Python (seconds instead of the slow tier's
XLA compiles)."""

import numpy as np
import pytest

from ethrex_tpu.guest import bytecode_vm as bv
from ethrex_tpu.models import bytecode_air as bca
from ethrex_tpu.ops import babybear as bb

P = bb.P


class ArrOps:
    """Vectorized canonical-int field ops over all transition rows at
    once (int64 is safe: (P-1)^2 < 2^63)."""

    def const(self, v):
        return np.int64(int(v) % P)

    def add(self, a, b):
        return (a + b) % P

    def sub(self, a, b):
        return (a - b) % P

    def mul(self, a, b):
        return (a * b) % P

    def neg(self, a):
        return (-a) % P


def _check_trace(trace, steps, air=None):
    """-> (row, constraint_index) of the first violation, or None."""
    air = air or bca.BytecodeAir()
    n = trace.shape[0]
    pers = air.periodic_columns(n)
    ops = ArrOps()
    pub = bca.bytecode_public_inputs(steps)
    for (r, c, v) in air.boundaries(pub, n):
        if int(trace[r, c]) != v % P:
            return (r, -1)
    tr = trace.astype(np.int64)
    local = [tr[:-1, c] for c in range(tr.shape[1])]
    nxt = [tr[1:, c] for c in range(tr.shape[1])]
    pvals = []
    for col in pers:
        full = np.tile(np.asarray(col, dtype=np.int64),
                       n // len(col))[:n - 1]
        pvals.append(full)
    for ci, c in enumerate(air.constraints(local, nxt, pvals, ops)):
        bad = np.nonzero(np.asarray(c) % P)[0]
        if bad.size:
            return (int(bad[0]), ci)
    return None


ADDR = bytes.fromhex("abcd" * 10)   # nonzero: ADDRESS pushes real limbs


def _run(code, cd=b"", pre=None):
    pre = pre or {}
    steps, snaps, writes = bv.run_trace(code, cd, b"\xaa" * 20, 0,
                                        lambda s: pre.get(s, 0),
                                        address=ADDR)
    trace = bca.generate_bytecode_trace(steps, snaps)
    return steps, snaps, trace


REGISTRY = bytes([
    0x60, 0x00, 0x35, 0x60, 0x20, 0x35, 0x80, 0x82, 0x54, 0x10,
    0x61, 0x00, 0x14, 0x57, 0x61, 0x03, 0xE8, 0x55, 0x50, 0x00,
    0x5B, 0x90, 0x55, 0x00,
])

PROGRAMS = {
    # ADD wrap to zero, ISZERO, SSTORE
    "add-wrap": (bytes([0x7F]) + b"\xff" * 32
                 + bytes([0x60, 0x01, 0x01, 0x15, 0x60, 0x00, 0x55, 0x00]),
                 b"", None),
    # SUB underflow wrap + GT on the wrapped value
    "sub-wrap-gt": (bytes([0x60, 0x01, 0x5F, 0x03, 0x5F, 0x11,
                           0x60, 0x07, 0x55, 0x00]), b"", None),
    "eq-swap": (bytes([0x60, 0x05, 0x60, 0x05, 0x14, 0x60, 0x09,
                       0x60, 0x03, 0x14, 0x90, 0x55, 0x00]), b"", None),
    "mem": (bytes([0x60, 0x2A, 0x60, 0x00, 0x52, 0x60, 0x07, 0x60, 0x60,
                   0x52, 0x60, 0x00, 0x51, 0x60, 0x60, 0x51, 0x01,
                   0x60, 0x01, 0x55, 0x00]), b"", None),
    "env": (bytes([0x33, 0x34, 0x01, 0x36, 0x01, 0x60, 0x03, 0x35, 0x01,
                   0x60, 0x02, 0x55, 0x00]), b"\x01\x02\x03\x04\x05", None),
    # a backwards-JUMP loop that iterates four times
    "loop": (bytes([0x5F, 0x5B, 0x60, 0x01, 0x01, 0x80, 0x60, 0x04, 0x11,
                    0x60, 0x01, 0x57, 0x5F, 0x55, 0x00]), b"", None),
    "deep-stack": (b"".join(bytes([0x60, i + 1]) for i in range(13))
                   + bytes([0x80, 0x9C, 0x55, 0x00]), b"", None),
    "push-pop": (bytes([0x5F, 0x50, 0x7F]) + bytes(range(32))
                 + bytes([0x50, 0x5B, 0x00]), b"", None),
    "runoff-stop": (bytes([0x60, 0x01, 0x50]), b"", None),
    "return": (bytes([0x5F, 0x5F, 0xF3]), b"", None),
    "registry-store": (REGISTRY,
                       (5).to_bytes(32, "big") + (42).to_bytes(32, "big"),
                       {5: 10}),
    # NOT, PC, ADDRESS: sstore(0, NOT(cdload(0))); sstore(1, pc);
    # sstore(2, ADDRESS) — a NONZERO address so the env path is real
    "not-pc-addr": (bytes([0x60, 0x00, 0x35, 0x19, 0x5F, 0x55,
                           0x58, 0x60, 0x01, 0x55,
                           0x30, 0x60, 0x02, 0x55, 0x00]),
                    (5).to_bytes(32, "big"), None),
    "registry-alt": (REGISTRY,
                     (5).to_bytes(32, "big") + (3).to_bytes(32, "big"),
                     {5: 10}),
}


@pytest.mark.parametrize("name", sorted(PROGRAMS))
def test_honest_traces_satisfy_constraints(name):
    code, cd, pre = PROGRAMS[name]
    steps, snaps, trace = _run(code, cd, pre)
    assert _check_trace(trace, steps) is None
    # digest determinism across the JSON wire form
    claimed = [bv.StepRec.from_json(s.to_json()) for s in steps]
    assert bca.bc_digest_stream(claimed) == \
        bca.bytecode_public_inputs(steps)


def _tamper(trace, steps, fn):
    t = trace.copy()
    fn(t, steps)
    return t


def test_tampered_traces_violate_constraints():
    cd = (5).to_bytes(32, "big") + (42).to_bytes(32, "big")
    steps, snaps, trace = _run(REGISTRY, cd, {5: 10})

    def flip_sstore(t, st):
        k = next(i for i, s in enumerate(st) if s.op == bv.OP_SSTORE)
        rows = slice(k * bca.SEG_LEN, (k + 1) * bca.SEG_LEN)
        t[rows, bca.RB + 10] = (t[rows, bca.RB + 10].astype(np.int64)
                                + 1) % P

    def flip_branch(t, st):
        k = next(i for i, s in enumerate(st) if s.op == bv.OP_JUMPI)
        rows = slice(k * bca.SEG_LEN, (k + 1) * bca.SEG_LEN)
        t[rows, bca.Z] = 1 - t[rows, bca.Z]

    def flip_lt_result(t, st):
        k = next(i for i, s in enumerate(st) if s.op == bv.OP_LT)
        rows = slice((k + 1) * bca.SEG_LEN, (k + 2) * bca.SEG_LEN)
        t[rows, bca.STK + 10] = (t[rows, bca.STK + 10].astype(np.int64)
                                 + 1) % P

    def drift_pc(t, st):
        rows = slice(3 * bca.SEG_LEN, 4 * bca.SEG_LEN)
        t[rows, bca.PC] = (t[rows, bca.PC].astype(np.int64) + 1) % P

    def unhalt(t, st):
        t[-1, bca.HALT] = 0

    for fn in (flip_sstore, flip_branch, flip_lt_result, drift_pc,
               unhalt):
        assert _check_trace(_tamper(trace, steps, fn), steps) \
            is not None, fn.__name__


def test_tampered_new_opcodes_caught():
    """Soundness pins for the round-5 opcode additions: lying about a
    NOT result, the PC push, or the ADDRESS value must violate the
    constraints (or the native address pin)."""
    code, cd, pre = PROGRAMS["not-pc-addr"]
    steps, snaps, trace = _run(code, cd, pre)

    def flip_not_result(t, st):
        # the stack top AFTER the NOT step
        k = next(i for i, s in enumerate(st) if s.op == bv.OP_NOT)
        rows = slice((k + 1) * bca.SEG_LEN, (k + 2) * bca.SEG_LEN)
        t[rows, bca.STK + 10] = (t[rows, bca.STK + 10].astype(np.int64)
                                 + 1) % P

    def flip_pc_push(t, st):
        k = next(i for i, s in enumerate(st) if s.op == bv.OP_PC)
        rows = slice((k + 1) * bca.SEG_LEN, (k + 2) * bca.SEG_LEN)
        t[rows, bca.STK + 10] = (t[rows, bca.STK + 10].astype(np.int64)
                                 + 1) % P

    def flip_address_rb(t, st):
        k = next(i for i, s in enumerate(st) if s.op == bv.OP_ADDRESS)
        rows = slice(k * bca.SEG_LEN, (k + 1) * bca.SEG_LEN)
        t[rows, bca.RB + 10] = (t[rows, bca.RB + 10].astype(np.int64)
                                + 1) % P

    for fn in (flip_not_result, flip_pc_push, flip_address_rb):
        assert _check_trace(_tamper(trace, steps, fn), steps) \
            is not None, fn.__name__
    # and a claimed ADDRESS value that differs from the tx target is
    # rejected natively (the env pin)
    claimed = [bv.StepRec.from_json(s.to_json()) for s in steps]
    for s in claimed:
        if s.op == bv.OP_ADDRESS:
            s.b ^= 1
    rows = []
    seen = {}
    for s in steps:
        if s.op in (bv.OP_SLOAD, bv.OP_SSTORE) and s.a not in seen:
            seen[s.a] = True
            rows.append((s.a, 0, s.b))
    # rebuild correct rows from the final writes
    final = {}
    for s in steps:
        if s.op == bv.OP_SSTORE:
            final[s.a] = s.b
    rows = [(slot, 0, final.get(slot, 0)) for slot, _, _ in rows]
    import pytest as _pytest

    with _pytest.raises(bv.StepCheckError):
        bv.check_steps(code, cd, b"\xaa" * 20, 0, claimed, rows,
                       address=ADDR)
