"""End-to-end chain tests: genesis -> build payload -> add_block -> state."""

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.primitives.block import Withdrawal
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.blockchain.blockchain import Blockchain, InvalidBlock
from ethrex_tpu.blockchain.fork_choice import apply_fork_choice
from ethrex_tpu.blockchain.mempool import Mempool, MempoolError
from ethrex_tpu.blockchain.payload import build_payload, create_payload_header
from ethrex_tpu.storage.store import Store

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
COINBASE = bytes.fromhex("ee" * 20)
OTHER = bytes.fromhex("aa" * 20)

GENESIS_JSON = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000),
    "baseFeePerGas": hex(7),
    "timestamp": "0x0",
}


def _setup():
    store = Store()
    genesis = Genesis.from_json(GENESIS_JSON)
    gh = store.init_genesis(genesis)
    chain = Blockchain(store, genesis.config)
    return store, chain, gh


def _tx(nonce, to=OTHER, value=1000, gas_limit=21000, prio=2):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=prio, max_fee_per_gas=10**10,
        gas_limit=gas_limit, to=to, value=value,
    ).sign(SECRET)


def _build_and_add(chain, store, parent, txs, timestamp=None,
                   withdrawals=None):
    header = create_payload_header(
        parent, chain.config, timestamp=timestamp or parent.timestamp + 12,
        coinbase=COINBASE)
    result = build_payload(chain, parent, header, txs, withdrawals or [])
    # re-import through the full validation path on a fresh state
    chain.add_block(result.block)
    apply_fork_choice(store, result.block.hash)
    return result.block


def test_genesis_state():
    store, chain, gh = _setup()
    assert store.latest_number() == 0
    acct = store.account_state(gh.state_root, SENDER)
    assert acct.balance == 10**21


def test_single_block_transfers():
    store, chain, gh = _setup()
    txs = [_tx(i, value=1000 + i) for i in range(5)]
    block = _build_and_add(chain, store, gh, txs)
    assert store.latest_number() == 1
    assert block.header.gas_used == 21000 * 5
    root = block.header.state_root
    assert store.account_state(root, OTHER).balance == sum(
        1000 + i for i in range(5))
    assert store.account_state(root, SENDER).nonce == 5
    # coinbase collected tips
    assert store.account_state(root, COINBASE).balance == 21000 * 5 * 2
    # receipts stored
    receipts = store.get_receipts(block.hash)
    assert len(receipts) == 5 and all(r.succeeded for r in receipts)


def test_multi_block_chain_and_contract():
    store, chain, gh = _setup()
    # deploy a counter: runtime increments slot 0 on every call
    # runtime: SLOAD(0); PUSH1 1; ADD; PUSH0; SSTORE; STOP
    runtime = bytes.fromhex("5f54600101 5f55 00".replace(" ", ""))
    initcode = bytes.fromhex(
        "67" + runtime.hex().ljust(16, "0") + "5f5260086018f3")
    deploy = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=0,
        max_priority_fee_per_gas=2, max_fee_per_gas=10**10,
        gas_limit=200_000, to=b"", value=0, data=initcode,
    ).sign(SECRET)
    b1 = _build_and_add(chain, store, gh, [deploy])
    receipts = store.get_receipts(b1.hash)
    assert receipts[0].succeeded
    # created address = keccak(rlp([sender, 0]))[12:]
    from ethrex_tpu.crypto.keccak import keccak256
    from ethrex_tpu.primitives import rlp as _rlp
    created = keccak256(_rlp.encode([SENDER, 0]))[12:]
    # call it twice over two blocks
    call1 = _tx(1, to=created, value=0, gas_limit=100_000)
    b2 = _build_and_add(chain, store, b1.header, [call1])
    call2 = _tx(2, to=created, value=0, gas_limit=100_000)
    b3 = _build_and_add(chain, store, b2.header, [call2])
    assert store.storage_at(b3.header.state_root, created, 0) == 2
    assert store.storage_at(b2.header.state_root, created, 0) == 1


def test_batch_import_interval_flush_net_zero_storage():
    """Regression: with intermediate VERIFY_INTERVAL merkleize flushes, a
    slot written to X before a flush boundary and back to its batch-start
    value after it must still land in the trie (the net-zero-write skip has
    to compare against the flushed root, not the batch-start root)."""
    store, chain, gh = _setup()
    # runtime: CALLDATALOAD(0) -> SSTORE slot 0; STOP
    runtime = bytes.fromhex("6000355f5500")
    initcode = bytes.fromhex(
        "65" + runtime.hex() + "5f526006601af3")
    deploy = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=0,
        max_priority_fee_per_gas=2, max_fee_per_gas=10**10,
        gas_limit=200_000, to=b"", value=0, data=initcode,
    ).sign(SECRET)
    from ethrex_tpu.crypto.keccak import keccak256
    from ethrex_tpu.primitives import rlp as _rlp
    created = keccak256(_rlp.encode([SENDER, 0]))[12:]

    def store_tx(nonce, value):
        return Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
            max_priority_fee_per_gas=2, max_fee_per_gas=10**10,
            gas_limit=100_000, to=created, value=0,
            data=value.to_bytes(32, "big"),
        ).sign(SECRET)

    b1 = _build_and_add(chain, store, gh, [deploy])
    assert store.get_receipts(b1.hash)[0].succeeded
    b2 = _build_and_add(chain, store, b1.header, [store_tx(1, 7)])
    b3 = _build_and_add(chain, store, b2.header, [store_tx(2, 0)])
    assert store.storage_at(b2.header.state_root, created, 0) == 7
    assert store.storage_at(b3.header.state_root, created, 0) == 0

    # re-import as ONE batch with a flush boundary between b2 and b3
    store2 = Store()
    store2.init_genesis(Genesis.from_json(GENESIS_JSON))
    chain2 = Blockchain(store2, chain.config)
    chain2.VERIFY_INTERVAL = 2
    chain2.add_blocks_in_batch([b1, b2, b3])
    apply_fork_choice(store2, b3.hash)
    assert store2.head_header().state_root == b3.header.state_root
    assert store2.storage_at(b3.header.state_root, created, 0) == 0

    # and with NO boundary inside the window: SSTORE gas/refund for b3's
    # write must use b2's value as 'current' and b2's as tx-start original
    # (get_original_storage must not read the stale batch-start source)
    store3 = Store()
    store3.init_genesis(Genesis.from_json(GENESIS_JSON))
    chain3 = Blockchain(store3, chain.config)
    chain3.add_blocks_in_batch([b1, b2, b3])
    assert store3.storage_at(b3.header.state_root, created, 0) == 0


def test_withdrawals_credit_balance():
    store, chain, gh = _setup()
    wds = [Withdrawal(index=0, validator_index=1, address=OTHER, amount=3)]
    block = _build_and_add(chain, store, gh, [], withdrawals=wds)
    assert store.account_state(
        block.header.state_root, OTHER).balance == 3 * 10**9


def test_bad_state_root_rejected():
    store, chain, gh = _setup()
    header = create_payload_header(
        gh, chain.config, timestamp=12, coinbase=COINBASE)
    result = build_payload(chain, gh, header, [_tx(0)], [])
    import dataclasses
    bad = dataclasses.replace(result.block.header,
                              state_root=b"\x11" * 32)
    from ethrex_tpu.primitives.block import Block
    with pytest.raises(InvalidBlock, match="state root"):
        chain.add_block(Block(bad, result.block.body))


def test_bad_base_fee_rejected():
    store, chain, gh = _setup()
    header = create_payload_header(
        gh, chain.config, timestamp=12, coinbase=COINBASE)
    result = build_payload(chain, gh, header, [], [])
    import dataclasses
    bad = dataclasses.replace(result.block.header, base_fee_per_gas=999)
    from ethrex_tpu.primitives.block import Block
    with pytest.raises(InvalidBlock, match="base fee"):
        chain.add_block(Block(bad, result.block.body))


def test_fork_choice_reorg():
    store, chain, gh = _setup()
    b1 = _build_and_add(chain, store, gh, [_tx(0)])
    # competing block at height 1 (different timestamp)
    header = create_payload_header(
        gh, chain.config, timestamp=gh.timestamp + 24, coinbase=OTHER)
    alt = build_payload(chain, gh, header, [], []).block
    chain.add_block(alt)
    # still canonical: b1
    assert store.canonical_hash(1) == b1.hash
    # reorg to alt
    apply_fork_choice(store, alt.hash)
    assert store.canonical_hash(1) == alt.hash
    assert store.head_header().hash == alt.hash


def test_mempool_ordering_and_replacement():
    pool = Mempool()
    t0 = _tx(0, prio=1)
    t1 = _tx(1, prio=5)
    pool.add_transaction(t0, 0, 10**21, 7)
    pool.add_transaction(t1, 0, 10**21, 7)
    pending = pool.pending(7, lambda s: 0)
    # nonce order must win over tip order within a sender
    assert [t.nonce for t in pending] == [0, 1]
    # replacement requires a 10% bump
    cheap = _tx(0, prio=1)
    with pytest.raises(MempoolError):
        pool.add_transaction(
            Transaction(tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=0,
                        max_priority_fee_per_gas=1,
                        max_fee_per_gas=10**10, gas_limit=21000, to=OTHER,
                        value=0).sign(SECRET),
            0, 10**21, 7)
    assert len(pool) == 2


def test_mempool_pending_queued_split():
    pool = Mempool()
    # contiguous nonces 0,1 pending; gap at 2 leaves 3,4 queued
    for n in (0, 1, 3, 4):
        pool.add_transaction(_tx(n), 0, 10**21, 7)
    pending, queued = pool.split(lambda s: 0)
    sender = next(iter(pending))
    assert sorted(pending[sender]) == [0, 1]
    assert sorted(queued[sender]) == [3, 4]
    assert pool.status(lambda s: 0) == {"pending": 2, "queued": 2}
    # filling the gap promotes everything
    pool.add_transaction(_tx(2), 0, 10**21, 7)
    pending, queued = pool.split(lambda s: 0)
    assert sorted(pending[sender]) == [0, 1, 2, 3, 4]
    assert not queued
    # account nonce advancing drops the low run from pending
    pending, queued = pool.split(lambda s: 3)
    assert sorted(pending[sender]) == [3, 4]


def test_pipelined_import_failure_discards_layer():
    """A failed pipelined import must not leak its batch node layer
    (an orphaned open layer absorbs unrelated writes and stalls their
    durability behind a never-imported tail block)."""
    import dataclasses

    from ethrex_tpu.primitives.block import Block

    store, chain, gh = _setup()
    store.enable_layering()
    header = create_payload_header(
        gh, chain.config, timestamp=12, coinbase=COINBASE)
    result = build_payload(chain, gh, header, [_tx(0)], [])
    bad = Block(dataclasses.replace(result.block.header,
                                    state_root=b"\x11" * 32),
                result.block.body)
    layers_before = list(store.nodes.layer_tags())
    with pytest.raises(InvalidBlock, match="state root"):
        chain.add_blocks_pipelined([bad])
    assert list(store.nodes.layer_tags()) == layers_before
    # the good block still imports cleanly afterwards
    chain.add_blocks_pipelined([result.block])
    assert store.latest_number() == 0  # head moves only on fork choice
    assert store.get_header(result.block.hash) is not None


def test_berlin_clear_refund_schedule():
    """EIP-3529 lowered the SSTORE clear refund to 4800 at LONDON; Berlin
    itself still refunds 15000 (EIP-2200 value under EIP-2929 pricing)."""
    from ethrex_tpu.evm import gas as G
    from ethrex_tpu.primitives.genesis import Fork

    assert G.schedule_for(Fork.BERLIN).sstore_clear_refund == 15000
    assert G.schedule_for(Fork.LONDON).sstore_clear_refund == 4800
    assert G.schedule_for(Fork.CANCUN).sstore_clear_refund == 4800


def test_mempool_fifo_eviction_regular():
    """Regular txs FIFO-evict past the cap instead of rejecting new ones
    (reference mempool.rs:462-475); blob txs never feel the pressure."""
    from ethrex_tpu.blockchain.mempool import Mempool

    pool = Mempool(capacity=3)
    hashes = []
    for n in range(5):
        tx = _tx(n)
        hashes.append(pool.add_transaction(tx, n, 10**21, 7))
    assert len(pool) == 3
    # the two oldest were evicted
    assert pool.get_transaction(hashes[0]) is None
    assert pool.get_transaction(hashes[1]) is None
    assert pool.get_transaction(hashes[4]) is not None


def test_mempool_blob_eviction_least_includable():
    """The blob sub-pool evicts the deepest per-sender nonce offset
    first, ties by lowest blob fee (reference mempool.rs:477-530)."""
    from ethrex_tpu.blockchain.mempool import Mempool
    from ethrex_tpu.primitives.transaction import Transaction

    pool = Mempool(capacity=100, blob_capacity=2)

    def blob_tx(nonce, blob_fee):
        return Transaction(
            tx_type=3, chain_id=1337, nonce=nonce,
            max_priority_fee_per_gas=2, max_fee_per_gas=10**10,
            gas_limit=21000, to=OTHER, value=0,
            max_fee_per_blob_gas=blob_fee,
            blob_versioned_hashes=[b"\x01" + bytes(31)]).sign(SECRET)

    h0 = pool.add_transaction(blob_tx(0, 10), 0, 10**21, 7,
                              blobs_bundle=object())
    h1 = pool.add_transaction(blob_tx(1, 99), 0, 10**21, 7,
                              blobs_bundle=object())
    # third blob: nonce offset 2 is the deepest -> IT is evicted at cap 2
    h2 = pool.add_transaction(blob_tx(2, 50), 0, 10**21, 7,
                              blobs_bundle=object())
    assert pool.get_transaction(h2) is None
    assert pool.get_transaction(h0) is not None
    assert pool.get_transaction(h1) is not None
