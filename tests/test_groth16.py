"""Groth16 over BN254: tiny-circuit round-trip, soundness rejections, and
the NTT/QAP plumbing."""

import pytest

from ethrex_tpu.crypto import bn254, groth16


def _mult_r1cs():
    """x * y = out, with out public: z = [1, out, x, y]."""
    return groth16.R1CS(
        num_vars=4, num_pub=1,
        constraints=[({2: 1}, {3: 1}, {1: 1})])


def test_fr_ntt_roundtrip():
    vals = [3, 1, 4, 1, 5, 9, 2, 6]
    back = groth16._ntt_fr(groth16._ntt_fr(vals), inverse=True)
    assert back == [v % groth16.R for v in vals]


def test_groth16_roundtrip_mult_gate():
    r1cs = _mult_r1cs()
    pk, vk = groth16.setup(r1cs, seed=b"test-setup-1")
    z = [1, 35, 5, 7]
    assert r1cs.is_satisfied(z)
    proof = groth16.prove(pk, r1cs, z, rnd=b"t1")
    assert groth16.verify(vk, proof, [35])
    # wrong public input rejected
    assert not groth16.verify(vk, proof, [36])
    # tampered proof rejected
    bad = dict(proof)
    bad["a"] = bn254.g1_mul(groth16.G1, 123)
    assert not groth16.verify(vk, bad, [35])


def test_groth16_multi_constraint():
    """(x + 1) * x = y;  y * x = out  -> z = [1, out, x, y]."""
    r1cs = groth16.R1CS(
        num_vars=4, num_pub=1,
        constraints=[
            ({2: 1, 0: 1}, {2: 1}, {3: 1}),
            ({3: 1}, {2: 1}, {1: 1}),
        ])
    x = 9
    y = (x + 1) * x % groth16.R
    out = y * x % groth16.R
    z = [1, out, x, y]
    assert r1cs.is_satisfied(z)
    pk, vk = groth16.setup(r1cs, seed=b"test-setup-2")
    proof = groth16.prove(pk, r1cs, z, rnd=b"t2")
    assert groth16.verify(vk, proof, [out])
    assert not groth16.verify(vk, proof, [out + 1])


def test_unsatisfied_witness_refused():
    r1cs = _mult_r1cs()
    pk, _vk = groth16.setup(r1cs, seed=b"test-setup-1")
    with pytest.raises(ValueError):
        groth16.prove(pk, r1cs, [1, 36, 5, 7], rnd=b"t3")
