"""Sponge-mode Poseidon2 AIR: in-circuit hash_leaves over multiple chunks."""

import numpy as np
import pytest

from ethrex_tpu.models import poseidon2_air as pair
from ethrex_tpu.ops import babybear as bb
from ethrex_tpu.ops import ext
from ethrex_tpu.ops.merkle import hash_leaf_ref
from ethrex_tpu.stark import prover, verifier
from ethrex_tpu.stark.air import HostExtOps
from ethrex_tpu.stark.prover import StarkParams

RNG = np.random.default_rng(21)
PARAMS = StarkParams(log_blowup=3, num_queries=30, log_final_size=4)


def test_sponge_trace_matches_hash_leaves():
    msg = [int(v) for v in RNG.integers(0, bb.P, 24)]  # 3 chunks
    trace = pair.generate_sponge_trace(msg)
    assert trace.shape == (128, 24)  # 3 chunks pad to 4 periods
    digest = hash_leaf_ref(msg)
    final_row = pair.PERIOD * 2 + pair.ROUNDS
    assert [int(v) for v in trace[final_row][:8]] == digest


def test_sponge_constraints_vanish():
    msg = [int(v) for v in RNG.integers(0, bb.P, 16)]  # 2 chunks
    air = pair.Poseidon2SpongeAir(num_chunks=2)
    trace = pair.generate_sponge_trace(msg)
    n = trace.shape[0]
    periodic_cols = air.periodic_columns(n)
    hops = HostExtOps()
    for r in range(n - 1):
        local = [ext.h_from_base(int(v)) for v in trace[r]]
        nxt = [ext.h_from_base(int(v)) for v in trace[r + 1]]
        periodic = [ext.h_from_base(int(col[r % len(col)]))
                    for col in periodic_cols]
        cons = air.constraints(local, nxt, periodic, hops)
        assert all(c == ext.ZERO_H for c in cons), f"row {r}"
    # tampering the absorb transition breaks a constraint
    bad = trace.copy()
    bad[pair.PERIOD, 2] = (int(bad[pair.PERIOD, 2]) + 1) % bb.P
    r = pair.PERIOD - 1
    local = [ext.h_from_base(int(v)) for v in bad[r]]
    nxt = [ext.h_from_base(int(v)) for v in bad[r + 1]]
    periodic = [ext.h_from_base(int(col[r % len(col)]))
                for col in periodic_cols]
    assert any(c != ext.ZERO_H
               for c in air.constraints(local, nxt, periodic, hops))


def test_sponge_prove_verify_and_binding():
    msg = [int(v) for v in RNG.integers(0, bb.P, 17)]  # pads to 24 -> k=3
    air = pair.Poseidon2SpongeAir(num_chunks=3)
    trace = pair.generate_sponge_trace(msg)
    pub = pair.sponge_public_inputs(msg)
    assert pub[-8:] == hash_leaf_ref(pub[:-8])
    proof = prover.prove(air, trace, pub, PARAMS)
    assert verifier.verify(air, proof, PARAMS)
    # forged digest rejected
    bad_pub = list(proof["pub_inputs"])
    bad_pub[-1] = (bad_pub[-1] + 1) % bb.P
    with pytest.raises(verifier.VerificationError):
        verifier.verify(air, {**proof, "pub_inputs": bad_pub}, PARAMS)
    # forged message chunk rejected
    bad_pub2 = list(proof["pub_inputs"])
    bad_pub2[9] = (bad_pub2[9] + 1) % bb.P  # limb in chunk 1
    with pytest.raises(verifier.VerificationError):
        verifier.verify(air, {**proof, "pub_inputs": bad_pub2}, PARAMS)
