"""BLS12-381 curve/pairing, KZG commitments, and the EIP-2537/EIP-4844
precompiles (parity: crates/common/crypto/{bls_blst.rs,kzg.rs} and
crates/vm/levm/src/precompiles.rs BLS/point-eval entries)."""

import pytest

from ethrex_tpu.crypto import bls12_381 as bls
from ethrex_tpu.crypto import kzg
from ethrex_tpu.evm import precompiles as pc
from ethrex_tpu.primitives.genesis import Fork


def test_pairing_bilinear():
    e = bls.pairing(bls.G1_GEN, bls.G2_GEN)
    assert e != bls.Fp12.one()
    assert e.pow(bls.R) == bls.Fp12.one()
    lhs = bls.pairing(bls.g1_mul(bls.G1_GEN, 6),
                      bls.g2_mul(bls.G2_GEN, 5))
    assert lhs == e.pow(30)


def test_point_serialization_roundtrip():
    p = bls.g1_mul(bls.G1_GEN, 0xDEADBEEF)
    q = bls.g2_mul(bls.G2_GEN, 0xCAFE)
    assert bls.decode_g1(bls.encode_g1(p)) == p
    assert bls.decode_g2(bls.encode_g2(q)) == q
    assert bls.g1_decompress(bls.g1_compress(p)) == p
    assert bls.g2_decompress(bls.g2_compress(q)) == q
    # the canonical compressed generator (public constant)
    assert bls.g1_compress(bls.G1_GEN).hex().startswith("97f1d3a73197d794")
    with pytest.raises(bls.DecodeError):
        bls.decode_g1(b"\x01" * 128)


def test_kzg_commit_prove_verify():
    setup = kzg.TrustedSetup.dev()
    blob = kzg.evals_to_blob([7 * i + 3 for i in range(64)])
    c = kzg.blob_to_kzg_commitment(blob, setup)
    proof, y = kzg.compute_kzg_proof(blob, 99, setup)
    assert kzg.verify_kzg_proof(c, 99, y, proof, setup)
    assert not kzg.verify_kzg_proof(c, 99, (y + 1) % kzg.BLS_MODULUS,
                                    proof, setup)
    # blob-level proof (the committer's sidecar flow)
    bp = kzg.compute_blob_kzg_proof(blob, c, setup)
    assert kzg.verify_blob_kzg_proof(blob, c, bp, setup)
    other = kzg.evals_to_blob([1])
    assert not kzg.verify_blob_kzg_proof(other, c, bp, setup)


def test_point_evaluation_precompile():
    setup = kzg.TrustedSetup.dev()
    kzg.set_setup(setup)
    try:
        blob = kzg.evals_to_blob(list(range(1, 33)))
        c = kzg.blob_to_kzg_commitment(blob, setup)
        z = 0x1234
        proof, y = kzg.compute_kzg_proof(blob, z, setup)
        inp = (kzg.commitment_to_versioned_hash(c)
               + z.to_bytes(32, "big") + y.to_bytes(32, "big") + c + proof)
        fn = pc.get_precompile(pc._a(10), Fork.CANCUN)
        assert fn is not None
        cost, out = fn(inp, 10**6, Fork.CANCUN)
        assert cost == 50_000 and out == kzg.POINT_EVAL_OUTPUT
        with pytest.raises(pc.PrecompileError):
            bad = bytearray(inp)
            bad[40] ^= 1  # z changes -> proof invalid
            fn(bytes(bad), 10**6, Fork.CANCUN)
        # not active before Cancun
        assert pc.get_precompile(pc._a(10), Fork.SHANGHAI) is None
    finally:
        kzg.set_setup(None)


def test_bls_precompiles_add_msm_pairing():
    f = Fork.PRAGUE
    g1 = bls.encode_g1(bls.G1_GEN)
    two = bls.encode_g1(bls.g1_mul(bls.G1_GEN, 2))
    add = pc.get_precompile(pc._a(0x0B), f)
    cost, out = add(g1 + g1, 10**6, f)
    assert cost == 375 and out == two
    # infinity encoding
    _, out0 = add(g1 + b"\x00" * 128, 10**6, f)
    assert out0 == g1

    msm = pc.get_precompile(pc._a(0x0C), f)
    scalar = (3).to_bytes(32, "big")
    cost, out = msm(g1 + scalar, 10**6, f)
    assert cost == 12_000
    assert out == bls.encode_g1(bls.g1_mul(bls.G1_GEN, 3))
    # two-pair MSM with the k=2 discount
    cost2, out2 = msm(g1 + scalar + two + scalar, 10**6, f)
    assert cost2 == 2 * 12_000 * 949 // 1000
    assert out2 == bls.encode_g1(bls.g1_mul(bls.G1_GEN, 9))

    g2add = pc.get_precompile(pc._a(0x0D), f)
    g2 = bls.encode_g2(bls.G2_GEN)
    cost, out = g2add(g2 + b"\x00" * 256, 10**6, f)
    assert cost == 600 and out == g2

    g2msm = pc.get_precompile(pc._a(0x0E), f)
    cost, out = g2msm(g2 + scalar, 10**6, f)
    assert cost == 22_500
    assert out == bls.encode_g2(bls.g2_mul(bls.G2_GEN, 3))

    pairing = pc.get_precompile(pc._a(0x0F), f)
    neg_g1 = bls.encode_g1((bls.G1_GEN[0], bls.P - bls.G1_GEN[1]))
    ok_input = g1 + g2 + neg_g1 + g2
    cost, out = pairing(ok_input, 10**6, f)
    assert cost == 32_600 * 2 + 37_700
    assert out == (1).to_bytes(32, "big")
    _, out = pairing(g1 + g2, 10**6, f)
    assert out == b"\x00" * 32

    # malformed inputs raise
    with pytest.raises(pc.PrecompileError):
        add(g1, 10**6, f)
    with pytest.raises(pc.PrecompileError):
        msm(b"", 10**6, f)
    # subgroup check enforced on MSM: a curve point outside G1's subgroup
    x = 5
    while True:
        y2 = (x * x * x + 4) % bls.P
        y = pow(y2, (bls.P + 1) // 4, bls.P)
        if y * y % bls.P == y2:
            cand = (x, y)
            if not bls.g1_in_subgroup(cand):
                break
        x += 1
    with pytest.raises(pc.PrecompileError):
        msm(bls.encode_g1(cand) + scalar, 10**6, f)
    # ...but ADD accepts it (EIP-2537: no subgroup check on ADD)
    add(bls.encode_g1(cand) + g1, 10**6, f)
    # not active before Prague
    assert pc.get_precompile(pc._a(0x0B), Fork.CANCUN) is None
