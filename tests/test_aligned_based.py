"""Aligned-mode L1ProofVerifier and the based BlockFetcher follower."""

import pytest

from ethrex_tpu.l2.aligned import AlignedLayer, L1ProofVerifier
from ethrex_tpu.l2.based import BlockFetcher, FetchError
from ethrex_tpu.l2.l1_client import InMemoryL1
from ethrex_tpu.l2.rollup_store import RollupStore
from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover.backend import get_backend

from tests.test_l2_pipeline import GENESIS, _transfer


def _setup():
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1(needed_prover_types=[protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,)))
    return node, l1, seq


def _commit_one_proven_batch(node, seq):
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    batch = seq.commit_next_batch()
    assert batch is not None
    # prove it directly (skip the TCP fleet for these unit tests)
    backend = get_backend(protocol.PROVER_EXEC)
    from ethrex_tpu.guest.execution import ProgramInput

    stored = seq.rollup.get_prover_input(batch.number,
                                         seq.cfg.commit_hash)
    proof = backend.prove(ProgramInput.from_json(stored),
                          protocol.FORMAT_STARK)
    seq.rollup.store_proof(batch.number, protocol.PROVER_EXEC, proof)
    return batch


def test_aligned_submit_poll_verify():
    node, l1, seq = _setup()
    batch = _commit_one_proven_batch(node, seq)
    aligned = AlignedLayer(latency_polls=2)
    ver = L1ProofVerifier(seq.rollup, l1, aligned,
                          [protocol.PROVER_EXEC])
    assert ver.step() == "submitted"
    assert ver.step() == "pending"
    assert ver.step() == "verified"        # second poll -> included
    assert l1.last_verified_batch() == batch.number
    assert seq.rollup.get_batch(batch.number).verified
    assert ver.step() is None              # nothing left


def test_aligned_lost_submission_resubmits():
    node, l1, seq = _setup()
    _commit_one_proven_batch(node, seq)
    aligned = AlignedLayer(latency_polls=1)
    ver = L1ProofVerifier(seq.rollup, l1, aligned,
                          [protocol.PROVER_EXEC])
    assert ver.step() == "submitted"
    # the aggregation drops the submission behind the verifier's back
    aligned.submissions[ver.inflight["sid"]]["state"] = AlignedLayer.LOST
    assert ver.step() == "resubmitted"
    assert ver.step() == "verified"
    assert l1.last_verified_batch() == 1


def test_aligned_rejects_invalid_proof():
    node, l1, seq = _setup()
    batch = _commit_one_proven_batch(node, seq)
    proof = seq.rollup.get_proof(batch.number, protocol.PROVER_EXEC)
    proof["output"] = "0x" + "00" * 8  # corrupt
    aligned = AlignedLayer()
    ver = L1ProofVerifier(seq.rollup, l1, aligned,
                          [protocol.PROVER_EXEC])
    with pytest.raises(ValueError):
        ver.step()


def test_based_follower_imports_committed_batches():
    node, l1, seq = _setup()
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    assert seq.commit_next_batch() is not None
    node.submit_transaction(_transfer(1))
    seq.produce_block()
    assert seq.commit_next_batch() is not None

    follower = Node(Genesis.from_json(GENESIS))
    rollup = RollupStore()
    fetcher = BlockFetcher(follower, l1, rollup)
    assert fetcher.fetch_once() == 2
    assert follower.store.latest_number() == node.store.latest_number()
    head = follower.store.get_canonical_block(follower.store.latest_number())
    assert head.header.state_root == \
        node.store.get_canonical_block(node.store.latest_number()) \
            .header.state_root
    assert rollup.get_batch(2).committed
    # idempotent: nothing new to fetch
    assert fetcher.fetch_once() == 0


def test_based_follower_detects_root_divergence():
    node, l1, seq = _setup()
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    batch = seq.commit_next_batch()
    # corrupt the committed root on the (hostile) L1 record
    root, comm = l1.commitments[batch.number]
    l1.commitments[batch.number] = (b"\x11" * 32, comm)
    follower = Node(Genesis.from_json(GENESIS))
    fetcher = BlockFetcher(follower, l1)
    with pytest.raises(FetchError):
        fetcher.fetch_once()


def test_based_follower_records_fatal_divergence():
    """A FetchError inside the polling loop must not die as an unhandled
    daemon-thread exception: the fetcher records it and stops, so health
    checks surface the frozen-follower condition."""
    import time

    node, l1, seq = _setup()
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    batch = seq.commit_next_batch()
    root, comm = l1.commitments[batch.number]
    l1.commitments[batch.number] = (b"\x22" * 32, comm)
    follower = Node(Genesis.from_json(GENESIS))
    fetcher = BlockFetcher(follower, l1)
    assert fetcher.healthy()
    fetcher.start(interval=0.01)
    deadline = time.time() + 5
    while fetcher.fatal is None and time.time() < deadline:
        time.sleep(0.01)
    assert not fetcher.healthy()
    assert "committed" in str(fetcher.fatal)
    fetcher.stop()


def test_check_coverage_rejects_downgrade():
    """The anti-downgrade hook (review finding): a tpu proof whose vm
    mode differs from the committer-recorded coverage is rejected —
    most importantly a claimed-log proof for a circuit-covered batch."""
    from ethrex_tpu.prover.tpu_backend import TpuBackend

    backend = TpuBackend()
    claimed = {"backend": protocol.PROVER_TPU, "output": "0x"}
    transfer = dict(claimed, vm={"mode": "transfer"})
    generic = dict(claimed, vm={"mode": "generic"})
    assert backend.check_coverage(transfer, "transfer")
    assert backend.check_coverage(generic, "generic")
    assert not backend.check_coverage(claimed, "transfer")
    assert not backend.check_coverage(claimed, "generic")
    assert not backend.check_coverage(transfer, "generic")
    # pre-metadata batches put no constraint
    assert backend.check_coverage(claimed, "")


def test_aligned_rejects_downgraded_transfer_batch():
    """AlignedLayer.submit refuses a claimed-log proof for a batch the
    committer marked transfer-covered, before any settlement."""
    aligned = AlignedLayer()
    downgraded = {"backend": protocol.PROVER_TPU, "format": "stark",
                  "output": "0x", "write_log": [],
                  "depth": 1, "seg_periods": 8,
                  "state_proof": {}, "proof": {}}
    with pytest.raises(ValueError, match="downgrades its vm coverage"):
        aligned.submit(7, 7, {protocol.PROVER_TPU: [downgraded]},
                       expected_modes={7: "transfer"})
