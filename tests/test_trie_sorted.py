"""Sorted bulk MPT construction (trie/trie_sorted.py): byte-identical to
incremental insertion, native and Python paths differential-tested."""

import random

import pytest

from ethrex_tpu.crypto.keccak import keccak256
from ethrex_tpu.primitives.account import EMPTY_TRIE_ROOT
from ethrex_tpu.trie.trie import Trie
from ethrex_tpu.trie.trie_sorted import build_from_sorted

RNG = random.Random(7)


def _random_pairs(n):
    d = {keccak256(RNG.randbytes(8)): RNG.randbytes(RNG.randint(1, 60))
         or b"\x01" for _ in range(n)}
    return sorted(d.items())


@pytest.mark.parametrize("n", [1, 2, 3, 17, 128, 1000])
def test_matches_incremental(n):
    pairs = _random_pairs(n)
    t = Trie({})
    for k, v in pairs:
        t.insert(k, v)
    want = t.commit()
    for use_native in (False, True):
        nodes = {}
        got, trie = build_from_sorted(pairs, nodes, use_native=use_native)
        assert got == want
        # the produced node table serves reads
        for k, v in pairs[:: max(1, n // 7)]:
            assert trie.get(k) == v


def test_empty_and_errors():
    root, _ = build_from_sorted([])
    assert root == EMPTY_TRIE_ROOT
    with pytest.raises(ValueError):
        build_from_sorted([(b"\x02" * 32, b"x"), (b"\x01" * 32, b"y")],
                          use_native=False)
    with pytest.raises(ValueError):
        build_from_sorted([(b"\x01" * 32, b"")], use_native=False)


def test_variable_length_keys_with_branch_value():
    # a key that is a strict prefix of another lands its value in the
    # branch; sorted build must agree with incremental insertion
    pairs = sorted({b"\x12\x34": b"a", b"\x12\x34\x56": b"b",
                    b"\x12\x35": b"c", b"\x12": b"d"}.items())
    t = Trie({})
    for k, v in pairs:
        t.insert(k, v)
    want = t.commit()
    got, _ = build_from_sorted(pairs, use_native=False)
    assert got == want
