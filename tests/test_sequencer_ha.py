"""Sequencer HA chaos battery (docs/SEQUENCER_HA.md): L1-fenced leader
leases, hot-standby failover, and leader-kill drills.

Covered here:
- the L1 lease cell: CAS semantics, epoch monotonicity, persistence
  across restarts and L1 reorgs, the EvmL1 storage-slot mirror;
- fencing discipline on both sinks (L1 commit/verify transactions and
  rollup-store write groups) with the typed FencedError;
- the "l1.lease" two-leg fault site (request lost vs response lost —
  the orphaned-term case) and the "seq.fence" checkpoint site;
- leader-kill drills at EVERY actor boundary: the standby promotes,
  finishes the pipeline, and converges byte-identically with a
  no-failover baseline, with zero double-commits;
- the commit crash-window kill (L1 accepted, leader died before any
  local persistence) handed to a standby instead of a restart;
- promotion-within-lease-TTL liveness with real threads;
- prover-fleet re-homing: an in-flight phase-checkpointed proof
  RESUMES under the new leader's coordinator (phase_resumes > 0);
- BlockFetcher failure paths and Sequencer/Node stop idempotency.

Select alone with `-m chaos`; everything but the loadgen soak is in
the fast tier.
"""

import threading
import time
import types

import pytest

from ethrex_tpu.guest.execution import ProgramInput
from ethrex_tpu.l2.based import BlockFetcher
from ethrex_tpu.l2.l1_client import InMemoryL1, PersistentInMemoryL1
from ethrex_tpu.l2.l1_evm import LEASE_EPOCH_SLOT, EvmL1
from ethrex_tpu.l2.leadership import (ROLE_CANDIDATE, ROLE_FOLLOWER,
                                      ROLE_LEADER, FencedError,
                                      LeadershipManager)
from ethrex_tpu.l2.rollup_store import PersistentRollupStore, RollupStore
from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover.backend import get_backend
from ethrex_tpu.utils import faults
from ethrex_tpu.utils.faults import FaultPlan, InjectedFault, injected
from tests.test_l2_pipeline import GENESIS, _transfer

pytestmark = pytest.mark.chaos

# two blocks / two batches is the canonical drill schedule: batch n
# covers exactly block n, block n carries txs (2n-2, 2n-1) at
# timestamp n — fully deterministic, so independent runs produce
# byte-identical chains
TOTAL_BLOCKS = 2


def _l1():
    return InMemoryL1([protocol.PROVER_EXEC])


def _cfg(role=None, ttl=60.0, node_id=None):
    return SequencerConfig(needed_prover_types=(protocol.PROVER_EXEC,),
                           ha_role=role, leader_lease=ttl,
                           ha_node_id=node_id)


def _seq(l1, role=None, ttl=60.0, node_id=None):
    node = Node(Genesis.from_json(GENESIS))
    return node, Sequencer(node, l1, _cfg(role, ttl, node_id))


def _produce(node, n):
    """Produce canonical block `n` (txs 2n-2, 2n-1 at timestamp n)."""
    for k in (2 * (n - 1), 2 * n - 1):
        node.submit_transaction(_transfer(k))
    return node.produce_block(timestamp=n)


def _prove(seq, number):
    backend = get_backend(protocol.PROVER_EXEC)
    stored = seq.rollup.get_prover_input(number, seq.cfg.commit_hash)
    assert stored is not None, f"batch {number} has no prover input"
    proof = backend.prove(ProgramInput.from_json(stored),
                          protocol.FORMAT_STARK)
    seq.rollup.store_proof(number, protocol.PROVER_EXEC, proof)


def _drive(seq, node, l1):
    """Finish the canonical schedule from wherever this node stands:
    (re)produce missing blocks, commit missing batches, prove and
    verify everything, adopt flags.  Work another leader already
    settled is adopted, never redone — this is exactly what a freshly
    promoted standby runs."""
    for n in range(1, TOTAL_BLOCKS + 1):
        if node.store.latest_number() < n:
            _produce(node, n)
        if seq.rollup.latest_batch_number() < n:
            assert seq.commit_next_batch() is not None
        if n > l1.last_verified_batch() and \
                seq.rollup.get_proof(n, protocol.PROVER_EXEC) is None:
            _prove(seq, n)
    seq.send_proofs()
    seq.update_state()


def _chain_fingerprint(node, l1):
    blocks = [node.store.get_canonical_block(n)
              for n in range(1, TOTAL_BLOCKS + 1)]
    return {
        "hashes": [b.hash for b in blocks],
        "roots": [b.header.state_root for b in blocks],
        "commitments": [l1.get_committed_commitment(n)
                        for n in range(1, TOTAL_BLOCKS + 1)],
        "l1_roots": [l1.get_committed_state_root(n)
                     for n in range(1, TOTAL_BLOCKS + 1)],
        "verified": l1.last_verified_batch(),
    }


@pytest.fixture(scope="module")
def baseline():
    """The no-failover fingerprint a failover run must converge to."""
    l1 = _l1()
    node, seq = _seq(l1)
    _drive(seq, node, l1)
    fp = _chain_fingerprint(node, l1)
    assert fp["verified"] == TOTAL_BLOCKS
    seq.stop()
    return fp


# ===========================================================================
# the L1 lease cell
# ===========================================================================

def test_lease_cas_and_epoch_monotonic():
    l1 = _l1()
    assert l1.get_lease() is None
    assert l1.acquire_lease("a", 60.0) == 1
    # CAS: a live lease blocks every other candidate
    assert l1.acquire_lease("b", 60.0) is None
    assert l1.renew_lease("a", 1, 60.0) is True
    # renewal binds holder AND epoch
    assert l1.renew_lease("a", 2, 60.0) is False
    assert l1.renew_lease("b", 1, 60.0) is False
    assert l1.release_lease("b", 1) is False
    assert l1.release_lease("a", 1) is True
    # epochs never repeat, even across clean release
    assert l1.acquire_lease("b", 60.0) == 2
    l1.expire_lease()
    assert l1.acquire_lease("c", 60.0) == 3
    lease = l1.get_lease()
    assert lease.holder == "c" and lease.epoch == 3
    assert lease.to_json()["epoch"] == 3


def test_lease_cell_survives_l1_reorg():
    """The lease cell is deliberately OUTSIDE the reorg snapshots: a
    rolled-back L1 must never re-mint an old epoch (that would unfence
    a deposed leader)."""
    l1 = _l1()
    l1.advance_blocks(4)
    assert l1.acquire_lease("a", 60.0) == 1
    l1.advance_blocks(3)
    l1.reorg(2)
    lease = l1.get_lease()
    assert lease is not None and lease.epoch == 1 and lease.holder == "a"
    l1.expire_lease()
    assert l1.acquire_lease("b", 60.0) == 2


def test_lease_persists_across_reopen(tmp_path):
    path = str(tmp_path / "l1.json")
    l1 = PersistentInMemoryL1(path, [protocol.PROVER_EXEC])
    assert l1.acquire_lease("a", 60.0) == 1

    l1b = PersistentInMemoryL1(path, [protocol.PROVER_EXEC])
    lease = l1b.get_lease()
    assert lease is not None and lease.holder == "a" and lease.epoch == 1
    # the epoch watermark survives too: a post-restart takeover mints 2
    l1b.expire_lease()
    assert l1b.acquire_lease("b", 60.0) == 2
    with pytest.raises(FencedError):
        l1b.commit_batch(1, b"\x11" * 32, b"\x22" * 48, epoch=1)


def test_evm_l1_mirrors_epoch_to_contract_slot():
    l1 = EvmL1([protocol.PROVER_EXEC])
    assert LEASE_EPOCH_SLOT == 7
    assert l1.lease_epoch_slot() == 0
    assert l1.acquire_lease("a", 60.0) == 1
    assert l1.lease_epoch_slot() == 1
    l1.expire_lease()
    assert l1.acquire_lease("b", 60.0) == 2
    assert l1.lease_epoch_slot() == 2


# ===========================================================================
# fencing discipline on both sinks
# ===========================================================================

def test_l1_rejects_stale_epoch_on_commit_and_verify():
    l1 = _l1()
    assert l1.acquire_lease("a", 60.0) == 1
    l1.expire_lease()
    assert l1.acquire_lease("b", 60.0) == 2

    with pytest.raises(FencedError) as exc:
        l1.commit_batch(1, b"\x11" * 32, b"\x22" * 48, epoch=1)
    assert exc.value.epoch == 1 and exc.value.current == 2
    with pytest.raises(FencedError):
        l1.verify_batches(1, 1, {}, epoch=1)
    with pytest.raises(FencedError):
        l1.verify_batches_aggregated(1, 1, {}, epoch=1)
    assert l1.fenced_writes_total == 3
    assert l1.last_committed_batch() == 0  # nothing landed
    # the current epoch and the non-HA None path both pass the fence
    l1.commit_batch(1, b"\x11" * 32, b"\x22" * 48, epoch=2)
    l1.commit_batch(2, b"\x33" * 32, b"\x44" * 48, epoch=None)
    assert l1.last_committed_batch() == 2


def test_rollup_store_fences_stale_write_groups(tmp_path):
    rollup = RollupStore()
    assert rollup.leadership_epoch() == 0
    rollup.fence(2)
    with pytest.raises(FencedError):
        with rollup.write_group(epoch=1):
            pass
    with rollup.write_group(epoch=2):
        pass
    with rollup.write_group(epoch=None):  # non-HA path
        pass
    rollup.fence(1)  # the watermark never moves backwards
    assert rollup.leadership_epoch() == 2

    # the persisted watermark fences a restarted zombie too
    store = PersistentRollupStore(str(tmp_path / "rollup.db"))
    store.fence(3)
    store.close()
    store2 = PersistentRollupStore(str(tmp_path / "rollup.db"))
    assert store2.leadership_epoch() == 3
    with pytest.raises(FencedError):
        with store2.write_group(epoch=2):
            pass
    store2.close()


# ===========================================================================
# fault sites: "l1.lease" (two legs) and "seq.fence"
# ===========================================================================

def test_lease_fault_request_leg_lost():
    """Leg 1: the acquire request never reaches the L1 — the bid fails
    cleanly and nothing is held."""
    l1 = _l1()
    lm = LeadershipManager(l1, "a", ttl=60.0)
    with injected(FaultPlan(seed=3).drop("l1.lease", times=1)):
        assert lm.try_acquire() is False
    assert l1.get_lease() is None
    assert lm.role == ROLE_CANDIDATE and lm.epoch is None
    # clean retry wins
    assert lm.try_acquire() is True
    assert lm.role == ROLE_LEADER and lm.epoch == 1


def test_lease_fault_response_leg_lost_orphans_a_term():
    """Leg 2 (`after=1`): the L1 granted the lease but the response was
    lost.  The candidate believes it failed — the orphaned term simply
    expires, and the next bid mints a FRESH epoch, so nothing the
    orphan could have stamped (epoch 1) survives the fence."""
    l1 = _l1()
    lm = LeadershipManager(l1, "a", ttl=60.0)
    with injected(FaultPlan(seed=3).drop("l1.lease", times=1, after=1)):
        assert lm.try_acquire() is False
    lease = l1.get_lease()
    assert lease is not None and lease.holder == "a" and lease.epoch == 1
    assert lm.role == ROLE_CANDIDATE and lm.epoch is None

    l1.expire_lease()  # the orphaned term runs out
    assert lm.try_acquire() is True
    assert lm.epoch == 2
    with pytest.raises(FencedError):
        l1.commit_batch(1, b"\x11" * 32, b"\x22" * 48, epoch=1)


def test_seq_fence_fault_site_fires_at_checkpoints():
    # non-HA: the checkpoint in Sequencer._fence
    l1 = _l1()
    node, seq = _seq(l1)
    node.submit_transaction(_transfer(0))
    node.produce_block(timestamp=1)
    with injected(FaultPlan(seed=1).drop("seq.fence", times=1)):
        with pytest.raises(InjectedFault):
            seq.commit_next_batch()
    assert seq.commit_next_batch() is not None  # budget exhausted
    seq.stop()

    # HA: the checkpoint in LeadershipManager.check
    lm = LeadershipManager(_l1(), "a", ttl=60.0)
    assert lm.try_acquire() is True
    with injected(FaultPlan(seed=1).drop("seq.fence", times=1)):
        with pytest.raises(InjectedFault):
            lm.check()
    assert lm.check() == 1


def test_ha_fault_sites_registered():
    assert "l1.lease" in faults.SITES
    assert "seq.fence" in faults.SITES


# ===========================================================================
# leadership manager lifecycle (threaded renewal loop)
# ===========================================================================

def test_leader_renews_past_ttl_and_releases_on_stop():
    l1 = _l1()
    lm = LeadershipManager(l1, "a", ttl=0.3, rng_seed=7).start()
    deadline = time.monotonic() + 5.0
    while not lm.is_leader() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert lm.is_leader()
    time.sleep(1.0)  # > 3 ttls: only renewal keeps the lease alive
    assert lm.is_leader()
    lease = l1.get_lease()
    assert lease.expires > time.time()
    lm.stop()
    assert lm.role == ROLE_FOLLOWER
    # clean release: the cell expired NOW, a successor needn't wait
    assert l1.acquire_lease("b", 60.0) == 2


def test_renewal_starvation_steps_down_within_safety_margin():
    l1 = _l1()
    demotions = []
    lm = LeadershipManager(l1, "a", ttl=0.3, rng_seed=7,
                           on_demote=lambda: demotions.append(1)).start()
    deadline = time.monotonic() + 5.0
    while not lm.is_leader() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert lm.is_leader()

    class _DeadL1:
        """An L1 that answers nothing: renewals and bids all fail."""

        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def renew_lease(self, node_id, epoch, ttl):
            return False

        def acquire_lease(self, node_id, ttl):
            return None

    lm.l1 = _DeadL1(l1)
    deadline = time.monotonic() + 5.0
    while lm.is_leader() and time.monotonic() < deadline:
        time.sleep(0.01)
    # stepped down BEFORE the lease could expire under a rival, and
    # parked through on_demote
    assert lm.role == ROLE_CANDIDATE
    assert demotions, "on_demote never ran"
    assert "starved" in (lm.last_error or "")
    lm.stop()


# ===========================================================================
# HA wiring: config validation, readiness, alerts, fenced demotion
# ===========================================================================

def test_ha_requires_lease_capable_l1():
    class NoLeases(InMemoryL1):
        def supports_leases(self):
            return False

    node = Node(Genesis.from_json(GENESIS))
    with pytest.raises(ValueError, match="leader-lease"):
        Sequencer(node, NoLeases([protocol.PROVER_EXEC]),
                  _cfg(role="leader"))
    with pytest.raises(ValueError, match="ha_role"):
        Sequencer(node, _l1(), _cfg(role="primary"))


def test_ready_json_and_rpc_ready_payload():
    from ethrex_tpu.rpc.server import _ready

    # no sequencer at all: plain node, trivially ready, not HA
    assert _ready(types.SimpleNamespace()) == \
        {"ready": True, "role": None, "ha": False}

    l1 = _l1()
    node, seq = _seq(l1, role="follower", node_id="standby")
    node.sequencer = seq
    rd = _ready(node)
    assert rd["ready"] is False and rd["ha"] is True
    assert rd["role"] == ROLE_FOLLOWER
    assert rd["leadership"]["epoch"] is None

    assert seq.leadership.try_acquire() is True
    rd = _ready(node)
    assert rd["ready"] is True and rd["role"] == ROLE_LEADER
    assert rd["promotions"] == 1
    assert rd["leadership"]["epoch"] == 1
    assert rd["leadership"]["promotionDowntimeSeconds"] is not None
    seq.stop()

    # non-HA sequencers stay ready without a leadership section
    node2, seq2 = _seq(_l1())
    node2.sequencer = seq2
    rd = _ready(node2)
    assert rd == {"ready": True, "role": "leader", "ha": False,
                  "reconciledAt": rd["reconciledAt"], "promotions": 0}
    seq2.stop()


def test_sequencer_leaderless_alert_signal():
    from ethrex_tpu.utils.alerts import sequencer_leaderless_signal

    # non-HA node: the signal stays disarmed (None), never firing
    assert sequencer_leaderless_signal(None, types.SimpleNamespace()) \
        is None
    node2, seq2 = _seq(_l1())
    node2.sequencer = seq2
    assert sequencer_leaderless_signal(None, node2) is None

    l1 = _l1()
    node, seq = _seq(l1, role="follower")
    node.sequencer = seq
    assert sequencer_leaderless_signal(None, node) == 1.0
    assert seq.leadership.try_acquire() is True
    assert sequencer_leaderless_signal(None, node) == 0.0
    seq.stop()
    seq2.stop()


def test_fenced_error_demotes_and_parks_actors():
    """The zombie path end-to-end: a deposed leader's commit is refused
    by the L1 with FencedError; handling it demotes the sequencer —
    every actor parked, coordinator down, epoch dropped — and re-enters
    candidacy."""
    l1 = _l1()
    node_a, a = _seq(l1, role="leader", node_id="a")
    node_b, b = _seq(l1, role="follower", node_id="b")
    assert a.leadership.try_acquire() is True
    _produce(node_a, 1)

    # the leader dies from the cluster's point of view; the standby wins
    l1.expire_lease()
    assert b.leadership.try_acquire() is True
    assert b.leadership.epoch == 2

    # ...but the old process is still running, and tries to commit
    with pytest.raises(FencedError):
        a.commit_next_batch()
    assert l1.fenced_writes_total == 1
    assert l1.last_committed_batch() == 0
    assert a.rollup.latest_batch_number() == 0

    # the actor loop's handler: demote without burning failure budget
    a.leadership.fenced(FencedError("deposed", epoch=1, current=2))
    assert a.leadership.role == ROLE_CANDIDATE
    assert a.leadership.epoch is None
    assert a.paused == set(Sequencer.ACTOR_NAMES)
    assert a.ready_json()["ready"] is False
    assert a.leadership.fenced_total == 1
    a.stop()
    b.stop()


# ===========================================================================
# the tentpole drill: leader killed at EVERY actor boundary; the
# standby promotes, finishes the pipeline, and converges byte-
# identically with the no-failover baseline
# ===========================================================================

KILL_POINTS = ("watch_l1", "produce_block", "commit_next_batch",
               "local_proof", "send_proofs", "aggregate_proofs",
               "update_state")


def _leader_steps(seq, node, kill_at):
    """Run the canonical schedule's first batch on the leader, dying
    right AFTER the named actor boundary."""
    seq.watch_l1()
    if kill_at == "watch_l1":
        return
    _produce(node, 1)
    if kill_at == "produce_block":
        return
    assert seq.commit_next_batch() is not None
    if kill_at == "commit_next_batch":
        return
    _prove(seq, 1)
    if kill_at == "local_proof":
        return
    seq.send_proofs()
    if kill_at == "send_proofs":
        return
    seq.aggregate_proofs()
    if kill_at == "aggregate_proofs":
        return
    seq.update_state()


@pytest.mark.parametrize("kill_at", KILL_POINTS)
def test_leader_kill_at_actor_boundary_converges(kill_at, baseline):
    l1 = _l1()
    commit_calls = []
    orig_commit = l1.commit_batch

    def counted(number, *a, **kw):
        commit_calls.append(number)
        return orig_commit(number, *a, **kw)

    l1.commit_batch = counted

    node_a, a = _seq(l1, role="leader", node_id="a")
    node_b, b = _seq(l1, role="follower", node_id="b")
    fetcher = BlockFetcher(node_b, l1, rollup=b.rollup)
    assert a.leadership.try_acquire() is True
    _leader_steps(a, node_a, kill_at)

    # the leader process is gone; its lease runs out
    l1.expire_lease()

    # hot-standby promotion: catch up from L1 DA, win the lease (which
    # runs reconciliation + repair as the ONLY startup path), continue
    fetcher.fetch_once()
    assert b.leadership.try_acquire() is True
    assert b.leadership.epoch == 2
    assert b.promotions_total == 1
    assert b.ready_json()["ready"] is True
    _drive(b, node_b, l1)

    # byte-identical convergence with the no-failover baseline: any
    # work the dead leader hadn't settled was re-derived to the SAME
    # blocks (deterministic schedule), anything settled was adopted
    assert _chain_fingerprint(node_b, l1) == baseline
    # zero double-commits across both leader generations
    assert sorted(commit_calls) == sorted(set(commit_calls))
    assert l1.last_committed_batch() == TOTAL_BLOCKS
    assert l1.last_verified_batch() == TOTAL_BLOCKS
    a.stop()
    b.stop()


def test_failover_through_commit_crash_window(baseline):
    """The nastiest kill: the L1 accepted batch 1 (commit tx + blobs
    mined) but the leader died before ANY local persistence.  The
    standby — which shares no disk with the dead leader — must adopt
    the settled batch from L1 data alone and finish the schedule."""
    l1 = _l1()
    node_a, a = _seq(l1, role="leader", node_id="a")
    node_b, b = _seq(l1, role="follower", node_id="b")
    fetcher = BlockFetcher(node_b, l1, rollup=b.rollup)
    assert a.leadership.try_acquire() is True
    _produce(node_a, 1)

    class Killed(RuntimeError):
        pass

    def dying(*args, **kwargs):
        raise Killed("process died before the rollup store heard")

    a.rollup.store_batch = dying
    with pytest.raises(Killed):
        a.commit_next_batch()
    assert l1.last_committed_batch() == 1
    assert l1.get_blob_sidecar(1) is not None
    assert a.rollup.latest_batch_number() == 0  # nothing persisted

    l1.expire_lease()
    assert fetcher.fetch_once() == 1
    assert b.leadership.try_acquire() is True
    # promotion repaired the prover input for the adopted batch
    assert b.rollup.get_prover_input(1, b.cfg.commit_hash) is not None
    _drive(b, node_b, l1)
    assert _chain_fingerprint(node_b, l1) == baseline
    a.stop()
    b.stop()


# ===========================================================================
# liveness: a real standby promotes within the lease TTL
# ===========================================================================

def test_standby_promotes_within_lease_ttl():
    ttl = 0.6
    l1 = _l1()
    node_a, a = _seq(l1, role="leader", ttl=ttl, node_id="a")
    node_b, b = _seq(l1, role="follower", ttl=ttl, node_id="b")
    fetcher = BlockFetcher(node_b, l1, rollup=b.rollup)
    try:
        a.start()
        deadline = time.monotonic() + 5.0
        while not a.leadership.is_leader() and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert a.leadership.is_leader()
        assert a.ready_json()["ready"] is True

        b.start()
        fetcher.start(interval=0.02)
        assert b.ready_json()["ready"] is False  # standby, parked

        # let the leader run a little, then crash it WITHOUT releasing
        # the lease: actors first (so the standby's view can catch up),
        # then the renewal loop
        time.sleep(max(ttl * 1.5, 1.0))
        a._stop.set()
        for t in a._threads:
            t.join(timeout=5.0)
        a.leadership._stop.set()
        a.leadership._thread.join(timeout=5.0)
        deadline = time.monotonic() + 5.0
        while fetcher.next_batch <= l1.last_committed_batch() and \
                time.monotonic() < deadline:
            time.sleep(0.01)

        t0 = time.monotonic()
        l1.expire_lease()  # the unreleased lease runs out
        deadline = t0 + 10.0
        while not b.leadership.is_leader() and \
                time.monotonic() < deadline:
            time.sleep(0.005)
        elapsed = time.monotonic() - t0
        assert b.leadership.is_leader(), "standby never promoted"
        assert elapsed <= ttl, (
            f"promotion took {elapsed:.3f}s, over the {ttl}s lease ttl")

        rd = b.ready_json()
        assert rd["ready"] is True and rd["role"] == ROLE_LEADER
        assert rd["leadership"]["promotionDowntimeSeconds"] is not None
        assert rd["leadership"]["epoch"] is not None
        assert b.promotions_total == 1
        assert not b.paused  # actors unparked
        lease = l1.get_lease()
        assert lease is not None and lease.holder == "b"
    finally:
        fetcher.stop()
        a.stop()
        b.stop()


# ===========================================================================
# prover-fleet re-homing: an in-flight phase-checkpointed proof
# resumes under the new leader's coordinator
# ===========================================================================

def test_prover_resumes_phases_after_coordinator_rehome(monkeypatch,
                                                        tmp_path):
    import numpy as np

    from ethrex_tpu.models import merkle_air as mair
    from ethrex_tpu.ops import babybear as bb
    from ethrex_tpu.ops.merkle import fold_path_canonical
    from ethrex_tpu.prover import runtime_errors as rt
    from ethrex_tpu.prover.client import ProverClient
    from ethrex_tpu.stark import prover as stark_prover
    from ethrex_tpu.stark.prover import StarkParams

    monkeypatch.setenv("ETHREX_PROOF_CKPT_DIR", str(tmp_path / "ckpt"))
    l1 = InMemoryL1([protocol.PROVER_TPU])
    cfg_a = SequencerConfig(needed_prover_types=(protocol.PROVER_TPU,),
                            ha_role="leader", leader_lease=60.0,
                            ha_node_id="a")
    cfg_b = SequencerConfig(needed_prover_types=(protocol.PROVER_TPU,),
                            ha_role="follower", leader_lease=60.0,
                            ha_node_id="b")
    node_a = Node(Genesis.from_json(GENESIS))
    a = Sequencer(node_a, l1, cfg_a)
    node_b = Node(Genesis.from_json(GENESIS))
    b = Sequencer(node_b, l1, cfg_b)
    fetcher = BlockFetcher(node_b, l1, rollup=b.rollup)

    assert a.leadership.try_acquire() is True
    a.coordinator.verify_submissions = False  # stub STARK payload
    _produce(node_a, 1)
    assert a.commit_next_batch() is not None

    # a small but REAL phase-checkpointed STARK pipeline as the
    # prover's device work (same shape as the p2p prover soak)
    rng = np.random.default_rng(23)
    depth = 3
    leaf = [int(v) for v in rng.integers(0, bb.P, 8)]
    siblings = [[int(v) for v in rng.integers(0, bb.P, 8)]
                for _ in range(depth)]
    index = int(rng.integers(0, 1 << depth))
    bits = [(index >> j) & 1 for j in range(depth)]
    root = fold_path_canonical(index, leaf, siblings)
    air = mair.Poseidon2MerkleAir(depth)
    mtrace = mair.generate_merkle_trace(leaf, siblings, bits)
    mpub = mair.merkle_public_inputs(leaf, root)
    sparams = StarkParams(log_blowup=3, num_queries=12, log_final_size=4)

    class CkptStarkBackend:
        prover_type = protocol.PROVER_TPU

        def prove(self, program_input, proof_format):
            stark = stark_prover.prove(air, mtrace, mpub, sparams)
            return {"backend": protocol.PROVER_TPU,
                    "stark": {"fri_roots": len(stark["fri"]["roots"])},
                    "output": "0x" + "00" * 176}

    resumes_before = rt.STATS["phase_resumes"]
    try:
        # phase 1: the prover starts the proof homed on leader A and is
        # preempted at its first phase boundary (checkpoints on disk)
        with injected(FaultPlan(seed=5).drop("backend.phase", times=1)):
            pc_a = ProverClient(CkptStarkBackend(),
                                [("127.0.0.1", a.coordinator.port)],
                                heartbeat_interval=0.1,
                                backoff_base=0.01, rng_seed=9)
            try:
                pc_a.poll_once()
            except Exception:  # noqa: BLE001 — the preemption itself
                pass
        assert a.rollup.get_proof(1, protocol.PROVER_TPU) is None

        # leader A dies; the standby catches up and promotes, which
        # re-homes the coordinator (same rollup view of batch 1)
        a.coordinator.stop()
        l1.expire_lease()
        assert fetcher.fetch_once() == 1
        assert b.leadership.try_acquire() is True
        b.coordinator.verify_submissions = False

        # phase 2: the SAME prover fleet polls the new home; the proof
        # must RESUME from the phase checkpoints, not restart
        pc_b = ProverClient(CkptStarkBackend(),
                            [("127.0.0.1", b.coordinator.port)],
                            heartbeat_interval=0.1,
                            backoff_base=0.01, rng_seed=9)
        deadline = time.time() + 90.0
        while time.time() < deadline and \
                b.rollup.get_proof(1, protocol.PROVER_TPU) is None:
            pc_b.poll_once()
            time.sleep(0.02)
        assert b.rollup.get_proof(1, protocol.PROVER_TPU) is not None, \
            "proof never landed at the new coordinator home"
        assert rt.STATS["phase_resumes"] > resumes_before, \
            "the re-homed prover re-proved from scratch instead of " \
            "resuming its phase checkpoints"
    finally:
        a.stop()
        b.stop()


# ===========================================================================
# BlockFetcher failure paths (the standby's lifeline)
# ===========================================================================

class _FlakyL1:
    """Delegating wrapper whose last_committed_batch fails on demand."""

    def __init__(self, inner):
        self._inner = inner
        self.fail = True

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def last_committed_batch(self):
        if self.fail:
            raise ConnectionError("l1 unreachable")
        return self._inner.last_committed_batch()


def test_fetcher_counts_errors_and_flips_health():
    node = Node(Genesis.from_json(GENESIS))
    flaky = _FlakyL1(_l1())
    fetcher = BlockFetcher(node, flaky, unhealthy_after=3)
    assert fetcher.healthy()
    fetcher.start(interval=0.01)
    deadline = time.monotonic() + 5.0
    while fetcher.consecutive_failures < 3 and \
            time.monotonic() < deadline:
        time.sleep(0.01)
    assert fetcher.fetch_errors >= 3
    assert not fetcher.healthy()
    assert "ConnectionError" in fetcher.last_error

    # the L1 heals: one clean pass resets the consecutive run (the
    # cumulative counter keeps the history) and health recovers
    flaky.fail = False
    deadline = time.monotonic() + 5.0
    while not fetcher.healthy() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert fetcher.healthy()
    assert fetcher.consecutive_failures == 0
    assert fetcher.last_error is None
    assert fetcher.fetch_errors >= 3
    fetcher.stop()


def test_fetcher_divergence_is_fatal():
    l1 = _l1()
    node_src, seq = _seq(l1)
    _produce(node_src, 1)
    seq.commit_next_batch()

    class _LyingL1:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def get_committed_state_root(self, number):
            return b"\xde" * 32  # disagrees with local execution

    node = Node(Genesis.from_json(GENESIS))
    fetcher = BlockFetcher(node, _LyingL1(l1))
    fetcher.start(interval=0.01)
    deadline = time.monotonic() + 5.0
    while fetcher.fatal is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert fetcher.fatal is not None
    assert not fetcher.healthy()
    assert fetcher._stop.is_set()  # the loop stopped itself
    fetcher.stop()
    seq.stop()


def test_fetcher_stop_idempotent_and_restartable():
    l1 = _l1()
    node_src, seq = _seq(l1)
    node = Node(Genesis.from_json(GENESIS))
    fetcher = BlockFetcher(node, l1)
    fetcher.stop()  # before start(): no-op
    fetcher.stop()

    _produce(node_src, 1)
    seq.commit_next_batch()
    fetcher.start(interval=0.01)
    fetcher.start(interval=0.01)  # idempotent while running
    deadline = time.monotonic() + 5.0
    while fetcher.batches_imported < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert fetcher.batches_imported == 1
    fetcher.stop()
    fetcher.stop()  # double-stop

    # restart after stop resumes from next_batch
    _produce(node_src, 2)
    seq.commit_next_batch()
    fetcher.start(interval=0.01)
    deadline = time.monotonic() + 5.0
    while fetcher.batches_imported < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert fetcher.batches_imported == 2
    assert node.store.latest_number() == 2
    assert fetcher.healthy()
    fetcher.stop()
    seq.stop()


# ===========================================================================
# stop idempotency (Sequencer + Node)
# ===========================================================================

def test_sequencer_stop_is_idempotent():
    node, seq = _seq(_l1())
    seq.start()
    assert seq.stop() is True
    assert seq.stop() is True  # second drain: cached verdict, no re-join

    # follower-safe: a standby whose actors never started drains clean
    node_f, follower = _seq(_l1(), role="follower")
    assert follower.stop() is True
    assert follower.stop() is True


def test_node_stop_is_idempotent():
    node = Node(Genesis.from_json(GENESIS))
    assert node.stop() is True  # before any producer started
    node2 = Node(Genesis.from_json(GENESIS))
    node2.start_dev_producer(block_time=0.01)
    assert node2.stop() is True
    assert node2._producer_thread is None
    assert node2.stop() is True


# ===========================================================================
# soak: live failover under load, downtime measured at the front door
# ===========================================================================

@pytest.mark.slow
def test_ha_failover_soak_keeps_serving(tmp_path):
    import json
    import urllib.request

    from ethrex_tpu.perf.loadgen import Harness
    from ethrex_tpu.rpc.server import RpcServer

    ttl = 0.6
    l1 = _l1()
    cfg = dict(block_time=0.05, commit_interval=0.05,
               proof_send_interval=0.2, aggregation_interval=0.2,
               watcher_interval=0.1)
    node_a = Node(Genesis.from_json(GENESIS))
    a = Sequencer(node_a, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,), ha_role="leader",
        leader_lease=ttl, ha_node_id="a", **cfg))
    node_b = Node(Genesis.from_json(GENESIS))
    b = Sequencer(node_b, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,), ha_role="follower",
        leader_lease=ttl, ha_node_id="b", **cfg))
    node_b.sequencer = b
    fetcher = BlockFetcher(node_b, l1, rollup=b.rollup)
    rpc = RpcServer(node_b, port=0).start()

    def ready():
        req = urllib.request.Request(
            f"http://127.0.0.1:{rpc.port}",
            data=json.dumps({"jsonrpc": "2.0", "id": 1,
                             "method": "ethrex_ready",
                             "params": []}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5.0) as resp:
            return json.loads(resp.read())["result"]

    try:
        a.start()
        deadline = time.monotonic() + 5.0
        while not a.leadership.is_leader() and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert a.leadership.is_leader()
        b.start()
        fetcher.start(interval=0.02)
        assert ready()["ready"] is False  # the standby is NOT ready

        def kill_leader():
            time.sleep(1.0)
            a._stop.set()
            for t in a._threads:
                t.join(timeout=5.0)
            a.leadership._stop.set()
            a.leadership._thread.join(timeout=5.0)
            l1.expire_lease()

        killer = threading.Thread(target=kill_leader, daemon=True)
        killer.start()
        # the front door keeps answering straight through the failover
        harness = Harness(f"http://127.0.0.1:{rpc.port}",
                          payload="ping", workers=2, timeout=5.0)
        rep = harness.run(20.0, duration=4.0)
        killer.join(10.0)
        assert rep["delivered"] > 0
        assert rep["errors"] == 0, "RPC errored during the failover"

        deadline = time.monotonic() + 10.0
        while not b.leadership.is_leader() and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        assert b.leadership.is_leader(), "standby never promoted"
        rd = ready()
        assert rd["ready"] is True and rd["role"] == ROLE_LEADER
        # the measured promotion downtime is on the wire for operators
        assert rd["leadership"]["promotionDowntimeSeconds"] is not None
        assert rd["leadership"]["promotionDowntimeSeconds"] < ttl
    finally:
        rpc.stop()
        fetcher.stop()
        a.stop()
        b.stop()
