"""snap/1 over real RLPx: full verified state download between two nodes."""

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.p2p.connection import P2PServer
from ethrex_tpu.p2p.snap import SnapError, snap_sync_state
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {
        "0x" + SENDER.hex(): {"balance": hex(10**21)},
        # an account with 600 storage slots: forces storage pagination
        # (> MAX_RESPONSE_ITEMS = 512) through the snap client
        "0x" + "fa" * 20: {
            "balance": "0x1", "code": "0x00",
            "storage": {hex(i): hex(i + 1) for i in range(600)},
        },
    },
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _rich_chain():
    """A chain with many accounts, a contract with storage, and code."""
    node = Node(Genesis.from_json(GENESIS))
    nonce = 0

    def send(to, value=0, data=b"", gas=300_000):
        nonlocal nonce
        node.submit_transaction(Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=gas, to=to, value=value, data=data).sign(SECRET))
        nonce += 1

    # spray value to 40 distinct fresh accounts
    for i in range(40):
        send(bytes([0x50 + i]) * 20, value=1000 + i, gas=21000)
    node.produce_block()
    # deploy a contract that writes 3 storage slots on deploy:
    # initcode: SSTORE(0,7) SSTORE(1,8) SSTORE(2,9); returns tiny runtime
    initcode = bytes.fromhex(
        "60075f55"       # SSTORE(0, 7)
        "6008600155"     # SSTORE(1, 8)
        "6009600255"     # SSTORE(2, 9)
        "625b5b5b5f52"   # PUSH3 0x5b5b5b; MSTORE at 0 (word-aligned)
        "6003601df3")    # RETURN(0x1d, 3) -> 3-byte runtime
    send(b"", data=initcode)
    node.produce_block()
    return node


def test_snap_sync_full_state():
    server_node = _rich_chain()
    client_node = Node(Genesis.from_json(GENESIS))
    srv_s = P2PServer(server_node).start()
    srv_c = P2PServer(client_node).start()
    try:
        peer = srv_c.dial(srv_s.host, srv_s.port, srv_s.pub)
        target_root = server_node.store.head_header().state_root
        synced = snap_sync_state(peer, client_node, target_root)
        assert synced >= 42  # sender + sprayed accounts + contract
        # client can now serve reads at the target root
        bal = client_node.store.account_state(
            target_root, bytes([0x50]) * 20)
        assert bal is not None and bal.balance > 0
        # contract storage + code arrived
        from ethrex_tpu.crypto.keccak import keccak256
        from ethrex_tpu.primitives import rlp as _rlp
        created = keccak256(_rlp.encode([SENDER, 40]))[12:]
        assert client_node.store.storage_at(target_root, created, 0) == 7
        assert client_node.store.storage_at(target_root, created, 2) == 9
        acct = client_node.store.account_state(target_root, created)
        assert client_node.store.code.get(acct.code_hash)
        # the 600-slot account synced through pagination
        big = bytes.fromhex("fa" * 20)
        assert client_node.store.storage_at(target_root, big, 599) == 600
        assert client_node.store.storage_at(target_root, big, 0) == 1
    finally:
        srv_s.stop()
        srv_c.stop()
        server_node.stop()
        client_node.stop()


def test_snap_sync_rejects_wrong_root():
    server_node = _rich_chain()
    client_node = Node(Genesis.from_json(GENESIS))
    srv_s = P2PServer(server_node).start()
    srv_c = P2PServer(client_node).start()
    try:
        peer = srv_c.dial(srv_s.host, srv_s.port, srv_s.pub)
        with pytest.raises(SnapError):
            snap_sync_state(peer, client_node, b"\x42" * 32)
    finally:
        srv_s.stop()
        srv_c.stop()
        server_node.stop()
        client_node.stop()
