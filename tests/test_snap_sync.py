"""snap/1 over real RLPx: full verified state download between two nodes."""

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.p2p.connection import P2PServer
from ethrex_tpu.p2p.snap import SnapError, snap_sync_state
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {
        "0x" + SENDER.hex(): {"balance": hex(10**21)},
        # an account with 600 storage slots: forces storage pagination
        # (> MAX_RESPONSE_ITEMS = 512) through the snap client
        "0x" + "fa" * 20: {
            "balance": "0x1", "code": "0x00",
            "storage": {hex(i): hex(i + 1) for i in range(600)},
        },
    },
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _rich_chain():
    """A chain with many accounts, a contract with storage, and code."""
    node = Node(Genesis.from_json(GENESIS))
    nonce = 0

    def send(to, value=0, data=b"", gas=300_000):
        nonlocal nonce
        node.submit_transaction(Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=gas, to=to, value=value, data=data).sign(SECRET))
        nonce += 1

    # spray value to 40 distinct fresh accounts
    for i in range(40):
        send(bytes([0x50 + i]) * 20, value=1000 + i, gas=21000)
    node.produce_block()
    # deploy a contract that writes 3 storage slots on deploy:
    # initcode: SSTORE(0,7) SSTORE(1,8) SSTORE(2,9); returns tiny runtime
    initcode = bytes.fromhex(
        "60075f55"       # SSTORE(0, 7)
        "6008600155"     # SSTORE(1, 8)
        "6009600255"     # SSTORE(2, 9)
        "625b5b5b5f52"   # PUSH3 0x5b5b5b; MSTORE at 0 (word-aligned)
        "6003601df3")    # RETURN(0x1d, 3) -> 3-byte runtime
    send(b"", data=initcode)
    node.produce_block()
    return node


def test_snap_sync_full_state():
    server_node = _rich_chain()
    client_node = Node(Genesis.from_json(GENESIS))
    srv_s = P2PServer(server_node).start()
    srv_c = P2PServer(client_node).start()
    try:
        peer = srv_c.dial(srv_s.host, srv_s.port, srv_s.pub)
        target_root = server_node.store.head_header().state_root
        synced = snap_sync_state(peer, client_node, target_root)
        assert synced >= 42  # sender + sprayed accounts + contract
        # client can now serve reads at the target root
        bal = client_node.store.account_state(
            target_root, bytes([0x50]) * 20)
        assert bal is not None and bal.balance > 0
        # contract storage + code arrived
        from ethrex_tpu.crypto.keccak import keccak256
        from ethrex_tpu.primitives import rlp as _rlp
        created = keccak256(_rlp.encode([SENDER, 40]))[12:]
        assert client_node.store.storage_at(target_root, created, 0) == 7
        assert client_node.store.storage_at(target_root, created, 2) == 9
        acct = client_node.store.account_state(target_root, created)
        assert client_node.store.code.get(acct.code_hash)
        # the 600-slot account synced through pagination
        big = bytes.fromhex("fa" * 20)
        assert client_node.store.storage_at(target_root, big, 599) == 600
        assert client_node.store.storage_at(target_root, big, 0) == 1
    finally:
        srv_s.stop()
        srv_c.stop()
        server_node.stop()
        client_node.stop()


def test_snap_sync_rejects_wrong_root():
    server_node = _rich_chain()
    client_node = Node(Genesis.from_json(GENESIS))
    srv_s = P2PServer(server_node).start()
    srv_c = P2PServer(client_node).start()
    try:
        peer = srv_c.dial(srv_s.host, srv_s.port, srv_s.pub)
        with pytest.raises(SnapError):
            snap_sync_state(peer, client_node, b"\x42" * 32)
    finally:
        srv_s.stop()
        srv_c.stop()
        server_node.stop()
        client_node.stop()


# ---------------------------------------------------------------------------
# SnapSyncer state machine: resume, re-pivot, healing
# (parity: crates/networking/p2p/sync/snap_sync.rs + sync/healing/)
# ---------------------------------------------------------------------------

def _state_matches(client_node, server_node, root):
    """Every account + storage slot + code at `root` is present and equal
    on the client (walked via the client's own tries)."""
    from ethrex_tpu.primitives.account import (AccountState,
                                               EMPTY_CODE_HASH,
                                               EMPTY_TRIE_ROOT)
    from ethrex_tpu.trie.trie import Trie

    server = Trie.from_nodes(root, server_node.store.nodes, share=True)
    client = Trie.from_nodes(root, client_node.store.nodes, share=True)
    count = 0
    for path, body in server.iter_from(b"\x00" * 32, max_items=10_000):
        key = bytes((path[i] << 4) | path[i + 1]
                    for i in range(0, len(path), 2))
        assert client.get(key) == body, f"account {key.hex()} differs"
        acct = AccountState.decode(body)
        if acct.storage_root != EMPTY_TRIE_ROOT:
            sserver = Trie.from_nodes(acct.storage_root,
                                      server_node.store.nodes, share=True)
            sclient = Trie.from_nodes(acct.storage_root,
                                      client_node.store.nodes, share=True)
            for sp, sv in sserver.iter_from(b"\x00" * 32, max_items=10_000):
                sk = bytes((sp[i] << 4) | sp[i + 1]
                           for i in range(0, len(sp), 2))
                assert sclient.get(sk) == sv
        if acct.code_hash != EMPTY_CODE_HASH:
            assert acct.code_hash in client_node.store.code
        count += 1
    return count


def test_snap_syncer_completes_and_resumes(monkeypatch):
    import ethrex_tpu.p2p.snap as snap_mod
    import ethrex_tpu.p2p.snap_sync as ss_mod
    from ethrex_tpu.p2p.snap_sync import SnapSyncer

    # small windows so the test chain spans several account ranges
    monkeypatch.setattr(snap_mod, "MAX_RESPONSE_ITEMS", 16)
    monkeypatch.setattr(ss_mod, "MAX_RESPONSE_ITEMS", 16)
    server_node = _rich_chain()
    client_node = Node(Genesis.from_json(GENESIS))
    srv_s = P2PServer(server_node).start()
    srv_c = P2PServer(client_node).start()
    try:
        peer = srv_c.dial(srv_s.host, srv_s.port, srv_s.pub)

        # fail the peer after 2 account-range answers -> progress persists
        class Flaky:
            def __init__(self, inner):
                self.inner = inner
                self.ranges = 0

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def snap_get_account_range(self, *a):
                self.ranges += 1
                if self.ranges > 2:
                    raise RuntimeError("simulated disconnect")
                return self.inner.snap_get_account_range(*a)

        try:
            SnapSyncer(client_node).run(Flaky(peer))
        except RuntimeError:
            pass
        saved = client_node.store.meta.get("snap_sync")
        assert saved is not None, "progress must persist across failures"

        # a NEW syncer (fresh process semantics) resumes and completes
        syncer = SnapSyncer(client_node)
        assert syncer.progress["pivot_root"] is not None
        summary = syncer.run(peer)
        assert summary["phase"] == "done"
        root = server_node.store.head_header().state_root
        assert _state_matches(client_node, server_node, root) >= 42
        assert client_node.store.meta.get("snap_sync") is None
    finally:
        srv_s.stop()
        srv_c.stop()


def test_snap_syncer_repivots_and_heals(monkeypatch):
    import ethrex_tpu.p2p.snap as snap_mod
    import ethrex_tpu.p2p.snap_sync as ss_mod
    from ethrex_tpu.p2p.snap_sync import SnapSyncer

    monkeypatch.setattr(snap_mod, "MAX_RESPONSE_ITEMS", 16)
    monkeypatch.setattr(ss_mod, "MAX_RESPONSE_ITEMS", 16)
    server_node = _rich_chain()
    client_node = Node(Genesis.from_json(GENESIS))
    srv_s = P2PServer(server_node).start()
    srv_c = P2PServer(client_node).start()
    try:
        peer = srv_c.dial(srv_s.host, srv_s.port, srv_s.pub)
        old_root = server_node.store.head_header().state_root

        class StalePivot:
            """Serves 1 range against the first pivot, then advances the
            chain and refuses the old root (pruned-peer behavior)."""

            def __init__(self, inner):
                self.inner = inner
                self.ranges = 0
                self.advanced = False

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def snap_get_account_range(self, root, origin, limit):
                self.ranges += 1
                if self.ranges > 1 and not self.advanced:
                    # the chain moves on: more balances change state
                    # (sender nonce after _rich_chain: 40 sprays + 1 deploy)
                    for i in range(6):
                        server_node.submit_transaction(Transaction(
                            tx_type=TYPE_DYNAMIC_FEE, chain_id=1337,
                            nonce=41 + i, max_priority_fee_per_gas=1,
                            max_fee_per_gas=10**10, gas_limit=21000,
                            to=bytes([0x50 + i]) * 20, value=999_999,
                        ).sign(SECRET))
                    blk = server_node.produce_block()
                    assert len(blk.body.transactions) == 6
                    self.advanced = True
                if self.advanced and root == old_root:
                    return [], []      # stale: peer pruned the old pivot
                return self.inner.snap_get_account_range(root, origin,
                                                         limit)

        wrapper = StalePivot(peer)
        syncer = SnapSyncer(client_node)
        summary = syncer.run(wrapper)
        assert summary["phase"] == "done"
        assert summary["repivots"] >= 1
        assert summary["healed"] > 0, "mixed pivots must trigger healing"
        new_root = server_node.store.head_header().state_root
        assert new_root != old_root
        assert _state_matches(client_node, server_node, new_root) >= 42
    finally:
        srv_s.stop()
        srv_c.stop()


def test_node_at_path_extension_boundary():
    """Healing regression: a path landing exactly on an extension node's
    hash child must resolve (extensions arise whenever keys share nibble
    prefixes)."""
    from ethrex_tpu.crypto.keccak import keccak256
    from ethrex_tpu.p2p.snap import node_at_path
    from ethrex_tpu.primitives.account import EMPTY_TRIE_ROOT
    from ethrex_tpu.primitives import rlp as _rlp
    from ethrex_tpu.trie.trie import Trie

    nodes = {}
    t = Trie.from_nodes(EMPTY_TRIE_ROOT, nodes, share=True)
    # shared 10-nibble prefix -> root extension over a branch
    t.insert(bytes.fromhex("aabbccddee" + "00" * 27), b"value-one" * 8)
    t.insert(bytes.fromhex("aabbccddee" + "ff" * 27), b"value-two" * 8)
    root = t.commit()
    root_node = nodes[root]
    item = _rlp.decode(root_node)
    assert len(item) == 2, "expected a root extension node"
    child_hash = bytes(item[1])
    assert len(child_hash) == 32
    # the extension's nibbles, one per byte (path of its child)
    from ethrex_tpu.trie.trie import hp_decode
    nib, is_leaf = hp_decode(bytes(item[0]))
    assert not is_leaf
    got = node_at_path(nodes, root, bytes(nib))
    assert got is not None and keccak256(got) == child_hash
