"""Poseidon2 JAX implementation vs host reference; Merkle commit/open."""

import numpy as np
import jax.numpy as jnp

from ethrex_tpu.ops import babybear as bb
from ethrex_tpu.ops import merkle
from ethrex_tpu.ops import poseidon2 as p2

RNG = np.random.default_rng(2)


def test_permute_matches_reference():
    states = RNG.integers(0, bb.P, size=(7, p2.WIDTH), dtype=np.uint32)
    got = np.asarray(bb.from_mont(p2.permute(bb.to_mont(jnp.asarray(states)))))
    for i in range(states.shape[0]):
        expect = p2.permute_ref(states[i])
        assert [int(x) for x in got[i]] == expect


def test_permute_is_permutation_not_identity():
    s = np.arange(p2.WIDTH, dtype=np.uint32)
    out = p2.permute_ref(s)
    assert out != list(s)
    assert len(set(out)) > 1


def test_hash_leaves_matches_reference():
    leaves = RNG.integers(0, bb.P, size=(4, 11), dtype=np.uint32)
    got = np.asarray(bb.from_mont(p2.hash_leaves(bb.to_mont(jnp.asarray(leaves)))))
    for i in range(4):
        assert [int(x) for x in got[i]] == merkle.hash_leaf_ref(leaves[i])


def test_merkle_commit_and_verify():
    leaves = RNG.integers(0, bb.P, size=(16, 4), dtype=np.uint32)
    levels = merkle.commit_levels(bb.to_mont(jnp.asarray(leaves)))
    root = merkle.root(levels)
    for idx in (0, 5, 15):
        path = merkle.open_path(levels, idx)
        leaf_digest = levels[0][idx]
        assert merkle.verify_path(root, idx, leaf_digest, path)
    # tampered path must fail
    path = merkle.open_path(levels, 3)
    bad = [np.asarray(p).copy() for p in path]
    bad[0][0] ^= 1
    assert not merkle.verify_path(root, 3, levels[0][3], bad)
