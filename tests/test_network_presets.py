"""Embedded network presets: genesis hashes against the canonical public
values, full EIP-2124 fork-hash ladders against the published mainnet
vectors, and the EIP-7840 blob schedule (reference:
crates/common/config/networks.rs:12-31)."""

import pytest

from ethrex_tpu.config import PRESET_NAMES, is_preset, load_network
from ethrex_tpu.p2p import eth_wire
from ethrex_tpu.primitives.genesis import Fork
from ethrex_tpu.storage.store import Store

MAINNET_HASH = bytes.fromhex(
    "d4e56740f876aef8c010b86a40d5f56745a118d0906a34e69aec8c0db1cb8fa3")
SEPOLIA_HASH = bytes.fromhex(
    "25a5cc106eea7138acab33231d7160d69cb777ee0c2c553fcddf5138993e6dd9")
HOODI_HASH = bytes.fromhex(
    "bbe312868b376a3001692a646dd2d7d1e4406380dfd86b98aa8a34d1557c971b")

# Published EIP-2124 / geth forkid checksums for mainnet, in activation
# order (genesis, homestead, DAO, tangerine, spurious, byzantium,
# constantinople+petersburg, istanbul, muir glacier, berlin, london,
# arrow glacier, gray glacier, shanghai, cancun, prague)
MAINNET_FORK_HASHES = [
    "fc64ec04", "97c2c34c", "91d1f948", "7a64da13", "3edd5b10",
    "a00bc324", "668db0af", "879d6e30", "e029e991", "0eb440f6",
    "b715077d", "20c327fc", "f0afd0e3", "dce96c2d", "9f3d2254",
    "c376cf8b",
]


def test_preset_genesis_hashes_are_canonical():
    want = {"mainnet": MAINNET_HASH, "sepolia": SEPOLIA_HASH,
            "hoodi": HOODI_HASH}
    for net in PRESET_NAMES:
        genesis, bootnodes = load_network(net)
        assert bootnodes and all(u.startswith("enode://")
                                 for u in bootnodes)
        header = Store().init_genesis(genesis)
        assert header.hash == want[net], net


def test_mainnet_fork_ladder_matches_published_forkid_vectors():
    genesis, _ = load_network("mainnet")
    cfg = genesis.config
    points = eth_wire._fork_points(cfg, genesis.timestamp)
    sums = [s.to_bytes(4, "big").hex()
            for s in eth_wire._checksums(MAINNET_HASH, points)]
    # the published ladder must be a prefix of ours (osaka/bpo points may
    # extend it beyond the last published checkpoint)
    assert sums[:len(MAINNET_FORK_HASHES)] == MAINNET_FORK_HASHES
    # the DAO fork and the glacier delays are distinct points
    assert 1920000 in cfg.aux_block_forks          # DAO
    assert 9200000 in cfg.aux_block_forks          # muir glacier
    assert cfg.block_forks[Fork.BERLIN] == 12244000


def test_mainnet_live_fork_id():
    """fork_id_for at a recent Prague-era head returns the published
    current mainnet fork hash."""
    genesis, _ = load_network("mainnet")
    fh, _next = eth_wire.fork_id_for(
        genesis.config, MAINNET_HASH,
        head_number=22_500_000, head_time=1_747_000_000,
        genesis_time=genesis.timestamp)
    assert fh.hex() == "c376cf8b"


def test_blob_schedule_parses_and_escalates():
    genesis, _ = load_network("hoodi")
    cfg = genesis.config
    assert cfg.blob_schedule, "hoodi must carry an EIP-7840 blob schedule"
    cancun_t = cfg.time_forks[Fork.CANCUN]
    prague_t = cfg.time_forks[Fork.PRAGUE]
    t_c, m_c, f_c = cfg.blob_params_at(cancun_t)
    t_p, m_p, f_p = cfg.blob_params_at(prague_t)
    assert (t_c, m_c) == (3 * 131072, 6 * 131072)      # cancun 3/6
    assert (t_p, m_p) == (6 * 131072, 9 * 131072)      # prague 6/9
    assert f_p > f_c
    # bpo points extend the schedule and the forkid ladder
    assert cfg.aux_time_forks
    last_t, last_target, last_max, _ = cfg.blob_schedule[-1]
    assert last_max > m_p


def test_preset_name_detection():
    assert is_preset("hoodi") and not is_preset("hoodi.json")


def test_excess_blob_gas_uses_new_block_schedule():
    """Review regression: the blob target for excess validation resolves
    at the NEW block's timestamp (spec; reference validate_excess_blob_gas),
    and Osaka adds the EIP-7918 reserve-price branch."""
    from ethrex_tpu.evm import gas as G

    cancun_target = 3 * 131072
    prague_target = 6 * 131072
    parent_excess, parent_used = 5 * 131072, 4 * 131072
    # at a Prague-era block after a Cancun parent, the Prague target rules
    assert G.calc_excess_blob_gas(parent_excess, parent_used,
                                  prague_target) == 3 * 131072
    assert G.calc_excess_blob_gas(parent_excess, parent_used,
                                  cancun_target) == 6 * 131072
    # EIP-7918: when execution gas is the better deal, excess decays
    # proportionally instead of by the full target
    got = G.calc_excess_blob_gas(
        parent_excess, parent_used, prague_target,
        max_blob_gas=9 * 131072, fraction=5007716,
        parent_base_fee=10**9, eip7918=True)
    assert got == parent_excess + parent_used * (9 - 6) // 9
    # with a tiny base fee the reserve-price branch does not bind
    got2 = G.calc_excess_blob_gas(
        parent_excess, parent_used, prague_target,
        max_blob_gas=9 * 131072, fraction=5007716,
        parent_base_fee=1, eip7918=True)
    assert got2 == 3 * 131072
