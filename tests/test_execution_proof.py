"""End-to-end execution proof: a real multi-block batch proven by the TPU
backend — write-log extraction, state-update STARK, binding STARK, witness
replay audit, and forgery rejection.

Parity target: the reference proves execute_blocks inside a zkVM
(crates/prover/src/backend/sp1.rs:145-163); here the state transition is
proven by the StateUpdateAir and audited against the witness MPT without
re-execution.
"""

import pytest

from ethrex_tpu.guest import access_log
from ethrex_tpu.guest.execution import (ProgramInput, ProgramOutput,
                                        execution_program)
from ethrex_tpu.guest.witness import generate_witness
from ethrex_tpu.prover.tpu_backend import TpuBackend
from tests.test_stateless import _make_chain_with_blocks

pytestmark = pytest.mark.slow  # full STARK compiles


@pytest.fixture(scope="module")
def batch():
    node, blocks = _make_chain_with_blocks()
    witness = generate_witness(node.chain, blocks)
    return ProgramInput(blocks=blocks, witness=witness, config=node.config)


def test_write_log_replays_into_witness(batch):
    blocks_log = []
    out = execution_program(batch, write_log=blocks_log)
    assert len(blocks_log) == len(batch.blocks)
    assert any(e[0] == "slot" for block in blocks_log for e in block)
    # the audit replays the log into the MPT without executing
    access_log.replay_log_against_witness(
        blocks_log, batch.witness.nodes,
        out.initial_state_root, out.final_state_root)
    # wire round-trip preserves the log exactly
    wire = access_log.raw_log_to_json(blocks_log)
    assert access_log.raw_log_from_json(wire) == blocks_log
    # a tampered old value is caught by the audit
    bad = access_log.raw_log_from_json(wire)
    for block in bad:
        for i, e in enumerate(block):
            if e[0] == "slot":
                block[i] = (e[0], e[1], e[2], e[3] + 1, e[4])
                break
    with pytest.raises(access_log.LogAuditError):
        access_log.replay_log_against_witness(
            bad, batch.witness.nodes,
            out.initial_state_root, out.final_state_root)


def test_flat_chain_consistency(batch):
    blocks_log = []
    execution_program(batch, write_log=blocks_log)
    entries = access_log.flatten_entries(blocks_log)
    records, r_pre, r_post, depth = \
        access_log.build_access_records(entries)
    assert len(records) == len(entries)
    assert r_pre != r_post
    # same key written twice across blocks chains old -> new correctly
    keys = [e.key for e in entries]
    assert len(set(keys)) < len(keys), "batch should revisit a key"


def test_tpu_backend_proves_and_audits_execution(batch):
    backend = TpuBackend()
    proof = backend.prove(batch, "stark")
    out = ProgramOutput.decode(bytes.fromhex(proof["output"][2:]))
    assert out.final_state_root == batch.blocks[-1].header.state_root

    # full verification: both STARKs + commitment recompute
    assert backend.verify(proof)
    # with the input: the witness MPT replay audit as well
    assert backend.verify_with_input(proof, batch)

    # tampered output bytes break the binding proof
    bad = dict(proof)
    raw = bytearray.fromhex(proof["output"][2:])
    raw[33] ^= 1  # final_state_root byte
    bad["output"] = "0x" + raw.hex()
    assert not backend.verify(bad)

    # a forged write (different new value) no longer matches the proven
    # log digest
    bad2 = dict(proof)
    log = [list(map(list, block)) if False else list(block)
           for block in access_log.raw_log_from_json(proof["write_log"])]
    tampered = False
    for block in log:
        for i, e in enumerate(block):
            if e[0] == "slot":
                block[i] = (e[0], e[1], e[2], e[3], e[4] ^ 1)
                tampered = True
                break
        if tampered:
            break
    assert tampered
    bad2["write_log"] = access_log.raw_log_to_json(log)
    assert not backend.verify(bad2)

    # dropping a whole entry shifts the digest too
    bad3 = dict(proof)
    log3 = access_log.raw_log_from_json(proof["write_log"])
    log3[0] = log3[0][1:]
    bad3["write_log"] = access_log.raw_log_to_json(log3)
    assert not backend.verify(bad3)


def test_cleared_storage_rewrite_is_logged_and_replayable():
    """Storage-clearing regression: a slot rewritten to its pre-block value
    after a destroy+recreate must appear in the write log, because the
    verifier rebuilds the cleared storage trie from the empty root using
    exactly the logged writes."""
    from ethrex_tpu.crypto.keccak import keccak256
    from ethrex_tpu.evm.db import StateDB, TrieSource
    from ethrex_tpu.primitives import rlp
    from ethrex_tpu.primitives.account import (EMPTY_CODE_HASH,
                                               EMPTY_TRIE_ROOT,
                                               AccountState)
    from ethrex_tpu.storage.store import apply_updates_to_tries
    from ethrex_tpu.trie.trie import Trie

    nodes = {}
    st = Trie.from_nodes(EMPTY_TRIE_ROOT, nodes, share=True)
    st.insert(keccak256((1).to_bytes(32, "big")), rlp.encode(5))
    sroot = st.commit()
    addr = b"\xaa" * 20
    acct = AccountState(nonce=1, balance=0, storage_root=sroot,
                        code_hash=EMPTY_CODE_HASH)
    t = Trie.from_nodes(EMPTY_TRIE_ROOT, nodes, share=True)
    t.insert(keccak256(addr), acct.encode())
    root = t.commit()

    db = StateDB(TrieSource(nodes, root))
    cached = db._load(addr)
    cached.nonce = 2                 # recreate changes the account
    cached.storage_cleared = True
    cached.storage = {1: 5}          # constructor rewrites the same value
    db.dirty_accounts.add(addr)
    db.dirty_storage[addr] = {1}
    assert db.get_storage(addr, 1) == 5

    log = []
    new_root = apply_updates_to_tries(nodes, {}, root, db, write_log=log)
    assert any(e[0] == "slot" for e in log), \
        "cleared-storage rewrite must be logged"
    # the verifier's non-executing replay reproduces the same root
    access_log.replay_log_against_witness(
        [log], [bytes(n) for n in nodes.values()], root, new_root)
