"""EF GeneralStateTest runner (the tooling/ef_tests/state_v2 seat).

The vendored fixtures under tests/fixtures/ef_state/ are written in the
exact EF wire format (see _generate.py there for provenance); a public EF
archive plugs in unmodified via EF_STATE_FIXTURES=<dir>.
"""

import copy
import json
import os

import pytest

from ethrex_tpu.utils import ef_state

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures", "ef_state")


def test_vendored_fixtures_all_pass():
    passed, failed = ef_state.run_directory(FIXDIR)
    assert not failed, "; ".join(
        f"{r.case.name}[{r.case.fork}{r.case.indexes}]: {r.detail}"
        for r in failed)
    # forks x indexes expansion: 6 files expand to well over 6 cases
    assert len(passed) >= 12


def test_case_expansion_covers_forks_and_indexes():
    cases = ef_state.load_fixture_file(
        os.path.join(FIXDIR, "create_tx.json"))
    # one fork, two value indexes
    assert {c.indexes for c in cases} == {(0, 0, 0), (0, 0, 1)}
    cases = ef_state.load_fixture_file(
        os.path.join(FIXDIR, "transfer_legacy.json"))
    assert {c.fork for c in cases} == {"Shanghai", "Cancun", "Prague"}


def test_tampered_hash_fails(tmp_path):
    with open(os.path.join(FIXDIR, "transfer_legacy.json")) as f:
        fixture = json.load(f)
    bad = copy.deepcopy(fixture)
    post = bad["transfer_legacy"]["post"]["Prague"][0]
    post["hash"] = "0x" + "11" * 32
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    passed, failed = ef_state.run_directory(str(tmp_path))
    assert len(failed) == 1 and "state root" in failed[0].detail


def test_tampered_logs_fails(tmp_path):
    with open(os.path.join(FIXDIR, "sstore_refund_log_1559.json")) as f:
        fixture = json.load(f)
    bad = copy.deepcopy(fixture)
    for cases in bad["sstore_refund_log_1559"]["post"].values():
        for post in cases:
            post["logs"] = "0x" + "22" * 32
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    passed, failed = ef_state.run_directory(str(tmp_path))
    assert failed and all("logs hash" in r.detail for r in failed)


def test_expect_exception_enforced(tmp_path):
    """A fixture claiming an exception for a VALID tx must fail."""
    with open(os.path.join(FIXDIR, "transfer_legacy.json")) as f:
        fixture = json.load(f)
    bad = copy.deepcopy(fixture)
    post = bad["transfer_legacy"]["post"]["Prague"][0]
    post["expectException"] = "TransactionException.INTRINSIC_GAS_TOO_LOW"
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    passed, failed = ef_state.run_directory(str(tmp_path))
    assert any("expected TransactionException" in r.detail for r in failed)


def test_info_blocks_skipped(tmp_path):
    p = tmp_path / "info.json"
    p.write_text(json.dumps({"weird": {"_info": {"comment": "no tx"}}}))
    assert ef_state.load_fixture_file(str(p)) == []


@pytest.mark.skipif(not os.environ.get("EF_STATE_FIXTURES"),
                    reason="EF_STATE_FIXTURES not set (archive not in image)")
def test_external_archive():
    passed, failed = ef_state.run_directory(
        os.environ["EF_STATE_FIXTURES"])
    assert not failed, f"{len(failed)} failures, first: {failed[0].detail}"
