"""REPL, monitor and prewarm tooling (reference inventory rows:
tooling/repl, tooling/monitor, crates/blockchain/prewarm.rs)."""

from ethrex_tpu.blockchain.prewarm import prewarm_transactions
from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import Transaction
from ethrex_tpu.rpc.server import RpcServer
from ethrex_tpu.utils.monitor import render_lines, snapshot
from ethrex_tpu.utils.repl import RpcSession, dispatch

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _tx(nonce, value=100):
    return Transaction(
        tx_type=2, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21_000, to=bytes([0x42]) * 20, value=value).sign(SECRET)


def _node_with_rpc():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, host="127.0.0.1", port=0).start()
    return node, server, f"http://127.0.0.1:{server.port}"


def test_repl_dispatch_commands():
    node, server, url = _node_with_rpc()
    try:
        node.submit_transaction(_tx(0))
        node.produce_block()
        rpc = RpcSession(url)
        assert dispatch(rpc, "bn") == "1"
        assert "#1" in dispatch(rpc, "head")
        assert dispatch(rpc, f"bal 0x{'42' * 20}") == "100"
        assert "gasUsed" in dispatch(rpc, "block 1")
        assert "pending" in dispatch(rpc, "raw txpool_status")
        assert dispatch(rpc, "eth_chainId") == "0x539"
        assert "unknown command" in dispatch(rpc, "nosuch")
        assert "bn" in dispatch(rpc, "help")
    finally:
        server._httpd.shutdown()


def test_monitor_snapshot_and_render():
    node, server, url = _node_with_rpc()
    try:
        for n in range(3):
            node.submit_transaction(_tx(n))
            node.produce_block()
        snap = snapshot(RpcSession(url), blocks=4)
        assert snap["head"]["number"] == 3
        assert [b["number"] for b in snap["recent"]] == [0, 1, 2, 3]
        assert snap["txpool"] == {"pending": 0, "queued": 0}
        lines = render_lines(snap, width=80)
        assert any("head #3" in ln for ln in lines)
        assert any("recent blocks" in ln for ln in lines)
    finally:
        server._httpd.shutdown()


def test_prewarm_is_side_effect_free_and_counts():
    node = Node(Genesis.from_json(GENESIS))
    parent = node.store.head_header()
    txs = [_tx(n) for n in range(5)]
    root_before = node.head_state_root()
    ran = prewarm_transactions(node.chain, parent, txs)
    assert ran == 5
    # canonical state untouched
    assert node.head_state_root() == root_before
    assert node.store.head_header().number == 0
    # the real block still builds and includes the txs
    for t in txs:
        node.submit_transaction(t)
    blk = node.produce_block()
    assert len(blk.body.transactions) == 5


def test_every_fault_site_has_chaos_coverage():
    """Every registered fault-injection site must be exercised by at
    least one chaos test, so a new site cannot land without battery
    coverage."""
    import glob
    import os

    from ethrex_tpu.utils import faults

    here = os.path.dirname(__file__)
    corpus = ""
    # the HA leader-kill battery is a chaos battery in all but filename
    paths = glob.glob(os.path.join(here, "test_*chaos*.py"))
    paths.append(os.path.join(here, "test_sequencer_ha.py"))
    for path in paths:
        with open(path) as f:
            corpus += f.read()
    missing = [s for s in sorted(faults.SITES) if f'"{s}"' not in corpus]
    assert not missing, f"fault sites without chaos coverage: {missing}"


def test_ha_fault_sites_covered_by_ha_battery():
    """The leadership sites are the HA battery's contract: each must be
    exercised in tests/test_sequencer_ha.py specifically (not merely
    mentioned somewhere in another battery)."""
    import os

    from ethrex_tpu.utils import faults

    here = os.path.dirname(__file__)
    with open(os.path.join(here, "test_sequencer_ha.py")) as f:
        corpus = f.read()
    ha_sites = ["l1.lease", "seq.fence"]
    missing = [s for s in ha_sites if s not in faults.SITES]
    assert not missing, \
        f"HA fault sites missing from faults.SITES: {missing}"
    missing = [s for s in ha_sites if f'"{s}"' not in corpus]
    assert not missing, \
        f"HA sites without HA-battery coverage: {missing}"


def test_store_fault_sites_covered_by_storage_battery():
    """The store.* sites are the storage battery's contract: each must be
    exercised in tests/test_storage_chaos.py specifically (not merely
    mentioned somewhere in another battery)."""
    import os

    from ethrex_tpu.utils import faults

    here = os.path.dirname(__file__)
    with open(os.path.join(here, "test_storage_chaos.py")) as f:
        corpus = f.read()
    store_sites = [s for s in sorted(faults.SITES)
                   if s.startswith("store.")]
    assert store_sites, "store.* fault sites missing from faults.SITES"
    missing = [s for s in store_sites if f'"{s}"' not in corpus]
    assert not missing, \
        f"store sites without storage-battery coverage: {missing}"


def test_serving_fault_sites_covered_by_overload_battery():
    """The serving-path sites (rpc.*, mempool.*) are the overload
    battery's contract: each must be exercised in
    tests/test_overload_chaos.py specifically."""
    import os

    from ethrex_tpu.utils import faults

    here = os.path.dirname(__file__)
    with open(os.path.join(here, "test_overload_chaos.py")) as f:
        corpus = f.read()
    serving_sites = [s for s in sorted(faults.SITES)
                     if s.startswith(("rpc.", "mempool."))
                     # the reorg re-injection path belongs to the reorg
                     # battery's contract, not the serving path's
                     and s != "mempool.reinject"]
    assert serving_sites, \
        "serving fault sites missing from faults.SITES"
    missing = [s for s in serving_sites if f'"{s}"' not in corpus]
    assert not missing, \
        f"serving sites without overload-battery coverage: {missing}"


def test_scheduler_fault_sites_covered_by_scheduler_battery():
    """The scheduling/aggregation sites are the scheduler battery's
    contract: each must be exercised in tests/test_scheduler_chaos.py
    specifically (coordinator.store_proof predates the fleet scheduler
    and stays with the prover battery)."""
    import os

    from ethrex_tpu.utils import faults

    here = os.path.dirname(__file__)
    with open(os.path.join(here, "test_scheduler_chaos.py")) as f:
        corpus = f.read()
    sched_sites = ["coordinator.schedule", "aggregate.prove",
                   "submit.duplicate"]
    missing = [s for s in sched_sites if s not in faults.SITES]
    assert not missing, \
        f"scheduler fault sites missing from faults.SITES: {missing}"
    missing = [s for s in sched_sites if f'"{s}"' not in corpus]
    assert not missing, \
        f"scheduler sites without scheduler-battery coverage: {missing}"


def test_p2p_fault_sites_covered_by_p2p_battery():
    """The p2p-path sites (net.*, peer.*, snap.*) are the p2p battery's
    contract: each must be exercised in tests/test_p2p_chaos.py
    specifically, so a new wire fault site cannot land without a drill."""
    import os

    from ethrex_tpu.utils import faults

    here = os.path.dirname(__file__)
    with open(os.path.join(here, "test_p2p_chaos.py")) as f:
        corpus = f.read()
    p2p_sites = [s for s in sorted(faults.SITES)
                 if s.startswith(("net.", "peer.", "snap."))]
    assert p2p_sites, "p2p fault sites missing from faults.SITES"
    missing = [s for s in p2p_sites if f'"{s}"' not in corpus]
    assert not missing, \
        f"p2p sites without p2p-battery coverage: {missing}"


def test_runtime_fault_sites_covered_by_runtime_battery():
    """The prover-runtime sites ("backend.phase", "device.lost") are the
    runtime battery's contract: each must be exercised in
    tests/test_runtime_chaos.py specifically — a new phase-level fault
    site cannot land without a checkpoint/ladder drill."""
    import os

    from ethrex_tpu.utils import faults

    here = os.path.dirname(__file__)
    with open(os.path.join(here, "test_runtime_chaos.py")) as f:
        corpus = f.read()
    runtime_sites = ["backend.phase", "device.lost"]
    missing = [s for s in runtime_sites if s not in faults.SITES]
    assert not missing, \
        f"runtime fault sites missing from faults.SITES: {missing}"
    missing = [s for s in runtime_sites if f'"{s}"' not in corpus]
    assert not missing, \
        f"runtime sites without runtime-battery coverage: {missing}"


def test_reorg_fault_sites_covered_by_reorg_battery():
    """The reorg-lifecycle sites ("forkchoice.apply", "mempool.reinject")
    are the reorg battery's contract: each must be exercised in
    tests/test_reorg_chaos.py specifically — the two-leg fork-choice
    crash window and the mid-settlement re-injection crash cannot lose
    their drills (docs/CHAIN_RESILIENCE.md)."""
    import os

    from ethrex_tpu.utils import faults

    here = os.path.dirname(__file__)
    with open(os.path.join(here, "test_reorg_chaos.py")) as f:
        corpus = f.read()
    reorg_sites = ["forkchoice.apply", "mempool.reinject"]
    missing = [s for s in reorg_sites if s not in faults.SITES]
    assert not missing, \
        f"reorg fault sites missing from faults.SITES: {missing}"
    missing = [s for s in reorg_sites if f'"{s}"' not in corpus]
    assert not missing, \
        f"reorg sites without reorg-battery coverage: {missing}"


def test_no_bare_print_in_library_modules():
    """Library diagnostics go through the structured logger
    (utils/tracing.py setup_logging), never bare print().  Terminal
    front-ends (cli, repl, monitor) own stdout and are allowlisted."""
    import pathlib
    import re

    import ethrex_tpu

    root = pathlib.Path(ethrex_tpu.__file__).parent
    # bench_suite is the bench.py CLI's engine: its contract is ONE JSON
    # line on stdout per measurement, so it owns stdout like cli/repl;
    # loadgen is the load-harness CLI printing its JSON report the same way
    allow = {"cli.py", "repl.py", "monitor.py", "bench_suite.py",
             "loadgen.py"}
    pat = re.compile(r"(?<![A-Za-z0-9_.])print\(")
    offenders = []
    for path in sorted(root.rglob("*.py")):
        if path.name in allow:
            continue
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            if pat.search(line):
                offenders.append(f"{path.relative_to(root)}:{lineno}")
    assert not offenders, \
        f"bare print() in library modules (use logging): {offenders}"


def test_every_native_source_has_probed_fallback():
    """Every native/*.c / *.cpp engine must have a Python wrapper module
    with an `available()` probe, so callers can gate on the native path
    uniformly and nothing hard-fails without a toolchain.  A new native
    source must be registered here with its wrapper."""
    import importlib
    import os
    import pathlib

    import ethrex_tpu

    wrappers = {
        "evm.cpp": "ethrex_tpu.evm.native_vm",
        "keccak.c": "ethrex_tpu.crypto.keccak",
        "kvstore.cpp": "ethrex_tpu.storage.persistent",
        "mpt.cpp": "ethrex_tpu.trie.native_mpt",
        "secp256k1.c": "ethrex_tpu.crypto.native_secp256k1",
    }
    native_dir = pathlib.Path(ethrex_tpu.__file__).parent.parent / "native"
    sources = sorted(p.name for p in native_dir.iterdir()
                     if p.suffix in (".c", ".cpp"))
    unmapped = [s for s in sources if s not in wrappers]
    assert not unmapped, \
        f"native sources without a registered Python wrapper: {unmapped}"
    for src, mod_name in sorted(wrappers.items()):
        assert os.path.exists(native_dir / src), \
            f"{mod_name} wraps native/{src}, which does not exist"
        mod = importlib.import_module(mod_name)
        probe = getattr(mod, "available", None)
        assert callable(probe), \
            f"{mod_name} (wrapper for native/{src}) lacks available()"
        assert isinstance(probe(), bool), \
            f"{mod_name}.available() must return a bool"


def test_bench_probe_reports_failure_detail(monkeypatch):
    """A degraded bench record must say WHY the backend probe failed —
    the last exception line of the child's stderr, or the timeout."""
    import subprocess

    import bench

    class Failed:
        returncode = 1
        stderr = (b"Traceback (most recent call last):\n"
                  b'  File "<string>", line 1, in <module>\n'
                  b"RuntimeError: no TPU devices found\n")

    monkeypatch.setattr(bench.subprocess, "run",
                        lambda *a, **kw: Failed())
    assert bench.probe_backend_error() == "RuntimeError: no TPU devices found"
    assert bench.probe_backend() is False

    class Ok:
        returncode = 0
        stderr = b""

    monkeypatch.setattr(bench.subprocess, "run", lambda *a, **kw: Ok())
    assert bench.probe_backend_error() is None
    assert bench.probe_backend() is True

    def hang(*a, **kw):
        raise subprocess.TimeoutExpired("probe", bench.PROBE_TIMEOUT)

    monkeypatch.setattr(bench.subprocess, "run", hang)
    err = bench.probe_backend_error()
    assert err is not None and "TimeoutExpired" in err


def test_every_metric_helper_has_help_text():
    """Every record_*/observe_* helper in utils/metrics.py AND the perf
    package must attach non-empty help text to each metric it touches —
    an undocumented family in the exposition is a family nobody can
    alert on.  A metric call carries its help as the second (or later)
    string literal, so each METRICS.inc/set/observe/set_labeled or
    _observe_safe call inside a helper must contain at least two
    non-empty string constants (name + help) or an explicit help_text=
    keyword."""
    import ast
    import inspect

    from ethrex_tpu.blockchain import fork_choice, mempool
    from ethrex_tpu.l2 import leadership
    from ethrex_tpu.perf import (bench_suite, chain_path, hlo_introspect,
                                 loadgen, occupancy, profiler, roofline)
    from ethrex_tpu.prover import checkpoint, runtime_errors
    from ethrex_tpu.utils import exec_cache, metrics, overload

    from ethrex_tpu.utils import tracing

    offenders = []
    for mod in (metrics, tracing, profiler, roofline, hlo_introspect,
                occupancy, bench_suite, loadgen, chain_path,
                mempool, fork_choice, overload, exec_cache, checkpoint,
                runtime_errors, leadership):
        tree = ast.parse(inspect.getsource(mod))
        for fn in ast.walk(tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            if not (fn.name.startswith("record_")
                    or fn.name.startswith("observe_")):
                continue
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                f = call.func
                is_metric = (
                    (isinstance(f, ast.Attribute)
                     and f.attr in ("inc", "set", "observe", "set_labeled",
                                    "inc_labeled")
                     and isinstance(f.value, ast.Name)
                     # "registry" covers helpers writing into a run-local
                     # Metrics() instead of the global singleton (loadgen)
                     and f.value.id in ("METRICS", "registry"))
                    or (isinstance(f, ast.Name) and f.id == "_observe_safe"))
                if not is_metric:
                    continue
                strings = [a.value for a in call.args
                           if isinstance(a, ast.Constant)
                           and isinstance(a.value, str) and a.value.strip()]
                kw_help = any(
                    k.arg == "help_text"
                    and isinstance(k.value, ast.Constant)
                    and isinstance(k.value.value, str)
                    and k.value.value.strip()
                    for k in call.keywords)
                if len(strings) < 2 and not kw_help:
                    offenders.append(f"{mod.__name__}.{fn.name} "
                                     f"(line {call.lineno})")
    assert not offenders, \
        f"metric calls without help text: {offenders}"


def test_histogram_exemplar_golden_exposition_line():
    """OpenMetrics exemplar syntax, golden: the bucket an observation
    lands in carries `# {trace_id="..."} value` (no timestamp — keeps
    this golden stable), other buckets stay bare."""
    from ethrex_tpu.utils.metrics import Metrics

    m = Metrics()
    tid = "ab" * 8
    m.observe("batch_proving_seconds", 0.003, None, "batch proving wall",
              exemplar=tid)
    lines = m.render().splitlines()
    assert ('batch_proving_seconds_bucket{le="0.004"} 1'
            f' # {{trace_id="{tid}"}} 0.003') in lines
    # the cumulative buckets above it count the observation WITHOUT
    # inheriting the exemplar
    assert 'batch_proving_seconds_bucket{le="0.008"} 1' in lines
    assert 'batch_proving_seconds_bucket{le="0.002"} 0' in lines
    # an over-ladder value exemplars the +Inf bucket
    m.observe("batch_proving_seconds", 10**6, None, "batch proving wall",
              exemplar="ff" * 8)
    text = m.render()
    assert (f'batch_proving_seconds_bucket{{le="+Inf"}} 2'
            f' # {{trace_id="{"ff" * 8}"}} 1000000.0') in text


def test_label_set_cardinality_clamp():
    """Unbounded label values cannot grow a family past MAX_LABEL_SETS
    (mirror of the profiler's MAX_KEYS): overflow series are dropped and
    counted, existing series keep updating."""
    from ethrex_tpu.utils.metrics import MAX_LABEL_SETS, Metrics

    m = Metrics()
    for i in range(MAX_LABEL_SETS + 88):
        m.observe("h_seconds", 0.1, {"k": f"v{i}"}, "h")
    assert len(m.histograms["h_seconds"].series) == MAX_LABEL_SETS
    assert m.counters["metrics_dropped_label_sets_total"] == 88
    # an existing series still updates after the clamp engages
    m.observe("h_seconds", 0.1, {"k": "v0"}, "h")
    row = m.histograms["h_seconds"].series[(("k", "v0"),)]
    assert row[len(m.histograms["h_seconds"].buckets)] == 2
    # labelled counters and gauges sit behind the same clamp
    for i in range(MAX_LABEL_SETS + 1):
        m.inc_labeled("c_total", {"k": f"v{i}"}, 1, "c")
        m.set_labeled("g", {"k": f"v{i}"}, 1.0, "g")
    assert len(m.lcounters["c_total"]) == MAX_LABEL_SETS
    assert len(m.lgauges["g"]) == MAX_LABEL_SETS
    # the drop counter itself is documented in the exposition
    assert "# HELP metrics_dropped_label_sets_total" in m.render()


def test_trace_analysis_rpcs_degrade_gracefully(monkeypatch):
    """ethrex_trace_criticalPath / ethrex_trace_export on an unknown
    trace or an empty ring (L1-only / pre-tracing node) answer with a
    found=False stub, never an error."""
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.rpc.server import RpcServer
    from ethrex_tpu.utils.tracing import Tracer

    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node)
    r = server.handle({"jsonrpc": "2.0", "id": 1,
                       "method": "ethrex_trace_criticalPath",
                       "params": ["ff" * 8]})
    assert r["result"] == {"found": False, "traceId": "ff" * 8,
                           "components": {}, "chain": []}
    r = server.handle({"jsonrpc": "2.0", "id": 2,
                       "method": "ethrex_trace_export",
                       "params": ["ff" * 8]})
    assert r["result"]["found"] is False
    assert r["result"]["traceEvents"] == []
    # empty ring + no trace-id argument: nothing to resolve
    monkeypatch.setattr("ethrex_tpu.rpc.server.TRACER", Tracer())
    for method in ("ethrex_trace_criticalPath", "ethrex_trace_export"):
        r = server.handle({"jsonrpc": "2.0", "id": 3, "method": method,
                           "params": []})
        assert r["result"]["found"] is False


def test_chain_path_rpc_degrades_on_idle_l1_node():
    """ethrex_chainPath on a fresh L1-only node (no traffic, no
    sequencer) answers a truthful idle stub — enabled, all three stage
    queues present at depth 0, no sampled lifecycles, bottleneck null —
    never an error.  The ethrex_health chainPath section degrades the
    same way."""
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node)
    try:
        r = server.handle({"jsonrpc": "2.0", "id": 1,
                           "method": "ethrex_chainPath", "params": []})
        out = r["result"]
        assert out["enabled"] is True
        assert "error" not in out
        assert set(out["stages"]) == {"admission", "producer", "batching"}
        for st in out["stages"].values():
            assert st["depth"] == 0 and st["arrivals"] == 0
        assert out["lifecycle"]["records"] == []
        assert out["explain"]["bottleneck"] is None
        h = server.handle({"jsonrpc": "2.0", "id": 2,
                           "method": "ethrex_health", "params": []})
        cp = h["result"]["chainPath"]
        assert cp["bottleneck"] is None
        assert cp["blocksProduced"] == 0
        assert cp["backlogSeconds"] is None
        assert cp["producerStallSeconds"] is None
    finally:
        node.stop()


def test_inclusion_bench_wired_into_cli_and_gate():
    """--measure-inclusion must exist as a cli branch and the
    --check-regression suite must gate block_inclusion_tps (same-backend
    history comparison, higher is better)."""
    import inspect

    from ethrex_tpu.perf import bench_suite

    assert callable(bench_suite.measure_inclusion)
    assert "--measure-inclusion" in inspect.getsource(bench_suite.cli)
    src = inspect.getsource(bench_suite.check_regression_suite)
    assert "block_inclusion_tps" in src


def test_every_bench_config_emits_stages():
    """Every bench measurement must publish a non-empty per-stage
    breakdown: a wall-clock number without attribution cannot drive the
    ROADMAP speed items.  Statically require each measure_* function in
    the bench suite to build its JSON record with a "stages" key."""
    import ast
    import inspect

    from ethrex_tpu.perf import bench_suite

    tree = ast.parse(inspect.getsource(bench_suite))
    offenders = []
    for fn in tree.body:
        if not isinstance(fn, ast.FunctionDef):
            continue
        if not fn.name.startswith("measure"):
            continue
        has_stages = any(
            isinstance(node, ast.Dict) and any(
                isinstance(k, ast.Constant) and k.value == "stages"
                for k in node.keys)
            for node in ast.walk(fn))
        if not has_stages:
            offenders.append(fn.name)
    assert not offenders, \
        f"bench configs without a stages breakdown: {offenders}"


def test_scaling_bench_emits_autopsy_fields():
    """The scaling sweep is only useful if it stays self-explaining:
    statically require measure_scaling to build its record with the
    "scaling" and "autopsy" keys, and measure_scaling_one to emit the
    per-kernel "kernels" and "occupancy" fields explain_scaling
    consumes — dropping any of them silently re-opens the ROADMAP
    item-1 attribution gap this layer closed."""
    import ast
    import inspect

    from ethrex_tpu.perf import bench_suite

    tree = ast.parse(inspect.getsource(bench_suite))
    required = {"measure_scaling": {"scaling", "autopsy"},
                "measure_scaling_one": {"kernels", "occupancy"}}
    offenders = []
    for fn in tree.body:
        if not isinstance(fn, ast.FunctionDef) or fn.name not in required:
            continue
        keys = {k.value for node in ast.walk(fn)
                if isinstance(node, ast.Dict)
                for k in node.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)}
        missing = required.pop(fn.name) - keys
        if missing:
            offenders.append(f"{fn.name} missing {sorted(missing)}")
    offenders.extend(f"{name} not found" for name in required)
    assert not offenders, \
        f"scaling bench lost its autopsy fields: {offenders}"


def test_every_env_knob_is_documented():
    """Every ETHREX_* environment variable the code reads must appear in
    docs/*.md — an undocumented knob is one an operator cannot discover.
    A new env var lands with its documentation or not at all."""
    import pathlib
    import re

    import ethrex_tpu

    pkg = pathlib.Path(ethrex_tpu.__file__).parent
    repo = pkg.parent
    pat = re.compile(r"ETHREX_[A-Z0-9_]+")
    used = set()
    for path in sorted(pkg.rglob("*.py")) + [repo / "bench.py"]:
        if "__pycache__" in path.parts:
            continue
        used.update(pat.findall(path.read_text()))
    # cli.py builds names as f"ETHREX_{name}"; the prefix alone is not a knob
    used.discard("ETHREX_")
    documented = set()
    for path in sorted((repo / "docs").glob("*.md")):
        documented.update(pat.findall(path.read_text()))
    missing = sorted(used - documented)
    assert not missing, \
        f"env vars read by code but absent from docs/*.md: {missing}"


def test_async_front_door_never_blocks_the_loop():
    """rpc/server.py is event-loop code: every blocking primitive
    (time.sleep, socket recv/accept/sendall, socket file objects) must
    live behind the executor boundary (handlers run in _execute on the
    pool), never in the module itself — one blocking call on the loop
    stalls every connection at once."""
    import pathlib
    import re

    import ethrex_tpu

    src = (pathlib.Path(ethrex_tpu.__file__).parent / "rpc"
           / "server.py").read_text()
    banned = [r"time\.sleep\(", r"\.recv\(", r"\.accept\(",
              r"\.sendall\(", r"\.makefile\("]
    offenders = []
    for pat in banned:
        for m in re.finditer(pat, src):
            lineno = src.count("\n", 0, m.start()) + 1
            offenders.append(f"rpc/server.py:{lineno} {m.group(0)}")
    assert not offenders, \
        f"blocking calls in the asyncio server module: {offenders}"


def test_serving_knobs_have_cli_flags_with_help():
    """Each serving tuning knob lands as BOTH an env var and a CLI flag
    with real help text — an operator reading --help must be able to
    discover the knob (the docs guard above holds the docs side of the
    same contract)."""
    import ast
    import pathlib

    import ethrex_tpu

    src = (pathlib.Path(ethrex_tpu.__file__).parent
           / "cli.py").read_text()
    tree = ast.parse(src)
    flags = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument" and node.args
                and isinstance(node.args[0], ast.Constant)):
            continue
        helps = [k.value for k in node.keywords if k.arg == "help"]
        flags[node.args[0].value] = (
            helps[0].value if helps
            and isinstance(helps[0], ast.Constant) else None)
    for flag, env in [("--rpc-executor-workers", "RPC_EXECUTOR_WORKERS"),
                      ("--rpc-max-batch", "RPC_MAX_BATCH"),
                      ("--rpc-backlog", "RPC_BACKLOG")]:
        assert flag in flags, f"missing CLI flag {flag}"
        assert flags[flag], f"{flag} has no help text"
        assert f'_env_int("{env}"' in src, \
            f"{flag} lacks its ETHREX_{env} env mirror"


def test_stark_partition_specs_reference_mesh_axis():
    """Every PartitionSpec built under stark/ must name the mesh axis
    through parallel.mesh.AXIS (or be fully replicated) — a
    string-literal axis name silently diverges from the shared
    partitioning policy the moment the mesh axis is renamed."""
    import ast
    import pathlib

    import ethrex_tpu

    stark_dir = pathlib.Path(ethrex_tpu.__file__).parent / "stark"
    offenders = []
    for path in sorted(stark_dir.rglob("*.py")):
        tree = ast.parse(path.read_text())
        aliases = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and "sharding" in node.module:
                for a in node.names:
                    if a.name == "PartitionSpec":
                        aliases.add(a.asname or a.name)
        if not aliases:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name not in aliases:
                continue
            args = list(node.args) + [k.value for k in node.keywords]
            for arg in args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str):
                        offenders.append(f"{path.name}:{node.lineno}")
    assert not offenders, (
        "string-literal axis names in stark/ PartitionSpec calls "
        f"(use parallel.mesh.AXIS): {sorted(set(offenders))}")


def test_bench_check_regression_exit_codes(capsys):
    """The CI regression gate: ok and missing-baseline pass (0), a
    throughput drop past the threshold fails (2), a broken current
    measurement is its own error (1)."""
    import json as _json

    import bench

    def run(current, baseline, threshold=0.8):
        code = bench.check_regression(current, baseline, threshold)
        return code, _json.loads(capsys.readouterr().out.strip())

    code, out = run({"value": 10.0}, {"value": 10.0})
    assert (code, out["status"]) == (0, "ok")
    assert out["ratio"] == 1.0
    code, out = run({"value": 10.0}, {})
    assert (code, out["status"]) == (0, "no-baseline")
    code, out = run({"value": 5.0}, {"value": 10.0})
    assert (code, out["status"]) == (2, "regression")
    assert out["ratio"] == 0.5
    # just inside the threshold: not a regression
    code, out = run({"value": 8.5}, {"value": 10.0})
    assert (code, out["status"]) == (0, "ok")
    code, out = run({"value": None, "error": "probe failed"}, {"value": 10})
    assert (code, out["status"]) == (1, "error")
    assert out["detail"] == "probe failed"


def test_fault_rule_after_skips_leading_occasions():
    """after=N arms a rule only from the N+1th matching occasion — the
    handle the chaos battery uses to hit the response leg of a two-leg
    site like l1.commit."""
    from ethrex_tpu.utils.faults import FaultPlan, InjectedFault

    plan = FaultPlan(seed=0).drop("l1.commit", times=1, after=1)
    assert plan.fire("l1.commit") is None          # leg 1: skipped
    try:
        plan.fire("l1.commit")                     # leg 2: fires
        raise AssertionError("expected InjectedFault")
    except InjectedFault:
        pass
    assert plan.fire("l1.commit") is None          # budget exhausted
    assert plan.log == [("l1.commit", "drop")]
