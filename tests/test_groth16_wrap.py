"""Groth16 wrap circuit: MiMC digest binding round-trip + rejections.

Setup builds ~2.9k constraints' worth of fixed-base scalar muls once per
process (wrap_keys caches), so the three tests share it.
"""

import pytest

from ethrex_tpu.prover import groth16_wrap as gw

pytestmark = pytest.mark.slow  # full STARK compiles

DIGEST = [123456789, 2013265920, 0, 77, 31337, 2**31 - 1, 42, 999999999]


def test_wrap_roundtrip():
    wrapped = gw.wrap_prove(DIGEST, rnd=b"t")
    assert wrapped["hash"] == gw.wrap_hash(DIGEST)
    assert gw.wrap_verify(wrapped, DIGEST)
    # wire round-trip
    wire = gw.proof_to_json(wrapped)
    assert gw.wrap_verify(gw.proof_from_json(wire), DIGEST)


def test_wrap_rejects_wrong_digest():
    wrapped = gw.wrap_prove(DIGEST, rnd=b"t")
    other = list(DIGEST)
    other[0] += 1
    assert not gw.wrap_verify(wrapped, other)


def test_wrap_rejects_tampered_proof():
    wrapped = gw.wrap_prove(DIGEST, rnd=b"t")
    bad = {"hash": wrapped["hash"],
           "proof": dict(wrapped["proof"], a=gw.groth16.G1)}
    assert not gw.wrap_verify(bad, DIGEST)


def test_wrap_range_check_enforced():
    with pytest.raises(ValueError):
        gw.wrap_prove([1 << 31] + DIGEST[1:])
