"""State-update AIR: host tree agreement, constraint satisfaction on the
honest trace, tamper rejection, and a full prove/verify round-trip."""

import numpy as np
import pytest

from ethrex_tpu.models import state_update_air as sua
from ethrex_tpu.ops import babybear as bb
from ethrex_tpu.ops import ext
from ethrex_tpu.stark import state_tree
from ethrex_tpu.stark.air import HostExtOps

RNG = np.random.default_rng(7)


def _word(i: int) -> bytes:
    return bytes(RNG.integers(0, 256, 32, dtype=np.uint8))


def _setup(num_keys=5, num_writes=4, depth=3):
    entries = {_word(i): _word(i + 100) for i in range(num_keys)}
    tree = state_tree.TouchedStateTree(entries, depth)
    r_pre = tree.root
    keys = list(entries)
    accesses = []
    for w in range(num_writes):
        key = keys[int(RNG.integers(0, len(keys)))]
        accesses.append(tree.update(key, _word(w + 200)))
    return entries, tree, r_pre, accesses


def test_tree_update_records_consistent_paths():
    from ethrex_tpu.ops.merkle import fold_path_canonical

    entries, tree, r_pre, accesses = _setup()
    root = r_pre
    for rec in accesses:
        assert fold_path_canonical(
            rec.index, rec.old_leaf_digest(), rec.siblings) == root
        root = fold_path_canonical(
            rec.index, rec.new_leaf_digest(), rec.siblings)
    assert root == tree.root


def test_trace_satisfies_constraints_and_binds_outputs():
    entries, tree, r_pre, accesses = _setup(num_keys=4, num_writes=2,
                                            depth=2)
    depth, S = 2, 8
    air = sua.StateUpdateAir(depth, seg_periods=S)
    trace = sua.generate_state_update_trace(accesses, r_pre, depth, S)
    n = trace.shape[0]
    assert n == sua.segment_count(len(accesses)) * S * sua.PERIOD

    pub = sua.state_update_public_inputs(accesses, r_pre, tree.root, S)
    # boundary values actually appear in the trace
    for row, col, val in air.boundaries(pub, n):
        assert int(trace[row, col]) == val, (row, col)

    periodic_cols = air.periodic_columns(n)
    hops = HostExtOps()

    def cons_at(tr, r):
        local = [ext.h_from_base(int(v)) for v in tr[r]]
        nxt = [ext.h_from_base(int(v)) for v in tr[(r + 1) % n]]
        periodic = [ext.h_from_base(int(col[r % len(col)]))
                    for col in periodic_cols]
        return air.constraints(local, nxt, periodic, hops)

    for r in range(n - 1):
        cs = cons_at(trace, r)
        bad = [i for i, c in enumerate(cs) if c != ext.ZERO_H]
        assert not bad, f"row {r}: constraints {bad} nonzero"


def test_tampered_write_breaks_constraints():
    entries, tree, r_pre, accesses = _setup(num_keys=4, num_writes=2,
                                            depth=2)
    depth, S = 2, 8
    air = sua.StateUpdateAir(depth, seg_periods=S)
    trace = sua.generate_state_update_trace(accesses, r_pre, depth, S)
    n = trace.shape[0]
    periodic_cols = air.periodic_columns(n)
    hops = HostExtOps()

    def violated(tr):
        for r in range(n - 1):
            local = [ext.h_from_base(int(v)) for v in tr[r]]
            nxt = [ext.h_from_base(int(v)) for v in tr[r + 1]]
            periodic = [ext.h_from_base(int(col[r % len(col)]))
                        for col in periodic_cols]
            if any(c != ext.ZERO_H for c in
                   air.constraints(local, nxt, periodic, hops)):
                return True
        return False

    # flip one new-value msg limb in segment 0: the new-leaf sponge no
    # longer matches the absorbed limbs -> some constraint must break
    bad = trace.copy()
    seg = slice(0, S * sua.PERIOD)
    col = sua.MSG + 22
    bad[seg, col] = (bad[seg, col] + 1) % bb.P
    assert violated(bad)

    # tamper the root chain: bump cur_root in segment 1
    bad2 = trace.copy()
    seg1 = slice(S * sua.PERIOD, 2 * S * sua.PERIOD)
    bad2[seg1, sua.CUR_ROOT] = (bad2[seg1, sua.CUR_ROOT] + 1) % bb.P
    assert violated(bad2)


def test_prove_verify_roundtrip():
    from ethrex_tpu.stark import prover as stark_prover
    from ethrex_tpu.stark import verifier as stark_verifier
    from ethrex_tpu.stark.prover import StarkParams

    params = StarkParams(log_blowup=3, num_queries=25, log_final_size=4)
    entries, tree, r_pre, accesses = _setup(num_keys=4, num_writes=3,
                                            depth=2)
    depth, S = 2, 8
    air = sua.StateUpdateAir(depth, seg_periods=S)
    trace = sua.generate_state_update_trace(accesses, r_pre, depth, S)
    pub = sua.state_update_public_inputs(accesses, r_pre, tree.root, S)
    proof = stark_prover.prove(air, trace, pub, params)
    assert stark_verifier.verify(air, proof, params)

    # a different claimed final root must not verify
    bad_pub = list(pub)
    bad_pub[8] = (bad_pub[8] + 1) % bb.P
    bad = dict(proof)
    bad["pub_inputs"] = bad_pub
    with pytest.raises(stark_verifier.VerificationError):
        stark_verifier.verify(air, bad, params)
