"""BabyBear field ops vs host bignum reference."""

import numpy as np
import jax.numpy as jnp

from ethrex_tpu.ops import babybear as bb

RNG = np.random.default_rng(0)


def _rand(n):
    return RNG.integers(0, bb.P, size=n, dtype=np.uint32)


def test_mulhi():
    a = _rand(1000)
    b = _rand(1000)
    expect = ((a.astype(np.uint64) * b.astype(np.uint64)) >> 32).astype(np.uint32)
    got = np.asarray(bb.mulhi_u32(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_array_equal(got, expect)


def test_mont_roundtrip():
    a = _rand(1000)
    m = bb.to_mont(jnp.asarray(a))
    back = np.asarray(bb.from_mont(m))
    np.testing.assert_array_equal(back, a)


def test_mont_mul_matches_bignum():
    a = _rand(1000)
    b = _rand(1000)
    am = bb.to_mont(jnp.asarray(a))
    bm = bb.to_mont(jnp.asarray(b))
    got = np.asarray(bb.from_mont(bb.mont_mul(am, bm)))
    expect = ((a.astype(np.uint64) * b.astype(np.uint64)) % bb.P).astype(np.uint32)
    np.testing.assert_array_equal(got, expect)


def test_add_sub_neg():
    a = _rand(1000)
    b = _rand(1000)
    np.testing.assert_array_equal(
        np.asarray(bb.add(jnp.asarray(a), jnp.asarray(b))),
        ((a.astype(np.uint64) + b) % bb.P).astype(np.uint32),
    )
    np.testing.assert_array_equal(
        np.asarray(bb.sub(jnp.asarray(a), jnp.asarray(b))),
        ((a.astype(np.int64) - b + bb.P) % bb.P).astype(np.uint32),
    )
    np.testing.assert_array_equal(
        np.asarray(bb.neg(jnp.asarray(a))),
        ((bb.P - a.astype(np.int64)) % bb.P).astype(np.uint32),
    )


def test_pow_and_inv():
    a = _rand(64)
    am = bb.to_mont(jnp.asarray(a))
    got = np.asarray(bb.from_mont(bb.mont_pow(am, 12345)))
    expect = np.array([pow(int(x), 12345, bb.P) for x in a], dtype=np.uint32)
    np.testing.assert_array_equal(got, expect)

    nz = np.where(a == 0, 1, a).astype(np.uint32)
    nm = bb.to_mont(jnp.asarray(nz))
    inv = bb.from_mont(bb.mont_inv(nm))
    prod = np.asarray(
        bb.from_mont(bb.mont_mul(nm, bb.to_mont(jnp.asarray(inv))))
    )
    np.testing.assert_array_equal(prod, np.ones_like(prod))


def test_batch_inv():
    a = _rand(257)
    a = np.where(a == 0, 1, a).astype(np.uint32)
    am = bb.to_mont(jnp.asarray(a))
    inv = bb.batch_mont_inv(am)
    prod = np.asarray(bb.from_mont(bb.mont_mul(am, inv)))
    np.testing.assert_array_equal(prod, np.ones_like(prod))


def test_root_of_unity():
    for log_n in (1, 4, 10, 27):
        w = bb.root_of_unity(log_n)
        assert pow(w, 1 << log_n, bb.P) == 1
        assert pow(w, 1 << (log_n - 1), bb.P) != 1


def test_mod_matmul_montgomery():
    """MXU limb matmul vs uint64 numpy reference, Montgomery in/out."""
    a = _rand((5, 37, 64))
    b = _rand((64, 4))
    am = bb.to_mont(jnp.asarray(a))
    bm = bb.to_mont(jnp.asarray(b))
    got = np.asarray(bb.from_mont(bb.mod_matmul(am, bm)))
    expect = np.zeros((5, 37, 4), dtype=np.uint64)
    for k in range(64):
        expect = (expect + a[..., k, None].astype(np.uint64)
                  * b[k].astype(np.uint64)) % bb.P
    np.testing.assert_array_equal(got, expect.astype(np.uint32))


def test_mod_matmul_canonical_and_chunked():
    """k > 128 exercises the chunked contraction; canonical mode."""
    k = 1000
    a = _rand((3, k))
    b = _rand((k, 8))
    got = np.asarray(bb.mod_matmul(jnp.asarray(a), jnp.asarray(b),
                                   montgomery=False))
    expect = np.zeros((3, 8), dtype=np.uint64)
    for i in range(k):
        expect = (expect + a[:, i, None].astype(np.uint64)
                  * b[i].astype(np.uint64)) % bb.P
    np.testing.assert_array_equal(got, expect.astype(np.uint32))


def test_mod_matmul_flush_path():
    """k > 64*128 forces the int32 accumulator to flush mid-contraction."""
    k = 64 * 128 + 257
    a = _rand((2, k))
    b = _rand((k, 4))
    am = bb.to_mont(jnp.asarray(a))
    bm = bb.to_mont(jnp.asarray(b))
    got = np.asarray(bb.from_mont(bb.mod_matmul(am, bm)))
    expect = (a.astype(object) @ b.astype(object)) % bb.P
    np.testing.assert_array_equal(got, expect.astype(np.uint32))
