"""build_payload under load (ROADMAP item 3, chain-path X-ray): the
producer draining thousands of pending transactions from hundreds of
senders must respect the block gas limit, keep per-sender nonce order,
drain the pool across consecutive blocks, and attribute its wall to the
payload profiler stages (docs/PERFORMANCE.md stage-attribution tree)."""

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.perf import profiler
from ethrex_tpu.perf.chain_path import CHAIN_PATH
from ethrex_tpu.perf.loadgen import derive_secrets
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction

N_SENDERS = 250
TXS_PER_SENDER = 8
GAS_LIMIT = 30_000_000
TX_GAS = 21_000


def _genesis(addresses):
    return {
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + a.hex(): {"balance": hex(10**20)}
                  for a in addresses},
        "gasLimit": hex(GAS_LIMIT), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }


def test_payload_drains_thousands_of_txs_within_gas_limit():
    secrets = derive_secrets(N_SENDERS, 0)
    addresses = [secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(s)) for s in secrets]
    node = Node(Genesis.from_json(_genesis(addresses)))
    try:
        total = N_SENDERS * TXS_PER_SENDER
        for secret in secrets:
            for nonce in range(TXS_PER_SENDER):
                node.submit_transaction(Transaction(
                    tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
                    max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                    gas_limit=TX_GAS, to=bytes([0xBB]) * 20,
                    value=1).sign(secret))
        assert len(node.mempool) == total

        blocks = []
        while len(node.mempool):
            blocks.append(node.produce_block())
            assert len(blocks) < 10, "producer failed to drain the pool"

        # gas limit respected, and the first block is actually full —
        # the producer packs to capacity instead of trickling
        per_block = GAS_LIMIT // TX_GAS
        assert len(blocks[0].body.transactions) == per_block
        for blk in blocks:
            assert blk.header.gas_used <= GAS_LIMIT
            assert len(blk.body.transactions) <= per_block
        assert sum(len(b.body.transactions) for b in blocks) == total

        # per-sender nonce order is strictly increasing within each
        # block and across the block sequence
        last_nonce: dict[bytes, int] = {}
        for blk in blocks:
            for tx in blk.body.transactions:
                sender = tx.sender()
                prev = last_nonce.get(sender, -1)
                assert tx.nonce == prev + 1, \
                    f"nonce order broken for {sender.hex()[:8]}"
                last_nonce[sender] = tx.nonce
        assert all(n == TXS_PER_SENDER - 1 for n in last_nonce.values())

        # the build wall is attributed: every payload stage recorded,
        # and execute dominates a 1400-tx transfer block build
        stages = profiler.PROFILER.tree()["components"]["payload"]["stages"]
        assert {"drain", "select", "execute", "merkleize",
                "seal"} <= set(stages)
        assert stages["execute"]["count"] == len(blocks)
        assert stages["execute"]["totalSeconds"] > 0

        # the chain-path admission queue saw every tx in and out
        adm = CHAIN_PATH.queues["admission"]
        assert adm.arrivals == total
        assert adm.departures == total
        assert adm.depth == 0 and adm.drops == 0
    finally:
        node.stop()
