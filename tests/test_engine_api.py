"""Engine API: the consensus-layer driving loop (fcU with attributes ->
getPayload -> newPayload -> fcU), plus JWT auth — the reference's
engine/payload.rs + fork_choice.rs behavior over real HTTP."""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.rpc.engine import jwt_encode
from ethrex_tpu.rpc.server import RpcServer

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}
JWT_SECRET = os.urandom(32)


@pytest.fixture(scope="module")
def engine():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, port=0, jwt_secret=JWT_SECRET,
                       engine=True).start()
    url = f"http://127.0.0.1:{server.port}"

    def call(method, *params, token=None):
        payload = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                              "params": list(params)}).encode()
        headers = {"Content-Type": "application/json"}
        headers["Authorization"] = "Bearer " + (
            token if token is not None else jwt_encode(JWT_SECRET))
        req = urllib.request.Request(url, data=payload, headers=headers)
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    yield call, node
    server.stop()
    node.stop()


def test_jwt_rejected_without_token(engine):
    call, node = engine
    with pytest.raises(urllib.error.HTTPError) as e:
        call("eth_chainId", token="")
    assert e.value.code == 401
    with pytest.raises(urllib.error.HTTPError):
        call("eth_chainId", token=jwt_encode(b"wrong-secret"))
    # valid token passes
    assert call("eth_chainId")["result"] == "0x539"


def test_build_and_import_cycle(engine):
    call, node = engine
    caps = call("engine_exchangeCapabilities", [])["result"]
    assert "engine_newPayloadV3" in caps
    # submit a tx, then drive the CL loop
    tx = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=0,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21000, to=b"\xaa" * 20, value=999,
    ).sign(SECRET)
    node.submit_transaction(tx)
    head = "0x" + node.genesis_header.hash.hex()
    fcu = call("engine_forkchoiceUpdatedV3",
               {"headBlockHash": head, "safeBlockHash": head,
                "finalizedBlockHash": head},
               {"timestamp": hex(int(time.time()) + 12),
                "prevRandao": "0x" + "11" * 32,
                "suggestedFeeRecipient": "0x" + "ee" * 20,
                "withdrawals": [],
                "parentBeaconBlockRoot": "0x" + "00" * 32})["result"]
    assert fcu["payloadStatus"]["status"] == "VALID"
    pid = fcu["payloadId"]
    assert pid is not None
    got = call("engine_getPayloadV3", pid)["result"]
    payload = got["executionPayload"]
    assert len(payload["transactions"]) == 1
    assert int(got["blockValue"], 16) > 0
    # import the built payload through newPayload
    status = call("engine_newPayloadV3", payload, [],
                  "0x" + "00" * 32)["result"]
    assert status["status"] == "VALID", status
    # make it canonical
    fcu2 = call("engine_forkchoiceUpdatedV3",
                {"headBlockHash": payload["blockHash"],
                 "safeBlockHash": payload["blockHash"],
                 "finalizedBlockHash": payload["blockHash"]})["result"]
    assert fcu2["payloadStatus"]["status"] == "VALID"
    assert node.store.latest_number() == 1
    # duplicate newPayload is VALID (idempotent)
    again = call("engine_newPayloadV3", payload, [],
                 "0x" + "00" * 32)["result"]
    assert again["status"] == "VALID"


def test_get_payload_bodies(engine):
    call, node = engine
    if node.store.latest_number() == 0:
        # self-contained: mine one block so the test runs in isolation
        tx = Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=0,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=21000, to=b"\xaa" * 20, value=1).sign(SECRET)
        node.submit_transaction(tx)
        node.produce_block()
    head = node.store.latest_number()
    assert head >= 1
    bodies = call("engine_getPayloadBodiesByRangeV1",
                  "0x1", hex(head))["result"]
    assert len(bodies) == head
    assert any(b and b["transactions"] for b in bodies)
    bh = "0x" + node.store.canonical_hash(1).hex()
    by_hash = call("engine_getPayloadBodiesByHashV1",
                   [bh, "0x" + "77" * 32])["result"]
    assert by_hash[0] is not None and by_hash[1] is None
    err = call("engine_getPayloadBodiesByRangeV1", "0x0", "0x1")
    assert err["error"]["code"] == -32602
    err = call("engine_getPayloadBodiesByRangeV1", "0x1", hex(2000))
    assert err["error"]["code"] == -38004
    # no trailing nulls past the head
    over = call("engine_getPayloadBodiesByRangeV1", "0x1", "0x80")["result"]
    assert len(over) == head


def test_new_payload_rejects_bad_block(engine):
    call, node = engine
    head_hash = node.store.meta["head"]
    blk = node.store.get_block(head_hash)
    from ethrex_tpu.rpc.engine import block_to_payload
    payload = block_to_payload(blk)
    payload["stateRoot"] = "0x" + "42" * 32
    # recompute hash so it passes the hash check but fails validation
    status = call("engine_newPayloadV3", payload, [],
                  "0x" + "00" * 32)["result"]
    assert status["status"] == "INVALID"
    # unknown parent => SYNCING
    payload2 = dict(payload)
    payload2["parentHash"] = "0x" + "77" * 32
    status = call("engine_newPayloadV3", payload2, [],
                  "0x" + "00" * 32)["result"]
    assert status["status"] in ("SYNCING", "INVALID")


def test_legacy_versions_fork_gated(engine):
    """Engine API spec: each method version serves a bounded fork range and
    answers -38005 (unsupported fork) outside it (reference validates per
    version in engine/payload.rs / fork_choice.rs)."""
    call, node = engine
    genesis_hash = node.store.canonical_hash(0)
    # Cancun is active from t=0 here, so V1/V2 payloads are unsupported.
    payload = {"timestamp": "0x1", "parentHash": "0x" + "00" * 32}
    resp = call("engine_newPayloadV1", payload)
    assert resp["error"]["code"] == -38005
    resp = call("engine_newPayloadV2", payload)
    assert resp["error"]["code"] == -38005

    fcu = {"headBlockHash": "0x" + genesis_hash.hex(),
           "safeBlockHash": "0x" + "00" * 32,
           "finalizedBlockHash": "0x" + "00" * 32}
    attrs = {"timestamp": "0x1", "prevRandao": "0x" + "00" * 32,
             "suggestedFeeRecipient": "0x" + "00" * 20}
    resp = call("engine_forkchoiceUpdatedV2", fcu, attrs)
    assert resp["error"]["code"] == -38005
    # V3 attributes must carry parentBeaconBlockRoot
    resp = call("engine_forkchoiceUpdatedV3", fcu, attrs)
    assert resp["error"]["code"] == -32602


def test_attrs_error_does_not_roll_back_forkchoice(engine):
    """Spec: a payloadAttributes validation failure must not roll back the
    already-applied forkchoiceState update."""
    call, node = engine
    head_num = node.store.latest_number()
    head_hash = node.store.canonical_hash(head_num)
    fcu = {"headBlockHash": "0x" + head_hash.hex(),
           "safeBlockHash": "0x" + head_hash.hex(),
           "finalizedBlockHash": "0x" + head_hash.hex()}
    bad_attrs = {"timestamp": "0x1", "prevRandao": "0x" + "00" * 32,
                 "suggestedFeeRecipient": "0x" + "00" * 20}  # no beacon root
    resp = call("engine_forkchoiceUpdatedV3", fcu, bad_attrs)
    assert resp["error"]["code"] == -32602
    # the head/safe/finalized update stuck despite the attrs error
    assert node.store.meta["finalized"] == head_hash


def test_engine_reorg_sequence_competing_branches():
    """Two competing branches driven purely over engine_newPayloadV3 +
    engine_forkchoiceUpdatedV3 flips: the canonical index, the mempool
    and the tx-location lookups must agree after every flip, a rollback
    re-injects the orphaned tx, and a non-ancestor safe/finalized hash
    is rejected with the spec's invalidForkChoiceState (-38002)."""
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, port=0, jwt_secret=JWT_SECRET,
                       engine=True).start()
    url = f"http://127.0.0.1:{server.port}"

    def call(method, *params):
        payload = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                              "params": list(params)}).encode()
        req = urllib.request.Request(
            url, data=payload,
            headers={"Content-Type": "application/json",
                     "Authorization": "Bearer " + jwt_encode(JWT_SECRET)})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    zero = "0x" + "00" * 32

    def build_on(parent_hex, ts):
        fcu = call("engine_forkchoiceUpdatedV3",
                   {"headBlockHash": parent_hex, "safeBlockHash": zero,
                    "finalizedBlockHash": zero},
                   {"timestamp": hex(ts), "prevRandao": "0x" + "11" * 32,
                    "suggestedFeeRecipient": "0x" + "ee" * 20,
                    "withdrawals": [],
                    "parentBeaconBlockRoot": zero})["result"]
        assert fcu["payloadStatus"]["status"] == "VALID"
        payload = call("engine_getPayloadV3",
                       fcu["payloadId"])["result"]["executionPayload"]
        status = call("engine_newPayloadV3", payload, [], zero)["result"]
        assert status["status"] == "VALID", status
        return payload

    def fcu_head(block_hex):
        return call("engine_forkchoiceUpdatedV3",
                    {"headBlockHash": block_hex, "safeBlockHash": zero,
                     "finalizedBlockHash": zero})["result"]

    try:
        tx = Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=0,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=21000, to=b"\xbb" * 20, value=5).sign(SECRET)
        node.submit_transaction(tx)
        genesis_hex = "0x" + node.genesis_header.hash.hex()
        base_ts = int(time.time())

        # branch A: one payload on genesis carrying the tx
        pa = build_on(genesis_hex, base_ts + 12)
        assert fcu_head(pa["blockHash"])["payloadStatus"]["status"] \
            == "VALID"
        a_hash = bytes.fromhex(pa["blockHash"][2:])
        assert node.store.canonical_hash(1) == a_hash
        assert node.mempool.get_transaction(tx.hash) is None  # adopted
        assert node.store.canonical_tx_location(tx.hash) == (a_hash, 0)

        # rollback to genesis over the engine API: the tx comes back
        assert fcu_head(genesis_hex)["payloadStatus"]["status"] == "VALID"
        assert node.store.latest_number() == 0
        assert node.mempool.get_transaction(tx.hash) is not None
        assert node.store.canonical_tx_location(tx.hash) is None

        # branch B: a competing payload on genesis (later timestamp)
        # picks the re-injected tx up again
        pb = build_on(genesis_hex, base_ts + 24)
        b_hash = bytes.fromhex(pb["blockHash"][2:])
        assert b_hash != a_hash
        assert pb["transactions"], "re-injected tx missing from rebuild"
        assert fcu_head(pb["blockHash"])["payloadStatus"]["status"] \
            == "VALID"
        assert node.store.canonical_hash(1) == b_hash
        assert node.mempool.get_transaction(tx.hash) is None
        assert node.store.canonical_tx_location(tx.hash) == (b_hash, 0)

        # flip A -> B -> A: index, pool and txloc stay consistent
        for head_hex, expect in ((pa["blockHash"], a_hash),
                                 (pb["blockHash"], b_hash),
                                 (pa["blockHash"], a_hash)):
            assert fcu_head(head_hex)["payloadStatus"]["status"] == "VALID"
            assert node.store.canonical_hash(1) == expect
            assert node.store.head_header().hash == expect
            assert node.mempool.get_transaction(tx.hash) is None
            assert node.store.canonical_tx_location(tx.hash) == (expect, 0)

        # non-ancestor safe/finalized: invalidForkChoiceState (-38002)
        resp = call("engine_forkchoiceUpdatedV3",
                    {"headBlockHash": pa["blockHash"],
                     "safeBlockHash": pb["blockHash"],
                     "finalizedBlockHash": zero})
        assert resp["error"]["code"] == -38002
        resp = call("engine_forkchoiceUpdatedV3",
                    {"headBlockHash": pa["blockHash"],
                     "safeBlockHash": zero,
                     "finalizedBlockHash": pb["blockHash"]})
        assert resp["error"]["code"] == -38002
        # the failed updates did not move the head
        assert node.store.head_header().hash == a_hash
    finally:
        server.stop()
        node.stop()
