"""Fault-injection battery for the p2p request path and the
partition-tolerant multi-peer snap-sync (docs/P2P_RESILIENCE.md).

Sites drilled here: "net.send" (dropped/corrupted frames), "net.recv"
(slow/severed reader), "peer.request" (request dies before any bytes
move), "snap.serve" (byzantine snap server).  Unit drills for the
phi-accrual timeout estimator, the jittered backoff, and the persisted
ban list run on fake clocks and never sleep (the pattern from
tests/test_scheduler_chaos.py).

Select alone with `-m chaos`; only the full-stack soak is `slow`.
"""

import random
import threading
import time

import pytest

from ethrex_tpu.node import Node
from ethrex_tpu.p2p.connection import P2PServer, PeerError
from ethrex_tpu.p2p.failure import Backoff, BanList, PhiAccrualDetector
from ethrex_tpu.p2p.snap_sync import PeerPool, SnapSyncer
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.utils.faults import FaultPlan, injected
from ethrex_tpu.utils.metrics import METRICS

from tests.test_snap_sync import GENESIS, SECRET, _state_matches

pytestmark = pytest.mark.chaos


def _counter(name: str) -> float:
    with METRICS.lock:
        return METRICS.counters.get(name, 0.0)


def _gauge(name: str):
    with METRICS.lock:
        return METRICS.gauges.get(name)


def _chain(node: Node) -> Node:
    """The rich test chain with PINNED timestamps, so two independently
    built server nodes are byte-identical (same block hashes AND state
    roots) — interchangeable snap peers for one logical chain."""
    nonce = 0

    def send(to, value=0, data=b"", gas=300_000):
        nonlocal nonce
        node.submit_transaction(Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=gas, to=to, value=value, data=data).sign(SECRET))
        nonce += 1

    for i in range(40):
        send(bytes([0x50 + i]) * 20, value=1000 + i, gas=21000)
    node.produce_block(timestamp=1000)
    initcode = bytes.fromhex(
        "60075f55" "6008600155" "6009600255" "625b5b5b5f52" "6003601df3")
    send(b"", data=initcode)
    node.produce_block(timestamp=1001)
    return node


def _small_windows(monkeypatch):
    import ethrex_tpu.p2p.snap as snap_mod
    import ethrex_tpu.p2p.snap_sync as ss_mod
    monkeypatch.setattr(snap_mod, "MAX_RESPONSE_ITEMS", 16)
    monkeypatch.setattr(ss_mod, "MAX_RESPONSE_ITEMS", 16)


# ---------------------------------------------------------------------------
# unit drills: phi-accrual timeouts, backoff, ban list (fake clocks only)

def test_phi_detector_adapts_per_peer_timeouts():
    det = PhiAccrualDetector(ceiling=10.0)
    # cold peer: conservative ceiling until the window has data
    assert det.timeout_for("headers") == 10.0
    for _ in range(50):
        det.observe(0.02)
    # fast steady peer: timeout collapses to the class floor, far below
    # the ceiling — stalls are detected in fractions of a second
    assert det.timeout_for("headers") == 0.25
    assert det.timeout_for("ranges") == 0.75
    # suspicion is monotone in elapsed time
    assert det.phi_at(0.02) < det.phi_at(0.2) < det.phi_at(2.0)

    slow = PhiAccrualDetector(ceiling=10.0)
    for _ in range(50):
        slow.observe(2.0)
    # slow-but-alive peer: timeout sits above its typical RTT (no false
    # eviction) but still below the ceiling
    t = slow.timeout_for("headers")
    assert 2.0 < t <= 10.0


def test_backoff_is_bounded_jittered_and_deterministic():
    b = Backoff(base=0.05, cap=2.0, rng=random.Random(7))
    first = [b.delay(0) for _ in range(20)]
    assert all(0.025 <= d < 0.05 for d in first)      # base * [0.5, 1.0)
    late = [b.delay(10) for _ in range(20)]
    assert all(1.0 <= d <= 2.0 for d in late)         # capped
    b2 = Backoff(base=0.05, cap=2.0, rng=random.Random(7))
    assert [b2.delay(0) for _ in range(20)] == first  # replayable


def test_ban_list_persists_doubles_and_decays():
    node = Node(Genesis.from_json(GENESIS))
    now = {"t": 1000.0}
    clock = lambda: now["t"]  # noqa: E731 — fake clock, no sleeping
    bans = BanList(node.store, base_seconds=100.0, cap_seconds=1000.0,
                   clock=clock)
    nid = b"\x11" * 64
    assert bans.ban(nid, "tampered proof") == 100.0
    assert bans.is_banned(nid)
    # persisted: a fresh BanList over the same store (restart) agrees
    assert BanList(node.store, clock=clock).is_banned(nid)
    # repeat offence while banned doubles the duration
    assert bans.ban(nid, "again") == 200.0
    # decaying TTL: past expiry the entry prunes and the count resets
    now["t"] += 1e6
    assert not bans.is_banned(nid)
    assert bans.active() == {}
    assert bans.ban(nid, "later") == 100.0
    bans.unban(nid)
    assert not bans.is_banned(nid)
    # a torn/garbage blob resets to empty — never refuses to start
    node.store.meta["p2p_bans"] = b"\xff\xfe{{{garbage"
    assert not BanList(node.store, clock=clock).is_banned(nid)


# ---------------------------------------------------------------------------
# request resilience over a real RLPx pair

def _pair():
    a = Node(Genesis.from_json(GENESIS))
    b = Node(Genesis.from_json(GENESIS))
    srv_a = P2PServer(a, timeout=0.6, retries=2).start()
    srv_b = P2PServer(b).start()
    return a, b, srv_a, srv_b


def test_dropped_request_frames_are_retried():
    a, b, srv_a, srv_b = _pair()
    try:
        peer = srv_a.dial(srv_b.host, srv_b.port, srv_b.pub)
        peer.backoff = Backoff(base=0.001, cap=0.002)  # fast drills
        base = _counter("p2p_request_retries_total")
        # the request dies before any bytes move ("peer.request"), then
        # the frame itself is dropped mid-send ("net.send"): both are
        # transient — fresh request id, jittered backoff, same answer
        with injected(FaultPlan(seed=1).drop("peer.request", times=1)):
            headers = peer.get_block_headers(0, 1)
        assert headers and headers[0].number == 0
        with injected(FaultPlan(seed=2).drop("net.send", times=1)):
            headers = peer.get_block_headers(0, 1)
        assert headers and headers[0].number == 0
        assert _counter("p2p_request_retries_total") >= base + 2
    finally:
        srv_a.stop()
        srv_b.stop()


def test_stalled_response_times_out_penalizes_and_retries():
    a, b, srv_a, srv_b = _pair()
    try:
        peer = srv_a.dial(srv_b.host, srv_b.port, srv_b.pub)
        peer.backoff = Backoff(base=0.001, cap=0.002)
        base_t = _counter("p2p_request_timeouts_total")
        score0 = peer.score
        # the response stalls past the adaptive timeout ("net.recv"
        # delay > the 0.6s ceiling): the request times out — counted +
        # small transient penalty — the late answer is dropped by its
        # stale request id, and the fresh-id retry succeeds
        with injected(FaultPlan(seed=3).delay("net.recv", 1.0, times=1)):
            headers = peer.get_block_headers(0, 1)
        assert headers and headers[0].number == 0
        assert _counter("p2p_request_timeouts_total") >= base_t + 1
        assert peer.score < score0    # transient penalty, far from a ban
    finally:
        srv_a.stop()
        srv_b.stop()


def test_broadcast_send_failures_are_counted_and_penalized():
    a, b, srv_a, srv_b = _pair()
    try:
        peer = srv_a.dial(srv_b.host, srv_b.port, srv_b.pub)
        blk = a.produce_block(timestamp=1000)

        # sever the SEND path only: the reader thread stays parked on the
        # real socket, so the peer remains in srv_a.peers and the failure
        # surfaces in the broadcast fan-out, not as a vanished peer
        real_sock = peer.sock

        class _DeadSock:
            def sendall(self, *_a):
                raise OSError("severed transport")

            def shutdown(self, *_a):
                pass

            def close(self):
                pass

        peer.sock = _DeadSock()
        base = _counter("p2p_broadcast_failures_total")
        score0 = peer.score
        srv_a.broadcast_block(blk)
        deadline = time.monotonic() + 5.0
        while _counter("p2p_broadcast_failures_total") <= base:
            assert time.monotonic() < deadline, \
                "broadcast failure never surfaced in metrics"
            time.sleep(0.01)
        assert peer.score < score0
        real_sock.close()
    finally:
        srv_a.stop()
        srv_b.stop()


def test_score_eviction_bans_across_server_restart():
    a, b, srv_a, srv_b = _pair()
    try:
        peer = srv_a.dial(srv_b.host, srv_b.port, srv_b.pub)
        base_bans = _counter("p2p_peer_bans_total")
        # two misbehavior offences cross SCORE_DISCONNECT: evicted + banned
        peer.record_failure(peer.PENALTY_MISBEHAVIOR, reason="tampered")
        peer.record_failure(peer.PENALTY_MISBEHAVIOR, reason="tampered")
        assert _counter("p2p_peer_bans_total") >= base_bans + 1
        assert srv_a.bans.is_banned(peer.node_id())
        with pytest.raises(PeerError):
            srv_a.dial(srv_b.host, srv_b.port, srv_b.pub)
        # restart semantics: a FRESH P2PServer over the same store still
        # refuses the peer (the ban lives in store.meta["p2p_bans"])
        srv_a2 = P2PServer(a)
        try:
            assert srv_a2.bans.is_banned(peer.node_id())
            with pytest.raises(PeerError):
                srv_a2.dial(srv_b.host, srv_b.port, srv_b.pub)
        finally:
            srv_a2.stop()
    finally:
        srv_a.stop()
        srv_b.stop()


# ---------------------------------------------------------------------------
# multi-peer snap-sync drills

def test_snap_serve_corruption_fails_over_to_another_peer(monkeypatch):
    """A byzantine snap server ("snap.serve" corrupt: tampered response
    bytes) costs that peer a hard penalty and the lease moves to another
    peer — never an abort."""
    _small_windows(monkeypatch)
    server_a = _chain(Node(Genesis.from_json(GENESIS)))
    server_b = _chain(Node(Genesis.from_json(GENESIS)))
    client = Node(Genesis.from_json(GENESIS))
    srv_a = P2PServer(server_a).start()
    srv_b = P2PServer(server_b).start()
    srv_c = P2PServer(client, timeout=1.0, retries=1).start()
    try:
        p1 = srv_c.dial(srv_a.host, srv_a.port, srv_a.pub)
        p2 = srv_c.dial(srv_b.host, srv_b.port, srv_b.pub)
        for p in (p1, p2):
            p.backoff = Backoff(base=0.001, cap=0.01)
        pool = PeerPool(peers=[p1, p2], partition_timeout=10.0)
        syncer = SnapSyncer(client)
        with injected(FaultPlan(seed=4)
                      .corrupt("snap.serve", times=2)) as plan:
            summary = syncer.run(pool)
        assert summary["phase"] == "done"
        assert ("snap.serve", "corrupt") in plan.log
        root = server_a.store.head_header().state_root
        assert _state_matches(client, server_a, root) >= 42
        # somebody paid for the tampering (timeout or misbehavior)
        assert min(p1.score, p2.score) < 0
    finally:
        srv_a.stop()
        srv_b.stop()
        srv_c.stop()


def test_peer_death_mid_range_fails_over_to_live_peer(monkeypatch):
    """A peer dying mid-account-range is a transient lease failure: the
    segment re-leases to the surviving peer from its checkpointed cursor
    and the sync completes."""
    _small_windows(monkeypatch)
    server_a = _chain(Node(Genesis.from_json(GENESIS)))
    server_b = _chain(Node(Genesis.from_json(GENESIS)))
    client = Node(Genesis.from_json(GENESIS))
    srv_a = P2PServer(server_a).start()
    srv_b = P2PServer(server_b).start()
    srv_c = P2PServer(client, timeout=2.0, retries=1).start()
    try:
        p1 = srv_c.dial(srv_a.host, srv_a.port, srv_a.pub)
        p2 = srv_c.dial(srv_b.host, srv_b.port, srv_b.pub)
        for p in (p1, p2):
            p.backoff = Backoff(base=0.001, cap=0.01)

        class DieAfter:
            """Serves `budget` ranges, then severs its own connection —
            a peer crashing mid-lease, as the pool sees it."""

            def __init__(self, inner, budget):
                self.inner = inner
                self.budget = budget

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def snap_get_account_range(self, *a):
                if self.budget <= 0:
                    self.inner.close()
                self.budget -= 1
                return self.inner.snap_get_account_range(*a)

        # the dying peer outranks the survivor, so it provably holds
        # leases when it dies
        p2.score = -10
        pool = PeerPool(peers=[DieAfter(p1, budget=1), p2],
                        partition_timeout=10.0)
        summary = SnapSyncer(client).run(pool)
        assert summary["phase"] == "done"
        assert p1._stop.is_set()            # it really died
        root = server_a.store.head_header().state_root
        assert _state_matches(client, server_a, root) >= 42
    finally:
        srv_a.stop()
        srv_b.stop()
        srv_c.stop()


def test_chaos_sync_matches_faultless_baseline_and_bans_byzantine(
        monkeypatch):
    """The flagship drill: 1-of-3 peers byzantine (tampers every account
    range it serves) plus bounded net.send drops and net.recv delays.
    The sync must complete with state byte-identical to a fault-free
    baseline, the byzantine peer must end banned — persisted across a
    server restart — and nothing may leak."""
    import os
    baseline_threads = threading.active_count()
    baseline_fds = len(os.listdir("/proc/self/fd"))

    _small_windows(monkeypatch)
    server_a = _chain(Node(Genesis.from_json(GENESIS)))
    server_b = _chain(Node(Genesis.from_json(GENESIS)))
    byz_node = _chain(Node(Genesis.from_json(GENESIS)))
    # interchangeable peers: the pinned-timestamp chains are identical
    root = server_a.store.head_header().state_root
    assert server_b.store.head_header().state_root == root
    assert byz_node.store.head_header().state_root == root

    # fault-free baseline client
    base_client = Node(Genesis.from_json(GENESIS))
    srv_a = P2PServer(server_a).start()
    srv_b = P2PServer(server_b).start()
    srv_z = P2PServer(byz_node).start()
    srv_base = P2PServer(base_client).start()
    srv_c = None
    try:
        base_peer = srv_base.dial(srv_a.host, srv_a.port, srv_a.pub)
        assert SnapSyncer(base_client).run(base_peer)["phase"] == "done"
        baseline_count = _state_matches(base_client, server_a, root)

        # chaos client: 3 peers, one byzantine
        client = Node(Genesis.from_json(GENESIS))
        srv_c = P2PServer(client, timeout=1.5, retries=2).start()
        honest1 = srv_c.dial(srv_a.host, srv_a.port, srv_a.pub)
        honest2 = srv_c.dial(srv_b.host, srv_b.port, srv_b.pub)
        byz_inner = srv_c.dial(srv_z.host, srv_z.port, srv_z.pub)
        for p in (honest1, honest2, byz_inner):
            p.backoff = Backoff(base=0.001, cap=0.01)

        class Tamper:
            """Byzantine snap peer: returns ranges whose last account
            body is flipped — the range proof cannot cover them."""

            def __init__(self, inner):
                self.inner = inner

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def snap_get_account_range(self, *a):
                accounts, proof = self.inner.snap_get_account_range(*a)
                if accounts:
                    h, body = accounts[-1]
                    accounts = accounts[:-1] + [
                        (h, body[:-1] + bytes([body[-1] ^ 1]))]
                return accounts, proof

        byz = Tamper(byz_inner)
        # score the honest peers down so the pool provably leases the
        # byzantine one first AND again after its first -25 offence
        # (acquire prefers high scores; two offences cross the ban line)
        honest1.score = honest2.score = -30
        pool = PeerPool(peers=[honest1, honest2, byz],
                        partition_timeout=15.0)
        syncer = SnapSyncer(client)
        base_bans = _counter("p2p_peer_bans_total")
        plan = (FaultPlan(seed=5)
                .drop("net.send", times=1, after=2)
                .delay("net.recv", 0.003, times=30))
        with injected(plan):
            summary = syncer.run(pool)
        assert summary["phase"] == "done"

        # byte-identical outcome: every account/slot/code at the target
        # root matches the server — exactly what the baseline client got
        assert _state_matches(client, server_a, root) == baseline_count
        # the byzantine peer crossed SCORE_DISCONNECT and was banned...
        assert _counter("p2p_peer_bans_total") >= base_bans + 1
        nid = byz_inner.node_id()
        assert srv_c.bans.is_banned(nid)
        assert byz_inner._stop.is_set()        # and evicted (closed)
        # ...and the ban survives a restart (fresh server, same store)
        srv_c2 = P2PServer(client)
        try:
            with pytest.raises(PeerError):
                srv_c2.dial(srv_z.host, srv_z.port, srv_z.pub)
            # honest peers are NOT collateral damage
            extra = srv_c2.dial(srv_b.host, srv_b.port, srv_b.pub)
            assert extra.remote_status is not None
        finally:
            srv_c2.stop()
    finally:
        for s in (srv_a, srv_b, srv_z, srv_base, srv_c):
            if s is not None:
                s.stop()

    # zero leaked threads/sockets once everything is torn down
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if threading.active_count() <= baseline_threads + 2 and \
                len(os.listdir("/proc/self/fd")) <= baseline_fds + 8:
            break
        time.sleep(0.1)
    assert threading.active_count() <= baseline_threads + 2, \
        "drill leaked threads"
    assert len(os.listdir("/proc/self/fd")) <= baseline_fds + 8, \
        "drill leaked file descriptors"


def test_partition_pauses_then_resumes_after_rejoin(monkeypatch):
    """Total partition mid-sync: the pool pauses cleanly (gauge +
    counter), and when a peer rejoins the sync resumes from its
    checkpoint and completes."""
    _small_windows(monkeypatch)
    server = _chain(Node(Genesis.from_json(GENESIS)))
    client = Node(Genesis.from_json(GENESIS))
    srv_s = P2PServer(server).start()
    srv_c = P2PServer(client, timeout=1.0, retries=1).start()
    try:
        first = srv_c.dial(srv_s.host, srv_s.port, srv_s.pub)
        first.backoff = Backoff(base=0.001, cap=0.01)
        pool = PeerPool(provider=lambda: list(srv_c.peers),
                        partition_timeout=30.0)
        syncer = SnapSyncer(client)
        base_ranges = _counter("snap_ranges_synced_total")
        base_pauses = _counter("snap_partition_pauses_total")
        result = {}

        def run():
            try:
                result["summary"] = syncer.run(pool)
            except Exception as e:  # noqa: BLE001 — surfaced by asserts
                result["error"] = e

        # throttle each request so the partition window is reachable
        with injected(FaultPlan(seed=6).delay("peer.request", 0.03)):
            t = threading.Thread(target=run, daemon=True)
            t.start()
            deadline = time.monotonic() + 15.0
            while _counter("snap_ranges_synced_total") <= base_ranges:
                assert time.monotonic() < deadline, "no progress"
                time.sleep(0.005)
            # partition: every live peer dies
            for p in list(srv_c.peers):
                p.close()
            deadline = time.monotonic() + 15.0
            while _gauge("snap_sync_paused") != 1:
                assert time.monotonic() < deadline, \
                    f"pool never paused ({result.get('error')})"
                time.sleep(0.01)
            # rejoin: one peer comes back; the pool provider sees it
            srv_c.dial(srv_s.host, srv_s.port, srv_s.pub)
            t.join(60.0)
        assert not t.is_alive(), "sync wedged after rejoin"
        assert "error" not in result, result.get("error")
        assert result["summary"]["phase"] == "done"
        assert _counter("snap_partition_pauses_total") >= base_pauses + 1
        assert _gauge("snap_sync_paused") == 0
        root = server.store.head_header().state_root
        assert _state_matches(client, server, root) >= 42
    finally:
        srv_s.stop()
        srv_c.stop()


def test_kill_restart_at_every_checkpoint_refetches_at_most_one_range(
        monkeypatch):
    """Crash-only acceptance drill: kill the syncer after every single
    leased range, restart with a FRESH SnapSyncer (process-restart
    semantics) each time.  Total ranges fetched across all restarts must
    not exceed the uninterrupted baseline plus one per kill."""
    _small_windows(monkeypatch)
    server = _chain(Node(Genesis.from_json(GENESIS)))
    srv_s = P2PServer(server).start()
    base_client = Node(Genesis.from_json(GENESIS))
    srv_base = P2PServer(base_client).start()
    chaos_client = Node(Genesis.from_json(GENESIS))
    srv_c = P2PServer(chaos_client).start()
    try:
        # uninterrupted baseline: how many ranges one clean sync takes
        t0 = _counter("snap_ranges_synced_total")
        peer0 = srv_base.dial(srv_s.host, srv_s.port, srv_s.pub)
        assert SnapSyncer(base_client).run(peer0)["phase"] == "done"
        baseline_ranges = _counter("snap_ranges_synced_total") - t0
        assert baseline_ranges >= 3, "windows too big for the drill"

        class KillAfter:
            """Serves `budget` account ranges, then dies (client-side
            process-kill stand-in; the transport stays healthy)."""

            def __init__(self, inner, budget):
                self.inner = inner
                self.budget = budget

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def snap_get_account_range(self, *a):
                if self.budget <= 0:
                    raise RuntimeError("killed at checkpoint")
                self.budget -= 1
                return self.inner.snap_get_account_range(*a)

        peer = srv_c.dial(srv_s.host, srv_s.port, srv_s.pub)
        t1 = _counter("snap_ranges_synced_total")
        kills = 0
        summary = None
        for _ in range(int(baseline_ranges) * 3 + 10):
            syncer = SnapSyncer(chaos_client)     # fresh process each time
            try:
                summary = syncer.run(KillAfter(peer, budget=1))
                break
            except RuntimeError:
                kills += 1
                assert chaos_client.store.meta.get("snap_sync"), \
                    "checkpoint must survive the kill"
        assert summary is not None and summary["phase"] == "done"
        assert kills >= 2, "the drill never actually killed mid-sync"
        chaos_ranges = _counter("snap_ranges_synced_total") - t1
        assert chaos_ranges <= baseline_ranges + kills, \
            (f"kill-restart re-fetched too much: {chaos_ranges} ranges "
             f"vs baseline {baseline_ranges} + {kills} kills")
        root = server.store.head_header().state_root
        assert _state_matches(chaos_client, server, root) >= 42
    finally:
        srv_s.stop()
        srv_base.stop()
        srv_c.stop()


def test_torn_checkpoint_blob_falls_back_to_fresh_sync():
    """A torn/garbage store.meta["snap_sync"] blob must produce a fresh
    sync (counted + logged), never a crashed loader."""
    client = Node(Genesis.from_json(GENESIS))
    base = _counter("snap_progress_resets_total")
    client.store.meta["snap_sync"] = b"\xff\xfe\x00{torn-mid-write"
    syncer = SnapSyncer(client)
    assert syncer.progress["phase"] == "accounts"
    assert syncer.progress["pivot_root"] is None
    assert _counter("snap_progress_resets_total") == base + 1
    # valid JSON that is not a progress object is equally garbage
    client.store.meta["snap_sync"] = '["not", "a", "progress", "dict"]'
    assert SnapSyncer(client).progress["pivot_root"] is None
    assert _counter("snap_progress_resets_total") == base + 2

    # and the fresh sync actually completes end to end
    server = _chain(Node(Genesis.from_json(GENESIS)))
    srv_s = P2PServer(server).start()
    srv_c = P2PServer(client).start()
    try:
        peer = srv_c.dial(srv_s.host, srv_s.port, srv_s.pub)
        assert SnapSyncer(client).run(peer)["phase"] == "done"
        root = server.store.head_header().state_root
        assert _state_matches(client, server, root) >= 42
    finally:
        srv_s.stop()
        srv_c.stop()


# ---------------------------------------------------------------------------
# full-stack soak: snap-sync under faults while the node serves RPC load

@pytest.mark.slow
def test_p2p_soak_sync_under_faults_while_serving_rpc(monkeypatch,
                                                      tmp_path):
    import os

    import numpy as np

    from ethrex_tpu.l2.l1_client import InMemoryL1
    from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
    from ethrex_tpu.models import merkle_air as mair
    from ethrex_tpu.ops import babybear as bb
    from ethrex_tpu.ops.merkle import fold_path_canonical
    from ethrex_tpu.perf.loadgen import Harness
    from ethrex_tpu.prover import protocol
    from ethrex_tpu.prover import runtime_errors as rt
    from ethrex_tpu.prover.client import ProverClient
    from ethrex_tpu.rpc.server import RpcServer
    from ethrex_tpu.stark import prover as stark_prover
    from ethrex_tpu.stark.prover import StarkParams
    from ethrex_tpu.utils.tracing import TRACER, critical_path

    baseline_threads = threading.active_count()
    baseline_fds = len(os.listdir("/proc/self/fd"))
    _small_windows(monkeypatch)
    monkeypatch.setenv("ETHREX_PROOF_CKPT_DIR", str(tmp_path / "ckpt"))
    server_a = _chain(Node(Genesis.from_json(GENESIS)))
    server_b = _chain(Node(Genesis.from_json(GENESIS)))
    client = Node(Genesis.from_json(GENESIS))
    srv_a = P2PServer(server_a).start()
    srv_b = P2PServer(server_b).start()
    srv_c = P2PServer(client, timeout=2.0, retries=3).start()
    rpc = RpcServer(client, port=0).start()

    # a live prover fleet rides along: one committed L2 batch, one
    # prover whose backend runs a real (small) STARK prove under phase
    # checkpoints — a mid-prove preemption must recover by RESUMING,
    # and the merged batch trace must attribute the recovery
    l2_node = Node(Genesis.from_json(GENESIS))
    seq = Sequencer(l2_node, InMemoryL1([protocol.PROVER_TPU]),
                    SequencerConfig(
                        needed_prover_types=(protocol.PROVER_TPU,),
                        prover_lease_timeout=0.3))
    seq.coordinator.verify_submissions = False   # stub STARK payload
    seq.coordinator.start()
    l2_node.submit_transaction(Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=0,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21000, to=bytes([0x77]) * 20, value=5).sign(SECRET))
    seq.produce_block()
    assert seq.commit_next_batch() is not None

    rng = np.random.default_rng(23)
    depth = 3
    leaf = [int(v) for v in rng.integers(0, bb.P, 8)]
    siblings = [[int(v) for v in rng.integers(0, bb.P, 8)]
                for _ in range(depth)]
    index = int(rng.integers(0, 1 << depth))
    bits = [(index >> j) & 1 for j in range(depth)]
    root2 = fold_path_canonical(index, leaf, siblings)
    air = mair.Poseidon2MerkleAir(depth)
    mtrace = mair.generate_merkle_trace(leaf, siblings, bits)
    mpub = mair.merkle_public_inputs(leaf, root2)
    sparams = StarkParams(log_blowup=3, num_queries=12, log_final_size=4)

    class CkptStarkBackend:
        """A prover whose device work is the real phase-checkpointed
        STARK pipeline (the L2 plumbing around it is stubbed)."""

        prover_type = protocol.PROVER_TPU

        def prove(self, program_input, proof_format):
            stark = stark_prover.prove(air, mtrace, mpub, sparams)
            return {"backend": protocol.PROVER_TPU,
                    "stark": {"fri_roots": len(stark["fri"]["roots"])},
                    "output": "0x" + "00" * 176}

    resumes_before = rt.STATS["phase_resumes"]
    prover_done = {}

    def run_prover():
        try:
            pc = ProverClient(CkptStarkBackend(),
                              [("127.0.0.1", seq.coordinator.port)],
                              heartbeat_interval=0.1, backoff_base=0.01,
                              rng_seed=9)
            deadline = time.time() + 90.0
            while time.time() < deadline and \
                    seq.rollup.get_proof(1, protocol.PROVER_TPU) is None:
                pc.poll_once()
                time.sleep(0.05)
            prover_done["proved"] = seq.rollup.get_proof(
                1, protocol.PROVER_TPU) is not None
        except Exception as e:  # noqa: BLE001 — surfaced by asserts
            prover_done["error"] = e

    try:
        p1 = srv_c.dial(srv_a.host, srv_a.port, srv_a.pub)
        p2 = srv_c.dial(srv_b.host, srv_b.port, srv_b.pub)
        for p in (p1, p2):
            p.backoff = Backoff(base=0.001, cap=0.02)
        pool = PeerPool(provider=lambda: list(srv_c.peers),
                        partition_timeout=15.0)
        syncer = SnapSyncer(client)
        result = {}

        def run_sync():
            try:
                result["summary"] = syncer.run(pool)
            except Exception as e:  # noqa: BLE001 — surfaced by asserts
                result["error"] = e

        # the extra "backend.phase" drop (p=1, its own budget) preempts
        # the prover at its first phase boundary without disturbing the
        # seeded p2p schedule (p<1 rules alone consume RNG draws)
        plan = (FaultPlan(seed=11)
                .delay("net.recv", 0.002, p=0.3)
                .drop("peer.request", p=0.1, times=5)
                .drop("net.send", times=2, after=4)
                .corrupt("snap.serve", times=1, after=2)
                .drop("backend.phase", times=1))
        with injected(plan):
            t = threading.Thread(target=run_sync, daemon=True)
            t.start()
            tp = threading.Thread(target=run_prover, daemon=True)
            tp.start()
            # the front door keeps answering while the sync churns
            harness = Harness(f"http://127.0.0.1:{rpc.port}",
                              payload="ping", workers=4, timeout=5.0)
            rep = harness.run(20.0, duration=2.0)
            t.join(120.0)
            tp.join(120.0)
        assert not t.is_alive(), "soak sync wedged"
        assert "error" not in result, result.get("error")
        assert result["summary"]["phase"] == "done"
        root = server_a.store.head_header().state_root
        assert _state_matches(client, server_a, root) >= 42
        assert rep["delivered"] > 0
        assert rep["errors"] == 0, "RPC served errors during the soak"
        # the preempted prover recovered by RESUMING, not re-proving
        assert not tp.is_alive(), "soak prover wedged"
        assert "error" not in prover_done, prover_done.get("error")
        assert prover_done.get("proved"), "batch never proven in soak"
        assert rt.STATS["phase_resumes"] > resumes_before
        # ...and the merged batch trace attributes the recovery: the
        # resumed phases' spans carry resumed=True under the batch's
        # one trace
        tid = seq.coordinator.batch_traces.get(1)
        assert tid is not None
        trace = TRACER.get_trace(tid)
        spans = trace["spans"]
        assert any(s.get("attrs", {}).get("resumed") for s in spans), \
            "no resumed-phase spans in the merged batch trace"
        # ...and the attribution still adds up: every second of the
        # batch's wall belongs to exactly one component
        cp = critical_path(trace)
        assert cp["spanCount"] > 0
        assert abs(sum(cp["components"].values()) -
                   cp["wallSeconds"]) < 1e-6
    finally:
        seq.stop()
        rpc.stop()
        srv_a.stop()
        srv_b.stop()
        srv_c.stop()

    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if threading.active_count() <= baseline_threads + 2 and \
                len(os.listdir("/proc/self/fd")) <= baseline_fds + 8:
            break
        time.sleep(0.1)
    assert threading.active_count() <= baseline_threads + 2, \
        "soak leaked threads"
    assert len(os.listdir("/proc/self/fd")) <= baseline_fds + 8, \
        "soak leaked file descriptors"
