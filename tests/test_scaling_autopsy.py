"""Mesh-scaling autopsy battery (PR 18, docs/PERFORMANCE.md "Reading
the scaling autopsy"): HLO collective accounting (perf/hlo_introspect),
device-occupancy timelines (perf/occupancy + the parallel prover
wiring), the explain_scaling 1-vs-N diff, and every surface the autopsy
flows through — gauges, ethrex_perf/ethrex_health stubs, the monitor
panel, the Perfetto device-lane view, and the occupancy/collective
alert pair.

The degradation drills matter as much as the goldens: every hook rides
the AOT-compile and prove hot paths, so a jaxlib that reshapes
memory_analysis() or an opaque executable must degrade to partial rows,
never a failed prove (never-raise contract)."""

import pytest

from ethrex_tpu.perf import hlo_introspect, occupancy
from ethrex_tpu.perf.roofline import _parse_cost
from ethrex_tpu.utils.metrics import METRICS


@pytest.fixture(autouse=True)
def _fresh_registries():
    hlo_introspect.REGISTRY.reset()
    occupancy.REGISTRY.reset()
    yield
    hlo_introspect.REGISTRY.reset()
    occupancy.REGISTRY.reset()


# ---------------------------------------------------------------------------
# roofline._parse_cost: newer jaxlib shapes (satellite)


class _AttrCost:
    """Newer jaxlib AOT surfaces report cost via properties, not dict
    keys."""

    def __init__(self, flops=None, bytes_accessed=None):
        if flops is not None:
            self.flops = flops
        if bytes_accessed is not None:
            self.bytes_accessed = bytes_accessed


def test_parse_cost_tolerates_attribute_objects():
    out = _parse_cost(_AttrCost(flops=2.0e6, bytes_accessed=4.0e3))
    assert out == {"flops": 2.0e6, "bytes": 4.0e3}
    # list-of-objects sums like list-of-dicts
    out = _parse_cost([_AttrCost(flops=1.0), _AttrCost(flops=2.0)])
    assert out == {"flops": 3.0, "bytes": None}
    # mixed dict + object entries in one list
    out = _parse_cost([{"flops": 1.0}, _AttrCost(bytes_accessed=8.0)])
    assert out == {"flops": 1.0, "bytes": 8.0}


def test_parse_cost_degrades_to_partial_rows():
    # absent fields -> None, not zero and not an exception
    assert _parse_cost(_AttrCost()) == {"flops": None, "bytes": None}
    assert _parse_cost(None) == {"flops": None, "bytes": None}
    assert _parse_cost([None, 3, "junk"]) == {"flops": None, "bytes": None}

    # a raising property degrades to a partial row: flops absent,
    # bytes still read
    class Bomb:
        @property
        def flops(self):
            raise RuntimeError("no cost model")
        bytes_accessed = 16.0

    assert _parse_cost(Bomb()) == {"flops": None, "bytes": 16.0}
    # negative and boolean values are rejected
    assert _parse_cost({"flops": -5}) == {"flops": None, "bytes": None}
    assert _parse_cost({"flops": True}) == {"flops": None, "bytes": None}


def test_parse_cost_method_style_accessors():
    class MethodCost:
        def flops(self):
            return 7.0

        def bytes_accessed(self):
            return 3.0

    assert _parse_cost(MethodCost()) == {"flops": 7.0, "bytes": 3.0}

    class MethodBomb:
        def flops(self):
            raise RuntimeError("boom")

    assert _parse_cost(MethodBomb()) == {"flops": None, "bytes": None}


# ---------------------------------------------------------------------------
# hlo_introspect: memory_analysis shapes


class _AttrMem:
    argument_size_in_bytes = 1000
    output_size_in_bytes = 200
    temp_size_in_bytes = 30
    alias_size_in_bytes = 4
    generated_code_size_in_bytes = 99


def test_parse_memory_analysis_tolerates_every_shape():
    full = hlo_introspect.parse_memory_analysis(_AttrMem())
    assert full["argBytes"] == 1000.0
    assert full["peakBytes"] == 1234.0
    assert full["codeBytes"] == 99.0

    as_dict = hlo_introspect.parse_memory_analysis(
        {"argument_size_in_bytes": 10, "temp_size_in_bytes": 5})
    assert as_dict["argBytes"] == 10.0
    assert as_dict["outputBytes"] is None
    assert as_dict["peakBytes"] == 15.0

    listed = hlo_introspect.parse_memory_analysis([_AttrMem(), _AttrMem()])
    assert listed["peakBytes"] == 2468.0

    empty = hlo_introspect.parse_memory_analysis(None)
    assert empty["peakBytes"] is None
    assert hlo_introspect.parse_memory_analysis(object())["peakBytes"] \
        is None
    assert hlo_introspect.parse_memory_analysis([None, "x"])["peakBytes"] \
        is None


# ---------------------------------------------------------------------------
# hlo_introspect: collective counting golden

_HLO = """\
HloModule prove_step, entry_computation_layout={...}

ENTRY %main (p0: u32[64,512]) -> u32[64,512] {
  %p0 = u32[64,512]{1,0} parameter(0)
  %ag-start = u32[64,4096]{1,0} all-gather-start(%p0), dimensions={1}
  %ag-done = u32[64,4096]{1,0} all-gather-done(%ag-start)
  %ar = u32[64,512]{1,0} all-reduce(%p0), to_apply=%add
  %cp = u32[64,512]{1,0} collective-permute(%ar), source_target_pairs={{0,1}}
  %copy.1 = u32[64,512]{1,0} copy(%cp)
  %small = bf16[8]{0} all-reduce(%junk), to_apply=%add
  ROOT %out = u32[64,512]{1,0} copy(%copy.1)
}
"""


def test_count_collectives_golden():
    ops = hlo_introspect.count_collectives(_HLO)
    # async pair counts ONCE, on the -start leg
    assert ops["all-gather"]["count"] == 1
    assert ops["all-gather"]["bytes"] == 64 * 4096 * 4
    assert ops["all-reduce"]["count"] == 2
    assert ops["all-reduce"]["bytes"] == 64 * 512 * 4 + 8 * 2
    assert ops["collective-permute"]["count"] == 1
    assert ops["copy"]["count"] == 2
    assert ops["reduce-scatter"]["count"] == 0
    # non-string input degrades to a zero table
    zeros = hlo_introspect.count_collectives(None)
    assert all(v == {"count": 0, "bytes": 0} for v in zeros.values())


def test_introspect_rolls_up_cross_device_bytes():
    class Fake:
        def as_text(self):
            return _HLO

        def memory_analysis(self):
            return _AttrMem()

    row = hlo_introspect.introspect(Fake())
    assert row["collectiveOps"] == 4           # copies NOT included
    assert row["copyOps"] == 2
    expected = (64 * 4096 * 4) + (64 * 512 * 4 + 8 * 2) + (64 * 512 * 4)
    assert row["crossDeviceBytes"] == expected
    assert row["memory"]["peakBytes"] == 1234.0


def test_registry_records_real_compiled_program():
    """End-to-end on a real jax AOT executable: whatever this jaxlib
    returns for as_text/memory_analysis must land as a row, not an
    exception (the stark _aot_phases hook path)."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    compiled = jax.jit(lambda x: x * 2 + 1).lower(
        jnp.arange(16, dtype=jnp.uint32)).compile()
    hlo_introspect.record("TestAir", "commit", compiled, devices=1)
    rep = hlo_introspect.REGISTRY.report()
    rows = {(k["air"], k["kernel"]): k for k in rep["kernels"]}
    assert ("TestAir", "commit") in rows
    assert rows[("TestAir", "commit")]["devices"] == 1
    # gauges rendered with help text
    text = METRICS.render()
    assert "# HELP prover_kernel_collective_ops" in text


def test_record_never_raises_on_opaque_executables():
    hlo_introspect.record("A", "k", object(), devices=3)
    hlo_introspect.record("A", "k2", None, devices="garbage")
    rep = hlo_introspect.REGISTRY.report()
    rows = {(k["air"], k["kernel"]) for k in rep["kernels"]}
    assert ("A", "k") in rows  # zero-row, but present


def test_collective_share_gauge_and_ici_override(monkeypatch):
    class Fake:
        def as_text(self):
            return _HLO

        def memory_analysis(self):
            return None

    monkeypatch.setenv("ETHREX_ICI_GBPS", "1e-3")  # 1 MB/s: huge share
    hlo_introspect.record("ShareAir", "quotient", Fake(), devices=8)
    hlo_introspect.record_collective_share("ShareAir", "quotient", 0.5)
    with METRICS.lock:
        share = METRICS.gauges.get("prover_collective_wall_share")
    assert share == 1.0  # clamped
    # unknown kernel / zero wall are silent no-ops
    hlo_introspect.record_collective_share("NoSuch", "open", 1.0)
    hlo_introspect.record_collective_share("ShareAir", "quotient", 0.0)


# ---------------------------------------------------------------------------
# occupancy math units (satellite)


def test_merge_intervals_collapses_overlap():
    merged = occupancy.merge_intervals(
        [(3.0, 4.0), (0.0, 1.0), (0.5, 2.0), (1.9, 2.5), ("x", 1), (5, 5)])
    assert merged == [(0.0, 2.5), (3.0, 4.0)]
    assert occupancy.busy_seconds([(0, 1), (0.5, 2)]) == pytest.approx(2.0)
    assert occupancy.merge_intervals(None) == []


def test_occupancy_two_lane_prove():
    lanes = {
        "0": {"intervals": [(0.0, 4.0), (5.0, 8.0)], "devices": 2},
        "1": {"intervals": [(0.0, 3.0)], "devices": 2},
    }
    rep = occupancy.compute(lanes, devices=4)
    assert rep["wallSeconds"] == pytest.approx(8.0)
    # busy-device-seconds: lane0 7s*2dev + lane1 3s*2dev = 20
    assert rep["busyDeviceSeconds"] == pytest.approx(20.0)
    assert rep["occupancy"] == pytest.approx(20.0 / 32.0)
    # the (4, 5) bubble is the only span with no lane busy
    assert rep["idleGapSeconds"] == pytest.approx(1.0)
    assert rep["idleGapCount"] == 1
    # per-lane busy+idle sums to the measured wall within 5%
    # (exactly, by construction)
    for lane in rep["lanes"]:
        total = lane["busySeconds"] + lane["idleSeconds"]
        assert abs(total - rep["wallSeconds"]) \
            <= 0.05 * max(rep["wallSeconds"], 1e-9)


def test_occupancy_serial_fallback_is_one_over_ndev():
    # a serial prove on an 8-device mesh: one weight-1 lane busy the
    # whole wall -> occupancy exactly 1/8
    rep = occupancy.compute({"0": [(0.0, 10.0)]}, devices=8)
    assert rep["occupancy"] == pytest.approx(1.0 / 8.0)
    # and a fully-busy single-device prove is 1.0, clamped never above
    rep1 = occupancy.compute(
        {"0": {"intervals": [(0.0, 10.0)], "devices": 1}}, devices=1)
    assert rep1["occupancy"] == pytest.approx(1.0)


def test_occupancy_empty_and_window():
    rep = occupancy.compute({}, devices=4)
    assert rep["occupancy"] == 0.0 and rep["wallSeconds"] == 0.0
    # an explicit window clips intervals outside it
    rep = occupancy.compute({"0": [(0.0, 10.0)]}, devices=1,
                            window=(2.0, 6.0))
    assert rep["wallSeconds"] == pytest.approx(4.0)
    assert rep["occupancy"] == pytest.approx(1.0)


def test_record_prove_feeds_registry_and_gauges():
    occupancy.record_prove({"0": [(0.0, 1.0)], "1": [(0.5, 2.0)]},
                           devices=2)
    rep = occupancy.REGISTRY.report()
    assert rep["provesRecorded"] == 1
    assert rep["lastProve"]["devices"] == 2
    assert rep["worstOccupancy"] == rep["lastProve"]["occupancy"]
    with METRICS.lock:
        assert METRICS.gauges.get("prover_device_occupancy") \
            == pytest.approx(rep["lastProve"]["occupancy"])
    # garbage lanes are swallowed (never-raise hook)
    occupancy.record_prove(object(), devices=None)


def test_run_proof_jobs_serial_path_records_occupancy():
    """The real wiring: mesh-less `_run_proof_jobs` runs jobs serially
    and must still land a single-lane occupancy record whose busy+idle
    matches the wall."""
    from ethrex_tpu.prover.tpu_backend import _run_proof_jobs

    def mk(tag):
        return lambda job_mesh: {"proof": tag}

    out = _run_proof_jobs(
        [("stateAir", "state", mk("s")),
         ("vm0", "vm_circuits", mk("v0")),
         ("vm1", "vm_circuits", mk("v1"))], None)
    assert out == {"stateAir": {"proof": "s"}, "vm0": {"proof": "v0"},
                   "vm1": {"proof": "v1"}}
    rep = occupancy.REGISTRY.report()
    assert rep["provesRecorded"] == 1
    last = rep["lastProve"]
    assert last["devices"] == 1
    (lane,) = last["lanes"]
    assert abs(lane["busySeconds"] + lane["idleSeconds"]
               - last["wallSeconds"]) \
        <= 0.05 * max(last["wallSeconds"], 1e-9)


# ---------------------------------------------------------------------------
# explain_scaling golden (satellite): planted dominant regressor


def _child(ndev, value, kernels, occ_fraction):
    return {"value": value, "devices": ndev, "kernels": kernels,
            "occupancy": {"fraction": occ_fraction, "devices": ndev}}


def test_explain_scaling_names_planted_collective_regressor():
    from ethrex_tpu.perf.bench_suite import explain_scaling

    base = _child(1, 192_000.0, {
        "commit": {"wall_s": 0.10, "compile_s": 20.0,
                   "collective_ops": 0, "collective_bytes": 0},
        "quotient": {"wall_s": 0.50, "compile_s": 30.0,
                     "collective_ops": 0, "collective_bytes": 0},
    }, 0.95)
    # 8 devices: quotient wall +38%, delta 0.19s, and the planted
    # all-gather traffic accounts for ~92% of it at 10 GB/s
    tgt = _child(8, 124_000.0, {
        "commit": {"wall_s": 0.11, "compile_s": 80.0,
                   "collective_ops": 2, "collective_bytes": int(1e8)},
        "quotient": {"wall_s": 0.69, "compile_s": 123.0,
                     "collective_ops": 9,
                     "collective_bytes": int(1.75e9)},
    }, 0.90)
    autopsy = explain_scaling({"1": base, "8": tgt}, ici_gbps=10.0)
    assert autopsy["baselineDevices"] == 1
    assert autopsy["targetDevices"] == 8
    dom = autopsy["dominant"]
    assert dom["kernel"] == "quotient"
    assert dom["regressor"] == "collectives"
    q = autopsy["kernels"]["quotient"]
    assert q["wallDeltaPct"] == pytest.approx(38.0)
    assert q["collectiveShareOfDelta"] == pytest.approx(0.921, abs=0.01)
    assert q["compileRatio"] == pytest.approx(4.1)
    assert "% of delta is collective bytes" in q["summary"]
    assert "compile x4.1" in q["summary"]
    assert autopsy["headline"]["targetOverBaseline"] \
        == pytest.approx(124_000.0 / 192_000.0, abs=1e-3)


def test_explain_scaling_degrades_without_kernel_data():
    from ethrex_tpu.perf.bench_suite import explain_scaling

    # pre-autopsy children (or failed children) -> an error stub, and
    # junk keys/records are skipped, never raised on
    out = explain_scaling({"1": {"value": 1.0}, "8": {"error": "boom"},
                           "x": None})
    assert out["error"].startswith("need kernel data")
    assert explain_scaling(None)["error"]


def test_explain_scaling_idle_regressor_and_no_regression():
    from ethrex_tpu.perf.bench_suite import explain_scaling

    k1 = {"commit": {"wall_s": 1.0, "compile_s": 1.0,
                     "collective_ops": 0, "collective_bytes": 0}}
    k8 = {"commit": {"wall_s": 1.4, "compile_s": 1.0,
                     "collective_ops": 0, "collective_bytes": 0}}
    out = explain_scaling({"1": _child(1, 10.0, k1, 0.95),
                           "8": _child(8, 5.0, k8, 0.2)}, ici_gbps=10.0)
    assert out["dominant"]["regressor"] == "idle"
    assert out["occupancy"]["drop"] == pytest.approx(0.75)
    # faster at 8 devices: nothing regressed, dominant says so
    out = explain_scaling({"1": _child(1, 10.0, k8, 0.9),
                           "8": _child(8, 20.0, k1, 0.9)}, ici_gbps=10.0)
    assert out["dominant"]["regressor"] == "none"


# ---------------------------------------------------------------------------
# surfaces: RPC stubs, monitor panel, Perfetto lanes, alerts, snapshot


def _l1_node():
    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis

    sender = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(0xA11CE))
    return Node(Genesis.from_json({
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + sender.hex(): {"balance": hex(10**21)}},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }))


def test_perf_rpc_collectives_and_occupancy_stubs_on_l1_node():
    from ethrex_tpu.rpc.server import RpcServer

    server = RpcServer(_l1_node())
    perf = server.handle({"jsonrpc": "2.0", "id": 1,
                          "method": "ethrex_perf", "params": []})["result"]
    # pre-autopsy / L1-only: well-formed empty stubs, never missing keys
    assert perf["collectives"]["kernels"] == []
    assert perf["collectives"]["iciGbpsAssumed"] > 0
    assert perf["occupancy"] == {"provesRecorded": 0, "lastProve": None,
                                 "worstOccupancy": None}
    health = server.handle({"jsonrpc": "2.0", "id": 2,
                            "method": "ethrex_health",
                            "params": []})["result"]
    assert health["perf"]["kernelsIntrospected"] == 0
    assert health["perf"]["collectiveOpsTotal"] == 0
    assert health["perf"]["deviceOccupancy"] is None


def test_perf_rpc_carries_autopsy_rows_once_populated():
    from ethrex_tpu.rpc.server import RpcServer

    class Fake:
        def as_text(self):
            return _HLO

        def memory_analysis(self):
            return _AttrMem()

    hlo_introspect.record("FibonacciAir", "quotient", Fake(), devices=8)
    occupancy.record_prove({"0": [(0.0, 1.0)]}, devices=8)
    server = RpcServer(_l1_node())
    perf = server.handle({"jsonrpc": "2.0", "id": 1,
                          "method": "ethrex_perf", "params": []})["result"]
    (row,) = perf["collectives"]["kernels"]
    assert row["air"] == "FibonacciAir" and row["devices"] == 8
    assert row["collectiveOps"] == 4
    assert perf["occupancy"]["provesRecorded"] == 1
    health = server.handle({"jsonrpc": "2.0", "id": 2,
                            "method": "ethrex_health",
                            "params": []})["result"]
    assert health["perf"]["kernelsIntrospected"] == 1
    assert health["perf"]["deviceOccupancy"] \
        == pytest.approx(1.0 / 8.0)


def test_monitor_panel_renders_autopsy_and_degrades():
    from ethrex_tpu.utils.monitor import _perf_lines

    snap = {"perf": {
        "enabled": True,
        "throughput": {"l1_import_mgas_per_sec": 12.5,
                       "prover_trace_cells_per_sec": 3.1e6,
                       "proofs_per_hour": None},
        "collectives": {"kernels": [
            {"air": "FibonacciAir", "kernel": "quotient", "devices": 8,
             "collectiveOps": 9, "crossDeviceBytes": 1.75e9,
             "copyOps": 3}]},
        "occupancy": {"provesRecorded": 2, "lastProve": {
            "occupancy": 0.41, "devices": 8, "idleGapSeconds": 1.25,
            "lanes": [{"lane": "0", "devices": 4, "busySeconds": 3.0,
                       "idleSeconds": 1.0},
                      {"lane": "1", "devices": 4, "busySeconds": 2.0,
                       "idleSeconds": 2.0}]}},
    }}
    text = "\n".join(_perf_lines(snap, 100))
    assert "collectives" in text
    assert "quotient" in text and "1.75e+09" in text
    assert "occupancy   41% of 8 devices" in text
    assert "lane 0" in text and "busy" in text
    # degraded sections (error stubs / None / wrong types) never raise
    for coll, occ in (({"error": "x"}, {"error": "y"}),
                      (None, None), ([], "junk"),
                      ({"kernels": "?"}, {"lastProve": "?"})):
        lines = _perf_lines({"perf": {"enabled": True,
                                      "collectives": coll,
                                      "occupancy": occ}}, 100)
        assert isinstance(lines, list)


def test_trace_events_render_device_lanes():
    from ethrex_tpu.utils.tracing import to_trace_events

    trace = {"traceId": "ab" * 8, "spans": [
        {"spanId": "s1", "name": "prove", "start": 0.0, "seconds": 4.0},
        {"spanId": "s2", "parentId": "s1", "name": "prove.vm0",
         "start": 0.5, "seconds": 1.5,
         "attrs": {"deviceLane": 0, "laneDevices": 2}},
        {"spanId": "s3", "parentId": "s1", "name": "prove.vm1",
         "start": 0.5, "seconds": 2.0,
         "attrs": {"deviceLane": 1, "laneDevices": 2}},
    ]}
    out = to_trace_events(trace)
    xs = {e["name"]: e for e in out["traceEvents"] if e["ph"] == "X"}
    assert xs["prove"]["tid"] == 1           # un-laned spans stay put
    assert xs["prove.vm0"]["tid"] == 2
    assert xs["prove.vm1"]["tid"] == 3
    lane_names = {e["args"]["name"] for e in out["traceEvents"]
                  if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "device-lane 0 (2 dev)" in lane_names
    assert "device-lane 1 (2 dev)" in lane_names
    # malformed lane attrs degrade to the default track, never raise
    bad = to_trace_events({"traceId": "cd" * 8, "spans": [
        {"spanId": "b", "name": "x", "start": 0.0, "seconds": 1.0,
         "attrs": {"deviceLane": "zero"}}]})
    (ev,) = [e for e in bad["traceEvents"] if e["ph"] == "X"]
    assert ev["tid"] == 1


def test_default_rules_include_autopsy_pair():
    from ethrex_tpu.utils.alerts import default_rules

    by_name = {r.name: r for r in default_rules(None)}
    occ_rule = by_name["prover_occupancy_floor:warn"]
    assert occ_rule.below is True and occ_rule.severity == "warn"
    assert occ_rule.threshold == pytest.approx(0.5)
    share_rule = by_name["prover_collective_share:warn"]
    assert share_rule.below is False and share_rule.severity == "warn"
    assert share_rule.threshold == pytest.approx(0.4)


def test_snapshot_perf_section_carries_autopsy():
    from ethrex_tpu.utils import snapshot

    occupancy.record_prove({"0": [(0.0, 1.0)]}, devices=2)
    bundle = snapshot.collect(None, reason="test")
    perf = bundle["perf"]
    assert "collectives" in perf and "occupancy" in perf
    assert perf["occupancy"]["provesRecorded"] == 1
