"""Differential opcode fuzzer: random straight-line programs over the
arithmetic/bitwise/comparison opcode family, executed by the EVM and by an
independent big-int reference evaluator written directly from the yellow-
paper semantics.  Stand-in for the EF state fixtures (not shipped in this
image) in the spirit of the reference's revm differential rerun
(tooling/ef_tests/state/README.md)."""

import numpy as np

from tests.test_evm import _call, _state

RNG = np.random.default_rng(1234)
M = 1 << 256
SIGN = 1 << 255


def _sx(v):
    """unsigned 256-bit -> signed"""
    return v - M if v & SIGN else v


def _ux(v):
    return v % M


def _byte(i, x):
    return (x >> (8 * (31 - i))) & 0xFF if i < 32 else 0


def _sar(shift, x):
    s = _sx(x)
    if shift >= 256:
        return _ux(-1) if s < 0 else 0
    return _ux(s >> shift)


def _signextend(b, x):
    if b >= 31:
        return x
    bit = 8 * (b + 1) - 1
    if x & (1 << bit):
        return _ux(x | (M - (1 << (bit + 1))))
    return x & ((1 << (bit + 1)) - 1)


# opcode -> (byte, arity, reference fn)
OPS = {
    "ADD": (0x01, 2, lambda a, b: _ux(a + b)),
    "MUL": (0x02, 2, lambda a, b: _ux(a * b)),
    "SUB": (0x03, 2, lambda a, b: _ux(a - b)),
    "DIV": (0x04, 2, lambda a, b: a // b if b else 0),
    "SDIV": (0x05, 2, lambda a, b: _ux(
        0 if _sx(b) == 0 else
        abs(_sx(a)) // abs(_sx(b)) * (1 if (_sx(a) < 0) == (_sx(b) < 0)
                                      else -1))),
    "MOD": (0x06, 2, lambda a, b: a % b if b else 0),
    "SMOD": (0x07, 2, lambda a, b: _ux(
        0 if _sx(b) == 0 else
        abs(_sx(a)) % abs(_sx(b)) * (1 if _sx(a) >= 0 else -1))),
    "ADDMOD": (0x08, 3, lambda a, b, n: (a + b) % n if n else 0),
    "MULMOD": (0x09, 3, lambda a, b, n: (a * b) % n if n else 0),
    "EXP": (0x0A, 2, lambda a, b: pow(a, b, M)),
    "SIGNEXTEND": (0x0B, 2, lambda b, x: _signextend(b, x)
                   if b < 32 else x),
    "LT": (0x10, 2, lambda a, b: int(a < b)),
    "GT": (0x11, 2, lambda a, b: int(a > b)),
    "SLT": (0x12, 2, lambda a, b: int(_sx(a) < _sx(b))),
    "SGT": (0x13, 2, lambda a, b: int(_sx(a) > _sx(b))),
    "EQ": (0x14, 2, lambda a, b: int(a == b)),
    "ISZERO": (0x15, 1, lambda a: int(a == 0)),
    "AND": (0x16, 2, lambda a, b: a & b),
    "OR": (0x17, 2, lambda a, b: a | b),
    "XOR": (0x18, 2, lambda a, b: a ^ b),
    "NOT": (0x19, 1, lambda a: a ^ (M - 1)),
    "BYTE": (0x1A, 2, lambda i, x: _byte(i, x)),
    "SHL": (0x1B, 2, lambda s, x: _ux(x << s) if s < 256 else 0),
    "SHR": (0x1C, 2, lambda s, x: x >> s if s < 256 else 0),
    "SAR": (0x1D, 2, _sar),
}
NAMES = list(OPS)


def _interesting_word():
    kind = RNG.integers(0, 6)
    if kind == 0:
        return int(RNG.integers(0, 256))          # tiny (shift counts)
    if kind == 1:
        return int(RNG.integers(0, 1 << 16))
    if kind == 2:
        return M - 1 - int(RNG.integers(0, 3))    # near -1
    if kind == 3:
        return SIGN - int(RNG.integers(0, 2))     # sign boundary
    if kind == 4:
        return (1 << int(RNG.integers(1, 256))) - int(RNG.integers(0, 2))
    return int.from_bytes(RNG.integers(0, 256, 32, dtype=np.uint8)
                          .tobytes(), "big")


def _push(v):
    if v == 0:
        return bytes([0x5F])                       # PUSH0
    raw = v.to_bytes((v.bit_length() + 7) // 8, "big")
    return bytes([0x5F + len(raw)]) + raw


def _gen_program(n_ops):
    """Random program; returns (code, reference stack evaluator result)."""
    code = bytearray()
    stack = []
    for _ in range(n_ops):
        # keep at least 3 words available; push with some probability
        if len(stack) < 3 or RNG.random() < 0.35:
            v = _interesting_word()
            code += _push(v)
            stack.append(v)
            continue
        name = NAMES[int(RNG.integers(0, len(NAMES)))]
        op, arity, fn = OPS[name]
        if len(stack) < arity:
            continue
        args = [stack.pop() for _ in range(arity)]
        code.append(op)
        stack.append(_ux(int(fn(*args))))
    # XOR-fold the stack so every produced word matters
    while len(stack) > 1:
        code.append(0x18)
        a, b = stack.pop(), stack.pop()
        stack.append(a ^ b)
    # MSTORE(0, result); RETURN(0, 32)
    code += bytes.fromhex("5f52" + "60205ff3")
    return bytes(code), stack[0]


def test_differential_random_programs():
    mismatches = []
    for trial in range(300):
        n_ops = int(RNG.integers(4, 40))
        code, expected = _gen_program(n_ops)
        ok, _, out = _call(_state(bytes(code)), gas=5_000_000)
        if not ok:
            mismatches.append((trial, code.hex(), "execution failed"))
            continue
        got = int.from_bytes(out, "big")
        if got != expected:
            mismatches.append((trial, code.hex(),
                               f"got {got:#x} want {expected:#x}"))
    assert not mismatches, mismatches[:3]


def test_differential_exp_edges():
    """EXP with large exponents (gas-heavy, run fewer)."""
    for _ in range(40):
        base = _interesting_word()
        exp = _interesting_word()
        code = _push(exp) + _push(base) + bytes([0x0A]) \
            + bytes.fromhex("5f5260205ff3")
        ok, _, out = _call(_state(bytes(code)), gas=10_000_000)
        assert ok
        assert int.from_bytes(out, "big") == pow(base, exp, M), \
            (hex(base), hex(exp))
