"""Real L1 settlement seam: the hand-assembled bridge contract driven
over HTTP JSON-RPC by the retrying/gas-bumping EthClient, and the full L2
sequencer pipeline settling against it (parity:
crates/l2/contracts/src/l1/OnChainProposer.sol + CommonBridge.sol and
the EthClient tx path, l1_committer.rs:42)."""

import time

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.l2.eth_client import EthClient, RpcError
from ethrex_tpu.l2.l1_client import L1Error
from ethrex_tpu.l2.l1_contract import RpcL1Client, bridge_runtime
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.prover import protocol
from ethrex_tpu.rpc.server import RpcServer

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

L1_GENESIS = {
    "config": {"chainId": 1, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


@pytest.fixture()
def l1():
    node = Node(Genesis.from_json(L1_GENESIS))
    node.start_dev_producer(0.05)
    srv = RpcServer(node, "127.0.0.1", 0).start()
    client = EthClient(f"http://127.0.0.1:{srv.port}", timeout=5.0)
    try:
        yield node, srv, client
    finally:
        srv.stop()
        node.stop()


def test_bridge_contract_rules_on_chain(l1):
    node, srv, client = l1
    bridge = RpcL1Client.deploy(client, SECRET, [protocol.PROVER_EXEC])
    assert bridge.last_committed_batch() == 0
    assert bridge.last_verified_batch() == 0

    # out-of-order commit reverts ON CHAIN
    with pytest.raises(L1Error):
        bridge.commit_batch(2, b"\x00" * 32, b"\x11" * 32)
    bridge.commit_batch(1, b"\xaa" * 32, b"\xc1" * 32)
    assert bridge.last_committed_batch() == 1
    # the commitment word is readable back
    assert bridge._view(b"\x08" + (1).to_bytes(32, "big"))[-32:] \
        == b"\xc1" * 32
    # verifying past the committed head reverts
    with pytest.raises(L1Error):
        bridge._tx(b"\x02" + (1).to_bytes(32, "big")
                   + (2).to_bytes(32, "big"))

    # deposits queue on-chain with value
    bridge.deposit(b"\x77" * 20, 12345)
    bridge.deposit(b"\x88" * 20, 67890)
    deps = bridge.get_deposits(0)
    assert [(d.recipient, d.amount, d.index) for d in deps] == [
        (b"\x77" * 20, 12345, 0), (b"\x88" * 20, 67890, 1)]
    assert bridge.get_deposits(1)[0].index == 1


def test_eth_client_gas_bump_on_underpriced(l1):
    node, srv, client = l1

    class Fussy(EthClient):
        """Rejects the first two submissions as underpriced."""

        def __init__(self, url):
            super().__init__(url, timeout=5.0)
            self.rejections = 0
            self.fees_seen = []

        def call(self, method, params):
            if method == "eth_sendRawTransaction" and self.rejections < 2:
                self.rejections += 1
                raise RpcError(-32000, "transaction underpriced")
            return super().call(method, params)

    fussy = Fussy(client.url)
    rec = fussy.send_tx_bump_gas_exponential_backoff(
        SECRET, to=b"\x99" * 20, value=5)
    assert int(rec["status"], 16) == 1
    assert fussy.rejections == 2  # two bumps happened before acceptance


def test_l2_pipeline_settles_on_rpc_l1(l1):
    from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
    from ethrex_tpu.prover.client import ProverClient
    from tests.test_l2_pipeline import GENESIS as L2_GENESIS
    from tests.test_l2_pipeline import _transfer

    node, srv, client = l1
    bridge = RpcL1Client.deploy(client, SECRET, [protocol.PROVER_EXEC],
                                l2_chain_id=65536999)
    l2_node = Node(Genesis.from_json(L2_GENESIS))
    cfg = SequencerConfig(needed_prover_types=(protocol.PROVER_EXEC,))
    seq = Sequencer(l2_node, bridge, cfg)
    seq.coordinator.start()
    try:
        # a deposit on the real L1 flows into an L2 privileged tx
        bridge.deposit(b"\x55" * 20, 777_000)
        seq.watch_l1()
        l2_node.submit_transaction(_transfer(0))
        seq.produce_block()
        batch = seq.commit_next_batch()
        assert batch is not None
        assert bridge.last_committed_batch() == 1

        prover = ProverClient(protocol.PROVER_EXEC,
                              [("127.0.0.1", seq.coordinator.port)])
        assert prover.poll_once() == 1
        assert seq.send_proofs() == (1, 1)
        assert bridge.last_verified_batch() == 1
        # the deposit minted on L2
        state = l2_node.store.account_state(
            l2_node.store.head_header().state_root, b"\x55" * 20)
        assert state is not None and state.balance == 777_000
    finally:
        seq.stop()
        l2_node.stop()


def test_runtime_assembles():
    code = bridge_runtime()
    assert len(code) < 512
    assert code[-1] == 0xFD  # trailing revert
