"""WebSocket JSON-RPC transport + eth_subscribe push subscriptions
(reference: rpc subscription_manager over websockets)."""

import base64
import hashlib
import json
import os
import socket
import time

import pytest

from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.rpc.server import RpcServer
from ethrex_tpu.rpc.websocket import (WsServer, _accept_key, make_frame,
                                      read_frame, OP_TEXT)

from tests.test_l2_pipeline import GENESIS, SENDER, _transfer


class WsClient:
    """Minimal masked-frame client for tests."""

    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port))
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall(
            f"GET / HTTP/1.1\r\nHost: {host}\r\nUpgrade: websocket\r\n"
            f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
            f"Sec-WebSocket-Version: 13\r\n\r\n".encode())
        resp = b""
        while b"\r\n\r\n" not in resp:
            resp += self.sock.recv(4096)
        assert b"101" in resp.split(b"\r\n")[0]
        assert _accept_key(key).encode() in resp

    def send(self, obj):
        import struct

        payload = json.dumps(obj).encode()
        mask = os.urandom(4)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        n = len(payload)
        if n < 126:
            header = bytes([0x80 | OP_TEXT, 0x80 | n])
        else:
            header = bytes([0x80 | OP_TEXT, 0x80 | 126]) \
                + struct.pack(">H", n)
        self.sock.sendall(header + mask + masked)

    def recv(self, timeout=10.0):
        self.sock.settimeout(timeout)
        _op, payload = read_frame(self.sock)
        return json.loads(payload)

    def close(self):
        self.sock.close()


@pytest.fixture()
def ws_setup():
    node = Node(Genesis.from_json(GENESIS))
    rpc = RpcServer(node, port=0)
    ws = WsServer(rpc).start()
    client = WsClient("127.0.0.1", ws.port)
    yield node, ws, client
    client.close()
    ws.stop()


def test_ws_plain_rpc_roundtrip(ws_setup):
    node, ws, client = ws_setup
    client.send({"jsonrpc": "2.0", "id": 1, "method": "eth_chainId",
                 "params": []})
    resp = client.recv()
    assert resp["result"] == hex(node.config.chain_id)


def test_ws_newheads_and_pending_subscriptions(ws_setup):
    node, ws, client = ws_setup
    client.send({"jsonrpc": "2.0", "id": 1, "method": "eth_subscribe",
                 "params": ["newHeads"]})
    heads_sid = client.recv()["result"]
    client.send({"jsonrpc": "2.0", "id": 2, "method": "eth_subscribe",
                 "params": ["newPendingTransactions"]})
    pending_sid = client.recv()["result"]

    tx = _transfer(0)
    node.submit_transaction(tx)
    note = client.recv()
    assert note["method"] == "eth_subscription"
    assert note["params"]["subscription"] == pending_sid
    assert note["params"]["result"] == "0x" + tx.hash.hex()

    block = node.produce_block()
    note = client.recv()
    assert note["params"]["subscription"] == heads_sid
    assert note["params"]["result"]["hash"] == "0x" + block.hash.hex()

    # unsubscribe stops the pushes
    client.send({"jsonrpc": "2.0", "id": 3, "method": "eth_unsubscribe",
                 "params": [heads_sid]})
    assert client.recv()["result"] is True


def test_ws_logs_subscription_filters(ws_setup):
    node, ws, client = ws_setup
    # contract emitting LOG1(topic=0x42...) on any call
    from ethrex_tpu.evm.db import InMemorySource  # noqa: F401 (docs)

    client.send({"jsonrpc": "2.0", "id": 1, "method": "eth_subscribe",
                 "params": ["logs", {"address": "0x" + "bb" * 20}]})
    sid = client.recv()["result"]
    # a plain transfer produces no logs -> no notification
    node.submit_transaction(_transfer(0))
    node.produce_block()
    client.sock.settimeout(0.5)
    with pytest.raises((TimeoutError, socket.timeout)):
        read_frame(client.sock)


def test_ws_rejects_unmasked_client_frame(ws_setup):
    """RFC 6455 §5.1: server must fail the connection on unmasked frames."""
    import struct

    _node, _ws, client = ws_setup
    payload = json.dumps({"jsonrpc": "2.0", "id": 1,
                          "method": "eth_chainId", "params": []}).encode()
    client.sock.sendall(make_frame(OP_TEXT, payload))  # unmasked
    client.sock.settimeout(5.0)
    op, body = read_frame(client.sock)
    assert op == 0x8  # close
    (code,) = struct.unpack(">H", body[:2])
    assert code == 1002


def test_ws_fanout_under_concurrent_load():
    """Subscription fan-out under load: N concurrent subscribers over
    real TCP all receive every newHeads push while submitter threads
    hammer the mempool, and both the per-connection counters and the
    global ws traffic counters account for exactly that fan-out."""
    import threading

    from ethrex_tpu.utils.metrics import METRICS

    node = Node(Genesis.from_json(GENESIS))
    rpc = RpcServer(node, port=0)
    ws = WsServer(rpc).start()
    n_subs, n_blocks = 6, 3
    before = METRICS.snapshot()["counters"]
    clients = [WsClient("127.0.0.1", ws.port) for _ in range(n_subs)]
    try:
        for i, client in enumerate(clients):
            client.send({"jsonrpc": "2.0", "id": i,
                         "method": "eth_subscribe",
                         "params": ["newHeads"]})
            assert client.recv()["result"].startswith("0x")
        assert METRICS.snapshot()["gauges"]["ws_connections"] == n_subs

        # concurrent load: submitter threads race block production
        def submit(base):
            for j in range(4):
                try:
                    node.submit_transaction(_transfer(base + j))
                except Exception:
                    pass   # nonce races are fine; load is the point

        threads = [threading.Thread(target=submit, args=(k * 4,))
                   for k in range(3)]
        for t in threads:
            t.start()
        blocks = [node.produce_block() for _ in range(n_blocks)]
        for t in threads:
            t.join()

        # every subscriber sees every head, in order
        for client in clients:
            hashes = [client.recv()["params"]["result"]["hash"]
                      for _ in range(n_blocks)]
            assert hashes == ["0x" + b.hash.hex() for b in blocks]
        for conn in ws.connections:
            assert conn.notifications_sent == n_blocks
            assert conn.send_failures == 0
    finally:
        for client in clients:
            client.close()
        ws.stop()
        node.stop()
    after = METRICS.snapshot()["counters"]

    def delta(name):
        return after.get(name, 0) - before.get(name, 0)

    assert delta("ws_connections_accepted_total") == n_subs
    assert delta("ws_notifications_total") == n_subs * n_blocks
    assert delta("ws_send_failures_total") == 0


def test_ws_rejects_oversized_message(ws_setup):
    """A client-declared length beyond MAX_MESSAGE_BYTES closes with 1009
    without buffering the body."""
    import struct

    _node, _ws, client = ws_setup
    # header claiming an 2^40-byte masked text frame; no body sent
    header = bytes([0x80 | OP_TEXT, 0x80 | 127]) \
        + struct.pack(">Q", 1 << 40) + os.urandom(4)
    client.sock.sendall(header)
    client.sock.settimeout(5.0)
    op, body = read_frame(client.sock)
    assert op == 0x8
    (code,) = struct.unpack(">H", body[:2])
    assert code == 1009
