"""Node-table diff layering (storage/layering.py): finalized layers
flatten to the durable backend, stale branches stay RAM-only, and a
restart regenerates the unflattened tail by re-execution."""

import os

from ethrex_tpu.blockchain.fork_choice import apply_fork_choice
from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import Transaction
from ethrex_tpu.storage.layering import LayeredTable
from ethrex_tpu.storage.persistent import PersistentBackend
from ethrex_tpu.storage.store import Store

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def test_layered_table_semantics():
    base = {b"a": b"1"}
    t = LayeredTable(base)
    t[b"direct"] = b"0"           # no layer open: straight to base
    assert base[b"direct"] == b"0"
    t.push_layer("blk1")
    t[b"b"] = b"2"
    t.push_layer("blk2")
    t[b"c"] = b"3"
    t[b"b"] = b"2'"               # newer layer shadows older
    assert t[b"a"] == b"1" and t[b"b"] == b"2'" and t[b"c"] == b"3"
    assert b"b" not in base
    assert t.flatten_layer("blk1") == 1
    assert base[b"b"] == b"2"     # blk1's value landed; blk2 still shadows
    assert t[b"b"] == b"2'"
    assert t.demote_layer("blk2") == 2
    assert not t.layers
    assert t[b"c"] == b"3" and b"c" not in base   # RAM overlay only


def _tx(nonce, value=100):
    return Transaction(
        tx_type=2, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21_000, to=bytes([0x42]) * 20, value=value).sign(SECRET)


def test_finalization_flattens_and_restart_regenerates(tmp_path):
    db = os.path.join(tmp_path, "chain.db")
    store = Store(PersistentBackend(db))
    store.enable_layering()
    node = Node(Genesis.from_json(GENESIS), store=store)
    hashes = []
    for n in range(4):
        node.submit_transaction(_tx(n))
        blk = node.produce_block()
        hashes.append(blk.header.hash)
    # nothing finalized yet: all four block layers are unflattened
    assert len(store.nodes.layers) == 4
    # finalize block 2: layers 1-2 flatten, 3-4 remain
    apply_fork_choice(store, hashes[-1], finalized_hash=hashes[1])
    assert [t for t, _ in store.nodes.layers] == \
        [(3, hashes[2]), (4, hashes[3])]
    head_root = node.head_state_root()
    head_bal = store.account_state(head_root, bytes([0x42]) * 20).balance
    assert head_bal == 400
    store.flush()
    store.backend.close()

    # crash: reopen the database; the unflattened tail (blocks 3-4) must
    # regenerate by re-execution
    store2 = Store(PersistentBackend(db))
    store2.enable_layering()
    assert store2.nodes.get(head_root) is None   # tail wasn't persisted
    node2 = Node(Genesis.from_json(GENESIS), store=store2)
    assert node2.head_state_root() == head_root
    bal = store2.account_state(head_root, bytes([0x42]) * 20).balance
    assert bal == 400


def test_settle_flattens_side_branches_too(tmp_path):
    """Settling flattens EVERY layer at or below the cutoff — stale
    branches included: content-addressed node tables plus the native
    engine's de-duplication mean a node first written by a stale branch
    can be shared by the canonical chain, so selective dropping would be
    unsound (review finding; refcounting is future work)."""
    db = os.path.join(tmp_path, "chain.db")
    store = Store(PersistentBackend(db))
    store.enable_layering()
    node = Node(Genesis.from_json(GENESIS), store=store)
    node.submit_transaction(_tx(0))
    b1 = node.produce_block()
    # a side block at the same height (different timestamp/coinbase)
    from ethrex_tpu.blockchain.payload import (build_payload,
                                               create_payload_header)

    parent = store.get_header(b1.header.parent_hash)
    side_header = create_payload_header(
        parent, node.config, timestamp=b1.header.timestamp + 1,
        coinbase=bytes([0x99]) * 20)
    side = build_payload(node.chain, parent, side_header,
                         [_tx(0, value=7)], []).block
    node.chain.add_block(side)
    assert len(store.nodes.layers) == 2
    apply_fork_choice(store, b1.header.hash, finalized_hash=b1.header.hash)
    assert not store.nodes.layers
    # both states durable and readable
    assert store.nodes.base.get(b1.header.state_root) is not None
    assert store.nodes.get(side.header.state_root) is not None


def test_failed_import_does_not_leak_a_layer(tmp_path):
    import dataclasses

    import pytest

    from ethrex_tpu.blockchain.blockchain import InvalidBlock

    db = os.path.join(tmp_path, "chain.db")
    store = Store(PersistentBackend(db))
    store.enable_layering()
    node = Node(Genesis.from_json(GENESIS), store=store)
    node.submit_transaction(_tx(0))
    b1 = node.produce_block()
    n_layers = len(store.nodes.layers)
    # same block, corrupted state root: import must fail WITHOUT leaving
    # an orphaned top layer behind
    bad_header = dataclasses.replace(b1.header, timestamp=b1.header.timestamp + 1,
                                     state_root=b"\x13" * 32)
    bad = dataclasses.replace(b1, header=bad_header)
    with pytest.raises(InvalidBlock):
        node.chain.add_block(bad)
    assert len(store.nodes.layers) == n_layers
