"""Serialized-executable cache (utils/exec_cache + stark hydration +
client pre-warm): store/load/corruption/retention unit drills with a
stubbed serializer, hydration grouping against the in-process phase
cache, the telemetry surfaces, and the slow cross-process warm-restart
drill (two real subprocesses sharing one cache directory)."""

import json
import os
import pickle
import subprocess
import sys

import pytest

from ethrex_tpu.prover.client import ProverClient
from ethrex_tpu.stark import prover
from ethrex_tpu.utils import exec_cache
from ethrex_tpu.utils.metrics import METRICS


class _FakeExecutable:
    """Picklable stand-in for a compiled XLA executable."""

    def __init__(self, tag):
        self.tag = tag


def _fake_serializer(monkeypatch):
    """Reroute jax.experimental.serialize_executable through pickle:
    load/store import the module object, so patching its attributes
    covers the real call sites without compiling anything."""
    from jax.experimental import serialize_executable as se

    monkeypatch.setattr(
        se, "serialize",
        lambda compiled: (pickle.dumps(compiled), "it", "ot"))

    def _deserialize(payload, in_tree, out_tree):
        assert (in_tree, out_tree) == ("it", "ot")
        return pickle.loads(payload)

    monkeypatch.setattr(se, "deserialize_and_load", _deserialize)


@pytest.fixture
def cache_env(monkeypatch, tmp_path):
    monkeypatch.setenv("ETHREX_EXEC_CACHE_DIR", str(tmp_path))
    monkeypatch.delenv("ETHREX_EXEC_CACHE_OFF", raising=False)
    monkeypatch.delenv("ETHREX_EXEC_CACHE_MAX", raising=False)
    monkeypatch.setattr(exec_cache, "_CONFIGURED_DIR", None)
    exec_cache.clear_stats()
    _fake_serializer(monkeypatch)
    yield tmp_path
    exec_cache.clear_stats()


def _path_for(parts):
    return os.path.join(exec_cache.cache_dir(),
                        exec_cache.entry_key(parts) + exec_cache._SUFFIX)


# ===========================================================================
# store / load / corruption / retention
# ===========================================================================

def _counter(name):
    # earlier tests' real proves may already have bumped the global
    # registry (the cache is default-on): assert deltas, not absolutes
    return METRICS.counters.get(name, 0.0)


def test_store_load_roundtrip_and_counters(cache_env):
    base = {n: _counter(f"executable_cache_{n}_total")
            for n in ("hits", "misses", "errors")}
    parts = {"kind": "phase", "kernel": "commit", "log_n": 4}
    assert exec_cache.load(parts) is None            # cold: a clean miss
    assert exec_cache.store(parts, _FakeExecutable("a")) is True
    got = exec_cache.load(parts)
    assert isinstance(got, _FakeExecutable) and got.tag == "a"
    assert exec_cache.STATS == {"hits": 1, "misses": 1, "errors": 0,
                                "stores": 1}
    assert _counter("executable_cache_hits_total") == base["hits"] + 1
    assert _counter("executable_cache_misses_total") == base["misses"] + 1
    assert _counter("executable_cache_errors_total") == base["errors"]


def test_distinct_parts_are_distinct_entries(cache_env):
    exec_cache.store({"kind": "phase", "kernel": "commit"},
                     _FakeExecutable("x"))
    exec_cache.store({"kind": "phase", "kernel": "deep"},
                     _FakeExecutable("y"))
    assert exec_cache.entry_count() == 2
    assert exec_cache.load({"kind": "phase", "kernel": "deep"}).tag == "y"


def test_env_drift_makes_entries_structurally_unreachable(
        cache_env, monkeypatch):
    """A jaxlib upgrade changes the key, so a stale entry is a plain
    miss — never an error, and invisible to the hydration scan."""
    parts = {"kind": "phase", "kernel": "open"}
    exec_cache.store(parts, _FakeExecutable("x"))
    real = exec_cache._env_parts()
    monkeypatch.setattr(exec_cache, "_env_parts",
                        lambda: dict(real, jaxlib="99.0"))
    assert exec_cache.load(parts) is None
    assert exec_cache.STATS["errors"] == 0
    assert exec_cache.scan() == []
    assert exec_cache.entry_count() == 1             # still on disk, benign


def test_env_mismatch_inside_entry_is_dropped_as_error(cache_env):
    """An entry whose recorded env no longer matches (e.g. a file copied
    from another host into the right filename) is counted as an error, a
    miss, and unlinked."""
    parts = {"kind": "phase", "kernel": "quotient"}
    exec_cache.store(parts, _FakeExecutable("z"))
    path = _path_for(parts)
    entry = pickle.loads(open(path, "rb").read())
    entry["env"] = dict(entry["env"], jax="0.0.0")
    with open(path, "wb") as f:
        f.write(pickle.dumps(entry))
    assert exec_cache.load(parts) is None
    assert exec_cache.STATS["errors"] == 1
    assert exec_cache.STATS["misses"] == 1
    assert not os.path.exists(path)


def test_corrupt_entry_is_error_plus_miss_then_plain_miss(cache_env):
    base_errors = _counter("executable_cache_errors_total")
    parts = {"kind": "phase", "kernel": "deep"}
    exec_cache.store(parts, _FakeExecutable("y"))
    path = _path_for(parts)
    with open(path, "wb") as f:
        f.write(b"\x00truncated-garbage")
    assert exec_cache.load(parts) is None
    assert exec_cache.STATS["errors"] == 1
    assert exec_cache.STATS["misses"] == 1
    assert _counter("executable_cache_errors_total") == base_errors + 1
    assert not os.path.exists(path)                  # dropped
    # the NEXT lookup finds nothing: a plain miss, no second error
    assert exec_cache.load(parts) is None
    assert exec_cache.STATS == {"hits": 0, "misses": 2, "errors": 1,
                                "stores": 1}


def test_unloadable_payload_is_rejected_at_store_time(cache_env,
                                                      monkeypatch):
    """serialize() of an executable whose compile was served from the
    XLA persistent compilation cache yields a payload missing its jit
    symbols — a later deserialize fails with "Symbols not found".
    store() round-trips the payload before publishing, so such an entry
    is rejected (error counted, nothing on disk) instead of poisoning
    every subsequent hydration."""
    from jax.experimental import serialize_executable as se

    def _symbols_lost(payload, in_tree, out_tree):
        raise RuntimeError("Symbols not found: [concatenate_fusion.12]")

    monkeypatch.setattr(se, "deserialize_and_load", _symbols_lost)
    base_errors = _counter("executable_cache_errors_total")
    parts = {"kind": "phase", "kernel": "commit", "log_n": 5}
    assert exec_cache.store(parts, _FakeExecutable("poisoned")) is False
    assert exec_cache.STATS == {"hits": 0, "misses": 0, "errors": 1,
                                "stores": 0}
    assert _counter("executable_cache_errors_total") == base_errors + 1
    assert exec_cache.entry_count() == 0
    assert not os.path.exists(_path_for(parts))


def test_code_fingerprint_participates_in_the_key(cache_env, monkeypatch):
    """A change to the kernel-defining sources must orphan every entry:
    the semantic parts cannot see function bodies, so the code hash in
    the env half of the key is what keeps a stale executable from ever
    being served after a deploy."""
    parts = {"kind": "phase", "kernel": "commit"}
    exec_cache.store(parts, _FakeExecutable("old-code"))
    monkeypatch.setattr(exec_cache, "_code_fingerprint", lambda: "deadbeef")
    assert exec_cache.load(parts) is None            # clean miss
    assert exec_cache.STATS["errors"] == 0
    assert exec_cache.scan() == []


def test_off_switch_disables_lookup_and_store(cache_env, monkeypatch):
    monkeypatch.setenv("ETHREX_EXEC_CACHE_OFF", "1")
    parts = {"kind": "phase", "kernel": "commit"}
    assert exec_cache.store(parts, _FakeExecutable("n")) is False
    assert exec_cache.load(parts) is None
    assert exec_cache.entry_count() == 0
    assert exec_cache.STATS == {"hits": 0, "misses": 0, "errors": 0,
                                "stores": 0}
    assert exec_cache.runtime_stats()["enabled"] is False


def test_retention_prunes_least_recently_used(cache_env):
    paths = {}
    for i in range(5):
        parts = {"kind": "phase", "i": i}
        exec_cache.store(parts, _FakeExecutable(i))
        paths[i] = _path_for(parts)
        os.utime(paths[i], (100 + i, 100 + i))       # deterministic LRU order
    assert exec_cache.prune(max_entries=3) == 2
    assert [i for i in range(5) if os.path.exists(paths[i])] == [2, 3, 4]


def test_store_prunes_via_env_cap(cache_env, monkeypatch):
    monkeypatch.setenv("ETHREX_EXEC_CACHE_MAX", "2")
    for i in range(4):
        exec_cache.store({"kind": "phase", "i": i}, _FakeExecutable(i))
    assert exec_cache.entry_count() <= 2


def test_scan_filters_kind_and_orders_oldest_first(cache_env):
    for i, kind in enumerate(["phase", "core_step", "phase"]):
        parts = {"kind": kind, "i": i}
        exec_cache.store(parts, _FakeExecutable(i))
        # reverse mtimes so insertion order != age order
        os.utime(_path_for(parts), (200 - i, 200 - i))
    got = exec_cache.scan("phase")
    assert [p["i"] for p in got] == [2, 0]
    assert all(p["kind"] == "phase" for p in got)
    assert len(exec_cache.scan()) == 3               # no filter: everything


def test_runtime_stats_shape(cache_env):
    parts = {"kind": "phase", "i": 1}
    exec_cache.store(parts, _FakeExecutable(1))
    exec_cache.load(parts)
    stats = exec_cache.runtime_stats()
    assert stats["enabled"] is True
    assert stats["entries"] == 1
    assert stats["hits"] == 1 and stats["stores"] == 1
    assert stats["dir"] == str(cache_env)


# ===========================================================================
# hydration into the in-process phase cache
# ===========================================================================

def _phase_parts(kernel, air="stub-air", log_n=4, mesh=None, **over):
    parts = {"kind": "phase", "air": air, "air_name": "StubAir",
             "width": 2, "nb": 3, "log_n": log_n, "log_blowup": 2,
             "shift": 7, "mesh": mesh, "kernel": kernel}
    parts.update(over)
    return parts


@pytest.fixture
def phase_cache_isolation():
    saved = dict(prover._PHASE_CACHE)
    prover._PHASE_CACHE.clear()
    yield
    prover._PHASE_CACHE.clear()
    prover._PHASE_CACHE.update(saved)


def test_hydrate_installs_only_complete_matching_groups(
        monkeypatch, phase_cache_isolation):
    monkeypatch.delenv("ETHREX_EXEC_CACHE_OFF", raising=False)
    entries = (
        [_phase_parts(k) for k in prover._KERNELS]             # complete
        + [_phase_parts(k, air="other-air", log_n=5)
           for k in ("commit", "quotient", "open")]            # incomplete
        + [_phase_parts(k, air="mesh-air", mesh=[[0, 1], ["fri"], [2]])
           for k in prover._KERNELS])                          # wrong mesh
    monkeypatch.setattr(exec_cache, "scan", lambda kind=None: list(entries))
    monkeypatch.setattr(exec_cache, "load",
                        lambda parts: f"exe:{parts['kernel']}")
    with METRICS.lock:
        hist0 = METRICS.histograms.get("prover_phase_compile_seconds")
        rows_before = set(hist0.series) if hist0 else set()
    assert prover.hydrate_phase_cache(None) == 1
    progs = prover._PHASE_CACHE[("stub-air", 4, 2, 7, None)]
    assert (progs.commit, progs.quotient, progs.open, progs.deep) == \
        ("exe:commit", "exe:quotient", "exe:open", "exe:deep")
    assert progs.plan is None
    assert progs.put_cols("x") == "x"        # identity on the 1-device path
    assert len(prover._PHASE_CACHE) == 1     # nothing else was installed
    # deserialize walls land in the compile histogram as source=deserialized
    with METRICS.lock:
        hist = METRICS.histograms["prover_phase_compile_seconds"]
        new = [dict(labels) for labels in hist.series
               if labels not in rows_before]
    assert {r["source"] for r in new} == {"deserialized"}
    assert {r["kernel"] for r in new} == set(prover._KERNELS)
    # idempotent: the group is already in-process, a second pass is a no-op
    assert prover.hydrate_phase_cache(None) == 0


def test_hydrate_skips_group_when_one_kernel_fails_to_load(
        monkeypatch, phase_cache_isolation):
    monkeypatch.delenv("ETHREX_EXEC_CACHE_OFF", raising=False)
    entries = [_phase_parts(k) for k in prover._KERNELS]
    monkeypatch.setattr(exec_cache, "scan", lambda kind=None: list(entries))
    monkeypatch.setattr(
        exec_cache, "load",
        lambda parts: None if parts["kernel"] == "open" else "exe")
    assert prover.hydrate_phase_cache(None) == 0
    assert prover._PHASE_CACHE == {}         # never partially installed


def test_hydrate_is_noop_when_disabled_or_unscannable(
        monkeypatch, phase_cache_isolation):
    monkeypatch.setenv("ETHREX_EXEC_CACHE_OFF", "1")
    assert prover.hydrate_phase_cache(None) == 0
    monkeypatch.delenv("ETHREX_EXEC_CACHE_OFF")

    def _boom(kind=None):
        raise OSError("cache dir unreadable")

    monkeypatch.setattr(exec_cache, "scan", _boom)
    assert prover.hydrate_phase_cache(None) == 0


# ===========================================================================
# client pre-warm and the advisory warm flag
# ===========================================================================

def test_prover_client_prewarm_sets_warm_flag():
    class Hydrating:
        prover_type = "exec"

        def prewarm(self):
            return 2

    client = ProverClient(Hydrating(), [])
    assert client._prewarm_done.wait(10.0)
    assert client.hydrated_groups == 2
    assert client.warm is True


def test_prover_client_prewarm_failure_is_cold_not_fatal():
    class Boom:
        prover_type = "exec"

        def prewarm(self):
            raise RuntimeError("cache exploded")

    client = ProverClient(Boom(), [])
    assert client._prewarm_done.wait(10.0)   # the failure never hangs polls
    assert client.hydrated_groups == 0
    assert client.warm is False


def test_prover_client_warm_after_first_proof_without_hydration():
    client = ProverClient("exec", [], prewarm=False)
    assert client._prewarm_done.is_set()
    assert client.warm is False              # nothing hydrated, nothing proven
    client.proved.append(1)
    assert client.warm is True               # a completed proof implies warm


def test_backend_default_prewarm_is_zero():
    from ethrex_tpu.prover.backend import get_backend

    assert get_backend("exec").prewarm() == 0


# ===========================================================================
# telemetry surfaces (ethrex_perf / ethrex_health / monitor)
# ===========================================================================

def test_perf_and_health_surface_exec_cache(cache_env):
    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.rpc.server import RpcServer

    exec_cache.store({"kind": "phase", "i": 0}, _FakeExecutable(0))
    exec_cache.load({"kind": "phase", "i": 0})
    sender = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(0xA11CE))
    server = RpcServer(Node(Genesis.from_json({
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + sender.hex(): {"balance": hex(10**21)}},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0"})))
    perf = server.handle({"jsonrpc": "2.0", "id": 1, "method": "ethrex_perf",
                          "params": []})["result"]
    assert perf["executableCache"]["hits"] == 1
    assert perf["executableCache"]["stores"] == 1
    assert perf["executableCache"]["entries"] == 1
    health = server.handle({"jsonrpc": "2.0", "id": 2,
                            "method": "ethrex_health",
                            "params": []})["result"]
    assert health["perf"]["executableCache"]["hits"] == 1
    assert health["perf"]["executableCache"]["enabled"] is True


def test_monitor_perf_panel_shows_exec_cache_line():
    from ethrex_tpu.utils.monitor import _perf_lines

    snap = {"perf": {"enabled": True,
                     "executableCache": {"enabled": True, "hits": 8,
                                         "misses": 1, "errors": 0,
                                         "entries": 12}}}
    text = "\n".join(_perf_lines(snap, 100))
    assert "exec cache [on]" in text
    assert "hits" in text and "8" in text
    # a degraded section renders no cache line rather than crashing
    snap["perf"]["executableCache"] = {"error": "boom"}
    assert "exec cache" not in "\n".join(_perf_lines(snap, 100))


# ===========================================================================
# the real thing: cross-process warm restart (slow tier)
# ===========================================================================

_DRILL = r"""
import hashlib, json, os, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from ethrex_tpu.models import fibonacci as fib
from ethrex_tpu.stark import prover
from ethrex_tpu.stark.prover import StarkParams
from ethrex_tpu.utils import exec_cache
from ethrex_tpu.utils.metrics import METRICS

hydrated = prover.hydrate_phase_cache(None)
params = StarkParams(log_blowup=2, num_queries=16, log_final_size=4)
air = fib.FibonacciAir()
trace = fib.generate_trace(64)
pub = fib.public_inputs(trace)
t0 = time.perf_counter()
proof = prover.prove(air, trace, pub, params)
prove_s = time.perf_counter() - t0
digest = hashlib.sha256(
    json.dumps(proof, sort_keys=True, default=repr).encode()).hexdigest()
by_source, build_s = {}, {}
with METRICS.lock:
    hist = METRICS.histograms.get("prover_phase_compile_seconds")
    if hist is not None:
        for labels, row in hist.series.items():
            src = dict(labels).get("source")
            by_source[src] = by_source.get(src, 0) + 1
            build_s[src] = build_s.get(src, 0.0) + row[-1]
print(json.dumps({"hydrated": hydrated, "digest": digest,
                  "prove_s": round(prove_s, 3), "by_source": by_source,
                  "build_s": {k: round(v, 3) for k, v in build_s.items()},
                  "exec_stats": dict(exec_cache.STATS)}))
"""


@pytest.mark.slow
def test_cross_process_warm_restart_drill(tmp_path):
    """The tentpole's acceptance drill: process A proves cold and
    populates the cache; a fresh process B sharing only the cache
    directory hydrates every phase program from disk, recompiles no
    phase kernel (no source="compiled" rows), and produces a
    byte-identical proof — with the phase build wall collapsing by far
    more than the 10x warmup target."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, ETHREX_EXEC_CACHE_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("ETHREX_EXEC_CACHE_OFF", None)

    def child():
        run = subprocess.run([sys.executable, "-c", _DRILL], env=env,
                             capture_output=True, text=True, timeout=1200)
        assert run.returncode == 0, run.stderr[-4000:]
        return json.loads(run.stdout.strip().splitlines()[-1])

    cold = child()
    assert cold["hydrated"] == 0
    assert cold["by_source"] == {"compiled": 4}
    assert cold["exec_stats"]["stores"] == 4

    warm = child()
    assert warm["hydrated"] == 1                     # one 4-kernel group
    assert warm["digest"] == cold["digest"]          # byte-identical proof
    assert warm["by_source"] == {"deserialized": 4}  # zero phase recompiles
    assert warm["exec_stats"] == {"hits": 4, "misses": 0, "errors": 0,
                                  "stores": 0}
    assert warm["build_s"]["deserialized"] * 5 < cold["build_s"]["compiled"]
