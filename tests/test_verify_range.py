"""Range-proof verification: property tests against real tries."""

import random

import pytest

from ethrex_tpu.crypto.keccak import keccak256
from ethrex_tpu.trie.trie import Trie
from ethrex_tpu.trie.verify_range import RangeProofError, verify_range

RNG = random.Random(7)


def _build_trie(n=120):
    t = Trie()
    items = {}
    for i in range(n):
        k = keccak256(b"key%d" % i)
        v = RNG.randbytes(RNG.randint(4, 40))
        t.insert(k, v)
        items[k] = v
    t.commit()
    return t, sorted(items.items())


def _range_with_proof(t, items, lo, hi):
    keys = [k for k, _ in items[lo:hi]]
    values = [v for _, v in items[lo:hi]]
    proof = {keccak256(n): n
             for n in t.get_proof(keys[0]) + t.get_proof(keys[-1])}
    return keys, values, list(proof.values())


@pytest.mark.parametrize("lo,hi", [(0, 120), (0, 10), (50, 70), (110, 120),
                                   (3, 4), (0, 2), (59, 61)])
def test_valid_ranges_verify(lo, hi):
    t, items = _build_trie()
    root = t.root_hash()
    keys, values, proof = _range_with_proof(t, items, lo, hi)
    assert verify_range(root, keys, values, proof)


def test_tampered_ranges_fail():
    t, items = _build_trie()
    root = t.root_hash()
    keys, values, proof = _range_with_proof(t, items, 40, 80)
    # omitted key in the middle
    assert not verify_range(root, keys[:10] + keys[11:],
                            values[:10] + values[11:], proof)
    # altered value
    bad_vals = list(values)
    bad_vals[5] = bad_vals[5] + b"x"
    assert not verify_range(root, keys, bad_vals, proof)
    # injected key inside the range
    extra = keccak256(b"not-in-trie")
    if keys[0] < extra < keys[-1]:
        ik = sorted(keys + [extra])
        iv = [dict(zip(keys, values)).get(k, b"zz") for k in ik]
        assert not verify_range(root, ik, iv, proof)
    # swapped order rejected structurally
    with pytest.raises(RangeProofError):
        verify_range(root, [keys[1], keys[0]], values[:2], proof)
    # incomplete proof (no nodes)
    with pytest.raises(RangeProofError):
        verify_range(root, keys, values, [])


def test_truncated_tail_is_valid_shorter_range():
    """Pin the proof-variant semantics: a server-truncated tail with a
    proof for the NEW last key verifies (the client re-requests from
    keys[-1] — liveness, not soundness; see verify_range docstring)."""
    t, items = _build_trie()
    root = t.root_hash()
    keys, values, _ = _range_with_proof(t, items, 40, 80)
    keys, values = keys[:-1], values[:-1]
    proof = {keccak256(n): n
             for n in t.get_proof(keys[0]) + t.get_proof(keys[-1])}
    assert verify_range(root, keys, values, list(proof.values()))


def test_many_random_windows():
    t, items = _build_trie(200)
    root = t.root_hash()
    for _ in range(25):
        lo = RNG.randrange(0, 199)
        hi = RNG.randrange(lo + 1, 201)
        keys, values, proof = _range_with_proof(t, items, lo, hi)
        assert verify_range(root, keys, values, proof), (lo, hi)
