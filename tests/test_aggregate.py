"""Recursion/aggregation: the FRI-verifier AIR (models/fri_verifier_air)
and the aggregate prove/verify flow (stark/aggregate) — constraint
satisfaction on honest traces, tamper rejection in-circuit and at the
digest, and a full 2-inner-proof aggregation round-trip."""

import numpy as np
import pytest

import jax.numpy as jnp

from ethrex_tpu.models import fri_verifier_air as fva
from ethrex_tpu.ops import babybear as bb
from ethrex_tpu.ops import ext
from ethrex_tpu.ops import fri
from ethrex_tpu.ops import ntt
from ethrex_tpu.ops.challenger import Challenger
from ethrex_tpu.stark import aggregate
from ethrex_tpu.stark.air import HostExtOps
from ethrex_tpu.stark.prover import StarkParams

pytestmark = pytest.mark.slow  # full STARK compiles

RNG = np.random.default_rng(11)


def _codeword(log_deg, log_blowup):
    n = 1 << log_deg
    coeffs = RNG.integers(0, bb.P, size=(4, n), dtype=np.uint32)
    evals = ntt.coset_evals_from_coeffs(
        bb.to_mont(jnp.asarray(coeffs)), n << log_blowup)
    return jnp.moveaxis(evals, 0, -1)


def _small_fri_items(num_queries=3):
    """One tiny FRI proof (N=32, 1 layer) -> aggregation work items."""
    params = fri.FriParams(log_blowup=2, num_queries=num_queries,
                           log_final_size=4)
    cw = _codeword(3, 2)  # N = 32, log_n0 = 5
    prover = fri.FriProver(params)
    proof, _ = prover.prove(cw, Challenger())
    indices, layer0, items = aggregate.derive_query_items(
        proof, 5, Challenger(), params, with_paths=True)
    return proof, items


def _eval_rows(air, trace, periodic_cols):
    hops = HostExtOps()
    n = trace.shape[0]
    bad = []
    for r in range(n - 1):
        local = [ext.h_from_base(int(v)) for v in trace[r]]
        nxt = [ext.h_from_base(int(v)) for v in trace[r + 1]]
        periodic = [ext.h_from_base(int(col[r % len(col)]))
                    for col in periodic_cols]
        cs = air.constraints(local, nxt, periodic, hops)
        bad.extend((r, i) for i, c in enumerate(cs) if c != ext.ZERO_H)
        if bad:
            break
    return bad


def test_fri_verify_trace_satisfies_constraints():
    proof, items = _small_fri_items()
    max_depth = max(it["msg"][fva.MF_DEPTH] for it in items)
    air = fva.FriVerifyAir(max_depth)
    trace = fva.generate_fri_verify_trace(items, max_depth,
                                          air.seg_periods)
    n = trace.shape[0]
    periodic_cols = air.periodic_columns(n)
    bad = _eval_rows(air, trace, periodic_cols)
    assert not bad, f"constraints violated: {bad[:5]}"
    digest = fva.transcript_digest([it["msg"] for it in items],
                                   air.seg_periods)
    for row, col, val in air.boundaries(digest, n):
        assert int(trace[row, col]) == val, (row, col)


def test_tampered_path_or_message_breaks_constraints():
    proof, items = _small_fri_items()
    max_depth = max(it["msg"][fva.MF_DEPTH] for it in items)
    air = fva.FriVerifyAir(max_depth)
    trace = fva.generate_fri_verify_trace(items, max_depth,
                                          air.seg_periods)
    periodic_cols = air.periodic_columns(trace.shape[0])
    seg_rows = air.seg_periods * fva.PERIOD

    # flip a sibling limb inside segment 0's fold window: the fold no
    # longer lands on the absorbed root
    bad = trace.copy()
    fold_rows = slice(2 * fva.PERIOD, 3 * fva.PERIOD)
    bad[fold_rows, fva.SIB] = (bad[fold_rows, fva.SIB] + 1) % bb.P
    assert _eval_rows(air, bad, periodic_cols)

    # flip the claimed root limb (message): same story via the root check
    bad2 = trace.copy()
    seg0 = slice(0, seg_rows)
    bad2[seg0, fva.MSG + fva.MF_ROOT] = \
        (bad2[seg0, fva.MSG + fva.MF_ROOT] + 1) % bb.P
    assert _eval_rows(air, bad2, periodic_cols)

    # flip the carried_out value: the fold equation must catch it
    bad3 = trace.copy()
    bad3[seg0, fva.MSG + fva.MF_COUT] = \
        (bad3[seg0, fva.MSG + fva.MF_COUT] + 1) % bb.P
    assert _eval_rows(air, bad3, periodic_cols)


def _fib_air_and_proofs(count=2):
    from ethrex_tpu.models.fibonacci import FibonacciAir, generate_trace
    from ethrex_tpu.stark import prover as stark_prover

    params = StarkParams(log_blowup=2, num_queries=2, log_final_size=4)
    airs, proofs = [], []
    for i in range(count):
        air = FibonacciAir()
        trace = generate_trace(16, a0=1, b0=2 + i)
        pub = [1, 2 + i, int(trace[-1, 1])]
        proofs.append(stark_prover.prove(air, trace, pub, params))
        airs.append(air)
    return airs, proofs, params


def test_aggregate_roundtrip_and_tamper():
    airs, proofs, params = _fib_air_and_proofs(2)
    outer_params = StarkParams(log_blowup=3, num_queries=8,
                               log_final_size=4)
    agg = aggregate.aggregate(airs, proofs, params, outer_params)
    # paths are dropped from the aggregate's inner proofs
    for inner in agg.inners:
        for per_layer in inner["fri"]["queries"]:
            for opening in per_layer:
                assert "path" not in opening
    assert aggregate.verify_aggregated(airs, agg, params, outer_params)

    # tampering an inner FRI value breaks the digest binding
    import copy

    bad = copy.deepcopy(agg)
    opening = bad.inners[0]["fri"]["queries"][0][0]
    vals = [list(v) for v in opening["values"]]
    vals[0][0] = (int(vals[0][0]) + 1) % bb.P
    opening["values"] = vals
    with pytest.raises(Exception):
        aggregate.verify_aggregated(airs, bad, params, outer_params)

    # tampering the outer public input is rejected
    bad2 = copy.deepcopy(agg)
    bad2.outer["pub_inputs"] = list(bad2.outer["pub_inputs"])
    bad2.outer["pub_inputs"][0] = \
        (int(bad2.outer["pub_inputs"][0]) + 1) % bb.P
    with pytest.raises(Exception):
        aggregate.verify_aggregated(airs, bad2, params, outer_params)
