"""L2 -> L1 withdrawal flow: burn on L2, prove + verify batch, claim on L1
with a Merkle message proof (the reference's CommonBridge withdrawal
round-trip, hermetic)."""

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.l2.l1_client import InMemoryL1, L1Error
from ethrex_tpu.l2.messages import (BRIDGE_ADDRESS, collect_messages,
                                    message_proof, message_root,
                                    verify_message_proof)
from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover.client import ProverClient

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _withdraw_tx(nonce, value):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21000, to=BRIDGE_ADDRESS, value=value,
    ).sign(SECRET)


def test_withdrawal_round_trip():
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1([protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,)))
    seq.coordinator.start()
    try:
        # two withdrawals in one batch
        node.submit_transaction(_withdraw_tx(0, 5000))
        node.submit_transaction(_withdraw_tx(1, 7000))
        block = seq.produce_block()
        seq.commit_next_batch()
        # prove + settle
        client = ProverClient(protocol.PROVER_EXEC,
                              [("127.0.0.1", seq.coordinator.port)])
        assert client.poll_once() == 1
        assert seq.send_proofs() == (1, 1)
        # the guest's committed output carries the same messages root
        proof_obj = seq.rollup.get_proof(1, protocol.PROVER_EXEC)
        from ethrex_tpu.guest.execution import ProgramOutput
        out = ProgramOutput.decode(bytes.fromhex(proof_obj["output"][2:]))
        receipts = [node.store.get_receipts(block.hash)]
        msgs = collect_messages([block], receipts)
        assert len(msgs) == 2
        assert out.messages_root == message_root(msgs)
        assert l1.message_roots[1] == out.messages_root
        # claim both on L1
        for i, m in enumerate(msgs):
            tx_hash = l1.claim_withdrawal(1, m.leaf(), i,
                                          message_proof(msgs, i))
            assert tx_hash
        # double-claim rejected
        with pytest.raises(L1Error, match="already claimed"):
            l1.claim_withdrawal(1, msgs[0].leaf(), 0,
                                message_proof(msgs, 0))
        # forged value rejected
        from ethrex_tpu.l2.messages import L2Message
        fake = L2Message(SENDER, 999999, msgs[0].tx_hash)
        with pytest.raises(L1Error, match="invalid message proof"):
            l1.claim_withdrawal(1, fake.leaf(), 0, message_proof(msgs, 0))
    finally:
        seq.stop()
        node.stop()


def test_claim_requires_verification():
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1([protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,)))
    try:
        node.submit_transaction(_withdraw_tx(0, 100))
        block = seq.produce_block()
        seq.commit_next_batch()  # committed but NOT verified
        receipts = [node.store.get_receipts(block.hash)]
        msgs = collect_messages([block], receipts)
        with pytest.raises(L1Error, match="not verified"):
            l1.claim_withdrawal(1, msgs[0].leaf(), 0,
                                message_proof(msgs, 0))
    finally:
        seq.stop()
        node.stop()


def test_message_tree_vectors():
    from ethrex_tpu.l2.messages import L2Message
    msgs = [L2Message(bytes([i]) * 20, i * 10, bytes([i]) * 32)
            for i in range(1, 6)]  # odd count exercises duplicate padding
    root = message_root(msgs)
    for i, m in enumerate(msgs):
        assert verify_message_proof(root, m.leaf(), i,
                                    message_proof(msgs, i))
    assert not verify_message_proof(root, msgs[0].leaf(), 1,
                                    message_proof(msgs, 0))
    assert message_root([]) == b"\x00" * 32
