"""Overload battery: chaos coverage for the serving-path fault sites
("rpc.handle", "mempool.add") and the 5x-overload soak proving the
graceful-degradation contract end to end over real TCP:

- the accounting identity holds exactly (scheduled = delivered + shed
  + missed — no request is silently lost),
- accepted requests keep their deadline budget while shedding,
- shed responses are answered fast (never executed),
- the shed level returns to 0 within one hysteresis window once the
  overload stops, and
- the run leaks no threads and no file descriptors.
"""

import os
import threading
import time

import pytest

from ethrex_tpu.blockchain.mempool import Mempool
from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.perf.loadgen import Harness, RpcConn
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.rpc.server import RpcServer
from ethrex_tpu.utils.faults import FaultPlan, InjectedFault, injected
from ethrex_tpu.utils.overload import (
    SERVER_BUSY_CODE,
    OverloadController,
    is_busy_error,
)

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _tx(nonce, fee=10**10):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=fee,
        gas_limit=21_000, to=bytes([0xAA]) * 20, value=1).sign(SECRET)


def _req(method, rid=1):
    return {"jsonrpc": "2.0", "id": rid, "method": method, "params": []}


# ---------------------------------------------------------------------------
# rpc.handle chaos: a crashing or slow handler body

def test_rpc_handle_injected_error_is_contained():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, port=0)
    with injected(FaultPlan(seed=1).error("rpc.handle", times=1)):
        resp = server.handle(_req("eth_blockNumber"))
        assert resp["error"]["code"] == -32603
        # the budget is spent: the next request works
        ok = server.handle(_req("eth_blockNumber"))
    assert "result" in ok


def test_rpc_handle_injected_drop_is_contained():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, port=0)
    with injected(FaultPlan(seed=2).drop("rpc.handle", times=1)):
        resp = server.handle(_req("eth_blockNumber"))
    assert resp["error"]["code"] == -32603


def test_rpc_handle_injected_delay_makes_a_slow_handler():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, port=0)
    with injected(FaultPlan(seed=3).delay("rpc.handle", 0.05, times=1)):
        t0 = time.monotonic()
        resp = server.handle(_req("eth_blockNumber"))
        elapsed = time.monotonic() - t0
    assert "result" in resp
    assert elapsed >= 0.05
    # the seat is AFTER admission: a shed request skips the injected
    # delay entirely (shed-early-and-cheaply)
    ctl = server.overload
    hold = ctl.admit("eth_blockNumber")
    saved = ctl.classes["read"].limit
    ctl.classes["read"].limit = 1
    try:
        with injected(FaultPlan(seed=4).delay("rpc.handle", 0.5)):
            t0 = time.monotonic()
            resp = server.handle(_req("eth_blockNumber"))
            elapsed = time.monotonic() - t0
    finally:
        ctl.classes["read"].limit = saved
        ctl.release(hold)
    assert resp["error"]["code"] == SERVER_BUSY_CODE
    assert elapsed < 0.25


# ---------------------------------------------------------------------------
# mempool.add chaos: a crashing or slow admission path

def test_mempool_add_injected_error_propagates_typed():
    pool = Mempool(capacity=10)
    with injected(FaultPlan(seed=5).error("mempool.add", times=1)):
        with pytest.raises(InjectedFault):
            pool.add_transaction(_tx(0), 0, 10**21, 7)
        # nothing was half-admitted
        assert len(pool) == 0
        assert pool.admitted == 0
        pool.add_transaction(_tx(0), 0, 10**21, 7)
    assert len(pool) == 1


def test_mempool_add_injected_delay_outside_the_lock():
    """The chaos seat fires before the pool lock, so a slow admission
    cannot serialize concurrent adders behind the sleeper."""
    pool = Mempool(capacity=10)
    with injected(FaultPlan(seed=6).delay("mempool.add", 0.2, times=1)):
        slow = threading.Thread(
            target=pool.add_transaction, args=(_tx(0), 0, 10**21, 7))
        slow.start()
        time.sleep(0.05)          # the sleeper holds the seat, not the lock
        t0 = time.monotonic()
        pool.add_transaction(_tx(1), 0, 10**21, 7)
        fast = time.monotonic() - t0
        slow.join()
    assert fast < 0.1
    assert len(pool) == 2


# ---------------------------------------------------------------------------
# the 5x-overload soak

def test_overload_soak_graceful_degradation_and_recovery():
    baseline_threads = threading.active_count()
    baseline_fds = len(os.listdir("/proc/self/fd"))

    node = Node(Genesis.from_json(GENESIS))
    ctl = OverloadController(
        read_limit=1, read_deadline=0.5, queue_high=0.05,
        raise_hold=0.1, recover_hold=0.4, tick_interval=0.05,
        signal_window=1.0, shed_pressure_min=3, retry_after=0.25)
    server = RpcServer(node, port=0, overload=ctl).start()
    url = f"http://127.0.0.1:{server.port}"
    try:
        # every handler body takes ~20ms: a single-slot server that
        # sustains ~50 req/s and not much more
        with injected(FaultPlan(seed=7).delay("rpc.handle", 0.02)):
            harness = Harness(url, payload="ping", workers=4,
                              timeout=5.0)
            # phase 1 — find the sustainable rate: 10 req/s (100ms
            # spacing vs ~20ms of work) is comfortably served with
            # zero shedding; 20 usually holds too
            sweep = harness.sweep([10.0, 20.0], duration=1.0)
            sustained = sweep["maxSustainableRate"]
            assert sustained is not None and sustained >= 10.0
            assert sweep["rates"][0]["shed"] == 0

            # phase 2 — 5x the sustainable rate
            rep = harness.run(5.0 * sustained, duration=1.5)
            # the accounting identity holds EXACTLY: nothing vanishes
            assert rep["scheduled"] == (rep["delivered"] + rep["shed"]
                                        + rep["missed"])
            assert rep["shed"] > 0
            assert rep["delivered"] > 0
            assert rep["errors"] == 0        # graceful: typed, not broken
            # accepted requests keep their deadline budget even while
            # the server sheds the excess
            assert rep["latency"]["p99"] is not None
            assert rep["latency"]["p99"] <= 0.5
            # sustained structural shedding raised the adaptive level
            assert ctl.level >= 1
            assert ctl.state == "shedding"

            # phase 3 — shed speed: refused requests are answered far
            # under the 10ms budget because they never execute (and
            # never reach the injected 20ms handler delay)
            hold = ctl.admit("eth_blockNumber")
            assert hold.admitted
            conn = RpcConn(url, timeout=5.0)
            try:
                lats = []
                for i in range(40):
                    t0 = time.monotonic()
                    out = conn.post(
                        b'{"jsonrpc":"2.0","id":1,'
                        b'"method":"eth_blockNumber","params":[]}')
                    lats.append(time.monotonic() - t0)
                    assert out["error"]["code"] == SERVER_BUSY_CODE
                    assert is_busy_error(out["error"])
                lats.sort()
                assert lats[int(len(lats) * 0.9)] < 0.010
            finally:
                conn.close()
                ctl.release(hold)

        # phase 4 — recovery: once the overload stops, the level must
        # fall back to 0 within one hysteresis window (signal_window
        # for the sheds to age out + recover_hold to clear)
        probe = RpcConn(url, timeout=5.0)
        try:
            t0 = time.monotonic()
            budget = ctl.signal_window + ctl.recover_hold + 2.0
            while ctl.level > 0:
                assert time.monotonic() - t0 < budget, \
                    f"stuck at shed level {ctl.level}"
                probe.call("eth_blockNumber", [])
                time.sleep(0.05)
            assert ctl.state in ("recovered", "ok")
        finally:
            probe.close()
    finally:
        server.stop()

    # phase 5 — no leaks: every worker, handler thread, and socket from
    # the soak is gone once the harness and server are torn down
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        threads = threading.active_count()
        fds = len(os.listdir("/proc/self/fd"))
        if threads <= baseline_threads + 2 and fds <= baseline_fds + 8:
            break
        time.sleep(0.1)
    assert threading.active_count() <= baseline_threads + 2, \
        "soak leaked threads"
    assert len(os.listdir("/proc/self/fd")) <= baseline_fds + 8, \
        "soak leaked file descriptors"
