"""EIP-7928 Block Access Lists (VERDICT #6): generation from the
journaled executor, canonical RLP/ordering, and BAL-validated import
rejecting a tampered list (reference seat:
crates/common/types/block_access_list.rs, blockchain.rs:552)."""

import dataclasses
import os

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.primitives.bal import (AccountChanges, BlockAccessList)
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import Transaction
from ethrex_tpu.blockchain.blockchain import Blockchain, InvalidBlock
from ethrex_tpu.node import Node

SECRET = 0xA11CE
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
OTHER = bytes.fromhex("44" * 20)
# reads slot 1, then sstore(0, calldataload(0))
CODE = bytes.fromhex("60015450" + "6000355f5500")
CONTRACT = bytes.fromhex("c0de" * 10)

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {
        "0x" + SENDER.hex(): {"balance": hex(10**21)},
        "0x" + CONTRACT.hex(): {"balance": "0x0",
                                "code": "0x" + CODE.hex(),
                                "storage": {hex(1): hex(99)}},
    },
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _block():
    node = Node(Genesis.from_json(GENESIS))
    node.submit_transaction(Transaction(
        tx_type=2, chain_id=1337, nonce=0, max_priority_fee_per_gas=1,
        max_fee_per_gas=10**10, gas_limit=21000, to=OTHER,
        value=500).sign(SECRET))
    node.submit_transaction(Transaction(
        tx_type=2, chain_id=1337, nonce=1, max_priority_fee_per_gas=1,
        max_fee_per_gas=10**10, gas_limit=90_000, to=CONTRACT, value=0,
        data=(42).to_bytes(32, "big")).sign(SECRET))
    block = node.produce_block()
    assert len(block.body.transactions) == 2
    return node, block


def test_generation_and_contents():
    node, block = _block()
    parent = node.store.get_header(block.header.parent_hash)
    bal = node.chain.generate_bal(block, parent)
    by_addr = {ac.address: ac for ac in bal.accounts}
    # sender: nonce + balance change at both tx indices
    s = by_addr[SENDER]
    assert [i for i, _ in s.nonce_changes] == [1, 2]
    assert [i for i, _ in s.balance_changes] == [1, 2]
    assert s.nonce_changes[-1][1] == 2
    # recipient: balance at index 1
    r = by_addr[OTHER]
    assert r.balance_changes == [(1, 500)]
    # contract: slot 0 written at index 2, slot 1 read-only
    c = by_addr[CONTRACT]
    assert c.storage_changes == {0: [(2, 42)]}
    assert c.storage_reads == {1}
    # coinbase collects tips
    cb = by_addr[block.header.coinbase]
    assert [i for i, _ in cb.balance_changes] == [1, 2]


def test_rlp_roundtrip_and_ordering():
    node, block = _block()
    parent = node.store.get_header(block.header.parent_hash)
    bal = node.chain.generate_bal(block, parent)
    wire = bal.encode()
    back = BlockAccessList.decode(wire)
    back.validate_ordering()
    assert back.encode() == wire
    assert back.hash() == bal.hash()
    # out-of-order accounts are rejected
    shuffled = BlockAccessList(accounts=list(reversed(bal.accounts)))
    # (encode() canonicalizes; the decoder-side validator must reject a
    # hand-built unsorted list)
    if len(shuffled.accounts) > 1:
        with pytest.raises(ValueError, match="out of order"):
            BlockAccessList(
                accounts=list(reversed(sorted(
                    bal.accounts, key=lambda a: a.address)))
            ).validate_ordering()


def test_bal_validated_import_and_tamper_rejection():
    node, block = _block()
    parent = node.store.get_header(block.header.parent_hash)
    bal = node.chain.generate_bal(block, parent)

    # fresh store: BAL-validated import accepts the honest list
    from ethrex_tpu.storage.store import Store

    store = Store()
    store.init_genesis(Genesis.from_json(GENESIS))
    chain = Blockchain(store, node.config)
    chain.add_block(block, bal=bal)
    assert store.get_header(block.hash) is not None

    # tampered post-value: import must reject
    t = node.chain.generate_bal(block, parent)
    for ac in t.accounts:
        if ac.address == CONTRACT:
            ac.storage_changes[0] = [(2, 43)]
    store2 = Store()
    store2.init_genesis(Genesis.from_json(GENESIS))
    chain2 = Blockchain(store2, node.config)
    with pytest.raises(InvalidBlock, match="access list mismatch"):
        chain2.add_block(block, bal=t)

    # omitted read: also a mismatch (the claim must be exact)
    t2 = node.chain.generate_bal(block, parent)
    for ac in t2.accounts:
        if ac.address == CONTRACT:
            ac.storage_reads = set()
    store3 = Store()
    store3.init_genesis(Genesis.from_json(GENESIS))
    chain3 = Blockchain(store3, node.config)
    with pytest.raises(InvalidBlock, match="access list mismatch"):
        chain3.add_block(block, bal=t2)


@pytest.mark.skipif((os.cpu_count() or 1) < 2,
                    reason="single-core host: parallel prefetch cannot "
                           "beat sequential by construction")
def test_parallel_warm_import_beats_sequential():
    """On a multi-core host the BAL prefetch fan-out must not lose to a
    cold sequential import of the same block (and generally wins once
    trie walks dominate)."""
    import time

    node, block = _block()
    parent = node.store.get_header(block.header.parent_hash)
    bal = node.chain.generate_bal(block, parent)
    from ethrex_tpu.storage.store import Store

    def run(with_bal):
        store = Store()
        store.init_genesis(Genesis.from_json(GENESIS))
        chain = Blockchain(store, node.config)
        t0 = time.perf_counter()
        chain.add_block(block, bal=bal if with_bal else None)
        return time.perf_counter() - t0

    cold = min(run(False) for _ in range(3))
    warm = min(run(True) for _ in range(3))
    assert warm < cold * 1.5


def test_padded_reads_rejected():
    """A BAL padded with bogus storage_reads must NOT self-certify via
    the warming path's journaled loads (review finding)."""
    node, block = _block()
    parent = node.store.get_header(block.header.parent_hash)
    bal = node.chain.generate_bal(block, parent)
    for ac in bal.accounts:
        if ac.address == CONTRACT:
            ac.storage_reads = set(ac.storage_reads) | {777, 888}
    from ethrex_tpu.storage.store import Store

    store = Store()
    store.init_genesis(Genesis.from_json(GENESIS))
    chain = Blockchain(store, node.config)
    with pytest.raises(InvalidBlock, match="access list mismatch"):
        chain.add_block(block, bal=bal)


def test_shared_withdrawal_address_single_index():
    """Two withdrawals to one address must yield ONE post-exec balance
    change entry (duplicate indices would fail ordering validation on an
    honest BAL — review finding)."""
    from ethrex_tpu.blockchain.payload import (build_payload,
                                               create_payload_header)
    from ethrex_tpu.primitives.block import Withdrawal
    from ethrex_tpu.storage.store import Store

    store = Store()
    genesis = Genesis.from_json(GENESIS)
    gh = store.init_genesis(genesis)
    chain = Blockchain(store, genesis.config)
    wds = [Withdrawal(index=0, validator_index=1, address=OTHER, amount=3),
           Withdrawal(index=1, validator_index=2, address=OTHER, amount=4)]
    header = create_payload_header(gh, chain.config, timestamp=12,
                                   coinbase=b"\xee" * 20)
    result = build_payload(chain, gh, header, [], wds)
    bal = chain.generate_bal(result.block, gh)
    bal.validate_ordering()
    by_addr = {ac.address: ac for ac in bal.accounts}
    assert by_addr[OTHER].balance_changes == [(1, 7 * 10**9)]
    # and the BAL-validated import accepts it
    store2 = Store()
    store2.init_genesis(genesis)
    chain2 = Blockchain(store2, genesis.config)
    chain2.add_block(result.block, bal=bal)
