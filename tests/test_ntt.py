"""NTT / LDE vs a naive O(n^2) host DFT."""

import numpy as np
import jax.numpy as jnp

from ethrex_tpu.ops import babybear as bb
from ethrex_tpu.ops import ntt

RNG = np.random.default_rng(1)


def _naive_dft(x, root):
    n = len(x)
    w = [pow(root, i, bb.P) for i in range(n)]
    return np.array(
        [sum(int(x[j]) * w[(i * j) % n] for j in range(n)) % bb.P for i in range(n)],
        dtype=np.uint32,
    )


def test_ntt_matches_naive():
    for log_n in (1, 3, 6):
        n = 1 << log_n
        x = RNG.integers(0, bb.P, size=n, dtype=np.uint32)
        root = bb.root_of_unity(log_n)
        expect = _naive_dft(x, root)
        got = np.asarray(bb.from_mont(ntt.ntt(bb.to_mont(jnp.asarray(x)))))
        np.testing.assert_array_equal(got, expect)


def test_ntt_roundtrip_batched():
    x = RNG.integers(0, bb.P, size=(5, 256), dtype=np.uint32)
    xm = bb.to_mont(jnp.asarray(x))
    back = np.asarray(bb.from_mont(ntt.intt(ntt.ntt(xm))))
    np.testing.assert_array_equal(back, x)


def test_coset_lde_extends_polynomial():
    # LDE of degree<n evals must agree with direct evaluation on the coset
    log_n, log_blowup = 4, 2
    n = 1 << log_n
    coeffs = RNG.integers(0, bb.P, size=n, dtype=np.uint32)

    def horner(cs, x):
        acc = 0
        for c in reversed([int(v) for v in cs]):
            acc = (acc * x + c) % bb.P
        return acc

    root = bb.root_of_unity(log_n)
    evals = np.array(
        [horner(coeffs, pow(root, i, bb.P)) for i in range(n)], dtype=np.uint32
    )
    got = np.asarray(
        bb.from_mont(ntt.coset_lde(bb.to_mont(jnp.asarray(evals)), log_blowup))
    )
    big_root = bb.root_of_unity(log_n + log_blowup)
    shift = bb.GENERATOR
    expect = np.array(
        [
            horner(coeffs, shift * pow(big_root, i, bb.P) % bb.P)
            for i in range(n << log_blowup)
        ],
        dtype=np.uint32,
    )
    np.testing.assert_array_equal(got, expect)


def test_eval_poly_at():
    coeffs = RNG.integers(0, bb.P, size=33, dtype=np.uint32)
    pt = 123456789
    got = int(
        bb.from_mont(
            ntt.eval_poly_at(
                bb.to_mont(jnp.asarray(coeffs)),
                bb.to_mont(jnp.asarray(np.uint32(pt))),
            )
        )
    )
    acc = 0
    for c in reversed([int(v) for v in coeffs]):
        acc = (acc * pt + c) % bb.P
    assert got == acc
