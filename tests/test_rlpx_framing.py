"""RLPx frame codec conformance: spec MAC construction, prefix-free wire
layout, and the snappy message compression (devp2p spec; reference:
crates/networking/p2p/rlpx/connection/codec.rs)."""

import os

import pytest

try:
    from cryptography.hazmat.primitives.ciphers import (Cipher, algorithms,
                                                        modes)
except ModuleNotFoundError:
    # same fallback the production stack uses; the conformance vectors
    # below still hold (crypto/aes.py is NIST-vector checked)
    from ethrex_tpu.crypto.aes import Cipher, algorithms, modes

from ethrex_tpu.crypto.keccak import IncrementalKeccak256
from ethrex_tpu.p2p import rlpx
from ethrex_tpu.primitives import rlp
from ethrex_tpu.utils import snappy


def _pair():
    """Two Secrets with mirrored seeds (what derive_secrets produces for
    the two ends of one session)."""
    aes = bytes(range(32))
    mac = bytes(range(32, 64))
    seed_a = b"\xaa" * 32
    seed_b = b"\xbb" * 32
    alice = rlpx.Secrets(aes, mac, egress_seed=seed_a, ingress_seed=seed_b)
    bob = rlpx.Secrets(aes, mac, egress_seed=seed_b, ingress_seed=seed_a)
    return alice, bob


def test_frame_roundtrip_and_wire_layout():
    alice, bob = _pair()
    payload = b"\x01\x02\x03" * 100
    frame = alice.seal_frame(0x10, payload)
    # spec layout: header-ct(16) + header-mac(16) + padded-frame + mac(16)
    frame_size = len(rlp.encode(0x10)) + len(payload)
    padded = frame_size + ((16 - frame_size % 16) % 16)
    assert len(frame) == 32 + padded + 16
    # streaming open: header first, then exactly body_len bytes
    size = bob.open_header(frame[:32])
    assert size == frame_size
    assert bob.body_len(size) == len(frame) - 32
    msg_id, got = bob.open_body(size, frame[32:])
    assert (msg_id, got) == (0x10, payload)


def test_header_mac_matches_spec_formula():
    """Recompute the first header MAC independently from the devp2p spec:
      header-mac-seed = aes(mac-secret, keccak(egress-mac)[:16]) ^ hdr-ct
      egress-mac     += header-mac-seed
      header-mac      = keccak(egress-mac)[:16]
    """
    aes = bytes(range(32))
    mac = bytes(range(32, 64))
    seed = b"\xcc" * 32
    secrets = rlpx.Secrets(aes, mac, egress_seed=seed,
                           ingress_seed=b"\x00" * 32)
    frame = secrets.seal_frame(0x01, b"hello")
    header_ct, header_mac = frame[:16], frame[16:32]

    sponge = IncrementalKeccak256()
    sponge.update(seed)
    prev = sponge.digest()[:16]
    ecb = Cipher(algorithms.AES(mac), modes.ECB()).encryptor()
    mseed = bytes(a ^ b for a, b in zip(ecb.update(prev), header_ct))
    sponge.update(mseed)
    assert sponge.digest()[:16] == header_mac


def test_frame_mac_matches_spec_formula():
    aes = bytes(range(32))
    mac = bytes(range(32, 64))
    seed = b"\xdd" * 32
    secrets = rlpx.Secrets(aes, mac, egress_seed=seed,
                           ingress_seed=b"\x00" * 32)
    payload = b"x" * 40
    frame = secrets.seal_frame(0x02, payload)
    frame_size = len(rlp.encode(0x02)) + len(payload)
    padded = frame_size + ((16 - frame_size % 16) % 16)
    header_ct = frame[:16]
    frame_ct = frame[32:32 + padded]
    frame_mac = frame[32 + padded:]

    sponge = IncrementalKeccak256()
    sponge.update(seed)
    ecb = Cipher(algorithms.AES(mac), modes.ECB()).encryptor()
    # header step
    prev = sponge.digest()[:16]
    sponge.update(bytes(a ^ b
                        for a, b in zip(ecb.update(prev), header_ct)))
    sponge.digest()
    # frame step: absorb ct, then whiten with the digest itself
    sponge.update(frame_ct)
    d = sponge.digest()[:16]
    sponge.update(bytes(a ^ b for a, b in zip(ecb.update(d), d)))
    assert sponge.digest()[:16] == frame_mac


def test_tampered_frame_rejected():
    alice, bob = _pair()
    frame = bytearray(alice.seal_frame(0x10, b"payload-bytes"))
    frame[40] ^= 1
    with pytest.raises(rlpx.RlpxError):
        size = bob.open_header(bytes(frame[:32]))
        bob.open_body(size, bytes(frame[32:]))


def test_snappy_roundtrips():
    cases = [
        b"",
        b"a",
        b"hello world " * 100,           # compressible
        os.urandom(3000),                # incompressible
        bytes(range(256)) * 300,
        b"\x00" * 70000,                 # long runs, >64-byte copies
    ]
    for data in cases:
        enc = snappy.compress(data)
        assert snappy.decompress(enc) == data
    # compressible input actually compresses
    rep = b"block gossip payload " * 200
    assert len(snappy.compress(rep)) < len(rep) // 2


def test_snappy_rejects_bad_streams():
    with pytest.raises(snappy.SnappyError):
        snappy.decompress(b"\xff\xff\xff\xff\xff\x00")  # huge preamble
    with pytest.raises(snappy.SnappyError):
        # copy with offset beyond output
        snappy.decompress(bytes([4]) + bytes([0x02, 0x10, 0x00]))
    with pytest.raises(snappy.SnappyError):
        # declared length mismatch
        snappy.decompress(bytes([5]) + bytes([0x00]) + b"a")
    with pytest.raises(snappy.SnappyError):
        snappy.decompress(snappy.compress(b"x" * 2000), max_len=100)


def test_connection_messages_are_snappy_compressed():
    """Peers negotiate p2p v5 in Hello and compress every later message."""
    from ethrex_tpu.node import Node
    from ethrex_tpu.p2p.connection import P2PServer
    from ethrex_tpu.primitives.genesis import Genesis
    from tests.test_l2_pipeline import GENESIS

    a = Node(Genesis.from_json(GENESIS))
    b = Node(Genesis.from_json(GENESIS))
    sa = P2PServer(a).start()
    sb = P2PServer(b).start()
    try:
        peer = sa.dial(sb.host, sb.port, sb.pub)
        assert peer.snappy_active
        # a round-trip request works over compressed frames
        headers = peer.get_block_headers(0, 1)
        assert headers and headers[0].number == 0
    finally:
        sa.stop()
        sb.stop()
