"""Mesh sharding: the pjit'd phase-program path of stark/prover.py and
the slice-parallel backend proving of prover/tpu_backend.py.

Two invariants are locked here:

1. Sharding is layout-only.  All prover arithmetic is exact u32 work,
   so a proof produced on an N-device mesh must be BYTE-identical to
   the single-device proof — same Merkle roots, same FRI openings,
   same verifier outcome.  The differential tests assert full
   JSON-equality of the proof dicts (conftest.py forces 8 virtual CPU
   devices via --xla_force_host_platform_device_count).
2. The split_mesh slice policy (parallel/mesh.py): min(jobs, devices)
   contiguous disjoint slices, every device used, sizes within one.

The fast tier keeps one cheap differential (Fibonacci) plus the pure
unit tests; the per-AIR sweep over the heavier circuits is slow-marked
(mesh phase programs are fresh XLA SPMD compiles).
"""

import json

import numpy as np
import pytest

from ethrex_tpu.ops import babybear as bb
from ethrex_tpu.parallel import mesh as mesh_lib
from ethrex_tpu.stark import prover as stark_prover
from ethrex_tpu.stark import verifier as stark_verifier
from ethrex_tpu.stark.prover import StarkParams

RNG = np.random.default_rng(11)


# ---------------------------------------------------------------------------
# split_mesh / sharding_for unit tests (pure host work)

def test_split_mesh_policy_odd_counts():
    """3 AIRs (jobs) on 2/4/8 devices: slice sizes within one, earlier
    slices take the extra device, every device used exactly once, in
    order."""
    expect = {2: [1, 1], 4: [2, 1, 1], 8: [3, 3, 2]}
    for ndev, sizes in expect.items():
        m = mesh_lib.make_mesh(ndev)
        slices = mesh_lib.split_mesh(m, 3)
        got = [len(list(s.devices.flat)) for s in slices]
        assert got == sizes, (ndev, got)
        flat = [d.id for s in slices for d in s.devices.flat]
        assert flat == [d.id for d in m.devices.flat], \
            "slices must be disjoint, contiguous and cover the mesh"
        for s in slices:
            assert s.axis_names == (mesh_lib.AXIS,)


def test_split_mesh_degenerate_cases():
    m8 = mesh_lib.make_mesh(8)
    # 1 job -> the whole mesh, unchanged
    assert mesh_lib.split_mesh(m8, 1) == [m8]
    # more jobs than devices -> one slice per device, never more
    assert [len(list(s.devices.flat))
            for s in mesh_lib.split_mesh(m8, 12)] == [1] * 8
    # 1 device -> serial fallback regardless of job count
    m1 = mesh_lib.make_mesh(1)
    assert mesh_lib.split_mesh(m1, 5) == [m1]


def test_sharding_for_drops_ragged_axes():
    """The shared partition-or-replicate policy: an AXIS entry survives
    only when the dimension splits evenly across the mesh."""
    from jax.sharding import PartitionSpec

    m4 = mesh_lib.make_mesh(4)
    A = mesh_lib.AXIS
    keep = mesh_lib.sharding_for(m4, (64, 8), (A, None))
    assert keep.spec == PartitionSpec(A, None)
    # 6 % 4 != 0 -> replicated
    ragged = mesh_lib.sharding_for(m4, (6, 8), (A, None))
    assert ragged.spec == PartitionSpec(None, None)
    # dim < ndev -> replicated
    small = mesh_lib.sharding_for(m4, (2, 8), (A, None))
    assert small.spec == PartitionSpec(None, None)
    # 1-device mesh shards nothing
    m1 = mesh_lib.make_mesh(1)
    none = mesh_lib.sharding_for(m1, (64, 8), (A, None))
    assert none.spec == PartitionSpec(None, None)


def test_mesh_key_distinguishes_layouts():
    """_mesh_key must separate no-mesh, different sizes and different
    device subsets — a stale compiled program must never be served
    across a mesh switch."""
    k_none = stark_prover._mesh_key(None)
    k2 = stark_prover._mesh_key(mesh_lib.make_mesh(2))
    k4 = stark_prover._mesh_key(mesh_lib.make_mesh(4))
    k4b = stark_prover._mesh_key(mesh_lib.make_mesh(4))
    sub = stark_prover._mesh_key(
        mesh_lib.split_mesh(mesh_lib.make_mesh(4), 2)[1])
    assert k_none is None
    assert len({k2, k4, sub}) == 3
    assert k4 == k4b, "identical layout must hit the cache"


def test_history_series_excludes_scaling_records(monkeypatch):
    """bench gate hygiene: records carrying a scaling sweep or a non-1
    devices field must stay out of the same-backend history series."""
    from ethrex_tpu.perf import bench_suite

    rows = [
        {"backend": "cpu", "metric": "m", "value": 1.0},
        {"backend": "cpu", "metric": "m", "value": 9.0, "devices": 8},
        {"backend": "cpu", "metric": "m", "value": 7.0,
         "scaling": {"1": {}}},
        {"backend": "cpu", "metric": "m", "value": 2.0, "devices": 1},
    ]
    monkeypatch.setattr(bench_suite, "_read_history", lambda: rows)
    assert bench_suite._history_series("m") == [("cpu", 1.0),
                                                ("cpu", 2.0)]


# ---------------------------------------------------------------------------
# sharded-vs-single differential proving

FAST_PARAMS = StarkParams(log_blowup=2, num_queries=16, log_final_size=4)


def _fib_case():
    from ethrex_tpu.models import fibonacci as fib

    air = fib.FibonacciAir()
    trace = fib.generate_trace(64)
    return air, trace, fib.public_inputs(trace), FAST_PARAMS


def _sponge_case():
    from ethrex_tpu.models import poseidon2_air as pair

    msg = [int(v) for v in RNG.integers(0, bb.P, 17)]
    air = pair.Poseidon2SpongeAir(num_chunks=3)
    trace = pair.generate_sponge_trace(msg)
    pub = pair.sponge_public_inputs(msg)
    return air, trace, pub, StarkParams(log_blowup=3, num_queries=25,
                                        log_final_size=4)


def _poseidon2_case():
    from ethrex_tpu.models import poseidon2_air as pair

    limbs = [int(v) for v in RNG.integers(0, bb.P, 8)]
    air = pair.Poseidon2Air()
    trace = pair.generate_trace(limbs)
    pub = pair.public_inputs(limbs)
    return air, trace, pub, StarkParams(log_blowup=3, num_queries=25,
                                        log_final_size=4)


def _merkle_case():
    from ethrex_tpu.models import merkle_air as mair
    from ethrex_tpu.ops.merkle import fold_path_canonical

    depth = 1
    leaf = [int(v) for v in RNG.integers(0, bb.P, 8)]
    siblings = [[int(v) for v in RNG.integers(0, bb.P, 8)]
                for _ in range(depth)]
    bits = [0]
    root = fold_path_canonical(0, leaf, siblings)
    air = mair.Poseidon2MerkleAir(depth)
    trace = mair.generate_merkle_trace(leaf, siblings, bits)
    pub = mair.merkle_public_inputs(leaf, root)
    return air, trace, pub, StarkParams(log_blowup=3, num_queries=25,
                                        log_final_size=4)


def _state_update_case():
    from ethrex_tpu.models import state_update_air as sua
    from ethrex_tpu.stark import state_tree

    rng = np.random.default_rng(3)

    def word(tag):
        return bytes(rng.integers(0, 256, 32, dtype=np.uint8))

    entries = {word(i): word(i + 100) for i in range(4)}
    tree = state_tree.TouchedStateTree(entries, 2)
    r_pre = tree.root
    keys = list(entries)
    accesses = [tree.update(keys[int(rng.integers(0, len(keys)))],
                            word(w + 200)) for w in range(3)]
    depth, S = 2, 8
    air = sua.StateUpdateAir(depth, seg_periods=S)
    trace = sua.generate_state_update_trace(accesses, r_pre, depth, S)
    pub = sua.state_update_public_inputs(accesses, r_pre, tree.root, S)
    return air, trace, pub, StarkParams(log_blowup=3, num_queries=25,
                                        log_final_size=4)


def _transfer_case():
    from ethrex_tpu.models import transfer_air as ta
    from ethrex_tpu.primitives.account import AccountState

    value, fee, tip = 1000, 21000 * 7, 21000 * 2
    s_old = AccountState(nonce=4, balance=10**18)
    s_new = AccountState(nonce=5, balance=10**18 - value - fee)
    r_old = AccountState(nonce=1, balance=500)
    r_new = AccountState(nonce=1, balance=500 + value)
    tx = ta.TxSeg(bytes.fromhex("11" * 20), bytes.fromhex("22" * 20),
                  s_old, s_new, r_old, r_new, value, fee, tip,
                  r_created=False, r_noop=False)
    air = ta.TransferAir()
    trace = ta.generate_transfer_trace([tx])
    pub = ta.transfer_public_inputs([tx])
    return air, trace, pub, StarkParams(log_blowup=3, num_queries=25,
                                        log_final_size=4)


def _token_case():
    from ethrex_tpu.guest.transfer_log import TokSeg
    from ethrex_tpu.models import token_air as tka

    v1 = 12345
    kf = int.from_bytes(b"\x11" * 32, "big")
    kt = int.from_bytes(b"\x22" * 32, "big")
    segs = [TokSeg(v1, kf, 10**6, 10**6 - v1, kt, 500, 500 + v1),
            TokSeg(0, 0, 0, 0, 0, 0, 0, noop=True)]
    air = tka.TokenAir()
    trace = tka.generate_token_trace(segs)
    pub = tka.token_public_inputs(segs)
    return air, trace, pub, StarkParams(log_blowup=3, num_queries=25,
                                        log_final_size=4)


def _assert_differential(case):
    air, trace, pub, params = case()
    single = stark_prover.prove(air, trace, pub, params)
    sharded = stark_prover.prove(air, trace, pub, params,
                                 mesh=mesh_lib.make_mesh(4))
    # byte-identical: same roots, same FRI layers, same query openings
    assert json.dumps(single, sort_keys=True) == \
        json.dumps(sharded, sort_keys=True)
    assert stark_verifier.verify(air, sharded, params)


@pytest.mark.slow
def test_sharded_prove_bit_identical_fibonacci():
    _assert_differential(_fib_case)


@pytest.mark.slow
@pytest.mark.parametrize("case", [
    _sponge_case, _poseidon2_case, _merkle_case, _state_update_case,
    _transfer_case, _token_case,
], ids=["sponge", "poseidon2", "merkle", "state_update", "transfer",
        "token"])
def test_sharded_prove_bit_identical(case):
    _assert_differential(case)


@pytest.mark.slow
def test_sharded_prove_bit_identical_bytecode():
    from ethrex_tpu.guest import bytecode_vm as bv
    from ethrex_tpu.models import bytecode_air as bca

    # registry-with-guard contract, store branch — mirrors the
    # test_bytecode_vm prove recipe
    code = bytes([
        0x60, 0x00, 0x35, 0x60, 0x20, 0x35, 0x80, 0x82, 0x54, 0x10,
        0x61, 0x00, 0x14, 0x57, 0x61, 0x03, 0xE8, 0x55, 0x50, 0x00,
        0x5B, 0x90, 0x55, 0x00,
    ])
    cd = (5).to_bytes(32, "big") + (42).to_bytes(32, "big")
    pre = {5: 10}
    steps, snaps, _writes = bv.run_trace(code, cd, b"\x11" * 20, 0,
                                         lambda s: pre.get(s, 0))
    air = bca.BytecodeAir()
    trace = bca.generate_bytecode_trace(steps, snaps)
    pub = bca.bytecode_public_inputs(steps)
    params = StarkParams(log_blowup=3, num_queries=40, log_final_size=4)
    single = stark_prover.prove(air, trace, pub, params)
    sharded = stark_prover.prove(air, trace, pub, params,
                                 mesh=mesh_lib.make_mesh(4))
    assert json.dumps(single, sort_keys=True) == \
        json.dumps(sharded, sort_keys=True)


@pytest.mark.slow
def test_aggregate_outer_proof_accepts_mesh():
    """FriVerifyAir differential through the aggregation entry point:
    the outer recursion proof must be byte-identical with and without a
    mesh, and verify_aggregated must accept the mesh-built aggregate."""
    from ethrex_tpu.models.fibonacci import FibonacciAir, generate_trace
    from ethrex_tpu.stark import aggregate

    params = StarkParams(log_blowup=2, num_queries=2, log_final_size=4)
    airs, proofs = [], []
    for i in range(2):
        air = FibonacciAir()
        trace = generate_trace(16, a0=1, b0=2 + i)
        pub = [1, 2 + i, int(trace[-1, 1])]
        proofs.append(stark_prover.prove(air, trace, pub, params))
        airs.append(air)
    outer_params = StarkParams(log_blowup=3, num_queries=8,
                               log_final_size=4)
    plain = aggregate.aggregate(airs, proofs, params, outer_params)
    meshed = aggregate.aggregate(airs, proofs, params, outer_params,
                                 mesh=mesh_lib.make_mesh(4))
    assert json.dumps(plain.outer, sort_keys=True) == \
        json.dumps(meshed.outer, sort_keys=True)
    assert aggregate.verify_aggregated(airs, meshed, params,
                                       outer_params)


# ---------------------------------------------------------------------------
# phase-cache staleness + retrace accounting

def _retrace_counts():
    from ethrex_tpu.utils.metrics import METRICS

    snap = METRICS.snapshot()
    fam = snap.get("labeled_counters", {}).get(
        "prover_kernel_retraces_total", [])
    return {row["labels"].get("mesh"): row["value"] for row in fam}


@pytest.mark.slow
def test_phase_cache_mesh_switches_never_stale():
    """no-mesh -> mesh(2) -> no-mesh -> mesh(4) -> mesh(2) again on one
    AIR shape: every proof byte-identical, each NEW layout is a counted
    retrace (labelled with its mesh shape), and revisiting a layout is
    a cache hit (no extra retrace)."""
    air, trace, pub, params = _fib_case()
    m2 = mesh_lib.make_mesh(2)
    m4 = mesh_lib.make_mesh(4)

    before = _retrace_counts()
    ref = stark_prover.prove(air, trace, pub, params)
    for mesh in (m2, None, m4, m2):
        proof = stark_prover.prove(air, trace, pub, params, mesh=mesh)
        assert json.dumps(proof, sort_keys=True) == \
            json.dumps(ref, sort_keys=True)
    after = _retrace_counts()

    def delta(label):
        return after.get(label, 0) - before.get(label, 0)

    # one build per distinct layout at most (zero when a previous test
    # in this process already compiled it), never one per prove
    assert delta("2") <= 1
    assert delta("4") <= 1
    assert delta("none") <= 1
    # the second mesh(2) prove and the second no-mesh prove were hits:
    # 5 proves, at most 3 builds
    total = sum(after.values()) - sum(before.values())
    assert total <= 3


# ---------------------------------------------------------------------------
# slice-parallel proof jobs (backend machinery)

@pytest.mark.slow
def test_parallel_proof_jobs_bit_identical_and_instrumented():
    """_run_proof_jobs: the mesh-sliced concurrent path returns the
    same proofs as the serial path, publishes the mesh gauges, and
    records per-slice vm_circuits/<air> stage walls."""
    from ethrex_tpu.prover.tpu_backend import _run_proof_jobs
    from ethrex_tpu.utils import tracing
    from ethrex_tpu.utils.metrics import METRICS

    air, trace, pub, params = _fib_case()

    def mk(name, group):
        def job(job_mesh):
            return stark_prover.prove(air, trace, pub, params,
                                      mesh=job_mesh)
        return (name, group, job)

    jobs = [mk("state_proof", "state_proof"),
            mk("vm_circuits/TransferAir", "vm_circuits"),
            mk("vm_circuits/TokenAir", "vm_circuits")]
    with tracing.span("backend.prove"):
        serial = _run_proof_jobs(jobs, None)
    snap = METRICS.snapshot()
    assert snap["gauges"]["prover_mesh_devices"] == 1.0
    assert snap["gauges"]["prover_vm_circuits_parallel"] == 1.0

    with tracing.span("backend.prove"):
        par = _run_proof_jobs(jobs, mesh_lib.make_mesh(2))
    assert json.dumps(serial, sort_keys=True) == \
        json.dumps(par, sort_keys=True)
    snap = METRICS.snapshot()
    assert snap["gauges"]["prover_mesh_devices"] == 2.0
    assert snap["gauges"]["prover_vm_circuits_parallel"] == 2.0
    hist = snap["histograms"].get("prover_stage_seconds", {})
    stages = {row["labels"]["stage"] for row in hist.get("series", [])}
    assert {"state_proof", "vm_circuits", "vm_circuits/TransferAir",
            "vm_circuits/TokenAir"} <= stages


def test_perf_rpc_exposes_mesh_gauges():
    """ethrex_perf carries the mesh section next to throughput."""
    from ethrex_tpu.rpc import server as rpc_server
    from ethrex_tpu.utils.metrics import (record_mesh_devices,
                                          record_vm_parallelism)

    record_mesh_devices(4)
    record_vm_parallelism(3)
    out = rpc_server._perf(None)
    assert out["mesh"]["devices"] == 4.0
    assert out["mesh"]["vmCircuitsParallel"] == 3.0
