"""L1 settlement chaos battery (docs/L1_SETTLEMENT_RESILIENCE.md):
crash-window replays around the L1-first commit ordering, idempotent
commit adoption when the acknowledgment is lost, L1 reorg drills
(commit and verify transactions dropped, shallow deposits), and a
flaky-L1 soak where the pipeline must fully settle without ever going
fatal.  Every fault is driven by the seeded FaultPlan sites `l1.commit`,
`l1.verify`, `l1.get_deposits`.

Select alone with `-m chaos`; the whole battery is in the fast tier.
"""

import time

import pytest

from ethrex_tpu.guest.execution import ProgramInput
from ethrex_tpu.l2.l1_client import InMemoryL1, PersistentInMemoryL1
from ethrex_tpu.l2.rollup_store import PersistentRollupStore, RollupStore
from ethrex_tpu.l2.sequencer import (Sequencer, SequencerConfig,
                                     SettlementDivergence)
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover.backend import get_backend
from ethrex_tpu.prover.client import ProverClient
from ethrex_tpu.storage.persistent import PersistentBackend
from ethrex_tpu.storage.store import Store
from ethrex_tpu.utils import faults
from ethrex_tpu.utils.faults import FaultPlan
from tests.test_l2_pipeline import GENESIS, _transfer

pytestmark = pytest.mark.chaos

CFG = SequencerConfig(needed_prover_types=(protocol.PROVER_EXEC,))


def _open_node(tmp_path):
    store = Store(PersistentBackend(str(tmp_path / "chain.db")))
    return Node(Genesis.from_json(GENESIS), store=store)


def _prove(seq, number):
    """Prove one committed batch directly with the exec backend (no
    coordinator round-trip; the chaos here targets the L1 legs)."""
    backend = get_backend(protocol.PROVER_EXEC)
    stored = seq.rollup.get_prover_input(number, seq.cfg.commit_hash)
    assert stored is not None, f"batch {number} has no prover input"
    proof = backend.prove(ProgramInput.from_json(stored),
                          protocol.FORMAT_STARK)
    seq.rollup.store_proof(number, protocol.PROVER_EXEC, proof)


def _settle(seq, l1):
    """Prove every committed batch and drive send_proofs until the L1
    has verified up to the local latest batch."""
    latest = seq.rollup.latest_batch_number()
    for n in range(l1.last_verified_batch() + 1, latest + 1):
        if seq.rollup.get_proof(n, protocol.PROVER_EXEC) is None:
            _prove(seq, n)
    seq.send_proofs()
    assert l1.last_verified_batch() == latest


# ===========================================================================
# crash windows: L1 accepted the commit, the process died before (some of)
# the local persistence ran
# ===========================================================================

@pytest.mark.parametrize("died_at", ["store_batch", "store_blobs_bundle",
                                     "store_prover_input", "set_committed"])
def test_commit_crash_window_reconciled_on_restart(tmp_path, died_at):
    """Kill the sequencer after l1.commit_batch but before `died_at`
    persisted; restart on the same stores.  Startup reconciliation must
    rebuild/repair the batch record from the canonical chain, adopt the
    settled flags, never re-commit, and the batch must still settle to
    fully verified."""
    path = str(tmp_path / "rollup.db")
    l1path = str(tmp_path / "l1.json")
    node = _open_node(tmp_path)
    l1 = PersistentInMemoryL1(l1path, [protocol.PROVER_EXEC])
    rollup = PersistentRollupStore(path)
    seq = Sequencer(node, l1, CFG, rollup=rollup)
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    node.store.flush()

    class Killed(RuntimeError):
        pass

    def dying(*a, **kw):
        raise Killed(f"process died at rollup.{died_at}")

    setattr(rollup, died_at, dying)
    with pytest.raises(Killed):
        seq.commit_next_batch()
    # the commit tx mined before the crash
    assert l1.last_committed_batch() == 1
    rollup.close()
    node.store.backend.close()

    node2 = _open_node(tmp_path)
    l1b = PersistentInMemoryL1(l1path, [protocol.PROVER_EXEC])
    rollup2 = PersistentRollupStore(path)
    seq2 = Sequencer(node2, l1b, CFG, rollup=rollup2)
    # the lost/partial record was rebuilt and adopted as committed
    assert rollup2.latest_batch_number() == 1
    b = rollup2.get_batch(1)
    assert b.committed
    assert l1b.get_committed_commitment(1) == b.commitment
    assert rollup2.get_prover_input(1, CFG.commit_hash) is not None
    assert rollup2.get_blobs_bundle(1) is not None
    assert seq2.rebuilt_batches_total >= (0 if died_at == "set_committed"
                                          else 1)
    # no duplicate commit: the L1 still holds exactly one batch
    assert seq2.commit_next_batch() is None
    assert l1b.last_committed_batch() == 1
    assert seq2.last_batched_block == node2.store.latest_number()
    # and it settles end-to-end
    _settle(seq2, l1b)
    assert l1b.last_verified_batch() == rollup2.latest_batch_number() == 1
    rollup2.close()
    node2.store.backend.close()


def test_crash_window_survives_second_restart(tmp_path):
    """The reconciled state is durable: a second clean restart sees a
    complete record and reconciliation is a no-op."""
    path = str(tmp_path / "rollup.db")
    l1path = str(tmp_path / "l1.json")
    node = _open_node(tmp_path)
    l1 = PersistentInMemoryL1(l1path, [protocol.PROVER_EXEC])
    rollup = PersistentRollupStore(path)
    seq = Sequencer(node, l1, CFG, rollup=rollup)
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    node.store.flush()
    rollup.store_batch = lambda *a, **kw: (_ for _ in ()).throw(
        RuntimeError("killed"))
    with pytest.raises(RuntimeError):
        seq.commit_next_batch()
    rollup.close()
    node.store.backend.close()

    node2 = _open_node(tmp_path)
    rollup2 = PersistentRollupStore(path)
    seq2 = Sequencer(node2, PersistentInMemoryL1(
        l1path, [protocol.PROVER_EXEC]), CFG, rollup=rollup2)
    assert seq2.rebuilt_batches_total == 1
    rollup2.close()
    node2.store.backend.close()

    node3 = _open_node(tmp_path)
    rollup3 = PersistentRollupStore(path)
    seq3 = Sequencer(node3, PersistentInMemoryL1(
        l1path, [protocol.PROVER_EXEC]), CFG, rollup=rollup3)
    assert seq3.rebuilt_batches_total == 0
    assert rollup3.get_batch(1).committed
    rollup3.close()
    node3.store.backend.close()


def test_divergent_local_commitment_fails_fast(tmp_path):
    """Same batch number, different commitment on the two sides: the
    sequencer must refuse to start rather than settle on a fork."""
    path = str(tmp_path / "rollup.db")
    node = _open_node(tmp_path)
    l1 = InMemoryL1([protocol.PROVER_EXEC])
    rollup = PersistentRollupStore(path)
    seq = Sequencer(node, l1, CFG, rollup=rollup)
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    batch = seq.commit_next_batch()
    assert batch is not None
    # corrupt the on-chain record (a fork's different batch 1)
    root, _ = l1.commitments[1]
    l1.commitments[1] = (root, b"\xde\xad" * 16)
    node.store.flush()
    rollup.close()
    node.store.backend.close()

    node2 = _open_node(tmp_path)
    rollup2 = PersistentRollupStore(path)
    with pytest.raises(SettlementDivergence):
        Sequencer(node2, l1, CFG, rollup=rollup2)
    rollup2.close()
    node2.store.backend.close()


# ===========================================================================
# idempotent commit: the two legs of the l1.commit fault site
# ===========================================================================

def _mini_l2():
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1([protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, CFG)
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    return node, l1, seq


def test_commit_request_lost_is_retried_not_adopted():
    """First leg: the commit request never reached the L1.  The retry
    must be a real commit, not an adoption."""
    node, l1, seq = _mini_l2()
    try:
        faults.install(FaultPlan(seed=3).drop("l1.commit", times=1))
        with pytest.raises(faults.InjectedFault):
            seq.commit_next_batch()
        assert l1.last_committed_batch() == 0
        assert seq.rollup.latest_batch_number() == 0
        faults.clear()
        batch = seq.commit_next_batch()
        assert batch is not None and batch.number == 1
        assert l1.last_committed_batch() == 1
        assert seq.commits_adopted_total == 0
    finally:
        faults.clear()


def test_commit_response_lost_is_adopted_not_duplicated():
    """Second leg (after=1 skips the request leg): the commit tx mined,
    the acknowledgment was lost before any local persistence.  The retry
    must detect the matching on-chain commitment and adopt it."""
    node, l1, seq = _mini_l2()
    try:
        faults.install(FaultPlan(seed=3).drop("l1.commit", times=1,
                                              after=1))
        with pytest.raises(faults.InjectedFault):
            seq.commit_next_batch()
        assert l1.last_committed_batch() == 1       # tx mined
        assert seq.rollup.latest_batch_number() == 0  # nothing persisted
        faults.clear()
        batch = seq.commit_next_batch()
        assert batch is not None and batch.number == 1
        assert seq.commits_adopted_total == 1
        assert l1.last_committed_batch() == 1       # no duplicate
        b = seq.rollup.get_batch(1)
        assert b.committed
        assert l1.get_committed_commitment(1) == b.commitment
        _settle(seq, l1)
    finally:
        faults.clear()


# ===========================================================================
# reorg drills
# ===========================================================================

def test_reorg_drops_commit_and_verify_then_recommitted():
    """A depth-2 reorg unwinds both the verify and the commit block.
    update_state detects the regression, rolls the flags back through the
    store, queues the batch; the committer re-submits it VERBATIM and the
    stored proof re-verifies without re-proving."""
    node, l1, seq = _mini_l2()
    assert seq.commit_next_batch().number == 1
    _settle(seq, l1)
    assert l1.last_verified_batch() == 1
    commitment = seq.rollup.get_batch(1).commitment

    l1.reorg(2)
    assert l1.last_committed_batch() == 0
    assert l1.last_verified_batch() == 0

    seq.update_state()
    assert seq.reorgs_total == 1
    b = seq.rollup.get_batch(1)
    assert not b.committed and not b.verified
    assert 1 in seq._recommit_queue

    batch = seq.commit_next_batch()     # drains the recommit queue first
    assert batch is not None and batch.number == 1
    assert batch.commitment == commitment   # verbatim re-submission
    assert seq.recommits_total == 1
    assert not seq._recommit_queue
    assert l1.last_committed_batch() == 1
    assert seq.rollup.get_batch(1).committed

    assert seq.send_proofs() == (1, 1)  # stored proof still valid
    assert l1.last_verified_batch() == 1
    seq.update_state()
    assert seq.rollup.get_batch(1).verified


def test_reorg_drops_verify_only_reverified():
    """A depth-1 reorg unwinds just the verify tx: the commitment
    survives, only the verified flag rolls back and send_proofs
    re-verifies."""
    node, l1, seq = _mini_l2()
    assert seq.commit_next_batch().number == 1
    _settle(seq, l1)

    l1.reorg(1)
    assert l1.last_committed_batch() == 1
    assert l1.last_verified_batch() == 0

    seq.update_state()
    assert seq.reorgs_total == 1
    b = seq.rollup.get_batch(1)
    assert b.committed and not b.verified
    assert not seq._recommit_queue      # commit still settled

    assert seq.send_proofs() == (1, 1)
    assert l1.last_verified_batch() == 1


def test_reorg_then_new_batches_settle_in_order():
    """After a recommit the pipeline keeps going: new blocks batch and
    settle on top of the re-settled batch."""
    node, l1, seq = _mini_l2()
    assert seq.commit_next_batch().number == 1
    _settle(seq, l1)
    l1.reorg(2)
    seq.update_state()
    assert seq.commit_next_batch().number == 1      # recommit
    node.submit_transaction(_transfer(1))
    seq.produce_block()
    batch2 = seq.commit_next_batch()
    assert batch2 is not None and batch2.number == 2
    assert l1.last_committed_batch() == 2
    _settle(seq, l1)
    assert l1.last_verified_batch() == 2


def test_shallow_deposit_not_ingested_before_confirmation():
    """With l1_confirmation_depth=3, a fresh deposit (1 confirmation) is
    not ingested; a reorg that drops it mints nothing; once re-deposited
    and matured past the depth it is ingested exactly once."""
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1([protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,),
        l1_confirmation_depth=3))
    l1.deposit(b"\x61" * 20, 1000)
    seq.watch_l1()
    assert not seq.pending_privileged   # 1 confirmation < 3

    l1.reorg(1)                         # the deposit block reorgs out
    assert not l1.deposits
    seq.watch_l1()
    assert not seq.pending_privileged   # nothing minted from the orphan

    l1.deposit(b"\x61" * 20, 1000)
    seq.watch_l1()
    assert not seq.pending_privileged   # still shallow
    l1.advance_blocks(2)                # now 3 confirmations deep
    seq.watch_l1()
    assert len(seq.pending_privileged) == 1
    seq.watch_l1()
    assert len(seq.pending_privileged) == 1  # cursor advanced, no dup


# ===========================================================================
# flaky-L1 soak: sustained transient faults must degrade, not kill
# ===========================================================================

def test_flaky_l1_soak_settles_without_going_fatal():
    """Live actor loops against an L1 dropping ~30% of commit/verify/
    deposit calls (bounded fault budgets).  The sequencer must never set
    `fatal`, classify the failures as transient, and fully settle once
    the plan drains — with the l1/prover sections in ethrex_health."""
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1([protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,),
        block_time=0.03, commit_interval=0.03, proof_send_interval=0.03,
        watcher_interval=0.03, max_backoff_factor=2))
    seq.stop_at_batch = 2       # bound the settlement target
    for n in range(2):
        node.submit_transaction(_transfer(n))
    plan = faults.install(
        FaultPlan(seed=11)
        .drop("l1.commit", p=0.3, times=4)
        .drop("l1.verify", p=0.3, times=4)
        .drop("l1.get_deposits", p=0.3, times=4))
    client = None
    try:
        seq.start()
        client = ProverClient(protocol.PROVER_EXEC,
                              [("127.0.0.1", seq.coordinator.port)],
                              heartbeat_interval=0, backoff_base=0.01,
                              rng_seed=2)
        deadline = time.time() + 30.0
        while time.time() < deadline:
            client.poll_once()
            if seq.fatal is not None:
                break
            if l1.last_verified_batch() >= 2:
                break
            time.sleep(0.02)
        assert seq.fatal is None, f"sequencer went fatal: {seq.fatal}"
        assert l1.last_verified_batch() >= 2
        assert l1.last_verified_batch() == l1.last_committed_batch()
        # transient classification: no actor ever burned a deterministic
        # failure from the injected drops
        for st in seq.health.values():
            assert st.consecutive_failures == 0
        if plan.log:
            assert any(st.last_error_class == "transient"
                       for st in seq.health.values())
        # health surface carries the settlement-resilience counters
        from ethrex_tpu.rpc.server import _health

        node.sequencer = seq
        h = _health(node)
        assert "l1" in h["l2"] and "prover" in h["l2"]
        assert h["l2"]["l1"]["confirmationDepth"] == 1
        assert h["l2"]["l1"]["recommitQueue"] == []
    finally:
        faults.clear()
        seq.stop()


def test_transient_budget_larger_than_deterministic():
    """Unit check on the classification: a ConnectionError-class failure
    burns the transient budget, an L1Error burns the deterministic one,
    and only the latter reaches `fatal` quickly."""
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1([protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,),
        watcher_interval=0.01, max_actor_failures=3,
        max_transient_failures=50, max_backoff_factor=1))
    try:
        faults.install(FaultPlan(seed=5).drop("l1.get_deposits", times=10))
        seq.start()
        deadline = time.time() + 5.0
        peak = 0
        while time.time() < deadline:
            st = seq.health.get("watch_l1")
            if st is not None:
                peak = max(peak, st.consecutive_transient)
            if peak >= 4:
                break
            time.sleep(0.005)
        st = seq.health["watch_l1"]
        # more transient failures than the deterministic budget allows,
        # yet the sequencer is still alive
        assert peak >= 4 > 3
        assert st.consecutive_failures == 0
        assert st.last_error_class == "transient"
        assert seq.fatal is None
    finally:
        faults.clear()
        seq.stop()


# ===========================================================================
# satellite regressions
# ===========================================================================

def test_update_state_flags_persist_across_restart(tmp_path):
    """update_state must adopt settlement flags through the write-through
    setter: after a restart the adopted flags are still set (the old
    in-place mutation silently skipped persistence)."""
    path = str(tmp_path / "rollup.db")
    node = _open_node(tmp_path)
    l1 = InMemoryL1([protocol.PROVER_EXEC])
    rollup = PersistentRollupStore(path)
    seq = Sequencer(node, l1, CFG, rollup=rollup)
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    assert seq.commit_next_batch().number == 1
    _settle(seq, l1)
    # wind the LOCAL flags back (as if a crash lost the flag writes),
    # then let update_state re-adopt them from the L1
    rollup.set_settlement(1, committed=False, verified=False)
    seq.update_state()
    b = rollup.get_batch(1)
    assert b.committed and b.verified
    node.store.flush()
    rollup.close()
    node.store.backend.close()

    rollup2 = PersistentRollupStore(path)
    b2 = rollup2.get_batch(1)
    assert b2.committed and b2.verified
    rollup2.close()


def test_rollup_store_meta_initialized_in_constructor():
    rs = RollupStore()
    assert rs._meta == {}
    assert rs.get_meta("missing", 42) == 42
    rs.set_meta("k", 7)
    assert rs.get_meta("k") == 7
