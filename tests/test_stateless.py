"""Witness generation + stateless guest execution round-trip."""

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.guest.execution import (ProgramInput, ProgramOutput,
                                        StatelessExecutionError,
                                        execution_program)
from ethrex_tpu.guest.witness import ExecutionWitness, generate_witness
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
OTHER = bytes.fromhex("aa" * 20)

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _make_chain_with_blocks():
    node = Node(Genesis.from_json(GENESIS))
    # block 1: transfers; block 2: contract deploy; block 3: contract calls
    nonce = 0

    def tx(to, value=0, data=b"", gas=100_000):
        nonlocal nonce
        t = Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=gas, to=to, value=value, data=data,
        ).sign(SECRET)
        nonce += 1
        return t

    blocks = []
    for tx_batch in (
        [tx(OTHER, value=100), tx(OTHER, value=200)],
        # counter contract: sload(0)+1 -> sstore(0)
        [tx(b"", data=bytes.fromhex(
            "67" + "5f546001015f55".ljust(16, "0") + "5f5260086018f3"))],
    ):
        for t in tx_batch:
            node.submit_transaction(t)
        blocks.append(node.produce_block())
    from ethrex_tpu.crypto.keccak import keccak256
    from ethrex_tpu.primitives import rlp
    created = keccak256(rlp.encode([SENDER, 2]))[12:]
    for t in [tx(created), tx(created)]:
        node.submit_transaction(t)
    blocks.append(node.produce_block())
    return node, blocks


def test_witness_roundtrip_stateless_execution():
    node, blocks = _make_chain_with_blocks()
    witness = generate_witness(node.chain, blocks)
    assert witness.nodes and witness.block_headers
    # serialize over the wire and back (the coordinator->prover path)
    pi = ProgramInput(blocks=blocks, witness=witness, config=node.config)
    pi2 = ProgramInput.from_json(pi.to_json())
    out = execution_program(pi2)
    assert out.final_state_root == blocks[-1].header.state_root
    assert out.last_block_hash == blocks[-1].hash
    assert out.first_block_number == 1
    assert out.last_block_number == 3
    # output encoding round-trip
    assert ProgramOutput.decode(out.encode()) == out


def test_stateless_rejects_tampered_block():
    node, blocks = _make_chain_with_blocks()
    witness = generate_witness(node.chain, blocks)
    import dataclasses
    from ethrex_tpu.primitives.block import Block
    bad_header = dataclasses.replace(blocks[-1].header,
                                     state_root=b"\x42" * 32)
    tampered = blocks[:-1] + [Block(bad_header, blocks[-1].body)]
    pi = ProgramInput(blocks=tampered, witness=witness, config=node.config)
    with pytest.raises(StatelessExecutionError):
        execution_program(pi)


def test_stateless_rejects_incomplete_witness():
    node, blocks = _make_chain_with_blocks()
    witness = generate_witness(node.chain, blocks)
    # drop the parent state root node itself — unquestionably required
    from ethrex_tpu.crypto.keccak import keccak256
    root = witness.block_headers[-1].state_root
    pruned = ExecutionWitness(
        nodes=[n for n in witness.nodes if keccak256(n) != root],
        codes=witness.codes,
        block_headers=witness.block_headers,
        first_block_number=witness.first_block_number,
    )
    assert len(pruned.nodes) == len(witness.nodes) - 1
    pi = ProgramInput(blocks=blocks, witness=pruned, config=node.config)
    with pytest.raises(StatelessExecutionError):
        execution_program(pi)


def test_stateless_rejects_wrong_parent():
    node, blocks = _make_chain_with_blocks()
    witness = generate_witness(node.chain, blocks[1:])
    # hand it blocks starting one earlier than the witness expects
    pi = ProgramInput(blocks=blocks, witness=witness, config=node.config)
    with pytest.raises(StatelessExecutionError):
        execution_program(pi)
