"""Open-loop load harness (ethrex_tpu/perf/loadgen.py).

The load-bearing property under test: the generator is OPEN-loop — a
stalled server shows up as rising measured latency while the offered
schedule (attempt count) stays fixed.  A closed-loop generator would
instead quietly send fewer requests and report healthy latencies
(coordinated omission)."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from ethrex_tpu.perf import loadgen
from ethrex_tpu.perf.bench_suite import build_serving_record


# ---------------------------------------------------------------------------
# schedules

def test_fixed_schedule_spacing_and_length():
    sched = loadgen.build_schedule(10, 1.0, "fixed")
    assert len(sched) == 10
    gaps = [b - a for a, b in zip(sched, sched[1:])]
    assert all(abs(g - 0.1) < 1e-9 for g in gaps)
    assert loadgen.build_schedule(0, 1.0) == []
    assert loadgen.build_schedule(10, 0) == []


def test_poisson_schedule_deterministic_and_rate_shaped():
    a = loadgen.build_schedule(200, 2.0, "poisson", seed=7)
    b = loadgen.build_schedule(200, 2.0, "poisson", seed=7)
    assert a == b
    assert a != loadgen.build_schedule(200, 2.0, "poisson", seed=8)
    # law of large numbers: ~rate*duration arrivals, irregular gaps
    assert 300 < len(a) < 500
    gaps = {round(y - x, 6) for x, y in zip(a, a[1:])}
    assert len(gaps) > 50
    assert all(t <= 2.0 for t in a)


def test_sender_secrets_deterministic_and_in_range():
    from ethrex_tpu.crypto import secp256k1

    s1 = loadgen.derive_secrets(4, seed=3)
    assert s1 == loadgen.derive_secrets(4, seed=3)
    assert len(set(s1)) == 4
    assert all(0 < s < secp256k1.N for s in s1)


# ---------------------------------------------------------------------------
# percentile estimation over cumulative histogram rows

def test_percentile_interpolates_within_bucket():
    buckets = (0.001, 0.002, 0.004, 0.008)
    # 10 observations, all in (0.001, 0.002]
    row = [0, 10, 10, 10, 10, 0.02]
    p50 = loadgen.percentile_from_rows(buckets, [row], 0.50)
    assert 0.001 < p50 <= 0.002
    # median of a bucket interpolates to its midpoint
    assert abs(p50 - 0.0015) < 1e-9
    # p100 caps at the last finite boundary even for +Inf observations
    inf_row = [0, 0, 0, 0, 5, 1.0]
    assert loadgen.percentile_from_rows(buckets, [inf_row], 0.99) == 0.008


def test_percentile_sums_across_series():
    buckets = (1.0, 2.0)
    fast = [8, 8, 8, 4.0]     # 8 obs <= 1.0
    slow = [0, 2, 2, 3.5]     # 2 obs in (1.0, 2.0]
    p50 = loadgen.percentile_from_rows(buckets, [fast, slow], 0.50)
    assert p50 <= 1.0
    p95 = loadgen.percentile_from_rows(buckets, [fast, slow], 0.95)
    assert 1.0 < p95 <= 2.0
    assert loadgen.percentile_from_rows(buckets, [], 0.5) is None
    assert loadgen.percentile_from_rows(buckets, [[0, 0, 0, 0.0]], 0.5) \
        is None


# ---------------------------------------------------------------------------
# open-loop behavior against a deliberately stalled server

class _StalledRpc(BaseHTTPRequestHandler):
    """JSON-RPC endpoint that sleeps `delay` before every response."""

    delay = 0.0

    def do_POST(self):
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        rid = json.loads(body).get("id", 1)
        time.sleep(type(self).delay)
        data = json.dumps({"jsonrpc": "2.0", "id": rid,
                           "result": "0x0"}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *args):
        pass


@pytest.fixture()
def stalled_server():
    class Handler(_StalledRpc):
        delay = 0.0

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    yield Handler, f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def test_open_loop_stall_raises_latency_not_send_rate(stalled_server):
    """The acceptance property: stalling the server must NOT slow the
    generator down.  Attempts stay pinned to the schedule; the stall
    appears in the measured percentiles instead."""
    handler, url = stalled_server
    rate, duration = 40, 1.0
    expected = len(loadgen.build_schedule(rate, duration, "fixed"))

    h = loadgen.Harness(url, payload="ping", workers=64, seed=0)
    fast = h.run(rate, duration, "fixed")
    assert fast["scheduled"] == expected
    assert fast["sent"] + fast["missed"] == expected

    handler.delay = 0.3
    slow = h.run(rate, duration, "fixed")
    # identical offered schedule: the generator did NOT back off
    assert slow["scheduled"] == expected
    assert slow["sent"] + slow["missed"] == expected
    assert slow["sent"] >= expected * 0.9   # 64 workers absorb 12 in-flight
    # the stall surfaces as measured latency
    assert slow["latency"]["p50"] >= 0.3 > (fast["latency"]["p50"] or 0)
    assert slow["latency"]["p99"] >= 0.3


def test_open_loop_misses_are_counted_never_deferred(stalled_server):
    """With a single worker and a 0.3s stall, most send slots find no
    free worker — they must be dropped and counted, not queued behind
    the stalled request (which would serialize sends = closed loop)."""
    handler, url = stalled_server
    handler.delay = 0.3
    h = loadgen.Harness(url, payload="ping", workers=1, seed=0)
    rep = h.run(rate=40, duration=1.0, arrivals="fixed")
    assert rep["scheduled"] == rep["sent"] + rep["missed"]
    # one worker at ~0.3s/req can deliver only ~3-4 of 40 slots
    assert rep["sent"] <= 8
    assert rep["missed"] >= 30
    # and the run still finishes on the schedule's clock, not the
    # server's: 40 slots * 0.3s serialized would take 12s
    assert rep["achievedRate"] < 10


def test_sweep_reports_max_sustainable_rate(stalled_server):
    handler, url = stalled_server
    h = loadgen.Harness(url, payload="ping", workers=32, seed=1)
    sweep = h.sweep([10, 20], duration=0.5, arrivals="poisson")
    assert [r["offeredRate"] for r in sweep["rates"]] == [10, 20]
    assert sweep["maxSustainableRate"] == 20
    for rep in sweep["rates"]:
        assert rep["errorRate"] == 0.0
        assert rep["latency"]["p99"] is not None
    # a stalled server + tiny worker pool drops below the achieved-
    # fraction floor, so nothing qualifies as sustainable
    handler.delay = 0.4
    h1 = loadgen.Harness(url, payload="ping", workers=1, seed=1)
    sweep = h1.sweep([20], duration=0.5)
    assert sweep["maxSustainableRate"] is None


def test_request_latency_histogram_uses_shared_ladder():
    from ethrex_tpu.utils.metrics import DEFAULT_BUCKETS, Metrics

    registry = Metrics()
    loadgen.observe_request_latency(registry, "ping", 0.005)
    snap = registry.snapshot()
    hist = snap["histograms"]["loadgen_request_seconds"]
    assert tuple(hist["buckets"]) == DEFAULT_BUCKETS
    assert hist["series"][0]["labels"] == {"kind": "ping"}
    assert "loadgen_request_seconds" in registry.help


# ---------------------------------------------------------------------------
# utils/load_test is a shim over this module

def test_load_test_shim_reexports_loadgen():
    from ethrex_tpu.utils import load_test

    assert load_test.run_load is loadgen.run_load
    assert load_test.main is loadgen.main
    assert load_test.SSTORE_INITCODE == loadgen.SSTORE_INITCODE
    assert load_test.SSTORE_RUNTIME == loadgen.SSTORE_RUNTIME


def test_token_initcode_returns_runtime():
    """The deploy wrapper must RETURN exactly the 8-byte runtime (same
    PUSH8/MSTORE/RETURN wrapper the sstore template uses)."""
    assert len(bytes.fromhex(loadgen.TOKEN_RUNTIME)) == 8
    assert loadgen.TOKEN_INITCODE == \
        "67" + loadgen.TOKEN_RUNTIME + "5f5260086018f3"


# ---------------------------------------------------------------------------
# serving record (bench_suite integration, pure part)

def test_build_serving_record_picks_sustained_rate():
    sweep = {
        "arrivals": "poisson",
        "maxSustainableRate": 25.0,
        "rates": [
            {"offeredRate": 10.0, "achievedRate": 10.0, "errorRate": 0.0,
             "missed": 0, "latency": {"p50": 0.001, "p95": 0.002,
                                      "p99": 0.003}},
            {"offeredRate": 25.0, "achievedRate": 24.0, "errorRate": 0.0,
             "missed": 1, "latency": {"p50": 0.002, "p95": 0.004,
                                      "p99": 0.006}},
        ],
    }
    rec = build_serving_record(sweep, setup_s=1.0, sweep_s=2.0)
    assert rec["metric"] == "serving_rpc_p99_seconds"
    assert rec["value"] == 0.006          # p99 AT the sustained rate
    assert rec["sustained_rate"] == 25.0
    assert rec["backend"] == "cpu"
    assert len(rec["rates"]) == 2
    assert rec["rates"][0]["p95"] == 0.002
    assert rec["stages"] == {"setup_s": 1.0, "sweep_s": 2.0}
    sub = rec["configs"]["serving_rate"]
    assert sub["metric"] == "serving_sustained_tps"
    assert sub["value"] == 25.0


def test_build_serving_record_nothing_sustained():
    sweep = {"arrivals": "fixed", "maxSustainableRate": None,
             "rates": [{"offeredRate": 50.0, "achievedRate": 3.0,
                        "errorRate": 0.2, "missed": 40,
                        "latency": {"p50": 0.5, "p95": 1.0, "p99": 2.0}}]}
    rec = build_serving_record(sweep)
    assert rec["sustained_rate"] == 0.0
    assert rec["value"] == 2.0            # gentlest rate still reported
    # a zero-valued sub-metric is excluded from history series, so a
    # collapsed run can never become the gate's baseline
    assert rec["configs"]["serving_rate"]["value"] == 0.0


# ---------------------------------------------------------------------------
# many-sender tx mode: the sweep shape behind BENCH_SERVING_SENDERS
# (ROADMAP item 3 — 10k-sender serving sweeps); funding must chunk
# below the mempool's per-sender slot cap or the ROOT key evicts its
# own funding tail and later senders never get funded

def test_many_sender_funding_chunks_below_sender_cap():
    from ethrex_tpu.blockchain.mempool import MAX_SENDER_SLOTS
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.rpc.server import RpcServer
    from tests.test_l2_pipeline import GENESIS

    n_senders = MAX_SENDER_SLOTS * 2 + 17  # forces 2+ funding chunks
    node = Node(Genesis.from_json(GENESIS))
    rpc = RpcServer(node, port=0).start()
    try:
        h = loadgen.Harness(f"http://127.0.0.1:{rpc.port}",
                            senders=n_senders, payload="tx",
                            workers=8, timeout=10.0, seed=4)
        h.setup(fund_wei=10 ** 15)
        # chunked funding produced intermediate blocks and funded EVERY
        # sender, including the tail past the per-sender cap
        assert node.store.latest_number() >= 2
        root = node.store.head_header().state_root
        for addr in h.addresses:
            acct = node.store.account_state(root, addr)
            assert acct is not None and acct.balance == 10 ** 15, \
                f"sender 0x{addr.hex()} left unfunded"

        rep = h.run(100.0, duration=0.5)
        # the sender spread is part of the record: 16-sender and
        # 10k-sender serving numbers are different benchmarks
        assert rep["senders"] == n_senders
        assert rep["delivered"] > 0
        assert rep["errors"] == 0
        sweep = h.sweep([50.0], duration=0.2)
        assert sweep["senders"] == n_senders
    finally:
        rpc.stop()
