"""Transfer VM AIR: host digest agreement, constraint satisfaction on the
honest trace, and rejection of tampered transfer amounts."""

import numpy as np
import pytest

from ethrex_tpu.guest import flat_model
from ethrex_tpu.models import transfer_air as ta
from ethrex_tpu.ops import babybear as bb
from ethrex_tpu.ops import ext
from ethrex_tpu.primitives.account import AccountState
from ethrex_tpu.stark.air import HostExtOps

SENDER = bytes.fromhex("11" * 20)
RECIP = bytes.fromhex("22" * 20)
CB = bytes.fromhex("33" * 20)


def _mk_segs(value=1000, fee=21000 * 7, tip=21000 * 2, create=False):
    s_old = AccountState(nonce=4, balance=10**18)
    s_new = AccountState(nonce=5, balance=10**18 - value - fee)
    if create:
        r_old, r_new = None, AccountState(nonce=0, balance=value)
    else:
        r_old = AccountState(nonce=1, balance=500)
        r_new = AccountState(nonce=1, balance=500 + value)
    cb_old = AccountState(nonce=0, balance=77)
    cb_new = AccountState(nonce=0, balance=77 + tip)
    tx = ta.TxSeg(SENDER, RECIP, s_old, s_new, r_old, r_new,
                  value, fee, tip, r_created=create, r_noop=False)
    cb = ta.CbSeg(CB, cb_old, cb_new, tip, created=False, noop=False)
    return [tx, cb]


def _check_rows(air, trace, periodic_cols, rows=None):
    n = trace.shape[0]
    hops = HostExtOps()
    bad_rows = []
    for r in (rows if rows is not None else range(n - 1)):
        local = [ext.h_from_base(int(v)) for v in trace[r]]
        nxt = [ext.h_from_base(int(v)) for v in trace[(r + 1) % n]]
        periodic = [ext.h_from_base(int(col[r % len(col)]))
                    for col in periodic_cols]
        cs = air.constraints(local, nxt, periodic, hops)
        bad = [i for i, c in enumerate(cs) if c != ext.ZERO_H]
        if bad:
            bad_rows.append((r, bad[:6]))
    return bad_rows


@pytest.mark.slow
def test_honest_trace_satisfies_constraints():
    segs = _mk_segs()
    air = ta.TransferAir()
    trace = ta.generate_transfer_trace(segs)
    n = trace.shape[0]
    assert n == ta.segment_count(len(segs)) * ta.SEG_LEN

    pub = ta.transfer_public_inputs(segs)
    for row, col, val in air.boundaries(pub, n):
        assert int(trace[row, col]) == val, (row, col, val)

    periodic_cols = air.periodic_columns(n)
    bad = _check_rows(air, trace, periodic_cols)
    assert not bad, f"violated rows: {bad[:8]}"


@pytest.mark.slow
def test_created_recipient_trace_satisfies_constraints():
    segs = _mk_segs(create=True)
    air = ta.TransferAir()
    trace = ta.generate_transfer_trace(segs)
    periodic_cols = air.periodic_columns(trace.shape[0])
    bad = _check_rows(air, trace, periodic_cols)
    assert not bad, f"violated rows: {bad[:8]}"
    pub = ta.transfer_public_inputs(segs)
    for row, col, val in air.boundaries(pub, trace.shape[0]):
        assert int(trace[row, col]) == val


@pytest.mark.slow
def test_tampered_amount_breaks_constraints():
    segs = _mk_segs()
    air = ta.TransferAir()
    trace = ta.generate_transfer_trace(segs)
    n = trace.shape[0]
    periodic_cols = air.periodic_columns(n)

    # inflate the recipient's new balance limb inside segment 0: either
    # the add chain or the absorbed digest must break
    bad = trace.copy()
    seg0 = slice(0, ta.SEG_LEN)
    col = ta.RNEW + ta.F_BAL + 10
    bad[seg0, col] = (bad[seg0, col] + 1) % bb.P
    assert _check_rows(air, bad, periodic_cols)

    # tamper the sender debit instead
    bad2 = trace.copy()
    col2 = ta.SNEW + ta.F_BAL + 10
    bad2[seg0, col2] = (bad2[seg0, col2] + 1) % bb.P
    assert _check_rows(air, bad2, periodic_cols)


def test_vm_digest_matches_trace_lane():
    segs = _mk_segs()
    trace = ta.generate_transfer_trace(segs)
    dig = ta.vm_digest(segs)
    assert [int(v) for v in trace[-1, ta.T:ta.T + 8]] == dig


def test_pack_unpack_roundtrip():
    st = AccountState(nonce=3, balance=12345678901234567890)
    d = flat_model.account_value_digest(st)
    assert flat_model.unpack32(flat_model.pack32(d)) == d
    assert flat_model.digest_limbs_of_value32(b"\x00" * 32) == [0] * 8


@pytest.mark.slow
def test_transfer_stark_roundtrip():
    from ethrex_tpu.stark import prover as stark_prover
    from ethrex_tpu.stark import verifier as stark_verifier
    from ethrex_tpu.stark.prover import StarkParams

    segs = _mk_segs()
    air = ta.TransferAir()
    trace = ta.generate_transfer_trace(segs)
    pub = ta.transfer_public_inputs(segs)
    params = StarkParams(log_blowup=3, num_queries=25, log_final_size=4)
    proof = stark_prover.prove(air, trace, pub, params)
    assert stark_verifier.verify(air, proof, params)

    bad = dict(proof)
    bad["pub_inputs"] = [(int(v) + 1) % bb.P for v in proof["pub_inputs"]]
    with pytest.raises(Exception):
        if not stark_verifier.verify(air, bad, params):
            raise ValueError("rejected")
