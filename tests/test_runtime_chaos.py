"""Preemption-tolerant proving battery (docs/PROVER_RESILIENCE.md
"Runtime failures, phase checkpoints, and the degradation ladder"):
the error taxonomy, the phase-checkpoint envelope (kill at every phase
boundary -> resume with at most one phase recomputed, byte-identical
proof; torn/garbage blobs discarded to a fresh prove), the OOM /
device-loss degradation ladder, nan-poison zero-retry quarantine, the
pre-prove memory gate, and the coordinator side: phase-transition
hedge re-anchoring, degraded-prover steering, and first-report poison
quarantine — all driven by seeded FaultPlans at the "backend.phase"
and "device.lost" sites.

Select alone with `-m chaos`; the drills that run a full STARK prove
(the crash loop and the ladder walks) are `slow` like the PR-14 soak —
the taxonomy/envelope/coordinator units stay in the fast tier.
"""

import os
import pickle

import numpy as np
import pytest

from ethrex_tpu.l2.proof_coordinator import ProofCoordinator
from ethrex_tpu.l2.rollup_store import RollupStore
from ethrex_tpu.models import merkle_air as mair
from ethrex_tpu.ops import babybear as bb
from ethrex_tpu.ops.merkle import fold_path_canonical
from ethrex_tpu.prover import checkpoint as ckpt
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover import runtime_errors as rt
from ethrex_tpu.prover.client import ProverClient
from ethrex_tpu.stark import prover
from ethrex_tpu.stark.prover import StarkParams
from ethrex_tpu.utils import faults
from ethrex_tpu.utils.faults import FaultPlan, InjectedFault

pytestmark = pytest.mark.chaos

EXEC = protocol.PROVER_EXEC
TPU = protocol.PROVER_TPU
RNG = np.random.default_rng(61)
PARAMS = StarkParams(log_blowup=3, num_queries=12, log_final_size=4)
DEPTH = 3
PHASES = ("commit", "quotient", "open", "fri")


@pytest.fixture(autouse=True)
def _runtime_isolation(tmp_path):
    """Every test gets its own checkpoint dir and zeroed counters; no
    fault plan or context leaks out."""
    ckpt.set_checkpoint_dir(str(tmp_path / "ckpt"))
    rt.reset_stats()
    with ckpt._LOCK:
        for key in ckpt.STATS:
            ckpt.STATS[key] = 0
    yield
    faults.clear()
    ckpt.set_checkpoint_dir(None)
    rt.reset_stats()


def _material(depth=DEPTH):
    leaf = [int(v) for v in RNG.integers(0, bb.P, 8)]
    siblings = [[int(v) for v in RNG.integers(0, bb.P, 8)]
                for _ in range(depth)]
    index = int(RNG.integers(0, 1 << depth))
    bits = [(index >> j) & 1 for j in range(depth)]
    root = fold_path_canonical(index, leaf, siblings)
    air = mair.Poseidon2MerkleAir(depth)
    trace = mair.generate_merkle_trace(leaf, siblings, bits)
    pub = mair.merkle_public_inputs(leaf, root)
    return air, trace, pub


# ===========================================================================
# taxonomy units
# ===========================================================================

def test_classify_taxonomy():
    assert rt.classify(RuntimeError(
        "RESOURCE_EXHAUSTED: failed to allocate 4.2G")) == "oom"
    assert rt.classify(MemoryError()) == "oom"
    assert rt.classify(RuntimeError(
        "INTERNAL: lost connection to the device")) == "device_lost"
    assert rt.classify(RuntimeError("TPU slice health check failed")) \
        == "device_lost"
    assert rt.classify(rt.NanPoisonError("commit", "x")) == "nan_poison"
    assert rt.classify(ValueError("anything else")) == "unknown"
    wrapped = rt.TransientPhaseError("oom", "open", MemoryError())
    assert rt.classify(wrapped) == "oom"


def test_check_phase_outputs_names_the_phase():
    # clean artifacts pass through
    rt.check_phase_outputs("commit", {"rows": np.array([1, 2], np.uint32),
                                      "wall": 0.25, "n": 7})
    # a NaN anywhere poisons, naming the phase
    with pytest.raises(rt.NanPoisonError) as ei:
        rt.check_phase_outputs("open", {"vals": np.array([1.0, float("nan")])})
    assert ei.value.phase == "open"
    # out-of-field integers poison too (exact-arithmetic invariant)
    with pytest.raises(rt.NanPoisonError):
        rt.check_phase_outputs("fri", np.array([bb.P + 3], np.uint64))
    # the corrupt-rule envelope marker
    with pytest.raises(rt.NanPoisonError):
        rt.check_phase_outputs("commit", {"__corrupt__": True})
    assert rt.STATS["nan_poisons"] == 3


def test_guard_phase_classifies_and_wraps():
    """Transient classes come out as TransientPhaseError for the ladder;
    unknown exceptions propagate untouched; the injected legs at
    "backend.phase" and "device.lost" classify like real failures."""
    with faults.injected(FaultPlan(seed=1).error(
            "backend.phase",
            exc=RuntimeError("RESOURCE_EXHAUSTED: oom"), times=1)):
        with pytest.raises(rt.TransientPhaseError) as ei:
            rt.guard_phase("commit", "air", lambda: 1)
    assert (ei.value.kind, ei.value.phase) == ("oom", "commit")
    # the bare device.lost rule's message carries its own marker
    with faults.injected(FaultPlan(seed=2).error("device.lost", times=1)):
        with pytest.raises(rt.TransientPhaseError) as ei:
            rt.guard_phase("quotient", "air", lambda: 1)
    assert ei.value.kind == "device_lost"

    def boom():
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        rt.guard_phase("open", "air", boom)
    assert rt.guard_phase("fri", "air", lambda: 41 + 1) == 42


def test_degradation_ladder_and_kill_switch(monkeypatch):
    rungs = rt.degradation_ladder(None)
    assert len(rungs) == 1          # forced-CPU floor below the default
    assert [d.platform for d in rungs[0].devices.flat] == ["cpu"]
    monkeypatch.setenv("ETHREX_MESH_DEGRADE_OFF", "1")
    assert rt.degradation_ladder(None) == []
    assert rt.ladder_enabled() is False


def test_memory_gate_shrinks_before_oom(monkeypatch):
    # fits in headroom: layout untouched, nothing counted
    assert rt.memory_gate("air", None, est_bytes=100,
                          avail_fn=lambda m: 10_000) is None
    assert rt.STATS["memory_gate_shrinks"] == 0
    # over budget on the current layout, the CPU rung (unreported
    # limits) absorbs it — one pre-emptive degradation, no OOM thrown
    gated = rt.memory_gate(
        "air", None, est_bytes=100,
        avail_fn=lambda m: 10 if m is None else None)
    assert gated is not None
    assert rt.STATS["memory_gate_shrinks"] == 1
    assert rt.runtime_stats()["lastDegradation"]["reason"] == "memory_gate"
    # the kill switch disables the gate with the ladder
    monkeypatch.setenv("ETHREX_MESH_DEGRADE_OFF", "1")
    assert rt.memory_gate("air", None, est_bytes=100,
                          avail_fn=lambda m: 1) is None
    # unknown availability -> never shrink on a guess
    monkeypatch.delenv("ETHREX_MESH_DEGRADE_OFF")
    assert rt.memory_gate("air", None, est_bytes=100,
                          avail_fn=lambda m: None) is None


# ===========================================================================
# checkpoint envelope units
# ===========================================================================

def test_checkpoint_roundtrip_torn_and_garbage(monkeypatch):
    parts = {"kind": "proof_ckpt", "job": "j", "phase": "commit"}
    payload = {"rows": np.arange(4, dtype=np.uint32), "ch": {"pos": 3}}
    assert ckpt.store(7, parts, payload, meta={"lease_token": "tok"})
    got = ckpt.load(7, parts)
    assert np.array_equal(got["rows"], payload["rows"])
    assert ckpt.STATS["stores"] == 1 and ckpt.STATS["loads"] == 1
    # different parts address a different (absent) envelope — no discard
    assert ckpt.load(7, {**parts, "phase": "open"}) is None
    assert ckpt.STATS["discards"] == 0

    path = ckpt._entry_path(7, parts)
    # torn write: truncated frame is discarded and unlinked, never raises
    with open(path, "r+b") as f:
        f.truncate(9)
    assert ckpt.load(7, parts) is None
    assert ckpt.STATS["discards"] == 1 and not os.path.exists(path)
    # garbage bytes: same fate
    assert ckpt.store(7, parts, payload)
    with open(path, "wb") as f:
        f.write(b"\x00garbage" * 64)
    assert ckpt.load(7, parts) is None
    assert ckpt.STATS["discards"] == 2 and not os.path.exists(path)
    # settled batch: complete() drops the whole envelope dir
    assert ckpt.store(7, parts, payload)
    ckpt.complete(7)
    assert ckpt.load(7, parts) is None
    assert not os.path.exists(ckpt._batch_dir(7))
    # kill switch: no stores, no loads
    monkeypatch.setenv("ETHREX_PROOF_CKPT_OFF", "1")
    assert ckpt.store(7, parts, payload) is False
    assert ckpt.load(7, parts) is None
    assert ckpt.enabled() is False


def test_phase_store_requires_batch_context():
    assert ckpt.phase_store(("air", 1), 5, (3, 12)) is None
    with ckpt.batch_context(11, lease_token="tok"):
        store = ckpt.phase_store(("air", 1), 5, (3, 12), mesh_label="1")
        assert store is not None
        assert store.store("commit", {"x": 1})
        assert store.load("commit") == {"x": 1}
        # lease token travels as metadata, NOT key material: a restarted
        # client's fresh token still addresses the same envelope
        store.meta["lease_token"] = "fresh-token"
        assert store.load("commit") == {"x": 1}
    ckpt.complete(11)


# ===========================================================================
# prove-level drills (real proofs; programs shared per-process)
# ===========================================================================

@pytest.mark.slow
def test_kill_at_every_phase_boundary_resumes_byte_identical():
    """The tentpole acceptance drill: SIGKILL (simulated by the
    "backend.phase" drop leg firing at the first phase BOUNDARY after a
    live phase completes) at every boundary in sequence.  Each restart
    loses at most the in-flight phase: exactly one new phase completes
    per cycle, the rest load from the envelope, and the final proof is
    byte-identical to an uncheckpointed run."""
    air, trace, pub = _material()
    baseline = prover.prove(air, trace, pub, PARAMS)

    # checkpointed but undisturbed: identical bytes, one store per phase
    with ckpt.batch_context(901, lease_token="t0"):
        p1 = prover.prove(air, trace, pub, PARAMS)
    assert pickle.dumps(p1) == pickle.dumps(baseline)
    assert ckpt.STATS["stores"] == len(PHASES) + 1      # + final proof

    cycles, proof = 0, None
    with ckpt.batch_context(902, lease_token="t1"):
        while proof is None:
            cycles += 1
            assert cycles <= len(PHASES) + 2, "crash loop diverged"
            faults.install(
                FaultPlan(seed=cycles).drop("backend.phase", times=1))
            try:
                proof = prover.prove(air, trace, pub, PARAMS)
            except InjectedFault:
                pass    # the simulated preemption kill
            finally:
                faults.clear()
    # one boundary kill per completed phase, then one clean pass
    assert cycles == len(PHASES) + 1
    assert pickle.dumps(proof) == pickle.dumps(baseline)
    # resumed-phase arithmetic: cycle k replays its k-1 finished phases
    assert rt.STATS["phase_resumes"] == sum(range(len(PHASES) + 1))

    # a restarted prover that already finished sees the stored proof
    before = rt.STATS["phase_resumes"]
    with ckpt.batch_context(902, lease_token="t2-after-restart"):
        p2 = prover.prove(air, trace, pub, PARAMS)
    assert pickle.dumps(p2) == pickle.dumps(baseline)
    assert rt.STATS["phase_resumes"] == before + 1
    ckpt.complete(901)
    ckpt.complete(902)


@pytest.mark.slow
def test_torn_checkpoints_fall_back_to_fresh_prove():
    """Mangling every stored envelope (torn tail, garbage bytes) never
    breaks a re-prove: bad blobs are discarded + counted, the phases
    recompute, and the proof stays byte-identical."""
    air, trace, pub = _material()
    with ckpt.batch_context(903, lease_token="t"):
        p0 = prover.prove(air, trace, pub, PARAMS)
    bdir = ckpt._batch_dir(903)
    names = sorted(os.listdir(bdir))
    assert len(names) == len(PHASES) + 1
    for i, name in enumerate(names):
        path = os.path.join(bdir, name)
        if i % 2:
            with open(path, "r+b") as f:       # torn mid-frame
                f.truncate(max(1, os.path.getsize(path) // 2))
        else:
            with open(path, "wb") as f:        # arbitrary garbage
                f.write(b"\xde\xad" * 37)
    with ckpt.batch_context(903, lease_token="t"):
        p1 = prover.prove(air, trace, pub, PARAMS)
    assert pickle.dumps(p1) == pickle.dumps(p0)
    # the proof short-circuit and the first phase were both tried and
    # thrown out; the contiguous-prefix scan stops at the first miss
    assert ckpt.STATS["discards"] >= 2
    assert rt.STATS["phase_resumes"] == 0
    ckpt.complete(903)


@pytest.mark.slow
def test_oom_walks_the_ladder_byte_identical():
    """A RESOURCE_EXHAUSTED mid-phase classifies as oom, burns no
    quarantine budget, and retries the attempt on the next rung (the
    forced-CPU floor here); exact u32 arithmetic keeps the proof
    byte-identical across layouts."""
    air, trace, pub = _material()
    baseline = prover.prove(air, trace, pub, PARAMS)
    faults.install(FaultPlan(seed=5).error(
        "backend.phase",
        exc=RuntimeError("RESOURCE_EXHAUSTED: failed to allocate"),
        times=1))
    try:
        p = prover.prove(air, trace, pub, PARAMS)
    finally:
        faults.clear()
    assert pickle.dumps(p) == pickle.dumps(baseline)
    stats = rt.runtime_stats()
    assert stats["oomRetries"] == 1
    assert stats["degradations"] == 1
    assert stats["lastDegradation"]["reason"] == "ladder"


@pytest.mark.slow
def test_device_loss_retries_on_next_rung():
    air, trace, pub = _material()
    baseline = prover.prove(air, trace, pub, PARAMS)
    faults.install(FaultPlan(seed=6).error("device.lost", times=1))
    try:
        p = prover.prove(air, trace, pub, PARAMS)
    finally:
        faults.clear()
    assert pickle.dumps(p) == pickle.dumps(baseline)
    assert rt.runtime_stats()["deviceLostRetries"] == 1


@pytest.mark.slow
def test_ladder_kill_switch_propagates_the_failure(monkeypatch):
    """ETHREX_MESH_DEGRADE_OFF=1: a transient failure has nowhere to
    fall and surfaces as the original exception (lease expiry handles
    it), not an infinite retry."""
    monkeypatch.setenv("ETHREX_MESH_DEGRADE_OFF", "1")
    air, trace, pub = _material()
    faults.install(FaultPlan(seed=7).error(
        "backend.phase", exc=RuntimeError("out of memory"), times=1))
    try:
        with pytest.raises(RuntimeError, match="out of memory"):
            prover.prove(air, trace, pub, PARAMS)
    finally:
        faults.clear()
    assert rt.STATS["degradations"] == 0


@pytest.mark.slow
def test_nan_poison_quarantines_without_retry():
    """A corrupt phase artifact raises NanPoisonError naming the phase
    on the FIRST attempt — the ladder never retries poison (recomputing
    garbage yields garbage) and no transient counter moves."""
    air, trace, pub = _material()
    faults.install(FaultPlan(seed=8).corrupt("backend.phase", times=1))
    try:
        with pytest.raises(rt.NanPoisonError) as ei:
            prover.prove(air, trace, pub, PARAMS)
    finally:
        faults.clear()
    assert ei.value.phase == "commit"       # first screened phase
    stats = rt.runtime_stats()
    assert stats["nanPoisons"] == 1
    assert stats["oomRetries"] == 0 and stats["degradations"] == 0


# ===========================================================================
# coordinator: phase re-anchoring, degraded steering, poison reports
# ===========================================================================

def _bare_coordinator(batches=1, **kw):
    store = RollupStore()
    for n in range(1, batches + 1):
        store.store_prover_input(n, protocol.PROTOCOL_VERSION, {"stub": n})
    kw.setdefault("needed_types", [EXEC])
    kw.setdefault("verify_submissions", False)
    return store, ProofCoordinator(store, **kw)


def _beat(co, batch, token, ptype=EXEC, **extra):
    msg = {"type": protocol.HEARTBEAT, "batch_id": batch,
           "prover_type": ptype, "lease_token": token}
    msg.update(extra)
    return co.handle_request(msg)


def test_phase_transition_reanchors_hedging(monkeypatch):
    """A prover grinding through long phases is NOT a straggler: every
    reported phase TRANSITION re-anchors the hedge clock (with the
    coordinator's own clock — phase_started is advisory), while a
    prover stuck inside one phase still gets hedged."""
    store, co = _bare_coordinator(hedge_min_samples=4, hedge_factor=1.5)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    co.durations.extend([1.0, 1.0, 1.0, 1.0])    # p99=1s -> deadline 1.5s

    batch, tok = co.assign(EXEC, "steady")
    assert batch == 1
    t[0] = 1.2
    assert _beat(co, 1, tok, phase="state_proof.commit",
                 phase_started=0.9)["ok"] is True
    assert co.lease_phase[(1, EXEC)] == ("state_proof.commit", 1.2)
    # 2.0s after assignment but only 0.8s after the transition: no hedge
    t[0] = 2.0
    assert co.assign(EXEC, "idle") == (None, None)
    # a repeat of the SAME phase does not re-anchor...
    t[0] = 2.4
    assert _beat(co, 1, tok, phase="state_proof.commit")["ok"] is True
    assert co.lease_phase[(1, EXEC)][1] == 1.2
    # ...so 1.6s of silence within one phase crosses the deadline
    t[0] = 2.8
    hbatch, htok = co.assign(EXEC, "idle")
    assert hbatch == 1 and htok not in (None, tok)
    assert co.hedges[(1, EXEC)]["reason"] == "straggler"
    # submit clears the per-lease phase record with the lease
    assert co.handle_request({
        "type": protocol.PROOF_SUBMIT, "batch_id": 1, "prover_type": EXEC,
        "lease_token": tok, "proof": {"backend": EXEC},
    })["type"] == protocol.SUBMIT_ACK
    assert (1, EXEC) not in co.lease_phase


def test_degraded_prover_steered_to_lightest_batch(monkeypatch):
    """A heartbeat-reported mesh downgrade makes the scheduler hand that
    prover the LIGHTEST waiting batch instead of trusting its stale
    EWMA."""
    store = RollupStore()

    def inp(txs):
        return {"blocks": [{"transactions": [{}] * txs}]}

    for num, txs in ((1, 1), (2, 5), (3, 3), (4, 7)):
        store.store_prover_input(num, protocol.PROTOCOL_VERSION, inp(txs))
    co = ProofCoordinator(store, needed_types=[EXEC],
                          verify_submissions=False)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    batch, tok = co.assign(EXEC, "deg")
    assert batch == 1                            # FCFS before any report
    assert _beat(co, 1, tok, prover_id="deg",
                 degraded={"from": "8", "to": "1"})["ok"] is True
    assert co.prover_stats["deg"]["degraded"] == {"from": "8", "to": "1"}
    # unleased = [2, 3, 4] with weights 6, 4, 8: the degraded prover
    # gets batch 3, not the FCFS pick (2)
    assert co.assign(EXEC, "deg")[0] == 3
    # surfaced through health for the monitor panel
    stats = co.stats_json()
    assert stats["runtime"]["degradedProvers"]["deg"]["to"] == "1"
    assert stats["scheduler"]["provers"]["deg"]["degraded"]["to"] == "1"


def test_poison_report_quarantines_first_report(monkeypatch):
    """A token-gated poison heartbeat quarantines the batch onto the
    fallback backend immediately — no failure budget burned, no second
    attempt on the poisoned backend — and the event names the phase."""
    store, co = _bare_coordinator(needed_types=[TPU])
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    batch, tok = co.assign(TPU, "p1")
    assert batch == 1
    # a forged token reports nothing
    assert _beat(co, 1, "forged", ptype=TPU,
                 poison={"phase": "state_proof.commit"})["ok"] is False
    assert co.quarantined == set() and co.poison_reports_total == 0
    # the holder's report quarantines on the spot
    assert _beat(co, 1, tok, ptype=TPU,
                 poison={"phase": "state_proof.commit",
                         "detail": "non-finite array value"})["ok"] is True
    assert co.quarantined == {1}
    assert co.poison_reports_total == 1
    assert co.failures == {}                     # zero budget burned
    assert (1, TPU) not in co.assignments        # lease released
    assert any(e["event"] == "quarantine"
               and "state_proof.commit" in e.get("detail", "")
               for e in co.events)
    # the fallback backend picks the batch straight up
    assert co.assign(EXEC, "fb")[0] == 1
    # a fallback-type poison report never quarantines (nowhere to fall)
    assert co.stats_json()["runtime"]["poisonReports"] == 1


def test_client_reports_poison_and_stops_retrying():
    """Full loop over real TCP: a backend that poisons loses exactly one
    attempt — the client reports the phase via heartbeat, the
    coordinator quarantines onto the fallback type, and the client
    never re-polls the batch on the poisoned backend."""
    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.l2.l1_client import InMemoryL1
    from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.primitives.transaction import (TYPE_DYNAMIC_FEE,
                                                   Transaction)

    secret = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
    sender = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(secret))
    genesis = {
        "config": {"chainId": 65536999, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + sender.hex(): {"balance": hex(10**21)}},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }

    class PoisonBackend:
        prover_type = TPU

        def prove(self, program_input, proof_format):
            raise rt.NanPoisonError("state_proof.open",
                                    "non-finite array value")

    node = Node(Genesis.from_json(genesis))
    l1 = InMemoryL1([TPU])
    seq = Sequencer(node, l1, SequencerConfig(needed_prover_types=(TPU,)))
    seq.coordinator.start()
    try:
        node.submit_transaction(Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=65536999, nonce=0,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=21000, to=bytes.fromhex("aa" * 20), value=5,
        ).sign(secret))
        seq.produce_block()
        assert seq.commit_next_batch() is not None
        co = seq.coordinator
        client = ProverClient(PoisonBackend(),
                              [("127.0.0.1", co.port)],
                              heartbeat_interval=0, backoff_base=0.01,
                              rng_seed=3)
        assert client.poll_once() == 0
        assert client.poisoned == [1]
        assert co.quarantined == {1}
        assert co.poison_reports_total == 1
        assert co.failures == {}
        # nothing left for the poisoned backend; fallback takes it
        assert client.poll_once() == 0
        assert co.assign(EXEC, "fb")[0] == 1
    finally:
        seq.stop()


def test_stats_json_runtime_section(monkeypatch):
    store, co = _bare_coordinator()
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    batch, tok = co.assign(EXEC, "p1")
    t[0] = 0.5
    assert _beat(co, 1, tok, phase="state_proof.fri")["ok"] is True
    t[0] = 2.0
    run = co.stats_json()["runtime"]
    for key in ("oomRetries", "deviceLostRetries", "nanPoisons",
                "degradations", "memoryGateShrinks", "phaseResumes",
                "poisonReports", "degradedProvers", "livePhases",
                "checkpoints"):
        assert key in run, key
    live, = run["livePhases"]
    assert live["batch"] == 1 and live["phase"] == "state_proof.fri"
    assert abs(live["sincePhaseSeconds"] - 1.5) < 1e-9
