"""Overload protection units: admission-control cost classes and shed
ladder, typed mempool admission rules (sender caps, nonce gaps, dynamic
fee floor, replacement-by-fee), WS slow-consumer protection, loadgen
shed classification, and the serving-bench shed surface.

The end-to-end 5x-overload soak lives in tests/test_overload_chaos.py.
"""

import socket
import time
from types import SimpleNamespace

import pytest

from ethrex_tpu.blockchain.mempool import (
    FeeBelowFloorError,
    Mempool,
    NonceGapError,
    ReplacementUnderpricedError,
    SenderLimitError,
    UnderpricedError,
)
from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.rpc.server import RpcServer
from ethrex_tpu.rpc.websocket import WsConnection
from ethrex_tpu.utils.metrics import METRICS
from ethrex_tpu.utils.overload import (
    SERVER_BUSY_CODE,
    OverloadController,
    classify,
    is_busy_error,
)

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _tx(nonce, secret=SECRET, fee=10**10, value=1):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=fee,
        gas_limit=21_000, to=bytes([0xAA]) * 20, value=value).sign(secret)


# ---------------------------------------------------------------------------
# cost classes and the typed busy error

def test_method_cost_classification():
    assert classify("eth_blockNumber") == "read"
    assert classify("eth_getBalance") == "read"
    assert classify("eth_sendRawTransaction") == "submit"
    assert classify("eth_getLogs") == "heavy"
    assert classify("eth_call") == "heavy"
    assert classify("eth_estimateGas") == "heavy"
    assert classify("eth_getProof") == "heavy"
    assert classify("debug_traceTransaction") == "heavy"
    # the control plane must never be shed: consensus driver, operator
    # eyes, and the namespaces behind auth
    assert classify("engine_newPayloadV3") == "control"
    assert classify("ethrex_health") == "control"
    assert classify("ethrex_alerts") == "control"
    assert classify("ethrex_debug_snapshot") == "control"
    assert classify("admin_peers") == "control"
    assert classify("web3_clientVersion") == "control"


def test_is_busy_error_classifier():
    busy = {"code": SERVER_BUSY_CODE, "message": "server busy",
            "data": {"reason": "level", "class": "read",
                     "retryAfter": 1.0, "shedLevel": 3}}
    assert is_busy_error(busy)
    assert not is_busy_error({"code": -32603, "message": "internal"})
    assert not is_busy_error({"code": SERVER_BUSY_CODE, "data": None})
    assert not is_busy_error("server busy")
    assert not is_busy_error(None)


def test_concurrency_limit_sheds_and_releases():
    ctl = OverloadController(read_limit=1, tick_interval=0.0,
                             raise_hold=10.0)
    d1 = ctl.admit("eth_blockNumber")
    assert d1.admitted
    d2 = ctl.admit("eth_blockNumber")
    assert not d2.admitted
    assert d2.reason == "concurrency"
    data = d2.error_data()
    assert data["class"] == "read"
    assert data["retryAfter"] > 0
    ctl.release(d1)
    d3 = ctl.admit("eth_blockNumber")
    assert d3.admitted
    ctl.release(d3)
    assert ctl.shed_total == 1
    assert ctl.shed_by_reason == {"concurrency": 1}


def test_stale_queue_age_sheds_on_deadline():
    ctl = OverloadController(read_deadline=0.2, tick_interval=0.0,
                             raise_hold=10.0)
    d = ctl.admit("eth_blockNumber", queue_age=1.0)
    assert not d.admitted
    assert d.reason == "deadline"
    # fresh requests still pass
    d2 = ctl.admit("eth_blockNumber", queue_age=0.0)
    assert d2.admitted
    ctl.release(d2)


def test_shed_level_ladder_and_hysteresis_recovery():
    ctl = OverloadController(queue_high=0.1, raise_hold=0.0,
                             recover_hold=0.0, tick_interval=0.0,
                             signal_window=0.3)
    for _ in range(20):
        ctl.note_queue_wait(0.5)     # 5x queue_high -> desired level 3
    d = ctl.admit("ethrex_health")   # control: admitted, but ticks
    ctl.release(d)
    assert ctl.level == 3
    assert ctl.state == "shedding"
    for method, expect_shed in (("debug_traceTransaction", True),
                                ("eth_sendRawTransaction", True),
                                ("eth_blockNumber", True),
                                ("ethrex_health", False)):
        dec = ctl.admit(method)
        assert dec.admitted == (not expect_shed), method
        if dec.admitted:
            ctl.release(dec)
        else:
            assert dec.reason == "level"
            assert dec.error_data()["shedLevel"] == 3
    # level sheds back off harder: retryAfter scales with the level
    lvl_shed = ctl.admit("eth_blockNumber")
    assert lvl_shed.retry_after == pytest.approx(ctl.retry_after * 3)
    # let the wait samples age out of the signal window, then recover
    time.sleep(0.35)
    d = ctl.admit("ethrex_health")
    ctl.release(d)
    assert ctl.level == 0
    assert ctl.state == "recovered"
    time.sleep(0.05)
    d = ctl.admit("ethrex_health")
    ctl.release(d)
    assert ctl.state == "ok"


def test_raise_hold_delays_the_ladder():
    """A transient spike shorter than raise_hold must not move the
    level — the same breach-persistence rule the alert engine uses."""
    ctl = OverloadController(queue_high=0.1, raise_hold=30.0,
                             tick_interval=0.0)
    for _ in range(20):
        ctl.note_queue_wait(0.5)
    d = ctl.admit("ethrex_health")
    ctl.release(d)
    assert ctl.level == 0
    assert ctl.state == "ok"


def test_mempool_pressure_sheds_submit_before_reads():
    ctl = OverloadController(mempool_probe=lambda: 0.99,
                             raise_hold=0.0, tick_interval=0.0)
    d = ctl.admit("ethrex_health")   # tick: probe pushes level to 2
    ctl.release(d)
    assert ctl.level == 2
    assert not ctl.admit("eth_sendRawTransaction").admitted
    assert not ctl.admit("debug_traceTransaction").admitted
    rd = ctl.admit("eth_blockNumber")
    assert rd.admitted               # reads survive level 2
    ctl.release(rd)


def test_disabled_controller_admits_everything():
    ctl = OverloadController(enabled=False, read_limit=1,
                             tick_interval=0.0)
    decisions = [ctl.admit("eth_blockNumber", queue_age=100.0)
                 for _ in range(5)]
    assert all(d.admitted for d in decisions)
    for d in decisions:
        ctl.release(d)
    assert ctl.shed_total == 0


def test_controller_to_json_surface():
    ctl = OverloadController(read_limit=7)
    out = ctl.to_json()
    assert out["enabled"] is True
    assert out["level"] == 0
    assert out["state"] == "ok"
    assert out["classes"]["read"]["limit"] == 7
    assert out["classes"]["control"]["deadlineSeconds"] is None
    assert out["classes"]["control"]["shedAtLevel"] == 0


# ---------------------------------------------------------------------------
# RPC integration: the typed busy answer, never-executed contract

def test_rpc_handle_sheds_stale_requests_without_executing():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, port=0)     # not started: handle() direct
    calls = []
    server.methods["test_probe"] = lambda: calls.append(1) or "ok"

    stale = time.monotonic() - 60.0      # way past the read deadline
    resp = server.handle({"jsonrpc": "2.0", "id": 9,
                          "method": "test_probe"}, accepted_at=stale)
    err = resp["error"]
    assert err["code"] == SERVER_BUSY_CODE
    assert err["message"] == "server busy"
    assert err["data"]["reason"] == "deadline"
    assert err["data"]["class"] == "read"
    assert err["data"]["retryAfter"] > 0
    assert is_busy_error(err)
    assert calls == []                   # shed means NEVER executed

    fresh = server.handle({"jsonrpc": "2.0", "id": 10,
                           "method": "test_probe"},
                          accepted_at=time.monotonic())
    assert fresh["result"] == "ok"
    assert calls == [1]


def test_health_surfaces_overload_state():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, port=0)
    out = server.handle({"jsonrpc": "2.0", "id": 1,
                         "method": "ethrex_health"})["result"]
    ov = out["rpc"]["overload"]
    assert ov["state"] == "ok"
    assert set(ov["classes"]) == {"control", "heavy", "read", "submit"}
    assert server.overload is node.rpc_overload


# ---------------------------------------------------------------------------
# mempool admission rules (typed, adversarial-sender caps)

BALANCE = 10**21


def test_nonce_gap_rejected_typed():
    pool = Mempool(capacity=100, max_nonce_gap=2)
    pool.add_transaction(_tx(0), 0, BALANCE, 7)
    pool.add_transaction(_tx(2), 0, BALANCE, 7)   # gap 2: at the limit
    with pytest.raises(NonceGapError, match="nonce gap 5 exceeds"):
        pool.add_transaction(_tx(5), 0, BALANCE, 7)
    assert pool.rejections == {"nonce_gap": 1}
    assert len(pool) == 2


def test_sender_slot_cap_rejected_typed():
    pool = Mempool(capacity=100, max_sender_slots=2)
    pool.add_transaction(_tx(0), 0, BALANCE, 7)
    pool.add_transaction(_tx(1), 0, BALANCE, 7)
    with pytest.raises(SenderLimitError, match="cap 2"):
        pool.add_transaction(_tx(2), 0, BALANCE, 7)
    assert pool.rejections == {"sender_limit": 1}
    # a replacement is exempt: it does not grow the sender's footprint
    pool.add_transaction(_tx(1, fee=2 * 10**10), 0, BALANCE, 7)
    assert len(pool) == 2


def test_dynamic_fee_floor_prices_admission_when_hot():
    pool = Mempool(capacity=4, fee_floor_start=0.5)
    base_fee = 10**10
    assert pool.fee_floor(base_fee) == 0          # cold pool: no floor
    for nonce in range(3):
        pool.add_transaction(_tx(nonce, fee=10**12), 0, BALANCE, base_fee)
    # 3/4 regular slots: span 0.5 of the ramp -> 5.5x base_fee
    floor = pool.fee_floor(base_fee)
    assert floor == int(5.5 * base_fee)
    with pytest.raises(FeeBelowFloorError, match="below dynamic floor"):
        pool.add_transaction(_tx(3, fee=base_fee), 0, BALANCE, base_fee)
    assert pool.rejections == {"fee_below_floor": 1}
    # paying the floor gets in
    pool.add_transaction(_tx(3, fee=floor), 0, BALANCE, base_fee)
    assert len(pool) == 4


def test_replacement_by_fee_typed_and_counted():
    pool = Mempool(capacity=10)
    pool.add_transaction(_tx(0, fee=10**10), 0, BALANCE, 7)
    with pytest.raises(ReplacementUnderpricedError,
                       match="replacement underpriced"):
        pool.add_transaction(_tx(0, fee=10**10 + 1), 0, BALANCE, 7)
    # the typed class IS the legacy class: ledger and surface unchanged
    assert issubclass(ReplacementUnderpricedError, UnderpricedError)
    assert ReplacementUnderpricedError.reason == "underpriced"
    assert pool.rejections == {"underpriced": 1}
    assert pool.replacements == 0
    # >=10% bump replaces in place
    pool.add_transaction(_tx(0, fee=11 * 10**9), 0, BALANCE, 7)
    assert len(pool) == 1
    assert pool.replacements == 1
    stats = pool.stats_json()
    assert stats["replacements"] == 1
    assert stats["senderSlotCap"] == pool.max_sender_slots
    assert stats["nonceGapLimit"] == pool.max_nonce_gap
    assert METRICS.snapshot()["counters"][
        "mempool_replacements_total"] >= 1


# ---------------------------------------------------------------------------
# WS slow-consumer protection

def test_ws_slow_consumer_is_disconnected():
    before = METRICS.snapshot()["counters"].get(
        "ws_slow_consumer_disconnects_total", 0)
    s_srv, s_cli = socket.socketpair()
    s_srv.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
    s_cli.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
    ws = SimpleNamespace(connections=set(), notify_queue_size=2,
                         slow_consumer_deadline=0.05)
    conn = WsConnection(ws, s_srv)
    ws.connections.add(conn)
    payload = "ff" * 16384    # one frame overflows the socket buffer
    deadline = time.monotonic() + 5.0
    while conn.alive and time.monotonic() < deadline:
        conn.notify("0x1", payload)   # consumer never reads
        time.sleep(0.005)
    assert not conn.alive
    assert conn.notifications_dropped > 0
    assert conn not in ws.connections
    after = METRICS.snapshot()["counters"][
        "ws_slow_consumer_disconnects_total"]
    assert after >= before + 1
    drops = METRICS.snapshot()["counters"][
        "ws_notifications_dropped_total"]
    assert drops >= conn.notifications_dropped
    s_cli.close()
    s_srv.close()


def test_ws_healthy_consumer_keeps_flowing():
    s_srv, s_cli = socket.socketpair()
    ws = SimpleNamespace(connections=set(), notify_queue_size=8,
                         slow_consumer_deadline=5.0)
    conn = WsConnection(ws, s_srv)
    for _ in range(5):
        assert conn.notify("0x1", "0x2a")
    s_cli.settimeout(5.0)
    got = b""
    while got.count(b"eth_subscription") < 5:
        got += s_cli.recv(65536)
    assert conn.alive
    deadline = time.monotonic() + 5.0
    while conn.notifications_sent < 5 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert conn.notifications_sent == 5
    assert conn.notifications_dropped == 0
    assert b"eth_subscription" in got
    conn._sendq.put_nowait(None)
    s_cli.close()
    s_srv.close()


# ---------------------------------------------------------------------------
# loadgen: typed busy answers are shed, not errors; shed is not delivered

@pytest.fixture()
def busy_rpc():
    from ethrex_tpu.perf import loadgen

    node = Node(Genesis.from_json(GENESIS))
    ctl = OverloadController(read_limit=1, raise_hold=30.0,
                             tick_interval=0.0)
    server = RpcServer(node, port=0, overload=ctl).start()
    try:
        yield loadgen, ctl, f"http://127.0.0.1:{server.port}"
    finally:
        server.stop()


def test_loadgen_classifies_shed_separately(busy_rpc):
    loadgen, ctl, url = busy_rpc
    hold = ctl.admit("eth_blockNumber")       # pin the single read slot
    assert hold.admitted
    try:
        h = loadgen.Harness(url, payload="ping", workers=2, timeout=5.0)
        rep = h.run(20.0, duration=0.5)
    finally:
        ctl.release(hold)
    assert rep["sent"] > 0
    assert rep["shed"] == rep["sent"]         # every request was shed
    assert rep["errors"] == 0                 # ...and none is an error
    assert rep["delivered"] == 0
    assert rep["shedRate"] == 1.0
    assert rep["scheduled"] == rep["delivered"] + rep["shed"] + \
        rep["missed"]
    # shed latencies live in their own histogram; the accepted-request
    # histogram stays empty so the serving p99 cannot be gamed
    assert rep["shedLatency"]["count"] == rep["shed"]
    assert rep["latency"]["count"] == 0
    assert rep["latency"]["p99"] is None


def test_sweep_counts_shed_as_not_delivered(busy_rpc):
    loadgen, ctl, url = busy_rpc
    hold = ctl.admit("eth_blockNumber")
    assert hold.admitted
    try:
        h = loadgen.Harness(url, payload="ping", workers=2, timeout=5.0)
        sweep = h.sweep([10.0], duration=0.5)
    finally:
        ctl.release(hold)
    # 100% graceful sheds and 0% errors is still NOT a sustained rate
    assert sweep["rates"][0]["errorRate"] == 0.0
    assert sweep["maxSustainableRate"] is None


def test_serving_record_carries_shed_rate():
    from ethrex_tpu.perf.bench_suite import build_serving_record

    sweep = {
        "arrivals": "fixed", "maxSustainableRate": 25.0,
        "rates": [
            {"offeredRate": 25.0, "achievedRate": 24.9, "errorRate": 0.0,
             "missed": 0, "shed": 3, "shedRate": 0.02,
             "latency": {"p50": 0.001, "p95": 0.002, "p99": 0.003}},
            {"offeredRate": 50.0, "achievedRate": 49.0, "errorRate": 0.0,
             "missed": 2, "shed": 30, "shedRate": 0.6,
             "latency": {"p50": 0.001, "p95": 0.002, "p99": 0.004}},
        ],
    }
    rec = build_serving_record(sweep)
    assert rec["value"] == 0.003          # accepted-only p99 at the pick
    assert rec["shed_rate"] == 0.02
    assert rec["rates"][1]["shed"] == 30
    assert rec["rates"][1]["shedRate"] == 0.6
    # sweeps recorded before shedding existed stay loadable
    old = {"arrivals": "fixed", "maxSustainableRate": 10.0,
           "rates": [{"offeredRate": 10.0, "achievedRate": 10.0,
                      "errorRate": 0.0, "missed": 0,
                      "latency": {"p50": 0.001, "p95": 0.002,
                                  "p99": 0.003}}]}
    assert build_serving_record(old)["shed_rate"] == 0.0


# ---------------------------------------------------------------------------
# surfaces: snapshot traffic section and monitor panel

def test_snapshot_traffic_includes_overload():
    from ethrex_tpu.utils import snapshot

    node = Node(Genesis.from_json(GENESIS))
    RpcServer(node, port=0)               # attaches node.rpc_overload
    out = snapshot.collect(node)["traffic"]
    assert out["overload"]["state"] == "ok"
    assert "shed" in out["rpc"]
    assert "shedLevel" in out["rpc"]


def test_monitor_traffic_panel_shows_shedding():
    from ethrex_tpu.utils.monitor import _traffic_lines

    snap = {"health": {"rpc": {
        "accepted": 10, "resets": 0, "eof": 0, "inflight": 1,
        "slowRequests": 0, "listenBacklog": 128, "requestBytes": 100,
        "responseBytes": 200, "wsConnections": 0, "wsNotifications": 0,
        "wsSendFailures": 0, "shed": 7, "shedLevel": 2,
        "wsNotificationsDropped": 3, "wsSlowConsumerDisconnects": 1,
    }}}
    text = "\n".join(_traffic_lines(snap, width=100))
    assert "shed 7" in text
    assert "shed level 2" in text
    assert "slow-consumer kicks 1" in text
    assert "{" not in text                # panels never leak raw dicts


def test_default_alert_rules_cover_shedding_and_churn():
    from ethrex_tpu.utils.alerts import default_rules

    names = {r.name for r in default_rules()}
    assert {"rpc_shed_rate:page", "rpc_shed_rate:warn",
            "mempool_replacement_churn:page",
            "mempool_replacement_churn:warn"} <= names
