"""EF BlockchainTest-format runner (VERDICT #5): the suite the public
archives plug into, exercised with self-generated smoke fixtures, plus
bit-exact decode parity with the reference's own chain.rlp fixture."""

import json
import os

import pytest

from ethrex_tpu.utils import ef_blockchain

FIX = os.path.join(os.path.dirname(__file__), "fixtures", "ef_blockchain")
REF = "/root/reference/fixtures/blockchain"


def test_smoke_fixture_file():
    res = ef_blockchain.run_fixture_file(os.path.join(FIX, "smoke.json"))
    assert res["failures"] == []
    assert res["passed"] == 5


def test_runner_catches_wrong_lastblockhash():
    with open(os.path.join(FIX, "smoke.json")) as f:
        units = json.load(f)
    unit = units["valid_transfer_contract_chain"]
    bad = dict(unit, lastblockhash="0x" + "ab" * 32)
    with pytest.raises(ef_blockchain.FixtureFailure, match="last valid"):
        ef_blockchain.run_unit("bad-last", bad)


def test_runner_catches_missing_exception():
    """A block marked expectException that imports cleanly must fail the
    unit (the reference runner's 'test expected failure' arm)."""
    with open(os.path.join(FIX, "smoke.json")) as f:
        units = json.load(f)
    unit = json.loads(json.dumps(units["valid_transfer_contract_chain"]))
    unit["blocks"][-1]["expectException"] = "InvalidStateRoot"
    with pytest.raises(ef_blockchain.FixtureFailure, match="accepted"):
        ef_blockchain.run_unit("should-fail", unit)


def test_runner_catches_post_state_mismatch():
    with open(os.path.join(FIX, "smoke.json")) as f:
        units = json.load(f)
    unit = json.loads(json.dumps(units["valid_transfer_contract_chain"]))
    for addr, acct in unit["postState"].items():
        acct["balance"] = hex(int(acct["balance"], 16) + 1)
        break
    with pytest.raises(ef_blockchain.FixtureFailure, match="balance"):
        ef_blockchain.run_unit("bad-post", unit)


# ---- the reference's own chain fixtures -----------------------------------

@pytest.mark.skipif(not os.path.isdir(REF),
                    reason="reference fixtures not available")
def test_reference_chain_rlp_decode_parity():
    """Reproduces the reference's decode test on fixtures/blockchain/
    chain.rlp — 20 blocks, numbers 1..20, and the same three block
    hashes (/root/reference/test/tests/cmd/decode_tests.rs:9-40).  Full
    replay is impossible hermetically: the chain's genesis (parent
    414c6377..) matches none of the vendored genesis files; the
    reference itself only decodes this fixture in tests."""
    from ethrex_tpu.primitives import rlp
    from ethrex_tpu.primitives.block import Block

    with open(f"{REF}/chain.rlp", "rb") as f:
        rest = f.read()
    blocks = []
    while rest:
        item, rest = rlp.decode_prefix(rest)
        blocks.append(Block.decode(rlp.encode(item)))
    assert len(blocks) == 20
    assert blocks[0].header.number == 1
    assert blocks[0].hash.hex() == ("ac5c61edb087a51279674fe01d5c1f65"
                                    "eac3fd8597f9bea215058e745df8088e")
    assert blocks[1].hash.hex() == ("a111ce2477e1dd45173ba93cac819e62"
                                    "947e62a63a7d561b6f4825fb31c22645")
    assert blocks[19].hash.hex() == ("8f64c4436f7213cfdf02cfb9f45d012f"
                                     "1774dfb329b8803de5e7479b11586902")
    # round-trip: re-encoding every block reproduces the fixture bytes
    with open(f"{REF}/chain.rlp", "rb") as f:
        raw = f.read()
    assert b"".join(b.encode() for b in blocks) == raw


@pytest.mark.skipif(not os.path.isdir(REF),
                    reason="reference fixtures not available")
def test_lfs_pointer_chains_documented():
    """2000-blocks.rlp and l2-1k-erc20.rlp are git-lfs POINTER files in
    the vendored reference (67 MB / 288 MB payloads never fetched —
    zero-egress image), so they cannot be replayed here.  This test
    documents that fact; if real payloads ever appear, it fails so they
    get wired into the replay suite."""
    for name in ("2000-blocks.rlp", "l2-1k-erc20.rlp"):
        with open(f"{REF}/{name}", "rb") as f:
            head = f.read(64)
        assert head.startswith(b"version https://git-lfs"), name
