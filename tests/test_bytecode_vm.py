"""Generic bytecode VM class (round 5, VERDICT #1): interpreter/checker
parity with the real executor, fine-log integration incl. no-op read
rows, witness replay, and (slow tier) the BytecodeAir STARK — a batch
containing a contract the templates don't cover proven with NO
claimed-log fallback, where tampering the write log defeats pure
`verify`."""

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.guest import access_log
from ethrex_tpu.guest import bytecode_vm as bv
from ethrex_tpu.guest import transfer_log as tl
from ethrex_tpu.guest.execution import ProgramInput, execution_program
from ethrex_tpu.guest.witness import generate_witness
from ethrex_tpu.guest.witness_oracles import WitnessOracles
from ethrex_tpu.models import bytecode_air as bca
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import Transaction
from ethrex_tpu.prover import tpu_backend as tb

SECRET = 0xA11CE
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
CONTRACT = bytes.fromhex("c0de" * 10)
RO = bytes.fromhex("0c0c" * 10)

# registry-with-guard: key=cdload(0), val=cdload(32);
# if sload(key) < val: sstore(key, val) else sstore(1000, val)
CODE = bytes([
    0x60, 0x00, 0x35, 0x60, 0x20, 0x35, 0x80, 0x82, 0x54, 0x10,
    0x61, 0x00, 0x14, 0x57, 0x61, 0x03, 0xE8, 0x55, 0x50, 0x00,
    0x5B, 0x90, 0x55, 0x00,
])
RO_CODE = bytes([0x60, 0x00, 0x54, 0x50, 0x00])   # sload(0); pop; stop

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {
        "0x" + SENDER.hex(): {"balance": hex(10**21)},
        "0x" + CONTRACT.hex(): {"balance": "0x0",
                                "code": "0x" + CODE.hex(),
                                "storage": {hex(5): hex(10)}},
        "0x" + RO.hex(): {"balance": "0x0", "code": "0x" + RO_CODE.hex()},
    },
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _cd(key, val):
    return key.to_bytes(32, "big") + val.to_bytes(32, "big")


def _tx(nonce, to, data):
    return Transaction(
        tx_type=2, chain_id=1337, nonce=nonce, max_priority_fee_per_gas=1,
        max_fee_per_gas=10**10, gas_limit=200_000, to=to, value=0,
        data=data).sign(SECRET)


def _batch():
    node = Node(Genesis.from_json(GENESIS))
    node.submit_transaction(_tx(0, CONTRACT, _cd(5, 42)))   # store branch
    node.submit_transaction(_tx(1, RO, b""))                 # read-only
    node.submit_transaction(_tx(2, CONTRACT, _cd(5, 7)))     # alt branch
    block = node.produce_block()
    assert len(block.body.transactions) == 3
    witness = generate_witness(node.chain, [block])
    return node, ProgramInput(blocks=[block], witness=witness,
                              config=node.config)


@pytest.fixture(scope="module")
def built():
    node, pi = _batch()
    coarse, receipts = [], []
    out = execution_program(pi, write_log=coarse, receipts_out=receipts)
    oracles = WitnessOracles(pi.witness, out.initial_state_root)
    vb = tl.build_vm_batch(pi.blocks, coarse, receipts, oracles=oracles)
    return pi, out, vb


def test_interpreter_matches_executor(built):
    _, _, vb = built
    assert len(vb.bc_calls) == 3
    # first call takes the store branch, third the alt branch
    ops0 = [s.op for s in vb.bc_calls[0].steps]
    ops2 = [s.op for s in vb.bc_calls[2].steps]
    assert bv.OP_SSTORE in ops0 and bv.OP_SSTORE in ops2
    assert ops0 != ops2     # different branches taken


def test_stream_recompute_and_code_pin(built):
    _, _, vb = built
    meta = tb._vm_meta_json(vb)
    assert meta["mode"] == "generic"
    items, tok_items, bc_pubs = tb._vm_stream_from_claims(meta,
                                                          vb.blocks_log)
    assert len(bc_pubs) == 3
    for call, pub in zip(vb.bc_calls, bc_pubs):
        assert pub == bca.bc_digest_stream(call.steps)
    # tamper the claimed code: the code-hash pin must reject
    bad = tb._vm_meta_json(vb)
    bad["codes"][CONTRACT.hex()] = (CODE + b"\x00").hex()
    with pytest.raises(ValueError):
        tb._vm_stream_from_claims(bad, vb.blocks_log)
    # tamper a stored value in the write log: step replay must reject
    bad_log = [list(rows) for rows in vb.blocks_log]
    for i, e in enumerate(bad_log[0]):
        if e[0] == "slot" and e[4] != e[3]:
            bad_log[0][i] = (e[0], e[1], e[2], e[3], e[4] ^ 1)
            break
    with pytest.raises(ValueError):
        tb._vm_stream_from_claims(tb._vm_meta_json(vb), bad_log)


def test_witness_replay_with_noop_rows(built):
    pi, out, vb = built
    access_log.replay_log_against_witness(
        vb.blocks_log, pi.witness.nodes,
        out.initial_state_root, out.final_state_root)


def test_checker_pins_control_flow(built):
    _, _, vb = built
    call = vb.bc_calls[0]
    meta = tb._vm_meta_json(vb)
    txm = meta["blocks"][0]["txs"][0]
    code = bytes.fromhex(meta["codes"][txm["to"]])
    data = bytes.fromhex(txm["data"])
    rows = [(s.a, 0, 0) for s in []]  # rebuilt below
    touched, seen = [], set()
    for s in call.steps:
        if s.op in (bv.OP_SLOAD, bv.OP_SSTORE) and s.a not in seen:
            seen.add(s.a)
            touched.append(s.a)
    rows = []
    cur = {}
    for e in vb.blocks_log[0]:
        if e[0] == "slot" and e[1] == CONTRACT and e[2] in touched \
                and e[2] not in cur:
            cur[e[2]] = True
            rows.append((e[2], e[3], e[4]))
    # legit passes
    bv.check_steps(code, data, SENDER, 0, call.steps, rows)
    # a step list that lands a jump off a JUMPDEST is rejected
    steps = [bv.StepRec.from_json(s.to_json()) for s in call.steps]
    for i, s in enumerate(steps):
        if s.op == bv.OP_JUMPI and i + 1 < len(steps) \
                and steps[i + 1].pc != s.pc + 1:
            steps[i + 1].pc = s.pc + 1  # claim fall-through instead
            break
    with pytest.raises(bv.StepCheckError):
        bv.check_steps(code, data, SENDER, 0, steps, rows)


def test_value_transfer_to_contract_falls_back():
    node = Node(Genesis.from_json(GENESIS))
    t = Transaction(
        tx_type=2, chain_id=1337, nonce=0, max_priority_fee_per_gas=1,
        max_fee_per_gas=10**10, gas_limit=200_000, to=RO, value=5,
        data=b"").sign(SECRET)
    node.submit_transaction(t)
    block = node.produce_block()
    assert len(block.body.transactions) == 1
    witness = generate_witness(node.chain, [block])
    pi = ProgramInput(blocks=[block], witness=witness, config=node.config)
    coarse, receipts = [], []
    out = execution_program(pi, write_log=coarse, receipts_out=receipts)
    oracles = WitnessOracles(pi.witness, out.initial_state_root)
    with pytest.raises(tl.NotTransferBatch):
        tl.build_vm_batch(pi.blocks, coarse, receipts, oracles=oracles)


@pytest.mark.slow
def test_bytecode_air_prove_verify():
    """The registry program proven by the BytecodeAir STARK; a trace that
    lies about the stored value cannot satisfy the constraints."""
    import numpy as np

    from ethrex_tpu.ops import babybear as bb
    from ethrex_tpu.stark import prover as sp
    from ethrex_tpu.stark import verifier as sv
    from ethrex_tpu.stark.prover import StarkParams

    pre = {5: 10}
    cd = _cd(5, 42)
    steps, snaps, writes = bv.run_trace(CODE, cd, SENDER, 0,
                                        lambda s: pre.get(s, 0))
    params = StarkParams(log_blowup=3, num_queries=40, log_final_size=4)
    air = bca.BytecodeAir()
    trace = bca.generate_bytecode_trace(steps, snaps)
    pub = bca.bytecode_public_inputs(steps)
    proof = sp.prove(air, trace, pub, params)
    assert sv.verify(air, proof, params)
    # flip one limb of the SSTORE record in the trace: no valid proof
    bad = trace.copy()
    k = next(i for i, s in enumerate(steps) if s.op == bv.OP_SSTORE)
    rows = slice(k * bca.SEG_LEN, (k + 1) * bca.SEG_LEN)
    bad[rows, bca.RB + 10] = (bad[rows, bca.RB + 10].astype(np.int64)
                              + 1) % bb.P
    p2 = sp.prove(air, bad, pub, params)
    assert not sv.verify(air, p2, params)


@pytest.mark.slow
def test_generic_batch_end_to_end():
    """TpuBackend on a batch with non-template contracts: NO claimed-log
    fallback (vm.mode == generic), pure verify accepts, tampering the
    write log's stored value makes pure verify reject, and
    verify_with_input audits the real witness."""
    node, pi = _batch()
    backend = tb.TpuBackend()
    proof = backend.prove(pi, "stark")
    assert proof["vm"]["mode"] == "generic"
    assert len(proof["bc_proofs"]) == 3
    assert backend.verify(proof)
    assert backend.verify_with_input(proof, pi)
    # tamper a stored value in the wire write log
    import copy

    bad = copy.deepcopy(proof)
    for rows in bad["write_log"]:
        for row in rows:
            if row[0] == "s" and row[3] != row[4]:
                row[4] = "%064x" % (int(row[4], 16) ^ 1)
                break
        else:
            continue
        break
    assert not backend.verify(bad)
