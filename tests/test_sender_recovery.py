"""Batched sender recovery + native secp256k1 engine battery.

Three contracts drilled here:

* the native engine (native/secp256k1.c) accepts EXACTLY the inputs the
  pure-Python oracle (crypto/secp256k1.py) accepts and returns the
  identical point — differential fuzz over signed round-trips and
  adversarial signatures (high-s, r >= N, rec_id 2/3 with r + N >= P,
  non-residue x, zero r/s, out-of-range rec_id);
* batched recovery under the worker pool yields byte-identical senders
  to serial `tx.sender()` for every tx type (legacy pre/post-155, 2930,
  1559, blob, 7702), seeding the `_sender` cache including the
  failed-recovery sentinel;
* the pipeline seats (add_block / add_blocks_in_batch / pipelined
  import / prewarm) produce the same chain with recovery batched as the
  tx-loop-inline recovery did.
"""

import random

import pytest

from ethrex_tpu.blockchain import sender_recovery
from ethrex_tpu.crypto import native_secp256k1, secp256k1
from ethrex_tpu.crypto.keccak import keccak256
from ethrex_tpu.primitives import rlp
from ethrex_tpu.primitives.transaction import (SENDER_INVALID, Transaction,
                                               TYPE_BLOB, TYPE_SET_CODE)

needs_native = pytest.mark.skipif(not native_secp256k1.available(),
                                  reason="native secp256k1 not built")

N = secp256k1.N
P = secp256k1.P


def _oracle_pub64(msg, r, s, rec):
    pub = secp256k1.recover(msg, r, s, rec)
    if pub is None:
        return None
    return pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")


# a small r whose x-coordinate is off the curve (x^3 + 7 a non-residue),
# forcing the expensive "recovery failed" path rather than a cheap check
NON_RESIDUE_R = next(r for r in range(2, 100)
                     if secp256k1.recover(b"\x55" * 32, r, 1, 0) is None)


@needs_native
def test_differential_fuzz_signed_roundtrips():
    rng = random.Random(0xEC)
    for i in range(40):
        secret = rng.randrange(1, N)
        msg = rng.randrange(0, 1 << 256).to_bytes(32, "big")
        r, s, rec = secp256k1.sign(msg, secret)
        if i % 3 == 1:
            s = N - s  # high-s twin: both engines must accept + agree
            rec ^= 1
        native = native_secp256k1.recover_pubkey_bytes(msg, r, s, rec)
        assert native == _oracle_pub64(msg, r, s, rec)
        assert native is not None
        # address dispatcher agrees with the pure pipeline
        assert secp256k1.recover_address(msg, r, s, rec) == \
            secp256k1.pubkey_to_address(secp256k1.recover(msg, r, s, rec))


@needs_native
def test_differential_fuzz_adversarial_inputs():
    rng = random.Random(0xAD)
    r_edges = [0, 1, NON_RESIDUE_R, N - 1, N, N + 1, P - N, P - N + 1,
               P - N - 1, (1 << 256) - 1]
    s_edges = [0, 1, N // 2, N // 2 + 1, N - 1, N, (1 << 256) - 1]
    for _ in range(400):
        msg = rng.randrange(0, 1 << 256).to_bytes(32, "big")
        r = rng.choice(r_edges + [rng.randrange(1, N)])
        s = rng.choice(s_edges + [rng.randrange(1, N)])
        rec = rng.randrange(0, 4)  # rec 2/3 exercises the r + N >= P gate
        native = native_secp256k1.recover_pubkey_bytes(msg, r, s, rec)
        assert native == _oracle_pub64(msg, r, s, rec), (r, s, rec)
    # out-of-range rec_id rejected without reaching the C layer
    assert native_secp256k1.recover(b"\x01" * 32, 1, 1, 4) is None
    assert native_secp256k1.recover(b"\x01" * 32, 1, 1, -1) is None


@needs_native
def test_native_batch_matches_single_calls():
    rng = random.Random(0xBA)
    items = []
    for i in range(24):
        secret = rng.randrange(1, N)
        msg = rng.randrange(0, 1 << 256).to_bytes(32, "big")
        r, s, rec = secp256k1.sign(msg, secret)
        if i % 4 == 0:
            r = NON_RESIDUE_R  # invalid entries interleaved with valid
        items.append((msg, r, s, rec))
    batch = native_secp256k1.recover_batch(items)
    singles = [native_secp256k1.recover_pubkey_bytes(*it) for it in items]
    assert batch == singles
    assert any(b is None for b in batch) and any(b for b in batch)
    assert native_secp256k1.recover_batch([]) == []


def _tx_of_every_type():
    """One signed tx per wire format, plus an unrecoverable one."""
    to = bytes([0x42]) * 20
    txs = [
        Transaction(tx_type=0, chain_id=None, nonce=0, gas_price=10**10,
                    gas_limit=21_000, to=to, value=1).sign(0xAA1),
        Transaction(tx_type=0, chain_id=1337, nonce=1, gas_price=10**10,
                    gas_limit=21_000, to=to, value=2).sign(0xAA2),
        Transaction(tx_type=1, chain_id=1337, nonce=2, gas_price=10**10,
                    gas_limit=25_000, to=to, value=3,
                    access_list=[(to, [1, 2])]).sign(0xAA3),
        Transaction(tx_type=2, chain_id=1337, nonce=3,
                    max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                    gas_limit=21_000, to=to, value=4).sign(0xAA4),
        Transaction(tx_type=TYPE_BLOB, chain_id=1337, nonce=4,
                    max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                    gas_limit=21_000, to=to, value=5,
                    max_fee_per_blob_gas=10**10,
                    blob_versioned_hashes=[b"\x01" + b"\x22" * 31],
                    ).sign(0xAA5),
        Transaction(tx_type=TYPE_SET_CODE, chain_id=1337, nonce=5,
                    max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                    gas_limit=80_000, to=to, value=6,
                    authorization_list=[{
                        "chain_id": 1337, "address": to, "nonce": 0,
                        "y_parity": 0, "r": 1, "s": 1}]).sign(0xAA6),
    ]
    bad = Transaction(tx_type=2, chain_id=1337, nonce=6,
                      max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                      gas_limit=21_000, to=to, value=7)
    bad.v, bad.r, bad.s = 0, NON_RESIDUE_R, 1
    return txs + [bad]


def test_batched_recovery_matches_serial_every_tx_type():
    serial = _tx_of_every_type()
    expected = [tx.sender() for tx in serial]
    assert expected[-1] is None and all(a is not None for a in expected[:-1])

    # wire round-trip drops the caches — recovery really runs cold
    batched = [Transaction.decode_canonical(tx.encode_canonical()) for tx in serial]
    assert all(tx._sender is None for tx in batched)
    n = sender_recovery.recover_senders(batched)
    assert n == len(batched)
    assert [tx.sender() for tx in batched] == expected
    # caches are seeded, including the failed-recovery sentinel
    assert batched[-1]._sender is SENDER_INVALID
    assert all(tx._sender == exp for tx, exp in
               zip(batched[:-1], expected[:-1]))
    # a second pass is a pure cache hit
    assert sender_recovery.recover_senders(batched) == 0


def test_batched_recovery_under_forced_pool_matches_serial():
    """Force a multi-worker pool (even on 1-CPU hosts) and a slice size
    that splits the batch, then check byte-identical results."""
    serial = _tx_of_every_type() * 3
    expected = [tx.sender() for tx in serial]
    batched = [Transaction.decode_canonical(tx.encode_canonical()) for tx in serial]
    sender_recovery.configure(4)
    try:
        assert sender_recovery.worker_count() == 4
        sender_recovery.recover_senders(batched)
    finally:
        sender_recovery.configure(None)
    assert [tx.sender() for tx in batched] == expected


def test_worker_count_resolution(monkeypatch):
    sender_recovery.configure(None)
    monkeypatch.setenv("ETHREX_SENDER_WORKERS", "3")
    assert sender_recovery.worker_count() == 3
    monkeypatch.setenv("ETHREX_SENDER_WORKERS", "junk")
    assert sender_recovery.worker_count() >= 1
    monkeypatch.delenv("ETHREX_SENDER_WORKERS")
    sender_recovery.configure(2)
    try:
        assert sender_recovery.worker_count() == 2
    finally:
        sender_recovery.configure(None)


def test_invalid_signature_memoized_not_recomputed(monkeypatch):
    """The expensive failure path must run EC recovery exactly once."""
    from ethrex_tpu.primitives import transaction as tx_mod

    calls = {"n": 0}
    real = secp256k1.recover_address

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(tx_mod.secp256k1, "recover_address", counting)
    bad = Transaction(tx_type=2, chain_id=1337, gas_limit=21_000,
                      to=bytes([0x42]) * 20)
    bad.v, bad.r, bad.s = 0, NON_RESIDUE_R, 1
    for _ in range(5):
        assert bad.sender() is None
    assert calls["n"] == 1
    assert bad._sender is SENDER_INVALID
    # re-signing resets the cache: the sentinel must not stick
    bad.sign(0xBB1)
    assert bad.sender() is not None


def test_cheap_failures_never_reach_recovery(monkeypatch):
    from ethrex_tpu.primitives import transaction as tx_mod

    def boom(*a, **kw):
        raise AssertionError("EC recovery must not run")

    monkeypatch.setattr(tx_mod.secp256k1, "recover_address", boom)
    to = bytes([0x42]) * 20
    high_s = Transaction(tx_type=2, chain_id=1337, gas_limit=21_000, to=to)
    high_s.v, high_s.r, high_s.s = 0, 1, N - 1  # high-s (EIP-2)
    assert high_s.sender() is None
    assert high_s._sender is SENDER_INVALID
    bad_v = Transaction(tx_type=2, chain_id=1337, gas_limit=21_000, to=to)
    bad_v.v, bad_v.r, bad_v.s = 7, 1, 1  # invalid y_parity for typed tx
    assert bad_v.sender() is None
    assert bad_v._sender is SENDER_INVALID


def test_7702_authorization_recovery_native_python_agree():
    """_apply_authorizations recovers authorities through the same
    dispatching recover_address; both engines must name the same
    authority for a well-formed tuple."""
    secret = 0xC0FFEE
    authority = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(secret))
    delegate = bytes([0x77]) * 20
    msg = keccak256(b"\x05" + rlp.encode([1337, delegate, 9]))
    r, s, y_parity = secp256k1.sign(msg, secret)
    via_dispatch = secp256k1.recover_address(msg, r, s, y_parity)
    pure = secp256k1.pubkey_to_address(secp256k1.recover(msg, r, s, y_parity))
    assert via_dispatch == pure == authority
    if native_secp256k1.available():
        raw = native_secp256k1.recover_pubkey_bytes(msg, r, s, y_parity)
        assert keccak256(raw)[12:] == authority


def test_pure_python_fallback_path(monkeypatch):
    """With the native engine unavailable, batched recovery degrades to
    serial pure-Python and still produces identical senders."""
    serial = _tx_of_every_type()
    expected = [tx.sender() for tx in serial]
    batched = [Transaction.decode_canonical(tx.encode_canonical()) for tx in serial]
    monkeypatch.setattr(native_secp256k1, "available", lambda: False)
    n = sender_recovery.recover_senders(batched)
    assert n == len(batched)
    assert [tx.sender() for tx in batched] == expected


def test_async_recovery_seeds_caches():
    txs = [Transaction.decode_canonical(t.encode_canonical()) for t in _tx_of_every_type()]
    pending = sender_recovery.recover_senders_async(txs)
    pending.wait()
    assert all(t._sender is not None for t in txs)
    assert sender_recovery.recover_senders_async([]).wait() is None

# ---------------------------------------------------------------------------
# Prewarm deadline + skip behavior (blockchain/prewarm.py)
# ---------------------------------------------------------------------------

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _funded_tx(nonce, value=100):
    return Transaction(
        tx_type=2, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21_000, to=bytes([0x42]) * 20, value=value).sign(SECRET)


def _fresh_node():
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    return Node(Genesis.from_json(GENESIS))


def test_prewarm_expired_deadline_runs_nothing():
    import time

    from ethrex_tpu.blockchain.prewarm import prewarm_transactions

    node = _fresh_node()
    parent = node.store.head_header()
    txs = [_funded_tx(n) for n in range(3)]
    assert prewarm_transactions(node.chain, parent, txs,
                                deadline=time.monotonic() - 1) == 0


def test_prewarm_skips_failing_tx_and_continues():
    from ethrex_tpu.blockchain.prewarm import prewarm_transactions

    node = _fresh_node()
    parent = node.store.head_header()
    bad = Transaction(tx_type=2, chain_id=1337, nonce=1,
                      max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                      gas_limit=21_000, to=bytes([0x42]) * 20, value=1)
    bad.v, bad.r, bad.s = 0, NON_RESIDUE_R, 1  # unrecoverable sender
    txs = [_funded_tx(0), bad, _funded_tx(1), _funded_tx(2)]
    ran = prewarm_transactions(node.chain, parent, txs)
    # the invalid tx is skipped, not the whole lane
    assert ran == 3


def test_prewarm_deadline_tracer_frame_boundary_abort():
    import time

    from ethrex_tpu.blockchain.prewarm import (_DeadlineAbort,
                                               _DeadlineTracer)

    live = _DeadlineTracer(time.monotonic() + 60)
    live.enter(None)
    live.exit(True, 0, b"")  # before the deadline: no-ops
    dead = _DeadlineTracer(time.monotonic() - 1)
    with pytest.raises(_DeadlineAbort):
        dead.enter(None)
    with pytest.raises(_DeadlineAbort):
        dead.exit(True, 0, b"")
    # no per-step hook: the native opcode loop must stay dispatched
    assert not hasattr(live, "step")


def test_prewarm_seeds_sender_caches_for_real_build():
    from ethrex_tpu.blockchain.prewarm import prewarm_transactions

    node = _fresh_node()
    parent = node.store.head_header()
    txs = [Transaction.decode_canonical(_funded_tx(n).encode_canonical())
           for n in range(3)]
    assert all(t._sender is None for t in txs)
    assert prewarm_transactions(node.chain, parent, txs) == 3
    assert all(t._sender == SENDER for t in txs)
