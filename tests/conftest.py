"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective tests run
against XLA's host platform with 8 virtual devices (SURVEY.md §7: the driver
separately dry-run-compiles the multi-chip path via __graft_entry__).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin ignores JAX_PLATFORMS; force CPU through jax.config
# (must happen before any computation touches a backend).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# persistent compilation cache: the STARK phase programs dominate test time
# on cold runs; cached XLA binaries make re-runs fast (host-fingerprinted —
# see ethrex_tpu/utils/jax_cache.py for why).
from ethrex_tpu.utils.jax_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
# the serialized-executable store only pays off ACROSS processes (the
# in-process phase cache already amortizes within one pytest run), so
# inside the suite its serialize + round-trip validation per fresh
# compile is pure overhead — off by default; exec-cache tests opt back
# in through their own env fixtures.
os.environ.setdefault("ETHREX_EXEC_CACHE_OFF", "1")


# ---------------------------------------------------------------------------
# fault-injection hygiene: a test that installs a FaultPlan must clear it
# before returning — a leaked plan would fire nondeterministically inside
# whatever test runs next (tests/test_prover_chaos.py is the battery).
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fault_plan_guard():
    yield
    from ethrex_tpu.utils import faults

    plan = faults.active()
    faults.clear()
    if plan is not None and plan.rules:
        pytest.fail(
            "test leaked a non-empty active FaultPlan "
            f"({len(plan.rules)} rule(s)); call faults.clear() "
            "or use the faults.injected() context manager")


@pytest.fixture(autouse=True)
def _metrics_isolation():
    """Snapshot/restore the process-global METRICS registry around every
    test, so counters incremented by one test cannot leak into another's
    assertions.  The global time-series ENGINE (which samples METRICS)
    and the flight-recorder destination are reset alongside — a sampler
    or snapshot dir left configured by one test must not fire in the
    next."""
    import copy

    from ethrex_tpu.utils.metrics import METRICS

    with METRICS.lock:
        saved = (dict(METRICS.counters), dict(METRICS.gauges),
                 copy.deepcopy(METRICS.histograms), dict(METRICS.help),
                 copy.deepcopy(METRICS.lgauges),
                 copy.deepcopy(METRICS.lcounters))
    yield
    from ethrex_tpu.perf import profiler, roofline
    from ethrex_tpu.utils import snapshot, timeseries

    timeseries.ENGINE.stop(timeout=2.0)
    timeseries.ENGINE.clear()
    snapshot.configure(None)
    # perf accumulators are process-global like METRICS: reset so one
    # test's prove cannot leak stage/kernel rows into another's report
    profiler.PROFILER.reset()
    profiler.configure(None)
    roofline.ROOFLINE.reset()
    # the chain-path X-ray singleton accumulates stage-queue and
    # lifecycle state from any test that produces blocks — reset it so
    # explain_chain_path() in one test cannot see another's traffic
    from ethrex_tpu.perf.chain_path import CHAIN_PATH
    CHAIN_PATH.reset()
    with METRICS.lock:
        METRICS.counters = dict(saved[0])
        METRICS.gauges = dict(saved[1])
        METRICS.histograms = saved[2]
        METRICS.help = dict(saved[3])
        METRICS.lgauges = saved[4]
        METRICS.lcounters = saved[5]


@pytest.fixture(autouse=True)
def _close_leaked_kv_backends():
    """Close any persistent KV handle a test left open (and release its
    flock) so one leaked backend cannot wedge every later test that
    reopens the same tmp path.  Silent: leaking is untidy, not a
    failure — the handle guards make post-close access raise cleanly."""
    yield
    import sys

    persistent = sys.modules.get("ethrex_tpu.storage.persistent")
    if persistent is not None:
        persistent.close_leaked_backends()
