"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective tests run
against XLA's host platform with 8 virtual devices (SURVEY.md §7: the driver
separately dry-run-compiles the multi-chip path via __graft_entry__).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin ignores JAX_PLATFORMS; force CPU through jax.config
# (must happen before any computation touches a backend).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# persistent compilation cache: the STARK phase programs dominate test time
# on cold runs; cached XLA binaries make re-runs fast.  The cache dir is
# keyed by a host-CPU fingerprint: XLA's AOT results embed machine features,
# and loading a cache written on a different host SIGSEGVs/SIGILLs (seen as
# "Compile machine features ... doesn't match" warnings before a crash).
import hashlib
import platform

try:
    with open("/proc/cpuinfo") as _f:
        _cpu = [ln for ln in _f if ln.startswith("flags")][0]
except (OSError, IndexError):
    _cpu = platform.processor() or "unknown"
_fp = hashlib.sha256(_cpu.encode()).hexdigest()[:12]
jax.config.update("jax_compilation_cache_dir",
                  f"/tmp/ethrex_tpu_jax_cache_{_fp}")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
