"""Storage chaos battery (docs/STORAGE_RESILIENCE.md): per-record
checksum corruption drills (detect -> quarantine -> rebuild when
derivable, fail fast when not), kill-at-every-write-point crash drills
over the journaled write groups (block import and batch settlement on
the same on-disk files), torn/replayed write-ahead journals, the
`store.open` / `store.put` / `store.flush` fault sites, restart-reopen
resumption, and the coordinated shutdown drain.

Select alone with `-m chaos`; the whole battery is in the fast tier.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from ethrex_tpu.l2.l1_client import InMemoryL1, PersistentInMemoryL1
from ethrex_tpu.l2.rollup_store import PersistentRollupStore
from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.prover import protocol
from ethrex_tpu.rpc.server import RpcServer, _health
from ethrex_tpu.storage.persistent import PersistentBackend, storage_stats
from ethrex_tpu.storage.store import CorruptRecord, Store
from ethrex_tpu.utils import faults, shutdown
from ethrex_tpu.utils.faults import FaultPlan
from ethrex_tpu.utils.repl import RpcSession
from ethrex_tpu.utils.shutdown import ShutdownManager, build_node_shutdown
from tests.test_l2_pipeline import GENESIS, _transfer

pytestmark = pytest.mark.chaos

CFG = SequencerConfig(needed_prover_types=(protocol.PROVER_EXEC,))


def _open_node(tmp_path):
    store = Store(PersistentBackend(str(tmp_path / "chain.db")))
    return Node(Genesis.from_json(GENESIS), store=store)


def _assert_chain_consistent(store):
    """Walk head -> genesis: every canonical entry, header link and body
    must agree — the all-or-nothing invariant after any crash."""
    cursor = store.head_header()
    while cursor.number > 0:
        assert store.canonical_hash(cursor.number) == cursor.hash
        assert store.get_body(cursor.hash) is not None
        parent = store.get_header(cursor.parent_hash)
        assert parent is not None and parent.number == cursor.number - 1
        cursor = parent
    assert store.canonical_hash(0) == cursor.hash


# ===========================================================================
# checksum envelope: detect -> quarantine -> rebuild / fail fast
# ===========================================================================

def test_corrupt_canonical_record_detected_and_rebuilt(tmp_path):
    """A canonical-chain index entry is derivable: a corrupt record is
    quarantined, rebuilt from the header parent-walk, and the rebuild is
    durable across a further reopen."""
    node = _open_node(tmp_path)
    for n in range(2):
        node.submit_transaction(_transfer(n))
        node.produce_block()
    good = node.store.canonical_hash(1)
    node.store.close()
    before = storage_stats()

    # valid native log record, broken checksum envelope — exactly what a
    # torn/bit-flipped store.put leaves behind
    b = PersistentBackend(str(tmp_path / "chain.db"))
    b.put_raw(b"canonical", (1).to_bytes(8, "big"), b"\x01\x00\x00\x00\x00j")
    b.close()

    store = Store(PersistentBackend(str(tmp_path / "chain.db")))
    assert store.canonical_hash(1) == good      # detected + rebuilt
    stats = storage_stats()
    assert stats["corrupt_records"] == before["corrupt_records"] + 1
    assert stats["rebuilt_records"] == before["rebuilt_records"] + 1
    _assert_chain_consistent(store)
    store.close()

    store2 = Store(PersistentBackend(str(tmp_path / "chain.db")))
    assert store2.canonical_hash(1) == good     # rebuild was durable
    assert storage_stats()["corrupt_records"] == stats["corrupt_records"]
    store2.close()


def test_corrupt_header_fails_fast_never_silently_served(tmp_path):
    """A header record is not derivable from other local data: the read
    must raise a diagnostic CorruptRecord — and the record must never be
    served afterwards either."""
    node = _open_node(tmp_path)
    node.submit_transaction(_transfer(0))
    node.produce_block()
    h1 = node.store.canonical_hash(1)
    node.store.close()

    b = PersistentBackend(str(tmp_path / "chain.db"))
    b.put_raw(b"headers", h1, b"\x01\xde\xad\xbe\xef" + b"garbage")
    b.close()

    store = Store(PersistentBackend(str(tmp_path / "chain.db")))
    with pytest.raises(CorruptRecord) as ei:
        store.headers[h1]
    msg = str(ei.value)
    assert "headers" in msg and "quarantined" in msg
    assert ei.value.table == "headers"
    # quarantined: gone, not garbage
    assert store.get_header(h1) is None
    assert ("headers", h1.hex()) in store.backend.quarantined
    store.close()


@pytest.mark.parametrize("kind", ["corrupt", "torn"])
def test_store_put_mangling_caught_by_envelope(tmp_path, kind):
    """Bytes mangled on their way to disk through the "store.put" site
    (bit flip or torn half-write) must be caught by the CRC envelope on
    the next read, not decoded."""
    path = str(tmp_path / "kv.db")
    backend = PersistentBackend(path)
    t = backend.table("scratch")
    rule = getattr(FaultPlan(), kind)
    with faults.injected(rule("store.put", times=1)):
        t[b"k"] = b"payload-bytes"
    backend.close()

    backend2 = PersistentBackend(path)
    t2 = backend2.table("scratch")
    with pytest.raises(CorruptRecord):
        t2[b"k"]
    assert t2.get(b"k") is None
    assert backend2.quarantined == [("scratch", b"k".hex())]
    backend2.close()


def test_store_open_fault_then_clean_retry(tmp_path):
    """An injected "store.open" failure surfaces to the caller; a retry
    without the fault opens the same files with the data intact."""
    path = str(tmp_path / "kv.db")
    backend = PersistentBackend(path)
    backend.table("scratch")[b"k"] = b"v"
    backend.close()
    with faults.injected(FaultPlan().error("store.open", times=1)):
        with pytest.raises(faults.InjectedFault):
            PersistentBackend(path)
    backend2 = PersistentBackend(path)
    assert backend2.table("scratch").get(b"k") == b"v"
    backend2.close()


# ===========================================================================
# write-ahead journal: torn -> discarded, durable -> replayed
# ===========================================================================

def test_torn_journal_write_discarded_on_reopen(tmp_path):
    """Crash mid-journal-write (torn "store.flush" leg 1): the batch
    never became durable, so reopen discards it — NONE of its ops may
    surface, and prior data is intact."""
    path = str(tmp_path / "kv.db")
    backend = PersistentBackend(path)
    t = backend.table("scratch")
    t[b"keep"] = b"1"
    before = storage_stats()
    with faults.injected(FaultPlan().torn("store.flush", times=1)):
        with pytest.raises(faults.InjectedFault):
            with backend.batch():
                t[b"a"] = b"A"
                t[b"b"] = b"B"
    assert os.path.exists(path + ".journal")
    # the handle is poisoned: no write may interleave with the pending
    # recovery
    with pytest.raises(OSError):
        t[b"c"] = b"C"
    backend.close()

    backend2 = PersistentBackend(path)
    t2 = backend2.table("scratch")
    assert t2.get(b"keep") == b"1"
    assert t2.get(b"a") is None and t2.get(b"b") is None
    assert storage_stats()["journal_discards"] == \
        before["journal_discards"] + 1
    assert not os.path.exists(path + ".journal")
    backend2.close()


def test_durable_journal_replayed_on_reopen(tmp_path):
    """Crash after the journal is durable but before any op applied
    (error at "store.flush" leg 2): reopen replays the WHOLE batch,
    including tombstones."""
    path = str(tmp_path / "kv.db")
    backend = PersistentBackend(path)
    t = backend.table("scratch")
    t[b"old"] = b"1"
    before = storage_stats()
    with faults.injected(FaultPlan().error("store.flush", times=1)):
        with pytest.raises(faults.InjectedFault):
            with backend.batch():
                t[b"a"] = b"A"
                t[b"b"] = b"B"
                t.pop(b"old")
    assert os.path.exists(path + ".journal")
    backend.close()

    backend2 = PersistentBackend(path)
    t2 = backend2.table("scratch")
    assert t2.get(b"a") == b"A" and t2.get(b"b") == b"B"
    assert t2.get(b"old") is None
    assert storage_stats()["journal_replays"] == \
        before["journal_replays"] + 1
    assert not os.path.exists(path + ".journal")
    backend2.close()


def test_aborted_batch_rolls_back_and_writes_nothing(tmp_path):
    """An exception inside the batch body (no crash) must restore the
    exact pre-batch cache state and leave no trace on disk."""
    path = str(tmp_path / "kv.db")
    backend = PersistentBackend(path)
    t = backend.table("scratch")
    t[b"keep"] = b"1"
    with pytest.raises(ValueError):
        with backend.batch():
            t[b"a"] = b"A"
            t.pop(b"keep")
            with backend.batch():    # reentrant: folds into the outer
                t[b"b"] = b"B"
            raise ValueError("abort")
    assert t.get(b"a") is None and t.get(b"b") is None
    assert t.get(b"keep") == b"1"
    backend.close()
    backend2 = PersistentBackend(path)
    t2 = backend2.table("scratch")
    assert t2.get(b"a") is None and t2.get(b"keep") == b"1"
    backend2.close()


# ===========================================================================
# kill-at-every-write-point: block import
# ===========================================================================

def test_kill_at_every_write_point_during_block_import(tmp_path):
    """Crash at the k-th durable write of a block import, for every k the
    import performs.  Each crash must reopen to a consistent chain (the
    journaled header/body/receipts/canonical/fork-choice group lands
    all-or-nothing) and resume block production on the same files."""
    node = _open_node(tmp_path)
    crashes = 0
    k = 0
    while True:
        nonce = node.store.latest_number()   # one transfer per block
        node.submit_transaction(_transfer(nonce))
        plan = faults.install(
            FaultPlan().error("store.put", after=k, times=1))
        try:
            try:
                node.produce_block()
            except Exception:
                # the injected error may surface wrapped by import-layer
                # handling; all that matters is that it was ours
                assert plan.log, "import failed without an injected fault"
            fired = bool(plan.log)
        finally:
            faults.clear()
        if not fired:
            # the import outran the schedule: every write point covered
            break
        crashes += 1
        node.store.close()

        node = _open_node(tmp_path)          # reopen the same files
        _assert_chain_consistent(node.store)
        # resume: the next block builds on whatever the crash left
        resume_nonce = node.store.latest_number()
        node.submit_transaction(_transfer(resume_nonce))
        block = node.produce_block()
        assert block.header.number == resume_nonce + 1
        _assert_chain_consistent(node.store)
        k += 1
    assert crashes >= 5, f"battery only crashed {crashes} write points"
    _assert_chain_consistent(node.store)
    node.store.close()


# ===========================================================================
# kill-at-every-write-point: batch settlement (rollup store)
# ===========================================================================

@pytest.mark.parametrize("k", range(8))
def test_kill_at_each_settlement_write_point(tmp_path, k):
    """Crash at the k-th durable rollup-store write during
    commit_next_batch.  The batch record group is journaled: reopening
    the same files either replays the full record or (torn journal)
    leaves none of it, and startup reconciliation + settlement still
    reach fully-verified."""
    path = str(tmp_path / "rollup.db")
    l1path = str(tmp_path / "l1.json")
    node = _open_node(tmp_path)
    l1 = PersistentInMemoryL1(l1path, [protocol.PROVER_EXEC])
    rollup = PersistentRollupStore(path)
    seq = Sequencer(node, l1, CFG, rollup=rollup)
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    node.store.flush()

    plan = faults.install(FaultPlan().error("store.put", after=k, times=1))
    try:
        try:
            seq.commit_next_batch()
        except Exception:
            assert plan.log, "commit failed without an injected fault"
        fired = bool(plan.log)
    finally:
        faults.clear()
    rollup.close()
    node.store.close()

    node2 = _open_node(tmp_path)
    l1b = PersistentInMemoryL1(l1path, [protocol.PROVER_EXEC])
    rollup2 = PersistentRollupStore(path)
    seq2 = Sequencer(node2, l1b, CFG, rollup=rollup2)
    if fired:
        # the commit tx mined before the crash (L1-first ordering); the
        # local record was journaled — replay or reconciliation must
        # yield a complete, committed batch, never a partial one
        assert l1b.last_committed_batch() == 1
    b = rollup2.get_batch(1)
    if b is None:
        assert seq2.commit_next_batch() is not None
        b = rollup2.get_batch(1)
    assert b is not None and b.committed
    assert rollup2.get_prover_input(1, CFG.commit_hash) is not None
    assert rollup2.get_blobs_bundle(1) is not None
    assert seq2.commit_next_batch() is None     # no duplicate commit
    assert l1b.last_committed_batch() == 1

    # settle to fully verified on the recovered stores
    from ethrex_tpu.guest.execution import ProgramInput
    from ethrex_tpu.prover.backend import get_backend

    backend = get_backend(protocol.PROVER_EXEC)
    stored = rollup2.get_prover_input(1, CFG.commit_hash)
    proof = backend.prove(ProgramInput.from_json(stored),
                          protocol.FORMAT_STARK)
    rollup2.store_proof(1, protocol.PROVER_EXEC, proof)
    seq2.send_proofs()
    assert l1b.last_verified_batch() == 1
    rollup2.close()
    node2.store.close()


def test_torn_settlement_journal_rebuilt_from_l1(tmp_path):
    """Torn journal during the settlement write group: the local batch
    record vanishes entirely; startup reconciliation rebuilds it from
    the L1 commitment and the batch still settles."""
    path = str(tmp_path / "rollup.db")
    l1path = str(tmp_path / "l1.json")
    node = _open_node(tmp_path)
    l1 = PersistentInMemoryL1(l1path, [protocol.PROVER_EXEC])
    rollup = PersistentRollupStore(path)
    seq = Sequencer(node, l1, CFG, rollup=rollup)
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    node.store.flush()
    with faults.injected(FaultPlan().torn("store.flush", times=1)):
        with pytest.raises(faults.InjectedFault):
            seq.commit_next_batch()
    assert l1.last_committed_batch() == 1   # the commit tx mined
    rollup.close()
    node.store.close()

    node2 = _open_node(tmp_path)
    l1b = PersistentInMemoryL1(l1path, [protocol.PROVER_EXEC])
    rollup2 = PersistentRollupStore(path)
    seq2 = Sequencer(node2, l1b, CFG, rollup=rollup2)
    assert seq2.rebuilt_batches_total >= 1
    b = rollup2.get_batch(1)
    assert b is not None and b.committed
    assert l1b.get_committed_commitment(1) == b.commitment
    rollup2.close()
    node2.store.close()


# ===========================================================================
# restart-reopen: a stopped node resumes where it left off
# ===========================================================================

def test_restart_reopen_serves_pre_restart_head(tmp_path):
    node = _open_node(tmp_path)
    server = RpcServer(node, "127.0.0.1", 0).start()
    node.submit_transaction(_transfer(0))
    node.produce_block()
    node.submit_transaction(_transfer(1))
    node.produce_block()
    head_hash = node.store.head_header().hash
    server.stop()
    assert node.stop()
    node.store.close()
    node.store.close()      # idempotent

    node2 = _open_node(tmp_path)
    assert node2.store.latest_number() == 2
    assert node2.store.head_header().hash == head_hash
    server2 = RpcServer(node2, "127.0.0.1", 0).start()
    try:
        rpc = RpcSession(f"http://127.0.0.1:{server2.port}")
        blk = rpc.call("eth_getBlockByNumber", ["0x2", False])
        assert blk["hash"] == "0x" + head_hash.hex()
        # block production resumes on top of the reopened head
        node2.submit_transaction(_transfer(2))
        assert node2.produce_block().header.number == 3
    finally:
        server2.stop()
        node2.store.close()


# ===========================================================================
# coordinated shutdown
# ===========================================================================

def test_shutdown_manager_drains_full_stack(tmp_path):
    """RPC + dev producer + sequencer actors + rollup/chain stores drain
    in dependency order within the deadline; every backend ends closed
    and the duration lands in health + metrics."""
    node = _open_node(tmp_path)
    rollup = PersistentRollupStore(str(tmp_path / "rollup.db"))
    l1 = InMemoryL1([protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,),
        block_time=0.05, commit_interval=0.05, proof_send_interval=0.05,
        watcher_interval=0.05), rollup=rollup)
    node.sequencer = seq
    server = RpcServer(node, "127.0.0.1", 0).start()
    node.start_dev_producer(0.05)
    seq.start()
    node.submit_transaction(_transfer(0))
    time.sleep(0.3)         # let some real work flow through the stack

    manager = build_node_shutdown(
        node=node, servers=[server], sequencer=seq,
        stores=[node.store, rollup], deadline=20.0)
    assert node.shutdown is manager
    health = _health(node)
    assert health["shutdown"]["phase"] == "running"
    assert set(health["l2"]["store"]) == {
        "corruptRecords", "rebuiltRecords", "journalReplays",
        "journalDiscards", "lastShutdownSeconds"}

    report = manager.run()
    assert report["phase"] == "done"
    assert all(step["ok"] for step in report["steps"]), report
    assert report["durationSeconds"] < 20.0
    phases = [step["phase"] for step in report["steps"]]
    assert phases == ["snapshot", "rpc", "sequencer", "producer",
                      "telemetry", "flush-close", "flush-close"]
    assert all(not t.is_alive() for t in seq._threads)
    assert node.store.backend.handle is None
    assert rollup.backend.handle is None
    assert shutdown.LAST_DURATION == report["durationSeconds"]
    from ethrex_tpu.utils.metrics import METRICS

    assert METRICS.gauges.get("shutdown_duration_seconds") == \
        report["durationSeconds"]
    # re-running is a no-op returning the same report
    assert manager.run() == report


def test_shutdown_deadline_skips_noncritical_still_closes(tmp_path):
    """Past the deadline, ordinary steps are skipped but the critical
    flush-close still runs — durability beats promptness."""
    backend = PersistentBackend(str(tmp_path / "kv.db"))
    manager = ShutdownManager(deadline=0.05)
    manager.register("slow", lambda t: time.sleep(0.2))
    manager.register("late", lambda t: None)
    manager.register("flush-close", lambda t: backend.close(),
                     critical=True)
    report = manager.run()
    by_phase = {step["phase"]: step for step in report["steps"]}
    assert by_phase["slow"]["ok"]
    assert by_phase["late"]["error"] == "deadline exhausted"
    assert by_phase["flush-close"]["ok"]
    assert backend.handle is None


def test_shutdown_step_failure_does_not_stop_the_drain(tmp_path):
    backend = PersistentBackend(str(tmp_path / "kv.db"))
    manager = ShutdownManager(deadline=5.0)
    manager.register("bad", lambda t: (_ for _ in ()).throw(
        RuntimeError("boom")))
    manager.register("flush-close", lambda t: backend.close(),
                     critical=True)
    report = manager.run()
    assert report["steps"][0]["ok"] is False
    assert "RuntimeError: boom" in report["steps"][0]["error"]
    assert report["steps"][1]["ok"]
    assert backend.handle is None


def test_sigterm_drains_running_node(tmp_path):
    """SIGTERM against a live `ethrex-tpu --dev` process (RPC + producer
    + layered persistent store): the drain completes, the process exits
    0, and the banner reports the shutdown duration."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ethrex_tpu.cli", "--dev",
         "--datadir", str(tmp_path / "data"), "--http.port", "0",
         "--block-time", "0.2", "--shutdown-deadline", "20"],
        cwd=repo, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    lines: list[str] = []

    def reader():
        for line in proc.stdout:
            lines.append(line)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    try:
        deadline = time.time() + 90
        while time.time() < deadline:
            if any("JSON-RPC listening" in ln for ln in lines):
                break
            if proc.poll() is not None:
                pytest.fail("node exited before listening:\n"
                            + "".join(lines))
            time.sleep(0.1)
        else:
            pytest.fail("node never started listening")
        time.sleep(0.5)     # let the dev producer tick at least once
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        t.join(timeout=5)
    out = "".join(lines)
    assert rc == 0, out
    assert "received SIGTERM; draining" in out
    assert "shutdown complete in" in out


# ===========================================================================
# health / monitor surfacing degrades gracefully
# ===========================================================================

def test_health_and_monitor_storage_surface_degrade_gracefully():
    from ethrex_tpu.utils.monitor import _storage_lines, render_lines

    node = Node(Genesis.from_json(GENESIS))
    h = _health(node)                    # L1-only: no l2, no shutdown
    assert "l2" not in h and "shutdown" not in h
    assert _storage_lines({"health": h}, 80) == []
    assert _storage_lines({"health": None}, 80) == []
    assert _storage_lines({"health": {"l2": {}}}, 80) == []

    l1 = InMemoryL1([protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, CFG)
    node.sequencer = seq
    h2 = _health(node)
    store = h2["l2"]["store"]
    assert store["corruptRecords"] >= 0
    lines = _storage_lines({"health": h2}, 80)
    assert any("storage resilience" in ln for ln in lines)

    # full render path with the section present (no crash, panel shown)
    snap = {"ts": 0, "head": {"number": 0, "hash": "0x00", "gas_used": 0,
                              "gas_limit": 1, "txs": 0, "base_fee": 0,
                              "timestamp": 0},
            "recent": [], "health": h2}
    assert any("storage resilience" in ln
               for ln in render_lines(snap, width=100))
