"""Two real nodes over real RLPx/TCP: handshake, status, full sync,
new-block propagation, transaction gossip (the reference's p2p test goals
without docker)."""

import time

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.p2p import eth_wire
from ethrex_tpu.p2p.connection import P2PServer, PeerError, full_sync
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
OTHER = bytes.fromhex("aa" * 20)

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _tx(nonce, value=100):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21000, to=OTHER, value=value,
    ).sign(SECRET)


@pytest.fixture()
def two_nodes():
    node_a = Node(Genesis.from_json(GENESIS))
    node_b = Node(Genesis.from_json(GENESIS))
    srv_a = P2PServer(node_a).start()
    srv_b = P2PServer(node_b).start()
    yield node_a, node_b, srv_a, srv_b
    srv_a.stop()
    srv_b.stop()
    node_a.stop()
    node_b.stop()


def test_handshake_and_full_sync(two_nodes):
    node_a, node_b, srv_a, srv_b = two_nodes
    # A mines 5 blocks
    for i in range(5):
        node_a.submit_transaction(_tx(i))
        node_a.produce_block()
    assert node_a.store.latest_number() == 5
    # B dials A over real TCP/RLPx and full-syncs
    peer = srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
    assert peer.remote_status.head_hash == node_a.store.head_header().hash
    imported = full_sync(peer, node_b)
    assert imported == 5
    assert node_b.store.head_header().hash == node_a.store.head_header().hash
    root = node_b.store.head_header().state_root
    assert node_b.store.account_state(root, OTHER).balance == 500


def test_new_block_propagation(two_nodes):
    node_a, node_b, srv_a, srv_b = two_nodes
    peer = srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
    # B mines a block and announces it; A imports
    node_b.submit_transaction(_tx(0))
    block = node_b.produce_block()
    # find A's peer object for the B connection
    deadline = time.time() + 5
    while time.time() < deadline and not srv_a.peers:
        time.sleep(0.05)
    peer.announce_block(block)
    deadline = time.time() + 5
    while time.time() < deadline and node_a.store.latest_number() < 1:
        time.sleep(0.05)
    assert node_a.store.head_header().hash == block.hash


def test_transaction_gossip(two_nodes):
    node_a, node_b, srv_a, srv_b = two_nodes
    srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
    deadline = time.time() + 5
    while time.time() < deadline and not srv_a.peers:
        time.sleep(0.05)
    tx = _tx(0, value=777)
    node_b.submit_transaction(tx)
    # B gossips its pending tx to A
    srv_b.peers[0].broadcast_transactions([tx])
    deadline = time.time() + 5
    while time.time() < deadline and len(node_a.mempool) < 1:
        time.sleep(0.05)
    assert node_a.mempool.get_transaction(tx.hash) is not None
    # A mines it
    block = node_a.produce_block()
    assert any(t.hash == tx.hash for t in block.body.transactions)


def test_receipts_and_pooled_hashes(two_nodes):
    node_a, node_b, srv_a, srv_b = two_nodes
    node_a.submit_transaction(_tx(0))
    block = node_a.produce_block()
    peer = srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
    receipts = peer.get_receipts([block.hash])
    assert len(receipts) == 1 and len(receipts[0]) == 1
    assert receipts[0][0].succeeded
    assert receipts[0][0].cumulative_gas_used == 21000
    # unknown hash -> empty list, not an error
    receipts = peer.get_receipts([b"\x99" * 32])
    assert receipts == [[]]
    # pooled-tx-hash announcement triggers a fetch: A requests the full tx
    # from B and imports it into its mempool
    tx = _tx(1)
    node_b.submit_transaction(tx)
    peer.announce_pooled_txs([tx])
    deadline = time.time() + 5
    while time.time() < deadline and \
            node_a.mempool.get_transaction(tx.hash) is None:
        time.sleep(0.05)
    assert node_a.mempool.get_transaction(tx.hash) is not None
    assert tx.hash in srv_a.peers[0].known_txs


def test_live_follow(two_nodes):
    """Nodes follow each other automatically: A produces, B imports via
    the gossip hook without any explicit sync calls."""
    node_a, node_b, srv_a, srv_b = two_nodes
    srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
    deadline = time.time() + 5
    while time.time() < deadline and not srv_a.peers:
        time.sleep(0.05)
    for i in range(3):
        node_a.submit_transaction(_tx(i))
        node_a.produce_block()  # on_new_block hook gossips automatically
    deadline = time.time() + 10
    while time.time() < deadline and node_b.store.latest_number() < 3:
        time.sleep(0.05)
    assert node_b.store.latest_number() == 3
    assert node_b.store.head_header().hash == node_a.store.head_header().hash


def test_chain_mismatch_rejected():
    node_a = Node(Genesis.from_json(GENESIS))
    other = dict(GENESIS)
    other["config"] = dict(GENESIS["config"])
    other["config"]["chainId"] = 999
    node_c = Node(Genesis.from_json(other))
    srv_a = P2PServer(node_a).start()
    srv_c = P2PServer(node_c).start()
    try:
        with pytest.raises((PeerError, ConnectionError, OSError)):
            srv_c.dial(srv_a.host, srv_a.port, srv_a.pub)
    finally:
        srv_a.stop()
        srv_c.stop()
        node_a.stop()
        node_c.stop()


# EIP-2124 fork id — checked against the spec's published mainnet vectors
# (genesis d4e56740..., Homestead..Petersburg block schedule).
MAINNET_GENESIS = bytes.fromhex(
    "d4e56740f876aef8c010b86a40d5f56745a118d0906a34e69aec8c0db1cb8fa3")
MAINNET_FORKS = {"homestead": 1150000, "dao": 1920000,
                 "tangerine": 2463000, "spurious": 2675000,
                 "byzantium": 4370000, "constantinople": 7280000,
                 "petersburg": 7280000}  # same block: folds in once


def _mainnet_config():
    from ethrex_tpu.primitives.genesis import ChainConfig
    cfg = ChainConfig(chain_id=1)
    cfg.block_forks = dict(MAINNET_FORKS)
    return cfg


def test_fork_id_eip2124_vectors():
    from ethrex_tpu.p2p.eth_wire import fork_id_for
    cfg = _mainnet_config()
    cases = [
        (0, (bytes.fromhex("fc64ec04"), 1150000)),        # unsynced
        (1149999, (bytes.fromhex("fc64ec04"), 1150000)),  # last Frontier
        (1150000, (bytes.fromhex("97c2c34c"), 1920000)),  # first Homestead
        (4369999, (bytes.fromhex("3edd5b10"), 4370000)),  # last Spurious
        (4370000, (bytes.fromhex("a00bc324"), 7280000)),  # first Byzantium
        (7280000, (bytes.fromhex("668db0af"), 0)),        # Petersburg, dedup
    ]
    for head, want in cases:
        assert fork_id_for(cfg, MAINNET_GENESIS, head, 0) == want, head


def test_fork_id_validation_rules():
    from ethrex_tpu.p2p.eth_wire import fork_id_for, validate_fork_id
    cfg = _mainnet_config()
    head = 7987396  # Petersburg-era mainnet head (EIP-2124 examples)

    def ok(remote):
        return validate_fork_id(cfg, MAINNET_GENESIS, head, 0, remote)

    assert ok((bytes.fromhex("668db0af"), 0))             # same, no next
    # same hash but remote announces a fork we already passed without it
    assert not ok((bytes.fromhex("668db0af"), 7280000))
    # stale remote naming the fork it has not applied yet -> compatible
    assert ok((bytes.fromhex("a00bc324"), 7280000))
    # stale remote NOT announcing the next fork -> incompatible
    assert not ok((bytes.fromhex("a00bc324"), 0))
    assert ok((bytes.fromhex("fc64ec04"), 1150000))       # far behind, ok
    assert not ok((bytes.fromhex("5cddc0e1"), 0))         # unknown schedule
    # remote ahead of us on our own schedule -> compatible
    early = 4369999
    ahead = fork_id_for(cfg, MAINNET_GENESIS, 7280000, 0)
    assert validate_fork_id(cfg, MAINNET_GENESIS, early, 0, ahead)
    # timestamp forks past genesis fold in; genesis-time ones do not
    cfg.time_forks = {"shanghai": 0, "cancun": 1681338455}
    with_time = fork_id_for(cfg, MAINNET_GENESIS, head, 0, genesis_time=0)
    assert with_time[1] == 1681338455  # announced as next, not yet passed
    passed = fork_id_for(cfg, MAINNET_GENESIS, head, 1681338455)
    assert passed[1] == 0 and passed[0] != with_time[0]


def test_fork_id_small_timestamp_devnet():
    """Devnet regression: a time fork whose timestamp is numerically small
    must still be judged against head TIME locally, never head number."""
    from ethrex_tpu.p2p.eth_wire import fork_id_for
    from ethrex_tpu.primitives.genesis import ChainConfig
    cfg = ChainConfig(chain_id=7)
    cfg.time_forks = {"cancun": 1700}
    g = b"\x11" * 32
    before = fork_id_for(cfg, g, 5000, 500, genesis_time=100)
    assert before[1] == 1700          # block 5000 alone does not pass it
    after = fork_id_for(cfg, g, 5000, 1700, genesis_time=100)
    assert after[1] == 0 and after[0] != before[0]


def test_peer_scoring(two_nodes):
    """Successful requests raise a peer's score; protocol violations and
    invalid blocks sink it; hitting SCORE_DISCONNECT closes the session
    and the server prunes the dead peer."""
    node_a, node_b, srv_a, srv_b = two_nodes
    node_a.submit_transaction(_tx(0))
    node_a.produce_block()
    peer = srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
    assert peer.score == 0
    headers = peer.get_block_headers(1, 1)
    assert headers and peer.score == 1          # success -> +1
    peer.get_block_bodies([headers[0].hash])
    assert peer.score == 2
    # a's view of b: feed it a structurally valid but INVALID block
    deadline = time.time() + 5
    while time.time() < deadline and not srv_a.peers:
        time.sleep(0.05)
    a_view = srv_a.peers[0]
    bad = node_a.store.get_block(node_a.store.head_header().hash)
    import dataclasses as _dc
    bad_header = _dc.replace(bad.header, state_root=b"\x42" * 32,
                             number=bad.header.number + 1,
                             parent_hash=bad.hash)
    from ethrex_tpu.primitives.block import Block as _B
    try:
        peer.send_msg(eth_wire.NEW_BLOCK,
                      eth_wire.encode_new_block(_B(bad_header, bad.body), 0))
    except OSError:
        pass   # eviction can close the pipe mid-send
    deadline = time.time() + 5
    while time.time() < deadline and a_view.score >= 0:
        time.sleep(0.05)
    assert a_view.score <= -25                  # invalid block penalty
    # sink the score to the disconnect threshold -> session closed + pruned
    for _ in range(10):
        a_view.record_failure(penalty=25)
    deadline = time.time() + 5
    while time.time() < deadline and srv_a.peers:
        time.sleep(0.05)
    assert a_view not in srv_a.peers


def test_eth69_negotiation_and_messages(two_nodes, monkeypatch):
    """Round 4: eth/69 — highest mutual version wins, Status69 carries the
    block range instead of the TD, the snap id space shifts by one, and
    receipts are served bloom-less (eth69/receipts.rs).  Round 5 raised
    the ceiling to 71, so this pins both ends at 69 to keep exercising
    the negotiation."""
    monkeypatch.setattr(eth_wire, "ETH_VERSIONS", (69, 68))
    node_a, node_b, srv_a, srv_b = two_nodes
    node_a.submit_transaction(_tx(0))
    node_a.produce_block()
    peer = srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
    assert peer.eth_version == 69
    assert peer.snap_offset == 0x22
    assert peer.remote_status.latest_block == 1
    assert peer.peer_block_range == (0, 1)
    # bloom-less receipts round-trip over the live session
    head_hash = node_a.store.head_header().hash
    receipts = peer.get_receipts([head_hash])
    want = node_a.store.get_receipts(head_hash)
    assert len(receipts[0]) == len(want) == 1
    got, exp = receipts[0][0], want[0]
    assert (got.succeeded, got.cumulative_gas_used) == \
        (exp.succeeded, exp.cumulative_gas_used)
    # the recomputed bloom matches (it never crossed the wire)
    assert got.bloom == exp.bloom


def test_eth69_wire_shapes():
    from ethrex_tpu.p2p import eth_wire as ew

    st = ew.Status69(version=69, network_id=7, genesis_hash=b"\x01" * 32,
                     fork_id=(b"\xaa" * 4, 99), earliest_block=3,
                     latest_block=12, latest_block_hash=b"\x02" * 32)
    rt = ew.Status69.decode(st.encode())
    assert rt == st and rt.head_hash == b"\x02" * 32
    payload = ew.encode_block_range_update(1, 9, b"\x03" * 32)
    assert ew.decode_block_range_update(payload) == (1, 9, b"\x03" * 32)
    import pytest as _pytest

    with _pytest.raises(ValueError):
        ew.decode_block_range_update(ew.encode_block_range_update(
            9, 1, b"\x03" * 32))


def test_eth71_negotiation_receipts_and_bals(two_nodes):
    """eth/71 is the highest mutual version: EIP-7975 resumable receipts
    (driven with a tiny soft cap to force the continuation loop) and
    EIP-8159 BlockAccessLists served + fetched over live RLPx."""
    node_a, node_b, srv_a, srv_b = two_nodes
    for i in range(3):
        node_a.submit_transaction(_tx(i))
        node_a.produce_block()
    peer = srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
    assert peer.eth_version == 71
    from ethrex_tpu.p2p import snap as snap_mod

    assert peer.snap_offset == snap_mod.SNAP_OFFSET_ETH71
    hashes = [node_a.store.get_canonical_block(n).hash
              for n in range(1, 4)]
    receipts = peer.get_receipts(hashes)
    assert len(receipts) == 3
    assert all(len(r) == 1 and r[0].succeeded for r in receipts)
    # force the EIP-7975 truncation/resume path with a tiny soft cap
    import ethrex_tpu.p2p.eth_wire as ew

    old_limit = ew.SOFT_RECEIPTS_LIMIT
    ew.SOFT_RECEIPTS_LIMIT = 1   # every receipt after the first truncates
    try:
        receipts2 = peer.get_receipts(hashes)
    finally:
        ew.SOFT_RECEIPTS_LIMIT = old_limit
    assert [len(r) for r in receipts2] == [1, 1, 1]
    assert all(r2[0].cumulative_gas_used == r1[0].cumulative_gas_used
               for r1, r2 in zip(receipts, receipts2))
    # EIP-8159 BALs: served for known blocks, None for unknown
    bals = peer.get_block_access_lists(hashes + [b"\xee" * 32])
    assert bals[3] is None
    for n, bal in zip(range(1, 4), bals[:3]):
        assert bal is not None
        bal.validate_ordering()
        block = node_a.store.get_canonical_block(n)
        parent = node_a.store.get_header(block.header.parent_hash)
        assert bal.hash() == node_a.chain.generate_bal(block, parent).hash()


def test_adversarial_payloads_do_not_kill_the_server(two_nodes):
    """A misbehaving peer sending garbage payloads for every eth/snap
    message id must not crash the serving node or poison other
    sessions (the reference's malformed-message handling seat).  The
    server may drop a session on garbage — the attacker re-dials so
    every message id actually reaches a live handler."""
    node_a, node_b, srv_a, srv_b = two_nodes
    node_a.submit_transaction(_tx(0))
    node_a.produce_block()
    garbage = [b"", b"\x00", b"\xff" * 8, b"\xc1\x80",
               bytes(range(64)), b"\xf8\x42" + b"\x99" * 0x42]
    evil = srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
    for msg_id in range(0x10, 0x29):
        for g in garbage:
            try:
                evil.send_msg(msg_id, g)
            except Exception:  # noqa: BLE001 — session dropped: re-dial
                try:
                    evil = srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
                    evil.send_msg(msg_id, g)
                except Exception:  # noqa: BLE001
                    pass
    time.sleep(0.5)
    # the server still serves a FRESH well-behaved session
    good = srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
    head = node_a.store.head_header()
    headers = good.get_block_headers(1, 1)
    assert headers and headers[0].hash == head.hash
    receipts = good.get_receipts([head.hash])
    assert receipts and len(receipts[0]) == 1


def test_oversized_and_lying_length_claims(two_nodes):
    """Serving stays bounded under absurd request sizes, verified on the
    RAW responses (not the client helper's padding): header serving is
    capped, and a mixed known/unknown receipts request returns aligned
    per-hash lists with the known block's receipts in position."""
    node_a, node_b, srv_a, srv_b = two_nodes
    node_a.submit_transaction(_tx(0))
    node_a.produce_block()
    peer = srv_b.dial(srv_a.host, srv_a.port, srv_a.pub)
    # ask for 100k headers: serving is capped, request completes
    rid = peer._next_request_id()
    payload = eth_wire.encode_get_block_headers(rid, 0, 100_000)
    headers = peer.request(eth_wire.GET_BLOCK_HEADERS, payload, rid)
    assert len(headers) <= 1024
    # RAW eth/70 receipts request: unknown hashes sandwiching the head
    head = node_a.store.head_header()
    hashes = [b"\x01" * 32, head.hash, b"\x02" * 32]
    rid = peer._next_request_id()
    payload = eth_wire.encode_get_receipts70(rid, 0, hashes)
    incomplete, lists = peer.request(eth_wire.GET_RECEIPTS, payload, rid)
    assert not incomplete
    assert [len(x) for x in lists] == [0, 1, 0]
    assert lists[1][0].succeeded
    # and a huge unknown-only request through the helper stays aligned
    many = [bytes([i % 256]) * 32 for i in range(2000)]
    receipts = peer.get_receipts(many)
    assert len(receipts) == 2000 and all(r == [] for r in receipts)
