"""The flagship fused prove step (parallel/core.py) vs a direct
re-derivation of each phase with the plain ops — catches regressions when
the fused step's phases are restructured for performance (MXU matmul DEEP
combination, scan-free inverses)."""

import numpy as np
import jax.numpy as jnp

from ethrex_tpu.ops import babybear as bb, ext, fri as fri_ops, ntt
from ethrex_tpu.ops import poseidon2 as p2
from ethrex_tpu.ops.fri import _fold_inv_points, _INV2
from ethrex_tpu.parallel.core import build_prove_step

LOG_N, WIDTH, LOG_BLOWUP, LOG_FINAL = 6, 8, 2, 4


def _commit_root_ref(leaves):
    digests = p2.hash_leaves(leaves)
    while digests.shape[0] > 1:
        digests = p2.compress(digests[0::2], digests[1::2])
    return digests[0]


def test_prove_step_matches_plain_op_composition():
    fn, (trace, zeta, gamma, betas) = build_prove_step(
        LOG_N, WIDTH, LOG_BLOWUP, LOG_FINAL, mesh=None)
    troot, fri_roots, final_cw = fn(trace, zeta, gamma, betas)

    n = 1 << LOG_N
    N = n << LOG_BLOWUP
    shift = bb.GENERATOR
    # phase 1-2: LDE + commit
    lde_rows = ntt.coset_lde(trace, LOG_BLOWUP, shift=shift).T
    np.testing.assert_array_equal(np.asarray(troot),
                                  np.asarray(_commit_root_ref(lde_rows)))
    # phase 3: DEEP combination, written the direct (pre-MXU) way
    pts_m = jnp.asarray(bb.to_mont_host(
        ntt.domain_points(LOG_N + LOG_BLOWUP, shift)))
    tcoeffs = ntt.intt(trace)
    pows = ext.ext_powers(zeta, n)
    tz = bb.sum_mod(bb.mont_mul(pows, tcoeffs[..., None]), axis=-2)
    x_ext = jnp.concatenate(
        [bb.sub(pts_m, jnp.broadcast_to(zeta[0], (N,)))[:, None],
         jnp.broadcast_to(bb.neg(zeta[1:]), (N, 3))], axis=-1)
    inv_xz = ext.batch_inv(x_ext)
    gpow = ext.ext_powers(gamma, WIDTH)
    diff = ext.sub(ext.from_base(lde_rows), tz[None])
    comb = bb.sum_mod(ext.mul(diff, gpow[None]), axis=1)
    cw = ext.mul(comb, inv_xz)
    # phase 4: FRI fold chain
    fold_invs = []
    s = shift
    L = (LOG_N + LOG_BLOWUP) - LOG_FINAL
    for k in range(L):
        fold_invs.append(jnp.asarray(
            _fold_inv_points(LOG_N + LOG_BLOWUP - k, s)))
        s = (s * s) % bb.P
    inv2 = jnp.asarray(np.uint32(int(bb.to_mont_host(_INV2))))
    for k in range(L):
        leaves = fri_ops._pair_leaves(cw)
        np.testing.assert_array_equal(
            np.asarray(fri_roots[k]), np.asarray(_commit_root_ref(leaves)))
        cw = fri_ops._fold(cw, betas[k], fold_invs[k], inv2)
    np.testing.assert_array_equal(np.asarray(final_cw), np.asarray(cw))
