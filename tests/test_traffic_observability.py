"""Traffic observability: RPC request-lifecycle telemetry, mempool flow
accounting with typed rejection reasons, and their surfaces
(ethrex_health, flight-recorder snapshots, monitor panel, alert rules,
the --rpc-backlog knob)."""

import json
import logging
import urllib.request

import pytest

from ethrex_tpu.blockchain.mempool import (
    BlobsMissingError,
    InsufficientFundsError,
    InvalidSignatureError,
    Mempool,
    MempoolError,
    NonceTooLowError,
    PrivilegedTxError,
    UnderpricedError,
)
from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import (TYPE_DYNAMIC_FEE,
                                               TYPE_PRIVILEGED, Transaction)
from ethrex_tpu.rpc.server import RpcServer
from ethrex_tpu.utils.metrics import METRICS

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _tx(nonce, secret=SECRET, fee=10**10, value=1):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=fee,
        gas_limit=21_000, to=bytes([0xAA]) * 20, value=value).sign(secret)


def _labeled(snap, name):
    return {tuple(sorted(e["labels"].items())): e["value"]
            for e in snap["labeled_counters"].get(name, [])}


# ---------------------------------------------------------------------------
# typed rejection reasons — differential against the legacy behavior

def test_rejection_reasons_typed_and_counted():
    """Every legacy rejection path must (a) raise the same-message error
    it always raised — now as a typed subclass, still a ValueError-free
    MempoolError — and (b) land in both the pool-local tallies and the
    labelled registry counter under its machine-readable reason."""
    pool = Mempool(capacity=10)
    balance = 10**21
    cases = []

    with pytest.raises(PrivilegedTxError, match="privileged txs bypass"):
        pool.add_transaction(
            Transaction(tx_type=TYPE_PRIVILEGED, chain_id=1337,
                        from_addr=SENDER, gas_limit=21_000),
            0, balance, 7)
    cases.append("privileged")

    with pytest.raises(InvalidSignatureError, match="invalid signature"):
        pool.add_transaction(
            Transaction(tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=0,
                        max_fee_per_gas=10**10, gas_limit=21_000,
                        to=bytes(20)),   # unsigned
            0, balance, 7)
    cases.append("invalid_signature")

    with pytest.raises(NonceTooLowError, match="nonce too low"):
        pool.add_transaction(_tx(0), 5, balance, 7)
    cases.append("nonce_too_low")

    with pytest.raises(InsufficientFundsError, match="insufficient funds"):
        pool.add_transaction(_tx(0), 0, 10, 7)
    cases.append("insufficient_funds")

    with pytest.raises(BlobsMissingError, match="requires blobs bundle"):
        from ethrex_tpu.primitives.transaction import TYPE_BLOB

        blob_tx = Transaction(
            tx_type=TYPE_BLOB, chain_id=1337, nonce=0,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            max_fee_per_blob_gas=1, gas_limit=21_000,
            to=bytes([0xAA]) * 20).sign(SECRET)
        pool.add_transaction(blob_tx, 0, balance, 7)
    cases.append("blobs_missing")

    pool.add_transaction(_tx(0), 0, balance, 7)
    with pytest.raises(UnderpricedError, match="replacement underpriced"):
        pool.add_transaction(_tx(0, fee=10**10 + 1), 0, balance, 7)
    cases.append("underpriced")

    assert pool.rejections == {r: 1 for r in cases}
    # every typed error IS a MempoolError carrying its reason
    for cls in (PrivilegedTxError, InvalidSignatureError, NonceTooLowError,
                InsufficientFundsError, BlobsMissingError,
                UnderpricedError):
        assert issubclass(cls, MempoolError)
        assert cls.reason in cases
    by_reason = _labeled(METRICS.snapshot(), "mempool_rejections_by_reason")
    for reason in cases:
        assert by_reason[(("reason", reason),)] >= 1
    assert METRICS.snapshot()["counters"][
        "mempool_rejections_total"] >= len(cases)


def test_admission_eviction_and_dwell_accounting():
    pool = Mempool(capacity=2)
    balance = 10**21
    h0 = pool.add_transaction(_tx(0), 0, balance, 7)
    pool.add_transaction(_tx(1), 0, balance, 7)
    assert pool.admitted == 2
    assert h0 in pool.added_at

    # FIFO eviction on overflow: counted, never raised (pinned behavior)
    pool.add_transaction(_tx(2), 0, balance, 7)
    assert pool.evictions == {"fifo": 1}
    assert len(pool) == 2 and h0 not in pool.by_hash

    # replacement counts as its own eviction flavor
    pool.add_transaction(_tx(2, fee=2 * 10**10), 0, balance, 7)
    assert pool.evictions["replaced"] == 1

    # inclusion observes dwell time into the histogram and is NOT an
    # eviction
    pool.remove_transaction(_tx(1).hash, reason="included")
    snap = METRICS.snapshot()
    hist = snap["histograms"]["mempool_time_in_pool_seconds"]
    assert sum(s["counts"][-1] for s in hist["series"]) >= 1
    assert "included" not in pool.evictions
    assert snap["gauges"]["mempool_size"] == float(len(pool))

    stats = pool.stats_json(top_k=3)
    assert stats["admitted"] == 4
    assert stats["evictions"] == {"fifo": 1, "replaced": 1}
    assert stats["size"] == len(pool)
    assert 0 < stats["utilization"] <= 1
    assert stats["topSenders"][0]["sender"] == "0x" + SENDER.hex()
    assert stats["topSenders"][0]["txs"] == len(pool)


def test_wrong_chain_id_counted_at_node_boundary():
    node = Node(Genesis.from_json(GENESIS))
    bad = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=2, nonce=0,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21_000, to=bytes(20), value=1).sign(SECRET)
    from ethrex_tpu.evm.executor import InvalidTransaction

    with pytest.raises(InvalidTransaction, match="wrong chain id"):
        node.submit_transaction(bad)
    assert node.mempool.rejections.get("wrong_chain_id") == 1
    node.stop()


# ---------------------------------------------------------------------------
# RPC request-lifecycle telemetry over real TCP

@pytest.fixture()
def live_rpc():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, port=0, backlog=7).start()
    url = f"http://127.0.0.1:{server.port}"

    def call(method, *params):
        payload = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                              "params": list(params)}).encode()
        req = urllib.request.Request(
            url, data=payload, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    yield call, node, server
    server.stop()
    node.stop()


def test_request_lifecycle_metrics(live_rpc):
    call, node, server = live_rpc
    for _ in range(3):
        assert call("eth_blockNumber")["result"] == "0x0"
    snap = METRICS.snapshot()
    # accept + queue-wait: every connection passed the accept->handler
    # seam exactly once
    assert snap["counters"]["rpc_connections_accepted_total"] >= 1
    qw = snap["histograms"]["rpc_queue_wait_seconds"]
    assert sum(s["counts"][-1] for s in qw["series"]) >= 1
    # byte accounting in both directions
    assert snap["counters"]["rpc_request_bytes_total"] > 0
    assert snap["counters"]["rpc_response_bytes_total"] > 0
    # in-flight gauges drained back to zero at rest
    assert snap["gauges"]["rpc_inflight_requests"] == 0.0
    for entry in snap["labeled_gauges"].get("rpc_method_inflight", []):
        assert entry["value"] == 0.0
    # the backlog knob is both applied and exported
    assert server._httpd.request_queue_size == 7
    assert snap["gauges"]["rpc_listen_backlog"] == 7.0


def test_slow_request_logs_trace_id(live_rpc, monkeypatch, caplog):
    call, node, server = live_rpc
    from ethrex_tpu.rpc import server as server_mod

    monkeypatch.setattr(server_mod, "SLOW_REQUEST_SECONDS", 0.0)
    with caplog.at_level(logging.WARNING, logger="ethrex.rpc"):
        call("eth_blockNumber")
    slow = [r for r in caplog.records
            if "slow rpc request" in r.getMessage()]
    assert slow
    msg = slow[0].getMessage()
    assert "method=eth_blockNumber" in msg
    assert "traceId=" in msg and "traceId=None" not in msg
    assert METRICS.snapshot()["counters"]["rpc_slow_requests_total"] >= 1


def test_health_exposes_traffic_sections(live_rpc):
    call, node, server = live_rpc
    call("eth_blockNumber")
    health = call("ethrex_health")["result"]
    rpc = health["rpc"]
    for key in ("accepted", "resets", "eof", "inflight", "listenBacklog",
                "requestBytes", "responseBytes", "slowRequests",
                "wsConnections", "wsNotifications", "wsSendFailures"):
        assert key in rpc, key
    assert rpc["accepted"] >= 1
    assert rpc["listenBacklog"] == 7
    flow = health["mempoolFlow"]
    for key in ("size", "capacity", "utilization", "admitted",
                "rejections", "evictions", "topSenders"):
        assert key in flow, key


def test_snapshot_bundle_has_traffic_section(live_rpc):
    call, node, server = live_rpc
    call("eth_blockNumber")
    from ethrex_tpu.utils import snapshot

    bundle = snapshot.collect(node, reason="test")
    traffic = bundle["traffic"]
    assert traffic["rpc"]["accepted"] >= 1
    assert traffic["mempoolFlow"]["size"] == 0
    # collect() without a node still answers the rpc side
    assert "rpc" in snapshot.collect(None)["traffic"]


def test_monitor_renders_traffic_panel(live_rpc):
    call, node, server = live_rpc
    call("eth_blockNumber")
    from ethrex_tpu.utils import monitor

    health = call("ethrex_health")["result"]
    lines = monitor._traffic_lines({"health": health}, width=100)
    text = "\n".join(lines)
    assert " rpc traffic" in text
    assert "accepted" in text and "backlog 7" in text
    assert " mempool flow" in text
    # raw nested dicts must NOT leak into the health dump panel
    assert "{" not in text


# ---------------------------------------------------------------------------
# knob plumbing + alert rules

def test_cli_backlog_flag_and_env(monkeypatch):
    from ethrex_tpu import cli
    import argparse

    parser = argparse.ArgumentParser()
    cli._add_node_flags(parser)
    assert parser.parse_args([]).rpc_backlog == 128
    assert parser.parse_args(["--rpc-backlog", "9"]).rpc_backlog == 9
    monkeypatch.setenv("ETHREX_RPC_BACKLOG", "33")
    parser2 = argparse.ArgumentParser()
    cli._add_node_flags(parser2)
    assert parser2.parse_args([]).rpc_backlog == 33


def test_traffic_alert_rules_present_and_ordered():
    from ethrex_tpu.utils.alerts import default_rules

    rules = {r.name: r for r in default_rules()}
    assert rules["rpc_request_p99:page"].severity == "page"
    assert rules["rpc_request_p99:warn"].severity == "warn"
    assert rules["mempool_saturation:page"].threshold > \
        rules["mempool_saturation:warn"].threshold
    for name in ("rpc_request_p99:page", "rpc_request_p99:warn",
                 "mempool_saturation:page", "mempool_saturation:warn"):
        assert rules[name].description and rules[name].runbook


def test_mempool_saturation_signal_reads_occupancy_gauge():
    """The alert signal chain end-to-end: admissions publish the
    utilization gauge; the engine samples it; gauge_signal reads it."""
    from ethrex_tpu.utils import timeseries
    from ethrex_tpu.utils.alerts import gauge_signal

    pool = Mempool(capacity=4)
    pool.add_transaction(_tx(0), 0, 10**21, 7)
    engine = timeseries.TimeSeriesEngine()
    engine.sample_now()
    value = gauge_signal("mempool_utilization")(engine, None)
    assert value == pytest.approx(0.25)
