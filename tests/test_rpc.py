"""JSON-RPC integration: real HTTP server, real requests (the reference's
test/tests/rpc pattern with the in-memory store as the universal fake)."""

import json
import urllib.request

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.rpc.serializers import hx
from ethrex_tpu.rpc.server import RpcServer

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
OTHER = bytes.fromhex("aa" * 20)

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


@pytest.fixture(scope="module")
def rpc():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, port=0).start()
    url = f"http://127.0.0.1:{server.port}"

    def call(method, *params):
        payload = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                              "params": list(params)}).encode()
        req = urllib.request.Request(
            url, data=payload, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())

    yield call, node
    server.stop()
    node.stop()


def test_basic_queries(rpc):
    call, node = rpc
    assert call("eth_chainId")["result"] == "0x539"
    assert call("eth_blockNumber")["result"] == "0x0"
    bal = call("eth_getBalance", "0x" + SENDER.hex(), "latest")["result"]
    assert int(bal, 16) == 10**21
    blk = call("eth_getBlockByNumber", "0x0", False)["result"]
    assert blk["number"] == "0x0"
    assert call("net_version")["result"] == "1337"
    assert call("web3_clientVersion")["result"].startswith("ethrex-tpu")


def test_send_tx_produce_block_receipt(rpc):
    call, node = rpc
    tx = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=0,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21000, to=OTHER, value=4242,
    ).sign(SECRET)
    resp = call("eth_sendRawTransaction",
                "0x" + tx.encode_canonical().hex())
    assert resp["result"] == "0x" + tx.hash.hex()
    # pending nonce reflects the queued tx
    assert call("eth_getTransactionCount", "0x" + SENDER.hex(),
                "pending")["result"] == "0x1"
    # mine it
    call("ethrex_produceBlock")
    assert call("eth_blockNumber")["result"] == "0x1"
    rec = call("eth_getTransactionReceipt",
               "0x" + tx.hash.hex())["result"]
    assert rec["status"] == "0x1"
    assert int(rec["gasUsed"], 16) == 21000
    assert int(call("eth_getBalance", "0x" + OTHER.hex(),
                    "latest")["result"], 16) == 4242
    full = call("eth_getBlockByNumber", "0x1", True)["result"]
    assert full["transactions"][0]["hash"] == "0x" + tx.hash.hex()


def test_eth_call_and_estimate(rpc):
    call, node = rpc
    # deploy a contract returning 7: runtime 60075f5260205ff3
    runtime = "60075f5260205ff3"
    initcode = "67" + runtime + "5f5260086018f3"
    nonce = int(call("eth_getTransactionCount", "0x" + SENDER.hex(),
                     "latest")["result"], 16)
    tx = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=200_000, to=b"", data=bytes.fromhex(initcode),
    ).sign(SECRET)
    call("eth_sendRawTransaction", "0x" + tx.encode_canonical().hex())
    call("ethrex_produceBlock")
    rec = call("eth_getTransactionReceipt", "0x" + tx.hash.hex())["result"]
    assert rec["status"] == "0x1"
    addr = rec["contractAddress"]
    assert call("eth_getCode", addr, "latest")["result"] == "0x" + runtime
    out = call("eth_call", {"to": addr}, "latest")["result"]
    assert int(out, 16) == 7
    est = call("eth_estimateGas", {"to": addr}, "latest")["result"]
    assert 21000 <= int(est, 16) < 30000


def test_get_proof_and_witness(rpc):
    call, node = rpc
    # eth_getProof verifies against the state root
    proof = call("eth_getProof", "0x" + SENDER.hex(), [], "latest")["result"]
    from ethrex_tpu.crypto.keccak import keccak256
    from ethrex_tpu.trie.trie import verify_proof
    root = node.store.head_header().state_root
    nodes = [bytes.fromhex(p[2:]) for p in proof["accountProof"]]
    ok, value = verify_proof(root, keccak256(SENDER), nodes)
    assert ok and value is not None
    from ethrex_tpu.primitives.account import AccountState
    acct = AccountState.decode(value)
    assert acct.balance == int(proof["balance"], 16)
    # debug_executionWitness -> stateless re-execution round trip over RPC
    head = node.store.latest_number()
    assert head >= 1
    wit_json = call("debug_executionWitness", "0x1", hex(head))["result"]
    from ethrex_tpu.guest.execution import ProgramInput, execution_program
    from ethrex_tpu.guest.witness import ExecutionWitness
    blocks = [node.store.get_canonical_block(n) for n in range(1, head + 1)]
    pi = ProgramInput(blocks=blocks,
                      witness=ExecutionWitness.from_json(wit_json),
                      config=node.config)
    out = execution_program(pi)
    assert out.final_state_root == blocks[-1].header.state_root


def test_debug_trace_transaction(rpc):
    call, node = rpc
    nonce = int(call("eth_getTransactionCount", "0x" + SENDER.hex(),
                     "latest")["result"], 16)
    # trace an existing transfer from the earlier test
    txs = call("eth_getBlockByNumber", "0x1", True)["result"]["transactions"]
    # geth default (no tracer option) = structLogs
    struct = call("debug_traceTransaction", txs[0]["hash"])["result"]
    assert "structLogs" in struct and struct["failed"] is False
    assert struct["gas"] == 21000
    trace = call("debug_traceTransaction", txs[0]["hash"],
                 {"tracer": "callTracer"})["result"]
    assert trace["type"] == "CALL"
    assert trace["from"] == txs[0]["from"]
    assert int(trace["gasUsed"], 16) >= 0
    # deploy + call with inner CALL to the identity precompile for a tree
    runtime = "60045f5f5f5f600461fffff15f5260205ff3"
    initcode = "71" + runtime + "5f526012600ef3"
    tx2 = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=300_000, to=b"", data=bytes.fromhex(initcode),
    ).sign(SECRET)
    call("eth_sendRawTransaction", "0x" + tx2.encode_canonical().hex())
    call("ethrex_produceBlock")
    rec = call("eth_getTransactionReceipt",
               "0x" + tx2.hash.hex())["result"]
    assert rec["status"] == "0x1"
    addr = rec["contractAddress"]
    tx3 = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce + 1,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=100_000, to=bytes.fromhex(addr[2:]), value=0,
    ).sign(SECRET)
    call("eth_sendRawTransaction", "0x" + tx3.encode_canonical().hex())
    call("ethrex_produceBlock")
    trace = call("debug_traceTransaction", "0x" + tx3.hash.hex(),
                 {"tracer": "callTracer"})["result"]
    assert trace["type"] == "CALL" and trace["to"] == addr
    assert len(trace.get("calls", [])) == 1
    inner = trace["calls"][0]
    assert inner["type"] == "CALL"
    assert inner["to"] == "0x" + "00" * 19 + "04"  # identity precompile
    # deploy trace shows CREATE
    trace2 = call("debug_traceTransaction", "0x" + tx2.hash.hex(),
                  {"tracer": "callTracer"})["result"]
    assert trace2["type"] == "CREATE"
    # structLogs on the inner-call tx shows opcode steps incl. the CALL
    struct3 = call("debug_traceTransaction", "0x" + tx3.hash.hex())["result"]
    ops = [e["op"] for e in struct3["structLogs"]]
    assert "CALL" in ops and "RETURN" in ops
    assert all(e["gasCost"] is not None for e in struct3["structLogs"])
    # unknown tx errors cleanly
    err = call("debug_traceTransaction", "0x" + "ab" * 32)
    assert "error" in err


def test_misc_wallet_methods(rpc):
    call, node = rpc
    # web3_sha3 known vector
    assert call("web3_sha3", "0x")["result"] == (
        "0xc5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470")
    assert call("eth_blobBaseFee")["result"] == "0x1"
    cnt = call("eth_getBlockTransactionCountByNumber", "0x1")["result"]
    assert int(cnt, 16) >= 1
    blk = call("eth_getBlockByNumber", "0x1", False)["result"]
    assert call("eth_getBlockTransactionCountByHash",
                blk["hash"])["result"] == cnt
    tx0 = call("eth_getTransactionByBlockNumberAndIndex",
               "0x1", "0x0")["result"]
    assert tx0["hash"] == blk["transactions"][0]
    assert call("eth_getTransactionByBlockNumberAndIndex",
                "0x1", "0x99")["result"] is None
    assert call("net_peerCount")["result"] == "0x0"  # no p2p attached


def test_error_paths(rpc):
    call, node = rpc
    assert "error" in call("eth_fooBar")
    assert call("eth_fooBar")["error"]["code"] == -32601
    # bad raw tx
    assert "error" in call("eth_sendRawTransaction", "0x00ff")
    # unknown block
    assert call("eth_getBlockByNumber", "0x999", False)["result"] is None


def test_filter_family(rpc):
    """eth_newFilter/newBlockFilter/newPendingTransactionFilter +
    getFilterChanges/getFilterLogs/uninstallFilter over live HTTP."""
    call, node = rpc
    bf = call("eth_newBlockFilter")["result"]
    pf = call("eth_newPendingTransactionFilter")["result"]
    assert call("eth_getFilterChanges", bf)["result"] == []

    nonce = int(call("eth_getTransactionCount", "0x" + SENDER.hex(),
                     "latest")["result"], 16)
    # deploy a contract whose runtime is PUSH0 PUSH0 LOG0 STOP
    deploy = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=300_000, to=b"", value=0,
        data=bytes.fromhex("635f5fa0005f526004601cf3")).sign(SECRET)
    call("eth_sendRawTransaction",
         "0x" + deploy.encode_canonical().hex())
    pending = call("eth_getFilterChanges", pf)["result"]
    assert "0x" + deploy.hash.hex() in pending
    assert call("eth_getFilterChanges", pf)["result"] == []  # drained
    call("ethrex_produceBlock")
    rcpt = call("eth_getTransactionReceipt",
                "0x" + deploy.hash.hex())["result"]
    contract = rcpt["contractAddress"]

    lf = call("eth_newFilter", {"address": contract})["result"]
    blocks = call("eth_getFilterChanges", bf)["result"]
    assert len(blocks) >= 1 and all(h.startswith("0x") for h in blocks)
    # trigger the log
    trig = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce + 1,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=100_000, to=bytes.fromhex(contract[2:]),
        value=0).sign(SECRET)
    call("eth_sendRawTransaction", "0x" + trig.encode_canonical().hex())
    call("ethrex_produceBlock")
    logs = call("eth_getFilterChanges", lf)["result"]
    assert len(logs) == 1 and logs[0]["address"] == contract
    assert call("eth_getFilterChanges", lf)["result"] == []
    # getFilterLogs re-evaluates the criteria from scratch: default range
    # latest..latest is the log's block, so the log appears again
    replay = call("eth_getFilterLogs", lf)["result"]
    assert len(replay) == 1 and replay[0]["address"] == contract
    assert call("eth_uninstallFilter", lf)["result"] is True
    err = call("eth_getFilterChanges", lf)
    assert err["error"]["code"] == -32000
    assert call("eth_uninstallFilter", lf)["result"] is False


def test_filter_ranges_and_pending_accumulation(rpc):
    """Review regressions: historical fromBlock served on first poll,
    toBlock bound honored forever, and a tx mined between two polls is
    still reported by a pending filter (arrival-time accumulation)."""
    call, node = rpc
    nonce = int(call("eth_getTransactionCount", "0x" + SENDER.hex(),
                     "latest")["result"], 16)
    pf = call("eth_newPendingTransactionFilter")["result"]
    deploy = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=300_000, to=b"", value=0,
        data=bytes.fromhex("635f5fa0005f526004601cf3")).sign(SECRET)
    call("eth_sendRawTransaction", "0x" + deploy.encode_canonical().hex())
    call("ethrex_produceBlock")                 # mined before the poll
    assert "0x" + deploy.hash.hex() in call(
        "eth_getFilterChanges", pf)["result"]
    contract = call("eth_getTransactionReceipt",
                    "0x" + deploy.hash.hex())["result"]["contractAddress"]
    log_block = None
    trig = Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce + 1,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=100_000, to=bytes.fromhex(contract[2:]),
        value=0).sign(SECRET)
    call("eth_sendRawTransaction", "0x" + trig.encode_canonical().hex())
    call("ethrex_produceBlock")
    rcpt = call("eth_getTransactionReceipt",
                "0x" + trig.hash.hex())["result"]
    log_block = int(rcpt["blockNumber"], 16)
    # historical range: a fresh filter's first poll returns the past log
    lf = call("eth_newFilter",
              {"fromBlock": "0x0", "address": contract})["result"]
    assert len(call("eth_getFilterChanges", lf)["result"]) >= 1
    # bounded: toBlock below the log block never reports it
    bounded = call("eth_newFilter",
                   {"fromBlock": "0x0", "toBlock": hx(log_block - 1),
                    "address": contract})["result"]
    assert call("eth_getFilterChanges", bounded)["result"] == []


def test_post_merge_constants(rpc):
    call, node = rpc
    assert call("eth_accounts")["result"] == []
    assert call("eth_mining")["result"] is False
    assert call("eth_hashrate")["result"] == "0x0"
    head = call("eth_blockNumber")["result"]
    assert call("eth_getUncleCountByBlockNumber", head)["result"] == "0x0"
    assert call("eth_getUncleByBlockNumberAndIndex",
                head, "0x0")["result"] is None
    # unknown blocks answer null, not "0x0"
    assert call("eth_getUncleCountByBlockHash",
                "0x" + "77" * 32)["result"] is None
    assert call("eth_getUncleCountByBlockNumber",
                "0x999999")["result"] is None


def test_error_surfaces_jsonrpc_spec(rpc):
    """JSON-RPC error-code conformance beyond the happy path: parse
    errors (-32700), invalid params, and survival after garbage."""
    call, node = rpc
    # invalid params: wrong arity/type must not 500 the server
    r = call("eth_getBalance")
    assert "error" in r and r["error"]["code"] in (-32602, -32000)
    assert "error" in call("eth_getBlockByNumber", {"bogus": True}, False)
    assert "error" in call("eth_getTransactionByHash", "0xnothex")
    # malformed JSON -> parse error on a dedicated server instance
    server = RpcServer(node, port=0).start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        req = urllib.request.Request(
            url, data=b"{this is not json",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            out = json.loads(resp.read())
        assert out["error"]["code"] == -32700
        # and the server still serves valid traffic afterwards
        good = json.dumps({"jsonrpc": "2.0", "id": 1,
                           "method": "web3_clientVersion",
                           "params": []}).encode()
        req = urllib.request.Request(
            url, data=good,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert "result" in json.loads(resp.read())
    finally:
        server.stop()


def test_concurrent_rpc_requests(rpc):
    """The HTTP server must survive concurrent mixed valid/invalid
    traffic without cross-talk between responses."""
    import threading

    call, node = rpc
    errors = []
    results = [None] * 24

    def worker(i):
        try:
            if i % 3 == 0:
                r = call("eth_blockNumber")
            elif i % 3 == 1:
                r = call("eth_fooBar")
            else:
                r = call("eth_getBalance", "0x" + "11" * 20, "latest")
            results[i] = r
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(24)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)
    assert not errors
    for i, r in enumerate(results):
        assert r is not None
        if i % 3 == 1:
            assert r["error"]["code"] == -32601
        else:
            assert "result" in r
