"""TPU BN254 limb arithmetic + MSM vs the host bignum implementation."""

import numpy as np
import jax.numpy as jnp
import pytest

from ethrex_tpu.crypto import bn254
from ethrex_tpu.ops import bn254_msm as msm_ops

pytestmark = pytest.mark.slow  # full STARK compiles

RNG = np.random.default_rng(5)
G1 = (1, 2)


def _rand_fp(n):
    return [int.from_bytes(RNG.bytes(40), "big") % bn254.P
            for _ in range(n)]


def _mont(vals):
    return jnp.asarray(np.stack([msm_ops.to_mont_host(v) for v in vals]))


def test_limb_roundtrip_and_mont():
    vals = _rand_fp(10)
    arr = _mont(vals)
    back = [msm_ops.from_mont_host(np.asarray(arr[i]))
            for i in range(len(vals))]
    assert back == vals


def test_field_ops_match_host():
    a = _rand_fp(50)
    b = _rand_fp(50)
    am, bm = _mont(a), _mont(b)
    got_add = msm_ops.fadd(am, bm)
    got_sub = msm_ops.fsub(am, bm)
    got_mul = msm_ops.fmul(am, bm)
    for i in range(50):
        assert msm_ops.from_mont_host(np.asarray(got_add[i])) == \
            (a[i] + b[i]) % bn254.P
        assert msm_ops.from_mont_host(np.asarray(got_sub[i])) == \
            (a[i] - b[i]) % bn254.P
        assert msm_ops.from_mont_host(np.asarray(got_mul[i])) == \
            a[i] * b[i] % bn254.P


def _host_msm(points, scalars):
    acc = None
    for pt, s in zip(points, scalars):
        term = bn254.g1_mul(pt, int(s) % bn254.R)
        acc = bn254.g1_add(acc, term)
    return acc


def test_point_double_add_match_host():
    pts = [bn254.g1_mul(G1, k) for k in (1, 2, 5, 77, 123456789)]
    X, Y, Z = msm_ops.points_to_device(pts)
    dX, dY, dZ = msm_ops.point_double(X, Y, Z)
    sX, sY, sZ = msm_ops.point_add(X, Y, Z, X[::-1], Y[::-1], Z[::-1])
    for i, pt in enumerate(pts):
        want = bn254.g1_add(pt, pt)
        one = msm_ops.msm([pt], [2])
        assert one == want
    # add of reversed pairing: p_i + p_{n-1-i}
    for i, pt in enumerate(pts):
        other = pts[len(pts) - 1 - i]
        want = bn254.g1_add(pt, other)
        z = msm_ops.from_mont_host(np.asarray(sZ[i]))
        if want is None:
            assert z == 0
            continue
        zinv = pow(z, bn254.P - 2, bn254.P)
        x = msm_ops.from_mont_host(np.asarray(sX[i])) * zinv * zinv % bn254.P
        y = msm_ops.from_mont_host(np.asarray(sY[i])) * zinv ** 3 % bn254.P
        assert (x, y) == want


def test_msm_matches_host():
    n = 17
    pts = [bn254.g1_mul(G1, int(RNG.integers(1, 1 << 30)))
           for _ in range(n)]
    scalars = [int.from_bytes(RNG.bytes(40), "big") % bn254.R
               for _ in range(n)]
    assert msm_ops.msm(pts, scalars) == _host_msm(pts, scalars)


def test_msm_edge_cases():
    # zero scalars -> infinity
    pts = [G1, bn254.g1_mul(G1, 7)]
    assert msm_ops.msm(pts, [0, 0]) is None
    # cancellation: k*G + (r-k)*G = r*G = infinity
    assert msm_ops.msm(pts[:1] * 2, [5, bn254.R - 5]) is None
    # infinity input points are ignored
    assert msm_ops.msm([None, G1], [3, 2]) == bn254.g1_mul(G1, 2)
