"""EVM interpreter + transaction executor tests (handwritten bytecode)."""

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.primitives.account import Account
from ethrex_tpu.primitives.genesis import ChainConfig
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.evm.db import InMemorySource, StateDB
from ethrex_tpu.evm.executor import InvalidTransaction, execute_tx
from ethrex_tpu.evm.vm import EVM, BlockEnv, Message

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
OTHER = bytes.fromhex("aa" * 20)
CONTRACT = bytes.fromhex("cc" * 20)

CONFIG = ChainConfig.from_json({
    "chainId": 1337, "terminalTotalDifficulty": 0,
    "shanghaiTime": 0, "cancunTime": 0,
})
BLOCK = BlockEnv(number=1, timestamp=1000, base_fee=7,
                 coinbase=bytes.fromhex("ee" * 20), gas_limit=30_000_000)


def _state(contract_code=b"", storage=None, balance=10**20):
    accounts = {
        SENDER: Account.new(balance=balance),
        CONTRACT: Account.new(code=contract_code, storage=storage or {}),
    }
    return StateDB(InMemorySource(accounts))


def _call(state, code=None, data=b"", value=0, gas=1_000_000):
    evm = EVM(state, BLOCK, CONFIG)
    msg = Message(caller=SENDER, to=CONTRACT, code_address=CONTRACT,
                  value=value, data=data, gas=gas,
                  code=code if code is not None else state.get_code(CONTRACT))
    return evm.execute_message(msg)


def _tx(to=CONTRACT, data=b"", value=0, gas_limit=100_000, nonce=0):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=1337, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=100,
        gas_limit=gas_limit, to=to, value=value, data=data,
    ).sign(SECRET)


def test_arithmetic_return():
    # PUSH1 2, PUSH1 3, ADD, PUSH0, MSTORE, PUSH1 32, PUSH0, RETURN
    code = bytes.fromhex("6002600301" + "5f52" + "60205ff3")
    ok, gas_left, out = _call(_state(code))
    assert ok and int.from_bytes(out, "big") == 5
    assert gas_left > 0


def test_division_by_zero_and_signed_ops():
    # 7 / 0 = 0; then -8 SDIV 2 = -4
    code = bytes.fromhex(
        "5f600704"                          # DIV(7, 0) -> 0
        "60027f" + "ff" * 31 + "f8" + "05"  # SDIV(-8, 2) -> -4
        "015f52" + "60205ff3"               # ADD, MSTORE, RETURN
    )
    ok, _, out = _call(_state(code))
    assert ok
    val = int.from_bytes(out, "big")
    assert val == (-4) % (1 << 256)


def test_storage_and_refund():
    # SSTORE(0, 1234) ; SLOAD(0) ; return it
    code = bytes.fromhex("6104d25f55" + "5f545f52" + "60205ff3")
    state = _state(code)
    ok, _, out = _call(state)
    assert ok and int.from_bytes(out, "big") == 1234
    assert state.get_storage(CONTRACT, 0) == 1234
    # clearing a pre-existing slot adds a refund
    state2 = _state(bytes.fromhex("5f5f55" + "5f5ff3"), storage={0: 99})
    ok, _, _ = _call(state2)
    assert ok and state2.refund == 4800


def test_transient_storage_and_mcopy():
    # TSTORE(0, 7); TLOAD(0) -> mem; MCOPY(32, 0, 32); return mem[32:64]
    code = bytes.fromhex("60075f5d" + "5f5c5f52" + "60205f60203e"[:0]
                         + "60205f60205e" + "6020602060f3"[:0] + "60206020f3")
    ok, _, out = _call(_state(code))
    assert ok and int.from_bytes(out, "big") == 7


def test_keccak_opcode():
    # KECCAK256 of empty: PUSH0 PUSH0 SHA3 ; MSTORE ; RETURN
    code = bytes.fromhex("5f5f20" + "5f52" + "60205ff3")
    ok, _, out = _call(_state(code))
    from ethrex_tpu.crypto.keccak import EMPTY_KECCAK
    assert ok and out == EMPTY_KECCAK


def test_call_between_contracts():
    # callee: returns 42
    callee_code = bytes.fromhex("602a5f52" + "60205ff3")
    callee_addr = bytes.fromhex("dd" * 20)
    # caller: CALL(gas, callee, 0, 0, 0, 0, 32); return returndata
    caller_code = bytes.fromhex(
        "60205f5f5f5f73" + callee_addr.hex() + "620f424of1"[:0]
        + "620f4240f1" + "5f51" + "5f52" + "60205ff3")
    accounts = {
        SENDER: Account.new(balance=10**20),
        CONTRACT: Account.new(code=caller_code),
        callee_addr: Account.new(code=callee_code),
    }
    state = StateDB(InMemorySource(accounts))
    ok, _, out = _call(state)
    assert ok and int.from_bytes(out, "big") == 42


def test_revert_rolls_back_storage():
    # SSTORE(0, 5) then REVERT(0, 0)
    code = bytes.fromhex("60055f55" + "5f5ffd")
    state = _state(code)
    ok, gas_left, out = _call(state)
    assert not ok
    assert state.get_storage(CONTRACT, 0) == 0
    assert gas_left > 0  # revert returns remaining gas


def test_create_and_call_created():
    # initcode: returns runtime code "602a5f5260205ff3" (returns 42)
    runtime = bytes.fromhex("602a5f5260205ff3")
    # initcode: PUSH8 runtime, PUSH0 MSTORE; RETURN(24, 8)
    initcode = bytes.fromhex("67" + runtime.hex() + "5f52" + "60086018f3")
    # deployer: CODECOPY initcode to mem then CREATE, store address
    # simpler: do it via execute_tx create
    tx = _tx(to=b"", data=initcode, gas_limit=200_000)
    state = _state()
    res = execute_tx(tx, state, BLOCK, CONFIG)
    assert res.success and res.created is not None
    assert state.get_code(res.created) == runtime
    assert state.get_nonce(res.created) == 1
    # call it
    evm = EVM(state, BLOCK, CONFIG)
    ok, _, out = evm.execute_message(Message(
        caller=SENDER, to=res.created, code_address=res.created, value=0,
        data=b"", gas=100_000, code=state.get_code(res.created)))
    assert ok and int.from_bytes(out, "big") == 42


def test_static_call_blocks_writes():
    # target tries SSTORE -> staticcall must fail
    writer = bytes.fromhex("60015f55" + "5f5ff3")
    writer_addr = bytes.fromhex("dd" * 20)
    caller_code = bytes.fromhex(
        "5f5f5f5f73" + writer_addr.hex() + "620f4240fa"
        + "5f52" + "60205ff3")
    accounts = {
        SENDER: Account.new(balance=10**20),
        CONTRACT: Account.new(code=caller_code),
        writer_addr: Account.new(code=writer),
    }
    state = StateDB(InMemorySource(accounts))
    ok, _, out = _call(state)
    assert ok
    assert int.from_bytes(out, "big") == 0  # inner call failed
    assert state.get_storage(writer_addr, 1) == 0


def test_precompiles_via_call():
    state = _state()
    evm = EVM(state, BLOCK, CONFIG)
    # sha256 of "abc" via direct message to 0x02
    import hashlib
    ok, _, out = evm.execute_message(Message(
        caller=SENDER, to=b"\x00" * 19 + b"\x02",
        code_address=b"\x00" * 19 + b"\x02", value=0, data=b"abc",
        gas=100_000))
    assert ok and out == hashlib.sha256(b"abc").digest()
    # identity
    ok, _, out = evm.execute_message(Message(
        caller=SENDER, to=b"\x00" * 19 + b"\x04",
        code_address=b"\x00" * 19 + b"\x04", value=0, data=b"hello",
        gas=100_000))
    assert ok and out == b"hello"
    # modexp: 3^4 mod 5 = 1
    data = (32).to_bytes(32, "big") + (32).to_bytes(32, "big") \
        + (32).to_bytes(32, "big") + (3).to_bytes(32, "big") \
        + (4).to_bytes(32, "big") + (5).to_bytes(32, "big")
    ok, _, out = evm.execute_message(Message(
        caller=SENDER, to=b"\x00" * 19 + b"\x05",
        code_address=b"\x00" * 19 + b"\x05", value=0, data=data,
        gas=100_000))
    assert ok and int.from_bytes(out, "big") == 1
    # ecrecover round-trip
    from ethrex_tpu.crypto.keccak import keccak256
    h = keccak256(b"msg")
    r, s, rec = secp256k1.sign(h, SECRET)
    data = h + (27 + rec).to_bytes(32, "big") + r.to_bytes(32, "big") \
        + s.to_bytes(32, "big")
    ok, _, out = evm.execute_message(Message(
        caller=SENDER, to=b"\x00" * 19 + b"\x01",
        code_address=b"\x00" * 19 + b"\x01", value=0, data=data,
        gas=100_000))
    assert ok and out[12:] == SENDER


def test_transfer_tx_end_to_end():
    state = _state()
    tx = _tx(to=OTHER, value=12345, gas_limit=21000)
    res = execute_tx(tx, state, BLOCK, CONFIG)
    assert res.success and res.gas_used == 21000
    assert state.get_balance(OTHER) == 12345
    assert state.get_nonce(SENDER) == 1
    # coinbase got the priority fee (tip = min(prio, maxfee - basefee) = 1)
    assert state.get_balance(BLOCK.coinbase) == 21000 * 1
    # sender paid value + gas * effective price (base 7 + tip 1)
    assert state.get_balance(SENDER) == 10**20 - 12345 - 21000 * 8


def test_invalid_txs_rejected():
    state = _state()
    with pytest.raises(InvalidTransaction):
        execute_tx(_tx(nonce=5), state, BLOCK, CONFIG)  # wrong nonce
    with pytest.raises(InvalidTransaction):
        execute_tx(_tx(gas_limit=20000), state, BLOCK, CONFIG)  # < intrinsic
    poor = StateDB(InMemorySource({SENDER: Account.new(balance=100)}))
    with pytest.raises(InvalidTransaction):
        execute_tx(_tx(to=OTHER, value=10**18), poor, BLOCK, CONFIG)


def test_out_of_gas_consumes_all():
    # infinite loop: JUMPDEST; PUSH0; JUMP
    code = bytes.fromhex("5b5f56")
    state = _state(code)
    ok, gas_left, _ = _call(state, gas=50_000)
    assert not ok and gas_left == 0


def test_selfdestruct_eip6780():
    # pre-existing contract selfdestructs -> only balance moves (Cancun)
    code = bytes.fromhex("73" + OTHER.hex() + "ff")
    accounts = {
        SENDER: Account.new(balance=10**20),
        CONTRACT: Account.new(code=code, balance=5000),
    }
    state = StateDB(InMemorySource(accounts))
    ok, _, _ = _call(state)
    assert ok
    assert state.get_balance(OTHER) == 5000
    assert state.get_balance(CONTRACT) == 0
    assert state.get_code(CONTRACT) == code  # code survives (EIP-6780)


def test_p256_verify_precompile():
    """P256VERIFY at 0x100 (RIP-7212/EIP-7951): valid sig -> 32-byte 1,
    anything malformed -> empty success."""
    import hashlib
    from ethrex_tpu.crypto import p256
    from ethrex_tpu.primitives.genesis import ChainConfig
    osaka_cfg = ChainConfig.from_json(
        {"chainId": 1337, "terminalTotalDifficulty": 0, "shanghaiTime": 0,
         "cancunTime": 0, "pragueTime": 0, "osakaTime": 0})
    state = _state()
    evm = EVM(state, BLOCK, osaka_cfg)
    addr = b"\x00" * 18 + b"\x01\x00"

    def call(data):
        return evm.execute_message(Message(
            caller=SENDER, to=addr, code_address=addr, value=0,
            data=data, gas=100_000))

    sk = 0xC9AF_A9D8_45BA_7516_6B5C_2157_67B1_D693_4E50_C3DB_36E8_9B12_7B8A_622B_120F_6721
    qx, qy = p256.pubkey_from_secret(sk)
    h = hashlib.sha256(b"sample").digest()
    r, s = p256.sign_for_tests(h, sk)
    good = h + r.to_bytes(32, "big") + s.to_bytes(32, "big") \
        + qx.to_bytes(32, "big") + qy.to_bytes(32, "big")
    ok, gas_left, out = call(good)
    assert ok and int.from_bytes(out, "big") == 1
    assert 100_000 - gas_left == 6900  # EIP-7951 cost
    # flipped s -> invalid -> empty output, still a successful call
    bad = bytearray(good); bad[95] ^= 1
    ok, _, out = call(bytes(bad))
    assert ok and out == b""
    # wrong length -> empty
    ok, _, out = call(good[:159])
    assert ok and out == b""
    ok, _, out = call(good + b"\x00")
    assert ok and out == b""
    # point not on curve -> empty
    offc = bytearray(good); offc[159] ^= 1
    ok, _, out = call(bytes(offc))
    assert ok and out == b""
    # r = 0 -> empty
    zr = h + b"\x00" * 32 + s.to_bytes(32, "big") \
        + qx.to_bytes(32, "big") + qy.to_bytes(32, "big")
    ok, _, out = call(zr)
    assert ok and out == b""


def test_p256_verify_inactive_before_osaka():
    """Pre-Osaka, 0x100 is an ordinary empty account: the call succeeds
    with empty output and burns no precompile gas (CONFIG is Prague)."""
    state = _state()
    evm = EVM(state, BLOCK, CONFIG)
    addr = b"\x00" * 18 + b"\x01\x00"
    ok, gas_left, out = evm.execute_message(Message(
        caller=SENDER, to=addr, code_address=addr, value=0,
        data=b"\x00" * 160, gas=100_000))
    assert ok and out == b"" and gas_left == 100_000


def test_drain_dirty_suppresses_stale_source_storage():
    """Pipelined-batch regression: a storage wipe (destroy+recreate) in
    block N of a batch must not leak stale pre-clear slots through the
    un-rebased source into later blocks of the same batch — and the
    cleared flag itself must NOT survive the drain (it would re-emit the
    clear at the next merkleize and drop recreated slots)."""
    accounts = {CONTRACT: Account.new(code=b"\x00", storage={1: 5})}
    state = StateDB(InMemorySource(accounts))
    assert state.get_storage(CONTRACT, 1) == 5
    state.begin_tx()
    state.mark_created(CONTRACT)  # CREATE2 redeploy wipes storage
    state.finalize_tx()
    state.drain_dirty()           # block boundary (pipelined handoff)
    # flag reset so the NEXT merkleize doesn't re-clear...
    assert not state.accounts[CONTRACT].storage_cleared
    # ...but source reads stay suppressed until rebase
    state.begin_tx()
    assert state.get_storage(CONTRACT, 1) == 0
    assert not state.has_nonempty_storage(CONTRACT)
    # rebase: the flushed source is authoritative again
    state.rebase(InMemorySource(
        {CONTRACT: Account.new(code=b"\x00", storage={3: 9})}))
    state.accounts.clear()
    assert state.get_storage(CONTRACT, 3) == 9
