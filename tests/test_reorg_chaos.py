"""Reorg chaos battery (docs/CHAIN_RESILIENCE.md): the reorg-safe
transaction lifecycle under adversarial fork-choice sequences — mempool
re-injection on rollback, A->B->A ping-pong flips at depth 1..8,
two-leg `forkchoice.apply` and `mempool.reinject` fault drills with
journal recovery, a kill-at-every-write-point crash loop through the
fork-choice write group on the persistent backend, the stale-txloc
verify-on-read regression, and a reorg-storm soak under live load
asserting the conservation invariant: no transaction accepted by the
pool is ever silently lost — each is included exactly once on the
canonical chain or still pending (or typed-pruned, counted).

Select alone with `-m chaos`; the whole battery is in the fast tier.
"""

import time

import pytest

from ethrex_tpu.blockchain.fork_choice import (REORG_JOURNAL_KEY,
                                               ForkChoiceError,
                                               InvalidForkChoiceState)
from ethrex_tpu.blockchain.payload import build_payload, create_payload_header
from ethrex_tpu.node import Node
from ethrex_tpu.perf.loadgen import Harness, ReorgDriver, _rpc
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.rpc.eth import EthApi
from ethrex_tpu.rpc.server import RpcServer
from ethrex_tpu.storage.persistent import PersistentBackend
from ethrex_tpu.storage.store import Store
from ethrex_tpu.utils import faults
from ethrex_tpu.utils.faults import FaultPlan
from tests.test_l2_pipeline import GENESIS, SECRET, _transfer

pytestmark = pytest.mark.chaos


def _open_node(tmp_path):
    store = Store(PersistentBackend(str(tmp_path / "chain.db")))
    return Node(Genesis.from_json(GENESIS), store=store)


def _seal_block(node, parent, txs, *, ts=None, coinbase=b"\x99" * 20):
    """Build + store a block on an arbitrary parent WITHOUT moving the
    head — the raw material for competing branches."""
    header = create_payload_header(
        parent, node.config,
        timestamp=ts if ts is not None else parent.timestamp + 1,
        coinbase=coinbase)
    result = build_payload(node.chain, parent, header, list(txs), [])
    node.chain.add_block(result.block)
    return result.block


def _assert_chain_consistent(store):
    """Walk head -> genesis: every canonical entry, header link and body
    must agree — the all-or-nothing invariant after any crash."""
    cursor = store.head_header()
    while cursor.number > 0:
        assert store.canonical_hash(cursor.number) == cursor.hash
        assert store.get_body(cursor.hash) is not None
        parent = store.get_header(cursor.parent_hash)
        assert parent is not None and parent.number == cursor.number - 1
        cursor = parent
    assert store.canonical_hash(0) == cursor.hash


def _canonical_inclusions(store):
    """{tx_hash: count} over the canonical chain, asserting every
    canonical inclusion is served by the verified txloc lookup."""
    included = {}
    for n in range(1, store.latest_number() + 1):
        blk = store.get_block(store.canonical_hash(n))
        assert blk is not None
        for i, tx in enumerate(blk.body.transactions):
            included[tx.hash] = included.get(tx.hash, 0) + 1
            assert store.canonical_tx_location(tx.hash) == (blk.hash, i)
    return included


def _assert_conservation(node, tx_hashes):
    """Every tracked tx is included exactly once XOR pending — never
    lost, never duplicated."""
    included = _canonical_inclusions(node.store)
    assert all(c == 1 for c in included.values()), "tx included twice"
    for h in tx_hashes:
        on_chain = included.get(h, 0) == 1
        pending = node.mempool.get_transaction(h) is not None
        assert on_chain != pending, \
            f"tx 0x{h.hex()[:16]} lost (or double-counted) by the reorg"


# ===========================================================================
# rollback -> re-injection -> re-inclusion
# ===========================================================================

def test_rollback_reinjects_orphaned_txs():
    node = Node(Genesis.from_json(GENESIS))
    txs = [_transfer(0), _transfer(1)]
    for tx in txs:
        node.submit_transaction(tx)
    node.produce_block()
    assert len(node.mempool) == 0
    genesis_hash = node.store.canonical_hash(0)

    out = node.reorg_handler.apply(genesis_hash)
    assert out.depth == 1 and out.reinjected == 2
    assert node.store.head_header().number == 0
    assert node.store.canonical_hash(1) is None
    # both txs are pending again, through the typed reinjected path
    for tx in txs:
        assert node.mempool.get_transaction(tx.hash) is not None
        assert node.store.canonical_tx_location(tx.hash) is None
    assert node.mempool.stats_json()["reinjections"] == 2
    stats = node.reorg_handler.stats_json()
    assert stats["reorgs"] == 1 and stats["lastDepth"] == 1
    assert stats["reinjected"] == 2 and not stats["pendingJournal"]

    # the RPC surface agrees: pending (null blockHash), no receipt
    api = EthApi(node)
    j = api.get_transaction_by_hash("0x" + txs[0].hash.hex())
    assert j is not None and j.get("blockHash") is None
    assert api.get_transaction_receipt("0x" + txs[0].hash.hex()) is None

    # production on the new head re-includes both exactly once
    node.produce_block()
    _assert_conservation(node, [tx.hash for tx in txs])
    assert len(node.mempool) == 0


def test_reinject_keeps_newer_pool_entry():
    """An occupied sender+nonce slot wins over the orphaned copy: the
    pool's entry postdates the orphan."""
    node = Node(Genesis.from_json(GENESIS))
    old = _transfer(0, value=100)
    node.submit_transaction(old)
    node.produce_block()
    # a replacement for the same nonce arrives after the inclusion got
    # orphaned conceptually — seed it directly, then roll back
    newer = _transfer(0, value=200)
    assert node.mempool.reinject(newer)  # occupy the slot
    out = node.reorg_handler.apply(node.store.canonical_hash(0))
    assert out.depth == 1 and out.reinjected == 0
    assert node.mempool.get_transaction(newer.hash) is not None
    assert node.mempool.get_transaction(old.hash) is None


# ===========================================================================
# depth 1..8 A->B->A ping-pong
# ===========================================================================

@pytest.mark.parametrize("depth", list(range(1, 9)))
def test_ping_pong_flips(depth):
    node = Node(Genesis.from_json(GENESIS))
    txs = []
    for n in range(depth):
        tx = _transfer(n)
        txs.append(tx)
        node.submit_transaction(tx)
        node.produce_block()
    a_hashes = {n: node.store.canonical_hash(n)
                for n in range(depth + 1)}
    a_tip = node.store.head_header().hash
    genesis_hash = a_hashes[0]

    # rollback to genesis: every included tx must come back
    out = node.reorg_handler.apply(genesis_hash)
    assert out.depth == depth and out.reinjected == depth
    assert len(node.mempool) == depth

    # branch B: same txs sealed onto genesis at distinct timestamps
    parent = node.store.get_header(genesis_hash)
    b_blocks = []
    for n in range(depth):
        blk = _seal_block(node, parent, [txs[n]],
                          ts=parent.timestamp + 2)
        assert blk.hash != a_hashes[n + 1]
        b_blocks.append(blk)
        parent = blk.header
    b_hashes = {n + 1: b_blocks[n].hash for n in range(depth)}
    b_tip = b_blocks[-1].hash

    def assert_on_branch(hashes):
        for n in range(1, depth + 1):
            assert node.store.canonical_hash(n) == hashes[n]
        assert len(node.mempool) == 0, "tx pending AND included"
        _assert_conservation(node, [tx.hash for tx in txs])

    # adopt B: the pool copies of the adopted txs are dropped
    node.reorg_handler.apply(b_tip)
    assert_on_branch(b_hashes)
    # ping-pong: A -> B -> A, consistent after every flip
    for tip, hashes in ((a_tip, a_hashes), (b_tip, b_hashes),
                       (a_tip, a_hashes)):
        out = node.reorg_handler.apply(tip)
        assert out.depth == depth and out.reinjected == 0
        assert_on_branch(hashes)
    assert node.reorg_handler.deepest == depth


# ===========================================================================
# safe/finalized ancestry validation (engine invalidForkChoiceState)
# ===========================================================================

def test_safe_finalized_must_be_ancestors():
    node = Node(Genesis.from_json(GENESIS))
    node.submit_transaction(_transfer(0))
    node.produce_block()
    head = node.store.head_header().hash
    sibling = _seal_block(node, node.store.get_header(
        node.store.canonical_hash(0)), [])
    with pytest.raises(InvalidForkChoiceState):
        node.reorg_handler.apply(head, safe_hash=sibling.hash)
    with pytest.raises(InvalidForkChoiceState):
        node.reorg_handler.apply(head, finalized_hash=sibling.hash)
    with pytest.raises(ForkChoiceError):
        node.reorg_handler.apply(head, safe_hash=b"\x42" * 32)
    # valid ancestors stick
    genesis_hash = node.store.canonical_hash(0)
    node.reorg_handler.apply(head, safe_hash=genesis_hash,
                             finalized_hash=genesis_hash)
    assert node.store.meta["finalized"] == genesis_hash


# ===========================================================================
# two-leg forkchoice.apply + mempool.reinject fault drills
# ===========================================================================

def _one_block_and_sibling(node):
    tx = _transfer(0)
    node.submit_transaction(tx)
    node.produce_block()
    sibling = _seal_block(node, node.store.get_header(
        node.store.canonical_hash(0)), [])
    return tx, sibling


def test_forkchoice_fault_leg1_leaves_old_chain_intact():
    node = Node(Genesis.from_json(GENESIS))
    tx, sibling = _one_block_and_sibling(node)
    a1 = node.store.head_header().hash
    plan = faults.install(FaultPlan().error("forkchoice.apply", times=1))
    try:
        with pytest.raises(Exception):
            node.reorg_handler.apply(sibling.hash)
        assert plan.log
    finally:
        faults.clear()
    # leg 1 fires BEFORE the write group: nothing moved, no journal
    assert node.store.head_header().hash == a1
    assert node.store.canonical_tx_location(tx.hash) is not None
    assert node.mempool.get_transaction(tx.hash) is None
    assert not node.reorg_handler.stats_json()["pendingJournal"]
    _assert_conservation(node, [tx.hash])


def test_forkchoice_fault_leg2_recovers_from_journal():
    node = Node(Genesis.from_json(GENESIS))
    tx, sibling = _one_block_and_sibling(node)
    plan = faults.install(
        FaultPlan().error("forkchoice.apply", after=1, times=1))
    try:
        with pytest.raises(Exception):
            node.reorg_handler.apply(sibling.hash)
        assert plan.log
    finally:
        faults.clear()
    # leg 2 fires AFTER the rewrite committed: canonical index and
    # txloc already moved, mempool debt journaled but unpaid
    assert node.store.head_header().hash == sibling.hash
    assert node.store.canonical_tx_location(tx.hash) is None
    assert node.mempool.get_transaction(tx.hash) is None
    assert node.reorg_handler.stats_json()["pendingJournal"]
    # recovery pays the debt and clears the journal
    out = node.reorg_handler.recover_pending()
    assert out is not None and out.recovered and out.reinjected == 1
    assert node.mempool.get_transaction(tx.hash) is not None
    assert not node.reorg_handler.stats_json()["pendingJournal"]
    assert node.reorg_handler.recoveries == 1
    _assert_conservation(node, [tx.hash])


def test_reinject_fault_replayed_by_next_apply():
    node = Node(Genesis.from_json(GENESIS))
    tx, sibling = _one_block_and_sibling(node)
    plan = faults.install(FaultPlan().error("mempool.reinject", times=1))
    try:
        with pytest.raises(Exception):
            node.reorg_handler.apply(sibling.hash)
        assert plan.log
    finally:
        faults.clear()
    # the crash hit mid-settlement: tx neither pending nor canonical,
    # but the journal still holds the debt
    assert node.store.canonical_tx_location(tx.hash) is None
    assert node.mempool.get_transaction(tx.hash) is None
    assert node.reorg_handler.stats_json()["pendingJournal"]
    # the NEXT apply (any head move) replays the journal first
    node.reorg_handler.apply(sibling.hash)
    assert node.mempool.get_transaction(tx.hash) is not None
    assert not node.reorg_handler.stats_json()["pendingJournal"]
    _assert_conservation(node, [tx.hash])


# ===========================================================================
# stale txloc: verify-on-read + no canonical clobber
# ===========================================================================

def test_stale_txloc_never_served():
    node = Node(Genesis.from_json(GENESIS))
    included = _transfer(0, value=100)
    node.submit_transaction(included)
    node.produce_block()
    a1 = node.store.head_header().hash
    # hand-build an orphaned inclusion: a sibling carrying a tx that is
    # NOT on the canonical chain — its txloc entry points off-chain
    orphan_only = _transfer(0, value=200)
    sibling = _seal_block(node, node.store.get_header(
        node.store.canonical_hash(0)), [orphan_only])
    assert node.store.tx_index.get(orphan_only.hash) is not None
    assert node.store.canonical_tx_location(orphan_only.hash) is None
    api = EthApi(node)
    assert api.get_transaction_by_hash(
        "0x" + orphan_only.hash.hex()) is None
    assert api.get_transaction_receipt(
        "0x" + orphan_only.hash.hex()) is None
    # a sibling repeating a canonically-included tx must NOT clobber
    # the canonical location
    sibling2 = _seal_block(node, node.store.get_header(
        node.store.canonical_hash(0)), [included], ts=3)
    assert sibling2.hash != sibling.hash
    assert node.store.canonical_tx_location(included.hash) == (a1, 0)
    rec = api.get_transaction_receipt("0x" + included.hash.hex())
    assert rec is not None and rec["blockHash"] == "0x" + a1.hex()


# ===========================================================================
# kill-at-every-write-point through the fork-choice write group
# ===========================================================================

def test_kill_at_every_write_point_through_fork_choice(tmp_path):
    node = _open_node(tmp_path)
    txs = []
    for n in range(3):
        tx = _transfer(n)
        txs.append(tx)
        node.submit_transaction(tx)
        node.produce_block()
    tip = node.store.head_header().hash
    a_hashes = [node.store.canonical_hash(n) for n in range(1, 4)]
    genesis_hash = node.store.canonical_hash(0)
    tx_hashes = [tx.hash for tx in txs]

    def assert_crash_state(node):
        """Post-reopen invariant at ANY crash point: consistent index,
        nothing duplicated, and every tx either canonical, pending
        (journal replayed), or — only when the settlement had fully
        committed before the crash (journal cleared) — still durably
        recoverable from the stored orphaned block the resume below
        re-adopts.  The pool is volatile; the blocks are not."""
        from ethrex_tpu.blockchain.fork_choice import REORG_JOURNAL_KEY
        included = _canonical_inclusions(node.store)
        assert all(c == 1 for c in included.values())
        for h in tx_hashes:
            on_chain = included.get(h, 0) == 1
            pending = node.mempool.get_transaction(h) is not None
            assert not (on_chain and pending), "pending AND included"
            if not on_chain and not pending:
                assert node.store.meta.get(REORG_JOURNAL_KEY) is None, \
                    "journal present but tx not replayed: reorg loss"
        # the orphaned blocks (and their txs) are never deleted
        for bh in a_hashes:
            assert node.store.get_body(bh) is not None

    k = 0
    crashes = 0
    while True:
        plan = faults.install(
            FaultPlan().error("store.put", after=k, times=1))
        try:
            try:
                node.reorg_handler.apply(genesis_hash)
            except Exception:
                assert plan.log, "rollback failed without a fault"
            fired = bool(plan.log)
        finally:
            faults.clear()
        if not fired:
            break
        crashes += 1
        # crash: drop the process state, reopen the same files — the
        # Node constructor replays any pending reorg journal
        node.store.close()
        node = _open_node(tmp_path)
        _assert_chain_consistent(node.store)
        assert_crash_state(node)
        # resume: complete the rollback, then re-adopt the tip
        node.reorg_handler.apply(genesis_hash)
        node.reorg_handler.apply(tip)
        _assert_chain_consistent(node.store)
        _assert_conservation(node, tx_hashes)
        assert len(node.mempool) == 0
        k += 1
    assert crashes >= 5, f"only {crashes} write points in the reorg group"
    # the un-faulted rollback completed: all txs pending again
    assert len(node.mempool) == 3
    node.reorg_handler.apply(tip)
    _assert_conservation(node, tx_hashes)
    node.store.close()


def test_leg2_crash_recovers_on_reopen(tmp_path):
    """Process death between the canonical rewrite and the mempool
    settlement: the reopened node must pay the journaled debt."""
    node = _open_node(tmp_path)
    tx = _transfer(0)
    node.submit_transaction(tx)
    node.produce_block()
    sibling = _seal_block(node, node.store.get_header(
        node.store.canonical_hash(0)), [])
    plan = faults.install(
        FaultPlan().error("forkchoice.apply", after=1, times=1))
    try:
        with pytest.raises(Exception):
            node.reorg_handler.apply(sibling.hash)
        assert plan.log
    finally:
        faults.clear()
    node.store.close()

    node = _open_node(tmp_path)
    # Node.__init__ ran recover_pending: the orphaned tx is pending
    assert node.mempool.get_transaction(tx.hash) is not None
    assert node.store.meta.get(REORG_JOURNAL_KEY) is None
    assert node.reorg_handler.recoveries == 1
    _assert_chain_consistent(node.store)
    _assert_conservation(node, [tx.hash])
    node.store.close()


# ===========================================================================
# reorg-storm soak under live load: the conservation invariant
# ===========================================================================

def test_reorg_storm_soak_conservation():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, port=0, engine=True).start()
    url = f"http://127.0.0.1:{server.port}"
    driver = None
    try:
        harness = Harness(url, key=SECRET, senders=4, token_frac=0.0,
                          workers=8, payload="tx", seed=7)
        harness.setup(fund_wei=10 ** 17)
        node.start_dev_producer(block_time=0.05, prewarm=False)
        driver = ReorgDriver(
            lambda method, *params: _rpc(url, method, *params),
            interval=0.2, depth=2).start()
        harness.run(rate=60, duration=4.0)
        driver.stop()
        # quiesce: no more flips; let the producer drain what it can
        deadline = time.monotonic() + 10.0
        while len(node.mempool) and time.monotonic() < deadline:
            time.sleep(0.1)
    finally:
        if driver is not None:
            driver.stop()
        node.stop()
        server.stop()

    assert driver.flips >= 2, f"storm never flipped: {driver.stats()}"
    assert node.reorg_handler.reorgs >= 1

    included = _canonical_inclusions(node.store)
    assert all(c == 1 for c in included.values()), "tx included twice"
    pending = len(node.mempool)
    # conservation: every admitted tx is included exactly once, still
    # pending, or pruned for a typed counted reason.  The prune ledger
    # upper-bounds the gap rather than closing it exactly: a pruned
    # tx's orphaned inclusion can be re-adopted by a later flip (it
    # ends up included AND in the prune count), so typed prunes must
    # COVER the gap — an uncovered gap is a silent loss
    prunes = sum(n for reason, n in node.reorg_handler.evictions.items()
                 if reason in ("nonce_below_account",
                               "insufficient_balance"))
    gap = node.mempool.admitted - (len(included) + pending)
    assert 0 <= gap <= prunes, (
        f"conservation broken: included={len(included)} "
        f"pending={pending} prunes={prunes} "
        f"admitted={node.mempool.admitted} "
        f"reorgs={node.reorg_handler.stats_json()}")
