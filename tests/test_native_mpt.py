"""Native C++ MPT engine: differential equivalence against the Python
trie (the behavioral reference), missing-node parity, and fresh-node
persistence (parity seat: crates/common/trie + the reference's
trie-optimization rounds, docs/l2/bench/prover_performance.md:63-75)."""

import numpy as np
import pytest

from ethrex_tpu.crypto.keccak import keccak256
from ethrex_tpu.primitives.account import EMPTY_TRIE_ROOT
from ethrex_tpu.trie.native_mpt import NativeMpt, available
from ethrex_tpu.trie.trie import MissingNode, Trie

pytestmark = pytest.mark.skipif(not available(),
                                reason="native mpt unavailable")

RNG = np.random.default_rng(11)


def _rand_key():
    return bytes(RNG.integers(0, 256, 32, dtype=np.uint8))


def _python_apply(table, root, ops):
    t = Trie.from_nodes(root, dict(table), share=True)
    for k, v in ops:
        if v:
            t.insert(k, v)
    for k, v in ops:
        if not v:
            t.remove(k)
    return t.commit()


def test_differential_random_batches():
    table = {}
    root = EMPTY_TRIE_ROOT
    native = NativeMpt()
    live = []
    for batch in range(6):
        ops = []
        for _ in range(80):
            k = keccak256(_rand_key())
            ops.append((k, b"val" + k[:6]))
            live.append(k)
        # delete some existing keys (inserts first, then deletes — the
        # pruned-witness ordering rule of apply_updates_to_tries)
        dels = [live.pop(RNG.integers(0, len(live)))
                for _ in range(min(25, len(live) // 2))]
        ops += [(k, b"") for k in dels]
        expected = _python_apply(table, root, ops)
        root = native.apply(table, root, ops)
        assert root == expected, f"batch {batch} diverged"


def test_variable_length_values_and_empty_trie():
    table = {}
    native = NativeMpt()
    ops = [(keccak256(bytes([i])), bytes([i]) * (1 + 7 * i))
           for i in range(40)]
    root = native.apply(table, EMPTY_TRIE_ROOT, ops)
    assert root == _python_apply({}, EMPTY_TRIE_ROOT, ops)
    # delete everything -> back to the empty root
    root = native.apply(table, root, [(k, b"") for k, _ in ops])
    assert root == EMPTY_TRIE_ROOT


def test_short_values_inline_nodes():
    """Values < 32 bytes produce inline (<32B) nodes — the embedding
    rules must match the Python encoder exactly."""
    table = {}
    native = NativeMpt()
    ops = [(keccak256(bytes([i, j])), bytes([i]))
           for i in range(6) for j in range(6)]
    root = native.apply(table, EMPTY_TRIE_ROOT, ops)
    assert root == _python_apply({}, EMPTY_TRIE_ROOT, ops)
    # python trie reads the native-written nodes back
    t = Trie.from_nodes(root, table, share=True)
    assert t.get(keccak256(bytes([2, 3]))) == bytes([2])


def test_missing_node_raises_like_python():
    table = {}
    py = Trie.from_nodes(EMPTY_TRIE_ROOT, table, share=True)
    for i in range(100):
        py.insert(keccak256(bytes([i])), b"v%d" % i)
    root = py.commit()
    pruned = dict(list(table.items())[:3])
    native = NativeMpt()
    with pytest.raises(MissingNode):
        native.apply(pruned, root, [(keccak256(bytes([5])), b"x")])


def test_fresh_nodes_persist_to_table():
    table = {}
    native = NativeMpt()
    ops = [(keccak256(bytes([i])), b"value-%d" % i) for i in range(50)]
    root = native.apply(table, EMPTY_TRIE_ROOT, ops)
    # a fresh python trie over the SAME table resolves every path
    t = Trie.from_nodes(root, table, share=True)
    for i in range(50):
        assert t.get(keccak256(bytes([i]))) == b"value-%d" % i
