"""Token VM AIR: host digest agreement, constraint satisfaction on the
honest trace, and rejection of tampered slot values/amounts."""

import numpy as np
import pytest

from ethrex_tpu.guest.transfer_log import TokSeg
from ethrex_tpu.models import token_air as tk
from ethrex_tpu.ops import babybear as bb
from ethrex_tpu.ops import ext
from ethrex_tpu.stark.air import HostExtOps

KF = int.from_bytes(b"\x11" * 32, "big")
KT = int.from_bytes(b"\x22" * 32, "big")


def _mk_segs():
    v1 = 12345
    v2 = (1 << 200) + 7   # crosses many limb boundaries
    return [
        TokSeg(v1, KF, 10**6, 10**6 - v1, KT, 500, 500 + v1),
        TokSeg(v2, KT, 1 << 220, (1 << 220) - v2, KF,
               (1 << 24) - 1, (1 << 24) - 1 + v2),
        TokSeg(0, 0, 0, 0, 0, 0, 0, noop=True),
    ]


def _check_rows(air, trace, periodic_cols, rows=None):
    n = trace.shape[0]
    hops = HostExtOps()
    bad_rows = []
    for r in (rows if rows is not None else range(n - 1)):
        local = [ext.h_from_base(int(v)) for v in trace[r]]
        nxt = [ext.h_from_base(int(v)) for v in trace[(r + 1) % n]]
        periodic = [ext.h_from_base(int(col[r % len(col)]))
                    for col in periodic_cols]
        cs = air.constraints(local, nxt, periodic, hops)
        bad = [i for i, c in enumerate(cs) if c != ext.ZERO_H]
        if bad:
            bad_rows.append((r, bad[:6]))
    return bad_rows


def test_tok_digest_matches_trace_lane():
    segs = _mk_segs()
    trace = tk.generate_token_trace(segs)
    dig = tk.token_public_inputs(segs)
    assert [int(v) for v in trace[-1, tk.T:tk.T + 8]] == dig


@pytest.mark.slow
def test_honest_trace_satisfies_constraints():
    segs = _mk_segs()
    air = tk.TokenAir()
    trace = tk.generate_token_trace(segs)
    n = trace.shape[0]
    assert n == tk.segment_count(len(segs)) * tk.SEG_LEN
    pub = tk.token_public_inputs(segs)
    for row, col, val in air.boundaries(pub, n):
        assert int(trace[row, col]) == val, (row, col, val)
    periodic_cols = air.periodic_columns(n)
    bad = _check_rows(air, trace, periodic_cols)
    assert not bad, f"violated rows: {bad[:8]}"


@pytest.mark.slow
def test_tampered_slot_values_break_constraints():
    segs = _mk_segs()
    air = tk.TokenAir()
    trace = tk.generate_token_trace(segs)
    periodic_cols = air.periodic_columns(trace.shape[0])
    seg0 = slice(0, tk.SEG_LEN)

    # inflate the recipient slot's new value: the carry chain must break
    bad = trace.copy()
    col = tk.TNEW + 10
    bad[seg0, col] = (bad[seg0, col] + 1) % bb.P
    assert _check_rows(air, bad, periodic_cols)

    # deflate the sender slot's debit
    bad2 = trace.copy()
    col2 = tk.FNEW + 10
    bad2[seg0, col2] = (bad2[seg0, col2] + 1) % bb.P
    assert _check_rows(air, bad2, periodic_cols)

    # underflow: amount > fold with a cooked borrow column
    seg_under = [TokSeg(100, KF, 5, (5 - 100) % (1 << 264), KT, 0, 100)]
    tr3 = tk.generate_token_trace(seg_under)
    assert _check_rows(air, tr3, air.periodic_columns(tr3.shape[0]))


@pytest.mark.slow
def test_token_stark_roundtrip():
    from ethrex_tpu.stark import prover as stark_prover
    from ethrex_tpu.stark import verifier as stark_verifier
    from ethrex_tpu.stark.prover import StarkParams

    segs = _mk_segs()
    air = tk.TokenAir()
    trace = tk.generate_token_trace(segs)
    pub = tk.token_public_inputs(segs)
    params = StarkParams(log_blowup=3, num_queries=25, log_final_size=4)
    proof = stark_prover.prove(air, trace, pub, params)
    assert stark_verifier.verify(air, proof, params)

    bad = dict(proof)
    bad["pub_inputs"] = [(int(v) + 1) % bb.P for v in proof["pub_inputs"]]
    with pytest.raises(Exception):
        if not stark_verifier.verify(air, bad, params):
            raise ValueError("rejected")
