"""L2 integration: sequencer -> batch -> coordinator(TCP) -> prover ->
proof -> L1 verification, with deposits — the reference's
test/tests/l2/integration_tests.rs pattern, exec backend as the fast fake
prover plus one full TPU-backend STARK round-trip."""

import time

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.l2.l1_client import InMemoryL1
from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover.client import ProverClient

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
OTHER = bytes.fromhex("aa" * 20)
DEPOSITEE = bytes.fromhex("dd" * 20)

GENESIS = {
    "config": {"chainId": 65536999, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _setup(prover_types):
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1(needed_prover_types=list(prover_types))
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=tuple(prover_types)))
    seq.coordinator.start()
    return node, l1, seq


def _transfer(nonce, value=100):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=65536999, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21000, to=OTHER, value=value,
    ).sign(SECRET)


def test_full_pipeline_exec_backend():
    node, l1, seq = _setup([protocol.PROVER_EXEC])
    try:
        # deposit on L1 -> privileged tx on L2
        l1.deposit(DEPOSITEE, 5 * 10**18)
        seq.watch_l1()
        node.submit_transaction(_transfer(0))
        block1 = seq.produce_block()
        assert any(tx.tx_type == 0x7E for tx in block1.body.transactions)
        root = block1.header.state_root
        assert node.store.account_state(root, DEPOSITEE).balance == 5 * 10**18
        # more activity, second block
        node.submit_transaction(_transfer(1))
        seq.produce_block()
        # commit the batch (blocks 1-2)
        batch = seq.commit_next_batch()
        assert batch.number == 1 and batch.last_block == 2
        assert l1.last_committed_batch() == 1
        # prover round-trip over real TCP
        client = ProverClient(protocol.PROVER_EXEC,
                              [("127.0.0.1", seq.coordinator.port)])
        assert client.poll_once() == 1
        assert seq.rollup.get_proof(1, protocol.PROVER_EXEC) is not None
        # duplicate proving finds nothing to do
        assert client.poll_once() == 0
        # proof sender verifies on L1
        assert seq.send_proofs() == (1, 1)
        assert l1.last_verified_batch() == 1
        assert seq.rollup.get_batch(1).verified
    finally:
        seq.stop()


def test_pipeline_multi_batch_and_wrong_version():
    node, l1, seq = _setup([protocol.PROVER_EXEC])
    try:
        for i in range(3):
            node.submit_transaction(_transfer(i))
            seq.produce_block()
            seq.commit_next_batch()
        assert l1.last_committed_batch() == 3
        # a prover with a mismatched version is rejected
        bad = ProverClient(protocol.PROVER_EXEC,
                           [("127.0.0.1", seq.coordinator.port)],
                           commit_hash="other-version")
        assert bad.poll_once() == 0
        good = ProverClient(protocol.PROVER_EXEC,
                            [("127.0.0.1", seq.coordinator.port)])
        # three polls, three batches proven
        total = 0
        for _ in range(4):
            total += good.poll_once()
        assert total == 3
        assert seq.send_proofs() == (1, 3)
        assert l1.last_verified_batch() == 3
    finally:
        seq.stop()


@pytest.mark.slow
def test_full_pipeline_tpu_backend():
    """One real TPU-prover round: DEEP-FRI STARK binding the batch output."""
    node, l1, seq = _setup([protocol.PROVER_TPU])
    try:
        node.submit_transaction(_transfer(0))
        seq.produce_block()
        batch = seq.commit_next_batch()
        assert batch is not None
        client = ProverClient(protocol.PROVER_TPU,
                              [("127.0.0.1", seq.coordinator.port)])
        t0 = time.time()
        assert client.poll_once() == 1
        proof = seq.rollup.get_proof(1, protocol.PROVER_TPU)
        assert proof["backend"] == "tpu" and proof["proof"] is not None
        # the proving trace carries the per-kernel stage spans
        from ethrex_tpu.utils.tracing import TRACER
        trace = TRACER.get_trace(seq.coordinator.batch_traces[1])
        names = {s["name"] for s in trace["spans"]}
        assert {"prover.assign", "prover.prove", "prove.trace_lde",
                "prove.merkle_commit", "prove.fri_fold",
                "prover.submit"} <= names
        # independent verification + L1 settlement
        assert seq.send_proofs() == (1, 1)
        assert l1.last_verified_batch() == 1
        # tampered output must not verify
        from ethrex_tpu.prover.backend import get_backend
        backend = get_backend(protocol.PROVER_TPU)
        assert backend.verify(proof)
        bad = dict(proof)
        out = bytearray.fromhex(proof["output"][2:])
        out[0] ^= 1
        bad["output"] = "0x" + out.hex()
        assert not backend.verify(bad)
    finally:
        seq.stop()


def test_l2_rpc_namespace():
    node, l1, seq = _setup([protocol.PROVER_EXEC])
    try:
        node.sequencer = seq
        from ethrex_tpu.rpc.server import RpcServer

        server = RpcServer(node)
        # no batches yet
        r = server.handle({"jsonrpc": "2.0", "id": 1,
                           "method": "ethrex_latestBatch", "params": []})
        assert r["result"] is None
        node.submit_transaction(_transfer(0))
        seq.produce_block()
        seq.commit_next_batch()
        r = server.handle({"jsonrpc": "2.0", "id": 1,
                           "method": "ethrex_latestBatch", "params": []})
        assert r["result"]["number"] == "0x1"
        assert r["result"]["committed"] is True
        r = server.handle({"jsonrpc": "2.0", "id": 1,
                           "method": "ethrex_getBatchByNumber",
                           "params": ["0x1"]})
        assert r["result"]["lastBlock"] == "0x1"
        h = server.handle({"jsonrpc": "2.0", "id": 1,
                           "method": "ethrex_health", "params": []})
        assert h["result"]["l2"]["latestBatch"] == 1
        # without a sequencer attached the namespace errors cleanly
        del node.sequencer
        r = server.handle({"jsonrpc": "2.0", "id": 1,
                           "method": "ethrex_latestBatch", "params": []})
        assert r["error"]["code"] == -32000
    finally:
        seq.stop()


def test_sequencer_timers_smoke():
    """Actors run on timers end-to-end (fast intervals)."""
    node, l1, _seq = _setup([protocol.PROVER_EXEC])
    _seq.stop()
    node2 = Node(Genesis.from_json(GENESIS))
    l1b = InMemoryL1([protocol.PROVER_EXEC])
    seq = Sequencer(node2, l1b, SequencerConfig(
        block_time=0.2, commit_interval=0.3, proof_send_interval=0.3,
        watcher_interval=0.2,
        needed_prover_types=(protocol.PROVER_EXEC,))).start()
    prover = ProverClient(protocol.PROVER_EXEC,
                          [("127.0.0.1", seq.coordinator.port)],
                          poll_interval=0.2).start()
    try:
        l1b.deposit(DEPOSITEE, 123)
        node2.submit_transaction(_transfer(0))
        deadline = time.time() + 20
        while time.time() < deadline and l1b.last_verified_batch() < 1:
            time.sleep(0.2)
        assert l1b.last_verified_batch() >= 1
    finally:
        prover.stop()
        seq.stop()
        node2.stop()


def test_actor_backoff_and_fatal_cancellation():
    """A persistently failing actor backs off exponentially, then fatally
    cancels the whole sequencer (reference: the cancellation-token ->
    non-zero-exit pattern, cmd/ethrex/ethrex.rs)."""
    import time as _time

    from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
    from ethrex_tpu.l2.l1_client import InMemoryL1

    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1([protocol.PROVER_EXEC])
    cfg = SequencerConfig(block_time=0.01, commit_interval=0.01,
                          proof_send_interval=0.01, watcher_interval=0.01,
                          needed_prover_types=(protocol.PROVER_EXEC,),
                          max_actor_failures=3, max_backoff_factor=4)
    seq = Sequencer(node, l1, cfg)

    boom_calls = []

    def boom():
        boom_calls.append(_time.time())
        raise RuntimeError("always failing")

    boom.__name__ = "boom"
    seq.commit_next_batch = boom
    fatal_seen = []
    seq.on_fatal = lambda actor, err: fatal_seen.append((actor, err))
    seq.start()
    try:
        deadline = _time.time() + 10
        while seq.fatal is None and _time.time() < deadline:
            _time.sleep(0.05)
        assert seq.fatal is not None, "fatal cancellation did not fire"
        assert seq.fatal[0] == "boom"
        assert fatal_seen and "always failing" in fatal_seen[0][1]
        assert len(boom_calls) == 3
        # backoff: gaps grow between consecutive failures
        gaps = [b - a for a, b in zip(boom_calls, boom_calls[1:])]
        assert gaps[-1] > gaps[0]
        # health reflects the failure
        st = seq.health["boom"]
        assert not st.healthy and "always failing" in st.last_error
        # cancellation stopped the other actors too (allow in-flight
        # actor bodies to finish their current run)
        deadline2 = _time.time() + 5
        while _time.time() < deadline2 and \
                any(t.is_alive() for t in seq._threads):
            _time.sleep(0.05)
        assert all(not t.is_alive() for t in seq._threads)
    finally:
        seq.stop()
        node.stop()


def test_l1_message_proof_rpc():
    """ethrex_getL1MessageProof serves the withdrawal claim data (batch,
    index, leaf, Merkle path) and the L1 accepts the claim built from it
    (reference: l2/networking/rpc/l2/messages.rs)."""
    import json as _json
    import urllib.request as _rq

    from ethrex_tpu.l2.messages import BRIDGE_ADDRESS
    from ethrex_tpu.rpc.server import RpcServer

    node, l1, seq = _setup([protocol.PROVER_EXEC])
    node.sequencer = seq
    server = RpcServer(node, port=0).start()
    try:
        # a withdrawal: value burned to the bridge address
        wd = Transaction(
            tx_type=TYPE_DYNAMIC_FEE, chain_id=65536999, nonce=0,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=21000, to=BRIDGE_ADDRESS, value=777).sign(SECRET)
        node.submit_transaction(wd)
        seq.produce_block()
        batch = seq.commit_next_batch()
        assert batch is not None

        def call(method, *params):
            payload = _json.dumps({"jsonrpc": "2.0", "id": 1,
                                   "method": method,
                                   "params": list(params)}).encode()
            req = _rq.Request(f"http://127.0.0.1:{server.port}",
                              data=payload,
                              headers={"Content-Type": "application/json"})
            with _rq.urlopen(req, timeout=10) as resp:
                return _json.loads(resp.read())

        proof = call("ethrex_getL1MessageProof",
                     "0x" + wd.hash.hex())["result"]
        assert proof is not None
        assert int(proof["batchNumber"], 16) == batch.number
        assert proof["verified"] is False
        assert call("ethrex_batchNumberByBlock",
                    hex(batch.first_block))["result"] == \
            proof["batchNumber"]
        # prove + verify the batch, then the claim goes through on L1
        client = ProverClient(protocol.PROVER_EXEC,
                              [("127.0.0.1", seq.coordinator.port)])
        assert client.poll_once() == 1
        assert seq.send_proofs() == (1, 1)
        leaf = bytes.fromhex(proof["messageHash"][2:])
        path = [bytes.fromhex(p[2:]) for p in proof["merkleProof"]]
        l1.claim_withdrawal(batch.number, leaf,
                            int(proof["messageId"], 16), path)
        # unknown tx -> null
        assert call("ethrex_getL1MessageProof",
                    "0x" + "ab" * 32)["result"] is None
    finally:
        server.stop()
        seq.stop()


def test_admin_committer_controls():
    """Admin surface (reference admin_server.rs): stop/start the
    committer actor over RPC — against the LIVE actor loop — plus the
    stop-at-batch cap, admin gating on the public server, and health
    visibility of the paused state."""
    import json as _json
    import time as _time
    import urllib.request as _rq

    from ethrex_tpu.rpc.server import RpcServer

    node, l1, seq = _setup([protocol.PROVER_EXEC])
    node.sequencer = seq
    seq.cfg.block_time = 0.05
    seq.cfg.commit_interval = 0.05
    seq.cfg.proof_send_interval = 0.05
    seq.cfg.watcher_interval = 0.05
    server = RpcServer(node, port=0, admin=True)
    public = RpcServer(node, port=0)            # admin NOT enabled

    def call(srv, method, *params):
        payload = _json.dumps({"jsonrpc": "2.0", "id": 1,
                               "method": method,
                               "params": list(params)}).encode()
        req = _rq.Request(f"http://127.0.0.1:{srv.port}",
                          data=payload,
                          headers={"Content-Type": "application/json"})
        with _rq.urlopen(req, timeout=10) as resp:
            return _json.loads(resp.read())

    server.start()
    public.start()
    try:
        # the public unauthenticated server refuses admin controls
        r = call(public, "ethrex_adminStopCommitter")
        assert r["error"]["code"] == -32601

        # pause the committer BEFORE starting the loops
        r = call(server, "ethrex_adminStopCommitter")["result"]
        assert r == {"committer": "paused"}
        health = call(server, "ethrex_health")["result"]
        assert health["l2"]["paused"] == ["commit_next_batch"]

        seq.start()
        node.submit_transaction(_transfer(0))
        deadline = _time.time() + 5
        while _time.time() < deadline and \
                node.store.latest_number() == 0:
            _time.sleep(0.05)
        assert node.store.latest_number() >= 1
        _time.sleep(0.5)   # several commit ticks elapse while paused
        assert seq.rollup.latest_batch_number() == 0

        # resume: the LIVE loop commits the batch
        assert call(server, "ethrex_adminStartCommitter")["result"] == \
            {"committer": "running"}
        deadline = _time.time() + 10
        while _time.time() < deadline and \
                seq.rollup.latest_batch_number() == 0:
            _time.sleep(0.05)
        assert seq.rollup.latest_batch_number() >= 1

        # stop-at-batch caps the live committer; null clears it.  A
        # commit tick can land between reading `cap` and the RPC taking
        # effect, so the invariant is that the batch number FREEZES once
        # the cap is set (any in-flight commit gets 0.3s to drain), not
        # that it equals the pre-RPC read.
        cap = seq.rollup.latest_batch_number()
        assert call(server, "ethrex_adminSetStopAtBatch",
                    hex(cap))["result"] == {"stopAtBatch": hex(cap)}
        _time.sleep(0.3)
        frozen = seq.rollup.latest_batch_number()
        node.submit_transaction(_transfer(1))
        _time.sleep(0.6)   # many commit ticks; a broken cap would commit
        assert seq.rollup.latest_batch_number() == frozen
        assert call(server, "ethrex_adminSetStopAtBatch",
                    None)["result"] == {"stopAtBatch": None}
        deadline = _time.time() + 10
        while _time.time() < deadline and \
                seq.rollup.latest_batch_number() == frozen:
            _time.sleep(0.05)
        assert seq.rollup.latest_batch_number() > frozen

        # unknown actor names are rejected, not silently accepted
        import pytest as _pytest

        with _pytest.raises(ValueError):
            seq.pause_actor("no_such_actor")
    finally:
        server.stop()
        public.stop()
        seq.stop()
