"""Self-generated BlockchainTest-format smoke fixtures for the
ef_blockchain runner (ethrex_tpu/utils/ef_blockchain.py).

These are the runner's harness, NOT independent conformance: expected
hashes come from this repo's own executor (public EF archives plug into
the same runner unchanged; they are not redistributable inside this
image).  Units: a valid Cancun transfer+contract chain with postState,
plus declared-invalid variants (tampered state root, wrong base fee,
undecodable RLP, tampered gas used).

Run:  python tests/fixtures/ef_blockchain/_generate.py
"""

import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

from ethrex_tpu.crypto import secp256k1  # noqa: E402
from ethrex_tpu.node import Node  # noqa: E402
from ethrex_tpu.primitives.block import Block  # noqa: E402
from ethrex_tpu.primitives.genesis import Genesis  # noqa: E402
from ethrex_tpu.primitives.transaction import Transaction  # noqa: E402

SECRET = 0xA11CE
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
OTHER = bytes.fromhex("44" * 20)
# sstore(0, calldataload(0)): 600035 5f 55 00
CODE = bytes.fromhex("6000355f5500")
CONTRACT = bytes.fromhex("c0de" * 10)

GENESIS_JSON = {
    "config": {"chainId": 1, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {
        "0x" + SENDER.hex(): {"balance": hex(10**21)},
        "0x" + CONTRACT.hex(): {"balance": "0x0",
                                "code": "0x" + CODE.hex()},
    },
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _hdr_json(h):
    out = {
        "parentHash": "0x" + h.parent_hash.hex(),
        "coinbase": "0x" + h.coinbase.hex(),
        "stateRoot": "0x" + h.state_root.hex(),
        "difficulty": hex(h.difficulty),
        "number": hex(h.number),
        "gasLimit": hex(h.gas_limit),
        "gasUsed": hex(h.gas_used),
        "timestamp": hex(h.timestamp),
        "extraData": "0x" + h.extra_data.hex(),
        "mixHash": "0x" + h.prev_randao.hex(),
        "nonce": "0x" + h.nonce.hex(),
        "hash": "0x" + h.hash.hex(),
    }
    if h.base_fee_per_gas is not None:
        out["baseFeePerGas"] = hex(h.base_fee_per_gas)
    if h.excess_blob_gas is not None:
        out["excessBlobGas"] = hex(h.excess_blob_gas)
    if h.blob_gas_used is not None:
        out["blobGasUsed"] = hex(h.blob_gas_used)
    return out


def _build_chain():
    node = Node(Genesis.from_json(GENESIS_JSON))
    blocks = []
    nonce = 0
    for n in range(3):
        node.submit_transaction(Transaction(
            tx_type=2, chain_id=1, nonce=nonce,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=21000, to=OTHER, value=1000 + n).sign(SECRET))
        nonce += 1
        node.submit_transaction(Transaction(
            tx_type=2, chain_id=1, nonce=nonce,
            max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
            gas_limit=90_000, to=CONTRACT, value=0,
            data=(7 + n).to_bytes(32, "big")).sign(SECRET))
        nonce += 1
        blocks.append(node.produce_block())
    return node, blocks


def main():
    node, blocks = _build_chain()
    store = node.store
    genesis = Genesis.from_json(GENESIS_JSON)
    gh = store.get_header(blocks[0].header.parent_hash)
    genesis_rlp = Block(gh, dataclasses.replace(
        blocks[0].body, transactions=[], withdrawals=[])).encode()

    head = blocks[-1].header
    root = head.state_root
    post = {}
    for addr in (SENDER, OTHER, CONTRACT):
        st = store.account_state(root, addr)
        entry = {"nonce": hex(st.nonce), "balance": hex(st.balance)}
        if addr == CONTRACT:
            entry["storage"] = {"0x00": hex(store.storage_at(root, addr, 0))}
        post["0x" + addr.hex()] = entry

    pre = GENESIS_JSON["alloc"]
    base = {
        "network": "Cancun",
        "genesisBlockHeader": _hdr_json(gh),
        "genesisRLP": "0x" + genesis_rlp.hex(),
        "pre": pre,
        "sealEngine": "NoProof",
    }

    units = {}
    units["valid_transfer_contract_chain"] = dict(
        base,
        blocks=[{"rlp": "0x" + b.encode().hex()} for b in blocks],
        lastblockhash="0x" + head.hash.hex(),
        postState=post,
    )
    # declared-invalid variants: the prefix chain is valid, the final
    # block is tampered and must be rejected
    bad_root = Block(dataclasses.replace(blocks[2].header,
                                         state_root=b"\x11" * 32),
                     blocks[2].body)
    units["invalid_state_root"] = dict(
        base,
        blocks=[{"rlp": "0x" + blocks[0].encode().hex()},
                {"rlp": "0x" + blocks[1].encode().hex()},
                {"rlp": "0x" + bad_root.encode().hex(),
                 "expectException": "InvalidStateRoot"}],
        lastblockhash="0x" + blocks[1].header.hash.hex(),
        postStateHash="0x" + blocks[1].header.state_root.hex(),
    )
    bad_fee = Block(dataclasses.replace(blocks[2].header,
                                        base_fee_per_gas=1234),
                    blocks[2].body)
    units["invalid_base_fee"] = dict(
        base,
        blocks=[{"rlp": "0x" + blocks[0].encode().hex()},
                {"rlp": "0x" + blocks[1].encode().hex()},
                {"rlp": "0x" + bad_fee.encode().hex(),
                 "expectException": "InvalidBaseFee"}],
        lastblockhash="0x" + blocks[1].header.hash.hex(),
        postStateHash="0x" + blocks[1].header.state_root.hex(),
    )
    bad_gas = Block(dataclasses.replace(blocks[2].header,
                                        gas_used=head.gas_used + 1),
                    blocks[2].body)
    units["invalid_gas_used"] = dict(
        base,
        blocks=[{"rlp": "0x" + blocks[0].encode().hex()},
                {"rlp": "0x" + blocks[1].encode().hex()},
                {"rlp": "0x" + bad_gas.encode().hex(),
                 "expectException": "InvalidGasUsed"}],
        lastblockhash="0x" + blocks[1].header.hash.hex(),
        postStateHash="0x" + blocks[1].header.state_root.hex(),
    )
    units["undecodable_block_rlp"] = dict(
        base,
        blocks=[{"rlp": "0x" + blocks[0].encode().hex()},
                {"rlp": "0xdeadbeef",
                 "expectException": "BlockRLPDecodeError"}],
        lastblockhash="0x" + blocks[0].header.hash.hex(),
        postStateHash="0x" + blocks[0].header.state_root.hex(),
    )

    out = os.path.join(os.path.dirname(__file__), "smoke.json")
    with open(out, "w") as f:
        json.dump(units, f, indent=1)
    print(f"wrote {len(units)} units to {out}")


if __name__ == "__main__":
    main()
