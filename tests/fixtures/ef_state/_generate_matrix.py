"""Matrix-generated GeneralStateTest fixtures with an INDEPENDENT gas
oracle.

Unlike _generate.py's handful of hand-authored scenarios, this module
systematically sweeps the gas-bearing surface of the EVM — opcode family
costs, memory expansion, EIP-2929 warm/cold access, the full EIP-2200/3529
SSTORE matrix, refund capping, copies, logs, EXP, transient storage,
CREATE/CREATE2, precompile pricing, intrinsic/access-list/EIP-7623-floor
arithmetic — and derives every case's expected gas from FIRST PRINCIPLES
in a tiny analytic assembler (cost tables written straight from the EIPs,
independent of ethrex_tpu/evm/*).

At generation time each case is executed by the repo's EVM and the two
implementations MUST agree on gas to the unit; a disagreement aborts
generation — that cross-check is the conformance content.  The emitted
fixtures then pin post-state hashes (which embed the gas via balances) in
the exact EF wire format, so the suite keeps failing loudly if either the
gas model or state handling drifts (reference runner equivalent:
/root/reference/tooling/ef_tests/state_v2/src/runner.rs).

Run:  python tests/fixtures/ef_state/_generate_matrix.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

from ethrex_tpu.crypto import secp256k1  # noqa: E402
from ethrex_tpu.utils import ef_state  # noqa: E402

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = "0x" + secp256k1.pubkey_to_address(
    secp256k1.pubkey_from_secret(SECRET)).hex()
COINBASE = "0x2adc25665018aa1fe0e6bc666dac8fc2697ff9ba"
CODE_ADDR = "0x" + "bb" * 20
AUX_ADDR = "0x" + "cc" * 20

ENV = {
    "currentCoinbase": COINBASE,
    "currentGasLimit": "0x1c9c380",
    "currentNumber": "0x1",
    "currentTimestamp": "0x3e8",
    "currentBaseFee": "0xa",
    "currentRandom": "0x" + "00" * 32,
}

FORKS = ("Cancun", "Prague")


# ---------------------------------------------------------------------------
# The analytic assembler: emits bytecode while accounting gas per the EIPs
# ---------------------------------------------------------------------------

def words(n):
    return (n + 31) // 32


def mem_cost(byte_size):
    w = words(byte_size)
    return 3 * w + w * w // 512


OP = {
    "STOP": 0x00, "ADD": 0x01, "MUL": 0x02, "SUB": 0x03, "DIV": 0x04,
    "SDIV": 0x05, "MOD": 0x06, "SMOD": 0x07, "ADDMOD": 0x08,
    "MULMOD": 0x09, "EXP": 0x0A, "SIGNEXTEND": 0x0B, "LT": 0x10,
    "GT": 0x11, "SLT": 0x12, "SGT": 0x13, "EQ": 0x14, "ISZERO": 0x15,
    "AND": 0x16, "OR": 0x17, "XOR": 0x18, "NOT": 0x19, "BYTE": 0x1A,
    "SHL": 0x1B, "SHR": 0x1C, "SAR": 0x1D, "KECCAK256": 0x20,
    "ADDRESS": 0x30, "BALANCE": 0x31, "ORIGIN": 0x32, "CALLER": 0x33,
    "CALLVALUE": 0x34, "CALLDATALOAD": 0x35, "CALLDATASIZE": 0x36,
    "CALLDATACOPY": 0x37, "CODESIZE": 0x38, "CODECOPY": 0x39,
    "GASPRICE": 0x3A, "EXTCODESIZE": 0x3B, "EXTCODECOPY": 0x3C,
    "RETURNDATASIZE": 0x3D, "RETURNDATACOPY": 0x3E, "EXTCODEHASH": 0x3F,
    "BLOCKHASH": 0x40, "COINBASE": 0x41, "TIMESTAMP": 0x42,
    "NUMBER": 0x43, "PREVRANDAO": 0x44, "GASLIMIT": 0x45, "CHAINID": 0x46,
    "SELFBALANCE": 0x47, "BASEFEE": 0x48, "BLOBHASH": 0x49,
    "BLOBBASEFEE": 0x4A, "POP": 0x50, "MLOAD": 0x51, "MSTORE": 0x52,
    "MSTORE8": 0x53, "SLOAD": 0x54, "SSTORE": 0x55, "JUMP": 0x56,
    "JUMPI": 0x57, "PC": 0x58, "MSIZE": 0x59, "GAS": 0x5A,
    "JUMPDEST": 0x5B, "TLOAD": 0x5C, "TSTORE": 0x5D, "MCOPY": 0x5E,
    "PUSH0": 0x5F, "CREATE": 0xF0, "CALL": 0xF1, "RETURN": 0xF3,
    "DELEGATECALL": 0xF4, "CREATE2": 0xF5, "STATICCALL": 0xFA,
    "LOG0": 0xA0, "LOG1": 0xA1, "LOG2": 0xA2, "LOG3": 0xA3, "LOG4": 0xA4,
}

BASE2 = {"ADDRESS", "ORIGIN", "CALLER", "CALLVALUE", "CALLDATASIZE",
         "CODESIZE", "GASPRICE", "COINBASE", "TIMESTAMP", "NUMBER",
         "PREVRANDAO", "GASLIMIT", "CHAINID", "RETURNDATASIZE", "POP",
         "PC", "MSIZE", "GAS", "BASEFEE", "BLOBBASEFEE"}
VERYLOW3 = {"ADD", "SUB", "LT", "GT", "SLT", "SGT", "EQ", "ISZERO", "AND",
            "OR", "XOR", "NOT", "BYTE", "SHL", "SHR", "SAR",
            "CALLDATALOAD"}
LOW5 = {"MUL", "DIV", "SDIV", "MOD", "SMOD", "SIGNEXTEND", "SELFBALANCE"}
MID8 = {"ADDMOD", "MULMOD", "JUMP"}


class Asm:
    """Bytecode emitter + independent gas meter (EIP cost tables)."""

    def __init__(self):
        self.code = bytearray()
        self.gas = 0
        self.mem = 0               # current memory size in bytes
        self.refund = 0
        self.warm_slots = set()    # per-tx warm (this code address)
        self.warm_addrs = set()

    def push(self, v: int):
        if v == 0:
            self.code.append(OP["PUSH0"])
            self.gas += 2
            return self
        b = v.to_bytes((v.bit_length() + 7) // 8, "big")
        self.code.append(0x5F + len(b))
        self.code += b
        self.gas += 3
        return self

    def op(self, name: str, cost: int | None = None):
        self.code.append(OP[name])
        if cost is not None:
            self.gas += cost
        elif name in BASE2:
            self.gas += 2
        elif name in VERYLOW3:
            self.gas += 3
        elif name in LOW5:
            self.gas += 5
        elif name in MID8:
            self.gas += 8
        elif name == "JUMPDEST":
            self.gas += 1
        elif name == "STOP":
            pass
        else:
            raise ValueError(f"op {name} needs an explicit cost")
        return self

    def _expand(self, end: int):
        if end > self.mem:
            self.gas += mem_cost(end) - mem_cost(self.mem)
            self.mem = (words(end)) * 32

    def mstore(self, off: int, v: int = 1):
        self.push(v).push(off)
        self._expand(off + 32)
        return self.op("MSTORE", 3)

    def mstore8(self, off: int, v: int = 1):
        self.push(v).push(off)
        self._expand(off + 1)
        return self.op("MSTORE8", 3)

    def mload(self, off: int):
        self.push(off)
        self._expand(off + 32)
        self.op("MLOAD", 3)
        return self.op("POP")

    def keccak(self, off: int, ln: int):
        self.push(ln).push(off)
        if ln:
            self._expand(off + ln)
        self.op("KECCAK256", 30 + 6 * words(ln))
        return self.op("POP")

    def copy(self, name: str, dst: int, src: int, ln: int,
             extra: int = 0):
        """CALLDATACOPY/CODECOPY/RETURNDATACOPY/MCOPY; extra = address
        access cost for EXTCODECOPY."""
        if name == "MCOPY" and ln:
            self._expand(max(dst, src) + ln)
        elif ln:
            self._expand(dst + ln)
        self.push(ln).push(src).push(dst)
        self.gas += extra + 3 + 3 * words(ln)
        self.code.append(OP[name])
        return self

    def sload(self, slot: int):
        self.push(slot)
        cold = slot not in self.warm_slots
        self.warm_slots.add(slot)
        self.op("SLOAD", 2100 if cold else 100)
        return self.op("POP")

    def sstore(self, slot: int, new: int, original: int, current: int):
        """EIP-2200/3529 + EIP-2929 pricing from the spec tables."""
        self.push(new).push(slot)
        cost = 0
        if slot not in self.warm_slots:
            cost += 2100
            self.warm_slots.add(slot)
        if new == current:
            cost += 100
        elif current == original:
            cost += 20000 if original == 0 else 2900
            if original != 0 and new == 0:
                self.refund += 4800
        else:  # dirty slot
            cost += 100
            if original != 0:
                if current == 0:
                    self.refund -= 4800
                elif new == 0:
                    self.refund += 4800
            if new == original:
                if original == 0:
                    self.refund += 20000 - 100
                else:
                    self.refund += 5000 - 2100 - 100
        return self.op("SSTORE", cost)

    def acct_op(self, name: str, addr: int):
        self.push(addr)
        cold = addr not in self.warm_addrs
        self.warm_addrs.add(addr)
        self.op(name, (2600 if cold else 100))
        return self.op("POP")

    def log(self, topics: int, off: int, ln: int):
        for t in range(topics):
            self.push(t + 1)
        self.push(ln).push(off)
        if ln:
            self._expand(off + ln)
        return self.op(f"LOG{topics}", 375 + 375 * topics + 8 * ln)

    def exp(self, base: int, exponent: int):
        self.push(exponent).push(base)
        blen = (exponent.bit_length() + 7) // 8 if exponent else 0
        self.op("EXP", 10 + 50 * blen)
        return self.op("POP")

    def tstore(self, slot: int, v: int):
        self.push(v).push(slot)
        return self.op("TSTORE", 100)

    def tload(self, slot: int):
        self.push(slot)
        self.op("TLOAD", 100)
        return self.op("POP")

    def call_precompile(self, addr: int, in_len: int, cost: int,
                        gas_arg: int = 0xFFFFF):
        """STATICCALL to an always-warm precompile with in_len input bytes
        (memory already expanded to in_len by the caller scenario)."""
        self._expand(in_len)
        self.push(0).push(0).push(in_len).push(0)
        self.push(addr).push(gas_arg)
        self.op("STATICCALL", 100 + cost)
        return self.op("POP")

    def call_stop_contract(self, name: str, addr: int, value: int,
                           cold: bool, new_account: bool = False):
        """CALL-family to a contract whose code is empty/STOP: the callee
        consumes nothing, so the net cost is the call surcharge itself."""
        if name == "CALL":
            self.push(0).push(0).push(0).push(0)
            self.push(value).push(addr).push(0)
        else:
            self.push(0).push(0).push(0).push(0)
            self.push(addr).push(0)
        cost = 2600 if cold else 100
        if name == "CALL" and value:
            cost += 9000 - 2300   # stipend comes back from the STOP callee
            if new_account:
                cost += 25000
        self.op(name, cost)
        return self.op("POP")

    def stop(self):
        self.code.append(OP["STOP"])
        return self

    @property
    def hexcode(self):
        return "0x" + bytes(self.code).hex()


# ---------------------------------------------------------------------------
# Case assembly
# ---------------------------------------------------------------------------

def intrinsic(data: bytes, access_list=None, create=False):
    z = data.count(0)
    nz = len(data) - z
    g = 21000 + 4 * z + 16 * nz
    if create:
        g += 32000 + 2 * words(len(data))
    for entry in access_list or []:
        g += 2400 + 1900 * len(entry.get("storageKeys", []))
    return g


def floor_gas(data: bytes):
    tokens = data.count(0) + 4 * (len(data) - data.count(0))
    return 21000 + 10 * tokens


class Case:
    def __init__(self, name, asm: Asm, *, data=b"", storage=None,
                 access_list=None, value=0, gas_limit=1_000_000,
                 aux_code=None, aux_balance=0, forks=FORKS,
                 target=CODE_ADDR, create=False, expected_gas=None,
                 extra_pre=None):
        self.name = name
        self.asm = asm
        self.data = data
        self.storage = storage or {}
        self.access_list = access_list
        self.value = value
        self.gas_limit = gas_limit
        self.aux_code = aux_code
        self.aux_balance = aux_balance
        self.forks = forks
        self.target = target
        self.create = create
        self._expected = expected_gas
        self.extra_pre = extra_pre or {}

    def expected_gas(self, fork):
        if self._expected is not None:
            return self._expected
        data = bytes(self.asm.code) if self.create else self.data
        exec_gas = intrinsic(data, self.access_list,
                             self.create) + self.asm.gas
        exec_gas -= min(self.asm.refund, exec_gas // 5)
        if fork == "Prague":
            return max(exec_gas, floor_gas(data))
        return exec_gas

    def build(self):
        pre = {
            SENDER: {"balance": "0x56bc75e2d63100000", "nonce": "0x00",
                     "code": "0x", "storage": {}},
        }
        if not self.create:
            pre[self.target] = {
                "balance": "0x0", "nonce": "0x01",
                "code": self.asm.hexcode,
                "storage": {hex(k): hex(v)
                            for k, v in self.storage.items()}}
        if self.aux_code is not None:
            pre[AUX_ADDR] = {"balance": hex(self.aux_balance),
                             "nonce": "0x01", "code": self.aux_code,
                             "storage": {}}
        pre.update(self.extra_pre)
        tx = {
            "data": ["0x" + (self.asm.hexcode[2:] if self.create
                             else self.data.hex())],
            "gasLimit": [hex(self.gas_limit)],
            "value": [hex(self.value)],
            "gasPrice": "0x14", "nonce": "0x00",
            "to": "" if self.create else self.target,
            "secretKey": hex(SECRET), "sender": SENDER,
        }
        if self.access_list is not None:
            tx["accessLists"] = [self.access_list]
            del tx["gasPrice"]
            tx["maxFeePerGas"] = "0x14"
            tx["maxPriorityFeePerGas"] = "0x01"
        return pre, tx


def _run(case: Case, pre, tx, fork):
    tc = ef_state.StateTestCase(
        name=case.name, fork=fork,
        tx=ef_state._build_tx(tx, {"data": 0, "gas": 0, "value": 0}),
        pre=ef_state._parse_pre(pre), env=ENV,
        expected_hash=b"\x00" * 32, expected_logs=b"\x00" * 32,
        expect_exception=None, indexes=(0, 0, 0))
    root, logs, err, gas = ef_state.execute_case(tc)
    assert err is None, f"{case.name}/{fork}: tx invalid: {err}"
    want = case.expected_gas(fork)
    assert gas == want, (
        f"{case.name}/{fork}: analytic gas {want} != executor {gas} "
        f"(delta {gas - want})")
    return {"hash": "0x" + root.hex(), "logs": "0x" + logs.hex(),
            "indexes": {"data": 0, "gas": 0, "value": 0}}


# ---------------------------------------------------------------------------
# The matrix
# ---------------------------------------------------------------------------

def build_cases() -> list[Case]:
    cases = []

    # 1. pure stack/arithmetic ops
    for name in sorted(BASE2 - {"POP", "RETURNDATASIZE"}):
        a = Asm()
        a.op(name).op("POP").stop()
        cases.append(Case(f"op_{name.lower()}", a))
    for name in sorted(VERYLOW3 - {"CALLDATALOAD"}) + sorted(LOW5) \
            + sorted(MID8 - {"JUMP"}):
        a = Asm()
        n_args = 3 if name in ("ADDMOD", "MULMOD") else \
            1 if name in ("ISZERO", "NOT") else 2
        for i in range(n_args):
            a.push(i + 7)
        a.op(name).op("POP").stop()
        cases.append(Case(f"op_{name.lower()}", a))
    a = Asm()
    a.push(5).push(0).op("BYTE").op("POP").stop()
    cases.append(Case("op_byte_args", a))

    # dup/swap/push widths
    for k in (1, 8, 16):
        a = Asm()
        for i in range(k):
            a.push(i + 1)
        a.code.append(0x80 + k - 1)  # DUPk
        a.gas += 3
        a.op("POP").stop()
        cases.append(Case(f"op_dup{k}", a))
        a = Asm()
        for i in range(k + 1):
            a.push(i + 1)
        a.code.append(0x90 + k - 1)  # SWAPk
        a.gas += 3
        a.stop()
        cases.append(Case(f"op_swap{k}", a))
    for width in (1, 4, 16, 32):
        a = Asm()
        a.push((1 << (8 * width)) - 1).op("POP").stop()
        cases.append(Case(f"op_push{width}", a))

    # jumps
    a = Asm()
    a.push(3).op("JUMP")
    a.code.append(OP["JUMPDEST"])
    a.gas += 1
    a.stop()
    cases.append(Case("op_jump", a))
    a = Asm()
    a.push(1).push(5).op("JUMPI", 10)
    a.code.append(OP["JUMPDEST"])
    a.gas += 1
    a.stop()
    cases.append(Case("op_jumpi_taken", a))

    # 2. memory expansion sweep (quadratic region included)
    for off in (0, 32, 96, 1024, 10_000, 100_000):
        a = Asm()
        a.mstore(off, 0xAB)
        a.stop()
        cases.append(Case(f"mem_mstore_{off}", a))
    a = Asm()
    a.mstore8(70_001, 0x7)
    a.stop()
    cases.append(Case("mem_mstore8_70001", a))
    a = Asm()
    a.mload(131_072)
    a.stop()
    cases.append(Case("mem_mload_131072", a))

    # 3. keccak sizes
    for ln in (0, 1, 32, 33, 256, 4096):
        a = Asm()
        a.keccak(0, ln)
        a.stop()
        cases.append(Case(f"keccak_{ln}", a))

    # 4. SSTORE matrix (original, current==original in pre, new) + dirty
    sstore_matrix = [
        (0, 1), (0, 0), (5, 5), (5, 0), (5, 9),
    ]
    for orig, new in sstore_matrix:
        a = Asm()
        a.sstore(1, new, orig, orig)
        a.stop()
        cases.append(Case(f"sstore_{orig}_to_{new}", a,
                          storage={1: orig} if orig else {}))
    # dirty transitions: write twice in one tx
    dirty = [
        (0, 1, 0),    # set then clear back to original-zero
        (5, 0, 5),    # clear then restore original
        (5, 9, 0),    # change then clear
        (5, 0, 9),    # clear then re-set nonzero
        (0, 1, 2),    # set then overwrite dirty
    ]
    for orig, first, second in dirty:
        a = Asm()
        a.sstore(1, first, orig, orig)
        a.sstore(1, second, orig, first)
        a.stop()
        cases.append(Case(f"sstore_dirty_{orig}_{first}_{second}", a,
                          storage={1: orig} if orig else {}))
    # refund cap: many clears, small execution -> refund capped at 1/5
    a = Asm()
    for slot in range(8):
        a.sstore(slot, 0, 7, 7)
    a.stop()
    cases.append(Case("sstore_refund_capped", a,
                      storage={s: 7 for s in range(8)}))
    # warm sload after sstore + repeat sload
    a = Asm()
    a.sload(3)
    a.sload(3)
    a.stop()
    cases.append(Case("sload_cold_then_warm", a, storage={3: 1}))

    # 5. account access warm/cold
    for name in ("BALANCE", "EXTCODESIZE", "EXTCODEHASH"):
        a = Asm()
        a.acct_op(name, int(AUX_ADDR, 16))
        a.acct_op(name, int(AUX_ADDR, 16))
        a.stop()
        cases.append(Case(f"acct_{name.lower()}_cold_warm", a,
                          aux_code="0x00"))
    a = Asm()
    ln = 40
    a._expand(ln)
    a.push(ln).push(0).push(0).push(int(AUX_ADDR, 16))
    a.gas += 2600 + 3 * words(ln)
    a.code.append(OP["EXTCODECOPY"])
    a.stop()
    cases.append(Case("acct_extcodecopy_cold", a, aux_code="0x00"))

    # 6. call family to STOP contracts
    aux = int(AUX_ADDR, 16)
    for name in ("CALL", "DELEGATECALL", "STATICCALL"):
        a = Asm()
        a.call_stop_contract(name, aux, 0, cold=True)
        a.call_stop_contract(name, aux, 0, cold=False)
        a.stop()
        cases.append(Case(f"call_{name.lower()}_cold_warm", a,
                          aux_code="0x00"))
    a = Asm()
    a.call_stop_contract("CALL", aux, 5, cold=True)
    a.stop()
    cases.append(Case("call_value_existing", a, aux_code="0x00",
                      aux_balance=1))
    a = Asm()
    a.call_stop_contract("CALL", 0xDEAD, 5, cold=True, new_account=True)
    a.stop()
    cases.append(Case("call_value_new_account", a))

    # 7. logs
    for topics in range(5):
        a = Asm()
        a.mstore(0, 0x1234)
        a.log(topics, 0, 32)
        a.stop()
        cases.append(Case(f"log{topics}_32b", a))
    a = Asm()
    a.log(0, 0, 0)
    a.stop()
    cases.append(Case("log0_empty", a))

    # 8. EXP exponent byte lengths
    for e in (0, 1, 0x100, 1 << 63, 1 << 255):
        a = Asm()
        a.exp(3, e)
        a.stop()
        cases.append(Case(f"exp_{e.bit_length()}bits", a))

    # 9. copies
    for ln in (0, 31, 32, 256, 4096):
        a = Asm()
        a.copy("CALLDATACOPY", 0, 0, ln)
        a.stop()
        cases.append(Case(f"calldatacopy_{ln}", a, data=b"\x01" * 64))
        a = Asm()
        a.copy("CODECOPY", 0, 0, ln)
        a.stop()
        cases.append(Case(f"codecopy_{ln}", a))
    a = Asm()
    a.mstore(0, 0x11)
    a.copy("MCOPY", 64, 0, 32)
    a.stop()
    cases.append(Case("mcopy_32", a))

    # 10. transient storage
    a = Asm()
    a.tstore(1, 7)
    a.tload(1)
    a.tload(9)
    a.stop()
    cases.append(Case("transient_store_load", a))

    # 11. precompile pricing (successful calls, spec formulas)
    precompiles = [
        ("ecrecover", 1, 128, 3000),
        ("sha256_0", 2, 0, 60),
        ("sha256_64", 2, 64, 60 + 12 * 2),
        ("ripemd_32", 3, 32, 600 + 120),
        ("identity_0", 4, 0, 15),
        ("identity_96", 4, 96, 15 + 3 * 3),
        ("ecadd_empty", 6, 0, 150),
        ("ecmul_empty", 7, 0, 6000),
        ("pairing_empty", 8, 0, 45000),
    ]
    for label, addr, in_len, cost in precompiles:
        a = Asm()
        a.call_precompile(addr, in_len, cost)
        a.stop()
        cases.append(Case(f"precompile_{label}", a))
    # modexp per EIP-2565: 32-byte base/exp/mod of small values
    a = Asm()
    a.mstore(0, 32)
    a.mstore(32, 32)
    a.mstore(64, 32)
    a.mstore(96, 3)
    a.mstore(128, 5)
    a.mstore(160, 257)
    # words(32)=1 -> mult=8? EIP-2565: f = ceil(32/8)^2 = 16;
    # iters = max(exp.bit_length()-1, 1) = 2; cost = max(200, 16*2/3) = 200
    a.call_precompile(5, 192, 200)
    a.stop()
    cases.append(Case("precompile_modexp_min", a))
    # blake2f: rounds field = 12 -> 12 gas
    a = Asm()
    a.mstore8(3, 12)           # rounds big-endian u32 at bytes 0..3
    a._expand(213)
    a.push(0).push(0).push(213).push(0)
    a.push(9).push(0xFFFFF)
    a.op("STATICCALL", 100 + 12)
    a.op("POP")
    a.stop()
    cases.append(Case("precompile_blake2f_12", a))

    # 12. intrinsic arithmetic
    a = Asm()
    a.stop()
    cases.append(Case("intrinsic_mixed_calldata", a,
                      data=bytes([0, 1, 0, 2, 0, 0, 3]) * 11))
    a = Asm()
    a.stop()
    cases.append(Case("intrinsic_floor_binding", a, data=b"\x00" * 2000,
                      gas_limit=200_000, forks=("Prague",)))
    a = Asm()
    a.stop()
    cases.append(Case("intrinsic_access_list", a,
                      access_list=[{"address": AUX_ADDR,
                                    "storageKeys": ["0x00", "0x01"]}],
                      extra_pre={AUX_ADDR: {"balance": "0x0",
                                            "nonce": "0x01",
                                            "code": "0x00",
                                            "storage": {}}}))

    # 13. creation: empty initcode / deposit cost via tx-create
    a = Asm()
    a.stop()  # initcode that stops: deploys empty code
    cases.append(Case("create_tx_empty", a, create=True))
    a = Asm()
    # initcode: MSTORE8(0, 0xFE); RETURN(0, 8) -> deposit 8 * 200
    a.mstore8(0, 0xFE)
    a.push(8).push(0)
    a.gas += 0
    a.code.append(OP["RETURN"])
    a.gas += 200 * 8
    cases.append(Case("create_tx_deposit8", a, create=True))
    # in-code CREATE with empty initcode (32000) and CREATE2 (+hash cost)
    a = Asm()
    a.push(0).push(0).push(0)
    a.op("CREATE", 32000)
    a.op("POP")
    a.stop()
    cases.append(Case("create_op_empty", a))
    a = Asm()
    a.push(0).push(0).push(0).push(0)
    a.op("CREATE2", 32000)
    a.op("POP")
    a.stop()
    cases.append(Case("create2_op_empty", a))

    # 14. blockhash / blobhash
    a = Asm()
    a.push(0)
    a.op("BLOCKHASH", 20)
    a.op("POP")
    a.stop()
    cases.append(Case("op_blockhash", a))
    a = Asm()
    a.push(0)
    a.op("BLOBHASH", 3)
    a.op("POP")
    a.stop()
    cases.append(Case("op_blobhash", a))
    return cases


def build():
    out = {}
    count = 0
    for case in build_cases():
        pre, tx = case.build()
        posts = {}
        for fork in case.forks:
            posts[fork] = [_run(case, pre, tx, fork)]
            count += 1
        out[case.name] = {"env": ENV, "pre": pre, "transaction": tx,
                          "post": posts}
    here = os.path.dirname(os.path.abspath(__file__))
    target = os.path.join(here, "matrix")
    os.makedirs(target, exist_ok=True)
    # shard into a handful of files by prefix
    shards: dict[str, dict] = {}
    for name, fixture in out.items():
        shard = name.split("_")[0]
        shards.setdefault(shard, {})[name] = fixture
    for shard, fixtures in shards.items():
        with open(os.path.join(target, f"{shard}_matrix.json"), "w") as f:
            json.dump(fixtures, f, indent=1, sort_keys=True)
    print(f"wrote {len(out)} fixtures / {count} fork cases "
          f"across {len(shards)} files")


if __name__ == "__main__":
    build()
