"""Fork-ladder GeneralStateTest fixtures with an INDEPENDENT gas oracle —
Frontier through Prague.

Companion to _generate_matrix.py (which sweeps the Cancun/Prague surface):
this module sweeps the FORK-DEPENDENT surface of the EVM across the whole
ladder — EIP-150 repricing, EIP-160 EXP, EIP-161 touch/new-account rules,
EIP-170, the four SSTORE regimes (legacy, EIP-1283, EIP-2200, EIP-2929/
3529), EIP-1884/2028 (Istanbul), pre-London refund rules (cap gas/2,
SELFDESTRUCT 24000), opcode availability per fork, and precompile pricing
eras (EIP-198 vs EIP-2565 modexp, pre/post-EIP-1108 bn254).

Every case's expected gas is derived from FIRST-PRINCIPLES cost tables
written straight from the EIPs/yellow paper — independent of
ethrex_tpu/evm/* — and cross-checked against the repo's executor at
generation time; a disagreement aborts generation.  Reference runner
equivalent: /root/reference/tooling/ef_tests/state_v2/src/runner.rs over
the pinned EF archives.

Run:  python tests/fixtures/ef_state/_generate_matrix_forks.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

from ethrex_tpu.crypto import secp256k1  # noqa: E402
from ethrex_tpu.utils import ef_state  # noqa: E402

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = "0x" + secp256k1.pubkey_to_address(
    secp256k1.pubkey_from_secret(SECRET)).hex()
COINBASE = "0x2adc25665018aa1fe0e6bc666dac8fc2697ff9ba"
CODE_ADDR = "0x" + "bb" * 20
AUX_ADDR = "0x" + "cc" * 20
DEAD_ADDR = "0x" + "dd" * 20   # never in pre-state

ENV = {
    "currentCoinbase": COINBASE,
    "currentGasLimit": "0x1c9c380",
    "currentNumber": "0x1",
    "currentTimestamp": "0x3e8",
    "currentBaseFee": "0xa",
    "currentRandom": "0x" + "00" * 32,
    "currentDifficulty": "0x20000",
}

ALL_FORKS = ("Frontier", "Homestead", "EIP150", "EIP158", "Byzantium",
             "Constantinople", "ConstantinopleFix", "Istanbul", "Berlin",
             "London", "Paris", "Shanghai", "Cancun", "Prague")

# fork ordinals for the oracle's own ladder comparisons
_ORD = {name: i for i, name in enumerate(ALL_FORKS)}


def at_least(fork, other):
    return _ORD[fork] >= _ORD[other]


class Sched:
    """The ORACLE's fork schedule — written from the EIPs, independent of
    the implementation under test."""

    def __init__(self, fork):
        self.fork = fork
        f = at_least
        self.eip150 = f(fork, "EIP150")
        self.eip158 = f(fork, "EIP158")
        self.istanbul = f(fork, "Istanbul")
        self.berlin = f(fork, "Berlin")
        self.london = f(fork, "London")
        # flat access costs (pre-Berlin)
        self.sload = 800 if self.istanbul else (200 if self.eip150 else 50)
        self.balance = 700 if self.istanbul else \
            (400 if self.eip150 else 20)
        self.extcode = 700 if self.eip150 else 20
        self.extcodehash = 700 if self.istanbul else 400
        self.call = 700 if self.eip150 else 40
        self.selfdestruct = 5000 if self.eip150 else 0
        self.exp_byte = 50 if self.eip158 else 10
        self.tx_nonzero = 16 if self.istanbul else 68
        self.tx_create = 32000 if f(fork, "Homestead") else 0
        self.refund_div = 5 if self.london else 2
        self.sd_refund = 0 if self.london else 24000
        if self.berlin:
            self.sstore = "berlin"
        elif self.istanbul:
            self.sstore = "net2200"
        elif fork == "Constantinople":
            self.sstore = "net1283"
        else:
            self.sstore = "legacy"
        self.net_sload = 800 if self.istanbul else 200

    def opcode_available(self, name):
        need = {
            "DELEGATECALL": "Homestead",
            "RETURNDATASIZE": "Byzantium", "RETURNDATACOPY": "Byzantium",
            "STATICCALL": "Byzantium", "REVERT": "Byzantium",
            "SHL": "Constantinople", "SHR": "Constantinople",
            "SAR": "Constantinople", "EXTCODEHASH": "Constantinople",
            "CREATE2": "Constantinople",
            "CHAINID": "Istanbul", "SELFBALANCE": "Istanbul",
            "BASEFEE": "London",
            "PUSH0": "Shanghai",
            "TLOAD": "Cancun", "TSTORE": "Cancun", "MCOPY": "Cancun",
            "BLOBHASH": "Cancun", "BLOBBASEFEE": "Cancun",
        }.get(name)
        return need is None or at_least(self.fork, need)


OP = {
    "STOP": 0x00, "ADD": 0x01, "MUL": 0x02, "SUB": 0x03, "EXP": 0x0A,
    "LT": 0x10, "EQ": 0x14, "ISZERO": 0x15, "AND": 0x16, "NOT": 0x19,
    "SHL": 0x1B, "SHR": 0x1C, "SAR": 0x1D, "KECCAK256": 0x20,
    "ADDRESS": 0x30, "BALANCE": 0x31, "ORIGIN": 0x32, "CALLER": 0x33,
    "CALLVALUE": 0x34, "CALLDATALOAD": 0x35, "CALLDATASIZE": 0x36,
    "CALLDATACOPY": 0x37, "CODESIZE": 0x38, "CODECOPY": 0x39,
    "GASPRICE": 0x3A, "EXTCODESIZE": 0x3B, "EXTCODECOPY": 0x3C,
    "RETURNDATASIZE": 0x3D, "RETURNDATACOPY": 0x3E, "EXTCODEHASH": 0x3F,
    "BLOCKHASH": 0x40, "COINBASE": 0x41, "TIMESTAMP": 0x42, "NUMBER": 0x43,
    "PREVRANDAO": 0x44, "GASLIMIT": 0x45, "CHAINID": 0x46,
    "SELFBALANCE": 0x47, "BASEFEE": 0x48, "POP": 0x50, "MLOAD": 0x51,
    "MSTORE": 0x52, "MSTORE8": 0x53, "SLOAD": 0x54, "SSTORE": 0x55,
    "JUMP": 0x56, "JUMPI": 0x57, "PC": 0x58, "MSIZE": 0x59, "GAS": 0x5A,
    "JUMPDEST": 0x5B, "TLOAD": 0x5C, "TSTORE": 0x5D, "MCOPY": 0x5E,
    "PUSH0": 0x5F, "LOG0": 0xA0, "LOG1": 0xA1, "LOG2": 0xA2,
    "CREATE": 0xF0, "CALL": 0xF1, "CALLCODE": 0xF2, "RETURN": 0xF3,
    "DELEGATECALL": 0xF4, "CREATE2": 0xF5, "STATICCALL": 0xFA,
    "REVERT": 0xFD, "SELFDESTRUCT": 0xFF,
}


def words(n):
    return (n + 31) // 32


def mem_cost(byte_size):
    w = words(byte_size)
    return 3 * w + w * w // 512


class Asm:
    """Bytecode emitter + fork-parameterized independent gas meter."""

    def __init__(self, sched: Sched):
        self.s = sched
        self.code = bytearray()
        self.gas = 0
        self.mem = 0
        self.refund = 0
        self.warm_slots = set()
        # EIP-2929: sender and tx.to are warm from tx start (EIP-3651
        # adds the coinbase at Shanghai)
        self.warm_addrs = {int(SENDER[2:], 16), int(CODE_ADDR[2:], 16)}
        if at_least(sched.fork, "Shanghai"):
            self.warm_addrs.add(int(COINBASE[2:], 16))
        self.died = False     # SELFDESTRUCT executed (halts; refund below)

    def push(self, v: int):
        b = v.to_bytes(max((v.bit_length() + 7) // 8, 1), "big")
        self.code.append(0x5F + len(b))
        self.code += b
        self.gas += 3
        return self

    def op(self, name, cost):
        self.code.append(OP[name])
        self.gas += cost
        return self

    def _expand(self, end):
        if end > self.mem:
            self.gas += mem_cost(end) - mem_cost(self.mem)
            self.mem = words(end) * 32

    def mstore(self, off, v=1):
        self.push(v).push(off)
        self._expand(off + 32)
        return self.op("MSTORE", 3)

    def mstore8(self, off, v):
        self.push(v).push(off)
        self._expand(off + 1)
        return self.op("MSTORE8", 3)

    def exp(self, base, exponent):
        self.push(exponent).push(base)
        blen = (exponent.bit_length() + 7) // 8 if exponent else 0
        self.op("EXP", 10 + self.s.exp_byte * blen)
        return self.op("POP", 2)

    def sload(self, slot):
        self.push(slot)
        if self.s.berlin:
            cold = slot not in self.warm_slots
            self.warm_slots.add(slot)
            self.op("SLOAD", 2100 if cold else 100)
        else:
            self.op("SLOAD", self.s.sload)
        return self.op("POP", 2)

    def balance_of(self, addr):
        self.push(addr)
        if self.s.berlin:
            cold = addr not in self.warm_addrs
            self.warm_addrs.add(addr)
            self.op("BALANCE", 2600 if cold else 100)
        else:
            self.op("BALANCE", self.s.balance)
        return self.op("POP", 2)

    def extcodesize_of(self, addr):
        self.push(addr)
        if self.s.berlin:
            cold = addr not in self.warm_addrs
            self.warm_addrs.add(addr)
            self.op("EXTCODESIZE", 2600 if cold else 100)
        else:
            self.op("EXTCODESIZE", self.s.extcode)
        return self.op("POP", 2)

    def extcodehash_of(self, addr):
        self.push(addr)
        if self.s.berlin:
            cold = addr not in self.warm_addrs
            self.warm_addrs.add(addr)
            self.op("EXTCODEHASH", 2600 if cold else 100)
        else:
            self.op("EXTCODEHASH", self.s.extcodehash)
        return self.op("POP", 2)

    def sstore(self, slot, new, original, current):
        """All four SSTORE regimes, from the spec tables."""
        self.push(new).push(slot)
        s = self.s
        if s.sstore == "legacy":
            if current == 0 and new != 0:
                cost = 20000
            else:
                cost = 5000
                if current != 0 and new == 0:
                    self.refund += 15000
            return self.op("SSTORE", cost)
        if s.sstore in ("net1283", "net2200"):
            noop = s.net_sload
            if new == current:
                cost = noop
            elif current == original:
                if original == 0:
                    cost = 20000
                else:
                    cost = 5000
                    if new == 0:
                        self.refund += 15000
            else:
                cost = noop
                if original != 0:
                    if current == 0:
                        self.refund -= 15000
                    elif new == 0:
                        self.refund += 15000
                if new == original:
                    self.refund += (20000 - noop) if original == 0 \
                        else (5000 - noop)
            return self.op("SSTORE", cost)
        # berlin — clear refund is still 15000 (EIP-2200) on Berlin
        # itself; EIP-3529 lowers it to 4800 only at London
        clear_ref = 4800 if self.s.london else 15000
        cost = 0
        if slot not in self.warm_slots:
            cost += 2100
            self.warm_slots.add(slot)
        if new == current:
            cost += 100
        elif current == original:
            cost += 20000 if original == 0 else 2900
            if original != 0 and new == 0:
                self.refund += clear_ref
        else:
            cost += 100
            if original != 0:
                if current == 0:
                    self.refund -= clear_ref
                elif new == 0:
                    self.refund += clear_ref
            if new == original:
                self.refund += (20000 - 100) if original == 0 \
                    else (5000 - 2100 - 100)
        return self.op("SSTORE", cost)

    def call_stop(self, kind, addr, value=0, target_exists=True,
                  target_empty=False):
        """CALL-family to an empty-code target: net cost = the surcharge
        (the forwarded gas comes back untouched)."""
        s = self.s
        if kind in ("CALL", "CALLCODE"):
            self.push(0).push(0).push(0).push(0)
            self.push(value).push(addr).push(0)
        else:
            self.push(0).push(0).push(0).push(0)
            self.push(addr).push(0)
        if s.berlin:
            cold = addr not in self.warm_addrs
            self.warm_addrs.add(addr)
            cost = 2600 if cold else 100
        else:
            cost = s.call
        if value:
            cost += 9000 - 2300   # stipend returns from the STOP callee
        if kind == "CALL":
            if s.eip158:
                if value and (not target_exists or target_empty):
                    cost += 25000
            elif not target_exists:
                cost += 25000     # pre-EIP-161: charged on nonexistence
        self.op(kind, cost)
        return self.op("POP", 2)

    def selfdestruct(self, target, target_exists=True, target_empty=False,
                     has_balance=True):
        s = self.s
        self.push(target)
        cost = s.selfdestruct
        if s.berlin:
            cold = target not in self.warm_addrs
            self.warm_addrs.add(target)
            cost += 0 if not cold else 2600
            if has_balance and (not target_exists or target_empty):
                cost += 25000
        elif s.eip158:
            if has_balance and (not target_exists or target_empty):
                cost += 25000
        elif s.eip150:
            if not target_exists:
                cost += 25000
        self.op("SELFDESTRUCT", cost)
        self.refund += s.sd_refund
        self.died = True
        return self

    def stop(self):
        self.code.append(OP["STOP"])
        return self

    @property
    def hexcode(self):
        return "0x" + bytes(self.code).hex()


def intrinsic(sched: Sched, data: bytes, create=False):
    z = data.count(0)
    nz = len(data) - z
    g = 21000 + 4 * z + sched.tx_nonzero * nz
    if create:
        g += sched.tx_create
        if at_least(sched.fork, "Shanghai"):
            g += 2 * words(len(data))
    return g


def floor_gas(data: bytes):
    tokens = data.count(0) + 4 * (len(data) - data.count(0))
    return 21000 + 10 * tokens


class Case:
    """One scenario: a per-fork Asm builder + fixture assembly."""

    def __init__(self, name, build_asm, *, forks=ALL_FORKS, data=b"",
                 storage=None, value=0, gas_limit=1_000_000,
                 extra_pre=None, target_balance=0, full_gas=False,
                 expected_gas=None):
        self.name = name
        self.build_asm = build_asm   # fn(sched) -> Asm or None (skip fork)
        self.forks = forks
        self.data = data
        self.storage = storage or {}
        self.value = value
        self.gas_limit = gas_limit
        self.extra_pre = extra_pre or {}
        self.target_balance = target_balance
        self.full_gas = full_gas     # exceptional halt: consumes it all
        self._expected = expected_gas

    def expected_gas(self, sched, asm):
        if self.full_gas:
            return self.gas_limit
        if self._expected is not None:
            return self._expected(sched)
        g = intrinsic(sched, self.data) + asm.gas
        refund = max(asm.refund, 0)
        g -= min(refund, g // sched.refund_div)
        if sched.fork == "Prague":
            g = max(g, floor_gas(self.data))
        return g

    def fixtures(self):
        """One fixture dict per DISTINCT generated bytecode: the EF format
        shares a single pre/tx across forks, so fork-varying code must
        split into separate files."""
        groups: dict = {}
        for fork in self.forks:
            sched = Sched(fork)
            asm = self.build_asm(sched)
            if asm is None:
                continue
            pre = {
                SENDER: {"balance": "0x56bc75e2d63100000", "nonce": "0x00",
                         "code": "0x", "storage": {}},
                CODE_ADDR: {"balance": hex(self.target_balance),
                            "nonce": "0x01", "code": asm.hexcode,
                            "storage": {hex(k): hex(v) for k, v
                                        in self.storage.items()}},
            }
            for addr, acct in self.extra_pre.items():
                pre[addr] = acct
            tx = {
                "data": ["0x" + self.data.hex()],
                "gasLimit": [hex(self.gas_limit)],
                "value": [hex(self.value)],
                "gasPrice": "0x14", "nonce": "0x00",
                "to": CODE_ADDR,
                "secretKey": hex(SECRET), "sender": SENDER,
            }
            tc = ef_state.StateTestCase(
                name=self.name, fork=fork,
                tx=ef_state._build_tx(tx, {"data": 0, "gas": 0,
                                           "value": 0}),
                pre=ef_state._parse_pre(pre), env=ENV,
                expected_hash=b"\x00" * 32, expected_logs=b"\x00" * 32,
                expect_exception=None, indexes=(0, 0, 0))
            root, logs, err, gas = ef_state.execute_case(tc)
            assert err is None, f"{self.name}/{fork}: tx invalid: {err}"
            want = self.expected_gas(sched, asm)
            assert gas == want, (
                f"{self.name}/{fork}: analytic gas {want} != executor "
                f"{gas} (delta {gas - want})")
            key = asm.hexcode
            grp = groups.setdefault(key, {"pre": pre, "tx": tx,
                                          "post": {}})
            grp["post"].setdefault(fork, []).append({
                "hash": "0x" + root.hex(), "logs": "0x" + logs.hex(),
                "indexes": {"data": 0, "gas": 0, "value": 0},
                "txbytes": "0x", })
        out = []
        for i, grp in enumerate(groups.values()):
            name = self.name if len(groups) == 1 else f"{self.name}_g{i}"
            out.append((name, {name: {
                "env": ENV, "pre": grp["pre"],
                "transaction": grp["tx"], "post": grp["post"],
            }}))
        return out


# ---------------------------------------------------------------------------
# Scenarios
# ---------------------------------------------------------------------------

def build_cases():
    cases = []

    # -- flat-vs-2929 access costs across every fork -----------------------
    def sweep(name, fn, **kw):
        cases.append(Case(name, fn, **kw))

    for reps in (1, 2, 3):
        def mk_sload(s, reps=reps):
            a = Asm(s)
            for _ in range(reps):
                a.sload(1)
            return a.stop()
        sweep(f"ladder_sload_x{reps}", mk_sload, storage={1: 7})

    # balance/extcodesize on self + on a dead address (warm/cold split)
    def mk_balance(s):
        a = Asm(s)
        a.balance_of(int(CODE_ADDR[2:], 16))
        a.balance_of(int(CODE_ADDR[2:], 16))   # warm the 2nd time (2929)
        a.balance_of(int(DEAD_ADDR[2:], 16))
        return a.stop()
    sweep("ladder_balance_warm_cold", mk_balance)

    def mk_extcodesize(s):
        a = Asm(s)
        a.extcodesize_of(int(CODE_ADDR[2:], 16))
        a.extcodesize_of(int(DEAD_ADDR[2:], 16))
        return a.stop()
    sweep("ladder_extcodesize", mk_extcodesize)

    def mk_extcodehash(s):
        if not s.opcode_available("EXTCODEHASH"):
            return None
        a = Asm(s)
        a.extcodehash_of(int(CODE_ADDR[2:], 16))
        return a.stop()
    sweep("ladder_extcodehash", mk_extcodehash)

    # -- EXP byte pricing (EIP-160) ----------------------------------------
    for ex in (0, 1, 0xFF, 0x100, 0x10000, (1 << 64) - 1, 1 << 128,
               (1 << 200) + 3, (1 << 248) + 5, (1 << 256) - 1):
        def mk_exp(s, ex=ex):
            a = Asm(s)
            return a.exp(3, ex).stop()
        sweep(f"ladder_exp_{ex:#x}", mk_exp)

    # -- SSTORE regimes ----------------------------------------------------
    # the full write-sequence state machine: every (original, sequence)
    # with sequences of length 1..3 over {0, original, other} exercises
    # each regime's clean/dirty/no-op/refund/un-refund paths exhaustively
    transitions = []
    for original in (0, 5):
        vals = sorted({0, original, 6})
        seqs = [(a,) for a in vals]
        seqs += [(a, b) for a in vals for b in vals]
        seqs += [(a, b, c) for a in vals for b in vals for c in vals
                 if (a, b) != (original, original)]  # trim redundant heads
        # length-4 chains drive the dirty-slot refund bookkeeping through
        # every add/remove/re-add path of each regime
        seqs += [(a, b, c, d) for a in vals for b in vals for c in vals
                 for d in vals if a != original or b != original]
        transitions += [(original, seq) for seq in seqs]
    for original, seq in transitions:
        sname = "_".join(str(v) for v in seq)

        def mk_sstore(s, original=original, seq=seq):
            a = Asm(s)
            cur = original
            for v in seq:
                a.sstore(2, v, original, cur)
                cur = v
            return a.stop()
        sweep(f"ladder_sstore_o{original}_{sname}", mk_sstore,
              storage={2: original} if original else {},
              gas_limit=400_000)

    # -- refund cap /2 vs /5 ----------------------------------------------
    def mk_refund_cap(s):
        a = Asm(s)
        # clear 4 slots: big refund against a modest execution cost
        for slot in (10, 11, 12, 13):
            a.sstore(slot, 0, 5, 5)
        return a.stop()
    sweep("ladder_refund_cap", mk_refund_cap,
          storage={10: 5, 11: 5, 12: 5, 13: 5}, gas_limit=300_000)

    # -- CALL family -------------------------------------------------------
    aux_stop = {AUX_ADDR: {"balance": "0x0", "nonce": "0x01",
                           "code": "0x00", "storage": {}}}
    for value in (0, 1):
        def mk_call(s, value=value):
            a = Asm(s)
            a.call_stop("CALL", int(AUX_ADDR[2:], 16), value=value)
            return a.stop()
        sweep(f"ladder_call_exist_v{value}", mk_call, extra_pre=aux_stop,
              target_balance=10)

        def mk_call_dead(s, value=value):
            a = Asm(s)
            a.call_stop("CALL", int(DEAD_ADDR[2:], 16), value=value,
                        target_exists=False)
            return a.stop()
        sweep(f"ladder_call_dead_v{value}", mk_call_dead,
              target_balance=10)

    def mk_callcode(s):
        a = Asm(s)
        a.call_stop("CALLCODE", int(AUX_ADDR[2:], 16))
        return a.stop()
    sweep("ladder_callcode", mk_callcode, extra_pre=aux_stop)

    def mk_callcode_broke(s):
        # CALLCODE with value exceeding the contract's balance: no
        # transfer happens, but the spec's balance check must fail the
        # call (pushes 0; forwarded gas + stipend return)
        a = Asm(s)
        a.call_stop("CALLCODE", int(AUX_ADDR[2:], 16), value=1)
        return a.stop()
    sweep("ladder_callcode_value_too_high", mk_callcode_broke,
          extra_pre=aux_stop, target_balance=0)

    def mk_delegate(s):
        if not s.opcode_available("DELEGATECALL"):
            return None
        a = Asm(s)
        a.call_stop("DELEGATECALL", int(AUX_ADDR[2:], 16))
        return a.stop()
    sweep("ladder_delegatecall", mk_delegate, extra_pre=aux_stop)

    def mk_static(s):
        if not s.opcode_available("STATICCALL"):
            return None
        a = Asm(s)
        a.call_stop("STATICCALL", int(AUX_ADDR[2:], 16))
        return a.stop()
    sweep("ladder_staticcall", mk_static, extra_pre=aux_stop)

    # pre-EIP-150 "forward everything": a huge gas argument OOGs before
    # Tangerine and is quietly capped after
    def mk_allgas(s):
        a = Asm(s)
        if s.eip150:
            a.call_stop("CALL", int(AUX_ADDR[2:], 16))
            # the 63/64 cap costs nothing extra: forwarded gas returns
            return a.stop()
        # pre-150: CALL with gas_req > remaining is an exceptional halt
        a.push(0).push(0).push(0).push(0)
        a.push(0).push(int(AUX_ADDR[2:], 16)).push(0xFFFFFF)
        a.op("CALL", 0)
        return a.stop()

    def mk_allgas_case(s):
        a = mk_allgas(s)
        return a
    cases.append(Case("ladder_call_allgas", mk_allgas_case,
                      extra_pre=aux_stop, gas_limit=100_000,
                      expected_gas=lambda s: None))
    # expected gas differs in kind: full consumption pre-150; patch below
    cases[-1].expected_gas = (
        lambda sched, asm, _c=cases[-1]:
        _c.gas_limit if not sched.eip150
        else intrinsic(sched, b"") + asm.gas)

    # -- EIP-161 touch: zero-value call creates an account pre-158 ---------
    # (covered by ladder_call_dead_v0's gas; the post-state hash pins the
    # created-empty-account difference across the ladder)

    # -- SELFDESTRUCT ------------------------------------------------------
    def mk_sd_exist(s):
        a = Asm(s)
        return a.selfdestruct(int(AUX_ADDR[2:], 16))
    sweep("ladder_selfdestruct_exist", mk_sd_exist, extra_pre=aux_stop,
          target_balance=7, gas_limit=100_000)

    def mk_sd_dead(s):
        a = Asm(s)
        return a.selfdestruct(int(DEAD_ADDR[2:], 16), target_exists=False)
    sweep("ladder_selfdestruct_dead", mk_sd_dead, target_balance=7,
          gas_limit=100_000)

    def mk_sd_nobal(s):
        a = Asm(s)
        return a.selfdestruct(int(DEAD_ADDR[2:], 16), target_exists=False,
                              has_balance=False)
    sweep("ladder_selfdestruct_dead_nobalance", mk_sd_nobal,
          target_balance=0, gas_limit=100_000)

    # -- opcode availability: an absent opcode consumes everything ---------
    for name in ("DELEGATECALL", "RETURNDATASIZE", "STATICCALL", "REVERT",
                 "SHL", "EXTCODEHASH", "CREATE2", "CHAINID", "SELFBALANCE",
                 "BASEFEE", "PUSH0", "TLOAD", "MCOPY"):
        def mk_missing(s, name=name):
            if s.opcode_available(name):
                return None
            a = Asm(s)
            # plenty of stack arguments so only decoding matters
            for _ in range(7):
                a.push(0)
            a.code.append(OP[name])
            return a
        cases.append(Case(f"ladder_missing_{name.lower()}", mk_missing,
                          gas_limit=60_000, full_gas=True))

    # -- per-address-class flat/warm access sweeps -------------------------
    addr_classes = {
        "self": int(CODE_ADDR[2:], 16), "sender": int(SENDER[2:], 16),
        "coinbase": int(COINBASE[2:], 16), "dead": int(DEAD_ADDR[2:], 16),
        "aux": int(AUX_ADDR[2:], 16),
    }
    for cname, caddr in addr_classes.items():
        def mk_bal_cls(s, caddr=caddr):
            a = Asm(s)
            a.balance_of(caddr)
            a.balance_of(caddr)     # second touch: warm at Berlin+
            return a.stop()
        sweep(f"ladder_balance_{cname}", mk_bal_cls, extra_pre=aux_stop)

        def mk_ecs_cls(s, caddr=caddr):
            a = Asm(s)
            a.extcodesize_of(caddr)
            return a.stop()
        sweep(f"ladder_extcodesize_{cname}", mk_ecs_cls,
              extra_pre=aux_stop)

    # -- calldata pricing (EIP-2028) ---------------------------------------
    for data in (b"\x00" * 32, b"\x01" * 32, bytes(range(48)),
                 b"\x00\x01" * 40, b"\xff" * 100, b"\x00" * 256,
                 bytes(range(256)), b"\x07"):
        def mk_data(s, data=data):
            a = Asm(s)
            return a.stop()
        sweep(f"ladder_txdata_{data[:2].hex()}_{len(data)}", mk_data,
              data=data)

    # -- precompile pricing eras -------------------------------------------
    def _call_precompile(a, addr, in_len, cost):
        # a modest forwarded-gas argument: pre-EIP-150 there is no 63/64
        # cap, so a huge request would be an exceptional halt.  CALL pops
        # (gas, to, value, inOff, inLen, outOff, outLen).
        a._expand(max(in_len, 32))
        a.push(0).push(0).push(in_len).push(0).push(0)
        a.push(addr).push(50000)
        s = a.s
        if s.berlin:
            base = 100   # precompiles are warm from tx start
        else:
            base = s.call
        a.op("CALL", base + cost)
        return a.op("POP", 2)

    def _precompile_pre(addr):
        # precompile accounts carry 1 wei in pre-state (the EF fixture
        # convention) so no fork charges the new-account surcharge
        return {"0x" + addr.to_bytes(20, "big").hex(): {
            "balance": "0x1", "nonce": "0x00", "code": "0x",
            "storage": {}}}

    def mk_sha(s):
        a = Asm(s)
        a.mstore(0, 7)
        _call_precompile(a, 2, 32, 60 + 12)
        return a.stop()
    sweep("ladder_precompile_sha256", mk_sha, gas_limit=100_000,
          extra_pre=_precompile_pre(2))

    def mk_ecadd(s):
        if not at_least(s.fork, "Byzantium"):
            return None
        a = Asm(s)
        # 0 + 0 = identity: valid 128-byte zero input
        cost = 150 if s.istanbul else 500
        _call_precompile(a, 6, 128, cost)
        return a.stop()
    sweep("ladder_precompile_ecadd", mk_ecadd, gas_limit=200_000,
          extra_pre=_precompile_pre(6))

    def mk_modexp(s):
        if not at_least(s.fork, "Byzantium"):
            return None
        a = Asm(s)
        # bsize=1, esize=1, msize=1, base=3, exp=5, mod=7
        a.mstore(0, 1).mstore(32, 1).mstore(64, 1)
        a.mstore8(96, 3).mstore8(97, 5).mstore8(98, 7)
        if s.berlin:
            cost = 200                      # EIP-2565 floor
        else:
            # EIP-198: mult_complexity(1)=1, iters=max(bitlen(5)-1,1)=2
            cost = 1 * 2 // 20              # = 0
        _call_precompile(a, 5, 99, cost)
        return a.stop()
    sweep("ladder_precompile_modexp_small", mk_modexp,
          gas_limit=200_000, extra_pre=_precompile_pre(5))

    def mk_blake(s):
        a = Asm(s)
        if not at_least(s.fork, "Istanbul"):
            # address 9 holds 1 wei in pre-state (the EF convention) and
            # is not yet a precompile: a plain call to an existing account
            a.call_stop("CALL", 9)
            return a.stop()
        a.mstore8(3, 1)         # rounds = 1 (big-endian u32 at offset 0)
        a.mstore8(212, 1)       # final flag
        _call_precompile(a, 9, 213, 1)
        return a.stop()
    sweep("ladder_precompile_blake2f", mk_blake, gas_limit=200_000,
          extra_pre=_precompile_pre(9))

    # -- volume sweeps: fork-invariant costs, per-fork post-state pins -----
    # (stack/arithmetic/memory surface; each case still cross-checks the
    # executor against the analytic meter on EVERY fork of the ladder)
    TWOARG = {"ADD": 3, "MUL": 5, "SUB": 3, "LT": 3, "EQ": 3, "AND": 3}
    for name, cost in TWOARG.items():
        for a1, a2 in ((0, 0), (1, 2), ((1 << 255) + 1, 7)):
            def mk_arith(s, name=name, cost=cost, a1=a1, a2=a2):
                a = Asm(s)
                a.push(a2).push(a1)
                a.op(name, cost)
                return a.op("POP", 2).stop()
            sweep(f"ladder_op_{name.lower()}_{a1 & 0xffff}_{a2}", mk_arith)

    for name, cost in (("ISZERO", 3), ("NOT", 3)):
        for v in (0, 1, 1 << 200):
            def mk_unary(s, name=name, cost=cost, v=v):
                a = Asm(s)
                a.push(v)
                a.op(name, cost)
                return a.op("POP", 2).stop()
            sweep(f"ladder_op_{name.lower()}_{v & 0xffff}", mk_unary)

    for name in ("ADDRESS", "ORIGIN", "CALLER", "CALLVALUE",
                 "CALLDATASIZE", "CODESIZE", "GASPRICE", "COINBASE",
                 "TIMESTAMP", "NUMBER", "GASLIMIT", "PC", "MSIZE", "GAS"):
        def mk_env(s, name=name):
            a = Asm(s)
            a.op(name, 2)
            return a.op("POP", 2).stop()
        sweep(f"ladder_op_{name.lower()}", mk_env)

    for width in range(1, 33):
        def mk_push(s, width=width):
            a = Asm(s)
            v = (1 << (8 * width)) - 1
            a.push(v)
            return a.op("POP", 2).stop()
        sweep(f"ladder_push{width}", mk_push)

    for k in (1, 2, 4, 8, 12, 16):
        def mk_dup(s, k=k):
            a = Asm(s)
            for i in range(k):
                a.push(i + 1)
            a.code.append(0x80 + k - 1)
            a.gas += 3
            return a.op("POP", 2).stop()
        sweep(f"ladder_dup{k}", mk_dup)

        def mk_swap(s, k=k):
            a = Asm(s)
            for i in range(k + 1):
                a.push(i + 1)
            a.code.append(0x90 + k - 1)
            a.gas += 3
            return a.op("POP", 2).stop()
        sweep(f"ladder_swap{k}", mk_swap)

    for size in (0, 32, 64, 256, 1024, 4096):
        def mk_keccak(s, size=size):
            a = Asm(s)
            a.push(size).push(0)
            if size:
                a._expand(size)
            a.op("KECCAK256", 30 + 6 * words(size))
            return a.op("POP", 2).stop()
        sweep(f"ladder_keccak_{size}", mk_keccak, gas_limit=200_000)

    for size in (32, 96, 512, 2048):
        def mk_mexp(s, size=size):
            a = Asm(s)
            return a.mstore(size - 32, 7).stop()
        sweep(f"ladder_memexpand_{size}", mk_mexp, gas_limit=200_000)

    for ln in (0, 1, 31, 32, 33, 256):
        def mk_cdc(s, ln=ln):
            a = Asm(s)
            if ln:
                a._expand(ln)
            a.push(ln).push(0).push(0)
            a.op("CALLDATACOPY", 3 + 3 * words(ln))
            return a.stop()
        sweep(f"ladder_calldatacopy_{ln}", mk_cdc,
              data=bytes(range(48)) * 2, gas_limit=200_000)

        def mk_cc(s, ln=ln):
            a = Asm(s)
            if ln:
                a._expand(ln)
            a.push(ln).push(0).push(0)
            a.op("CODECOPY", 3 + 3 * words(ln))
            return a.stop()
        sweep(f"ladder_codecopy_{ln}", mk_cc, gas_limit=200_000)

    for topics in (0, 1, 2):
        for ln in (0, 7, 64):
            def mk_log(s, topics=topics, ln=ln):
                a = Asm(s)
                for t in range(topics):
                    a.push(t + 1)
                a.push(ln).push(0)
                if ln:
                    a._expand(ln)
                a.op(f"LOG{topics}", 375 + 375 * topics + 8 * ln)
                return a.stop()
            sweep(f"ladder_log{topics}_{ln}", mk_log, gas_limit=200_000)

    for name, since in (("SHL", "Constantinople"), ("SHR", "Constantinople"),
                        ("SAR", "Constantinople")):
        for sh in (0, 1, 255, 256):
            def mk_shift(s, name=name, since=since, sh=sh):
                if not at_least(s.fork, since):
                    return None
                a = Asm(s)
                a.push(7).push(sh)
                a.op(name, 3)
                return a.op("POP", 2).stop()
            sweep(f"ladder_{name.lower()}_{sh}", mk_shift)

    # -- CALL with input/output memory regions -----------------------------
    for in_len in (0, 32, 64):
        for out_len in (0, 32):
            def mk_call_mem(s, in_len=in_len, out_len=out_len):
                a = Asm(s)
                a._expand(max(in_len, out_len))
                a.push(out_len).push(0).push(in_len).push(0).push(0)
                a.push(int(AUX_ADDR[2:], 16)).push(20000)
                if s.berlin:
                    cold = int(AUX_ADDR[2:], 16) not in a.warm_addrs
                    a.warm_addrs.add(int(AUX_ADDR[2:], 16))
                    a.op("CALL", 2600 if cold else 100)
                else:
                    a.op("CALL", s.call)
                return a.op("POP", 2).stop()
            sweep(f"ladder_call_mem_{in_len}_{out_len}", mk_call_mem,
                  extra_pre=aux_stop, gas_limit=100_000)

    # -- CREATE / CREATE2 --------------------------------------------------
    for value in (0, 3):
        def mk_create(s, value=value):
            a = Asm(s)
            # empty initcode -> empty contract; child consumes nothing
            a.push(0).push(0).push(value)
            a.op("CREATE", 32000)
            return a.op("POP", 2).stop()
        sweep(f"ladder_create_empty_v{value}", mk_create,
              target_balance=10, gas_limit=200_000)

    def mk_create_deposit(s):
        a = Asm(s)
        # initcode = [PUSH2 0x6000, PUSH1 0, MSTORE, PUSH1 2, PUSH1 30,
        #             RETURN] -> deposits the 2-byte runtime 0x6000
        init = bytes([0x61, 0x60, 0x00, 0x60, 0x00, 0x52,
                      0x60, 0x02, 0x60, 0x1e, 0xf3])
        # place initcode into memory with MSTOREs (one word)
        word = int.from_bytes(init.ljust(32, b"\x00"), "big")
        a.mstore(0, word)
        a.push(len(init)).push(0).push(0)
        # child: 2 pushes (3+3) + MSTORE 3 + mem 3 + RETURN mem already
        # counted... child costs: PUSH2(3)+PUSH1(3)+MSTORE(3+mem3)+
        # PUSH1(3)+PUSH1(3)+RETURN(0) = 18; deposit 2*200 = 400
        a.op("CREATE", 32000 + 18 + 400)
        if at_least(s.fork, "Shanghai"):
            a.gas += 2 * words(len(init))   # EIP-3860 initcode cost
        return a.op("POP", 2).stop()
    sweep("ladder_create_deposit", mk_create_deposit, target_balance=10,
          gas_limit=300_000)

    def mk_create2(s):
        if not s.opcode_available("CREATE2"):
            return None
        a = Asm(s)
        a.push(7).push(0).push(0).push(0)   # salt, len, off, value
        a.op("CREATE2", 32000)              # empty initcode: no hash words
        return a.op("POP", 2).stop()
    sweep("ladder_create2_empty", mk_create2, target_balance=10,
          gas_limit=200_000)

    # -- RETURN / REVERT with data -----------------------------------------
    for ln in (0, 32, 96):
        def mk_return(s, ln=ln):
            a = Asm(s)
            if ln:
                a._expand(ln)
            a.push(ln).push(0)
            a.op("RETURN", 0)
            return a
        sweep(f"ladder_return_{ln}", mk_return, gas_limit=100_000)

        def mk_revert(s, ln=ln):
            if not s.opcode_available("REVERT"):
                return None
            a = Asm(s)
            if ln:
                a._expand(ln)
            a.push(ln).push(0)
            a.op("REVERT", 0)
            return a
        # a reverted outer frame consumes only up to the REVERT point and
        # undoes state; gas accounting still matches the meter
        sweep(f"ladder_revert_{ln}", mk_revert, gas_limit=100_000)

    # -- plain value transfers (empty target code) -------------------------
    for value in (0, 1, 10**15):
        def mk_xfer(s, value=value):
            a = Asm(s)
            return a.stop()
        sweep(f"ladder_transfer_{value}", mk_xfer, value=value)

    # -- two-slot SSTORE interleaves ---------------------------------------
    for o1, o2 in ((0, 5), (5, 0), (5, 5)):
        def mk_two_slots(s, o1=o1, o2=o2):
            a = Asm(s)
            a.sstore(21, 9, o1, o1)
            a.sstore(22, 0, o2, o2)
            a.sstore(21, o1, o1, 9)
            return a.stop()
        st = {}
        if o1:
            st[21] = o1
        if o2:
            st[22] = o2
        sweep(f"ladder_sstore2_{o1}_{o2}", mk_two_slots, storage=st,
              gas_limit=400_000)

    # -- BLOCKHASH / PREVRANDAO-vs-DIFFICULTY ------------------------------
    def mk_blockhash(s):
        a = Asm(s)
        a.push(0)
        a.op("BLOCKHASH", 20)
        return a.op("POP", 2).stop()
    sweep("ladder_blockhash", mk_blockhash)

    def mk_prevrandao(s):
        a = Asm(s)
        a.op("PREVRANDAO", 2)   # DIFFICULTY pre-Paris, same cost
        return a.op("POP", 2).stop()
    sweep("ladder_prevrandao_difficulty", mk_prevrandao)

    # -- CALLDATALOAD offsets ----------------------------------------------
    for off in (0, 16, 31, 64):
        def mk_cdl(s, off=off):
            a = Asm(s)
            a.push(off)
            a.op("CALLDATALOAD", 3)
            return a.op("POP", 2).stop()
        sweep(f"ladder_calldataload_{off}", mk_cdl, data=bytes(range(40)))

    # -- MLOAD / MSTORE8 offsets -------------------------------------------
    for off in (0, 1, 31, 96):
        def mk_m8(s, off=off):
            a = Asm(s)
            return a.mstore8(off, 0xAB).stop()
        sweep(f"ladder_mstore8_{off}", mk_m8)

        def mk_ml(s, off=off):
            a = Asm(s)
            a.push(off)
            a._expand(off + 32)
            a.op("MLOAD", 3)
            return a.op("POP", 2).stop()
        sweep(f"ladder_mload_{off}", mk_ml)

    # jumps
    def mk_jump(s):
        a = Asm(s)
        # JUMP over one byte: [PUSH1 dest][JUMP][INVALID][JUMPDEST]...
        dest = 4
        a.push(dest)
        a.op("JUMP", 8)
        a.code.append(0xFE)
        a.code.append(OP["JUMPDEST"])
        a.gas += 1
        return a.stop()
    sweep("ladder_jump", mk_jump)

    def mk_jumpi(s):
        a = Asm(s)
        dest = 6
        a.push(1).push(dest)
        a.op("JUMPI", 10)
        a.code.append(0xFE)
        a.code.append(OP["JUMPDEST"])
        a.gas += 1
        return a.stop()
    sweep("ladder_jumpi_taken", mk_jumpi)

    return cases


def main():
    outdir = os.path.join(os.path.dirname(__file__), "forks")
    os.makedirs(outdir, exist_ok=True)
    total_files = 0
    total_cases = 0
    for case in build_cases():
        for name, fixture in case.fixtures():
            path = os.path.join(outdir, f"{name}.json")
            with open(path, "w") as f:
                json.dump(fixture, f, indent=1, sort_keys=True)
            nposts = sum(len(v) for v in fixture[name]["post"].values())
            total_files += 1
            total_cases += nposts
    print(f"wrote {total_files} fixtures / {total_cases} fork cases "
          f"to {outdir}")


if __name__ == "__main__":
    main()
