"""Generate the vendored EF-format state fixtures in this directory.

EF fixture archives are not available in this image (no network egress), so
these files are authored here, in the exact GeneralStateTest wire format
(matching /root/reference/tooling/ef_tests/state_v2/src/modules/types.rs),
with expected post hashes produced by this repo's executor — which is
itself validated byte-exactly against the reference's fixture chains and a
replayed Hoodi block (tests/test_reference_chains.py, test_hoodi_replay.py).
They pin behavior as regression tests and prove the runner speaks the real
EF format, so public archives plug in unmodified via EF_STATE_FIXTURES.

Run:  python tests/fixtures/ef_state/_generate.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))

from ethrex_tpu.crypto import secp256k1  # noqa: E402
from ethrex_tpu.utils import ef_state  # noqa: E402

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = "0x" + secp256k1.pubkey_to_address(
    secp256k1.pubkey_from_secret(SECRET)).hex()
COINBASE = "0x2adc25665018aa1fe0e6bc666dac8fc2697ff9ba"
TARGET = "0x" + "aa" * 20

ENV = {
    "currentCoinbase": COINBASE,
    "currentGasLimit": "0x1c9c380",
    "currentNumber": "0x1",
    "currentTimestamp": "0x3e8",
    "currentBaseFee": "0xa",
    "currentRandom": "0x" + "00" * 32,
}

PRE_BASE = {
    SENDER: {"balance": "0x56bc75e2d63100000", "nonce": "0x00",
             "code": "0x", "storage": {}},
}


def _post_for(tx_raw, pre, fork="Prague", indexes=None,
              expect_exception=None):
    """Execute one case with the repo executor to fill in hash/logs."""
    idx = indexes or {"data": 0, "gas": 0, "value": 0}
    case = ef_state.StateTestCase(
        name="gen", fork=fork, tx=ef_state._build_tx(tx_raw, idx),
        pre=ef_state._parse_pre(pre), env=ENV,
        expected_hash=b"\x00" * 32, expected_logs=b"\x00" * 32,
        expect_exception=expect_exception, indexes=(0, 0, 0))
    post_root, logs_hash, err, _gas = ef_state.execute_case(case)
    if expect_exception:
        assert err is not None, "expected-invalid tx was accepted"
    else:
        assert err is None, f"tx unexpectedly invalid: {err}"
    return {"hash": "0x" + post_root.hex(), "logs": "0x" + logs_hash.hex(),
            "indexes": idx,
            **({"expectException": expect_exception}
               if expect_exception else {})}


def build():
    fixtures = {}

    # 1. plain value transfer, legacy tx
    tx = {"data": ["0x"], "gasLimit": ["0x5208"], "value": ["0x0186a0"],
          "gasPrice": "0x14", "nonce": "0x00", "to": TARGET,
          "secretKey": hex(SECRET), "sender": SENDER}
    fixtures["transfer_legacy"] = {
        "env": ENV, "pre": PRE_BASE, "transaction": tx,
        "post": {f: [_post_for(tx, PRE_BASE, f)]
                 for f in ("Shanghai", "Cancun", "Prague")}}

    # 2. EIP-1559 dynamic fee + SSTORE fresh/update/clear (refund paths)
    code_addr = "0x" + "bb" * 20
    sstore_pre = dict(PRE_BASE)
    # SSTORE(0,1); SSTORE(1,0 from 5 -> clear refund); SLOAD(0) LOG1
    code = ("0x60015f55"      # SSTORE(0, 1)
            "5f600155"        # SSTORE(1, 0)  (pre=5 -> clearing refund)
            "5f54" "5f52"     # MSTORE(0, SLOAD(0))
            "60205f" "5fa1")  # LOG1(0, 32, topic=0)
    sstore_pre[code_addr] = {"balance": "0x0", "nonce": "0x01",
                             "code": code, "storage": {"0x01": "0x05"}}
    tx2 = {"data": ["0x"], "gasLimit": ["0x30d40"], "value": ["0x0"],
           "maxFeePerGas": "0x64", "maxPriorityFeePerGas": "0x02",
           "nonce": "0x00", "to": code_addr,
           "secretKey": hex(SECRET), "sender": SENDER}
    fixtures["sstore_refund_log_1559"] = {
        "env": ENV, "pre": sstore_pre, "transaction": tx2,
        "post": {f: [_post_for(tx2, sstore_pre, f)]
                 for f in ("Cancun", "Prague")}}

    # 3. contract creation (CREATE via tx.to == null) + multiple value idxs
    initcode = ("0x"
                "6960016000526001601ff3"  # PUSH10 runtime-deploy prefix
                "5f52600a6016f3")         # MSTORE; RETURN(22, 10)
    tx3 = {"data": [initcode], "gasLimit": ["0x186a0"],
           "value": ["0x0", "0x01"],
           "gasPrice": "0x14", "nonce": "0x00", "to": "",
           "secretKey": hex(SECRET), "sender": SENDER}
    fixtures["create_tx"] = {
        "env": ENV, "pre": PRE_BASE, "transaction": tx3,
        "post": {"Prague": [
            _post_for(tx3, PRE_BASE, "Prague",
                      {"data": 0, "gas": 0, "value": 0}),
            _post_for(tx3, PRE_BASE, "Prague",
                      {"data": 0, "gas": 0, "value": 1}),
        ]}}

    # 4. access-list tx (type 0x01) touching a pre-warmed slot
    tx4 = {"data": ["0x"], "gasLimit": ["0x30d40"], "value": ["0x0"],
           "gasPrice": "0x14", "nonce": "0x00", "to": code_addr,
           "accessLists": [[{"address": code_addr,
                             "storageKeys": ["0x00", "0x01"]}]],
           "secretKey": hex(SECRET), "sender": SENDER}
    fixtures["access_list_warm_sstore"] = {
        "env": ENV, "pre": sstore_pre, "transaction": tx4,
        "post": {"Prague": [_post_for(tx4, sstore_pre, "Prague")]}}

    # 5. invalid nonce -> tx rejected, state unchanged
    tx5 = {"data": ["0x"], "gasLimit": ["0x5208"], "value": ["0x01"],
           "gasPrice": "0x14", "nonce": "0x07", "to": TARGET,
           "secretKey": hex(SECRET), "sender": SENDER}
    fixtures["invalid_nonce_rejected"] = {
        "env": ENV, "pre": PRE_BASE, "transaction": tx5,
        "post": {"Prague": [_post_for(
            tx5, PRE_BASE, "Prague",
            expect_exception="TransactionException.NONCE_MISMATCH_TOO_HIGH")]}}

    # 6. revert inside a call: value moved back, gas charged
    rev_addr = "0x" + "cc" * 20
    rev_pre = dict(PRE_BASE)
    rev_pre[rev_addr] = {"balance": "0x0", "nonce": "0x01",
                         "code": "0x60015f55" "5f5ffd",  # SSTORE then REVERT
                         "storage": {}}
    tx6 = {"data": ["0x"], "gasLimit": ["0x30d40"], "value": ["0x64"],
           "gasPrice": "0x14", "nonce": "0x00", "to": rev_addr,
           "secretKey": hex(SECRET), "sender": SENDER}
    fixtures["revert_sstore_undone"] = {
        "env": ENV, "pre": rev_pre, "transaction": tx6,
        "post": {f: [_post_for(tx6, rev_pre, f)]
                 for f in ("Shanghai", "Cancun", "Prague")}}

    return fixtures


if __name__ == "__main__":
    out_dir = os.path.dirname(os.path.abspath(__file__))
    for name, fixture in build().items():
        path = os.path.join(out_dir, f"{name}.json")
        with open(path, "w") as f:
            json.dump({name: fixture}, f, indent=1, sort_keys=True)
        print("wrote", path)
