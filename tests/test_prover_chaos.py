"""Fault-injection battery for the prover pipeline (docs/
PROVER_RESILIENCE.md): every failure mode is driven by a seeded,
deterministic FaultPlan — prover crash mid-prove, slow proofs kept alive
by heartbeats, corrupt proofs, flapping endpoints, poison batches — plus
the lease/assignment unit coverage (timeout reassignment, no
double-assign under races, duplicate/unsolicited submits, oversized and
malformed frames).

Select alone with `-m chaos`; the whole battery is in the fast tier.
"""

import io
import socket
import threading
import time

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.l2.l1_client import InMemoryL1
from ethrex_tpu.l2.proof_coordinator import ProofCoordinator
from ethrex_tpu.l2.rollup_store import RollupStore
from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover.backend import ProverBackend
from ethrex_tpu.prover.client import ProverClient
from ethrex_tpu.utils import faults
from ethrex_tpu.utils.faults import FaultPlan
from ethrex_tpu.utils.metrics import METRICS

pytestmark = pytest.mark.chaos

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
OTHER = bytes.fromhex("aa" * 20)

GENESIS = {
    "config": {"chainId": 65536999, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _transfer(nonce, value=100):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=65536999, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21000, to=OTHER, value=value,
    ).sign(SECRET)


def _mini_l2(prover_types, **cfg_kw):
    """A real Node + sequencer + live TCP coordinator, one committed
    batch, ready for provers to pull."""
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1(list(prover_types))
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=tuple(prover_types), **cfg_kw))
    seq.coordinator.start()
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    assert seq.commit_next_batch() is not None
    return node, l1, seq


def _endpoints(seq):
    return [("127.0.0.1", seq.coordinator.port)]


def _poll_until_proven(client, seq, prover_type, deadline_s=8.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        client.poll_once()
        if seq.rollup.get_proof(1, prover_type) is not None:
            return
        time.sleep(0.05)
    raise AssertionError(f"batch 1 never proven as {prover_type}")


# ===========================================================================
# chaos scenarios (tentpole acceptance battery)
# ===========================================================================

def test_prover_crash_mid_prove_reassigned_and_recovered():
    """A prover that dies inside backend.prove loses its lease; after
    expiry the batch is reassigned and eventually proven — and the fault
    schedule is exactly the seeded plan, nothing more."""
    node, l1, seq = _mini_l2((protocol.PROVER_EXEC,),
                             prover_lease_timeout=0.25)
    co = seq.coordinator
    try:
        plan = faults.install(
            FaultPlan(seed=7).error("backend.prove", times=1))
        client = ProverClient(protocol.PROVER_EXEC, _endpoints(seq),
                              heartbeat_interval=0, backoff_base=0.01,
                              rng_seed=1)
        assert client.poll_once() == 0          # injected crash mid-prove
        assert seq.rollup.get_proof(1, protocol.PROVER_EXEC) is None
        # the lease is still live: the batch is NOT immediately re-served
        assert co.next_batch_to_assign(protocol.PROVER_EXEC) is None
        time.sleep(0.3)                         # lease expires
        _poll_until_proven(client, seq, protocol.PROVER_EXEC)
        assert co.reassignments_total == 1
        assert co.failures[(1, protocol.PROVER_EXEC)] == 1
        assert plan.log == [("backend.prove", "error")]
        assert seq.send_proofs() == (1, 1)
        assert l1.last_verified_batch() == 1
    finally:
        faults.clear()
        seq.stop()


def test_heartbeat_keeps_slow_proof_alive():
    """A proof 3x longer than the lease survives because the client's
    heartbeat thread keeps extending the assignment — no reassignment,
    proof accepted (the old fixed-600s-timeout failure mode)."""
    node, l1, seq = _mini_l2((protocol.PROVER_EXEC,),
                             prover_lease_timeout=0.4)
    co = seq.coordinator
    key = (1, protocol.PROVER_EXEC)
    try:
        faults.install(
            FaultPlan(seed=3).delay("backend.prove", 1.2, times=1))
        client = ProverClient(protocol.PROVER_EXEC, _endpoints(seq),
                              heartbeat_interval=0.1, rng_seed=0)
        results = []
        t = threading.Thread(
            target=lambda: results.append(client.poll_once()))
        t.start()
        deadline = time.time() + 3
        while key not in co.assignments and time.time() < deadline:
            time.sleep(0.01)
        assert key in co.assignments
        d0 = co.assignments[key]
        time.sleep(0.6)                          # well past the raw lease
        with co.lock:
            still_held = key in co.assignments
            extended = still_held and co.assignments[key] > d0
        assert still_held and extended, "heartbeats did not extend lease"
        # nobody else can steal the batch while the lease is being fed
        assert co.next_batch_to_assign(protocol.PROVER_EXEC) is None
        t.join(timeout=8)
        assert results == [1]
        assert seq.rollup.get_proof(1, protocol.PROVER_EXEC) is not None
        assert co.heartbeats_total >= 2
        assert co.reassignments_total == 0
    finally:
        faults.clear()
        seq.stop()


def test_corrupt_proof_rejected_then_retried():
    """A corrupted proof is rejected at submit time (not at settlement),
    the assignment slot is freed immediately, and the retry stores a
    clean proof."""
    node, l1, seq = _mini_l2((protocol.PROVER_EXEC,))
    co = seq.coordinator
    try:
        faults.install(
            FaultPlan(seed=11).corrupt("backend.prove", times=1))
        client = ProverClient(protocol.PROVER_EXEC, _endpoints(seq),
                              heartbeat_interval=0, backoff_base=0.01,
                              rng_seed=2)
        assert client.poll_once() == 0           # submit rejected
        assert seq.rollup.get_proof(1, protocol.PROVER_EXEC) is None
        assert co.rejected_submits_total == 1
        assert co.failures[(1, protocol.PROVER_EXEC)] == 1
        # rejection freed the slot: no lease expiry needed for the retry
        # (and no client-side backoff either — the endpoint was healthy)
        _poll_until_proven(client, seq, protocol.PROVER_EXEC)
        proof = seq.rollup.get_proof(1, protocol.PROVER_EXEC)
        assert proof["backend"] == protocol.PROVER_EXEC
        assert "__corrupt__" not in proof
        assert seq.send_proofs() == (1, 1)
    finally:
        faults.clear()
        seq.stop()


def test_rejected_submit_does_not_trip_breaker():
    """A prover producing invalid proofs (its own bug, or injected
    corruption) accumulates submit rejections, NOT endpoint failures: the
    coordinator answered the poll fine, so the breaker stays closed and
    the next attempt runs with no backoff or cooldown to wait out."""
    node, l1, seq = _mini_l2((protocol.PROVER_EXEC,))
    ep = _endpoints(seq)[0]
    try:
        faults.install(
            FaultPlan(seed=9).corrupt("backend.prove", times=2))
        client = ProverClient(protocol.PROVER_EXEC, [ep],
                              heartbeat_interval=0, backoff_base=0.01,
                              breaker_threshold=2, rng_seed=3)
        st = client.endpoint_states[ep]
        before = METRICS.counters.get("prover_submit_rejections_total", 0)
        assert client.poll_once() == 0
        assert client.poll_once() == 0
        # two rejections >= breaker_threshold, yet nothing was counted
        # against the (healthy) endpoint
        assert st.breaker == "closed" and st.failures == 0
        assert client.submit_rejections == 2
        assert METRICS.counters["prover_submit_rejections_total"] == \
            before + 2
        assert seq.coordinator.rejected_submits_total == 2
        # third poll proves cleanly, immediately
        assert client.poll_once() == 1
        assert seq.rollup.get_proof(1, protocol.PROVER_EXEC) is not None
    finally:
        faults.clear()
        seq.stop()


def test_flapping_endpoint_breaker_opens_and_recovers():
    """K consecutive connection drops open the endpoint's circuit
    breaker; while open the endpoint is skipped entirely; after the
    cooldown a half-open probe succeeds and the breaker closes."""
    node, l1, seq = _mini_l2((protocol.PROVER_EXEC,))
    ep = _endpoints(seq)[0]
    try:
        faults.install(FaultPlan(seed=5).drop("proto.send", times=3))
        client = ProverClient(protocol.PROVER_EXEC, [ep],
                              heartbeat_interval=0, backoff_base=0.01,
                              breaker_threshold=3, breaker_cooldown=0.3,
                              rng_seed=4)
        st = client.endpoint_states[ep]
        for _ in range(3):
            time.sleep(0.03)                     # clear the backoff gate
            assert client.poll_once() == 0
        assert st.breaker == "open" and st.failures == 3
        # open breaker: the endpoint is not even attempted
        assert client.poll_once() == 0
        assert st.failures == 3
        time.sleep(0.35)                         # cooldown elapses
        assert client.poll_once() == 1           # half-open probe succeeds
        assert st.breaker == "closed" and st.failures == 0
        assert seq.rollup.get_proof(1, protocol.PROVER_EXEC) is not None
        rendered = METRICS.render()
        assert "prover_breaker_transitions_total" in rendered
        assert "prover_poll_errors_total" in rendered
    finally:
        faults.clear()
        seq.stop()


def test_poison_batch_quarantined_to_exec_fallback():
    """A batch that keeps killing its tpu prover is quarantined after N
    failed assignments and settled by the exec fallback backend — and the
    whole path (metrics, health endpoint, L1 settlement) sees it."""
    class CrashingTpu(ProverBackend):
        prover_type = protocol.PROVER_TPU

        def prove(self, program_input, proof_format):
            raise RuntimeError("tpu backend wedged")

    node, l1, seq = _mini_l2((protocol.PROVER_TPU,),
                             prover_lease_timeout=0.25,
                             prover_quarantine_threshold=2)
    co = seq.coordinator
    try:
        bad = ProverClient(CrashingTpu(), _endpoints(seq),
                           heartbeat_interval=0, backoff_base=0.01,
                           breaker_threshold=100, rng_seed=0)
        assert bad.poll_once() == 0              # assignment 1 crashes
        time.sleep(0.3)
        assert bad.poll_once() == 0              # expiry 1 -> reassigned
        time.sleep(0.3)
        assert bad.poll_once() == 0              # expiry 2 -> quarantine
        assert co.quarantined == {1}
        # tpu provers are no longer offered the poisoned batch
        assert co.next_batch_to_assign(protocol.PROVER_TPU) is None
        # graceful degradation: the exec fallback takes it over
        good = ProverClient(protocol.PROVER_EXEC, _endpoints(seq),
                            heartbeat_interval=0, rng_seed=0)
        assert good.poll_once() == 1
        assert seq.rollup.get_proof(1, protocol.PROVER_EXEC) is not None
        # settlement consumes the fallback proof in the tpu slot
        assert seq.send_proofs() == (1, 1)
        assert l1.last_verified_batch() == 1
        # metrics + health endpoint surface the quarantine
        rendered = METRICS.render()
        assert "proof_reassignments_total" in rendered
        assert "quarantined_batches" in rendered
        from ethrex_tpu.rpc.server import RpcServer

        node.sequencer = seq
        h = RpcServer(node).handle({
            "jsonrpc": "2.0", "id": 1,
            "method": "ethrex_health", "params": []})
        prover_stats = h["result"]["l2"]["prover"]
        assert prover_stats["quarantined"] == [1]
        assert prover_stats["reassignments"] >= 2
    finally:
        seq.stop()


def test_fault_plan_determinism():
    """Same seed -> same fault schedule, independent of wall clock."""
    def run(seed):
        plan = FaultPlan(seed).error("backend.prove", p=0.5)
        outcomes = []
        for _ in range(32):
            try:
                plan.fire("backend.prove")
                outcomes.append(0)
            except ConnectionError:
                outcomes.append(1)
        return outcomes

    a, b = run(5), run(5)
    assert a == b and len(a) == 32
    assert 0 < sum(a) < 32          # p=0.5 actually mixes over 32 draws
    assert run(6) != a or run(7) != a


# ===========================================================================
# coordinator lease/assignment units (satellites)
# ===========================================================================

def _bare_coordinator(batches=1, **kw):
    store = RollupStore()
    for n in range(1, batches + 1):
        store.store_prover_input(n, protocol.PROTOCOL_VERSION, {"stub": n})
    kw.setdefault("needed_types", [protocol.PROVER_EXEC])
    return store, ProofCoordinator(store, **kw)


def test_lease_timeout_reassignment(monkeypatch):
    """Assigned batch, lease expires (faked clock), the SAME batch goes
    to a second prover of the same type; the expiry is counted."""
    store, co = _bare_coordinator()
    t = [100.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    assert co.next_batch_to_assign(protocol.PROVER_EXEC) == 1
    assert co.next_batch_to_assign(protocol.PROVER_EXEC) is None
    t[0] += co.lease_timeout + 1
    assert co.next_batch_to_assign(protocol.PROVER_EXEC) == 1
    assert co.reassignments_total == 1
    assert co.failures[(1, protocol.PROVER_EXEC)] == 1


def test_heartbeat_extends_lease_and_rejects_unknown(monkeypatch):
    store, co = _bare_coordinator()
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    assert co.next_batch_to_assign(protocol.PROVER_EXEC) == 1
    d0 = co.assignments[(1, protocol.PROVER_EXEC)]
    token = co.lease_token(1, protocol.PROVER_EXEC)
    assert token

    def beat(batch, tok=token):
        return co.handle_request({"type": protocol.HEARTBEAT,
                                  "batch_id": batch,
                                  "prover_type": protocol.PROVER_EXEC,
                                  "lease_token": tok})

    t[0] = co.lease_timeout - 1
    ack = beat(1)
    assert ack["type"] == protocol.HEARTBEAT_ACK and ack["ok"] is True
    assert co.assignments[(1, protocol.PROVER_EXEC)] == \
        t[0] + co.lease_timeout > d0
    # a heartbeat without the holder's token never extends the lease
    assert beat(1, tok=None)["ok"] is False
    assert beat(1, tok="forged")["ok"] is False
    assert co.assignments[(1, protocol.PROVER_EXEC)] == \
        t[0] + co.lease_timeout
    # an expired lease is not revived by a late heartbeat
    t[0] += co.lease_timeout + 1
    assert beat(1)["ok"] is False
    # and a heartbeat for a batch never assigned is refused
    assert beat(99)["ok"] is False


def test_heartbeat_cannot_extend_past_max_lease_lifetime(monkeypatch):
    """A hung prover's heartbeats keep arriving but the lease still dies:
    extensions are capped at max_lease_lifetime past first assignment, so
    the batch is eventually reassigned and the hang counted as a failure
    (the liveness property the old fixed 600 s timeout guaranteed)."""
    store, co = _bare_coordinator(lease_timeout=10.0,
                                  max_lease_lifetime=25.0)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    assert co.next_batch_to_assign(protocol.PROVER_EXEC) == 1
    token = co.lease_token(1, protocol.PROVER_EXEC)
    key = (1, protocol.PROVER_EXEC)

    def beat():
        return co.handle_request({"type": protocol.HEARTBEAT,
                                  "batch_id": 1,
                                  "prover_type": protocol.PROVER_EXEC,
                                  "lease_token": token})["ok"]

    t[0] = 8.0
    assert beat() is True
    assert co.assignments[key] == 18.0       # full extension
    t[0] = 16.0
    assert beat() is True
    assert co.assignments[key] == 25.0       # clamped to the hard cap
    t[0] = 24.0
    assert beat() is True                    # still inside the lifetime
    assert co.assignments[key] == 25.0       # but no further extension
    t[0] = 26.0                              # lifetime spent, lease dead
    assert beat() is False
    assert co.next_batch_to_assign(protocol.PROVER_EXEC) == 1
    assert co.failures[key] == 1
    assert co.reassignments_total == 1


def test_next_batch_never_double_assigns_under_race():
    """N concurrent polls over K batches: each batch handed out exactly
    once (the assignment map is the mutual exclusion)."""
    store, co = _bare_coordinator(batches=3)
    results = []
    lock = threading.Lock()
    barrier = threading.Barrier(8)

    def worker():
        barrier.wait()
        got = co.next_batch_to_assign(protocol.PROVER_EXEC)
        with lock:
            results.append(got)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assigned = [r for r in results if r is not None]
    assert sorted(assigned) == [1, 2, 3]
    assert results.count(None) == 5


def test_duplicate_and_unsolicited_submits():
    """Unsolicited ProofSubmit (no assignment) must not write the proof
    store; a duplicate submit is a no-op ACK keeping the first proof."""
    store, co = _bare_coordinator(verify_submissions=False)
    msg = {"type": protocol.PROOF_SUBMIT, "batch_id": 1,
           "prover_type": protocol.PROVER_EXEC,
           "proof": {"backend": protocol.PROVER_EXEC, "v": 1}}
    r = co.handle_request(msg)
    assert r["type"] == protocol.ERROR
    assert store.get_proof(1, protocol.PROVER_EXEC) is None
    assert co.unsolicited_submits_total == 1
    # with a live assignment but no lease token, the submit is still
    # refused — verification is off, so the token is the only write gate
    assert co.next_batch_to_assign(protocol.PROVER_EXEC) == 1
    r = co.handle_request(msg)
    assert r["type"] == protocol.ERROR and "stale lease" in r["message"]
    assert store.get_proof(1, protocol.PROVER_EXEC) is None
    assert co.stale_submits_total == 1
    # with the holder's token the same submit lands
    msg["lease_token"] = co.lease_token(1, protocol.PROVER_EXEC)
    assert co.handle_request(msg)["type"] == protocol.SUBMIT_ACK
    # duplicate (different payload!) -> no-op ACK, first proof kept
    dup = dict(msg, proof={"backend": protocol.PROVER_EXEC, "v": 2})
    assert co.handle_request(dup)["type"] == protocol.SUBMIT_ACK
    assert store.get_proof(1, protocol.PROVER_EXEC)["v"] == 1


def test_invalid_submit_rejected_and_slot_freed():
    """verify_submissions: a proof the backend refuses is not stored and
    the batch is immediately assignable again."""
    store, co = _bare_coordinator()        # verify_submissions=True
    assert co.next_batch_to_assign(protocol.PROVER_EXEC) == 1
    token = co.lease_token(1, protocol.PROVER_EXEC)
    r = co.handle_request({"type": protocol.PROOF_SUBMIT, "batch_id": 1,
                           "prover_type": protocol.PROVER_EXEC,
                           "proof": {"backend": "__corrupt__"},
                           "lease_token": token})
    assert r["type"] == protocol.ERROR and "invalid proof" in r["message"]
    assert store.get_proof(1, protocol.PROVER_EXEC) is None
    assert co.rejected_submits_total == 1
    assert co.next_batch_to_assign(protocol.PROVER_EXEC) == 1


def test_stale_submit_cannot_evict_live_lease():
    """An invalid proof from a connection that does NOT hold the lease
    token (stale evicted prover, or any third party) is refused without
    touching the live holder's lease or the batch's quarantine budget —
    and the holder's own valid submit still lands afterwards."""
    store, co = _bare_coordinator(quarantine_threshold=2)
    assert co.next_batch_to_assign(protocol.PROVER_EXEC) == 1
    token = co.lease_token(1, protocol.PROVER_EXEC)
    key = (1, protocol.PROVER_EXEC)
    d0 = co.assignments[key]

    def attack(tok):
        return co.handle_request({
            "type": protocol.PROOF_SUBMIT, "batch_id": 1,
            "prover_type": protocol.PROVER_EXEC,
            "proof": {"backend": "__corrupt__"}, "lease_token": tok})

    # enough corrupt submits to quarantine the batch, were they counted
    for tok in (None, "forged", "forged", "forged"):
        r = attack(tok)
        assert r["type"] == protocol.ERROR
        assert "stale lease" in r["message"]
    assert co.assignments[key] == d0          # lease untouched
    assert co.failures == {}                  # no failure burned
    assert co.rejected_submits_total == 0
    assert co.stale_submits_total == 4
    assert co.quarantined == set()            # no forced downgrade
    # the real holder's valid proof is accepted, not "no assignment"
    valid = {"backend": protocol.PROVER_EXEC,
             "output": "0x" + "00" * 176}     # decodable ProgramOutput
    r = co.handle_request({"type": protocol.PROOF_SUBMIT, "batch_id": 1,
                           "prover_type": protocol.PROVER_EXEC,
                           "proof": valid, "lease_token": token})
    assert r["type"] == protocol.SUBMIT_ACK
    assert store.get_proof(1, protocol.PROVER_EXEC) is not None


# ===========================================================================
# wire-protocol hardening (satellites)
# ===========================================================================

def test_recv_msg_file_oversized_truncated_malformed():
    # a line longer than max_size comes back from readline() with no
    # trailing newline — previously fed straight into json.loads
    with pytest.raises(ConnectionError, match="message too large"):
        protocol.recv_msg_file(io.BytesIO(b"x" * 200), max_size=64)
    # EOF mid-frame (peer died): also not a parseable message
    with pytest.raises(ConnectionError, match="truncated frame"):
        protocol.recv_msg_file(io.BytesIO(b'{"a": 1'), max_size=64)
    with pytest.raises(ConnectionError, match="malformed frame"):
        protocol.recv_msg_file(io.BytesIO(b"not json\n"))
    with pytest.raises(ConnectionError, match="not a JSON object"):
        protocol.recv_msg_file(io.BytesIO(b"[1,2]\n"))
    assert protocol.recv_msg_file(io.BytesIO(b"")) is None
    assert protocol.recv_msg_file(io.BytesIO(b'{"a":1}\n')) == {"a": 1}


def test_recv_msg_oversized_and_malformed():
    a, b = socket.socketpair()
    try:
        a.sendall(b"garbage\n")
        with pytest.raises(ConnectionError, match="malformed frame"):
            protocol.recv_msg(b)
        a.sendall(b"y" * 200)
        with pytest.raises(ConnectionError, match="message too large"):
            protocol.recv_msg(b, max_size=64)
    finally:
        a.close()
        b.close()


def test_poll_error_goes_to_logger_and_metrics(caplog):
    """A dead endpoint increments prover_poll_errors_total and logs via
    the module logger (the old bare print is gone)."""
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()                       # nothing listens here any more
    before = METRICS.counters.get("prover_poll_errors_total", 0)
    client = ProverClient(protocol.PROVER_EXEC, [("127.0.0.1", port)],
                          heartbeat_interval=0, rng_seed=0)
    import logging

    with caplog.at_level(logging.WARNING, "ethrex_tpu.prover.client"):
        assert client.poll_once() == 0
    assert METRICS.counters["prover_poll_errors_total"] == before + 1
    assert client.endpoint_states[("127.0.0.1", port)].failures == 1
    assert any("poll failed" in r.getMessage() for r in caplog.records)


def test_fault_guard_requires_cleanup():
    """The injected() context manager clears the plan (what the conftest
    guard enforces for every test)."""
    with faults.injected(FaultPlan(seed=1).drop("proto.send", times=1)):
        assert faults.active() is not None
    assert faults.active() is None


def test_recv_drop_poll_fails_then_recovers():
    """An inbound frame dropped mid-read (proto.recv) fails that poll
    gracefully; once the fault budget drains the same client proves the
    batch."""
    node, l1, seq = _mini_l2((protocol.PROVER_EXEC,))
    try:
        faults.install(FaultPlan(seed=13).drop("proto.recv", times=1))
        client = ProverClient(protocol.PROVER_EXEC, _endpoints(seq),
                              heartbeat_interval=0, backoff_base=0.01,
                              rng_seed=6)
        assert client.poll_once() == 0      # the read died mid-frame
        assert seq.rollup.get_proof(1, protocol.PROVER_EXEC) is None
        time.sleep(0.03)                    # clear the backoff gate
        _poll_until_proven(client, seq, protocol.PROVER_EXEC)
        assert seq.send_proofs() == (1, 1)
        assert l1.last_verified_batch() == 1
    finally:
        faults.clear()
        seq.stop()


def test_store_proof_crash_reassigned_after_lease_expiry():
    """The coordinator crashing at rollup.store_proof
    (coordinator.store_proof) loses the proof but not the lease
    accounting: after expiry the batch is reassigned and settles."""
    node, l1, seq = _mini_l2((protocol.PROVER_EXEC,),
                             prover_lease_timeout=0.25)
    try:
        faults.install(
            FaultPlan(seed=17).error("coordinator.store_proof", times=1))
        client = ProverClient(protocol.PROVER_EXEC, _endpoints(seq),
                              heartbeat_interval=0, backoff_base=0.01,
                              rng_seed=7)
        client.poll_once()                  # proof computed, store crashed
        assert seq.rollup.get_proof(1, protocol.PROVER_EXEC) is None
        time.sleep(0.3)                     # lease expires -> reassigned
        _poll_until_proven(client, seq, protocol.PROVER_EXEC)
        assert seq.send_proofs() == (1, 1)
        assert l1.last_verified_batch() == 1
    finally:
        faults.clear()
        seq.stop()
