"""L2 blobs: KZG sidecar generation, payload round-trip, and state
reconstruction from blobs (parity: crates/l2/sequencer/l1_committer.rs
generate_blobs_bundle, crates/l2/utils/state_reconstruct.rs)."""

import pytest

from ethrex_tpu.crypto import kzg
from ethrex_tpu.l2 import blobs
from tests.test_stateless import _make_chain_with_blocks


def test_payload_packing_roundtrip():
    for payload in (b"", b"x", b"hello" * 1000, bytes(range(256)) * 200):
        packed = blobs.pack_payload(payload)
        assert all(len(b) == kzg.BYTES_PER_BLOB for b in packed)
        assert blobs.unpack_payload(packed) == payload
    # every packed word is a canonical field element
    packed = blobs.pack_payload(b"\xff" * 500)
    kzg.blob_to_evals(packed[0])


def test_bundle_generation_and_reconstruction():
    node, blocks_list = _make_chain_with_blocks()
    bundle = blobs.generate_blobs_bundle(blocks_list)
    assert bundle.verify()
    assert len(bundle.versioned_hashes) == len(bundle.blobs)
    assert all(h[0] == 0x01 for h in bundle.versioned_hashes)
    # the whole batch comes back out of the sidecar
    rebuilt = blobs.reconstruct_blocks(bundle)
    assert [b.hash for b in rebuilt] == [b.hash for b in blocks_list]
    # a flipped blob byte fails KZG verification
    tampered = blobs.BlobsBundle(
        blobs=[bytes([bundle.blobs[0][0]]) + bundle.blobs[0][1:-1]
               + bytes([bundle.blobs[0][-1] ^ 1])],
        commitments=list(bundle.commitments),
        proofs=list(bundle.proofs))
    with pytest.raises(blobs.BlobError):
        blobs.reconstruct_blocks(tampered)


def test_committer_stores_verifiable_bundle():
    from ethrex_tpu.l2.l1_client import InMemoryL1
    from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
    from ethrex_tpu.prover import protocol
    from tests.test_l2_pipeline import _setup, _transfer

    node, l1, seq = _setup([protocol.PROVER_EXEC])
    try:
        node.submit_transaction(_transfer(0))
        seq.produce_block()
        batch = seq.commit_next_batch()
        assert batch is not None
        bundle = seq.rollup.get_blobs_bundle(batch.number)
        assert bundle is not None and bundle.verify()
        rebuilt = blobs.reconstruct_blocks(bundle)
        assert rebuilt[-1].header.state_root == batch.state_root
    finally:
        seq.stop()
