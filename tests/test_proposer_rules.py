"""Settlement state machine (l2/proposer_rules.py): every revert
condition of the reference's OnChainProposer/CommonBridge exercised
case-by-case (OnChainProposer.sol:226-687, CommonBridge.sol:135-687)."""

import pytest

from ethrex_tpu.crypto.keccak import keccak256
from ethrex_tpu.l2.proposer_rules import (CommonBridgeRules,
                                          OnChainProposerRules, Revert,
                                          alias_sender, merkle_verify,
                                          versioned_hash, withdrawal_leaf)

OWNER = b"\xaa" * 20
OTHER = b"\xbb" * 20
L2_BRIDGE = b"\xfe" * 20
USER = b"\xcc" * 20
ROOT1 = b"\x11" * 32
HASH1 = b"\x22" * 32
COMMIT = b"\x33" * 32
BLOB = b"\x01" + b"\x44" * 31


def _fixture(needed=("tpu",), validium=False):
    bridge = CommonBridgeRules(chain_id=1337, l2_bridge=L2_BRIDGE)
    prop = OnChainProposerRules(bridge, OWNER, list(needed),
                                validium=validium)
    prop.set_verification_key(OWNER, COMMIT, "tpu", b"\x77" * 32)
    prop.verifiers["tpu"] = lambda vk, pub, proof: proof == b"ok"
    return bridge, prop


def _commit(prop, n, *, priv=b"", wroot=b"", blob=BLOB, count=1,
            commit=COMMIT, last_hash=HASH1, root=ROOT1, caller=OWNER):
    prop.commit_batch(caller, n, root, wroot, priv, last_hash, count,
                      commit, blob_versioned_hash=blob)


# ---- commitBatch reverts ---------------------------------------------------

def test_commit_happy_and_succession():
    _, prop = _fixture()
    _commit(prop, 1)
    assert prop.last_committed == 1
    with pytest.raises(Revert, match="BatchNumberNotSuccessor"):
        _commit(prop, 3)
    with pytest.raises(Revert, match="BatchNumberNotSuccessor"):
        _commit(prop, 1)


def test_commit_only_owner_and_pause():
    _, prop = _fixture()
    with pytest.raises(Revert, match="OwnableUnauthorizedAccount"):
        _commit(prop, 1, caller=OTHER)
    prop.pause(OWNER)
    with pytest.raises(Revert, match="EnforcedPause"):
        _commit(prop, 1)


def test_commit_zero_last_block_hash():
    _, prop = _fixture()
    with pytest.raises(Revert, match="LastBlockHashIsZero"):
        _commit(prop, 1, last_hash=b"\x00" * 32)


def test_commit_privileged_rolling_hash_binding():
    bridge, prop = _fixture()
    h1 = bridge.deposit(USER, USER, 100, now=1000)
    h2 = bridge.deposit(USER, USER, 200, now=1000)
    good = bridge.pending_versioned_hash(2)
    assert good == versioned_hash(2, [h1, h2])
    _commit(prop, 1, priv=good)
    # tampered rolling hash: count prefix right, digest wrong
    bad = good[:2] + b"\x00" * 30
    with pytest.raises(Revert, match="InvalidPrivilegedTransactionLogs"):
        _commit(prop, 2, priv=bad)
    # claiming more pending txs than exist
    overcount = versioned_hash(3, [h1, h2, h2])
    with pytest.raises(Revert, match="greater than the length"):
        _commit(prop, 2, priv=overcount)


def test_commit_blob_rules_rollup_vs_validium():
    _, prop = _fixture()
    with pytest.raises(Revert, match="RollupBlobNotPublished"):
        _commit(prop, 1, blob=b"")
    _, vprop = _fixture(validium=True)
    with pytest.raises(Revert, match="ValidiumBlobPublished"):
        _commit(vprop, 1)          # blob present in validium mode
    _commit(vprop, 1, blob=b"")    # and absent is fine


def test_commit_hash_and_vk_rules():
    _, prop = _fixture()
    with pytest.raises(Revert, match="CommitHashIsZero"):
        _commit(prop, 1, commit=b"\x00" * 32)
    with pytest.raises(Revert, match="MissingVerificationKeyForCommit"):
        _commit(prop, 1, commit=b"\x55" * 32)   # no vk registered
    with pytest.raises(Revert, match="CommitHashIsZero"):
        prop.set_verification_key(OWNER, b"\x00" * 32, "tpu", b"\x01")


def test_commit_publishes_withdrawals_once():
    bridge, prop = _fixture()
    _commit(prop, 1, wroot=b"\x66" * 32)
    assert bridge.withdrawal_roots[1] == b"\x66" * 32
    with pytest.raises(Revert, match="already published"):
        bridge.publish_withdrawals(1, b"\x67" * 32,
                                   caller_is_proposer=True)
    with pytest.raises(Revert, match="onlyOnChainProposer"):
        bridge.publish_withdrawals(2, b"\x67" * 32,
                                   caller_is_proposer=False)


# ---- verifyBatches reverts -------------------------------------------------

def test_verify_happy_path_and_pruning():
    _, prop = _fixture()
    _commit(prop, 1)
    _commit(prop, 2, root=b"\x12" * 32)
    prop.verify_batches(OWNER, 1, {"tpu": [b"ok", b"ok"]})
    assert prop.last_verified == 2
    # verified predecessors pruned (n-1 on each verify)
    assert 1 not in prop.commitments and 2 in prop.commitments


def test_verify_rejects_bad_proof_and_sequence():
    _, prop = _fixture()
    _commit(prop, 1)
    with pytest.raises(Revert, match="InvalidTpuProof"):
        prop.verify_batches(OWNER, 1, {"tpu": [b"bad"]})
    with pytest.raises(Revert, match="BatchNotSequential"):
        prop.verify_batches(OWNER, 2, {"tpu": [b"ok"]})
    with pytest.raises(Revert, match="EmptyBatchArray"):
        prop.verify_batches(OWNER, 1, {"tpu": []})
    prop.verify_batches(OWNER, 1, {"tpu": [b"ok"]})
    with pytest.raises(Revert, match="BatchNotCommitted"):
        prop.verify_batches(OWNER, 2, {"tpu": [b"ok"]})


def test_verify_consumes_privileged_queue():
    bridge, prop = _fixture()
    bridge.deposit(USER, USER, 100, now=1000)
    bridge.deposit(USER, USER, 200, now=1000)
    rolling = bridge.pending_versioned_hash(2)
    _commit(prop, 1, priv=rolling)
    assert bridge._pending_len() == 2
    prop.verify_batches(OWNER, 1, {"tpu": [b"ok"]}, now=1001)
    assert bridge._pending_len() == 0


def test_expired_privileged_deadline_forces_inclusion():
    """Once a privileged tx sits past its deadline, batches carrying
    ordinary transactions cannot verify until the privileged queue is
    drained (censorship resistance, OnChainProposer.sol:348-353)."""
    bridge, prop = _fixture()
    bridge.deposit(USER, USER, 100, now=1000)
    deadline = 1000 + bridge.privileged_wait
    # batch WITHOUT the privileged tx but with ordinary txs
    _commit(prop, 1, count=3)
    with pytest.raises(Revert, match="ExpiredPrivilegedTransactionDeadline"):
        prop.verify_batches(OWNER, 1, {"tpu": [b"ok"]}, now=deadline + 1)
    # an all-privileged batch (non_privileged == 0) still verifies...
    _, prop2 = _fixture()
    bridge2 = prop2.bridge
    bridge2.deposit(USER, USER, 100, now=1000)
    rolling = bridge2.pending_versioned_hash(1)
    prop2.commit_batch(OWNER, 1, ROOT1, b"", rolling, HASH1, 0, COMMIT,
                       blob_versioned_hash=BLOB)
    prop2.verify_batches(OWNER, 1, {"tpu": [b"ok"]}, now=deadline + 1)
    assert prop2.last_verified == 1


# ---- revertBatch -----------------------------------------------------------

def test_revert_batch_rules():
    _, prop = _fixture()
    _commit(prop, 1)
    _commit(prop, 2, root=b"\x12" * 32)
    with pytest.raises(Revert, match="ExpectedPause"):
        prop.revert_batch(OWNER, 2)
    prop.pause(OWNER)
    with pytest.raises(Revert, match="NoBatchesToRevert"):
        prop.revert_batch(OWNER, 3)
    prop.revert_batch(OWNER, 2)
    assert prop.last_committed == 1 and 2 not in prop.commitments
    prop.unpause(OWNER)
    prop.verify_batches(OWNER, 1, {"tpu": [b"ok"]})
    prop.pause(OWNER)
    with pytest.raises(Revert, match="CannotRevertVerifiedBatch"):
        prop.revert_batch(OWNER, 1)


# ---- bridge: deposits, aliasing, claims ------------------------------------

def test_gas_limit_cap_and_aliasing():
    bridge, _ = _fixture()
    with pytest.raises(Revert, match="gasLimit exceeds l2GasLimit"):
        bridge.send_to_l2(USER, USER, 0, bridge.l2_gas_limit + 1, b"",
                          now=0)
    assert alias_sender(USER, is_contract=False) == USER
    aliased = alias_sender(USER, is_contract=True)
    assert aliased != USER and len(aliased) == 20


def _withdrawal_tree(leaves):
    level = list(leaves)
    layers = [level]
    while len(level) > 1:
        if len(level) % 2:
            level = level + [level[-1]]
        level = [keccak256(min(a, b) + max(a, b))
                 for a, b in zip(level[0::2], level[1::2])]
        layers.append(level)
    return layers[-1][0], layers


def _proof_for(layers, idx):
    proof = []
    for level in layers[:-1]:
        if len(level) % 2:
            level = level + [level[-1]]
        sib = idx ^ 1
        proof.append(level[sib])
        idx //= 2
    return proof


def test_claim_withdrawal_full_lifecycle():
    bridge, prop = _fixture()
    bridge.deposit(USER, USER, 1000, now=0)
    amount = 400
    msg_hash = keccak256(b"\x00" * 20 + b"\x00" * 20 + USER
                         + amount.to_bytes(32, "big"))
    leaves = [withdrawal_leaf(L2_BRIDGE, msg_hash, 0),
              withdrawal_leaf(L2_BRIDGE, keccak256(b"other"), 1)]
    root, layers = _withdrawal_tree(leaves)
    proof = _proof_for(layers, 0)
    assert merkle_verify(proof, root, leaves[0])
    _commit(prop, 1, wroot=root)
    # before verification: claim refused
    with pytest.raises(Revert, match="was not verified"):
        bridge.claim_withdrawal(USER, amount, 1, 0, proof)
    prop.verify_batches(OWNER, 1, {"tpu": [b"ok"]})
    bridge.claim_withdrawal(USER, amount, 1, 0, proof)
    assert bridge.deposits_pool == 600
    with pytest.raises(Revert, match="already claimed"):
        bridge.claim_withdrawal(USER, amount, 1, 0, proof)
    with pytest.raises(Revert, match="Invalid proof"):
        bridge.claim_withdrawal(USER, amount, 1, 2, proof)
    with pytest.raises(Revert, match="more tokens/ETH than were deposited"):
        bridge.claim_withdrawal(USER, 10**9, 1, 3, proof)
    with pytest.raises(Revert, match="was not committed"):
        bridge.claim_withdrawal(USER, amount, 9, 4, proof)


def test_versioned_hash_shape():
    h = versioned_hash(2, [b"\x01" * 32, b"\x02" * 32])
    assert h[:2] == (2).to_bytes(2, "big")
    assert h[2:] == keccak256(b"\x01" * 32 + b"\x02" * 32)[2:]


# ---- revert rollback semantics (Python has no implicit state rollback) ----

def test_failed_claim_does_not_brick_withdrawal():
    """A claim attempt with a bad proof must not consume the message id:
    the subsequent legitimate claim has to succeed (Solidity reverts roll
    claimed state back; checks-before-effects must emulate that)."""
    bridge, prop = _fixture()
    bridge.deposit(USER, USER, 1000, now=0)
    amount = 400
    msg_hash = keccak256(b"\x00" * 20 + b"\x00" * 20 + USER
                         + amount.to_bytes(32, "big"))
    leaves = [withdrawal_leaf(L2_BRIDGE, msg_hash, 0),
              withdrawal_leaf(L2_BRIDGE, keccak256(b"other"), 1)]
    root, layers = _withdrawal_tree(leaves)
    proof = _proof_for(layers, 0)
    _commit(prop, 1, wroot=root)
    prop.verify_batches(OWNER, 1, {"tpu": [b"ok"]})
    bad_proof = [b"\x00" * 32] + proof[1:]
    with pytest.raises(Revert, match="Invalid proof"):
        bridge.claim_withdrawal(USER, amount, 1, 0, bad_proof)
    # id 0 must NOT be marked claimed by the failed attempt
    bridge.claim_withdrawal(USER, amount, 1, 0, proof)
    assert bridge.deposits_pool == 600


def test_failed_verify_keeps_privileged_queue():
    """verify_batches is all-or-nothing: a bad proof mid-call must leave
    the privileged queue, last_verified and pruning untouched."""
    bridge, prop = _fixture()
    bridge.deposit(USER, USER, 100, now=1000)
    bridge.deposit(USER, USER, 200, now=1000)
    rolling = bridge.pending_versioned_hash(2)
    _commit(prop, 1, priv=rolling)
    before_pending = bridge._pending_len()
    with pytest.raises(Revert, match="InvalidTpuProof"):
        prop.verify_batches(OWNER, 1, {"tpu": [b"bad"]}, now=1001)
    assert bridge._pending_len() == before_pending
    assert prop.last_verified == 0
    assert 1 in prop.commitments
    # the legitimate retry succeeds and consumes the queue
    prop.verify_batches(OWNER, 1, {"tpu": [b"ok"]}, now=1001)
    assert bridge._pending_len() == 0


def test_failed_multi_batch_verify_rolls_back_all():
    """A failure on batch k of a multi-batch verifyBatches call must
    roll back batches < k too (non-atomic loop divergence)."""
    bridge, prop = _fixture()
    _commit(prop, 1)
    _commit(prop, 2, root=b"\x12" * 32, last_hash=b"\x23" * 32)
    with pytest.raises(Revert, match="InvalidTpuProof"):
        prop.verify_batches(OWNER, 1, {"tpu": [b"ok", b"bad"]})
    assert prop.last_verified == 0
    assert 1 in prop.commitments  # batch 1 not pruned by the failed call
    prop.verify_batches(OWNER, 1, {"tpu": [b"ok", b"ok"]})
    assert prop.last_verified == 2


def test_failed_commit_does_not_publish_withdrawals():
    """commit_batch publishing the withdrawal root before a later revert
    check would block the retry with 'already published'."""
    _, prop = _fixture()
    wroot = b"\x55" * 32
    # zero commit hash trips a check AFTER the old publish point
    with pytest.raises(Revert, match="CommitHashIsZero"):
        _commit(prop, 1, wroot=wroot, commit=b"\x00" * 32)
    assert not prop.bridge.withdrawal_roots
    _commit(prop, 1, wroot=wroot)  # retry must not hit 'already published'
    assert prop.bridge.withdrawal_roots[1] == wroot
