"""MPT tests: spec vectors, random consistency, proofs, witness tries."""

import random

import pytest

from ethrex_tpu.crypto.keccak import keccak256
from ethrex_tpu.primitives import rlp
from ethrex_tpu.primitives.account import EMPTY_TRIE_ROOT
from ethrex_tpu.trie.trie import (
    MissingNode, Trie, bytes_to_nibbles, hp_decode, hp_encode,
    trie_root_from_items, verify_proof,
)


def test_hex_prefix_roundtrip():
    for nibbles, leaf in [((), True), ((1,), False),
                          ((1, 2, 3), True), ((0, 0, 0, 0), False),
                          (tuple(range(16)), True)]:
        enc = hp_encode(nibbles, leaf)
        assert hp_decode(enc) == (nibbles, leaf)


def test_empty_root():
    assert Trie().root_hash() == EMPTY_TRIE_ROOT
    assert keccak256(rlp.encode(b"")) == EMPTY_TRIE_ROOT


def test_known_ethereum_vector():
    # canonical MPT test vector (ethereum/tests trietest: do/dog/doge/horse)
    t = Trie()
    for k, v in [(b"do", b"verb"), (b"dog", b"puppy"), (b"doge", b"coin"),
                 (b"horse", b"stallion")]:
        t.insert(k, v)
    assert t.root_hash().hex() == (
        "5991bb8c6514148a29db676a14ac506cd2cd5775ace63c30a4fe457715e9ac84")


def test_insert_get_remove_random():
    rng = random.Random(42)
    t = Trie()
    ref = {}
    for _ in range(500):
        k = rng.randbytes(rng.randint(1, 8))
        v = rng.randbytes(rng.randint(1, 40))
        t.insert(k, v)
        ref[k] = v
    for k, v in ref.items():
        assert t.get(k) == v
    # removal of half the keys
    keys = sorted(ref)
    for k in keys[::2]:
        t.remove(k)
        del ref[k]
    for k in keys:
        assert t.get(k) == ref.get(k)
    # root must equal a freshly built trie over the same final content
    fresh = Trie()
    for k, v in ref.items():
        fresh.insert(k, v)
    assert t.root_hash() == fresh.root_hash()
    # insertion order must not matter
    shuffled = list(ref.items())
    rng.shuffle(shuffled)
    t2 = Trie()
    for k, v in shuffled:
        t2.insert(k, v)
    assert t2.root_hash() == t.root_hash()


def test_remove_everything_returns_empty_root():
    t = Trie()
    items = [(bytes([i]), b"v%d" % i) for i in range(50)]
    for k, v in items:
        t.insert(k, v)
    for k, _ in items:
        t.remove(k)
    assert t.root_hash() == EMPTY_TRIE_ROOT


def test_proofs():
    t = Trie()
    ref = {}
    rng = random.Random(1)
    for i in range(100):
        k = keccak256(bytes([i]))
        v = rng.randbytes(30)
        t.insert(k, v)
        ref[k] = v
    root = t.root_hash()
    for k in list(ref)[:10]:
        proof = t.get_proof(k)
        ok, value = verify_proof(root, k, proof)
        assert ok and value == ref[k]
    # proof for an absent key proves absence
    absent = keccak256(b"nope")
    proof = t.get_proof(absent)
    ok, value = verify_proof(root, absent, proof)
    assert ok and value is None
    # tampered proof fails
    proof2 = t.get_proof(list(ref)[0])
    tampered = [proof2[0][:-1] + bytes([proof2[0][-1] ^ 1])] + proof2[1:]
    ok, _ = verify_proof(root, list(ref)[0], tampered)
    assert not ok


def test_witness_trie_from_nodes():
    t = Trie()
    rng = random.Random(2)
    ref = {}
    for i in range(200):
        k = keccak256(bytes([i]))
        v = rng.randbytes(20)
        t.insert(k, v)
        ref[k] = v
    root = t.commit()
    # witness = union of proofs for a few touched keys
    touched = list(ref)[:5]
    nodes = {}
    for k in touched:
        for enc in t.get_proof(k):
            nodes[keccak256(enc)] = enc
    wt = Trie.from_nodes(root, nodes)
    for k in touched:
        assert wt.get(k) == ref[k]
    # an untouched key walks into a pruned subtree
    with pytest.raises(MissingNode):
        for k in ref:
            if k not in touched:
                wt.get(k)
    # mutation of a touched key + re-hash matches the full trie's result
    wt.insert(touched[0], b"new-value")
    t.insert(touched[0], b"new-value")
    assert wt.root_hash() == t.root_hash()


def test_trie_root_from_items():
    items = [(rlp.encode(i), b"tx%d" % i) for i in range(10)]
    r1 = trie_root_from_items(items)
    r2 = trie_root_from_items(list(reversed(items)))
    assert r1 == r2 != EMPTY_TRIE_ROOT
