"""Poseidon2 AIR: round-function equivalence with the reference
permutation, constraint satisfaction on honest traces, and a full
prove/verify round-trip at blowup 8."""

import numpy as np
import pytest

from ethrex_tpu.models import poseidon2_air as pair
from ethrex_tpu.ops import babybear as bb
from ethrex_tpu.ops import ext
from ethrex_tpu.ops import poseidon2 as p2
from ethrex_tpu.stark import prover, verifier
from ethrex_tpu.stark.air import HostExtOps
from ethrex_tpu.stark.prover import StarkParams

RNG = np.random.default_rng(11)
PARAMS = StarkParams(log_blowup=3, num_queries=30, log_final_size=4)


def _limbs():
    return [int(v) for v in RNG.integers(0, bb.P, 16)]


def test_trace_matches_reference_permutation():
    limbs = _limbs()
    trace = pair.generate_trace(limbs)
    assert [int(x) for x in trace[pair.ROUNDS]] == p2.permute_ref(limbs)


def test_constraints_vanish_on_honest_trace():
    limbs = _limbs()
    air = pair.Poseidon2Air()
    trace = pair.generate_trace(limbs)
    periodic_cols = air.periodic_columns(pair.PERIOD)
    hops = HostExtOps()
    for r in range(pair.PERIOD - 1):
        local = [ext.h_from_base(int(v)) for v in trace[r]]
        nxt = [ext.h_from_base(int(v)) for v in trace[r + 1]]
        periodic = [ext.h_from_base(int(col[r % len(col)]))
                    for col in periodic_cols]
        cons = air.constraints(local, nxt, periodic, hops)
        assert all(c == ext.ZERO_H for c in cons), f"row {r}"
    # and a corrupted row violates them
    bad = trace.copy()
    bad[5, 3] = (int(bad[5, 3]) + 1) % bb.P
    local = [ext.h_from_base(int(v)) for v in bad[5]]
    nxt = [ext.h_from_base(int(v)) for v in bad[6]]
    periodic = [ext.h_from_base(int(col[5 % len(col)]))
                for col in periodic_cols]
    cons = air.constraints(local, nxt, periodic, hops)
    assert any(c != ext.ZERO_H for c in cons)


def test_prove_verify_roundtrip_and_tamper():
    limbs = _limbs()
    air = pair.Poseidon2Air()
    trace = pair.generate_trace(limbs)
    pub = pair.public_inputs(limbs)
    proof = prover.prove(air, trace, pub, PARAMS)
    assert verifier.verify(air, proof, PARAMS)
    # a wrong digest must not verify (the binding property)
    bad_pub = list(proof["pub_inputs"])
    bad_pub[16] = (bad_pub[16] + 1) % bb.P
    bad = {**proof, "pub_inputs": bad_pub}
    with pytest.raises(verifier.VerificationError):
        verifier.verify(air, bad, PARAMS)
    # digest consistency with the framework's compression function
    from ethrex_tpu.ops.merkle import compress_ref
    digest = pub[16:24]
    assert digest == compress_ref(pub[:8], pub[8:16])
