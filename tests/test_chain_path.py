"""Chain-path X-ray (docs/OBSERVABILITY.md "Chain-path telemetry"):
StageQueue accounting + Little's-law cross-check, sampled per-tx
lifecycle records, the bottleneck explainer, loadgen typed-rejection
classification, the inclusion-bench record builder, and the end-to-end
acceptance run — a real-TCP overload where the explainer must name the
admission/producer stage and a sampled lifecycle's hop dwells must sum
to its admitted→included wall."""

import json

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.node import Node
from ethrex_tpu.perf import loadgen
from ethrex_tpu.perf.chain_path import (
    CHAIN_PATH,
    ChainPath,
    StageQueue,
    explain_chain_path,
)
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.rpc.server import RpcServer
from ethrex_tpu.utils.metrics import METRICS

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

GENESIS = {
    "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _tx(nonce, secret=SECRET, chain_id=1337, fee=10**10):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=chain_id, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=fee,
        gas_limit=21_000, to=bytes([0xAA]) * 20, value=1).sign(secret)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# StageQueue

def test_stage_queue_counts_depth_and_drops():
    clk = FakeClock()
    q = StageQueue("t", window=100.0, clock=clk)
    q.arrive(3)
    q.depart(dwell=0.5)                 # a service completion
    q.depart(dropped=True)              # an eviction
    st = q.stats()
    assert st["depth"] == 1
    assert st["arrivals"] == 3
    assert st["departures"] == 1
    assert st["drops"] == 1
    assert st["errors"] == 0
    assert st["meanDwellSeconds"] == 0.5


def test_stage_queue_windowed_rates_and_utilization():
    clk = FakeClock()
    q = StageQueue("t", window=100.0, clock=clk)
    # 10 arrivals over 10s, 5 services: rho = lambda/mu = 2
    for k in range(10):
        clk.t = float(k)
        q.arrive()
        if k % 2:
            q.depart(dwell=1.0)
    clk.t = 10.0
    st = q.stats()
    assert st["arrivalRate"] == pytest.approx(1.0)
    assert st["serviceRate"] == pytest.approx(0.5)
    assert st["utilization"] == pytest.approx(2.0)


def test_stage_queue_little_law_cross_check():
    """Deterministic M/D/1-ish stream: one arrival per second, each
    resident exactly 2s.  Observed time-averaged depth (the exact
    depth-dt integral) must equal lambda * W."""
    clk = FakeClock()
    q = StageQueue("t", window=100.0, clock=clk)
    for t in range(12):
        clk.t = float(t)
        if t < 10:
            q.arrive()
        if 2 <= t:
            q.depart(dwell=2.0)
    clk.t = 12.0
    st = q.stats()
    ll = st["littleLaw"]
    assert ll["predictedDepth"] == pytest.approx(ll["observedDepth"],
                                                 rel=0.01)
    assert ll["ratio"] == pytest.approx(1.0, abs=0.01)


def test_stage_queue_never_raises_on_bad_input():
    q = StageQueue("t", window=100.0)
    q.arrive("garbage")
    q.depart(dwell="also garbage")
    assert q.errors == 2
    q.depart(n=5)                       # departing an empty queue
    assert q.depth == 0                 # clamped, not negative
    assert isinstance(q.stats(), dict)


def test_stage_queue_saturated_utilization_is_inf():
    clk = FakeClock()
    q = StageQueue("t", window=100.0, clock=clk)
    q.arrive(4)
    clk.t = 5.0
    st = q.stats()
    assert st["utilization"] == float("inf")


# ---------------------------------------------------------------------------
# lifecycle sampling

def test_lifecycle_full_pipeline_marks_and_hops():
    clk = FakeClock(100.0)
    cp = ChainPath(sample=1, ring=8, window=1000.0, clock=clk)
    cp.tx_admitted(b"\x01" * 32)
    clk.t = 101.0
    cp.txs_selected([b"\x01" * 32])
    clk.t = 103.0
    cp.block_produced(7, [b"\x01" * 32], build_seconds=2.0)
    clk.t = 106.0
    cp.blocks_batched(3, 7, 7, trace_id="cafebabe")
    clk.t = 110.0
    cp.batch_proved(3)
    clk.t = 115.0
    cp.batches_settled(3)
    [rec] = cp.lifecycles_json()
    assert set(rec["events"]) == {"admitted", "selected", "included",
                                  "batched", "proved", "settled"}
    assert rec["block"] == 7 and rec["batch"] == 3
    assert rec["traceId"] == "cafebabe"
    assert rec["hops"] == {
        "admitted_to_selected": pytest.approx(1.0),
        "selected_to_included": pytest.approx(2.0),
        "included_to_batched": pytest.approx(3.0),
        "batched_to_proved": pytest.approx(4.0),
        "proved_to_settled": pytest.approx(5.0),
    }
    # hop dwells telescope to the end-to-end wall
    assert sum(rec["hops"].values()) == pytest.approx(
        rec["events"]["settled"] - rec["events"]["admitted"])
    # batching stage saw the seal->commit dwell
    assert cp.queues["batching"].stats()["meanDwellSeconds"] == \
        pytest.approx(3.0)


def test_lifecycle_ring_is_bounded_and_sampling_strides():
    cp = ChainPath(sample=2, ring=3, window=100.0, clock=FakeClock())
    for i in range(10):
        cp.tx_admitted(bytes([i]) * 32)
    j = cp.to_json()
    assert j["lifecycle"]["seen"] == 10
    assert j["lifecycle"]["sampled"] == 5      # every 2nd admission
    assert len(j["lifecycle"]["records"]) == 3  # ring-evicted to capacity


def test_backlog_and_stall_are_none_on_idle_or_l1_only():
    clk = FakeClock()
    cp = ChainPath(sample=1, window=100.0, clock=clk)
    assert cp.backlog_seconds() is None          # empty pool
    assert cp.producer_stall_seconds() is None   # never produced
    cp.tx_admitted(b"\x01" * 32)
    # depth > 0 but zero blocks produced: an L1-only follower's pool is
    # not "backlogged" — the signal stays armed-but-silent
    assert cp.backlog_seconds() is None
    assert cp.producer_stall_seconds() is None


def test_backlog_and_stall_fire_under_pressure():
    clk = FakeClock()
    cp = ChainPath(sample=1, window=100.0, clock=clk)
    for i in range(20):
        cp.tx_admitted(bytes([i]) * 32)
    clk.t = 10.0
    cp.block_produced(1, [bytes([0]) * 32], build_seconds=0.1)
    cp.tx_removed(bytes([0]) * 32, "included", dwell=10.0)
    clk.t = 40.0
    # 19 txs left, service rate 1 removal / 40s window
    backlog = cp.backlog_seconds()
    assert backlog is not None and backlog > 0
    stall = cp.producer_stall_seconds()
    assert stall == pytest.approx(30.0)


# ---------------------------------------------------------------------------
# the explainer

def test_explain_idle_names_no_bottleneck():
    cp = ChainPath(sample=1, window=100.0, clock=FakeClock())
    out = explain_chain_path(cp)
    assert out["bottleneck"] is None
    assert "keeping up" in out["verdict"]


def test_explain_names_admission_when_arrivals_never_drain():
    clk = FakeClock()
    cp = ChainPath(sample=0, window=100.0, clock=clk)
    for i in range(50):
        cp.tx_admitted(bytes([i % 256, i // 256]) * 16)
    clk.t = 10.0
    out = explain_chain_path(cp)
    assert out["bottleneck"] == "admission"
    assert out["pressures"]["admission"] == 50
    assert "admission" in out["verdict"]


def test_explain_names_producer_on_stall():
    clk = FakeClock()
    cp = ChainPath(sample=0, window=100.0, clock=clk)
    cp.tx_admitted(b"\x01" * 32)
    clk.t = 1.0
    cp.block_produced(1, [], build_seconds=0.05)
    clk.t = 50.0   # 49s since the last block with a tx still waiting
    out = explain_chain_path(cp)
    assert out["bottleneck"] == "producer"
    assert "stalled" in out["verdict"]


def test_explain_ignores_batching_until_batching_is_active():
    """Sealed blocks that never drain into batches are normal on an
    L1-only node — batching must not be named while zero batches have
    ever been committed."""
    clk = FakeClock()
    cp = ChainPath(sample=0, window=100.0, clock=clk)
    for b in range(5):
        clk.t = float(b)
        cp.block_produced(b, [], build_seconds=0.01)
    clk.t = 30.0
    out = explain_chain_path(cp)
    assert cp.queues["batching"].depth == 5
    assert out["bottleneck"] is None
    assert out["pressures"]["batching"] == 0


def test_chain_path_json_is_strict_json_under_saturation():
    clk = FakeClock()
    cp = ChainPath(sample=1, window=100.0, clock=clk)
    cp.tx_admitted(b"\x01" * 32)
    clk.t = 5.0
    # admission rho is inf here; every surface must still round-trip
    # through a strict (allow_nan=False) JSON serializer
    for payload in (cp.to_json(), cp.health_json(),
                    explain_chain_path(cp)):
        json.loads(json.dumps(payload, allow_nan=False))


# ---------------------------------------------------------------------------
# loadgen typed-rejection classification

def _rej(reason):
    return {"error": {"code": -32000, "message": "no",
                      "data": {"rejected": True, "reason": reason}}}


def _busy():
    return {"error": {"code": -32005, "message": "busy",
                      "data": {"retryAfter": 0.1}}}


def test_rejection_reason_strict_shape():
    assert loadgen.rejection_reason(
        _rej("sender_limit")["error"]) == "sender_limit"
    # untyped -32000, wrong code, empty reason: all None
    assert loadgen.rejection_reason({"code": -32000, "message": "x"}) is None
    assert loadgen.rejection_reason(
        {"code": -32005, "data": {"reason": "x"}}) is None
    assert loadgen.rejection_reason(
        {"code": -32000, "data": {"reason": ""}}) is None
    assert loadgen.rejection_reason("nope") is None


def test_classify_single_responses():
    assert loadgen._classify(_rej("nonce_gap")) == (False, False,
                                                    "nonce_gap")
    assert loadgen._classify(_busy()) == (False, True, None)
    assert loadgen._classify(
        {"error": {"code": -32000, "message": "x"}}) == (True, False, None)
    assert loadgen._classify({"result": "0x1"}) == (False, False, None)


def test_classify_batch_responses():
    ok = {"result": "0x1"}
    # partial service: some entries refused, some served -> delivered
    assert loadgen._classify([ok, _rej("sender_limit")]) == \
        (False, False, None)
    # every entry typed-rejected -> one rejected slot with its reason
    assert loadgen._classify([_rej("fee_below_floor")] * 3) == \
        (False, False, "fee_below_floor")
    # every entry shed -> shed
    assert loadgen._classify([_busy(), _busy()]) == (False, True, None)
    # any untyped error entry -> the whole request is an error
    assert loadgen._classify(
        [_busy(), {"error": {"code": -32603, "message": "boom"}}]) == \
        (True, False, None)
    assert loadgen._classify([]) == (True, False, None)


# ---------------------------------------------------------------------------
# inclusion-bench record builder

def _run_row(tps, err=0.0):
    return {"report": {"offeredRate": 100, "achievedRate": 99,
                       "errorRate": err, "shed": 0, "shedRate": 0.0,
                       "rejected": 2, "rejectionRate": 0.02,
                       "rejections": {"sender_limit": 2}, "missed": 0},
            "blocks": 4, "txsIncluded": int(tps * 3), "includedTps": tps}


def test_build_inclusion_record_headline_prefers_healthy_rates():
    from ethrex_tpu.perf.bench_suite import build_inclusion_record

    rec = build_inclusion_record(
        [_run_row(120.0), _run_row(300.0, err=0.5), _run_row(80.0)],
        queues={"admission": {"depth": 0}},
        explain={"bottleneck": None}, setup_s=1.0, sweep_s=9.0)
    # 300 tps came from a 50%-error run: disqualified
    assert rec["metric"] == "block_inclusion_tps"
    assert rec["value"] == 120.0
    assert rec["unit"] == "tx/s"
    assert rec["backend"] == "cpu"
    assert rec["stages"] == {"setup_s": 1.0, "sweep_s": 9.0}
    assert rec["rates"][0]["rejections"] == {"sender_limit": 2}
    assert rec["queues"]["admission"]["depth"] == 0
    # falls back to best-overall when no rate stayed clean; empty -> 0
    assert build_inclusion_record([_run_row(300.0, err=0.5)])["value"] == 300.0
    assert build_inclusion_record([])["value"] == 0.0


# ---------------------------------------------------------------------------
# node wiring

def test_node_wiring_populates_queues_lifecycles_and_spans():
    from ethrex_tpu.perf import profiler

    CHAIN_PATH.configure(sample=1)
    node = Node(Genesis.from_json(GENESIS))
    try:
        for n in range(5):
            node.submit_transaction(_tx(n))
        blk = node.produce_block()
        assert len(blk.body.transactions) == 5
        j = CHAIN_PATH.to_json()
        adm = j["stages"]["admission"]
        assert adm["arrivals"] == 5 and adm["departures"] == 5
        assert adm["depth"] == 0 and adm["drops"] == 0
        prod = j["stages"]["producer"]
        assert prod["departures"] == 1
        assert j["blocksProduced"] == 1 and j["txsIncluded"] == 5
        assert j["inclusionTps"] > 0
        # every sampled record reached `included` and carries hop dwells
        recs = j["lifecycle"]["records"]
        assert len(recs) == 5
        for rec in recs:
            assert {"admitted", "selected", "included"} <= set(rec["events"])
            assert rec["block"] == blk.header.number
        # the live gauge and the payload profiler spans landed
        assert METRICS.snapshot()["gauges"]["block_inclusion_tps"] > 0
        comp = profiler.PROFILER.tree()["components"]["payload"]
        assert {"drain", "select", "execute", "merkleize",
                "seal"} <= set(comp["stages"])
    finally:
        node.stop()


def test_mempool_time_in_pool_labelled_by_reason():
    node = Node(Genesis.from_json(GENESIS))
    try:
        node.submit_transaction(_tx(0, fee=10**10))
        node.submit_transaction(_tx(0, fee=2 * 10**10))  # replacement
        node.produce_block()                             # includes nonce 0
        hist = METRICS.snapshot()["histograms"][
            "mempool_time_in_pool_seconds"]
        reasons = {tuple(s["labels"].items())[0][1]
                   for s in hist["series"]}
        assert {"replaced", "included"} <= reasons
    finally:
        node.stop()


def test_rpc_send_raw_transaction_carries_typed_rejection():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node)
    try:
        bad = _tx(0, chain_id=999)
        r = server.handle({
            "jsonrpc": "2.0", "id": 1,
            "method": "eth_sendRawTransaction",
            "params": ["0x" + bad.encode_canonical().hex()]})
        err = r["error"]
        assert err["code"] == loadgen.REJECTION_CODE
        assert err["data"]["reason"] == "wrong_chain_id"
        assert loadgen._classify(r) == (False, False, "wrong_chain_id")
    finally:
        node.stop()


# ---------------------------------------------------------------------------
# acceptance: real-TCP overload run

def test_overload_run_names_bottleneck_and_hop_dwells_sum():
    """Saturate the mempool through a real TCP RPC with no producer
    running: the explainer must name the admission (or producer) stage.
    Then drain one block and check a sampled lifecycle's hop dwells sum
    to its admitted->included wall."""
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, host="127.0.0.1", port=0).start()
    try:
        harness = loadgen.Harness(f"http://127.0.0.1:{server.port}",
                                  key=SECRET, senders=4, workers=16,
                                  timeout=5.0, payload="tx")
        harness.setup()
        CHAIN_PATH.configure(sample=1)   # measure the run, not setup
        rep = harness.run(rate=300.0, duration=1.0, arrivals="fixed")
        assert rep["sent"] > 0
        # typed accounting identity survives overload
        assert rep["delivered"] == \
            rep["sent"] - rep["shed"] - rep["rejected"]
        if rep["rejected"]:
            assert rep["rejections"]
            assert sum(rep["rejections"].values()) == rep["rejected"]
        j = CHAIN_PATH.to_json()
        assert j["stages"]["admission"]["depth"] > 0
        out = explain_chain_path(CHAIN_PATH)
        assert out["bottleneck"] in ("admission", "producer")
        assert out["verdict"]

        # drain: seal one block, then audit a sampled included record
        blk = node.produce_block()
        assert len(blk.body.transactions) > 0
        included = [r for r in CHAIN_PATH.lifecycles_json(limit=512)
                    if "included" in r["events"]]
        assert included
        for rec in included:
            wall = rec["events"]["included"] - rec["events"]["admitted"]
            assert sum(rec["hops"].values()) == pytest.approx(
                wall, abs=1e-3)
    finally:
        server.stop()
        node.stop()
