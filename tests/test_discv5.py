"""Node discovery v5 (VERDICT #9): wire codec vectors, ENR signing, the
WHOAREYOU handshake with live UDP servers, and FINDNODE/NODES serving
(reference: crates/networking/p2p/discv5/*,
discovery/discv5_handlers.rs)."""

import time

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.p2p import discv5 as d5


def _wait(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_packet_masking_roundtrip():
    dest_id = bytes(range(32))
    h = d5.Header(0, b"\x07" * 12, b"\xaa" * 32)
    pkt = d5.encode_packet(dest_id, h, b"\x55" * 32,
                           masking_iv=b"\x01" * 16)
    iv, back, msg = d5.decode_packet(dest_id, pkt)
    assert back.flag == 0 and back.nonce == h.nonce
    assert back.authdata == h.authdata and msg == b"\x55" * 32
    # a different local id unmasks to garbage and is rejected
    with pytest.raises(d5.Discv5Error):
        d5.decode_packet(b"\xff" * 32, pkt)


def test_enr_sign_verify_tamper():
    enr = d5.Enr.make(0xBEEF, 7, "127.0.0.1", 30303, tcp_port=30303)
    back = d5.Enr.decode(enr.encode())
    assert back.seq == 7
    assert back.node_id == d5.node_id_from_pubkey(
        secp256k1.pubkey_from_secret(0xBEEF))
    bad = d5.Enr(seq=8, pairs=dict(enr.pairs), signature=enr.signature)
    with pytest.raises(d5.Discv5Error):
        d5.Enr.decode(bad.encode())


def test_session_key_symmetry():
    a_sec, b_sec = 0x1234, 0x5678
    a_pub = secp256k1.pubkey_from_secret(a_sec)
    b_pub = secp256k1.pubkey_from_secret(b_sec)
    a_id = d5.node_id_from_pubkey(a_pub)
    b_id = d5.node_id_from_pubkey(b_pub)
    challenge = b"\xcd" * 63
    eph_sec = 0x9999
    eph_pub = secp256k1.pubkey_from_secret(eph_sec)
    # initiator uses (eph_secret, B_static); recipient (B_secret, eph_pub)
    a_out, a_in = d5.derive_session_keys(eph_sec, b_pub, a_id, b_id,
                                         challenge, is_initiator=True)
    b_out, b_in = d5.derive_session_keys(b_sec, eph_pub, a_id, b_id,
                                         challenge, is_initiator=False)
    assert a_out == b_in and a_in == b_out


def test_id_signature():
    sig = d5.create_id_signature(0xABCD, b"\x01" * 63, b"\x02" * 33,
                                 b"\x03" * 32)
    pub = secp256k1.pubkey_from_secret(0xABCD)
    assert d5.verify_id_signature(pub, b"\x01" * 63, b"\x02" * 33,
                                  b"\x03" * 32, sig)
    assert not d5.verify_id_signature(pub, b"\x01" * 63, b"\x02" * 33,
                                      b"\x04" * 32, sig)


def test_live_handshake_ping_findnode():
    a = d5.Discv5Server(0x1111)
    b = d5.Discv5Server(0x2222)
    c = d5.Discv5Server(0x3333)
    a.start()
    b.start()
    try:
        a.ping(b.enr)
        assert _wait(lambda: any(t == d5.MSG_PONG
                                 for _, t, _ in a.received))
        assert any(t == d5.MSG_PING for _, t, _ in b.received)
        assert b.local_id in a.sessions and a.local_id in b.sessions
        # session reuse: no second handshake
        n = len(b.sessions)
        a.received.clear()
        a.ping(b.enr)
        assert _wait(lambda: any(t == d5.MSG_PONG
                                 for _, t, _ in a.received))
        assert len(b.sessions) == n
        # FINDNODE at c's log2 distance returns its ENR
        b.table[c.enr.node_id] = c.enr
        dist = d5.log2_distance(b.local_id, c.enr.node_id)
        a.received.clear()
        a.find_node(b.enr, [dist])
        assert _wait(lambda: c.enr.node_id in a.table)
    finally:
        a.stop()
        b.stop()
        c.stop()
