"""End-to-end STARK prove/verify on the Fibonacci AIR, plus soundness probes."""

import copy

import numpy as np
import pytest

from ethrex_tpu.models import fibonacci as fib
from ethrex_tpu.stark import prover, verifier
from ethrex_tpu.stark.prover import StarkParams

PARAMS = StarkParams(log_blowup=2, num_queries=16, log_final_size=4)


def _make_proof(n=64):
    air = fib.FibonacciAir()
    trace = fib.generate_trace(n)
    pub = fib.public_inputs(trace)
    proof = prover.prove(air, trace, pub, PARAMS)
    return air, proof


AIR, PROOF = None, None


def _cached():
    global AIR, PROOF
    if PROOF is None:
        AIR, PROOF = _make_proof()
    return AIR, copy.deepcopy(PROOF)


def test_prove_verify_roundtrip():
    air, proof = _cached()
    assert verifier.verify(air, proof, PARAMS)


def test_wrong_public_input_rejected():
    air, proof = _cached()
    proof["pub_inputs"][2] = (proof["pub_inputs"][2] + 1) % (2**31 - 2**27 + 1)
    with pytest.raises(verifier.VerificationError):
        verifier.verify(air, proof, PARAMS)


def test_tampered_trace_root_rejected():
    air, proof = _cached()
    proof["trace_root"][0] ^= 1
    with pytest.raises(verifier.VerificationError):
        verifier.verify(air, proof, PARAMS)


def test_tampered_opening_rejected():
    air, proof = _cached()
    proof["openings"][0]["trace_lo"][0] ^= 1
    with pytest.raises(verifier.VerificationError):
        verifier.verify(air, proof, PARAMS)


def test_tampered_fri_final_rejected():
    air, proof = _cached()
    proof["fri"]["final_coeffs"][0][0] ^= 1
    with pytest.raises(verifier.VerificationError):
        verifier.verify(air, proof, PARAMS)


def test_tampered_zeta_opening_rejected():
    air, proof = _cached()
    proof["trace_at_zeta"][0] = tuple(
        (x + 1) % (2**31 - 2**27 + 1) for x in proof["trace_at_zeta"][0]
    )
    with pytest.raises(verifier.VerificationError):
        verifier.verify(air, proof, PARAMS)


def test_invalid_trace_rejected():
    # a trace violating the transition constraint must not produce a proof
    # that verifies (the quotient is not a polynomial -> identity fails)
    air = fib.FibonacciAir()
    trace = fib.generate_trace(64)
    trace[10, 1] = (int(trace[10, 1]) + 1) % (2**31 - 2**27 + 1)
    pub = fib.public_inputs(trace)
    proof = prover.prove(air, trace, pub, PARAMS)
    with pytest.raises(verifier.VerificationError):
        verifier.verify(air, proof, PARAMS)
