"""Fleet-wide distributed tracing: span shipping over the prover
protocol, merged cross-process batch trees, critical-path attribution,
Perfetto export, and the chaos drills for partial/hedged subtrees
(docs/OBSERVABILITY.md "Distributed tracing")."""

import json
import os
import subprocess
import sys
import time

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.l2.l1_client import InMemoryL1
from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.prover import protocol
from ethrex_tpu.rpc.server import RpcServer
from ethrex_tpu.utils import tracing
from ethrex_tpu.utils.metrics import METRICS
from ethrex_tpu.utils.tracing import (INGEST_SPANS_PER_SOURCE, TRACER,
                                      WIRE_VERSION, Span, Tracer,
                                      critical_path, export_wire,
                                      to_trace_events)

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

GENESIS = {
    "config": {"chainId": 65536999, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _transfer(nonce, value=100):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=65536999, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21000, to=bytes.fromhex("aa" * 20), value=value,
    ).sign(SECRET)


def _committed_sequencer():
    """Node + sequencer with batch 1 committed and the coordinator's TCP
    server running — the fixture every cross-process drill starts from."""
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1(needed_prover_types=[protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,)))
    seq.coordinator.start()
    node.submit_transaction(_transfer(0))
    seq.produce_block()
    assert seq.commit_next_batch() is not None
    return node, seq


def _record(tracer, tid, name, start, seconds, parent=None, span_id=None,
            **attrs):
    """Drop one completed span into a scratch tracer."""
    sp = Span(tid, span_id or tracing.new_span_id(), parent, name, attrs)
    sp.start = start
    sp.seconds = seconds
    tracer.record(sp)
    return sp.span_id


# ---------------------------------------------------------------------------
# wire export


def test_export_wire_payload_shape_and_bounds():
    t = Tracer(capacity=8)
    tid = "ab" * 8
    root = _record(t, tid, "root", 100.0, 5.0)
    for i in range(5):
        _record(t, tid, f"leaf{i}", 100.5 + i, 0.1 * (i + 1), parent=root)
    payload = export_wire(tid, tracer=t)
    assert payload["v"] == WIRE_VERSION
    assert payload["truncated"] is False
    starts = [s["start"] for s in payload["spans"]]
    assert starts == sorted(starts)
    assert len(payload["spans"]) == 6
    # over max_spans the LONGEST spans survive (critical-path fodder)
    small = export_wire(tid, max_spans=2, tracer=t)
    assert small["truncated"] is True
    assert {s["name"] for s in small["spans"]} == {"root", "leaf4"}
    # over max_bytes the list is halved until the payload fits
    tiny = export_wire(tid, max_bytes=400, tracer=t)
    assert tiny["truncated"] is True
    assert len(json.dumps(tiny)) < 400 + 100  # envelope slack
    assert any(s["name"] == "root" for s in tiny["spans"])


def test_export_wire_unknown_or_bad_trace_is_none():
    t = Tracer(capacity=4)
    assert export_wire("ff" * 8, tracer=t) is None
    assert export_wire(None, tracer=t) is None
    assert export_wire(1234, tracer=t) is None
    assert export_wire("", tracer=t) is None


# ---------------------------------------------------------------------------
# ingest / merge


def test_ingest_rejects_malformed_payloads_without_raising():
    t = Tracer(capacity=4)
    for junk in (None, "x", 42, [], {}, {"v": 99, "spans": []},
                 {"v": WIRE_VERSION}, {"v": WIRE_VERSION, "spans": "nope"}):
        assert t.ingest(junk) == 0
    assert len(t) == 0 and t.ingested == 0


def test_ingest_merges_dedupes_and_counts():
    t = Tracer(capacity=8)
    tid = "cd" * 8
    good = {"traceId": tid, "spanId": "s1", "parentId": None,
            "name": "prover.prove", "start": 10.0, "seconds": 2.0,
            "attrs": {"batch": 1}, "status": "ok"}
    bad = {"traceId": tid, "name": "no-span-id", "start": 10.0,
           "seconds": 1.0}
    payload = {"v": WIRE_VERSION, "spans": [good, bad]}
    assert t.ingest(payload, source="prover-a") == 1
    assert t.ingested == 1 and t.ingest_dropped == 1
    rec = t.get_trace(tid)
    assert rec["spans"][0]["source"] == "prover-a"
    assert rec["spans"][0]["attrs"] == {"batch": 1}
    # heartbeat payloads are cumulative: re-shipping is an idempotent no-op
    assert t.ingest(payload, source="prover-a") == 0
    assert len(t.get_trace(tid)["spans"]) == 1


def test_ingest_caps_spans_per_source():
    t = Tracer(capacity=8)
    tid = "ee" * 8
    spans = [{"traceId": tid, "spanId": f"s{i}", "name": "n",
              "start": float(i), "seconds": 0.1} for i in range(300)]
    added = t.ingest({"v": WIRE_VERSION, "spans": spans}, source="chatty")
    assert added == INGEST_SPANS_PER_SOURCE
    assert t.ingest_dropped == 300 - INGEST_SPANS_PER_SOURCE
    # a different source still gets its own allowance on the same trace
    other = [{"traceId": tid, "spanId": f"o{i}", "name": "n",
              "start": float(i), "seconds": 0.1} for i in range(10)]
    assert t.ingest({"v": WIRE_VERSION, "spans": other}, source="b") == 10


def test_rootless_trace_renders_partial_without_skewing_slowest():
    t = Tracer(capacity=8)
    # shipped subtree whose parent never made it into this ring: every
    # span has a parentId, so the trace has no root
    tid = "aa" * 8
    spans = [{"traceId": tid, "spanId": "s1", "parentId": "gone",
              "name": "prover.prove", "start": 0.0, "seconds": 2.0},
             {"traceId": tid, "spanId": "s2", "parentId": "s1",
              "name": "stark.fri_fold", "start": 500.0, "seconds": 0.5}]
    assert t.ingest({"v": WIRE_VERSION, "spans": spans}, source="p") == 2
    # a rooted trace of modest extent
    _record(t, "bb" * 8, "root", 0.0, 3.0)
    slowest = t.slowest(5)
    # the rootless trace reports its longest single span (2.0s), NOT the
    # fabricated 500.5s wall extent — so the rooted 3s trace sorts first
    assert [e["traceId"] for e in slowest] == ["bb" * 8, "aa" * 8]
    partial = slowest[1]
    assert partial["partial"] is True and partial["seconds"] == 2.0
    assert "partial" not in slowest[0]


# ---------------------------------------------------------------------------
# critical-path analysis


def _trace(spans):
    return {"traceId": "t1", "spans": spans}


def _span(sid, name, start, seconds, parent=None, source=None, stage=None):
    s = {"traceId": "t1", "spanId": sid, "parentId": parent, "name": name,
         "start": start, "seconds": seconds}
    if source:
        s["source"] = source
    if stage:
        s["attrs"] = {"stage": stage}
    return s


def test_critical_path_components_sum_to_wall():
    cp = critical_path(_trace([
        _span("a", "prover.assign", 0.0, 10.0),
        _span("p", "prover.prove", 2.0, 6.0, parent="a", source="x"),
        _span("l", "stark.trace_lde", 2.0, 3.0, parent="p", source="x",
              stage="trace_lde"),
        _span("q", "stark.quotient", 5.5, 2.0, parent="p", source="x",
              stage="quotient"),
    ]))
    assert cp["wallSeconds"] == 10.0
    assert abs(sum(cp["components"].values()) - 10.0) < 1e-9
    # stage spans are attributed per-stage; uncovered prove time stays
    # with prove; assign owns the head/tail the prove never covered
    assert abs(cp["components"]["prove/trace_lde"] - 3.0) < 1e-9
    assert abs(cp["components"]["prove/quotient"] - 2.0) < 1e-9
    assert cp["sources"] == ["local", "x"]
    assert cp["partial"] is False
    # the chain is ordered by start and carries component labels
    chain = cp["chain"]
    assert [e["start"] for e in chain] == sorted(e["start"] for e in chain)
    assert {"prover.assign", "prover.prove"} <= {e["name"] for e in chain}


def test_critical_path_gap_is_queue_wait():
    cp = critical_path(_trace([
        _span("a", "prover.assign", 0.0, 3.0),
        _span("v", "proof.verify", 5.0, 5.0),
    ]))
    assert cp["wallSeconds"] == 10.0
    assert abs(cp["components"]["queue-wait"] - 2.0) < 1e-9
    assert abs(cp["components"]["verify"] - 5.0) < 1e-9
    assert abs(sum(cp["components"].values()) - 10.0) < 1e-9


def test_critical_path_hedged_overlap_never_double_counts():
    # hedged batch: two prover subtrees racing over overlapping wall time
    cp = critical_path(_trace([
        _span("p1", "prover.prove", 0.0, 6.0, parent="gone-a", source="a"),
        _span("p2", "prover.prove", 4.0, 6.0, parent="gone-b", source="b"),
    ]))
    assert cp["wallSeconds"] == 10.0
    # 12 span-seconds ran, but only 10 wall-seconds are attributed
    assert abs(sum(cp["components"].values()) - 10.0) < 1e-9
    assert cp["sources"] == ["a", "b"]
    # orphans anchor at top level, so the whole wall is covered by prove
    assert abs(cp["components"]["prove"] - 10.0) < 1e-9
    assert cp["partial"] is True  # every span has a (missing) parent


def test_critical_path_is_defensive():
    assert critical_path(None)["spanCount"] == 0
    assert critical_path({})["components"] == {}
    cp = critical_path({"traceId": "x", "spans": [
        "junk", {"spanId": "no-times"},
        {"spanId": "ok", "name": "n", "start": 1.0, "seconds": 1.0}]})
    assert cp["spanCount"] == 1 and cp["wallSeconds"] == 1.0


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace-event export


def test_trace_events_pids_flows_and_json():
    doc = to_trace_events(_trace([
        _span("a", "prover.assign", 0.0, 10.0),
        _span("p", "prover.prove", 2.0, 6.0, parent="a", source="px"),
        _span("l", "stark.trace_lde", 2.0, 3.0, parent="p", source="px",
              stage="trace_lde"),
    ]))
    events = doc["traceEvents"]
    json.dumps(doc)  # schema-valid JSON all the way down
    xs = {e["name"]: e for e in events if e["ph"] == "X"}
    assert set(xs) == {"prover.assign", "prover.prove", "stark.trace_lde"}
    # local process is pid 1; the remote source gets its own pid
    assert xs["prover.assign"]["pid"] == 1
    assert xs["prover.prove"]["pid"] == xs["stark.trace_lde"]["pid"] == 2
    assert xs["prover.prove"]["dur"] == 6.0 * 1e6
    assert xs["stark.trace_lde"]["args"]["stage"] == "trace_lde"
    metas = {e["args"]["name"] for e in events if e["ph"] == "M"
             and e["name"] == "process_name"}
    assert metas == {"local", "prover:px"}
    # exactly one flow pair crosses the submit seam (assign -> prove);
    # the intra-pid prove -> trace_lde link needs no flow arrow
    starts = [e for e in events if e["ph"] == "s"]
    finishes = [e for e in events if e["ph"] == "f"]
    assert len(starts) == len(finishes) == 1
    assert starts[0]["id"] == finishes[0]["id"]
    assert starts[0]["name"] == finishes[0]["name"] == "submit-seam"
    assert (starts[0]["pid"], finishes[0]["pid"]) == (1, 2)


def test_trace_events_tolerates_garbage():
    # no spans survive filtering: only process metadata remains, and the
    # document still loads
    for junk in (None, {"traceId": "x", "spans": ["junk", {"a": 1}]}):
        doc = to_trace_events(junk)
        assert all(e["ph"] == "M" for e in doc["traceEvents"])
        json.dumps(doc)


# ---------------------------------------------------------------------------
# flagship: a real second process ships its subtree over TCP


_PROVER_SCRIPT = """
import sys, time
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover.client import ProverClient

client = ProverClient(protocol.PROVER_EXEC,
                      [("127.0.0.1", int(sys.argv[1]))],
                      heartbeat_interval=0.05,
                      prover_id="remote-prover", prewarm=False)
deadline = time.monotonic() + 120
while time.monotonic() < deadline:
    if client.poll_once():
        sys.exit(0)
    time.sleep(0.1)
sys.exit(3)
"""


def test_e2e_one_merged_trace_across_processes():
    """The acceptance drill: the prover runs in a SEPARATE process, so
    the spans it ships over TCP are ones this process's ring never saw —
    one batch still renders as one merged cross-process tree, with
    critical-path attribution, a Perfetto export whose flow links cross
    the submit seam, and an exemplar resolving to the trace."""
    node, seq = _committed_sequencer()
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))))
        proc = subprocess.run(
            [sys.executable, "-c", _PROVER_SCRIPT,
             str(seq.coordinator.port)],
            env=env, timeout=300, capture_output=True)
        assert proc.returncode == 0, proc.stderr.decode()[-2000:]
        assert seq.send_proofs() == (1, 1)

        tid = seq.coordinator.batch_traces[1]
        trace = TRACER.get_trace(tid)
        spans = trace["spans"]
        names = {s["name"] for s in spans}
        # local lifecycle spans AND the subprocess's shipped subtree,
        # including its per-stage span, under ONE trace ID
        assert {"prover.assign", "prover.store_proof", "proof.verify",
                "proof.settle", "prover.prove", "prover.execute"} <= names
        shipped = [s for s in spans if s.get("source") == "remote-prover"]
        assert {"prover.prove", "prover.execute"} <= \
            {s["name"] for s in shipped}
        stage_spans = [s for s in shipped
                       if (s.get("attrs") or {}).get("stage")]
        assert stage_spans, "shipped subtree lost its stage spans"
        # the remote subtree reattached: prove's parent is the local
        # assign span
        by_name = {s["name"]: s for s in spans}
        assert by_name["prover.prove"]["parentId"] == \
            by_name["prover.assign"]["spanId"]
        assert TRACER.ingested > 0

        # critical path sums to the wall (acceptance: within 5%)
        cp = critical_path(trace)
        assert cp["wallSeconds"] > 0
        assert abs(sum(cp["components"].values()) - cp["wallSeconds"]) \
            <= 0.05 * cp["wallSeconds"]
        assert cp["sources"] == ["local", "remote-prover"]

        node.sequencer = seq
        server = RpcServer(node)
        r = server.handle({"jsonrpc": "2.0", "id": 1,
                           "method": "ethrex_trace_criticalPath",
                           "params": [tid]})
        assert r["result"]["found"] is True
        assert r["result"]["components"] == cp["components"]
        json.dumps(r)

        # Perfetto export: two processes, flow links across the seam
        r = server.handle({"jsonrpc": "2.0", "id": 2,
                           "method": "ethrex_trace_export",
                           "params": [tid]})
        doc = r["result"]
        assert doc["found"] is True
        json.dumps(doc)
        events = doc["traceEvents"]
        metas = {e["args"]["name"] for e in events if e["ph"] == "M"
                 and e["name"] == "process_name"}
        assert metas == {"local", "prover:remote-prover"}
        flows = [e for e in events if e["ph"] in ("s", "f")]
        by_id = {}
        for e in flows:
            by_id.setdefault(e["id"], []).append(e)
        crossing = [pair for pair in by_id.values()
                    if len(pair) == 2 and pair[0]["pid"] != pair[1]["pid"]]
        assert crossing, "no flow link crosses the submit seam"

        # the batch_proving_seconds exemplar resolves to this trace
        text = METRICS.render()
        exline = [ln for ln in text.splitlines()
                  if ln.startswith("batch_proving_seconds_bucket")
                  and f'trace_id="{tid}"' in ln]
        assert exline, "no exemplar pointing at the merged trace"

        # the per-batch lifecycle timeline surfaced in ethrex_health
        r = server.handle({"jsonrpc": "2.0", "id": 3,
                           "method": "ethrex_health", "params": []})
        lifecycle = r["result"]["l2"]["lifecycle"]
        mine = [e for e in lifecycle if e.get("batch") == 1]
        assert mine and mine[0]["traceId"] == tid
        assert mine[0]["components"]
        # ...and the component histogram fed the alert signals
        assert "batch_critical_path_seconds_bucket" in text
    finally:
        seq.stop()


# ---------------------------------------------------------------------------
# chaos drills (coordinator handlers, no TCP needed)


def test_chaos_prover_death_mid_prove_leaves_partial_subtree():
    """A prover that heartbeats its completed stage spans and then dies
    before submitting still leaves a renderable partial subtree in the
    coordinator's merged trace."""
    node, seq = _committed_sequencer()
    try:
        resp = seq.coordinator.handle_request({
            "type": protocol.INPUT_REQUEST,
            "commit_hash": seq.coordinator.commit_hash,
            "prover_type": protocol.PROVER_EXEC, "prover_id": "doomed"})
        assert resp["type"] == protocol.INPUT_RESPONSE
        tid, parent = resp["trace_id"], resp["span_id"]
        now = time.time()
        payload = {"v": WIRE_VERSION, "spans": [
            {"traceId": tid, "spanId": "dd01", "parentId": parent,
             "name": "prover.prove", "start": now, "seconds": 1.5},
            {"traceId": tid, "spanId": "dd02", "parentId": "dd01",
             "name": "stark.trace_lde", "start": now, "seconds": 0.4,
             "attrs": {"stage": "trace_lde"}},
        ]}
        beat = {"type": protocol.HEARTBEAT, "batch_id": resp["batch_id"],
                "prover_type": protocol.PROVER_EXEC,
                "lease_token": resp["lease_token"],
                "prover_id": "doomed", "spans": payload}
        assert seq.coordinator.handle_request(beat)["ok"] is True
        # the beat is cumulative; a second identical one adds nothing
        before = len(TRACER.get_trace(tid)["spans"])
        seq.coordinator.handle_request(beat)
        assert len(TRACER.get_trace(tid)["spans"]) == before
        # ...and the prover dies here: no submit ever arrives.
        trace = TRACER.get_trace(tid)
        names = {s["name"] for s in trace["spans"]}
        assert {"prover.assign", "prover.prove", "stark.trace_lde"} <= names
        assert all(s["source"] == "doomed" for s in trace["spans"]
                   if s.get("source"))
        cp = critical_path(trace)
        assert abs(sum(cp["components"].values()) - cp["wallSeconds"]) \
            < 1e-6
        assert "prove/trace_lde" in cp["components"]
        # the partial trace renders in the summaries without raising
        assert any(e["traceId"] == tid for e in TRACER.slowest(50))
    finally:
        seq.stop()


def test_chaos_hedged_submits_merge_two_subtrees():
    """Both legs of a hedged race land their subtrees: the winner via a
    leased submit, the loser via the duplicate-submit no-op ACK — two
    prover subtrees under one trace, attribution still sums to wall."""
    from ethrex_tpu.guest.execution import ProgramInput
    from ethrex_tpu.prover.backend import ExecBackend

    node, seq = _committed_sequencer()
    try:
        resp = seq.coordinator.handle_request({
            "type": protocol.INPUT_REQUEST,
            "commit_hash": seq.coordinator.commit_hash,
            "prover_type": protocol.PROVER_EXEC, "prover_id": "prover-a"})
        assert resp["type"] == protocol.INPUT_RESPONSE
        tid, parent = resp["trace_id"], resp["span_id"]
        proof = ExecBackend().prove(
            ProgramInput.from_json(resp["input"]), resp["format"])
        now = time.time()

        def _subtree(prefix, t0, dur):
            return {"v": WIRE_VERSION, "spans": [
                {"traceId": tid, "spanId": f"{prefix}1", "parentId": parent,
                 "name": "prover.prove", "start": t0, "seconds": dur}]}

        ack = seq.coordinator.handle_request({
            "type": protocol.PROOF_SUBMIT, "batch_id": resp["batch_id"],
            "prover_type": protocol.PROVER_EXEC, "proof": proof,
            "lease_token": resp["lease_token"], "prover_id": "prover-a",
            "trace_id": tid, "spans": _subtree("aa", now, 2.0)})
        assert ack["type"] == protocol.SUBMIT_ACK
        # the losing leg: overlapping wall time, duplicate submit, no
        # valid lease — its subtree still merges via the no-op ACK path
        ack = seq.coordinator.handle_request({
            "type": protocol.PROOF_SUBMIT, "batch_id": resp["batch_id"],
            "prover_type": protocol.PROVER_EXEC, "proof": proof,
            "lease_token": None, "prover_id": "prover-b",
            "trace_id": tid, "spans": _subtree("bb", now + 1.0, 2.0)})
        assert ack["type"] == protocol.SUBMIT_ACK

        trace = TRACER.get_trace(tid)
        sources = {s.get("source") for s in trace["spans"]
                   if s.get("source")}
        assert sources == {"prover-a", "prover-b"}
        cp = critical_path(trace)
        # overlapping subtrees, yet every wall second is attributed once
        assert abs(sum(cp["components"].values()) - cp["wallSeconds"]) \
            < 1e-6
        assert {"local", "prover-a", "prover-b"} <= set(cp["sources"])
        metas = {e["args"]["name"]
                 for e in to_trace_events(trace)["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"}
        assert {"local", "prover:prover-a", "prover:prover-b"} <= metas
    finally:
        seq.stop()


# ---------------------------------------------------------------------------
# serving overhead


def test_span_shipping_overhead_under_two_percent():
    """Span shipping must not show up in the serving tail: each hop —
    export_wire in the prover process, ingest in the coordinator process
    (no single serving thread ever pays both) — must cost under 2% of
    the p99@30-connection serving reference (~7.8ms), i.e. ~156us, for
    a realistic ~64-span trace."""
    t = Tracer(capacity=8)
    tid = "ab" * 8
    root = _record(t, tid, "prover.prove", 100.0, 5.0)
    for i in range(63):
        _record(t, tid, f"stark.stage{i}", 100.0 + i * 0.05, 0.05,
                parent=root, stage=f"s{i % 8}")
    budget = 0.02 * 0.0078
    payload = export_wire(tid, tracer=t)
    assert len(payload["spans"]) == 64
    best_export = best_ingest = float("inf")
    for _ in range(100):
        t0 = time.perf_counter()
        export_wire(tid, tracer=t)
        best_export = min(best_export, time.perf_counter() - t0)
        sink = Tracer(capacity=8)
        t0 = time.perf_counter()
        sink.ingest(payload, source="p")
        best_ingest = min(best_ingest, time.perf_counter() - t0)
    assert best_export < budget, \
        f"export cost {best_export * 1e6:.0f}us > 156us budget"
    assert best_ingest < budget, \
        f"ingest cost {best_ingest * 1e6:.0f}us > 156us budget"


def test_bench_measure_reports_critical_path():
    """The headline --measure record carries a critical_path breakdown
    next to stages (statically, like the stages lint: the full prove is
    a slow-bench, not a tier-1 test)."""
    import ast
    import inspect

    from ethrex_tpu.perf import bench_suite

    tree = ast.parse(inspect.getsource(bench_suite))
    fn = next(n for n in tree.body
              if isinstance(n, ast.FunctionDef) and n.name == "measure")
    keys = {k.value for node in ast.walk(fn) if isinstance(node, ast.Dict)
            for k in node.keys if isinstance(k, ast.Constant)}
    assert "critical_path" in keys and "stages" in keys
    # and the breakdown comes from the tracing walker, not a hand-rolled
    # sum that could drift from the RPC's attribution
    assert "critical_path" in inspect.getsource(bench_suite.measure)
