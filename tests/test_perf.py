"""Performance-observability battery (docs/PERFORMANCE.md): the
continuous stage profiler, roofline accounting, the perf surfaces
(metrics exposition, snapshot, ethrex_perf RPC, monitor panel, alert
floors), and the bench suite's CPU fallback + history regression gate.

The never-raise drills matter most: every perf hook sits inside the
prover or import hot path, so a malformed cost_analysis() or a broken
jax.profiler must degrade to missing telemetry, never a failed prove."""

import json
import os

import pytest

from ethrex_tpu.perf import bench_suite, profiler, roofline
from ethrex_tpu.utils import tracing
from ethrex_tpu.utils.metrics import (
    METRICS, observe_import_stage, record_import_throughput,
    record_kernel_flops, record_proof_wall, record_prover_throughput)


# ---------------------------------------------------------------------------
# stage profiler

def test_profiler_accumulates_and_builds_tree():
    p = profiler.StageProfiler()
    p.record("l1_import", "execute", 0.5)
    p.record("l1_import", "execute", 1.5)
    p.record("l1_import", "merkleize", 2.0)
    tree = p.tree()
    comp = tree["components"]["l1_import"]
    assert comp["totalSeconds"] == pytest.approx(4.0)
    ex = comp["stages"]["execute"]
    assert ex["count"] == 2
    assert ex["totalSeconds"] == pytest.approx(2.0)
    assert ex["meanSeconds"] == pytest.approx(1.0)
    assert ex["maxSeconds"] == pytest.approx(1.5)
    assert ex["lastSeconds"] == pytest.approx(1.5)
    assert ex["share"] == pytest.approx(0.5)
    assert p.stage_totals("l1_import") == {
        "execute": pytest.approx(2.0), "merkleize": pytest.approx(2.0)}
    assert tree["droppedKeys"] == 0
    p.reset()
    assert p.tree() == {"components": {}, "droppedKeys": 0}


def test_profiler_never_raises_and_bounds_cardinality():
    p = profiler.StageProfiler()
    # garbage seconds must be swallowed, not raised (hot-path contract)
    p.record("c", "s", "not-a-number")
    p.record("c", "s", None)
    p.record(object(), object(), 1.0)   # coerced via str(), still lands
    assert "c" not in p.tree()["components"]  # bad rows never landed
    # runaway label cardinality is clamped at MAX_KEYS
    p2 = profiler.StageProfiler()
    for i in range(profiler.MAX_KEYS + 7):
        p2.record("c", f"stage{i}", 0.001)
    tree = p2.tree()
    assert len(tree["components"]["c"]["stages"]) == profiler.MAX_KEYS
    assert tree["droppedKeys"] == 7


def test_span_observer_folds_stages_by_component():
    with tracing.span("prove.quotient", stage="quotient"):
        pass
    with tracing.span("backend.execute", stage="execute"):
        pass
    with tracing.span("novel.thing", stage="brand_new_stage"):
        pass
    comps = profiler.PROFILER.tree()["components"]
    assert "quotient" in comps["stark"]["stages"]
    assert "execute" in comps["prover"]["stages"]
    assert "brand_new_stage" in comps["other"]["stages"]


def test_raising_stage_observer_cannot_break_spans():
    def bomb(name, stage, seconds):
        raise RuntimeError("observer bomb")

    tracing.STAGE_OBSERVERS.append(bomb)
    try:
        with tracing.span("prove.quotient", stage="quotient"):
            pass
    finally:
        tracing.STAGE_OBSERVERS.remove(bomb)
    # the well-behaved observer after/before the bomb still recorded
    comps = profiler.PROFILER.tree()["components"]
    assert "quotient" in comps.get("stark", {}).get("stages", {})


def test_capture_is_noop_without_destination_and_never_raises(
        tmp_path, monkeypatch):
    import jax

    profiler.configure(None)
    assert profiler.configured_dir() is None
    with profiler.capture("prove") as cap:
        assert cap._started is False          # no dir -> transparent no-op

    # a broken jax.profiler must not break the wrapped body
    def boom(*a, **kw):
        raise RuntimeError("profiler plugin broken")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    profiler.configure(str(tmp_path / "traces"))
    ran = []
    with profiler.capture("prove"):
        ran.append(True)
    assert ran == [True]
    assert profiler._TRACE_ACTIVE is False    # slot released for next try


def test_capture_is_single_flight(tmp_path, monkeypatch):
    import jax

    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.__setitem__(
                            "start", calls["start"] + 1))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.__setitem__("stop", calls["stop"] + 1))
    profiler.configure(str(tmp_path))
    with profiler.capture("outer"):
        with profiler.capture("inner"):   # nested: degrades to no-op
            pass
        assert calls == {"start": 1, "stop": 0}
    assert calls == {"start": 1, "stop": 1}


# ---------------------------------------------------------------------------
# roofline

def test_parse_cost_tolerates_every_shape():
    pc = roofline._parse_cost
    assert pc(None) == {"flops": None, "bytes": None}
    assert pc([]) == {"flops": None, "bytes": None}
    assert pc(["garbage", 42]) == {"flops": None, "bytes": None}
    assert pc({"flops": "NaN-ish"}) == {"flops": None, "bytes": None}
    assert pc([{"flops": 5.0}]) == {"flops": 5.0, "bytes": None}
    assert pc({"bytes accessed": 7}) == {"flops": None, "bytes": 7.0}
    # list-of-dicts (jax 0.4.x): entries sum
    assert pc([{"flops": 2, "bytes accessed": 3},
               {"flops": 4}]) == {"flops": 6.0, "bytes": 3.0}


def test_roofline_partial_cost_yields_null_fields_not_errors():
    roofline.record_cost("A", "commit", None)
    roofline.record_cost("A", "quotient", [{"bytes accessed": 64.0}])
    roofline.record_wall("A", "commit", 0.25)
    rep = roofline.ROOFLINE.report()
    by_kernel = {k["kernel"]: k for k in rep["kernels"]
                 if k["air"] == "A"}
    commit = by_kernel["commit"]
    assert commit["flops"] is None
    assert commit["wallLastSeconds"] == pytest.approx(0.25)
    assert commit["achievedFlopsPerSec"] is None
    assert commit["utilizationVsPeak"] is None
    quotient = by_kernel["quotient"]
    assert quotient["bytes"] == 64.0
    assert quotient["intensityFlopsPerByte"] is None
    # module-level hooks swallow even structurally hostile input
    roofline.record_cost("A", "open", object())
    roofline.record_wall("A", "open", "not-a-float")


def test_roofline_report_and_gauges_with_calibrated_peak(monkeypatch):
    monkeypatch.setenv("ETHREX_PEAK_FLOPS", "1e9")
    roofline.record_cost(
        "FibAir", "commit", [{"flops": 2.0e9, "bytes accessed": 1.0e6}])
    roofline.record_wall("FibAir", "commit", 2.0)
    rep = roofline.ROOFLINE.report()
    assert rep["peakFlopsEstimate"] == 1e9
    assert rep["peakSource"] == "env"
    (k,) = [k for k in rep["kernels"] if k["air"] == "FibAir"]
    assert k["achievedFlopsPerSec"] == pytest.approx(1.0e9)
    assert k["utilizationVsPeak"] == pytest.approx(1.0)
    assert k["intensityFlopsPerByte"] == pytest.approx(2000.0)
    # the live gauges were exported with full labels
    text = METRICS.render()
    assert ('prover_kernel_flops{air="FibAir",stage="commit"} '
            "2000000000.0") in text
    assert ('prover_kernel_achieved_flops_per_sec'
            '{air="FibAir",stage="commit"}') in text
    assert ('prover_kernel_utilization{air="FibAir",stage="commit"} '
            "1.0") in text


def test_peak_estimate_fallbacks(monkeypatch):
    monkeypatch.delenv("ETHREX_PEAK_FLOPS", raising=False)
    assert roofline.peak_flops_estimate("cpu") == roofline._cpu_peak()
    assert roofline.peak_flops_estimate("tpu") == 275.0e12
    assert roofline.peak_flops_estimate("quantum") is None
    monkeypatch.setenv("ETHREX_PEAK_FLOPS", "not-a-number")
    assert roofline.peak_flops_estimate("tpu") == 275.0e12  # bad env ignored


# ---------------------------------------------------------------------------
# metrics exposition (golden lines)

def test_perf_metric_families_render_with_help_text():
    observe_import_stage("execute", 0.1)
    observe_import_stage("merkleize", 0.2)
    record_import_throughput(12.5)
    record_prover_throughput(3.0e6)
    record_proof_wall(7200.0)
    record_kernel_flops("Air", "deep", 1000.0, 500.0, 0.25)
    text = METRICS.render()
    assert "# HELP block_import_stage_seconds" in text
    assert '# TYPE block_import_stage_seconds histogram' in text
    # exposition shape, not exact counts: the process-global registry
    # may carry residue recorded between tests (thread teardown etc.)
    assert 'block_import_stage_seconds_bucket{stage="execute"' in text
    assert 'block_import_stage_seconds_count{stage="merkleize"}' in text
    assert "# HELP l1_import_mgas_per_sec" in text
    assert "l1_import_mgas_per_sec 12.5" in text
    assert "prover_trace_cells_per_sec 3000000.0" in text
    assert "proofs_per_hour 0.5" in text
    assert "# HELP prover_kernel_flops" in text
    assert 'prover_kernel_flops{air="Air",stage="deep"} 1000.0' in text


def test_record_proof_wall_guards_nonpositive():
    before = METRICS.gauges.get("proofs_per_hour")
    record_proof_wall(0.0)
    record_proof_wall(-5.0)
    assert METRICS.gauges.get("proofs_per_hour") == before


# ---------------------------------------------------------------------------
# import-path stage attribution (pipelined)

def test_pipelined_import_attributes_substages():
    from ethrex_tpu.blockchain.blockchain import Blockchain
    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis
    from ethrex_tpu.primitives.transaction import Transaction
    from ethrex_tpu.storage.store import Store

    secret = 0xA11CE
    sender = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(secret))
    genesis = {
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + sender.hex(): {"balance": hex(10**21)}},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }
    node = Node(Genesis.from_json(genesis))
    nonce = 0
    blocks = []
    for _ in range(3):
        for _ in range(4):
            node.submit_transaction(Transaction(
                tx_type=2, chain_id=1337, nonce=nonce,
                max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
                gas_limit=21_000, to=bytes([0x42]) * 20,
                value=100 + nonce).sign(secret))
            nonce += 1
        blocks.append(node.produce_block())

    store = Store()
    store.init_genesis(Genesis.from_json(genesis))
    chain = Blockchain(store, node.config)
    before = profiler.PROFILER.stage_totals("l1_import")
    chain.add_blocks_pipelined(blocks)
    after = profiler.PROFILER.stage_totals("l1_import")
    for stage in ("execute", "merkleize", "store_write"):
        assert after.get(stage, 0.0) > before.get(stage, 0.0), stage
    # the same legs flow into the labelled histogram
    hist = METRICS.histograms["block_import_stage_seconds"]
    seen = {dict(labels)["stage"] for labels in hist.series}
    assert {"execute", "merkleize", "store_write"} <= seen
    # and the pipelined wall updates the live throughput gauge
    assert METRICS.gauges.get("l1_import_mgas_per_sec", 0.0) > 0.0
    # the EVM split recorded under the evm component during execution
    evm_stages = profiler.PROFILER.stage_totals("evm")
    assert evm_stages.get("sig_recovery", 0.0) > 0.0
    assert evm_stages.get("opcode_loop", 0.0) > 0.0


# ---------------------------------------------------------------------------
# a real (tiny) prove populates roofline + profiler + throughput

def test_tiny_prove_populates_roofline_and_profiler():
    from ethrex_tpu.models import fibonacci as fib
    from ethrex_tpu.stark import prover, verifier
    from ethrex_tpu.stark.prover import StarkParams

    params = StarkParams(log_blowup=2, num_queries=16, log_final_size=4)
    # force an AOT rebuild so cost_analysis lands even when an earlier
    # test already compiled these phases (cost is recorded at build)
    prover._PHASE_CACHE.clear()
    air = fib.FibonacciAir()
    trace = fib.generate_trace(64)
    proof = prover.prove(air, trace, fib.public_inputs(trace), params)
    assert verifier.verify(air, proof, params)

    rep = roofline.ROOFLINE.report()
    kernels = {k["kernel"]: k for k in rep["kernels"]
               if k["air"] == "FibonacciAir"}
    assert set(kernels) >= {"commit", "quotient", "open", "deep"}
    with_cost = [k for k in kernels.values() if k["flops"]]
    assert with_cost, "no kernel captured a static cost"
    assert all(k["wallCount"] >= 1 for k in kernels.values())
    assert any(k["achievedFlopsPerSec"] for k in with_cost)

    comps = profiler.PROFILER.tree()["components"]
    assert {"merkle_commit", "quotient", "fri_fold", "query"} <= set(
        comps["stark"]["stages"])
    assert METRICS.gauges.get("prover_trace_cells_per_sec", 0.0) > 0.0
    # the full stack shows up on every surface: exposition...
    assert 'prover_kernel_flops{air="FibonacciAir"' in METRICS.render()
    # ...the flight-recorder snapshot...
    from ethrex_tpu.utils import snapshot
    bundle = snapshot.collect(None, reason="test")
    assert "stark" in bundle["perf"]["profiler"]["components"]
    assert bundle["perf"]["roofline"]["kernels"]


# ---------------------------------------------------------------------------
# RPC + health + monitor surfaces

def _l1_node():
    from ethrex_tpu.crypto import secp256k1
    from ethrex_tpu.node import Node
    from ethrex_tpu.primitives.genesis import Genesis

    secret = 0xA11CE
    sender = secp256k1.pubkey_to_address(
        secp256k1.pubkey_from_secret(secret))
    return Node(Genesis.from_json({
        "config": {"chainId": 1337, "terminalTotalDifficulty": 0,
                   "shanghaiTime": 0, "cancunTime": 0},
        "alloc": {"0x" + sender.hex(): {"balance": hex(10**21)}},
        "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7",
        "timestamp": "0x0",
    }))


def test_ethrex_perf_rpc_degrades_gracefully_on_l1_only_node():
    from ethrex_tpu.rpc.server import RpcServer

    server = RpcServer(_l1_node())
    resp = server.handle({"jsonrpc": "2.0", "id": 1,
                          "method": "ethrex_perf", "params": []})
    perf = resp["result"]
    assert perf["enabled"] is True
    # an L1-only node that never proved still answers with valid,
    # merely-empty sections — never an RPC error
    assert "components" in perf["profiler"]
    assert perf["roofline"]["kernels"] == []
    assert set(perf["throughput"]) == {
        "l1_import_mgas_per_sec", "prover_trace_cells_per_sec",
        "proofs_per_hour"}
    assert all(v is None for v in perf["throughput"].values())

    # once gauges exist they flow through verbatim
    record_import_throughput(42.0)
    perf = server.handle({"jsonrpc": "2.0", "id": 2,
                          "method": "ethrex_perf",
                          "params": []})["result"]
    assert perf["throughput"]["l1_import_mgas_per_sec"] == 42.0

    health = server.handle({"jsonrpc": "2.0", "id": 3,
                            "method": "ethrex_health",
                            "params": []})["result"]
    assert health["perf"]["kernelsProfiled"] == 0
    assert health["perf"]["maxUtilizationVsPeak"] is None
    assert isinstance(health["perf"]["componentsProfiled"], list)


def test_monitor_perf_panel_renders_and_degrades():
    from ethrex_tpu.utils.monitor import _perf_lines

    # no ethrex_perf (older node) and disabled both yield no panel
    assert _perf_lines({"perf": None}, 100) == []
    assert _perf_lines({"perf": {"enabled": False}}, 100) == []
    snap = {"perf": {
        "enabled": True,
        "throughput": {"l1_import_mgas_per_sec": 12.5,
                       "prover_trace_cells_per_sec": 3.1e6,
                       "proofs_per_hour": None},
        "profiler": {"components": {
            "stark": {"totalSeconds": 8.0, "stages": {
                "fri_fold": {"totalSeconds": 6.0, "share": 0.75},
                "quotient": {"totalSeconds": 2.0, "share": 0.25}}}}},
        "roofline": {"kernels": [
            {"air": "FibonacciAir", "kernel": "quotient",
             "flops": 3.9e7, "utilizationVsPeak": 0.37}]},
    }}
    lines = _perf_lines(snap, 100)
    text = "\n".join(lines)
    assert " performance" in text
    assert "12.5 Mgas/s" in text
    assert "stark" in text and "fri_fold 75%" in text
    assert "FibonacciAir" in text and "37.0%" in text


def test_throughput_floor_alerts_fire_below_not_above():
    from ethrex_tpu.utils.alerts import AlertEngine, AlertRule

    value = {"v": None}
    rule = AlertRule(
        name="floor:warn", severity="warn",
        signal=lambda eng, node: value["v"], threshold=0.1,
        for_count=2, resolve_count=1, below=True)
    eng = AlertEngine(rules=[rule])
    eng.evaluate()                      # None: a never-sampled gauge
    assert eng.active() == []           # must not alert (idle L1 node)
    value["v"] = 5.0
    eng.evaluate()
    eng.evaluate()
    assert eng.active() == []           # healthy throughput, above floor
    value["v"] = 0.05
    eng.evaluate()
    assert eng.active() == []           # first breach: pending only
    eng.evaluate()
    (alert,) = eng.active()
    assert alert["name"] == "floor:warn"
    assert alert["below"] is True
    value["v"] = 5.0
    eng.evaluate()
    assert eng.active() == []           # recovered


def test_default_rules_include_throughput_floors():
    from ethrex_tpu.utils.alerts import default_rules

    by_name = {r.name: r for r in default_rules(None)}
    assert by_name["l1_import_throughput_floor:warn"].below is True
    assert by_name["prover_throughput_floor:warn"].below is True


# ---------------------------------------------------------------------------
# bench suite: CPU fallback + history + regression gate

_HEADLINE = {
    "metric": "transfer_batch_prove_wall_s", "value": 12.3, "unit": "s",
    "vs_baseline": 0.02, "batch_gas": 210000, "num_txs": 10,
    "stages": {"execute": 1.0, "state_proof": 9.0},
}


def _wire_bench(monkeypatch, tmp_path, *, detect, probe_err, cpu_err):
    monkeypatch.setattr(bench_suite, "HISTORY_PATH",
                        str(tmp_path / "history.jsonl"))
    monkeypatch.setattr(bench_suite, "LAST_PATH",
                        str(tmp_path / "last.json"))
    monkeypatch.setattr(bench_suite, "ATTEMPTS", 2)
    monkeypatch.setattr(bench_suite.time, "sleep", lambda s: None)
    monkeypatch.setattr(bench_suite, "detect_backend", lambda: detect)
    monkeypatch.setattr(bench_suite, "probe_backend_error",
                        lambda: probe_err)
    monkeypatch.setattr(bench_suite, "probe_cpu_error", lambda: cpu_err)
    monkeypatch.setattr(
        bench_suite, "_mgas_config",
        lambda: {"metric": "l1_import_mgas_per_sec", "value": 30.0,
                 "stages": {"execute": 1.0, "merkleize": 0.5,
                            "store_write": 0.2}})
    monkeypatch.setattr(
        bench_suite, "_core_config",
        lambda: {"metric": "stark_prove_core_trace_cells_per_sec",
                 "value": 2.0e6})
    monkeypatch.delenv("BENCH_ALLOW_CPU", raising=False)
    monkeypatch.delenv("BENCH_SKIP_EXTRAS", raising=False)


def _history(tmp_path):
    with open(tmp_path / "history.jsonl") as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def test_bench_dead_tunnel_falls_back_to_forced_cpu(
        monkeypatch, tmp_path, capsys):
    """A present-but-BROKEN plugin (detect_backend None, every chip probe
    failing) must still yield a REAL forced-CPU record — the dead-tunnel
    fix — and that record is never cached as a chip baseline."""
    _wire_bench(monkeypatch, tmp_path, detect=None,
                probe_err="RuntimeError: tunnel is dead", cpu_err=None)
    calls = []

    def fake_attempt(flag, timeout):
        calls.append((flag, os.environ.get("BENCH_ALLOW_CPU")))
        return dict(_HEADLINE)

    monkeypatch.setattr(bench_suite, "_attempt", fake_attempt)
    bench_suite.main()
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["backend"] == "cpu"
    assert record["value"] == 12.3
    assert "degraded" not in record
    assert "tunnel is dead" in record["fallback_reason"]
    assert record["stages"]["state_proof"] == 9.0
    # the fallback prove ran with the forced-CPU escape hatch armed
    assert calls == [("--measure", "1")]
    # sub-records still attached: mgas with its import attribution + core
    assert record["configs"]["mgas"]["stages"]["merkleize"] == 0.5
    assert record["configs"]["core"]["value"] == 2.0e6
    # appended to history, NOT cached as a chip record
    (entry,) = _history(tmp_path)
    assert entry["backend"] == "cpu" and "ts" in entry
    assert not (tmp_path / "last.json").exists()


def test_bench_cpu_only_host_runs_upfront_fallback(
        monkeypatch, tmp_path, capsys):
    """ABSENT chip (jax says backend=cpu): no probe retries, the headline
    runs on CPU immediately."""
    _wire_bench(monkeypatch, tmp_path, detect="cpu",
                probe_err=None, cpu_err=None)
    monkeypatch.setattr(bench_suite, "_attempt",
                        lambda flag, timeout: dict(_HEADLINE))
    bench_suite.main()
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["backend"] == "cpu"
    assert "fallback_reason" not in record
    assert "degraded" not in record
    assert not (tmp_path / "last.json").exists()
    assert len(_history(tmp_path)) == 1


def test_bench_degrades_only_when_even_cpu_is_broken(
        monkeypatch, tmp_path, capsys):
    _wire_bench(monkeypatch, tmp_path, detect=None,
                probe_err="RuntimeError: tunnel is dead",
                cpu_err="ImportError: jaxlib hosed")
    monkeypatch.setattr(bench_suite, "_attempt",
                        lambda flag, timeout: {"_err": "should not run"})
    bench_suite.main()
    record = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert record["degraded"] is True
    assert record["value"] == 0.0
    assert "tunnel is dead" in record["error"]
    # degraded replays are poison for the gate: excluded from the series
    (entry,) = _history(tmp_path)
    assert entry["degraded"] is True
    assert bench_suite._history_series("transfer_batch_prove_wall_s") == []


def test_history_series_and_same_backend_gate(
        monkeypatch, tmp_path, capsys):
    monkeypatch.setattr(bench_suite, "HISTORY_PATH",
                        str(tmp_path / "history.jsonl"))
    wall = "transfer_batch_prove_wall_s"
    cells = "stark_prove_core_trace_cells_per_sec"
    bench_suite.append_history(
        {"metric": wall, "value": 10.0, "backend": "tpu",
         "configs": {"core": {"metric": cells, "value": 100.0}}})
    bench_suite.append_history(
        {"metric": wall, "value": 25.0, "backend": "tpu",
         "configs": {"core": {"metric": cells, "value": 40.0}}})
    assert bench_suite._history_series(wall) == [
        ("tpu", 10.0), ("tpu", 25.0)]
    # sub-config metrics are first-class series entries
    assert bench_suite._history_series(cells) == [
        ("tpu", 100.0), ("tpu", 40.0)]

    # wall is lower-is-better: 10s -> 25s is a 0.4 ratio, a regression
    code = bench_suite.check_history_metric(wall, 0.8,
                                            lower_is_better=True)
    out = json.loads(capsys.readouterr().out.strip())
    assert (code, out["status"]) == (2, "regression")
    assert out["ratio"] == pytest.approx(0.4)
    # cells is higher-is-better: 100 -> 40 also regresses
    assert bench_suite.check_history_metric(cells, 0.8) == 2
    capsys.readouterr()

    # a CPU-fallback record must NOT be judged against the chip numbers
    bench_suite.append_history(
        {"metric": wall, "value": 500.0, "backend": "cpu"})
    code = bench_suite.check_history_metric(wall, 0.8,
                                            lower_is_better=True)
    out = json.loads(capsys.readouterr().out.strip())
    assert (code, out["status"]) == (0, "no-baseline")
    assert out["backend"] == "cpu"
    # a second cpu record forms a same-backend pair
    bench_suite.append_history(
        {"metric": wall, "value": 510.0, "backend": "cpu"})
    code = bench_suite.check_history_metric(wall, 0.8,
                                            lower_is_better=True)
    out = json.loads(capsys.readouterr().out.strip())
    assert (code, out["status"]) == (0, "ok")
    assert out["baseline"] == 500.0 and out["current"] == 510.0

    # torn trailing line (crash mid-append) must not kill the gate
    with open(tmp_path / "history.jsonl", "a") as f:
        f.write('{"metric": "transfer_batch_pro')
    assert len(bench_suite._history_series(wall)) == 4


def test_check_regression_suite_worst_code_wins(monkeypatch):
    def codes(mgas, wall, cells):
        monkeypatch.setattr(bench_suite, "check_regression",
                            lambda threshold: mgas)
        monkeypatch.setattr(
            bench_suite, "check_history_metric",
            lambda metric, threshold, lower_is_better=False:
                wall if "wall" in metric else cells)
        return bench_suite.check_regression_suite()

    assert codes(0, 0, 0) == 0
    assert codes(1, 0, 0) == 1       # broken measurement: error, not pass
    assert codes(0, 2, 0) == 2       # headline wall regressed
    assert codes(1, 0, 2) == 2       # regression outranks error
