"""L2 settlement through the OnChainProposer BYTECODE executed by our
own EVM (VERDICT #8): the full pipeline — sequencer -> commit tx ->
TCP prover -> verify tx with the STATICCALL'd verifier — against
l2/l1_evm.EvmL1, plus the contract's revert identities."""

import json

import pytest

from ethrex_tpu.guest.execution import ProgramOutput
from ethrex_tpu.l2.l1_client import L1Error
from ethrex_tpu.l2.l1_evm import EvmL1
from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover.client import ProverClient

from tests.test_l2_pipeline import DEPOSITEE, GENESIS, _transfer


def test_full_pipeline_settles_through_bytecode():
    node = Node(Genesis.from_json(GENESIS))
    l1 = EvmL1([protocol.PROVER_EXEC], l2_chain_id=65536999)
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,)))
    seq.coordinator.start()
    try:
        l1.deposit(DEPOSITEE, 5 * 10**18)
        seq.watch_l1()
        node.submit_transaction(_transfer(0))
        block1 = seq.produce_block()
        assert any(tx.tx_type == 0x7E for tx in block1.body.transactions)
        batch = seq.commit_next_batch()
        assert batch.number == 1
        # the CONTRACT's storage is the source of truth
        assert l1.last_committed_batch() == 1
        assert l1.last_verified_batch() == 0
        client = ProverClient(protocol.PROVER_EXEC,
                              [("127.0.0.1", seq.coordinator.port)])
        assert client.poll_once() == 1
        assert seq.send_proofs() == (1, 1)
        assert l1.last_verified_batch() == 1
        # contract kept the committed state root
        assert l1._slot(0) == 1
    finally:
        seq.stop()


def _fake_proof(root, msgs=b"\x00" * 32):
    out = ProgramOutput(
        initial_state_root=b"\x00" * 32, final_state_root=root,
        last_block_hash=b"\x33" * 32, first_block_number=1,
        last_block_number=1, messages_root=msgs)
    return json.dumps({"backend": "exec",
                       "output": "0x" + out.encode().hex()}).encode()


def test_bytecode_revert_identities():
    l1 = EvmL1([protocol.PROVER_EXEC])
    with pytest.raises(L1Error, match="BatchNumberNotSuccessor"):
        l1.commit_batch(5, b"\x11" * 32, b"\x22" * 32)
    with pytest.raises(L1Error, match="CommitHashIsZero"):
        l1.commit_batch(1, b"\x11" * 32, b"\x00" * 32)
    l1.commit_batch(1, b"\x11" * 32, b"\x22" * 32)
    l1.commit_batch(2, b"\x44" * 32, b"\x55" * 32)
    with pytest.raises(L1Error, match="BatchNotSequential"):
        l1.verify_batches(2, 2,
                          {protocol.PROVER_EXEC: [_fake_proof(b"\x44" * 32)]})
    with pytest.raises(L1Error, match="InvalidProof"):
        l1.verify_batches(1, 1,
                          {protocol.PROVER_EXEC: [_fake_proof(b"\x99" * 32)]})
    # multi-batch verify in ONE call; second has a bad proof -> the whole
    # tx reverts and lastVerified is untouched (contract-enforced
    # atomicity, the Solidity semantics the Python port emulates)
    with pytest.raises(L1Error, match="InvalidProof"):
        l1.verify_batches(1, 2, {protocol.PROVER_EXEC: [
            _fake_proof(b"\x11" * 32), _fake_proof(b"\x00" * 32)]})
    assert l1.last_verified_batch() == 0
    l1.verify_batches(1, 2, {protocol.PROVER_EXEC: [
        _fake_proof(b"\x11" * 32), _fake_proof(b"\x44" * 32)]})
    assert l1.last_verified_batch() == 2


def test_bytecode_pause_and_ownership():
    from ethrex_tpu.l2.l1_evm import OWNER
    from ethrex_tpu.l2.proposer_evm import SEL_PAUSE

    l1 = EvmL1([protocol.PROVER_EXEC])
    # non-owner cannot pause or commit
    with pytest.raises(L1Error, match="OwnableUnauthorizedAccount"):
        l1._tx(SEL_PAUSE.to_bytes(4, "big"), sender=b"\xbb" * 20)
    l1._tx(SEL_PAUSE.to_bytes(4, "big"), sender=OWNER)
    with pytest.raises(L1Error, match="EnforcedPause"):
        l1.commit_batch(1, b"\x11" * 32, b"\x22" * 32)
    from ethrex_tpu.l2.proposer_evm import SEL_UNPAUSE

    l1._tx(SEL_UNPAUSE.to_bytes(4, "big"), sender=OWNER)
    l1.commit_batch(1, b"\x11" * 32, b"\x22" * 32)
    assert l1.last_committed_batch() == 1
