"""Fault-injection battery for the fleet scheduler and the aggregation
pipeline (docs/AGGREGATION.md): scheduler crashes mid-decision
("coordinator.schedule"), aggregation crashes on either side of the
recursion build ("aggregate.prove"), and the losing leg of a hedged
assignment ("submit.duplicate") — plus the hedging/steal unit drills
(straggler re-assigned past the p99 deadline, first result wins, the
original's duplicate submit no-op-acked without burning quarantine
budget) and the FCFS policy flag.

Select alone with `-m chaos`; the whole battery is in the fast tier.
"""

import time

import pytest

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.l2.aggregator import INFLIGHT_META_KEY, ProofAggregator
from ethrex_tpu.l2.l1_client import InMemoryL1
from ethrex_tpu.l2.proof_coordinator import ProofCoordinator
from ethrex_tpu.l2.rollup_store import RollupStore
from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover.client import ProverClient
from ethrex_tpu.utils import faults
from ethrex_tpu.utils.faults import FaultPlan, InjectedFault

pytestmark = pytest.mark.chaos

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))
OTHER = bytes.fromhex("aa" * 20)
EXEC = protocol.PROVER_EXEC

GENESIS = {
    "config": {"chainId": 65536999, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _transfer(nonce, value=100):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=65536999, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21000, to=OTHER, value=value,
    ).sign(SECRET)


def _mini_l2(batches=1, **cfg_kw):
    """Real Node + sequencer + live TCP coordinator with `batches`
    committed batches ready for provers to pull."""
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1([EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(EXEC,), **cfg_kw))
    seq.coordinator.start()
    for i in range(batches):
        node.submit_transaction(_transfer(i))
        seq.produce_block()
        assert seq.commit_next_batch() is not None
    return node, l1, seq


def _endpoints(seq):
    return [("127.0.0.1", seq.coordinator.port)]


def _prove_all(seq, batches, deadline_s=10.0):
    client = ProverClient(EXEC, _endpoints(seq), heartbeat_interval=0,
                          backoff_base=0.01, rng_seed=0)
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        client.poll_once()
        if all(seq.rollup.get_proof(n, EXEC) is not None
               for n in range(1, batches + 1)):
            return
        time.sleep(0.02)
    raise AssertionError(f"batches 1..{batches} never fully proven")


# ===========================================================================
# coordinator.schedule — scheduler crash / slow decision
# ===========================================================================

def test_schedule_crash_drops_connection_then_recovers():
    """A scheduler crash inside assign() drops the prover's connection
    before any lease is granted; the prover backs off, retries, and the
    batch is proven — no lease or failure accounting is left behind."""
    node, l1, seq = _mini_l2()
    co = seq.coordinator
    try:
        plan = faults.install(
            FaultPlan(seed=21).error("coordinator.schedule", times=1))
        client = ProverClient(EXEC, _endpoints(seq),
                              heartbeat_interval=0, backoff_base=0.01,
                              rng_seed=5)
        assert client.poll_once() == 0       # scheduler crashed mid-decide
        assert co.assignments == {}          # no lease was granted
        assert co.failures == {}             # and no failure was charged
        assert plan.log == [("coordinator.schedule", "error")]
        time.sleep(0.03)                     # clear the client backoff
        _prove_all(seq, 1)
        assert seq.send_proofs() == (1, 1)
        assert l1.last_verified_batch() == 1
    finally:
        faults.clear()
        seq.stop()


def test_schedule_delay_slow_decision_still_grants():
    """A slow scheduling decision (delay rule) stalls the poll but the
    grant still lands with a usable lease token."""
    node, l1, seq = _mini_l2()
    try:
        faults.install(
            FaultPlan(seed=22).delay("coordinator.schedule", 0.2, times=1))
        client = ProverClient(EXEC, _endpoints(seq),
                              heartbeat_interval=0, rng_seed=6)
        t0 = time.time()
        assert client.poll_once() == 1
        assert time.time() - t0 >= 0.2
        assert seq.rollup.get_proof(1, EXEC) is not None
    finally:
        faults.clear()
        seq.stop()


# ===========================================================================
# aggregate.prove — crash on either leg of the recursion build
# ===========================================================================

def _proven_run(batches=2):
    node, l1, seq = _mini_l2(batches=batches)
    _prove_all(seq, batches)
    agg = ProofAggregator(seq.rollup, l1, coordinator=seq.coordinator,
                          needed_types=[EXEC], min_batches=2)
    return node, l1, seq, agg


def test_aggregate_crash_before_build_then_recovers():
    """A crash BEFORE the aggregate is built loses only work: nothing
    reached the L1, no inflight marker is left, and the retry settles the
    whole run as one aggregated proof."""
    node, l1, seq, agg = _proven_run()
    try:
        faults.install(
            FaultPlan(seed=23).error("aggregate.prove", times=1))
        with pytest.raises(InjectedFault):
            agg.step()
        assert l1.last_verified_batch() == 0
        assert seq.rollup.get_meta(INFLIGHT_META_KEY) is None
        faults.clear()
        assert agg.step() == (1, 2)
        assert l1.last_verified_batch() == 2
        assert l1.aggregated_settlements == 1
        assert seq.rollup.get_batch(1).verified
        assert seq.rollup.get_batch(2).verified
    finally:
        faults.clear()
        seq.stop()


def test_aggregate_crash_after_build_before_settlement():
    """after=1 targets the second leg: the aggregate was built but the
    settlement never went out — the L1 is untouched, no marker is stuck,
    and the retry re-builds and settles (the range is L1-anchored, so
    double-settling is structurally impossible)."""
    node, l1, seq, agg = _proven_run()
    try:
        plan = faults.install(
            FaultPlan(seed=24).error("aggregate.prove", times=1, after=1))
        with pytest.raises(InjectedFault):
            agg.step()
        assert plan.log == [("aggregate.prove", "error")]
        assert l1.last_verified_batch() == 0
        assert l1.aggregated_settlements == 0
        assert seq.rollup.get_meta(INFLIGHT_META_KEY) is None
        faults.clear()
        assert agg.step() == (1, 2)
        assert l1.last_verified_batch() == 2
        # one settlement for the whole run, not one per batch
        assert l1.aggregated_settlements == 1
        assert l1.proofs_settled_aggregated == 2
    finally:
        faults.clear()
        seq.stop()


# ===========================================================================
# hedged re-assignment drills (fake clock) + submit.duplicate
# ===========================================================================

def _bare_coordinator(batches=1, **kw):
    store = RollupStore()
    for n in range(1, batches + 1):
        store.store_prover_input(n, protocol.PROTOCOL_VERSION, {"stub": n})
    kw.setdefault("needed_types", [EXEC])
    kw.setdefault("verify_submissions", False)
    return store, ProofCoordinator(store, **kw)


def _submit(co, batch, token, prover_id=None):
    msg = {"type": protocol.PROOF_SUBMIT, "batch_id": batch,
           "prover_type": EXEC, "lease_token": token,
           "proof": {"backend": EXEC, "output": "0x" + "00" * 176}}
    if prover_id is not None:
        msg["prover_id"] = prover_id
    return co.handle_request(msg)


def test_hedged_straggler_first_result_wins_duplicate_noop(monkeypatch):
    """The chaos drill from the issue: a straggler holds the primary
    lease past the p99-derived deadline; an idle prover is granted a
    hedge with its OWN token; the hedge wins the race; the straggler's
    late submit hits the duplicate path — no-op SUBMIT_ACK, no lease or
    quarantine mutation — even when a "submit.duplicate" fault kills the
    ack mid-flight first."""
    store, co = _bare_coordinator(hedge_min_samples=4, hedge_factor=1.5)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    co.durations.extend([1.0, 1.0, 1.0, 1.0])    # p99=1s -> deadline 1.5s

    batch, tok_slow = co.assign(EXEC, "slow-prover")
    assert (batch, tok_slow is not None) == (1, True)
    # inside the deadline: no hedge yet (the lease itself is live)
    t[0] = 1.0
    assert co.assign(EXEC, "fast-prover") == (None, None)
    # past p99 * factor: the idle prover gets a hedge with its own token
    t[0] = 2.0
    hbatch, tok_fast = co.assign(EXEC, "fast-prover")
    assert hbatch == 1 and tok_fast not in (None, tok_slow)
    assert co.hedged_assignments_total == 1
    assert co.hedges[(1, EXEC)]["reason"] == "straggler"
    # one hedge at a time per batch; never hedge the holder against itself
    assert co.assign(EXEC, "third-prover") == (None, None)
    assert co.assign(EXEC, "slow-prover") == (None, None)
    # the hedge holder can feed its own lease with its own token
    hb = co.handle_request({"type": protocol.HEARTBEAT, "batch_id": 1,
                            "prover_type": EXEC, "lease_token": tok_fast})
    assert hb["ok"] is True
    # ... and the primary's token still feeds the primary lease
    hb = co.handle_request({"type": protocol.HEARTBEAT, "batch_id": 1,
                            "prover_type": EXEC, "lease_token": tok_slow})
    assert hb["ok"] is True

    # first result wins: the hedge submits first
    t[0] = 3.0
    r = _submit(co, 1, tok_fast, prover_id="fast-prover")
    assert r["type"] == protocol.SUBMIT_ACK
    assert store.get_proof(1, EXEC) is not None
    assert co.hedges == {} and (1, EXEC) not in co.assignments
    # the winner's proving clock started at the HEDGE grant (t=2 -> t=3)
    assert co.prover_stats["fast-prover"]["completed"] == 1
    assert abs(co.prover_stats["fast-prover"]["ewma"] - 1.0) < 1e-9
    assert any(e["event"] == "proof-stored"
               and e.get("detail") == "hedge won" for e in co.events)

    # the straggler finally finishes; its submit is a duplicate.  A
    # fault that kills the no-op ack drops the connection but mutates
    # nothing; the plain retry is acknowledged.
    faults.install(FaultPlan(seed=31).error("submit.duplicate", times=1))
    try:
        with pytest.raises(InjectedFault):
            _submit(co, 1, tok_slow, prover_id="slow-prover")
    finally:
        faults.clear()
    r = _submit(co, 1, tok_slow, prover_id="slow-prover")
    assert r["type"] == protocol.SUBMIT_ACK
    assert co.duplicate_submits_total == 2       # both attempts counted
    # the loser burned NO failure/quarantine budget and lost no lease
    assert co.failures == {}
    assert co.quarantined == set()
    assert co.rejected_submits_total == 0
    assert co.stale_submits_total == 0
    # the stored proof is still the winner's (first write wins)
    assert store.get_proof(1, EXEC) is not None


def test_hedge_rejected_submit_burns_no_quarantine_budget(monkeypatch):
    """An INVALID proof from the hedge holder costs the hedge its lease
    but charges nothing against the batch: the primary keeps proving and
    the quarantine budget is untouched."""
    store, co = _bare_coordinator(verify_submissions=True,
                                  hedge_min_samples=2, hedge_factor=1.0,
                                  quarantine_threshold=2)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    co.durations.extend([0.5, 0.5])
    batch, tok_p = co.assign(EXEC, "primary")
    assert batch == 1
    t[0] = 1.0
    hbatch, tok_h = co.assign(EXEC, "hedger")
    assert hbatch == 1
    r = co.handle_request({"type": protocol.PROOF_SUBMIT, "batch_id": 1,
                           "prover_type": EXEC, "lease_token": tok_h,
                           "proof": {"backend": "__corrupt__"}})
    assert r["type"] == protocol.ERROR and "invalid proof" in r["message"]
    assert co.hedges == {}                       # hedge lease revoked
    assert (1, EXEC) in co.assignments           # primary lease intact
    assert co.failures == {} and co.quarantined == set()
    assert co.rejected_submits_total == 1


def test_work_steal_from_overloaded_prover(monkeypatch):
    """An idle prover steals (hedges) a batch from a holder sitting on
    steal_threshold live leases, without waiting for the p99 deadline."""
    store, co = _bare_coordinator(batches=2, steal_threshold=2)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    assert co.assign(EXEC, "busy")[0] == 1
    assert co.assign(EXEC, "busy")[0] == 2
    # no duration samples -> the straggler path is disarmed; only the
    # steal rule can hedge, and only for an idle requester
    batch, token = co.assign(EXEC, "idle")
    assert batch in (1, 2) and token is not None
    assert co.hedges[(batch, EXEC)]["reason"] == "steal"
    # the overloaded holder itself cannot steal its own work back
    assert co.assign(EXEC, "busy") == (None, None)


def test_size_aware_placement_fast_gets_heavy(monkeypatch):
    """Fleet placement: with EWMA stats on both sides, the fastest prover
    is steered to the heaviest waiting batch and the slowest to the
    lightest."""
    store = RollupStore()
    light = {"blocks": [{"transactions": []}]}              # weight 1
    heavy = {"blocks": [{"transactions": [{}] * 9}] * 2}    # weight 20
    store.store_prover_input(1, protocol.PROTOCOL_VERSION, light)
    store.store_prover_input(2, protocol.PROTOCOL_VERSION, heavy)
    co = ProofCoordinator(store, needed_types=[EXEC],
                          verify_submissions=False)
    co.prover_stats["fast"] = {"completed": 3, "ewma": 1.0, "last_seen": 0}
    co.prover_stats["slow"] = {"completed": 3, "ewma": 9.0, "last_seen": 0}
    assert co.assign(EXEC, "fast")[0] == 2     # heaviest first
    assert co.assign(EXEC, "slow")[0] == 1
    assert co.queue_depth in (0, 1)            # depth sampled pre-grant


def test_fcfs_policy_flag_pins_original_behavior(monkeypatch):
    """scheduler_policy="fcfs" keeps the original scan: oldest batch
    first regardless of stats, and NO hedging even past the deadline."""
    store = RollupStore()
    light = {"blocks": [{"transactions": []}]}
    heavy = {"blocks": [{"transactions": [{}] * 9}] * 2}
    store.store_prover_input(1, protocol.PROTOCOL_VERSION, light)
    store.store_prover_input(2, protocol.PROTOCOL_VERSION, heavy)
    co = ProofCoordinator(store, needed_types=[EXEC],
                          verify_submissions=False,
                          scheduler_policy="fcfs",
                          hedge_min_samples=2, hedge_factor=1.0)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    co.prover_stats["fast"] = {"completed": 3, "ewma": 1.0, "last_seen": 0}
    co.prover_stats["slow"] = {"completed": 3, "ewma": 9.0, "last_seen": 0}
    co.durations.extend([0.1, 0.1])
    # FCFS: the fast prover still gets the OLDEST batch, not the heaviest
    assert co.assign(EXEC, "fast")[0] == 1
    assert co.assign(EXEC, "slow")[0] == 2
    # way past any deadline: still no hedge under fcfs
    t[0] = 100.0
    t[0] = min(100.0, co.lease_timeout - 1)    # keep both leases live
    assert co.assign(EXEC, "idle") == (None, None)
    assert co.hedged_assignments_total == 0
    # and an unknown policy is rejected outright
    with pytest.raises(ValueError):
        ProofCoordinator(store, scheduler_policy="lifo")


def test_scheduler_state_in_stats_json(monkeypatch):
    """The coordinator's health payload carries the scheduler section the
    monitor panel and ethrex_health render."""
    store, co = _bare_coordinator(batches=2)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    co.durations.extend([1.0] * co.hedge_min_samples)
    assert co.assign(EXEC, "p1")[0] == 1
    sched = co.stats_json()["scheduler"]
    assert sched["policy"] == "fleet"
    assert sched["hedgedAssignments"] == 0
    assert sched["duplicateSubmits"] == 0
    assert sched["queueDepth"] == 1            # batch 2 still waiting
    assert sched["hedgeDeadlineSeconds"] == pytest.approx(
        co.hedge_factor * 1.0)
    assert sched["provers"]["p1"]["liveLeases"] == 1


# ===========================================================================
# warm-aware handoff — cold-start routing after a fleet restart
# ===========================================================================

def test_cold_prover_deferred_while_warm_peer_absorbs(monkeypatch):
    """Restart scenario: a warm peer is known and can absorb the queue,
    so a prover that explicitly reports warm=False sits out the poll and
    the batch lands on the warm prover instead."""
    store, co = _bare_coordinator(batches=2)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    assert co.assign(EXEC, "warm-p", warm=True)[0] is not None
    # one batch left, one recently-seen warm peer: the cold prover waits
    assert co.assign(EXEC, "cold-p", warm=False) == (None, None)
    assert co.cold_deferrals_total == 1
    sched = co.stats_json()["scheduler"]
    assert sched["coldDeferrals"] == 1
    assert sched["provers"]["warm-p"]["warm"] is True
    assert sched["provers"]["cold-p"]["warm"] is False
    assert sched["provers"]["cold-p"]["coldDeferrals"] == 1
    # the warm prover comes back for the deferred batch
    assert co.assign(EXEC, "warm-p", warm=True)[0] is not None
    # empty queue afterwards: the cold prover's (None, None) is not a
    # deferral, so the counter does not creep
    assert co.assign(EXEC, "cold-p", warm=False) == (None, None)
    assert co.cold_deferrals_total == 1


def test_cold_deferral_cap_prevents_starvation(monkeypatch):
    """A fleet whose warm capacity never shows up must not starve the
    cold prover: after COLD_DEFERRAL_CAP consecutive sit-outs it is fed,
    and reporting warm=True resets the budget."""
    from ethrex_tpu.l2.proof_coordinator import COLD_DEFERRAL_CAP

    store, co = _bare_coordinator(batches=2)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    assert co.assign(EXEC, "warm-p", warm=True)[0] is not None
    for _ in range(COLD_DEFERRAL_CAP):
        assert co.assign(EXEC, "cold-p", warm=False) == (None, None)
    batch, token = co.assign(EXEC, "cold-p", warm=False)
    assert batch is not None and token is not None   # cap reached: fed
    assert co.cold_deferrals_total == COLD_DEFERRAL_CAP
    # hydration finished: the warm report clears the deferral budget
    co.assign(EXEC, "cold-p", warm=True)
    st = co.prover_stats["cold-p"]
    assert st["cold_deferrals"] == 0 and st["warm"] is True


def test_legacy_client_without_warm_flag_never_deferred(monkeypatch):
    store, co = _bare_coordinator(batches=2)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    assert co.assign(EXEC, "warm-p", warm=True)[0] is not None
    # an older client sends no warm flag at all: fed immediately
    batch, token = co.assign(EXEC, "legacy-p")
    assert batch is not None and token is not None
    assert co.cold_deferrals_total == 0


def test_stale_warm_peer_does_not_defer_cold(monkeypatch):
    """A warm peer last seen outside WARM_PEER_WINDOW is not live warm
    capacity — the cold prover gets the batch."""
    from ethrex_tpu.l2.proof_coordinator import WARM_PEER_WINDOW

    store, co = _bare_coordinator(batches=2)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    assert co.assign(EXEC, "warm-p", warm=True)[0] is not None
    t[0] = WARM_PEER_WINDOW + 1.0
    batch, token = co.assign(EXEC, "cold-p", warm=False)
    assert batch is not None and token is not None
    assert co.cold_deferrals_total == 0


def test_fcfs_policy_never_defers_cold(monkeypatch):
    store, co = _bare_coordinator(batches=2, scheduler_policy="fcfs")
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    assert co.assign(EXEC, "warm-p", warm=True)[0] == 1
    batch, token = co.assign(EXEC, "cold-p", warm=False)
    assert batch == 2 and token is not None
    assert co.cold_deferrals_total == 0


def test_cold_granted_wall_excluded_from_ewma_and_durations(monkeypatch):
    """The compile-inclusive first wall of a cold-granted batch must not
    poison the EWMA placement signal or the p99 hedge-deadline window;
    the first warm proof is the first EWMA sample."""
    store, co = _bare_coordinator(batches=2)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    # no warm peers around: the cold prover is fed immediately
    batch, token = co.assign(EXEC, "restarted", warm=False)
    assert batch == 1 and token is not None
    t[0] = 300.0                        # compile-inclusive first wall
    r = _submit(co, batch, token, "restarted")
    assert r["type"] == protocol.SUBMIT_ACK
    st = co.prover_stats["restarted"]
    assert st["completed"] == 1
    assert st["ewma"] is None           # 300 s never entered the EWMA
    assert list(co.durations) == []     # nor the hedge-deadline window
    # hydrated now: the steady-state wall is the first placement sample
    batch2, tok2 = co.assign(EXEC, "restarted", warm=True)
    assert batch2 == 2
    t[0] = 308.0
    assert _submit(co, batch2, tok2, "restarted")["type"] == \
        protocol.SUBMIT_ACK
    st = co.prover_stats["restarted"]
    assert st["completed"] == 2
    assert st["ewma"] == pytest.approx(8.0)
    assert list(co.durations) == [pytest.approx(8.0)]


def test_warm_flag_parsed_from_input_request_wire(monkeypatch):
    """The warm flag rides the INPUT_REQUEST wire message; a deferred
    cold prover sees TYPE_NOT_NEEDED, and a non-bool warm value from a
    hostile client is ignored rather than crashed on."""
    store, co = _bare_coordinator(batches=2)
    t = [0.0]
    monkeypatch.setattr(co, "_now", lambda: t[0])
    assert co.assign(EXEC, "warm-p", warm=True)[0] is not None
    r = co.handle_request({"type": protocol.INPUT_REQUEST,
                           "commit_hash": protocol.PROTOCOL_VERSION,
                           "prover_type": EXEC, "prover_id": "cold-p",
                           "warm": False})
    assert r["type"] == protocol.TYPE_NOT_NEEDED
    assert co.cold_deferrals_total == 1
    r = co.handle_request({"type": protocol.INPUT_REQUEST,
                           "commit_hash": protocol.PROTOCOL_VERSION,
                           "prover_type": EXEC, "prover_id": "odd-p",
                           "warm": "yes"})
    assert r["type"] == protocol.INPUT_RESPONSE
    assert co.cold_deferrals_total == 1


def test_client_reports_warm_after_first_proof():
    """End-to-end over the real wire: the exec backend hydrates nothing,
    so the client's first InputRequest is cold (its wall excluded from
    the durations window) and every request after its first proof
    reports warm."""
    node, l1, seq = _mini_l2(batches=2)
    co = seq.coordinator
    try:
        client = ProverClient(EXEC, _endpoints(seq), heartbeat_interval=0,
                              backoff_base=0.01, rng_seed=9)
        assert client._prewarm_done.wait(5.0)
        assert client.warm is False
        deadline = time.time() + 10.0
        while time.time() < deadline and len(client.proved) < 2:
            client.poll_once()
            time.sleep(0.01)
        assert len(client.proved) == 2
        assert client.warm is True
        assert co.prover_stats[client.prover_id]["warm"] is True
        # the cold-granted first batch stayed out of the durations window
        assert len(co.durations) == 1
    finally:
        seq.stop()
