"""Replay of a REAL Hoodi testnet block (1265656, 4.4 Mgas, 11 txs incl.
Groth16 verifier calls hitting ecAdd/ecMul/ecPairing) from the reference's
cached witness — the ethrex-replay conformance path
(/root/reference/docs/ethrex_replay/ethrex_replay.md).

Ground truth established by oracle probing (receipts-root sweeps + header
logs-bloom membership + state-root sweeps, round 2):
  * txs 0-2, 5 (blob transfers): exactly 21000 each.
  * tx 9: exactly the EIP-7623 floor (28130).
  * txs 4, 6, 8, 10 match the chain's gas exactly (their sum + header
    arithmetic pins them; every log address/topic we emit is present in the
    header bloom).
  * txs 3 and 7 relay the SAME bridge message; on-chain tx 3 FAILED (its
    receiver address appears in NO header-bloom log position) and tx 7
    succeeded — our replay reproduces exactly that shape.

Round-3 deep diagnosis of the residual (supersedes the round-2 note):

  * The block's relay txs route fees through one shared beacon-proxied
    paymaster implementation (0xd15d6cf0be3d...).  It brackets the relay
    with `startGas = gasleft()` (depth 2) ... `used = startGas - gasleft()`
    (depth 4, across two delegatecall boundaries) and emits a gas-derived
    refund: amount = used*price + used*price/4 with price 0xe4ba2f80.
  * Our tx4 measures used = 785,959 (0xbfe27); the header bloom has
    exactly THREE bits not covered by our logs ({1565, 1819, 1857}) and
    exactly ONE of our items absent from the bloom (our tx4 refund
    topic).  Sweeping `used` over 400k..1.2M, a single value reproduces
    those three bits: used' = 787,216 — the chain consumed EXACTLY
    1,257 more gas than us inside the paymaster bracket (p < 1e-8 of a
    bloom false positive over that sweep).
  * Simulating a flat 1,257 surcharge at the paymaster impl entry makes
    the tx4 refund amount byte-exact vs the bloom and shifts txs 4/6/7/8
    by +1,257 each, leaving an 838 residual on the header total.
    5,866 = 14 x 419 and 1,257 = 3 x 419 suggest a per-iteration
    419-gas undercharge (3 relayers in txs 4/6/7), but no distribution
    of 419-quanta over the txs matches the RECEIPTS ROOT, and the state
    root also stays off after balance-only corrections — so some log
    DATA or storage value (fee quotes / token payouts) still differs
    from the chain beyond pure gas.
  * Audits that came back CLEAN: every formulaic charge in tx4
    (keccak/copy/log/exp/memory-expansion recomputed independently, 0
    mismatches), precompile prices (ecAdd 150, ecMul 6000, pairing
    45k+34k*k), the diamond-router dispatch SLOAD/cold-account charges,
    intrinsic gas, and the 63/64 forwarding chain (cap inversions are
    integer-consistent at every boundary).
  * The dying tx3 frame burns its whole 161,467 allocation (OOG at an
    SSTORE_SET with 12,368 left), so tx3's total is INSENSITIVE to
    in-frame charges; its on-chain 816,911 implied a different
    distribution across txs 4/6/7/8 all along — round 2's "tx 4/6/8
    match exactly" was an artifact of attributing the whole residual to
    tx3.  The hard oracles are header.gas_used, receipts_root,
    state_root, and the bloom — the per-tx pins below reflect OUR
    current measured values and the bloom-proven tx4 refund.
"""

import json
import os

import pytest

from ethrex_tpu.blockchain.blockchain import Blockchain
from ethrex_tpu.crypto.keccak import keccak256
from ethrex_tpu.evm.db import StateDB
from ethrex_tpu.evm.executor import execute_tx
from ethrex_tpu.evm.vm import BlockEnv
from ethrex_tpu.guest.execution import WitnessSource, _GuestChainView
from ethrex_tpu.primitives.genesis import ChainConfig
from ethrex_tpu.primitives.receipt import logs_bloom
from ethrex_tpu.utils.replay import load_cache

CACHE = "/root/reference/fixtures/cache/rpc_prover/cache_hoodi_1265656.json"
GENESIS = "/root/reference/cmd/ethrex/networks/hoodi/genesis.json"


def _bloom_has(bloom: bytes, item: bytes) -> bool:
    h3 = keccak256(item)
    for i in (0, 2, 4):
        bit = ((h3[i] << 8) | h3[i + 1]) & 0x7FF
        if not (bloom[256 - 1 - bit // 8] >> (bit % 8)) & 1:
            return False
    return True


@pytest.mark.skipif(not os.path.exists(CACHE),
                    reason="reference cache not available")
def test_hoodi_block_replay():
    with open(GENESIS) as f:
        cfg = ChainConfig.from_json(json.load(f)["config"])
    pi = load_cache(CACHE, cfg)
    blk = pi.blocks[0]
    h = blk.header
    w = pi.witness
    nodes = {keccak256(bytes(n)): bytes(n) for n in w.nodes}
    codes = {keccak256(bytes(c)): bytes(c) for c in w.codes}
    headers = {x.number: x for x in w.block_headers}
    parent = w.block_headers[-1]
    assert parent.hash == h.parent_hash  # witness linkage

    chain = Blockchain(_GuestChainView(), cfg)
    fork = cfg.fork_at(h.number, h.timestamp)
    env = BlockEnv(
        number=h.number, coinbase=h.coinbase, timestamp=h.timestamp,
        gas_limit=h.gas_limit, prev_randao=h.prev_randao,
        base_fee=h.base_fee_per_gas or 0,
        excess_blob_gas=h.excess_blob_gas or 0,
        parent_beacon_block_root=h.parent_beacon_block_root or b"\x00" * 32)
    source = WitnessSource(nodes, codes, headers, parent.state_root)
    state = StateDB(source)
    chain._pre_tx_system_ops(state, env, h, fork)
    results = [execute_tx(tx, state, env, cfg)
               for tx in blk.body.transactions]

    # per-tx gas pins for OUR implementation (drift detectors).  The blob
    # transfers and the EIP-7623-floor tx are chain-exact by construction;
    # the relay txs 4/6/7/8 are our measured values — the chain's are
    # +1257-ish each (see module docstring), tracked via the residual.
    gases = [r.gas_used for r in results]
    assert gases[:3] == [21000] * 3
    assert gases[5] == 21000
    assert gases[9] == 28130          # EIP-7623 floor, byte-exact
    assert gases[4] == 828658
    assert gases[6] == 818616
    assert gases[7] == 818602
    assert gases[8] == 921210
    assert gases[10] == 86820
    # status shape: tx3 (first relay of the duplicated message) fails,
    # tx7 (the second relay) succeeds — exactly as on-chain
    assert [r.success for r in results] == [
        True, True, True, False, True, True, True, True, True, True, True]
    assert gases[3] == 811045, "tx3 residual changed — retighten this test"
    total = sum(gases)
    assert h.gas_used - total == 5866, (
        f"aggregate residual changed: {h.gas_used - total}")

    # bloom structure: our logs cover ALL header-bloom bits except exactly
    # the three belonging to the true (chain) tx4 refund amount, and our
    # only spurious item is our own tx4 refund amount — the paymaster
    # gas-metering divergence is the SOLE topic-level log delta.
    have = {n for n in range(2048)
            if (h.bloom[256 - 1 - n // 8] >> (n % 8)) & 1}

    def _bits(item: bytes) -> set:
        h3 = keccak256(item)
        return {((h3[i] << 8) | h3[i + 1]) & 0x7FF for i in (0, 2, 4)}

    ours = set()
    spurious = []
    for i, r in enumerate(results):
        for log in r.logs:
            for item in [log.address] + [bytes(t) for t in log.topics]:
                ours |= _bits(item)
                if not _bloom_has(h.bloom, item):
                    spurious.append((i, item))
    assert have - ours == {1565, 1819, 1857}
    assert len(spurious) == 1 and spurious[0][0] == 4
    our_amt = int.from_bytes(spurious[0][1], "big")

    # the chain's refund amount reproduces those three bits at
    # used' = 787,216 = our measured 785,959 + 1,257 (and at no other
    # used value nearby) — the bracket divergence is pinned to the gas
    price = 0xE4BA2F80
    assert our_amt == 785959 * price + 785959 * price // 4
    chain_amt = 787216 * price + 787216 * price // 4
    assert _bits(chain_amt.to_bytes(32, "big")) == {1565, 1819, 1857}
