"""Replay of a REAL Hoodi testnet block (1265656, 4.4 Mgas, 11 txs incl.
Groth16 verifier calls hitting ecAdd/ecMul/ecPairing) from the reference's
cached witness — the ethrex-replay conformance path
(/root/reference/docs/ethrex_replay/ethrex_replay.md).

Ground truth established by oracle probing (receipts-root sweeps + header
logs-bloom membership + state-root sweeps, round 2):
  * txs 0-2, 5 (blob transfers): exactly 21000 each.
  * tx 9: exactly the EIP-7623 floor (28130).
  * txs 4, 6, 8, 10 match the chain's gas exactly (their sum + header
    arithmetic pins them; every log address/topic we emit is present in the
    header bloom).
  * txs 3 and 7 relay the SAME bridge message; on-chain tx 3 FAILED (its
    receiver address appears in NO header-bloom log position) and tx 7
    succeeded — our replay reproduces exactly that shape.
  * residual gap (tracked): tx 3 fails with gas_used 811045 vs the 816911
    implied by the header total.  Struct-log analysis (round 2) localizes
    OUR failure point exactly: a depth-4 SSTORE (SSTORE_SET, 20000) with
    12368 gas left inside the bridge-relay cascade — a clean OOG whose
    burn equals the gas forwarded into that frame, so the 5866 delta sits
    UPSTREAM in a forwarded amount, not at the failure site.  All call-
    site accounting (memory-expansion-first ordering, 2929 access charge,
    63/64 cap, stipend) matches the EIPs on audit; isolating the one
    divergent charge needs a reference opcode trace or EF fixtures
    (neither is available in this image — the EF fixture chains in
    fixtures/blockchain are Git-LFS pointers without objects).
"""

import json
import os

import pytest

from ethrex_tpu.blockchain.blockchain import Blockchain
from ethrex_tpu.crypto.keccak import keccak256
from ethrex_tpu.evm.db import StateDB
from ethrex_tpu.evm.executor import execute_tx
from ethrex_tpu.evm.vm import BlockEnv
from ethrex_tpu.guest.execution import WitnessSource, _GuestChainView
from ethrex_tpu.primitives.genesis import ChainConfig
from ethrex_tpu.primitives.receipt import logs_bloom
from ethrex_tpu.utils.replay import load_cache

CACHE = "/root/reference/fixtures/cache/rpc_prover/cache_hoodi_1265656.json"
GENESIS = "/root/reference/cmd/ethrex/networks/hoodi/genesis.json"


def _bloom_has(bloom: bytes, item: bytes) -> bool:
    h3 = keccak256(item)
    for i in (0, 2, 4):
        bit = ((h3[i] << 8) | h3[i + 1]) & 0x7FF
        if not (bloom[256 - 1 - bit // 8] >> (bit % 8)) & 1:
            return False
    return True


@pytest.mark.skipif(not os.path.exists(CACHE),
                    reason="reference cache not available")
def test_hoodi_block_replay():
    with open(GENESIS) as f:
        cfg = ChainConfig.from_json(json.load(f)["config"])
    pi = load_cache(CACHE, cfg)
    blk = pi.blocks[0]
    h = blk.header
    w = pi.witness
    nodes = {keccak256(bytes(n)): bytes(n) for n in w.nodes}
    codes = {keccak256(bytes(c)): bytes(c) for c in w.codes}
    headers = {x.number: x for x in w.block_headers}
    parent = w.block_headers[-1]
    assert parent.hash == h.parent_hash  # witness linkage

    chain = Blockchain(_GuestChainView(), cfg)
    fork = cfg.fork_at(h.number, h.timestamp)
    env = BlockEnv(
        number=h.number, coinbase=h.coinbase, timestamp=h.timestamp,
        gas_limit=h.gas_limit, prev_randao=h.prev_randao,
        base_fee=h.base_fee_per_gas or 0,
        excess_blob_gas=h.excess_blob_gas or 0,
        parent_beacon_block_root=h.parent_beacon_block_root or b"\x00" * 32)
    source = WitnessSource(nodes, codes, headers, parent.state_root)
    state = StateDB(source)
    chain._pre_tx_system_ops(state, env, h, fork)
    results = [execute_tx(tx, state, env, cfg)
               for tx in blk.body.transactions]

    # exact per-tx gas for everything except the tracked tx3 residual
    gases = [r.gas_used for r in results]
    assert gases[:3] == [21000] * 3
    assert gases[5] == 21000
    assert gases[9] == 28130          # EIP-7623 floor, byte-exact
    assert gases[4] == 828658
    assert gases[6] == 818616
    assert gases[7] == 818602
    assert gases[8] == 921210
    assert gases[10] == 86820
    # status shape: tx3 (first relay of the duplicated message) fails,
    # tx7 (the second relay) succeeds — exactly as on-chain
    assert [r.success for r in results] == [
        True, True, True, False, True, True, True, True, True, True, True]
    # tracked residual: tx3's OOG burns 811045 vs 816911 implied on-chain
    assert gases[3] == 811045, "tx3 residual changed — retighten this test"
    total = sum(gases)
    assert h.gas_used - total == 5866, (
        f"aggregate residual changed: {h.gas_used - total}")

    # every log element we emit is present in the header bloom (we produce
    # no spurious logs); the known delta is tx4's gas-derived refund amount
    for i, r in enumerate(results):
        for log in r.logs:
            assert _bloom_has(h.bloom, log.address), f"tx{i} addr not in bloom"
            for j, t in enumerate(log.topics):
                if i == 4 and j == 2 and log.topics[0].hex().startswith(
                        "518ae4ce"):
                    continue  # tracked: gas-derived indexed refund amount
                assert _bloom_has(h.bloom, t), f"tx{i} topic not in bloom"
