"""Replay of a REAL Hoodi testnet block (1265656, 4.4 Mgas, 11 txs incl.
Groth16 verifier calls hitting ecAdd/ecMul/ecPairing) from the reference's
cached witness — the ethrex-replay conformance path
(/root/reference/docs/ethrex_replay/ethrex_replay.md).

FULL byte-exact consensus with the live chain: header gas total, the
RECEIPTS ROOT, and the final STATE ROOT all match, so every per-tx gas,
status, log (topics AND data), and storage/balance write in the block is
pinned against Hoodi itself.

History: rounds 1-2 carried a tracked 5,866-gas residual attributed to
tx3.  Round 3 localized it with bloom-bit analysis (the chain's
gas-derived paymaster refund implied exactly +1,257 gas in one metering
bracket) and the EF matrix generator's independent gas oracle then caught
the mechanism in a 5-byte case: the interpreter jumped to JUMPDEST + 1,
skipping the target's 1-gas charge on every taken jump (5,866 = the
block's taken-jump count outside OOG frames).  One line in evm/vm.py
(_jump/_jumpi landing ON the JUMPDEST) closed every oracle at once.
"""

import json
import os

import pytest

from ethrex_tpu.blockchain.blockchain import Blockchain
from ethrex_tpu.crypto.keccak import keccak256
from ethrex_tpu.evm.db import StateDB
from ethrex_tpu.guest.execution import WitnessSource, _GuestChainView
from ethrex_tpu.primitives.genesis import ChainConfig
from ethrex_tpu.primitives.receipt import logs_bloom
from ethrex_tpu.utils.replay import load_cache

CACHE = "/root/reference/fixtures/cache/rpc_prover/cache_hoodi_1265656.json"
GENESIS = "/root/reference/cmd/ethrex/networks/hoodi/genesis.json"


@pytest.mark.skipif(not os.path.exists(CACHE),
                    reason="reference cache not available")
def test_hoodi_block_replay():
    with open(GENESIS) as f:
        cfg = ChainConfig.from_json(json.load(f)["config"])
    pi = load_cache(CACHE, cfg)
    blk = pi.blocks[0]
    h = blk.header
    w = pi.witness
    nodes = {keccak256(bytes(n)): bytes(n) for n in w.nodes}
    codes = {keccak256(bytes(c)): bytes(c) for c in w.codes}
    headers = {x.number: x for x in w.block_headers}
    parent = w.block_headers[-1]
    assert parent.hash == h.parent_hash  # witness linkage

    chain = Blockchain(_GuestChainView(), cfg)
    source = WitnessSource(nodes, codes, headers, parent.state_root)
    state = StateDB(source)
    outcome = chain.execute_block(blk, parent, state)

    receipts = outcome.receipts
    # per-tx gas, chain-exact (header gas + receipts root pin them)
    cums = [r.cumulative_gas_used for r in receipts]
    gases = [b - a for a, b in zip([0] + cums, cums)]
    assert gases == [21000, 21000, 21000, 811078, 830030, 21000, 819954,
                     819940, 922953, 28130, 86862]
    assert outcome.gas_used == h.gas_used
    # status shape: tx3 (first relay of the duplicated message) fails,
    # tx7 (the second relay) succeeds — exactly as on-chain
    assert [r.succeeded for r in receipts] == [
        True, True, True, False, True, True, True, True, True, True, True]

    # the receipts root: statuses, cumulative gas, blooms, and every log
    # (addresses, topics incl. the gas-derived paymaster refund amount,
    # and data) byte-match the chain
    from ethrex_tpu.blockchain.blockchain import compute_receipts_root

    assert compute_receipts_root(receipts) == h.receipts_root
    assert logs_bloom([log for r in receipts
                       for log in r.logs]) == h.bloom

    # the final state root: every storage write and balance in the block
    # matches Hoodi
    from ethrex_tpu.storage.store import apply_updates_to_tries

    final_root = apply_updates_to_tries(nodes, codes, parent.state_root,
                                        state)
    assert final_root == h.state_root
