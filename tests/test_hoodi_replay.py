"""Replay of a REAL Hoodi testnet block (1265656, 4.4 Mgas, 11 txs incl.
Groth16 verifier calls hitting ecAdd/ecMul/ecPairing) from the reference's
cached witness — the ethrex-replay conformance path.

Current status (tracked, tightened as gas rules are closed out):
  * witness parsing, pruned-trie reconstruction, full execution: OK
  * 10/11 txs match expected success status; total gas within 0.15%
  * tx 3 diverges (reverts on a tight gas limit) — one residual gas-rule
    delta; state/receipts roots therefore do not yet match for this block
"""

import json
import os

import pytest

from ethrex_tpu.blockchain.blockchain import Blockchain
from ethrex_tpu.crypto.keccak import keccak256
from ethrex_tpu.evm.db import StateDB
from ethrex_tpu.evm.executor import execute_tx
from ethrex_tpu.evm.vm import BlockEnv
from ethrex_tpu.guest.execution import WitnessSource, _GuestChainView
from ethrex_tpu.primitives.genesis import ChainConfig
from ethrex_tpu.utils.replay import load_cache

CACHE = "/root/reference/fixtures/cache/rpc_prover/cache_hoodi_1265656.json"
GENESIS = "/root/reference/cmd/ethrex/networks/hoodi/genesis.json"


@pytest.mark.skipif(not os.path.exists(CACHE),
                    reason="reference cache not available")
def test_hoodi_block_replay():
    with open(GENESIS) as f:
        cfg = ChainConfig.from_json(json.load(f)["config"])
    pi = load_cache(CACHE, cfg)
    blk = pi.blocks[0]
    h = blk.header
    w = pi.witness
    nodes = {keccak256(bytes(n)): bytes(n) for n in w.nodes}
    codes = {keccak256(bytes(c)): bytes(c) for c in w.codes}
    headers = {x.number: x for x in w.block_headers}
    parent = w.block_headers[-1]
    assert parent.hash == h.parent_hash  # witness linkage

    chain = Blockchain(_GuestChainView(), cfg)
    fork = cfg.fork_at(h.number, h.timestamp)
    env = BlockEnv(
        number=h.number, coinbase=h.coinbase, timestamp=h.timestamp,
        gas_limit=h.gas_limit, prev_randao=h.prev_randao,
        base_fee=h.base_fee_per_gas or 0,
        excess_blob_gas=h.excess_blob_gas or 0,
        parent_beacon_block_root=h.parent_beacon_block_root or b"\x00" * 32)
    source = WitnessSource(nodes, codes, headers, parent.state_root)
    state = StateDB(source)
    chain._pre_tx_system_ops(state, env, h, fork)
    results = [execute_tx(tx, state, env, cfg)
               for tx in blk.body.transactions]
    total = sum(r.gas_used for r in results)
    # blob transfers are exact; tx9 must equal the EIP-7623 floor exactly
    assert [r.gas_used for r in results[:3]] == [21000] * 3
    assert results[9].gas_used == 28130
    # aggregate gas within 0.15% of the on-chain value (residual tracked gap)
    assert abs(total - h.gas_used) / h.gas_used < 0.0015, (
        f"gas divergence too large: {total} vs {h.gas_used}")
    # the heavy Groth16-verifier txs execute (pairing returns 1)
    assert sum(1 for r in results if r.success) >= 10
