"""CLI subcommands (import/export/removedb/compute-state-root) and the
ETHREX_* env-var flag mirrors (cmd/ethrex/cli.rs parity)."""

import json
import os
import subprocess
import sys

import pytest

from ethrex_tpu.cli import DEV_GENESIS, main
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis


def _run(args, env=None):
    return subprocess.run(
        [sys.executable, "-m", "ethrex_tpu.cli", *args],
        capture_output=True, text=True,
        env={**os.environ, **(env or {})},
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_compute_state_root_and_env_mirror(tmp_path):
    out = _run(["compute-state-root", "--dev"])
    assert out.returncode == 0 and "state root: 0x" in out.stdout
    # same via ETHREX_DEV env mirror
    out2 = _run(["compute-state-root"], env={"ETHREX_DEV": "1"})
    assert out2.returncode == 0
    assert out.stdout.splitlines()[0] == out2.stdout.splitlines()[0]


def test_import_export_roundtrip(tmp_path):
    gpath = tmp_path / "g.json"
    gpath.write_text(json.dumps(DEV_GENESIS))
    node = Node(Genesis.from_json(DEV_GENESIS))
    node.produce_block()
    node.produce_block()
    chain = tmp_path / "chain.rlp"
    with open(chain, "wb") as f:
        for n in (1, 2):
            f.write(node.store.get_canonical_block(n).encode())
    datadir = tmp_path / "db"
    out = _run(["import", str(chain), "--network", str(gpath),
                "--datadir", str(datadir)])
    assert out.returncode == 0 and "imported 2 blocks" in out.stdout
    # export from the persisted datadir and compare bytes
    exported = tmp_path / "out.rlp"
    out = _run(["export", str(exported), "--network", str(gpath),
                "--datadir", str(datadir)])
    assert out.returncode == 0, out.stderr
    assert exported.read_bytes() == chain.read_bytes()
    # removedb deletes it
    out = _run(["removedb", "--datadir", str(datadir), "--force"])
    assert out.returncode == 0 and not datadir.exists()


def test_removedb_without_datadir_fails():
    assert main(["removedb", "--force"]) == 1
