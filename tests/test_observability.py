"""Observability layer: hierarchical tracing, labelled Prometheus
histograms, trace propagation over the prover protocol, and the monitor's
graceful degradation (docs/OBSERVABILITY.md)."""

import json
import threading

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.l2.l1_client import InMemoryL1
from ethrex_tpu.l2.sequencer import ActorHealth, Sequencer, SequencerConfig
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover.client import ProverClient
from ethrex_tpu.rpc.server import RpcServer
from ethrex_tpu.utils import tracing
from ethrex_tpu.utils.metrics import METRICS, Metrics
from ethrex_tpu.utils.tracing import TRACER, Tracer, span, trace_context

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

GENESIS = {
    "config": {"chainId": 65536999, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _transfer(nonce, value=100):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=65536999, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21000, to=bytes.fromhex("aa" * 20), value=value,
    ).sign(SECRET)


# ---------------------------------------------------------------------------
# tracing core


def test_span_nesting_and_trace_record():
    with span("outer", kind="test") as outer:
        assert outer is not None
        with span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    trace = TRACER.get_trace(outer.trace_id)
    by_name = {s["name"]: s for s in trace["spans"]}
    assert by_name["outer"]["parentId"] is None
    assert by_name["inner"]["parentId"] == outer.span_id
    assert by_name["outer"]["attrs"]["kind"] == "test"
    assert by_name["outer"]["seconds"] >= by_name["inner"]["seconds"]


def test_span_records_error_and_reraises():
    try:
        with span("boom") as sp:
            raise ValueError("exploded")
    except ValueError:
        pass
    else:
        raise AssertionError("span must not swallow exceptions")
    rec = TRACER.get_trace(sp.trace_id)["spans"][0]
    assert rec["status"] == "error"
    assert "ValueError: exploded" in rec["error"]


def test_tracer_ring_buffer_is_bounded():
    t = Tracer(capacity=4)
    for i in range(10):
        sp = tracing.Span(tracing.new_trace_id(), tracing.new_span_id(),
                          None, f"t{i}", {})
        t.record(sp)
    assert len(t) == 4
    assert t.dropped == 6
    # the survivors are the newest four
    assert {s["name"] for tr in t.recent(10) for s in tr["spans"]} == \
        {"t6", "t7", "t8", "t9"}


def test_tracing_never_raises_into_traced_path(monkeypatch):
    def explode(_span):
        raise RuntimeError("tracer is broken")

    monkeypatch.setattr(TRACER, "record", explode)
    with span("guarded") as sp:
        ran = True
    assert ran and sp is not None
    with trace_context(object()):  # junk trace id: coerced, not raised
        with span("still-fine"):
            pass


def test_trace_context_continues_remote_trace():
    with trace_context("feedbeef00000000", "cafe0001"):
        with span("continued") as sp:
            assert sp.trace_id == "feedbeef00000000"
            assert sp.parent_id == "cafe0001"
    spans = TRACER.get_trace("feedbeef00000000")["spans"]
    assert any(s["name"] == "continued" for s in spans)


def test_thread_local_isolation():
    ids = {}

    def worker(key):
        with span(f"w-{key}") as sp:
            ids[key] = sp.trace_id

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    with span("main") as sp:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ids["main"] = sp.trace_id
    assert len(set(ids.values())) == 3


def test_slowest_and_stage_breakdown():
    t = Tracer(capacity=16)
    for name, secs, stage in (("fast", 0.01, "a"), ("slow", 5.0, "b")):
        sp = tracing.Span(tracing.new_trace_id(), tracing.new_span_id(),
                          None, name, {"stage": stage})
        sp.seconds = secs
        t.record(sp)
        slow_tid = sp.trace_id
    slowest = t.slowest(1)
    assert slowest[0]["name"] == "slow"
    assert t.stage_breakdown(slow_tid) == {"b": 5.0}


# ---------------------------------------------------------------------------
# Prometheus histogram exposition


def test_histogram_exposition_format():
    m = Metrics()
    # a label value that needs escaping, plus enough observations to
    # spread across buckets
    label = {"method": 'eth_"call"\nx\\y'}
    for v in (0.0005, 0.003, 0.003, 0.7, 100.0, 10**6):
        m.observe("rpc_request_seconds", v, label, "help text")
    text = m.render()
    lines = text.splitlines()
    assert "# TYPE rpc_request_seconds histogram" in lines
    assert "# HELP rpc_request_seconds help text" in lines
    # label escaping: backslash, quote, newline
    assert 'method="eth_\\"call\\"\\nx\\\\y"' in text
    bucket_lines = [ln for ln in lines
                    if ln.startswith("rpc_request_seconds_bucket")]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in bucket_lines]
    # cumulative buckets must be monotone non-decreasing
    assert counts == sorted(counts)
    # le="+Inf" equals _count equals total observations
    inf = [ln for ln in bucket_lines if 'le="+Inf"' in ln]
    assert len(inf) == 1 and int(inf[0].rsplit(" ", 1)[1]) == 6
    count_ln = [ln for ln in lines
                if ln.startswith("rpc_request_seconds_count")]
    assert int(count_ln[0].rsplit(" ", 1)[1]) == 6
    sum_ln = [ln for ln in lines
              if ln.startswith("rpc_request_seconds_sum")]
    assert abs(float(sum_ln[0].rsplit(" ", 1)[1]) - 1000100.7065) < 1e-3
    # the le ladder parses as increasing floats
    les = [ln.split('le="')[1].split('"')[0] for ln in bucket_lines[:-1]]
    as_floats = [float(v) for v in les]
    assert as_floats == sorted(as_floats) and len(set(as_floats)) == len(les)


def test_bucket_le_labels_are_canonical_shortest_floats():
    """Golden exposition: the `le` labels render as Python's shortest
    repr of the float boundary — stable and joinable across scrapes,
    whatever numeric type produced the boundary (satellite of the SLO
    engine: windowed percentiles join samples on these labels)."""
    from ethrex_tpu.utils.metrics import DEFAULT_BUCKETS, _fmt_le

    m = Metrics()
    m.observe("g_seconds", 0.5)
    text = m.render()
    les = [ln.split('le="')[1].split('"')[0]
           for ln in text.splitlines() if ln.startswith("g_seconds_bucket")]
    # the full golden ladder: 1ms * 2^i is exact under binary doubling,
    # so every label is the clean decimal
    assert les == [
        "0.001", "0.002", "0.004", "0.008", "0.016", "0.032", "0.064",
        "0.128", "0.256", "0.512", "1.024", "2.048", "4.096", "8.192",
        "16.384", "32.768", "65.536", "131.072", "262.144", "524.288",
        "+Inf"]
    assert les[:-1] == [repr(b) for b in DEFAULT_BUCKETS]
    # numpy scalars / ints / plain floats all canonicalise identically
    import numpy as np

    assert _fmt_le(np.float32(0.5)) == _fmt_le(0.5) == "0.5"
    assert _fmt_le(np.int64(5)) == _fmt_le(5) == _fmt_le(5.0) == "5.0"


def test_metrics_reset_clears_every_family():
    m = Metrics()
    m.inc("c_total", 3, "a counter")
    m.set("g", 7)
    m.observe("h_seconds", 0.1)
    started = m.started
    m.reset()
    assert m.counters == {} and m.gauges == {} and m.histograms == {}
    assert m.help == {}
    assert m.started >= started
    # a fresh registry still renders (uptime only)
    assert "process_uptime_seconds" in m.render()
    assert "c_total" not in m.render()


def test_metrics_server_404_and_aborted_scrape():
    """The scrape endpoint: unknown paths get a proper 404 with a
    Content-Type, and a scraper that drops the connection mid-response
    must not wedge the server thread."""
    import socket
    import urllib.error
    import urllib.request

    from ethrex_tpu.utils.metrics import METRICS as M, MetricsServer

    M.inc("scrape_probe_total", 1, "probe")
    server = MetricsServer(port=0).start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{url}/metrics", timeout=5) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            assert b"scrape_probe_total" in r.read()
        try:
            urllib.request.urlopen(f"{url}/nope", timeout=5)
            raise AssertionError("expected a 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert e.headers["Content-Type"].startswith("text/plain")
            assert e.read() == b"not found\n"
        # an aborted scrape: connect, send the request, hang up before
        # reading the response
        s = socket.create_connection(("127.0.0.1", server.port), timeout=5)
        s.sendall(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
        s.close()
        # the server is still healthy for the next scraper
        with urllib.request.urlopen(f"{url}/metrics", timeout=5) as r:
            assert r.status == 200
    finally:
        server.stop()


def test_histograms_do_not_break_counters_and_gauges():
    m = Metrics()
    m.inc("things_total", 2, "things")
    m.set("level", 7)
    m.observe("latency_seconds", 0.1)
    text = m.render()
    assert "things_total 2" in text
    assert "level 7" in text
    # unlabelled histogram series renders without a dangling comma
    assert 'latency_seconds_bucket{le="0.001"} 0' in text
    assert "latency_seconds_count 1" in text


def test_rpc_and_prover_stage_histograms_exposed():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node)
    server.handle({"jsonrpc": "2.0", "id": 1,
                   "method": "eth_blockNumber", "params": []})
    from ethrex_tpu.utils.metrics import observe_prover_stage

    observe_prover_stage("trace_lde", 0.25)
    text = METRICS.render()
    assert 'rpc_request_seconds_bucket{method="eth_blockNumber",le="+Inf"}' \
        in text
    assert 'prover_stage_seconds_bucket{stage="trace_lde",le="+Inf"}' in text
    assert 'rpc_request_seconds_count{method="eth_blockNumber"}' in text


# ---------------------------------------------------------------------------
# end-to-end: one proved batch == one trace


def test_single_trace_covers_batch_lifecycle():
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1(needed_prover_types=[protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,)))
    seq.coordinator.start()
    try:
        node.submit_transaction(_transfer(0))
        seq.produce_block()
        assert seq.commit_next_batch() is not None
        client = ProverClient(protocol.PROVER_EXEC,
                              [("127.0.0.1", seq.coordinator.port)])
        assert client.poll_once() == 1
        assert seq.send_proofs() == (1, 1)

        tid = seq.coordinator.batch_traces[1]
        trace = TRACER.get_trace(tid)
        spans = trace["spans"]
        names = {s["name"] for s in spans}
        assert {"prover.assign", "prover.prove", "prover.submit",
                "prover.store_proof", "proof.verify",
                "proof.settle"} <= names
        # every span shares the one trace ID (coordinator thread, prover
        # client thread, and sequencer all joined the same trace)
        assert {s["traceId"] for s in spans} == {tid}
        # the cross-process span tree is linked: prove hangs off assign,
        # store_proof hangs off submit
        by_name = {s["name"]: s for s in spans}
        assert by_name["prover.prove"]["parentId"] == \
            by_name["prover.assign"]["spanId"]
        assert by_name["prover.store_proof"]["parentId"] == \
            by_name["prover.submit"]["spanId"]

        # retrievable through the RPC trace namespace
        node.sequencer = seq
        server = RpcServer(node)
        r = server.handle({"jsonrpc": "2.0", "id": 1,
                           "method": "ethrex_trace_recentTraces",
                           "params": [300]})
        match = [t for t in r["result"] if t["traceId"] == tid]
        assert match and match[0]["spanCount"] == len(spans)
        json.dumps(r)  # JSON-serializable all the way down
        r = server.handle({"jsonrpc": "2.0", "id": 2,
                           "method": "ethrex_trace_slowest",
                           "params": ["0x5"]})
        assert len(r["result"]) <= 5
        # critical-path attribution of the same trace partitions its wall
        r = server.handle({"jsonrpc": "2.0", "id": 3,
                           "method": "ethrex_trace_criticalPath",
                           "params": [tid]})
        cp = r["result"]
        assert cp["found"] is True and cp["chain"]
        assert abs(sum(cp["components"].values()) - cp["wallSeconds"]) \
            <= 0.05 * max(cp["wallSeconds"], 1e-9)
        json.dumps(r)
        # ...and exports as loadable Chrome trace-event JSON
        r = server.handle({"jsonrpc": "2.0", "id": 4,
                           "method": "ethrex_trace_export",
                           "params": [tid]})
        evs = r["result"]["traceEvents"]
        assert any(e["ph"] == "X" and e["name"] == "prover.prove"
                   for e in evs)
        json.dumps(r)
    finally:
        seq.stop()


def test_health_reports_actor_loop_latency():
    st = ActorHealth("produce_block")
    st.note_duration(0.5)
    st.note_duration(0.1)
    loop = st.to_json()["loop"]
    assert loop["lastSeconds"] == 0.1
    assert abs(loop["avgSeconds"] - 0.3) < 1e-9
    assert loop["maxSeconds"] == 0.5
    # untimed actor: nulls, not division errors
    assert ActorHealth("x").to_json()["loop"]["avgSeconds"] is None


def test_health_includes_tracing_and_loop_stats():
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1(needed_prover_types=[protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,)))
    node.sequencer = seq
    seq.health["produce_block"] = ActorHealth("produce_block")
    seq.health["produce_block"].note_duration(0.02)
    server = RpcServer(node)
    r = server.handle({"jsonrpc": "2.0", "id": 1,
                       "method": "ethrex_health", "params": []})
    health = r["result"]
    assert "bufferedTraces" in health["tracing"]
    actor = health["l2"]["actors"]["produce_block"]
    assert actor["loop"]["lastSeconds"] == 0.02


# ---------------------------------------------------------------------------
# monitor degradation


def test_monitor_degrades_against_l1_only_node():
    from ethrex_tpu.utils.monitor import render_lines, snapshot
    from ethrex_tpu.utils.repl import RpcSession

    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node).start()
    # simulate an older / L1-only node: no L2 namespace, no trace RPCs
    for method in ("ethrex_health", "ethrex_latestBatch",
                   "ethrex_trace_slowest", "ethrex_trace_recentTraces",
                   "ethrex_trace_criticalPath", "ethrex_trace_export"):
        server.methods.pop(method)
    try:
        node.produce_block()
        snap = snapshot(RpcSession(f"http://127.0.0.1:{server.port}"))
        assert snap["head"]["number"] == 1
        assert snap["batch"] is None
        assert snap["health"] is None
        assert snap["traces"] is None
        assert snap["criticalPath"] is None
        lines = render_lines(snap, width=80)
        assert any("head #1" in ln for ln in lines)
        assert not any("slowest traces" in ln for ln in lines)
        assert not any("actor loop latency" in ln for ln in lines)
    finally:
        server._httpd.shutdown()


def test_monitor_renders_latency_panels():
    from ethrex_tpu.utils.monitor import render_lines

    snap = {
        "head": {"number": 1, "hash": "0x" + "00" * 32, "gas_used": 0,
                 "gas_limit": 30_000_000, "txs": 0, "base_fee": 7,
                 "timestamp": 0},
        "recent": [],
        "health": {"l2": {"actors": {"produce_block": {
            "loop": {"lastSeconds": 0.004, "avgSeconds": 0.002,
                     "maxSeconds": 0.01}}}}},
        "traces": [{"name": "prover.assign", "seconds": 1.25,
                    "spanCount": 7, "traceId": "ab" * 8}],
    }
    lines = render_lines(snap, width=100)
    assert any("actor loop latency" in ln for ln in lines)
    assert any("produce_block" in ln and "4.0ms" in ln for ln in lines)
    assert any("slowest traces" in ln for ln in lines)
    assert any("prover.assign" in ln for ln in lines)
    # malformed/partial payloads must not crash the panel
    snap["traces"] = ["garbage", {"name": "x"}]
    snap["health"] = {"l2": {"actors": {"a": "not-a-dict"}}}
    render_lines(snap, width=100)


# ---------------------------------------------------------------------------
# structured logging


def test_json_log_formatter_carries_trace_context():
    import io
    import logging

    buf = io.StringIO()
    tracing.setup_logging("info", json_mode=True, stream=buf)
    try:
        with trace_context("ab" * 8, "cd" * 4):
            logging.getLogger("ethrex_tpu.test").info("hello %s", "world")
        rec = json.loads(buf.getvalue())
        assert rec["msg"] == "hello world"
        assert rec["traceId"] == "ab" * 8
        assert rec["spanId"] == "cd" * 4
        assert rec["level"] == "info"
    finally:
        logging.getLogger("ethrex_tpu").handlers[:] = []


def test_cli_accepts_log_flags():
    import argparse

    from ethrex_tpu.cli import _add_node_flags

    p = argparse.ArgumentParser()
    _add_node_flags(p)
    args = p.parse_args(["--log-level", "debug", "--log-json"])
    assert args.log_level == "debug" and args.log_json is True
    args = p.parse_args([])
    assert args.log_level == "info" and args.log_json is False
