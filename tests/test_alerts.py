"""SLO engine battery (docs/OBSERVABILITY.md): rolling-window
time-series queries, burn-rate alert hysteresis, the flight recorder,
graceful RPC degradation, and a full-stack quiet -> firing -> resolved
lifecycle driven by a real injected prover fault."""

import json
import os
import time

from ethrex_tpu.crypto import secp256k1
from ethrex_tpu.l2.l1_client import InMemoryL1
from ethrex_tpu.l2.sequencer import Sequencer, SequencerConfig
from ethrex_tpu.node import Node
from ethrex_tpu.primitives.genesis import Genesis
from ethrex_tpu.primitives.transaction import TYPE_DYNAMIC_FEE, Transaction
from ethrex_tpu.prover import protocol
from ethrex_tpu.prover.client import ProverClient
from ethrex_tpu.rpc.server import RpcServer
from ethrex_tpu.utils import faults, snapshot, timeseries
from ethrex_tpu.utils.alerts import (AlertEngine, AlertRule, actor_stall_signal,
                                     aggregation_lag_signal,
                                     build_default_engine, default_rules,
                                     rate_signal, settlement_lag_signal)
from ethrex_tpu.utils.faults import FaultPlan
from ethrex_tpu.utils.metrics import METRICS, Metrics
from ethrex_tpu.utils.repl import RpcSession
from ethrex_tpu.utils.timeseries import TimeSeriesEngine

SECRET = 0x45A915E4D060149EB4365960E6A7A45F334393093061116B197E3240065FF2D8
SENDER = secp256k1.pubkey_to_address(secp256k1.pubkey_from_secret(SECRET))

GENESIS = {
    "config": {"chainId": 65536999, "terminalTotalDifficulty": 0,
               "shanghaiTime": 0, "cancunTime": 0},
    "alloc": {"0x" + SENDER.hex(): {"balance": hex(10**21)}},
    "gasLimit": hex(30_000_000), "baseFeePerGas": "0x7", "timestamp": "0x0",
}


def _transfer(nonce, value=100):
    return Transaction(
        tx_type=TYPE_DYNAMIC_FEE, chain_id=65536999, nonce=nonce,
        max_priority_fee_per_gas=1, max_fee_per_gas=10**10,
        gas_limit=21000, to=bytes.fromhex("aa" * 20), value=value,
    ).sign(SECRET)


# ---------------------------------------------------------------------------
# time-series engine: rates


def test_rate_from_counter_deltas_and_reset_clamp():
    m = Metrics()
    eng = TimeSeriesEngine(m)
    t0 = 1000.0
    m.inc("work_total", 10)
    eng.sample_now(now=t0)
    m.inc("work_total", 30)
    eng.sample_now(now=t0 + 10)
    assert eng.rate("work_total", window=60.0) == 3.0
    # counter reset (simulated restart): the new value IS the increase,
    # never a negative rate
    m.reset()
    m.inc("work_total", 5)
    eng.sample_now(now=t0 + 20)
    r = eng.rate("work_total", window=15.0)
    assert r is not None and r >= 0
    assert r == 5 / 10  # oldest-in-window is the t0+10 sample
    # unknown counter: no data, not zero
    assert eng.rate("no_such_total", window=60.0) is None


def test_rate_window_excludes_old_samples():
    m = Metrics()
    eng = TimeSeriesEngine(m)
    m.inc("work_total", 100)
    eng.sample_now(now=0.0)
    m.inc("work_total", 2)
    eng.sample_now(now=100.0)
    m.inc("work_total", 2)
    eng.sample_now(now=110.0)
    # 60s window from the newest sample: only the 100 -> 110 delta
    assert eng.rate("work_total", window=60.0) == 2 / 10
    # a window wide enough to reach the first sample sees all 4 increments
    assert eng.rate("work_total", window=200.0) == 4 / 110


def test_rate_requires_two_samples_in_window():
    m = Metrics()
    eng = TimeSeriesEngine(m)
    assert eng.rate("work_total") is None          # no samples at all
    m.inc("work_total", 1)
    eng.sample_now(now=0.0)
    assert eng.rate("work_total") is None          # one sample
    eng.sample_now(now=500.0)
    # the older sample fell out of the 60s window: still no data
    assert eng.rate("work_total", window=60.0) is None


# ---------------------------------------------------------------------------
# time-series engine: windowed percentiles


def test_windowed_percentiles_from_bucket_deltas():
    m = Metrics()
    eng = TimeSeriesEngine(m)
    eng.sample_now(now=-100.0)
    # stale observations BEFORE the window must not pollute the estimate
    for _ in range(100):
        m.observe("lat_seconds", 400.0)
    eng.sample_now(now=0.0)
    for _ in range(99):
        m.observe("lat_seconds", 0.010)
    m.observe("lat_seconds", 0.100)
    eng.sample_now(now=10.0)
    p = eng.percentiles("lat_seconds", window=60.0)
    assert p is not None
    # 99/100 windowed observations sit in the (0.008, 0.016] bucket
    assert 0.008 < p["p50"] <= 0.016
    assert 0.008 < p["p95"] <= 0.016
    assert p["p99"] <= 0.128
    # had the window covered everything, the stale 400s would dominate
    p_all = eng.percentiles("lat_seconds", window=1000.0)
    assert p_all["p95"] > 100.0


def test_percentiles_cold_start_and_quiet_window_are_none():
    m = Metrics()
    eng = TimeSeriesEngine(m)
    assert eng.percentiles("lat_seconds") is None   # no samples
    m.observe("lat_seconds", 1.0)
    eng.sample_now(now=0.0)
    eng.sample_now(now=10.0)
    # histogram exists but nothing was observed inside the window:
    # no-data, not zero
    assert eng.percentiles("lat_seconds", window=60.0) is None
    assert eng.percentiles("no_such_seconds", window=60.0) is None


def test_percentiles_label_filter():
    m = Metrics()
    eng = TimeSeriesEngine(m)
    eng.sample_now(now=0.0)
    m.observe("stage_seconds", 0.010, {"stage": "fast"})
    m.observe("stage_seconds", 60.0, {"stage": "slow"})
    eng.sample_now(now=10.0)
    fast = eng.percentiles("stage_seconds", labels={"stage": "fast"})
    slow = eng.percentiles("stage_seconds", labels={"stage": "slow"})
    both = eng.percentiles("stage_seconds")
    assert fast["p95"] <= 0.016
    assert slow["p95"] > 30.0
    assert fast["p95"] < both["p95"] <= slow["p95"]
    assert eng.percentiles("stage_seconds", labels={"stage": "nope"}) is None


def test_windows_json_shape():
    m = Metrics()
    eng = TimeSeriesEngine(m)
    assert eng.windows_json()["samples"] == 0
    m.inc("work_total", 1)
    m.set("level", 7.0)
    eng.sample_now(now=0.0)
    m.inc("work_total", 5)
    m.observe("lat_seconds", 0.01)
    eng.sample_now(now=10.0)
    out = eng.windows_json(window=60.0)
    assert out["samples"] == 2
    assert out["rates"]["work_total"] == 0.5
    assert "p95" in out["percentiles"]["lat_seconds"]
    assert out["gauges"]["level"] == 7.0
    assert out["samplerErrors"] == 0
    json.dumps(out)  # JSON-safe all the way down


# ---------------------------------------------------------------------------
# time-series engine: never-raise + sampler thread


def test_tick_never_raises_and_counts_errors():
    class Broken:
        def snapshot(self):
            raise RuntimeError("registry is broken")

    eng = TimeSeriesEngine(Broken())
    eng.tick()
    assert eng.sampler_errors == 1

    m = Metrics()
    eng = TimeSeriesEngine(m)

    def bad_evaluator():
        raise ValueError("rule exploded")

    ran = []
    eng.add_evaluator(bad_evaluator)
    eng.add_evaluator(lambda: ran.append(1))
    eng.tick()
    # the broken evaluator is counted, the healthy one still ran, the
    # sample still landed
    assert eng.sampler_errors == 1
    assert ran == [1]
    assert len(eng.samples) == 1


def test_sampler_thread_lifecycle_and_drain():
    m = Metrics()
    eng = TimeSeriesEngine(m)
    eng.start(interval=0.01)
    assert eng.running()
    assert eng.start(interval=0.01) is eng      # idempotent
    deadline = time.time() + 5.0
    while len(eng.samples) < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert len(eng.samples) >= 3
    before = len(eng.samples)
    eng.stop()
    assert not eng.running()
    # stop() takes one final drain sample
    assert len(eng.samples) >= before + 1
    eng.stop()  # idempotent, never raises


# ---------------------------------------------------------------------------
# alert engine: hysteresis state machine


def _scripted_engine(values, threshold=10.0, for_count=2, resolve_count=2):
    """AlertEngine over a canned signal: pops one value per evaluate."""
    feed = list(values)
    rule = AlertRule("test_rule:page", "page",
                     lambda eng, node: feed.pop(0), threshold,
                     for_count=for_count, resolve_count=resolve_count)
    return AlertEngine(engine=TimeSeriesEngine(Metrics()), rules=[rule])


def test_cold_start_none_never_fires():
    eng = _scripted_engine([None] * 50)
    for _ in range(50):
        eng.evaluate(now=0.0)
    st = eng.states["test_rule:page"]
    assert st.state == "ok"
    assert eng.transitions_total == 0 and not eng.active()


def test_fires_after_for_count_and_resolves_after_resolve_count():
    eng = _scripted_engine([5, 50, 50, 50, 5, 5, 5], for_count=2,
                           resolve_count=2)
    st = eng.states["test_rule:page"]
    eng.evaluate(now=1.0)
    assert st.state == "ok"                 # below threshold
    eng.evaluate(now=2.0)
    assert st.state == "pending"            # first breach: pending, no page
    assert not eng.active()
    eng.evaluate(now=3.0)
    assert st.state == "firing"             # second consecutive breach
    assert [a["name"] for a in eng.active()] == ["test_rule:page"]
    eng.evaluate(now=4.0)
    assert st.state == "firing"             # still breaching
    eng.evaluate(now=5.0)
    assert st.state == "firing"             # one clear does NOT resolve
    eng.evaluate(now=6.0)
    assert st.state == "ok"                 # second consecutive clear
    events = [(h["rule"], h["event"]) for h in eng.history]
    assert events == [("test_rule:page", "firing"),
                      ("test_rule:page", "resolved")]
    assert eng.transitions_total == 2


def test_flapping_suppressed_by_hysteresis():
    # strobing around the threshold: breach streaks never reach
    # for_count, so the rule never pages
    eng = _scripted_engine([50, 5] * 20, for_count=2)
    for i in range(40):
        eng.evaluate(now=float(i))
    assert eng.transitions_total == 0
    assert eng.states["test_rule:page"].state in ("ok", "pending")


def test_signal_exception_is_guarded_and_recorded():
    def boom(eng, node):
        raise RuntimeError("signal exploded")

    rule = AlertRule("broken:warn", "warn", boom, 1.0)
    eng = AlertEngine(engine=TimeSeriesEngine(Metrics()), rules=[rule])
    for _ in range(3):
        eng.evaluate(now=0.0)
    st = eng.states["broken:warn"]
    assert st.state == "ok"
    assert "RuntimeError: signal exploded" in st.last_error
    assert eng.eval_errors == 3
    json.dumps(eng.to_json())


def test_transitions_recorded_in_global_metrics():
    eng = _scripted_engine([50] * 4 + [5, 5], for_count=2, resolve_count=2)
    before = METRICS.counters.get("alert_transitions_total", 0)
    for i in range(6):
        eng.evaluate(now=float(i))
    assert METRICS.counters["alert_transitions_total"] == before + 2
    # the firing gauge tracked the lifecycle and ended at zero
    assert METRICS.gauges["alerts_firing"] == 0


# ---------------------------------------------------------------------------
# signal helpers + the stock rule set


def test_settlement_lag_signal():
    m = Metrics()
    eng = TimeSeriesEngine(m)
    assert settlement_lag_signal(eng, None) is None     # cold start
    m.set("ethrex_l2_latest_batch", 30)
    eng.sample_now(now=0.0)
    assert settlement_lag_signal(eng, None) == 30.0     # nothing verified
    m.set("ethrex_l2_last_verified_batch", 28)
    eng.sample_now(now=1.0)
    assert settlement_lag_signal(eng, None) == 2.0


def test_aggregation_lag_signal():
    m = Metrics()
    eng = TimeSeriesEngine(m)
    assert aggregation_lag_signal(eng, None) is None    # cold start
    m.set("ethrex_l2_latest_batch", 30)
    eng.sample_now(now=0.0)
    # per-batch-settling nodes never sample the aggregated gauge and
    # must stay silent, however far settlement itself lags
    assert aggregation_lag_signal(eng, None) is None
    m.set("ethrex_l2_last_aggregated_batch", 24)
    eng.sample_now(now=1.0)
    assert aggregation_lag_signal(eng, None) == 6.0


def test_actor_stall_signal():
    from types import SimpleNamespace as NS

    eng = TimeSeriesEngine(Metrics())
    assert actor_stall_signal(eng, None) is None        # no sequencer
    now = time.time()
    seq = NS(started_at=now - 200,
             health={"fresh": NS(last_success=now - 5, runs=10,
                                 consecutive_failures=0),
                     "stalled": NS(last_success=now - 90, runs=10,
                                   consecutive_failures=3),
                     "never-ran": NS(last_success=None, runs=0,
                                     consecutive_failures=0)})
    node = NS(sequencer=seq)
    worst = actor_stall_signal(eng, node)
    # the least-recently-successful actor wins; the never-scheduled one
    # is ignored rather than read as stalled-since-boot
    assert 89 <= worst < 95


def test_default_rules_pair_page_and_warn():
    rules = default_rules()
    names = {r.name for r in rules}
    for slo in ("batch_proving_p95", "prover_reassignment_rate",
                "store_corruption_rate", "l1_settlement_lag",
                "aggregation_lag", "sequencer_stall"):
        assert f"{slo}:page" in names and f"{slo}:warn" in names
    assert "sequencer_loop_p95:warn" in names
    for r in rules:
        assert r.severity in ("page", "warn")
        assert r.description and r.runbook, f"{r.name} lacks docs"
        assert r.for_count >= 2, f"{r.name} would page on a single sample"
    eng = build_default_engine()
    eng.evaluate()          # cold start over the stock set: quiet
    assert not eng.active() and eng.transitions_total == 0


# ---------------------------------------------------------------------------
# flight recorder


def test_snapshot_collect_sections_and_json():
    bundle = snapshot.collect(None, reason="manual")
    assert bundle["version"] == 1 and bundle["reason"] == "manual"
    for key in ("metrics", "timeseries", "traces", "store", "tpu"):
        assert key in bundle
    assert bundle["alerts"] is None          # no engine attached
    assert "counters" in bundle["metrics"]
    assert "slowest" in bundle["traces"]
    assert "cache" in bundle["tpu"] and "compiles" in bundle["tpu"]["cache"]
    json.dumps(bundle, default=str)


def test_snapshot_collect_sections_are_independently_guarded(monkeypatch):
    monkeypatch.setattr(snapshot.METRICS, "snapshot",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    bundle = snapshot.collect(None)
    assert bundle["metrics"] == {"error": "RuntimeError: boom"}
    # the broken section did not take the others down
    assert "slowest" in bundle["traces"]


def test_snapshot_write_prune_and_counter(tmp_path):
    snapshot.configure(str(tmp_path), keep=2)
    before = METRICS.counters.get("debug_snapshots_total", 0)
    paths = [snapshot.write(reason=f"r{i}") for i in range(4)]
    assert all(p is not None for p in paths)
    remaining = sorted(os.listdir(tmp_path))
    assert len(remaining) == 2              # pruned to the newest `keep`
    assert os.path.basename(paths[-1]) in remaining
    assert os.path.basename(paths[0]) not in remaining
    with open(paths[-1]) as f:
        assert json.load(f)["reason"] == "r3"
    assert METRICS.counters["debug_snapshots_total"] == before + 4


def test_snapshot_write_unconfigured_or_bad_dir_is_none(tmp_path):
    snapshot.configure(None)
    assert snapshot.write(reason="x") is None
    # destination is a file, not a directory: swallowed, not raised
    blocker = tmp_path / "blocker"
    blocker.write_text("x")
    assert snapshot.write(reason="x", directory=str(blocker)) is None


def test_on_fatal_writes_snapshot(tmp_path):
    snapshot.configure(str(tmp_path))
    path = snapshot.on_fatal("prove_batches", RuntimeError("actor died"))
    assert path is not None and "fatal-prove_batches" in path
    with open(path) as f:
        assert json.load(f)["reason"] == "fatal-prove_batches"


# ---------------------------------------------------------------------------
# RPC surface: degradation + health sections


def test_ethrex_alerts_and_snapshot_degrade_on_l1_only_node():
    node = Node(Genesis.from_json(GENESIS))
    server = RpcServer(node, host="127.0.0.1", port=0).start()
    try:
        rpc = RpcSession(f"http://127.0.0.1:{server.port}")
        out = rpc.call("ethrex_alerts", [])
        assert out == {"enabled": False, "rules": [], "active": [],
                       "recent": []}
        bundle = rpc.call("ethrex_debug_snapshot", [])
        assert bundle["alerts"] is None
        assert "path" not in bundle          # no snapshot dir configured
        assert "counters" in bundle["metrics"]
        health = rpc.call("ethrex_health", [])
        assert "alerts" not in health and "telemetry" not in health
    finally:
        server._httpd.shutdown()


def test_health_includes_alerts_and_telemetry_sections():
    node = Node(Genesis.from_json(GENESIS))
    eng = TimeSeriesEngine(METRICS)
    eng.sample_now(now=0.0)
    rule = AlertRule("r:page", "page", lambda e, n: 99.0, 1.0, for_count=1)
    alerts_eng = AlertEngine(engine=eng, rules=[rule], node=node)
    alerts_eng.evaluate(now=0.0)
    node.telemetry, node.alerts = eng, alerts_eng
    health = RpcServer(node).handle(
        {"jsonrpc": "2.0", "id": 1, "method": "ethrex_health",
         "params": []})["result"]
    assert health["alerts"]["firing"] == 1
    assert health["alerts"]["page"] == 1
    assert health["alerts"]["active"] == ["r:page"]
    assert health["telemetry"]["samples"] == 1
    assert health["telemetry"]["samplerRunning"] is False


def test_monitor_renders_alerts_panel_and_degrades():
    from ethrex_tpu.utils.monitor import render_lines

    snap = {
        "head": {"number": 1, "hash": "0x" + "00" * 32, "gas_used": 0,
                 "gas_limit": 30_000_000, "txs": 0, "base_fee": 7,
                 "timestamp": 0},
        "recent": [],
        "alerts": {"enabled": True,
                   "active": [{"name": "store_corruption_rate:page",
                               "severity": "page", "value": 0.5,
                               "threshold": 0.1}],
                   "recent": [{"event": "firing",
                               "rule": "store_corruption_rate:page"}]},
    }
    lines = render_lines(snap, width=100)
    assert any("alerts  firing 1" in ln for ln in lines)
    assert any("store_corruption_rate:page" in ln and "[page]" in ln
               for ln in lines)
    # disabled engine (L1-only node): panel disappears entirely
    snap["alerts"] = {"enabled": False, "active": [], "recent": []}
    assert not any("alerts" in ln for ln in render_lines(snap, width=100))
    # malformed payloads must not crash the panel
    snap["alerts"] = {"enabled": True, "active": ["junk", {}],
                      "recent": "junk"}
    render_lines(snap, width=100)


# ---------------------------------------------------------------------------
# full stack: a real injected fault drives quiet -> firing -> resolved


def test_alert_lifecycle_full_stack(tmp_path):
    """FaultPlan-injected corrupt proofs push the reassignment rate over
    an SLO threshold; the alert fires after hysteresis, is observable
    through ethrex_alerts over real TCP, lands in a debug-snapshot
    bundle (with windowed percentiles and TPU telemetry), and resolves
    once the fault clears and the burn ages out of the window."""
    node = Node(Genesis.from_json(GENESIS))
    l1 = InMemoryL1([protocol.PROVER_EXEC])
    seq = Sequencer(node, l1, SequencerConfig(
        needed_prover_types=(protocol.PROVER_EXEC,)))
    seq.coordinator.start()
    node.sequencer = seq
    server = None
    try:
        node.submit_transaction(_transfer(0))
        seq.produce_block()
        assert seq.commit_next_batch() is not None

        eng = TimeSeriesEngine(METRICS)
        rule = AlertRule(
            "prover_reassignment_rate:warn", "warn",
            rate_signal("proof_reassignments_total", window=60.0),
            threshold=0.05, window=60.0, for_count=2, resolve_count=2)
        alerts_eng = AlertEngine(engine=eng, rules=[rule], node=node)
        node.telemetry, node.alerts = eng, alerts_eng
        snapshot.configure(str(tmp_path))
        server = RpcServer(node, host="127.0.0.1", port=0).start()
        rpc = RpcSession(f"http://127.0.0.1:{server.port}")

        # ---- quiet: cold start must not fire
        t0 = time.time()
        eng.sample_now(now=t0)
        alerts_eng.evaluate(now=t0)
        out = rpc.call("ethrex_alerts", [])
        assert out["enabled"] is True and out["active"] == []

        # ---- fault: three corrupt proofs, three submit rejections,
        # three reassignments
        before = METRICS.counters.get("proof_reassignments_total", 0)
        with faults.injected(
                FaultPlan(seed=5).corrupt("backend.prove", times=3)):
            client = ProverClient(
                protocol.PROVER_EXEC,
                [("127.0.0.1", seq.coordinator.port)],
                heartbeat_interval=0, backoff_base=0.01, rng_seed=4)
            for _ in range(3):
                assert client.poll_once() == 0
        assert METRICS.counters["proof_reassignments_total"] == before + 3

        # ---- burn: rate 3/10s = 0.3/s >= 0.05 — pending, then firing
        eng.sample_now(now=t0 + 10)
        alerts_eng.evaluate(now=t0 + 10)
        assert alerts_eng.states[rule.name].state == "pending"
        eng.sample_now(now=t0 + 20)
        alerts_eng.evaluate(now=t0 + 20)
        assert alerts_eng.states[rule.name].state == "firing"

        out = rpc.call("ethrex_alerts", [])
        active = out["active"]
        assert [a["name"] for a in active] == [rule.name]
        assert active[0]["value"] >= 0.05
        assert any(h["event"] == "firing" for h in out["recent"])

        # ---- flight recorder captured mid-incident
        bundle = rpc.call("ethrex_debug_snapshot", [])
        assert [a["name"] for a in bundle["alerts"]["active"]] == [rule.name]
        rates = bundle["timeseries"]["rates"]
        assert rates["proof_reassignments_total"] >= 0.05
        # windowed percentiles from real traffic (the RPC calls above)
        assert bundle["timeseries"]["percentiles"][
            "rpc_request_seconds"]["p95"] > 0
        assert "compiles" in bundle["tpu"]["cache"]
        assert bundle["metrics"]["counters"][
            "proof_reassignments_total"] == before + 3
        # persisted to the configured dir, and readable back
        assert bundle["path"] and os.path.exists(bundle["path"])
        with open(bundle["path"]) as f:
            assert json.load(f)["reason"] == "rpc"

        # ---- recovery: fault cleared, the proof lands cleanly
        assert client.poll_once() == 1
        assert seq.rollup.get_proof(1, protocol.PROVER_EXEC) is not None

        # ---- resolve: the burn ages out of the 60s window
        eng.sample_now(now=t0 + 120)
        alerts_eng.evaluate(now=t0 + 120)
        assert alerts_eng.states[rule.name].state == "firing"  # 1 clear
        eng.sample_now(now=t0 + 130)
        alerts_eng.evaluate(now=t0 + 130)
        assert alerts_eng.states[rule.name].state == "ok"

        out = rpc.call("ethrex_alerts", [])
        assert out["active"] == []
        events = [h["event"] for h in out["recent"]]
        assert events == ["firing", "resolved"]
        assert METRICS.gauges["alerts_firing"] == 0
    finally:
        if server is not None:
            server._httpd.shutdown()
        seq.stop()
