"""Merkle-membership AIR: agreement with the host path fold, constraint
satisfaction, and a full prove/verify round-trip with forgery rejection."""

import numpy as np
import pytest

from ethrex_tpu.models import merkle_air as mair
from ethrex_tpu.ops import babybear as bb
from ethrex_tpu.ops import ext
from ethrex_tpu.ops.merkle import fold_path_canonical
from ethrex_tpu.stark import prover, verifier
from ethrex_tpu.stark.air import HostExtOps
from ethrex_tpu.stark.prover import StarkParams

RNG = np.random.default_rng(31)
PARAMS = StarkParams(log_blowup=3, num_queries=30, log_final_size=4)


def _path(depth):
    leaf = [int(v) for v in RNG.integers(0, bb.P, 8)]
    siblings = [[int(v) for v in RNG.integers(0, bb.P, 8)]
                for _ in range(depth)]
    index = int(RNG.integers(0, 1 << depth))
    bits = [(index >> j) & 1 for j in range(depth)]
    root = fold_path_canonical(index, leaf, siblings)
    return leaf, siblings, bits, index, root


def test_trace_matches_host_fold():
    leaf, siblings, bits, index, root = _path(3)
    trace = mair.generate_merkle_trace(leaf, siblings, bits)
    air = mair.Poseidon2MerkleAir(3)
    assert trace.shape == (mair.PERIOD * air.periods, 33)
    tail = mair.PERIOD * 3
    assert [int(v) for v in trace[tail, 16:24]] == root


def test_constraints_vanish_and_catch_tampering():
    leaf, siblings, bits, index, root = _path(2)
    air = mair.Poseidon2MerkleAir(2)
    trace = mair.generate_merkle_trace(leaf, siblings, bits)
    n = trace.shape[0]
    periodic_cols = air.periodic_columns(n)
    hops = HostExtOps()

    def cons_at(tr, r):
        local = [ext.h_from_base(int(v)) for v in tr[r]]
        nxt = [ext.h_from_base(int(v)) for v in tr[r + 1]]
        periodic = [ext.h_from_base(int(col[r])) for col in periodic_cols]
        return air.constraints(local, nxt, periodic, hops)

    for r in range(n - 1):
        assert all(c == ext.ZERO_H for c in cons_at(trace, r)), f"row {r}"
    # flip the direction bit of level 1 -> the handoff constraint breaks
    bad = trace.copy()
    rows = slice(mair.PERIOD, 2 * mair.PERIOD)
    bad[rows, 32] = 1 - bad[mair.PERIOD, 32]
    broke = any(any(c != ext.ZERO_H for c in cons_at(bad, r))
                for r in range(n - 1))
    assert broke


@pytest.mark.parametrize("depth", [1, 3])
def test_prove_verify_membership(depth):
    leaf, siblings, bits, index, root = _path(depth)
    air = mair.Poseidon2MerkleAir(depth)
    trace = mair.generate_merkle_trace(leaf, siblings, bits)
    pub = mair.merkle_public_inputs(leaf, root)
    proof = prover.prove(air, trace, pub, PARAMS)
    assert verifier.verify(air, proof, PARAMS)
    # a different root must not verify (membership forgery)
    bad_root = list(root)
    bad_root[0] = (bad_root[0] + 1) % bb.P
    bad_pub = mair.merkle_public_inputs(leaf, bad_root)
    with pytest.raises(verifier.VerificationError):
        verifier.verify(air, {**proof, "pub_inputs": bad_pub}, PARAMS)
    # a different leaf must not verify either
    bad_leaf = list(leaf)
    bad_leaf[3] = (bad_leaf[3] + 1) % bb.P
    with pytest.raises(verifier.VerificationError):
        verifier.verify(
            air, {**proof,
                  "pub_inputs": mair.merkle_public_inputs(bad_leaf, root)},
            PARAMS)
