"""Native EVM hot loop (native/evm.cpp): differential conformance against
the pure-Python interpreter and a hot-loop throughput sanity check.

The EF fixture ladder pins post-state roots + logs digests produced by
the PYTHON interpreter; running the same fixtures with the native loop
FORCED is a full differential test of every handled opcode's semantics
and gas across all 14 forks.
"""

import os
import subprocess
import sys
import time

import pytest

from ethrex_tpu.evm import native_vm

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures", "ef_state")


def test_native_extension_builds():
    assert native_vm.available()


def test_differential_ef_forks_ladder():
    """The whole fork-ladder fixture set, native loop forced, in a child
    process (the force switch is read per-frame but the library state is
    process-global; a child keeps this hermetic)."""
    code = (
        "import os; os.environ['ETHREX_TPU_NATIVE_EVM'] = '1';"
        "from ethrex_tpu.utils import ef_state;"
        f"p, f = ef_state.run_directory({FIXDIR + '/forks'!r});"
        "print(len(p), len(f));"
        "assert not f, [r.detail for r in f[:3]];"
    )
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=600,
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-800:]
    n_pass = int(proc.stdout.split()[0])
    assert n_pass > 4000


def _loop_code(n):
    """Countdown loop: PUSH2 n; [JUMPDEST DUP1 ISZERO PUSH2 exit JUMPI
    PUSH1 1 SWAP1 SUB PUSH2 3 JUMP] exit: JUMPDEST STOP."""
    return bytes([0x61, n >> 8, n & 0xFF,
                  0x5B, 0x80, 0x15, 0x61, 0x00, 0x12, 0x57,
                  0x60, 0x01, 0x90, 0x03,
                  0x61, 0x00, 0x03, 0x56,
                  0x5B, 0x00])


def _run_loop(iters):
    from ethrex_tpu.evm.db import InMemorySource, StateDB
    from ethrex_tpu.evm.vm import EVM, BlockEnv, Frame, Message, _Halt
    from ethrex_tpu.primitives.genesis import ChainConfig

    cfg = ChainConfig(chain_id=1)
    cfg.block_forks = {}
    cfg.terminal_total_difficulty = 0
    evm = EVM(StateDB(InMemorySource()),
              BlockEnv(number=1, timestamp=10**9), cfg)
    code = _loop_code(iters)
    msg = Message(caller=b"\x01" * 20, to=b"\x02" * 20,
                  code_address=b"\x02" * 20, value=0, data=b"",
                  gas=50_000_000, code=code)
    f = Frame(msg, code)
    t0 = time.perf_counter()
    try:
        evm._run(f)
    except _Halt:
        pass
    return time.perf_counter() - t0, 50_000_000 - f.gas


@pytest.mark.skipif(not native_vm.available(), reason="no native evm")
def test_hot_loop_throughput_and_gas_parity(monkeypatch):
    import ethrex_tpu.evm.vm as vm_mod

    # python reference
    monkeypatch.setenv("ETHREX_TPU_NATIVE_EVM", "0")
    vm_mod._NATIVE_STATE[0] = None
    t_py, gas_py = _run_loop(20000)
    # native (code is 20 bytes < threshold, so force)
    monkeypatch.setenv("ETHREX_TPU_NATIVE_EVM", "1")
    vm_mod._NATIVE_STATE[0] = None
    t_nat, gas_nat = _run_loop(20000)
    vm_mod._NATIVE_STATE[0] = None
    monkeypatch.delenv("ETHREX_TPU_NATIVE_EVM")

    assert gas_py == gas_nat          # exact gas parity
    # the native loop must be dramatically faster on hot code; 5x is a
    # deliberately loose floor for contended CI boxes (measured 28-60x)
    assert t_nat * 5 < t_py, (t_nat, t_py)
    assert gas_nat / t_nat > 50e6     # >= 50 Mgas/s on the hot loop


@pytest.mark.skipif(not native_vm.available(), reason="no native evm")
def test_escape_roundtrip_preserves_state(monkeypatch):
    """A contract mixing native ops with escaping SLOAD/SSTORE: the
    hybrid must produce the same storage and gas as pure Python."""
    from ethrex_tpu.evm.db import InMemorySource, StateDB
    from ethrex_tpu.evm.vm import EVM, BlockEnv, Frame, Message, _Halt
    from ethrex_tpu.primitives.genesis import ChainConfig

    # for i in 0..63: sstore(i, i*3+1)   (SSTORE escapes, arithmetic is
    # native); pad to >= 64 bytes so the auto heuristic kicks in
    code = bytearray()
    for i in range(64):
        v = i * 3 + 1
        code += bytes([0x61, v >> 8, v & 0xFF])   # PUSH2 v
        code += bytes([0x60, i])                  # PUSH1 i
        code += bytes([0x55])                     # SSTORE
    code += b"\x00"

    def run(native):
        monkeypatch.setenv("ETHREX_TPU_NATIVE_EVM",
                           "1" if native else "0")
        import ethrex_tpu.evm.vm as vm_mod

        vm_mod._NATIVE_STATE[0] = None
        cfg = ChainConfig(chain_id=1)
        cfg.block_forks = {}
        cfg.terminal_total_difficulty = 0
        state = StateDB(InMemorySource())
        evm = EVM(state, BlockEnv(number=1, timestamp=10**9), cfg)
        msg = Message(caller=b"\x01" * 20, to=b"\x02" * 20,
                      code_address=b"\x02" * 20, value=0, data=b"",
                      gas=10_000_000, code=bytes(code))
        f = Frame(msg, bytes(code))
        try:
            evm._run(f)
        except _Halt:
            pass
        storage = {s: state.get_storage(b"\x02" * 20, s)
                   for s in range(64)}
        return f.gas, storage

    gas_py, st_py = run(False)
    gas_nat, st_nat = run(True)
    import ethrex_tpu.evm.vm as vm_mod

    vm_mod._NATIVE_STATE[0] = None
    monkeypatch.delenv("ETHREX_TPU_NATIVE_EVM")
    assert gas_py == gas_nat
    assert st_py == st_nat == {i: i * 3 + 1 for i in range(64)}
